"""Batched serving entry points: group -> stack -> one dispatch -> scatter.

``batched_qr`` / ``batched_lstsq`` accept a LIST of heterogeneous
requests and turn them into a handful of vmapped dispatches of the
blocked engine:

1. every request's ``(m, n, dtype)`` is rounded onto the bucket lattice
   (``serve.buckets.plan_bucket`` — exact orthogonal-column + zero-row
   padding, so truncated answers are exact);
2. each bucket group is stacked into one host buffer (one device
   transfer) and dispatched through the AOT executable cache
   (``serve.cache`` — ``lower().compile()`` once per
   (bucket, dtype, engine-knobs) key, LRU-bounded, counted);
3. per-request results are sliced back out IN INPUT ORDER, truncated to
   the request's own shape.

This is the first tier that optimizes *throughput* rather than
single-factorization latency: at small n the MXU only stays busy when
factorizations are batched (tests/test_batched.py pins the
transformability; arXiv:2112.09017 makes the same argument for TPU
dense linear algebra), and a heterogeneous stream only stays compiled
when shapes are bucketed.

Engine scope: the blocked Householder XLA path only (``pallas=False`` —
the fused panel kernel is a single-problem VMEM tier; under vmap the
XLA path is the MXU one), single device. Precision policies and
iterative refinement compose exactly as on ``lstsq``: the policy's
panel/trailing go to the factor stage, ``apply`` to the Q^H-apply,
``refine`` into in-program refinement sweeps.
"""

from __future__ import annotations

import dataclasses
from contextlib import contextmanager
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from dhqr_tpu.faults import harness as _faults
from dhqr_tpu.numeric import guards as _nguards
from dhqr_tpu.obs import pulse as _pulse
from dhqr_tpu.obs import trace as _obs
from dhqr_tpu.obs import xray as _obs_xray
from dhqr_tpu.numeric.errors import Breakdown
from dhqr_tpu.ops import blocked as _blocked
from dhqr_tpu.ops import solve as _solve
from dhqr_tpu.serve.errors import DispatchFailed, ServeError
from dhqr_tpu.serve.buckets import (
    Bucket,
    bucket_batch,
    pad_group,
    plan_bucket,
)
from dhqr_tpu.serve.cache import CacheKey, ExecutableCache, default_cache
from dhqr_tpu.utils.config import DHQRConfig, ServeConfig

# Default compact-WY panel width for BATCHED dispatches (block_size=None).
# Deliberately narrower than the single-problem auto_block_size tier: in a
# vmapped factorization the trailing updates already aggregate B problems
# per GEMM, so MXU/SIMD occupancy does not need wide panels — and the
# panel interior is the batch's sequential critical path, so narrow
# panels shorten it. Measured on the CPU vmapped ladder (round 8):
# nb=32 beats nb=128 by 4.5x at B=16 384x128 (54 vs 245 ms), 2.7x at
# B=16 768x256, 2.4x at B=16 512x192, and never loses at small shapes.
# Override per call with block_size= (the TPU ladder may prefer wider).
SERVE_DEFAULT_BLOCK = 32


@partial(
    jax.jit,
    static_argnames=("block_size", "precision", "trailing_precision",
                     "apply_precision", "refine", "norm", "panel_impl"),
)
def _batched_lstsq_impl(A, b, block_size, precision="highest",
                        trailing_precision=None, apply_precision=None,
                        refine=0, norm="accurate", panel_impl="loop"):
    """One bucket's least-squares program: vmapped blocked factor +
    two-stage solve, with ``refine`` in-program refinement sweeps
    (residual matvec at full precision, reusing the factorization).

    NOT donated, deliberately: the output x is (B, n) while the stacked
    input is (B, m, n), so no output can alias the donated buffer and
    jax would warn "donated buffers were not usable" on every lowering;
    XLA already frees the stack after its last in-program use. The
    factor-only dispatch (:func:`dhqr_tpu.ops.blocked._batched_qr_impl_donate`)
    is the one whose output is input-shaped, and it does donate.
    """
    ap = precision if apply_precision is None else apply_precision

    def one(a, rhs):
        H, alpha = _blocked._blocked_qr_impl(
            a, block_size, precision=precision, pallas=False, norm=norm,
            panel_impl=panel_impl, trailing_precision=trailing_precision,
        )

        def qr_solve(r):
            c = _blocked._apply_qt_impl(H, r, block_size, precision=ap)
            return _solve.back_substitute(H, alpha, c)

        x = qr_solve(rhs)
        for _ in range(refine):
            resid = rhs - jnp.matmul(a, x, precision="highest")
            x = x + qr_solve(resid)
        return x

    return jax.vmap(one)(A, b)


def _resolve_serve_cfg(config: Optional[DHQRConfig],
                       overrides) -> "tuple[DHQRConfig, object]":
    """Shared config/policy resolution for the serve entry points —
    the same validation chain the single-request API runs
    (models.qr_model), so a config error is reported identically whether
    a request is served singly or batched. Returns ``(cfg, policy)``
    with the policy's precision fields folded into the classic knobs.
    """
    from dhqr_tpu.models.qr_model import (_check_panel_impl,
                                          _resolve_policy_cfg)

    cfg = dataclasses.replace(config or DHQRConfig(), **overrides)
    cfg, pol = _resolve_policy_cfg(cfg)
    # NOTE: a policy's refine is NOT folded into cfg.refine here — the
    # lstsq family wants it as in-program sweeps, while batched_qr arms
    # it on the returned factorizations' solves (and must still reject
    # an EXPLICIT refine=). Each entry point places it.
    if cfg.engine != "householder":
        raise ValueError(
            f"the serving tier's configs keep engine='householder' (got "
            f"engine={cfg.engine!r}): program families are selected by "
            "the KIND — batched_lstsq/batched_qr batch the blocked "
            "householder engine, batched_sketched_lstsq is the sketched "
            "kind (its knobs steer the sketch core) — while the "
            "tsqr/cholqr families are single-problem fast paths"
        )
    if not cfg.blocked:
        raise ValueError(
            "the serving tier batches the blocked engine only "
            "(got blocked=False)"
        )
    if cfg.use_pallas == "always":
        raise ValueError(
            "use_pallas='always' is not supported on the serving tier: "
            "the fused panel kernel is a single-problem VMEM tier; "
            "batched dispatches run the vmapped XLA path"
        )
    if cfg.lookahead or cfg.agg_panels:
        raise ValueError(
            "lookahead/agg_panels are panel-schedule levers for large "
            "single factorizations; the serving tier's buckets are small "
            f"(got lookahead={cfg.lookahead}, agg_panels={cfg.agg_panels})"
        )
    if cfg.norm not in ("accurate", "fast"):
        raise ValueError(
            f"norm must be 'accurate' or 'fast', got {cfg.norm!r}"
        )
    if cfg.refine < 0:
        raise ValueError(f"refine must be >= 0, got {cfg.refine}")
    _check_panel_impl(cfg)
    return cfg, pol


def _resolve_bucket_plan(kind: str, cfg: DHQRConfig, bucket: Bucket, pol):
    """Per-bucket twin of ``models.qr_model._resolve_plan_cfg``: the
    serve tier's plan key is the BUCKET shape (what actually compiles
    and dispatches), so ``plan="auto"`` resolves here, inside the group
    loop, once per bucket. Tuned knobs land on the config BEFORE
    ``_plan_key`` builds the cache key, so a tuned dispatch and its
    prewarm hit the same executable — zero-recompile serving holds with
    plans exactly as without."""
    spec = cfg.plan
    if spec is None:
        return cfg
    if isinstance(spec, str) and spec == "default":
        return dataclasses.replace(cfg, plan=None)
    from dhqr_tpu.tune import Plan, apply_plan_to_config, resolve_plan

    if cfg.block_size is not None or cfg.panel_impl != "loop":
        raise ValueError(
            "pass either plan= or block_size=/panel_impl=, not both "
            f"(got block_size={cfg.block_size}, "
            f"panel_impl={cfg.panel_impl!r} with plan={spec!r})"
        )
    if isinstance(spec, Plan):
        plan = spec
    elif isinstance(spec, str) and spec == "auto":
        plan = resolve_plan(f"serve_{kind}", bucket.m, bucket.n,
                            bucket.dtype, policy=pol)
        if plan is None:  # DB miss with on_miss="default"
            return dataclasses.replace(cfg, plan=None)
    else:
        raise ValueError(
            f"plan must be 'auto', 'default', None or a dhqr_tpu.tune.Plan,"
            f" got {spec!r}"
        )
    if plan.engine != "householder" or plan.lookahead or plan.agg_panels \
            or plan.comms:
        raise ValueError(
            "serve plans carry block_size/panel_impl/trailing_precision "
            "only (the serving tier batches the blocked householder "
            "engine — no schedule levers, and no collectives for a "
            f"comms wire format to compress); got {plan.describe()!r}"
        )
    if plan.trailing_precision and cfg.trailing_precision is not None:
        raise ValueError(
            f"the plan carries trailing_precision="
            f"{plan.trailing_precision!r} but the policy/config already "
            f"set {cfg.trailing_precision!r} — drop one"
        )
    return apply_plan_to_config(cfg, plan)


def _plan_key(kind: str, count: int, m: int, n: int, dtype,
              cfg: DHQRConfig, scfg: ServeConfig) -> "tuple[CacheKey, Bucket]":
    """The ONE place a request shape + config becomes a cache key —
    shared by live dispatch and :func:`prewarm`, so a prewarmed key is
    guaranteed to be the key serving hits."""
    bucket = plan_bucket(m, n, dtype, scfg)
    batch = bucket_batch(count, scfg)
    nb = min(cfg.block_size or SERVE_DEFAULT_BLOCK, bucket.n)
    # cfg.comms (dhqr-wire, round 18) is deliberately NOT a key field:
    # the bucket programs launch zero collectives (the comms audit's
    # batched_lstsq contract), so a policy naming a wire format must
    # share the uncompressed executable — same rule as qr dropping
    # refine/apply from its factor-only key below.
    if kind == "sketch":
        # Round 17: the sketched kind's program is fully determined by
        # the bucket shape + the (s, seed, operator) triple — derived
        # HERE, the one key mint, from SketchConfig + the bucket, so
        # prewarm and live dispatch (and every process sharing the
        # seed) agree on the executable by construction.
        from dhqr_tpu.solvers import sketch as _sketch
        from dhqr_tpu.utils.config import SketchConfig

        skcfg = SketchConfig.from_env()
        s = _sketch.sketch_dim(bucket.m, bucket.n, factor=skcfg.factor)
        op = _sketch.resolve_operator(skcfg.operator, bucket.m)
        key = CacheKey(kind, batch, bucket.m, bucket.n, bucket.dtype, nb,
                       cfg.precision, cfg.trailing_precision, None,
                       cfg.refine, cfg.norm, "loop",
                       sketch=(s, skcfg.seed, op))
        return key, bucket
    if kind == "qr":
        # refine/apply live in the solve stage; a factor-only program is
        # identical across them — keep them out of the key so policy
        # variants share one executable.
        key = CacheKey(kind, batch, bucket.m, bucket.n, bucket.dtype, nb,
                       cfg.precision, cfg.trailing_precision, None, 0,
                       cfg.norm, cfg.panel_impl)
    else:
        key = CacheKey(kind, batch, bucket.m, bucket.n, bucket.dtype, nb,
                       cfg.precision, cfg.trailing_precision,
                       cfg.apply_precision, cfg.refine, cfg.norm,
                       cfg.panel_impl)
    return key, bucket


def cache_key_plan(key: CacheKey):
    """The :class:`~dhqr_tpu.tune.Plan` a serve CacheKey carries — the
    inverse of the plan-application step inside :func:`_plan_key`.

    The fleet store's canonical cross-process key spelling
    (``serve.store.canonical_key``, round 22) routes the plan segment
    through ``Plan.describe()`` — ONE deterministic string owned by
    tune, rather than a second ad-hoc rendering of the same knobs —
    which is a concrete step toward ROADMAP item 6's "a Route instance
    IS the cache key" fold: when that lands, this reconstruction
    disappears and the key carries the route. Serve plans carry only
    block_size / panel_impl / trailing_precision (``_resolve_plan``
    rejects schedule levers and comms), so those three fields round-trip
    exactly; the batched engine family is the blocked householder by
    construction.
    """
    from dhqr_tpu.tune import Plan

    return Plan(engine="householder", block_size=key.block_size,
                panel_impl=key.panel_impl,
                trailing_precision=key.trailing_precision or None)


def _lower_for_key(key: CacheKey):
    """Build the Lowered program for a serve cache key (the cache owns
    the ``.compile()``)."""
    dtype = jnp.dtype(key.dtype)
    A = jax.ShapeDtypeStruct((key.batch, key.m, key.n), dtype)
    if key.kind == "sketch":
        from dhqr_tpu.solvers import sketch as _sketch

        s, seed, op = key.sketch
        fn = _sketch.batched_sketch_program(
            key.m, key.n, s, seed, op, key.block_size,
            precision=key.precision,
            trailing_precision=key.trailing_precision, norm=key.norm,
            refine=key.refine, dtype=key.dtype)
        b = jax.ShapeDtypeStruct((key.batch, key.m), dtype)
        return jax.jit(fn).lower(A, b)
    if key.kind == "qr":
        return _blocked._batched_qr_impl_donate.lower(
            A, key.block_size, precision=key.precision, norm=key.norm,
            panel_impl=key.panel_impl,
            trailing_precision=key.trailing_precision,
        )
    b = jax.ShapeDtypeStruct((key.batch, key.m), dtype)
    return _batched_lstsq_impl.lower(
        A, b, key.block_size, precision=key.precision,
        trailing_precision=key.trailing_precision,
        apply_precision=key.apply_precision, refine=key.refine,
        norm=key.norm, panel_impl=key.panel_impl,
    )


def bucket_program(kind: str, config: Optional[DHQRConfig] = None,
                   **overrides):
    """The exact traced callable a serve bucket dispatch compiles, as a
    plain function of the stacked arrays — the lint jaxpr pass traces
    ``batched_lstsq`` through this under every policy preset
    (analysis/jaxpr_pass), so program-representation regressions in the
    serving tier surface without a compile."""
    cfg, pol = _resolve_serve_cfg(config, overrides)
    if cfg.plan is not None:
        raise ValueError(
            "bucket_program takes resolved knobs (block_size=, ...): "
            "plan= is resolved per bucket by the serve entry points"
        )
    if pol is not None and pol.refine:
        cfg = dataclasses.replace(cfg, refine=pol.refine)

    def lstsq_fn(A, b):
        nb = min(cfg.block_size or SERVE_DEFAULT_BLOCK, A.shape[2])
        return _batched_lstsq_impl(
            A, b, nb, precision=cfg.precision,
            trailing_precision=cfg.trailing_precision,
            apply_precision=cfg.apply_precision, refine=cfg.refine,
            norm=cfg.norm, panel_impl=cfg.panel_impl,
        )

    def qr_fn(A):
        nb = min(cfg.block_size or SERVE_DEFAULT_BLOCK, A.shape[2])
        return _blocked._batched_qr_impl_donate(
            A, nb, precision=cfg.precision, norm=cfg.norm,
            panel_impl=cfg.panel_impl,
            trailing_precision=cfg.trailing_precision,
        )

    if kind == "lstsq":
        return lstsq_fn
    if kind == "qr":
        return qr_fn
    if kind == "sketch":
        from dhqr_tpu.solvers import sketch as _sketch
        from dhqr_tpu.utils.config import SketchConfig

        skcfg = SketchConfig.from_env()

        def sketch_fn(A, b):
            _, m, n = A.shape
            s = _sketch.sketch_dim(m, n, factor=skcfg.factor)
            op = _sketch.resolve_operator(skcfg.operator, m)
            nb = min(cfg.block_size or SERVE_DEFAULT_BLOCK, n)
            prog = _sketch.batched_sketch_program(
                m, n, s, skcfg.seed, op, nb, precision=cfg.precision,
                trailing_precision=cfg.trailing_precision, norm=cfg.norm,
                refine=skcfg.refine + cfg.refine, dtype=A.dtype)
            return prog(A, b)

        return sketch_fn
    # Unreachable for any registered kind: the route registry is the
    # enumeration (tune/registry.SERVE_PROGRAM_KINDS) and the dispatch
    # above covers it exactly — DHQR501/503 audit that coverage.
    from dhqr_tpu.tune.registry import SERVE_PROGRAM_KINDS

    raise ValueError(
        f"kind must be one of {SERVE_PROGRAM_KINDS}, got {kind!r}")


def _resolve_dispatch_cfg(kind: str, config: Optional[DHQRConfig],
                          overrides):
    """The ONE place serve config/policy resolution places the policy's
    refine for a program family — shared by ``batched_lstsq`` /
    ``batched_qr`` / :func:`prewarm` and the async scheduler
    (``serve.scheduler``), so a request resolved for queued dispatch is
    byte-identical to the same request resolved for a sync call.

    Returns ``(cfg, pol, qr_solve_args)``:

    * ``kind == "lstsq"``: the policy's refine is folded into
      ``cfg.refine`` (in-program sweeps); ``qr_solve_args`` is None.
    * ``kind == "qr"``: an explicit ``refine=`` is rejected (factor-only
      programs have no solve to refine — arm it via ``policy=``), and
      ``qr_solve_args = (apply_precision, solve_refine)`` carries what
      the scatter stage records on each returned factorization.
    """
    cfg, pol = _resolve_serve_cfg(config, overrides)
    if kind == "lstsq":
        if pol is not None and pol.refine:
            cfg = dataclasses.replace(cfg, refine=pol.refine)
        return cfg, pol, None
    if kind == "sketch":
        # Round 17: the sketched kind. The TOTAL CGLS iteration count
        # is resolved HERE — SketchConfig baseline + the caller's
        # policy/refine extra — so the cache key's ``refine`` field and
        # the compiled program agree wherever the key is minted
        # (prewarm, sync dispatch, the async scheduler).
        from dhqr_tpu.utils.config import SketchConfig

        if cfg.panel_impl != "loop":
            raise ValueError(
                "panel_impl applies to the blocked householder kinds "
                f"(kind='sketch', panel_impl={cfg.panel_impl!r}: the "
                "sketch core's panel interior is fixed)"
            )
        extra = pol.refine if pol is not None else cfg.refine
        cfg = dataclasses.replace(
            cfg, refine=SketchConfig.from_env().refine + extra)
        return cfg, pol, None
    if kind != "qr":
        from dhqr_tpu.tune.registry import SERVE_PROGRAM_KINDS

        raise ValueError(
            f"kind must be one of {SERVE_PROGRAM_KINDS}, got {kind!r}")
    if cfg.refine:
        raise ValueError(
            "refine applies to batched_lstsq only — batched_qr returns raw "
            "factorizations; pass a policy= with refine > 0 to arm "
            "refinement on the factorizations' solves"
        )
    solve_refine = pol.refine if pol is not None else 0
    apply_prec = cfg.apply_precision or cfg.precision
    return cfg, pol, (apply_prec, solve_refine)


def _scatter_lstsq(As: Sequence, emit):
    """Input-order scatter for lstsq dispatches: a ``consume`` callback
    (see :func:`_dispatch_groups`) that slices each request's solution
    out of the stacked output and hands it to ``emit(i, x_i)`` — the
    sync API's ``emit`` fills a result list, the async scheduler's
    resolves futures. One slicing rule, two front ends."""

    def consume(chunk, key, xs):
        for row, i in enumerate(chunk):
            emit(i, xs[row, :As[i].shape[1]])

    return consume


def _scatter_qr(As: Sequence, emit, qr_solve_args):
    """Input-order scatter for factor-only dispatches: truncates each
    stacked factorization to its request's shape, wraps it in a
    ``QRFactorization`` armed with the resolved solve-stage fields
    (:func:`_resolve_dispatch_cfg`), and hands it to ``emit(i, fact)``."""
    from dhqr_tpu.models.qr_model import QRFactorization

    apply_prec, solve_refine = qr_solve_args

    def consume(chunk, key, outs):
        Hs, alphas = outs
        for row, i in enumerate(chunk):
            m, n = As[i].shape
            emit(i, QRFactorization(
                Hs[row, :m, :n], alphas[row, :n],
                block_size=key.block_size, precision=apply_prec,
                refine=solve_refine,
                matrix=jnp.asarray(As[i]) if solve_refine else None,
            ))

    return consume


def _validate_requests(As: Sequence, bs: "Sequence | None"):
    if bs is not None and len(As) != len(bs):
        raise ValueError(
            f"got {len(As)} matrices but {len(bs)} right-hand sides"
        )
    for i, A in enumerate(As):
        shape = getattr(A, "shape", None)
        if shape is None or len(shape) != 2:
            raise ValueError(
                f"request {i}: expected a 2-D matrix, got shape {shape}"
            )
        m, n = shape
        if m < n or n < 1:
            raise ValueError(
                f"request {i}: the serving tier factors tall problems "
                f"(m >= n >= 1), got shape ({m}, {n})"
            )
        if bs is not None:
            b = bs[i]
            bshape = getattr(b, "shape", None)
            if bshape != (m,):
                raise ValueError(
                    f"request {i}: b must be a length-m vector matching A "
                    f"(A is ({m}, {n}), b has shape {bshape}); block "
                    "right-hand sides are not batched yet — stack them as "
                    "separate requests"
                )
            import numpy as np

            if np.dtype(getattr(b, "dtype", None)) != np.dtype(A.dtype):
                # The stacked buffer takes A's bucket dtype; a wider b
                # would be downcast SILENTLY there, diverging from what
                # lstsq(A, b) (which promotes) returns — refuse instead.
                raise ValueError(
                    f"request {i}: b dtype {getattr(b, 'dtype', None)} does "
                    f"not match A dtype {A.dtype}; cast explicitly (the "
                    "stacked dispatch runs entirely in A's dtype)"
                )


def _group_by_bucket(As: Sequence, scfg: ServeConfig):
    """bucket -> list of request indices, insertion-ordered."""
    groups: "dict[Bucket, list[int]]" = {}
    for i, A in enumerate(As):
        m, n = A.shape
        bucket = plan_bucket(m, n, A.dtype, scfg)
        groups.setdefault(bucket, []).append(i)
    return groups


def _dispatch_groups(kind, As, bs, cfg, scfg, cache, consume, pol=None,
                     trace_id=None):
    """The one group -> chunk -> key -> compile -> pad -> dispatch loop
    shared by ``batched_lstsq`` and ``batched_qr`` (a chunking or key
    fix must not have to land twice). ``consume(chunk, key, outs)`` is
    called once per dispatched chunk with the request indices, the cache
    key, and the stacked program outputs. ``pol`` (the resolved policy,
    if any) keys per-bucket plan resolution.

    Failure routing (round 12): the cache raises typed
    ``CompileFailed`` / ``Quarantined``; the device launch here is
    wrapped into :class:`DispatchFailed` (the ``serve.dispatch`` /
    ``serve.latency`` fault-injection sites live at the launch, so an
    injected fault takes exactly the organic failure path). ``consume``
    is OUTSIDE the wrap: a scatter/callback bug is the caller's error,
    not a device failure to retry.

    Numeric guard (round 13): with ``cfg.guards`` armed, the stacked
    outputs are health-checked BEFORE scatter — a non-finite row
    (a NaN-bearing or breakdown-grade request hiding in the batch)
    raises a typed :class:`~dhqr_tpu.numeric.Breakdown` instead of
    scattering garbage; the async scheduler passes that straight to
    bisect-isolation, so the one bad matrix fails alone and its batch
    neighbors complete. The check is OUTSIDE the compiled program
    (same cache key, same executable, zero recompiles) and entirely
    skipped when guards are off (the default)."""
    # Tracing (round 14): ``trace_id`` is the SYNC caller's call-scoped
    # id (batched_lstsq/batched_qr mint it); the async scheduler passes
    # None here because it records per-request spans itself. The id is
    # host-side only — _plan_key/CacheKey never see it, so armed
    # tracing compiles exactly the disarmed programs.
    rec = _obs.active() if trace_id is not None else None
    for bucket, idxs in _group_by_bucket(As, scfg).items():
        cfg_b = _resolve_bucket_plan(kind, cfg, bucket, pol)
        for lo in range(0, len(idxs), scfg.max_batch):
            chunk = idxs[lo:lo + scfg.max_batch]
            key, _ = _plan_key(kind, len(chunk), bucket.m, bucket.n,
                               bucket.dtype, cfg_b, scfg)
            # plan_bucket is idempotent (bucket dims are lattice points),
            # so re-planning from the bucket's own shape returns it.
            # Span compile attribution: a key already resident is
            # DEFINITIVELY compile-free (0.0, whatever concurrent
            # compiles land in the window); only a genuine miss reads
            # the timer delta, which a concurrent worker's compile of a
            # DIFFERENT key can still over-attribute — same
            # shared-timer caveat (and clamp) the scheduler's EWMA
            # documents. Good enough for the warm-vs-cold split the
            # per-phase evidence needs; exact per-key attribution would
            # need the cache to return its own compile time.
            was_resident = rec is not None and key in cache
            compile0 = cache.timer.total("aot_compile") \
                if rec is not None and not was_resident else 0.0
            compiled = cache.get_or_compile(key, partial(_lower_for_key, key))
            if rec is not None:
                compile_s = 0.0 if was_resident else max(
                    cache.timer.total("aot_compile") - compile0, 0.0)
                rec.event(trace_id, "dispatch", bucket=bucket.label,
                          batch=key.batch, requests=len(chunk),
                          compile_s=round(compile_s, 6))
            A_buf, b_buf = pad_group(
                [(As[i], None if bs is None else bs[i]) for i in chunk],
                bucket, key.batch)
            _faults.latency("serve.latency")
            try:
                _faults.fire("serve.dispatch")
                if kind == "qr":
                    def launch(A_buf=A_buf, b_buf=None):
                        return compiled(jnp.asarray(A_buf))
                else:       # lstsq / sketch: stacked (A, b) programs
                    def launch(A_buf=A_buf, b_buf=b_buf):
                        return compiled(jnp.asarray(A_buf),
                                        jnp.asarray(b_buf))
                # dhqr-pulse (round 16): the bucket dispatch is
                # contracted COLLECTIVE-FREE (the EOF comms note below);
                # armed, the first dispatch of each key is profiled once
                # and any measured collective fails its DHQR306 verdict
                # — the runtime twin of the static DHQR301 contract.
                # Disarmed: one module-global None check. The label is
                # the FULL CacheKey (knobs included): two programs
                # sharing a bucket but differing in block_size/
                # precision/plan are distinct executables and each gets
                # its own runtime check. When a pulse measurement
                # carries a comms block, it is paired into the armed
                # xray store's report for the same key so one table
                # shows both sides of the roofline.
                if _pulse.active() is None:
                    outs = launch()
                else:
                    def pair(report, key=key):
                        # Fires once, at capture time only (the warm
                        # path never reaches it): a measured comms
                        # block pairs into the armed xray store's
                        # report for the same program.
                        if report.comms is not None:
                            xstore = _obs_xray.active()
                            if xstore is not None:
                                xstore.attach_comms(key, report.comms)
                    outs = _pulse.observed_dispatch(
                        "serve:" + ":".join(str(f) for f in key),
                        launch, contract_families=(), n_devices=1,
                        on_report=pair)
            except ServeError:
                raise
            except Exception as e:
                raise DispatchFailed(key, e) from e
            if cfg.guards is not None:
                bad = (_nguards.any_nonfinite(*outs) if kind == "qr"
                       else _nguards.any_nonfinite(outs))
                if bad:
                    raise Breakdown(
                        f"non-finite rows in the stacked {kind} dispatch "
                        f"for {key!r}: a request in this batch is "
                        "numerically poisoned (NaN input or breakdown); "
                        "bisect to isolate it",
                        engine=cfg_b.engine)
            consume(chunk, key, outs)


def _trace_sync_call(kind: str, n_requests: int):
    """Mint a call-scoped trace id for a SYNC batched entry point (the
    whole list call is one "request" here — it has one caller, one
    return). Returns ``(recorder, trace_id)``, both None/None when
    tracing is disarmed — the hot path pays exactly one global read."""
    rec = _obs.active()
    if rec is None:
        return None, None
    tid = rec.mint()
    rec.event(tid, "submit", kind=kind, sync=True, requests=n_requests)
    return rec, tid


@contextmanager
def _trace_sync_resolve(rec, tid):
    """Close a sync call's span path: "resolve ok" on normal exit, or a
    typed-outcome resolve + error trace-id stamping + the on_error
    auto-dump hook when the dispatch raised (the ServeError /
    NumericalError contract — "the error carries its trace id" — holds
    on the sync tier exactly as on futures)."""
    if rec is None:
        yield
        return
    try:
        yield
    except Exception as e:
        rec.event(tid, "resolve", outcome=type(e).__name__,
                  error=str(e)[:200])
        rec.on_error(e, tid)
        raise
    rec.event(tid, "resolve", outcome="ok")


def batched_lstsq(
    As: Sequence,
    bs: Sequence,
    config: Optional[DHQRConfig] = None,
    serve_config: Optional[ServeConfig] = None,
    cache: Optional[ExecutableCache] = None,
    **overrides,
) -> List[jax.Array]:
    """Least squares for a heterogeneous batch of requests.

    ``As``/``bs``: equal-length sequences of tall matrices (m_i, n_i)
    and vectors (m_i,). Returns the per-request solutions ``x_i``
    (n_i,), in input order — each exactly (to roundoff) what
    ``lstsq(As[i], bs[i])`` on the same engine settings returns, but
    computed by one vmapped dispatch per shape bucket through the AOT
    executable cache.

    ``config``/``**overrides`` are the usual :class:`DHQRConfig` knobs
    (``policy=`` composes exactly as on ``lstsq``: trailing precision to
    the factor, apply precision to the solve, ``refine`` sweeps
    in-program). ``serve_config`` shapes the bucket lattice and batch
    cap; ``cache`` overrides the process-default executable cache.
    """
    scfg = serve_config or ServeConfig.from_env()
    cache = cache if cache is not None else default_cache()
    cfg, pol, _ = _resolve_dispatch_cfg("lstsq", config, overrides)
    _validate_requests(As, bs)
    rec, tid = _trace_sync_call("lstsq", len(As))
    out: "list[jax.Array | None]" = [None] * len(As)
    consume = _scatter_lstsq(As, lambda i, x: out.__setitem__(i, x))
    with _trace_sync_resolve(rec, tid):
        _dispatch_groups("lstsq", As, bs, cfg, scfg, cache, consume,
                         pol=pol, trace_id=tid)
    return out


def batched_sketched_lstsq(
    As: Sequence,
    bs: Sequence,
    config: Optional[DHQRConfig] = None,
    serve_config: Optional[ServeConfig] = None,
    cache: Optional[ExecutableCache] = None,
    **overrides,
) -> List[jax.Array]:
    """Sketched least squares for a heterogeneous batch — the serve
    tier's ``"sketch"`` kind (round 17): same bucketing/padding/cache/
    scatter pipeline as :func:`batched_lstsq`, but each bucket compiles
    the vmapped sketch-and-precondition program
    (``dhqr_tpu.solvers.sketch.batched_sketch_program``) instead of the
    direct factorization — the tall-skinny fast path, served.

    The sketch operator is derived from ``DHQR_SKETCH_*`` (seed,
    operator family, size factor) per bucket and rides the cache key,
    so prewarmed keys are the keys live dispatch hits and two
    processes sharing the seed agree on every compiled program.
    ``policy=``'s refine ADDS CGLS iterations on top of the
    ``SketchConfig`` baseline; precision knobs steer the core QR.
    """
    scfg = serve_config or ServeConfig.from_env()
    cache = cache if cache is not None else default_cache()
    cfg, pol, _ = _resolve_dispatch_cfg("sketch", config, overrides)
    _validate_requests(As, bs)
    rec, tid = _trace_sync_call("sketch", len(As))
    out: "list[jax.Array | None]" = [None] * len(As)
    consume = _scatter_lstsq(As, lambda i, x: out.__setitem__(i, x))
    with _trace_sync_resolve(rec, tid):
        _dispatch_groups("sketch", As, bs, cfg, scfg, cache, consume,
                         pol=pol, trace_id=tid)
    return out


def batched_qr(
    As: Sequence,
    config: Optional[DHQRConfig] = None,
    serve_config: Optional[ServeConfig] = None,
    cache: Optional[ExecutableCache] = None,
    **overrides,
) -> List:
    """Factor a heterogeneous batch: per-request ``QRFactorization``\\ s,
    in input order, each the exact (to roundoff) packed factorization of
    its request — computed by one donated vmapped dispatch per bucket
    (the stacked buffer is consumed and aliased into the stacked H).

    A ``policy=`` with ``refine > 0`` arms solve-side refinement on each
    returned factorization, exactly like ``qr(A, policy=...)`` (the
    original matrix rides along for the residual matvec).
    """
    scfg = serve_config or ServeConfig.from_env()
    cache = cache if cache is not None else default_cache()
    cfg, pol, qr_solve_args = _resolve_dispatch_cfg("qr", config, overrides)
    _validate_requests(As, None)
    rec, tid = _trace_sync_call("qr", len(As))
    out: "list | None" = [None] * len(As)
    consume = _scatter_qr(As, lambda i, f: out.__setitem__(i, f),
                          qr_solve_args)
    with _trace_sync_resolve(rec, tid):
        _dispatch_groups("qr", As, None, cfg, scfg, cache, consume,
                         pol=pol, trace_id=tid)
    return out


def prewarm(
    shapes: Sequence,
    kind: str = "lstsq",
    config: Optional[DHQRConfig] = None,
    serve_config: Optional[ServeConfig] = None,
    cache: Optional[ExecutableCache] = None,
    **overrides,
) -> List[CacheKey]:
    """Compile the executables a request mix will need, ahead of traffic.

    ``shapes``: iterable of raw request-shape specs ``(count, m, n)`` or
    ``(count, m, n, dtype)`` (dtype defaults to float32). A spec's
    ``count`` means "this many same-shape requests arriving in one
    batched call". Shapes are bucketed by the same planner live dispatch
    uses, and the compiled key set is the UNION of

    * each spec's own arrival (chunked past ``serve_config.max_batch``
      exactly as live dispatch chunks — the remainder chunk has its own
      batch bucket), and
    * for specs whose shapes share a bucket, their combined arrival
      (live ``_group_by_bucket`` merges same-bucket requests from one
      call, which plans a larger batch key than either spec alone),

    so a mix served as declared — specs separately or together — hits
    only prewarmed keys. Returns the (deduplicated) keys in compile
    order; stats land on the cache's counters like any other compile.
    """
    scfg = serve_config or ServeConfig.from_env()
    cache = cache if cache is not None else default_cache()
    # Shared resolver: prewarmed keys must be the keys live dispatch
    # (sync or queued) hits, policy presets and refine placement included.
    cfg, pol, _ = _resolve_dispatch_cfg(kind, config, overrides)
    per_arrival: "list[tuple[Bucket, int]]" = []
    merged: "dict[Bucket, int]" = {}
    for spec in shapes:
        spec = tuple(spec)
        if len(spec) == 3:
            count, m, n = spec
            dtype = "float32"
        elif len(spec) == 4:
            count, m, n, dtype = spec
        else:
            raise ValueError(
                f"prewarm spec must be (count, m, n[, dtype]), got {spec!r}"
            )
        bucket = plan_bucket(int(m), int(n), dtype, scfg)
        per_arrival.append((bucket, int(count)))
        merged[bucket] = merged.get(bucket, 0) + int(count)
    keys: "list[CacheKey]" = []
    bucket_cfgs: "dict[Bucket, DHQRConfig]" = {}
    for bucket, count in per_arrival + list(merged.items()):
        # One plan resolution per bucket (``plan="auto"`` TUNES here on
        # a DB miss — prewarm is exactly where that cost belongs), via
        # the same resolver live dispatch uses, so prewarmed keys stay
        # the keys serving hits.
        if bucket not in bucket_cfgs:
            bucket_cfgs[bucket] = _resolve_bucket_plan(kind, cfg, bucket, pol)
        cfg_b = bucket_cfgs[bucket]
        for lo in range(0, count, scfg.max_batch):
            chunk_count = min(scfg.max_batch, count - lo)
            key, _ = _plan_key(kind, chunk_count, bucket.m, bucket.n,
                               bucket.dtype, cfg_b, scfg)
            if key not in keys:
                keys.append(key)
                cache.get_or_compile(key, partial(_lower_for_key, key))
    return keys


# Comms contract (dhqr-audit): the bucket dispatch is contracted
# COLLECTIVE-FREE — requests are embarrassingly parallel, so any psum
# or gather appearing in bucket_program's trace under a sharded batch
# axis is a DHQR301 finding, and the donated factor dispatch must keep
# its input-output aliasing (DHQR304, analysis/comms_pass.check_donation).
