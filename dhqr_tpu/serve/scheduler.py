"""Async serving front-end: deadline-aware continuous micro-batching.

The batched tier (``serve.engine``) answers "here is a pre-collected
request list" — but production traffic from many concurrent users is a
STREAM: requests arrive one at a time, each with its own latency budget
and tenant, and nobody upstream collects them into convenient lists.
This module is the admission layer that turns the existing bucket
lattice / AOT executable cache / per-bucket tuned plans into a service
(arXiv 2112.09017 frames TPU dense linear algebra as exactly this kind
of serving workload):

* :meth:`AsyncScheduler.submit` accepts one request — ``(kind, A, b)``
  plus ``deadline`` / ``tenant`` / ``policy`` / ``plan`` — validates it
  with the sync tier's own checks, and returns a
  ``concurrent.futures.Future``;
* queued requests coalesce per (kind, bucket, resolved-config) group —
  the same grouping ``batched_lstsq`` computes for a list — and a
  dispatcher loop launches a group as ONE stacked dispatch when it
  reaches the batch cap ("full"), when its oldest request's deadline
  minus the bucket's EWMA dispatch latency approaches ("deadline"), or
  when its oldest request has waited the flush interval ("interval");
* within an oversubscribed flush, requests are picked by smooth weighted
  round-robin across tenants (``SchedulerConfig.tenant_weights``), so a
  flooding tenant cannot starve the others out of a bucket;
* past ``SchedulerConfig.queue_depth`` total queued requests — or when
  the admission-priced deadline check says the queue's expected drain
  time already exceeds the request's budget — ``submit`` rejects with
  :class:`BackpressureError` carrying a ``retry_after`` hint — bounded
  queues keep the tail latency bounded, and a request that cannot make
  its deadline is refused at the door instead of timing out inside;
* :meth:`AsyncScheduler.drain` / :meth:`AsyncScheduler.shutdown` flush
  and complete everything in flight, so rolling restarts never drop
  accepted work.

Failure behavior is DESIGNED (round 12, docs/DESIGN.md "Fault model"):
a failed flush retries with exponential backoff capped by the oldest
in-group deadline; retries that keep failing bisect the batch until the
poison request fails ALONE (typed) and the rest succeed; a quarantined
program key backs off for its cooldown; a crashed dispatcher worker is
detected, its in-flight requests requeued, and a replacement thread
respawned. Every submitted request's future resolves — success or a
typed :class:`~dhqr_tpu.serve.errors.ServeError` — never hangs. The
``serve.worker`` fault-injection site (``dhqr_tpu.faults``) drives the
crash path deterministically in tests and the chaos benchmark. Round 13
adds the numerics sibling: with ``DHQRConfig.guards`` armed, a
non-finite output row raises a typed
:class:`~dhqr_tpu.numeric.NumericalError`, which skips retry (the
failure lives in the request's data) and goes straight to the bisection
path — one bad matrix fails alone while its batch neighbors complete.

ONE dispatch path, by construction: a flush calls the engine's own
``_dispatch_groups`` with consumers built by the engine's own
``_scatter_lstsq`` / ``_scatter_qr``, and cache keys are minted by the
engine's ``_plan_key`` — this module owns no lowering, no key scheme,
and no padding logic of its own, so steady state stays zero-recompile
against a cache prewarmed for the sync tier (pinned by
tests/test_scheduler.py key-parity and by the lint jaxpr pass, which
refuses to trace the async entry if the functions diverge).

Latency accounting rides ``utils.profiling``: a bounded
:class:`~dhqr_tpu.utils.profiling.LatencyHistogram` of submit→complete
seconds (p50/p99 in :meth:`AsyncScheduler.stats`), one
:class:`~dhqr_tpu.utils.profiling.Ewma` of dispatch seconds per bucket
(the deadline-flush lead time), and flush-reason / admission
:class:`~dhqr_tpu.utils.profiling.Counters`.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import traceback
from concurrent.futures import Future
from typing import Optional

from dhqr_tpu.armor.errors import ShardFailure
from dhqr_tpu.faults import harness as _faults
from dhqr_tpu.numeric.errors import NumericalError
from dhqr_tpu.obs import metrics as _obs_metrics
from dhqr_tpu.obs import trace as _obs
from dhqr_tpu.serve import engine as _engine
from dhqr_tpu.serve.buckets import Bucket, plan_bucket
from dhqr_tpu.serve.cache import ExecutableCache, default_cache
from dhqr_tpu.serve.errors import (
    BackpressureError,
    DeadlineExceeded,
    DispatchFailed,
    Quarantined,
    ServeError,
)
from dhqr_tpu.utils import lockwitness as _lockwitness
from dhqr_tpu.utils.config import DHQRConfig, SchedulerConfig, ServeConfig
from dhqr_tpu.utils.profiling import (
    Counters,
    Ewma,
    LatencyHistogram,
    sync as _sync,
)

# Deadline-flush lead time: dispatch is launched when
#   now >= deadline - (_LEAD_FACTOR * ewma + _LEAD_FLOOR_S)
# so the expected dispatch latency plus a 25% EWMA-noise margin still
# fits inside the budget. Not a config knob: the measurable quantity is
# the EWMA; the margin only absorbs its variance.
_LEAD_FACTOR = 1.25
_LEAD_FLOOR_S = 1e-3


# BackpressureError moved to serve/errors.py in round 12 (it is one of
# the typed ServeError family now); the name stays importable from here.
__all__ = ["AsyncScheduler", "BackpressureError", "dispatch_program"]


@dataclasses.dataclass
class _Pending:
    """One queued request (everything the flush stage needs).

    ``attempts`` counts FAILED flushes this request has ridden (the
    retry/bisect escalation key); ``claimed`` marks a future already
    moved to RUNNING by a prior flush — a requeued request must not
    claim twice (``set_running_or_notify_cancel`` raises on a RUNNING
    future)."""

    seq: int
    A: object
    b: object
    tenant: str
    submitted_at: float
    deadline_at: float
    future: Future
    attempts: int = 0
    claimed: bool = False
    # Round 14: the obs trace id minted at submit (None when tracing
    # was disarmed at admission). Host-side request state ONLY — never
    # part of _plan_key/CacheKey, never traced into a program.
    trace_id: "int | None" = None


class _Group:
    """Pending requests sharing (kind, bucket, resolved config) — the
    unit the dispatcher flushes as one stacked micro-batch. For the
    ``"update"`` kind (round 17) the group is keyed on the LIVE
    :class:`~dhqr_tpu.solvers.update.UpdatableQR` instead: its ops are
    ordered state mutations, so the group holds the session, flushes
    strictly FIFO, and is serialized via ``busy`` (two workers must
    never interleave ops against one factorization)."""

    __slots__ = ("kind", "bucket", "cfg", "pol", "qr_solve_args", "queue",
                 "credits", "not_before", "session", "busy", "gkey")

    def __init__(self, kind, bucket, cfg, pol, qr_solve_args,
                 session=None, gkey=None):
        self.kind = kind
        self.bucket = bucket
        self.cfg = cfg
        self.pol = pol
        self.qr_solve_args = qr_solve_args
        self.session = session      # "update" kind: the UpdatableQR
        self.busy = False           # "update" kind: one flush at a time
        self.gkey = gkey            # "update" kind: for idle pruning
        self.queue: "collections.deque[_Pending]" = collections.deque()
        # Smooth-WRR credit per tenant, persisted ACROSS flushes (a light
        # tenant that loses an oversubscribed flush is ahead next flush).
        self.credits: "dict[str, float]" = {}
        # Retry backoff horizon: after a failed flush the group does not
        # re-flush before this clock time (drain ignores it).
        self.not_before: float = 0.0


class AsyncScheduler:
    """Thread-safe admission queue + micro-batching dispatcher over the
    batched serving tier.

    >>> sched = AsyncScheduler(block_size=8)
    >>> fut = sched.submit("lstsq", A, b, deadline=0.05, tenant="acme")
    >>> x = fut.result()            # the same x batched_lstsq returns
    >>> sched.stats()["latency"]    # p50/p99, flush reasons, EWMA, ...
    >>> sched.shutdown()            # drains, then stops the dispatcher

    Construction mirrors ``batched_lstsq``: ``config``/``**overrides``
    are the base :class:`DHQRConfig` knobs every request inherits
    (per-request ``policy=``/``plan=`` override them, each combination
    coalescing as its own group), ``serve_config`` the bucket lattice,
    ``cache`` the executable cache (the process default when omitted, so
    a cache prewarmed for the sync tier serves the queue too).

    ``start=False`` skips the dispatcher thread: nothing flushes until
    :meth:`poll` (or :meth:`drain`) is called, and ``clock`` can be a
    fake — that is how tests pin deadline/fairness decisions without
    wall-clock races. The default is a daemon dispatcher thread driven
    by ``time.monotonic``.
    """

    def __init__(
        self,
        config: Optional[DHQRConfig] = None,
        serve_config: Optional[ServeConfig] = None,
        sched_config: Optional[SchedulerConfig] = None,
        cache: Optional[ExecutableCache] = None,
        clock=time.monotonic,
        start: bool = True,
        workers: int = 2,
        **overrides,
    ) -> None:
        self._scfg = serve_config or ServeConfig.from_env()
        self._kcfg = sched_config or SchedulerConfig.from_env()
        self._cache = cache if cache is not None else default_cache()
        self._base_config = config
        self._overrides = dict(overrides)   # guarded by: frozen

        self._clock = clock
        self._lock = _lockwitness.make_lock("AsyncScheduler._lock")
        self._work = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        # Resolution memo is read/written from every submitting thread,
        # so it lives under the lock like the queues, even though
        # resolution itself is pure given the base config.
        self._resolved: dict = {}              # guarded by: _lock
        # Fail fast on a bad base config (same checks the sync tier runs)
        # rather than on the first submit; also seeds the resolution memo.
        self._resolve(None, None, "lstsq")
        self._groups: "dict[tuple, _Group]" = {}   # guarded by: _lock
        self._depth = 0            # queued, not yet popped for dispatch
        self._inflight = 0         # popped, dispatch not yet completed
        self._seq = 0
        self._draining = False
        self._closed = False
        self._crash_streak = 0     # consecutive worker crashes (backoff)
        self._last_crash: "str | None" = None   # last crash traceback

        self.counters = Counters()
        self.latency = LatencyHistogram()
        self._ewma: "dict[Bucket, Ewma]" = {}  # guarded by: _lock
        self.keys_seen: set = set()            # guarded by: _lock
        # Unified metrics (round 14): serve.sched.* dotted names on the
        # process registry; weakly held, so test schedulers leave with GC.
        _obs_metrics.registry().register("serve.sched", self)

        # Dispatcher pool: each worker runs the same select→take→flush
        # loop against the shared lock, so two ready groups flush
        # CONCURRENTLY — worker B's host-side padding/scatter overlaps
        # worker A's device execution (XLA releases the GIL; measured
        # worth ~15-20% requests/s on the CPU open-loop ladder, where
        # one dispatch is ~half host prep). Request-level ordering needs
        # nothing from the workers: each flush owns its popped requests,
        # and group selection under the lock is atomic.
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self._threads: "list[threading.Thread]" = []  # guarded by: _lock
        if start:
            self._threads = [
                threading.Thread(target=self._run,
                                 name=f"dhqr-serve-dispatch-{i}", daemon=True)
                for i in range(workers)
            ]
            for t in self._threads:
                t.start()

    # ------------------------------------------------------------ admission

    def _resolve(self, policy, plan, kind):
        """Resolve (policy, plan, kind) -> (cfg, pol, qr_solve_args) via
        the engine's own resolver, memoized per combination (resolution
        is pure given the base config)."""
        try:
            memo_key = (kind, policy, plan)
            with self._lock:
                hit = self._resolved.get(memo_key)
        except TypeError:           # unhashable policy/plan object
            memo_key, hit = None, None
        if hit is not None:
            return hit
        ov = dict(self._overrides)
        if policy is not None:
            ov["policy"] = policy
        if plan is not None:
            ov["plan"] = plan
        # Resolution runs OUTSIDE the lock (it may validate configs);
        # a racing duplicate just recomputes the same pure value.
        resolved = _engine._resolve_dispatch_cfg(kind, self._base_config, ov)
        if memo_key is not None:
            with self._lock:
                self._resolved[memo_key] = resolved
        return resolved

    def submit(
        self,
        kind: str,
        A,
        b=None,
        *,
        deadline: "float | None" = None,
        tenant: str = "default",
        policy=None,
        plan=None,
    ) -> Future:
        """Queue one request; returns a Future resolving to exactly what
        the sync tier returns for it (``x`` for ``kind="lstsq"``, a
        ``QRFactorization`` for ``kind="qr"``).

        ``deadline`` is the request's latency budget in SECONDS from now
        (``SchedulerConfig.slo_ms`` when omitted) — the dispatcher
        flushes the request's bucket early enough that the bucket's
        expected dispatch latency still fits inside it. Raises
        :class:`BackpressureError` past the queue-depth high-water mark
        and ``RuntimeError`` after :meth:`shutdown`.
        """
        if kind == "update":
            # Round 17: ops against a live UpdatableQR. ``A`` is the
            # session, ``b`` the op payload ("update"/"downdate", u, v)
            # or ("solve", rhs). No config resolution — the session
            # already owns its numerics — and no stacked program: the
            # flush runs the ops host-side, in submission order,
            # serialized per session (_Group.busy).
            from dhqr_tpu.solvers.update import UpdatableQR

            if not isinstance(A, UpdatableQR):
                raise ValueError(
                    "kind='update' takes an UpdatableQR session as its "
                    f"first argument, got {type(A).__name__}"
                )
            if policy is not None or plan is not None:
                raise ValueError(
                    "kind='update' ops take no policy=/plan= — the "
                    "session's numerics were fixed at construction"
                )
            if (not isinstance(b, tuple) or not b
                    or b[0] not in ("update", "downdate", "solve")
                    or (b[0] == "solve" and len(b) != 2)
                    or (b[0] in ("update", "downdate") and len(b) != 3)):
                raise ValueError(
                    "kind='update' payload must be ('update', u, v), "
                    "('downdate', u, v) or ('solve', rhs), got "
                    f"{b!r:.120}"
                )
            cfg = pol = qr_solve_args = None
        else:
            cfg, pol, qr_solve_args = self._resolve(policy, plan, kind)
            if kind in ("lstsq", "sketch"):
                if b is None:
                    raise ValueError(
                        f"kind={kind!r} needs a right-hand side b")
                _engine._validate_requests([A], [b])
            else:
                if b is not None:
                    raise ValueError("kind='qr' takes no right-hand side")
                _engine._validate_requests([A], None)
        bucket = plan_bucket(A.shape[0], A.shape[1], A.dtype, self._scfg)
        if deadline is None:
            deadline = self._kcfg.slo_ms / 1e3
        elif not deadline > 0:
            raise ValueError(f"deadline must be > 0 seconds, got {deadline}")

        now = self._clock()
        fut: Future = Future()
        # Trace admission (round 14): the id is minted HERE — the
        # recorder read is the one None check the disarmed path pays —
        # and rides the future (fut.trace_id), the queue entry, and any
        # typed error this request ever resolves with.
        rec = _obs.active()
        tid = rec.mint() if rec is not None else None
        if tid is not None:
            fut.trace_id = tid
        est = None
        with self._lock:
            if self._closed:
                raise RuntimeError("scheduler is shut down")
            if self._depth >= self._kcfg.queue_depth:
                self.counters.bump("rejected")
                retry = self._retry_after_locked()
                err = BackpressureError(
                    f"admission queue full ({self._depth} >= "
                    f"{self._kcfg.queue_depth}); retry in ~{retry:.3f}s",
                    retry_after=retry)
                if rec is not None:
                    rec.attach(err, tid)
                    rec.event(tid, "reject", t=now, cause="queue_full",
                              retry_after=round(retry, 6),
                              depth=self._depth)
                raise err
            # Admission-priced deadline (ROADMAP item 1 remainder): if
            # the queue's expected drain time — batches ahead of this
            # request x the bucket's measured EWMA dispatch latency —
            # already exceeds the request's budget, reject NOW with a
            # priced retry hint rather than accept work destined to blow
            # its deadline inside the queue. A bucket with no EWMA yet
            # (first request) is always admitted: rejection is priced on
            # measurement, never on a guess.
            est = self._admission_estimate_locked(bucket)
            if est is not None and est > deadline:
                self.counters.bump("rejected_unmeetable")
                retry = max(self._kcfg.flush_interval_ms / 1e3,
                            est - deadline)
                err = BackpressureError(
                    f"deadline {deadline:.3f}s cannot be met at the "
                    f"current queue (expected wait ~{est:.3f}s); retry "
                    f"in ~{retry:.3f}s", retry_after=retry)
                if rec is not None:
                    rec.attach(err, tid)
                    rec.event(tid, "reject", t=now, cause="unmeetable",
                              est_s=round(est, 6),
                              retry_after=round(retry, 6))
                raise err
            gkey = (kind, id(A)) if kind == "update" else \
                (kind, bucket, cfg, qr_solve_args)
            group = self._groups.get(gkey)
            if group is None:
                group = self._groups[gkey] = _Group(
                    kind, bucket, cfg, pol, qr_solve_args,
                    session=A if kind == "update" else None,
                    gkey=gkey if kind == "update" else None)
            self._seq += 1
            # The submit span is recorded BEFORE the queue entry becomes
            # visible (append + notify): with live dispatcher workers, a
            # flush can otherwise race ahead of the admission span and
            # the flight dump would open mid-path instead of at
            # "submit" (the first-span contract the benchmark and the
            # runbook rely on). The recorder lock is a leaf — same
            # ordering the reject events above already use.
            if rec is not None:
                attrs = {"kind": kind, "bucket": bucket.label,
                         "tenant": tenant, "deadline_s": round(deadline, 6),
                         "depth": self._depth + 1}
                if est is not None:   # the admission price, when measured
                    attrs["est_s"] = round(est, 6)
                rec.event(tid, "submit", t=now, **attrs)
            group.queue.append(_Pending(
                self._seq, A, b, tenant, now, now + deadline, fut,
                trace_id=tid))
            self._depth += 1
            self.counters.bump("submitted")
            self._work.notify()
        return fut

    def _retry_after_locked(self) -> float:
        """Backpressure hint: queue depth over the average dispatch
        latency's implied drain rate, floored at the flush interval —
        the floor is the EMPTY-EWMA clamp: before any dispatch has been
        measured (first-request buckets, a cold scheduler) the product
        is 0.0, and a zero/negative retry hint would have clients
        busy-spin on a queue that cannot possibly have drained."""
        lat = [e.value for e in self._ewma.values() if e.value is not None]
        avg = sum(lat) / len(lat) if lat else 0.0
        batches = -(-self._depth // max(1, self._scfg.max_batch))
        return max(self._kcfg.flush_interval_ms / 1e3, batches * avg)

    def _admission_estimate_locked(self, bucket: Bucket) -> "float | None":
        """Expected seconds until a request submitted NOW into ``bucket``
        completes, priced from queue depth x the bucket's EWMA dispatch
        latency. None when the bucket has no measurement yet (the
        admission check must not reject on a guess). The global depth is
        a deliberate over-approximation of the per-group backlog — under
        mixed traffic it prices the dispatcher contention ahead of this
        request, which is exactly what delays its flush."""
        ewma = self._ewma.get(bucket)
        val = ewma.value if ewma is not None else None
        if val is None or val <= 0.0:
            return None
        batches = -(-(self._depth + 1) // max(1, self._scfg.max_batch))
        return batches * val

    # ----------------------------------------------------------- flush policy

    def _lead_s(self, bucket: Bucket) -> float:
        ewma = self._ewma.get(bucket)
        val = ewma.value if ewma is not None else None
        return _LEAD_FACTOR * (val or 0.0) + _LEAD_FLOOR_S

    def _flush_reason(self, group: _Group, now: float) -> "str | None":
        if now < group.not_before:
            return None         # retry backoff window (drain bypasses)
        if len(group.queue) >= self._scfg.max_batch:
            return "full"
        oldest = group.queue[0]
        if now >= oldest.deadline_at - self._lead_s(group.bucket):
            return "deadline"
        if now - oldest.submitted_at >= self._kcfg.flush_interval_ms / 1e3:
            return "interval"
        return None

    def _next_wake_locked(self, now: float) -> "float | None":
        """Seconds until the earliest future flush condition, None when
        nothing is queued."""
        soonest = None
        for group in self._groups.values():
            if not group.queue:
                continue
            if group.busy:
                # An update session mid-flush: its queue will be
                # re-examined when the flush completes (poll loops), so
                # it must not drive the wake horizon to "now" — that
                # would busy-spin the dispatcher against the busy gate.
                continue
            oldest = group.queue[0]
            t = min(
                oldest.deadline_at - self._lead_s(group.bucket),
                oldest.submitted_at + self._kcfg.flush_interval_ms / 1e3,
            )
            # A group in retry backoff is not ready before not_before,
            # whatever its deadlines say — without this the dispatcher
            # busy-spins on a past flush horizon for the backoff window.
            t = max(t, group.not_before)
            soonest = t if soonest is None else min(soonest, t)
        if soonest is None:
            return None
        return max(soonest - now, 0.0)

    def _select_locked(self, now: float, drain: bool):
        """Pick the most urgent ready group: earliest oldest-deadline
        first (EDF) among ready groups. Returns (group, reason) or None."""
        best, best_reason = None, None
        for group in self._groups.values():
            if not group.queue:
                continue
            if group.busy:
                continue    # update session mid-flush: ordering gate
            reason = "drain" if drain else self._flush_reason(group, now)
            if reason is None:
                continue
            if best is None or \
                    group.queue[0].deadline_at < best.queue[0].deadline_at:
                best, best_reason = group, reason
        return (best, best_reason) if best is not None else None

    def _take_locked(self, group: _Group, count: int) -> "list[_Pending]":
        """Pop up to ``count`` requests: the group's oldest request — the
        one whose deadline/interval triggered the flush — is ALWAYS in
        the taken head, the rest by smooth weighted round-robin across
        tenants (FIFO within a tenant). Each round every tenant with
        pending work gains its weight of credit, the richest tenant
        (ties to the oldest head request) yields its head and pays the
        active total back; credit persists on the group across flushes
        (``_Group.credits``) so a light tenant that loses one
        oversubscribed partial flush starts the next one ahead instead
        of from zero — without persistence a 5:1 flooder holding 2+ deep
        backlog starves a light tenant's head request past its deadline
        on every cycle. Credit for tenants with nothing left queued is
        dropped (classic smooth-WRR idle reset). With equal weights this
        is plain FIFO interleaving; with 3:1 a flooding tenant keeps 3/4
        of an oversubscribed flush and the light tenant still lands
        1/4."""
        if group.kind == "update":
            # Ops are ordered state mutations: strict FIFO, tenant
            # arbitration never reorders a session's op stream.
            taken = [group.queue.popleft()
                     for _ in range(min(count, len(group.queue)))]
            self._depth -= len(taken)
            return taken
        by_tenant: "dict[str, collections.deque[_Pending]]" = {}
        for p in group.queue:
            by_tenant.setdefault(p.tenant, collections.deque()).append(p)
        if len(by_tenant) == 1:     # fast path: nothing to arbitrate
            taken = [group.queue.popleft()
                     for _ in range(min(count, len(group.queue)))]
            self._depth -= len(taken)
            group.credits.clear()
            return taken
        credit = group.credits
        taken: "list[_Pending]" = []

        def take_round(forced: "str | None" = None) -> None:
            total = sum(self._kcfg.weight_for(t) for t in by_tenant)
            winner = forced
            for t in by_tenant:
                credit[t] = credit.get(t, 0.0) + self._kcfg.weight_for(t)
                if forced is None and (
                        winner is None or credit[t] > credit[winner] or (
                            credit[t] == credit[winner]
                            and by_tenant[t][0].seq
                            < by_tenant[winner][0].seq)):
                    winner = t
            credit[winner] = credit.get(winner, 0.0) - total
            taken.append(by_tenant[winner].popleft())
            if not by_tenant[winner]:
                del by_tenant[winner]

        # Head-of-line guarantee: take the group's oldest request first,
        # charged to its tenant like a won round.
        take_round(forced=group.queue[0].tenant)
        while len(taken) < count and by_tenant:
            take_round()
        taken_ids = {id(p) for p in taken}
        remaining = [p for p in group.queue if id(p) not in taken_ids]
        group.queue.clear()
        group.queue.extend(remaining)
        still_active = {p.tenant for p in remaining}
        for t in [t for t in credit if t not in still_active]:
            del credit[t]
        self._depth -= len(taken)
        return taken

    # ------------------------------------------------------------- dispatch

    def _span_batch(self, requests, name: str, t: "float | None" = None,
                    per=None, **attrs) -> None:
        """Record one span per request — THE one spelling of the
        fetch-recorder/None-check/loop block every batch-level hop uses
        (a hop recorded through any other path risks drifting out of
        the complete-path invariant the benchmark pins). ``per(p, now)``
        supplies per-request attributes; ``attrs`` may include a
        ``batch`` span attribute (hence the positional's name);
        disarmed cost is the single recorder read."""
        rec = _obs.active()
        if rec is None:
            return
        now = self._clock() if t is None else t
        for p in requests:
            extra = per(p, now) if per is not None else {}
            rec.event(p.trace_id, name, t=now, **attrs, **extra)

    def _flush(self, group: _Group, taken: "list[_Pending]",
               reason: str) -> None:
        """Dispatch one popped micro-batch through the engine's shared
        path. Runs OUTSIDE the scheduler lock (a compile or a slow
        dispatch must not block admission). A dispatch failure is
        HANDLED here — retry with backoff, bisect to isolate a poison
        request, or resolve the futures with their typed error — so the
        exception never reaches the worker loop and every taken request
        either completes, requeues, or fails typed."""
        # Claim every future before dispatch: a client that already
        # called fut.cancel() drops out here, and a claimed (RUNNING)
        # future can no longer be cancelled, so the set_result /
        # set_exception below can never raise InvalidStateError (which
        # would kill the dispatcher worker). A requeued request arrives
        # already claimed and is kept as-is.
        live: "list[_Pending]" = []
        for p in taken:
            if p.claimed or p.future.set_running_or_notify_cancel():
                p.claimed = True
                live.append(p)
            else:
                self.counters.bump("cancelled")
        if not live:
            return
        self.counters.bump(f"flush_{reason}")
        self._span_batch(
            live, "flush", reason=reason, batch=len(live),
            per=lambda p, now: {"wait_s": round(now - p.submitted_at, 6)})
        try:
            self._dispatch_batch(group, live)
        except Exception as e:
            # Requests from chunks that completed before the failure
            # were already resolved by _dispatch_batch: escalate only
            # the unresolved remainder.
            self._handle_failure(
                group, [p for p in live if not p.future.done()], e)

    def _dispatch_batch(self, group: _Group,
                        batch: "list[_Pending]") -> None:
        """One engine dispatch of ``batch``; resolves every future with
        its result on success, raises (typed where the engine/cache
        classified it) on failure WITHOUT touching the futures — the
        caller decides between retry, bisect and typed failure."""
        if group.kind == "update":
            self._dispatch_update_ops(group, batch)
            return
        self.counters.bump("dispatches")
        self._span_batch(batch, "dispatch", bucket=group.bucket.label,
                         batch=len(batch))
        As = [p.A for p in batch]
        resolved: "list[tuple[int, object]]" = []
        raw_outs: "list[object]" = []
        emit = lambda i, val: resolved.append((i, val))  # noqa: E731
        if group.kind in ("lstsq", "sketch"):
            bs = [p.b for p in batch]
            consume_inner = _engine._scatter_lstsq(As, emit)
        else:
            bs = None
            consume_inner = _engine._scatter_qr(As, emit,
                                                group.qr_solve_args)

        def consume(chunk, key, outs):
            with self._lock:
                self.keys_seen.add(key)
            raw_outs.append(outs)
            consume_inner(chunk, key, outs)

        t0 = self._clock()
        compile0 = self._cache.timer.total("aot_compile")
        try:
            _engine._dispatch_groups(
                group.kind, As, bs, group.cfg, self._scfg, self._cache,
                consume, pol=group.pol)
        except Exception:
            # A multi-chunk batch (a drain can span many engine chunks)
            # failed partway: chunks that already dispatched and
            # consumed are FINISHED device work — resolve their futures
            # now so the caller's retry/bisect only re-pays the failed
            # remainder instead of the whole batch.
            self._resolve_completed_chunks(batch, resolved, raw_outs)
            raise
        out: "list[object | None]" = [None] * len(batch)
        for i, val in resolved:
            out[i] = val
        # Fence on the STACKED program outputs (O(1) arrays per
        # chunk), not the per-request slices (O(batch) readback
        # kernels — measured ~10 ms/flush on CPU): once the stack is
        # ready, the truncating slices the futures carry are views
        # of completed work.
        _sync(raw_outs)
        # The EWMA prices WARM dispatch, so subtract any AOT compile
        # that happened inside this flush (first touch of a novel
        # bucket, recompile after eviction). Priced WITH the compile,
        # one multi-second spike would have the admission check reject
        # every normal-deadline submit for the bucket — and since
        # rejected requests never dispatch, the EWMA could never decay:
        # a permanent starvation loop. Steady state is zero-recompile,
        # so warm dispatch time is also what the estimate is FOR. (A
        # concurrent worker's compile landing in the window can only
        # over-subtract; the clamp keeps the sample sane.)
        compile_s = self._cache.timer.total("aot_compile") - compile0
        seconds = max(self._clock() - t0 - compile_s, 0.0)
        chunks = -(-len(batch) // self._scfg.max_batch)
        # EWMA updates on SUCCESS only: a failed dispatch returns in
        # exception time, not dispatch time, and under injected faults
        # those near-zero samples would drag the deadline-flush lead
        # toward zero exactly when dispatches are least reliable.
        # Under the lock: _retry_after_locked and stats() iterate
        # _ewma, and a first-dispatch setdefault would resize the
        # dict mid-iteration.
        with self._lock:
            self._ewma.setdefault(group.bucket, Ewma()).update(
                seconds / max(1, chunks))
            self._crash_streak = 0  # dispatching again: crash storm over
        done = self._clock()
        # Warm dispatch seconds vs AOT compile seconds split per
        # request — the per-phase evidence the ROADMAP's TPU
        # re-laddering needs (EWMA-free: these are THIS flush's
        # measurements, not a smoothed estimate).
        self._span_batch(batch, "dispatch_ok", t=done,
                         seconds=round(seconds, 6),
                         compile_s=round(compile_s, 6), chunks=chunks)
        for p, val in zip(batch, out):
            self._resolve_success(p, val, done)

    def _dispatch_update_ops(self, group: _Group,
                             batch: "list[_Pending]") -> None:
        """The ``"update"`` kind's flush (round 17): run each op
        against the group's live UpdatableQR, in submission order,
        resolving per op as it commits. No stacked program, no cache —
        the ops ARE host-orchestrated state mutations — but the fault
        sites (``serve.dispatch``/``serve.latency``), the typed-error
        contract, the spans and the latency accounting all apply
        exactly as on the batched kinds.

        Failure routing: a :class:`NumericalError` is a property of
        the op's DATA (a poisoned vector, a refactor the PR-8 ladder
        refused) — it resolves THAT op typed and the stream continues
        (the session rolled the op back, so neighbors are safe); the
        round-19 ``ShardFailure`` is carved out as presumed-transient
        infrastructure, exactly as in ``_handle_failure``. Any
        other failure raises out of the flush with the already-resolved
        ops excluded, so ``_handle_failure`` retries only the remainder
        — requeued at the front, order preserved — and a transient
        injected fault behaves exactly as on a batched dispatch."""
        self.counters.bump("dispatches")
        self._span_batch(batch, "dispatch", bucket=group.bucket.label,
                         batch=len(batch))
        session = group.session
        for p in sorted(batch, key=lambda q: q.seq):
            _faults.latency("serve.latency")
            try:
                _faults.fire("serve.dispatch")
                op = p.b[0]
                if op == "solve":
                    val = session.solve(p.b[1])
                elif op == "update":
                    val = session.update(p.b[1], p.b[2])
                else:
                    val = session.downdate(p.b[1], p.b[2])
            except ShardFailure:
                # Round 19: a lost shard contribution is presumed
                # TRANSIENT infrastructure, not poisoned data — raise
                # out of the flush (already-resolved ops excluded) so
                # _handle_failure routes it through retry/backoff like
                # a DispatchFailed, same as on the batched kinds.
                raise
            except NumericalError as e:
                self.counters.bump("numeric_failures")
                self.counters.bump("poisoned")
                self._span_batch([p], "numeric_isolate",
                                 cause=type(e).__name__, batch=1)
                self._fail(p, e)
                continue
            except ServeError:
                raise
            except Exception as e:
                raise DispatchFailed(
                    ("update", group.bucket.label, p.b[0]), e) from e
            self._resolve_success(p, val, self._clock())

    def _resolve_success(self, p: _Pending, val, done: float) -> None:
        self.latency.record(done - p.submitted_at)
        if done > p.deadline_at:
            self.counters.bump("deadline_misses")
        self.counters.bump("completed")
        _obs.event(p.trace_id, "resolve", t=done, outcome="ok",
                   e2e_s=round(done - p.submitted_at, 6))
        p.future.set_result(val)

    def _resolve_completed_chunks(self, batch: "list[_Pending]",
                                  resolved: "list[tuple[int, object]]",
                                  raw_outs: "list[object]") -> None:
        """A chunked dispatch failed after some chunks already consumed:
        fence those chunks' outputs and resolve their futures with the
        finished results. Callers then see them as done and only
        retry/bisect the remainder. If even the fence fails, resolve
        nothing — everything retries. (No EWMA sample either way: the
        timing window is polluted by the failure.)"""
        if not resolved:
            return
        try:
            _sync(raw_outs)
        except Exception:
            return
        done = self._clock()
        for i, val in resolved:
            self._resolve_success(batch[i], val, done)

    # ------------------------------------------------------ failure handling

    def _typed_error(self, group: _Group, exc: BaseException):
        """Every failure a future carries is typed: a ServeError (the
        engine and cache classify theirs — CompileFailed,
        DispatchFailed, Quarantined) or its round-13 numerics sibling
        ``NumericalError`` (the serve guard's output health check).
        Anything else — e.g. an XLA runtime error surfacing at the
        completion fence — is a dispatch failure."""
        if isinstance(exc, (ServeError, NumericalError)):
            return exc
        err = DispatchFailed((group.kind, group.bucket), exc)
        err.__cause__ = exc
        return err

    def _fail(self, p: _Pending, err: RuntimeError) -> None:
        self.counters.bump("failed")
        rec = _obs.active()
        if rec is not None:
            # The typed error carries its request's trace id(s), the
            # resolve span closes the path, and the on_error hook dumps
            # it while the spans are still resident (ObsConfig.auto_dump).
            rec.event(p.trace_id, "resolve", t=self._clock(),
                      outcome=type(err).__name__, error=str(err)[:200])
            rec.on_error(err, p.trace_id)
        p.future.set_exception(err)

    def _requeue(self, group: _Group, batch: "list[_Pending]",
                 not_before: float) -> None:
        """Put a failed batch back at the FRONT of its group (original
        order — they are the oldest work) and arm the backoff horizon."""
        with self._lock:
            group.queue.extendleft(reversed(batch))
            self._depth += len(batch)
            group.not_before = max(group.not_before, not_before)
            self._work.notify_all()

    def _handle_failure(self, group: _Group, batch: "list[_Pending]",
                        exc: Exception) -> None:
        """The retry / bisect / typed-failure escalation for one failed
        flush (docs/DESIGN.md "Fault model" has the state machine):

        1. requests whose deadline already passed fail typed NOW
           (DeadlineExceeded chaining the underlying error) — no retry
           can help them;
        2. a Quarantined key backs the group off for the remaining
           cooldown (deadline permitting) without spending retry
           budget — the quarantine IS the schedule; during drain it
           fails typed instead (drain means "complete everything now");
        2b. a NumericalError (round 13: the serve guard flagged
           non-finite output rows) skips retry entirely — the failure
           is in the request's data — and goes straight to bisection,
           so one bad matrix degrades itself, never its neighbors;
        3. other failures retry the whole batch with exponential
           backoff (``retry_base_ms * 2**k``) while attempts stay
           within ``max_retries`` AND the backoff still lands before
           the oldest in-batch deadline;
        4. out of budget, a multi-request batch BISECTS: halves
           dispatch independently, recursing on failure, until the
           poison request fails alone (typed) and everyone else's
           work completes.
        """
        err = self._typed_error(group, exc)
        now = self._clock()
        self.counters.bump("flush_failures")
        alive: "list[_Pending]" = []
        for p in batch:
            if now >= p.deadline_at:
                dead = DeadlineExceeded(
                    f"deadline passed after a failed dispatch "
                    f"({type(err).__name__}: {err})")
                dead.__cause__ = err
                self._fail(p, dead)
            else:
                alive.append(p)
        if not alive:
            return
        draining = self._draining
        if isinstance(err, Quarantined):
            # The cooldown is the retry schedule; attempts are not
            # spent on it (the compile was never re-run). Per-REQUEST
            # deadline gating: one tight-deadline rider must not force
            # typed failure on batchmates whose budgets absorb the
            # cooldown. A request that cannot wait fails typed NOW —
            # re-dispatching it is pointless, the quarantine guarantees
            # the failure. Draining: nobody waits (drain means
            # "complete everything now").
            wait = err.retry_after
            can_wait = [] if draining else \
                [p for p in alive if now + wait < p.deadline_at]
            waiting = set(map(id, can_wait))
            for p in alive:
                if id(p) not in waiting:
                    self._fail(p, err)
            if can_wait:
                self.counters.bump("retries")
                # Distinct vocabulary from the budgeted-retry span:
                # ``cooldown_s``, no ``attempt`` — the quarantine wait
                # spends no retry budget, and overloading the retry
                # span's fields with different semantics would corrupt
                # the runbook's reading of both.
                self._span_batch(can_wait, "retry", t=now,
                                 cause="Quarantined",
                                 cooldown_s=round(wait, 6))
                self._requeue(group, can_wait, now + wait)
            return
        if isinstance(err, NumericalError) and \
                not isinstance(err, ShardFailure):
            # Round 13: a numerical failure is a property of the
            # request's DATA — no backoff or retry can fix it, so no
            # retry budget is spent. A LONE request fails typed NOW
            # (re-dispatching it would deterministically reproduce the
            # same failure — the singleton second chance exists for
            # transients, which this is not); a batch goes straight to
            # bisection, which re-dispatches the halves (completing
            # the innocent batchmates) until the poison request fails
            # alone with the typed NumericalError.
            #
            # Round 19 carve-out: armor's ShardFailure is EXCLUDED —
            # a lost shard contribution is presumed transient
            # infrastructure (preemption, a flaky link), so it falls
            # through to the retry/backoff/bisect machinery below
            # exactly like a DispatchFailed; its sibling
            # CorruptionDetected stays on this bisect-isolation path
            # (the armor seam already spent the re-dispatches that
            # could have helped).
            self.counters.bump("numeric_failures")
            self._span_batch(alive, "numeric_isolate", t=now,
                             cause=type(err).__name__, batch=len(alive))
            if len(alive) == 1:
                self.counters.bump("poisoned")
                self._fail(alive[0], err)
            else:
                self._isolate_now(group, alive, err)
            return
        # Retry budget and backoff are PER REQUEST, like the deadline
        # gating above: a fresh request coalesced into a group whose
        # older rider already burned its retries requeues on its own
        # attempt-1 backoff; only requests that are out of budget, or
        # whose own deadline cannot absorb their backoff, take the
        # immediate isolation pass (a group bisects now, a lone request
        # re-dispatches once and fails typed only if it fails alone
        # again).
        #
        # EXCEPT for the "update" kind (round 17): its requests are
        # ORDERED state mutations against one live factorization, and a
        # per-request split could re-dispatch op k+1 now (escalate)
        # while op k waits out a backoff — a state no submission order
        # produces. The whole remainder moves as one unit: requeue ALL
        # alive ops (front, original order) when the HEAD op still has
        # budget and deadline room, else escalate ALL in order (the
        # update isolation path dispatches sequentially).
        if group.kind == "update":
            for p in alive:
                p.attempts += 1
            head = alive[0]
            backoff = (self._kcfg.retry_base_ms / 1e3
                       * (2 ** (head.attempts - 1)))
            if head.attempts <= self._kcfg.max_retries and \
                    now + backoff < head.deadline_at:
                self.counters.bump("retries")
                self._span_batch(alive, "retry", t=now,
                                 cause=type(err).__name__,
                                 backoff_s=round(backoff, 6),
                                 per=lambda p, _: {"attempt": p.attempts})
                self._requeue(group, alive, now + backoff)
            else:
                self._isolate_now(group, alive, err)
            return
        for p in alive:
            p.attempts += 1
        base = self._kcfg.retry_base_ms / 1e3
        can_wait, escalate = [], []
        for p in alive:
            backoff = base * (2 ** (p.attempts - 1))
            if p.attempts <= self._kcfg.max_retries and \
                    now + backoff < p.deadline_at:
                can_wait.append(p)
            else:
                escalate.append(p)
        if can_wait:
            self.counters.bump("retries")
            # The group horizon takes the SOONEST requeued backoff: a
            # fresh rider is not over-delayed by an older one's longer
            # window (the older simply rides the earlier flush).
            soonest = min(base * (2 ** (p.attempts - 1)) for p in can_wait)
            # The span records the ACTUAL wait (the group horizon) —
            # every rider re-flushes together at now+soonest, and a
            # per-request nominal backoff here would overstate the
            # delay for all but the freshest rider. ``attempt`` is the
            # failed flushes this request has ridden, THIS one included.
            self._span_batch(can_wait, "retry", t=now,
                             cause=type(err).__name__,
                             backoff_s=round(soonest, 6),
                             per=lambda p, _: {"attempt": p.attempts})
            self._requeue(group, can_wait, now + soonest)
        if escalate:
            self._isolate_now(group, escalate, err)

    def _isolate_now(self, group: _Group, batch: "list[_Pending]",
                     err: ServeError) -> None:
        """Escalation for requests with no retry budget (or no deadline
        room to wait one out): a group enters bisection — each half
        re-dispatches now, so a transient that cleared still completes
        the innocent requests — and a LONE request gets that same
        immediate re-dispatch (failing typed only if it fails again,
        alone): without it a singleton hit by a one-off transient would
        be denied exactly the attempt a bisection half gets."""
        self._span_batch(batch, "isolate", cause=type(err).__name__,
                         batch=len(batch))
        if len(batch) > 1:
            self._bisect(group, batch)
        else:
            self._dispatch_or_isolate(group, batch)

    def _bisect(self, group: _Group, batch: "list[_Pending]") -> None:
        self.counters.bump("bisections")
        self._span_batch(batch, "bisect", size=len(batch))
        mid = len(batch) // 2
        self._dispatch_or_isolate(group, batch[:mid])
        self._dispatch_or_isolate(group, batch[mid:])

    def _dispatch_or_isolate(self, group: _Group,
                             batch: "list[_Pending]") -> None:
        """Bisection leg: dispatch ``batch``; on failure split again
        until the culprit fails alone. Terminates in O(log batch)
        splits; every request resolves (result or typed error)."""
        try:
            self._dispatch_batch(group, batch)
        except Exception as e:
            err = self._typed_error(group, e)
            # Chunks that completed before the failure already resolved.
            batch = [p for p in batch if not p.future.done()]
            if not batch:
                return
            if len(batch) == 1:
                self.counters.bump("poisoned")
                self._fail(batch[0], err)
                return
            self._bisect(group, batch)

    def _flush_count(self, reason: str, queued: int) -> int:
        """How many requests a flush takes. Full groups take the batch
        cap; a drain takes everything (the engine chunks past the cap).
        A deadline/interval flush of a PARTIAL group takes the largest
        power of two <= queued instead of all of it: the batch axis is
        pow2-bucketed (``serve.buckets.bucket_batch``), so flushing 19
        requests pads to 32 — 13 identity fillers factored at full cost.
        16 now + the (newest, latest-deadline) remainder next flush costs
        20 batch rows instead of 32; the deadline-triggering oldest
        request is always in the taken head, and steady state only ever
        dispatches the pow2 batch keys prewarm mints. Measured: this is
        the difference between ~0.6x and ~0.9x of the sync ceiling on
        the round-11 CPU open-loop ladder."""
        if reason == "drain":
            return queued
        if queued >= self._scfg.max_batch:
            return self._scfg.max_batch
        return 1 << (queued.bit_length() - 1)

    def poll(self) -> int:
        """Flush every currently-ready group once; returns the number of
        flushes performed. The manual-mode twin of the dispatcher thread
        (same selection logic), for tests driving a fake clock."""
        flushed = 0
        while True:
            with self._lock:
                pick = self._select_locked(self._clock(), self._draining)
                if pick is None:
                    if flushed:
                        self._idle.notify_all()
                    return flushed
                group, reason = pick
                count = len(group.queue) if group.kind == "update" \
                    else self._flush_count(reason, len(group.queue))
                taken = self._take_locked(group, count)
                self._inflight += len(taken)
                if group.kind == "update":
                    group.busy = True   # serialize ops per session
            try:
                self._flush(group, taken, reason)
            except BaseException:
                # _flush handles dispatch failures itself, so anything
                # arriving here is a crash past the failure handler (a
                # scheduler bug, an injected worker fault landing
                # mid-flush): requeue what this flush still owes before
                # the exception takes the worker down, so crash recovery
                # (respawn, or the next poll) re-dispatches it instead
                # of hanging the futures forever.
                self._requeue(group,
                              [p for p in taken if not p.future.done()],
                              not_before=0.0)
                raise
            finally:
                with self._lock:
                    self._inflight -= len(taken)
                    if group.kind == "update":
                        group.busy = False
                        if not group.queue:
                            # Idle update groups are PRUNED: they are
                            # keyed per live session (id), so unlike
                            # the bounded bucket-group set they would
                            # otherwise pin every session ever
                            # submitted (and its m x n state arrays)
                            # for the scheduler's lifetime. A later
                            # submit for the same session simply mints
                            # a fresh group.
                            self._groups.pop(group.gkey, None)
                        self._work.notify()  # re-examine its queue
                    self._idle.notify_all()
            flushed += 1

    def _run(self) -> None:
        """Dispatcher thread: wait for work or the next flush horizon,
        flush what is ready, repeat. A crash anywhere in the loop —
        including the ``serve.worker`` fault-injection site at its top —
        is detected, counted, and answered by RESPAWNING a replacement
        worker (in-flight work was requeued by ``poll``), so the pool
        never silently shrinks to zero dispatchers."""
        try:
            while True:
                _faults.fire("serve.worker")
                with self._lock:
                    if self._closed and self._depth == 0:
                        return
                    now = self._clock()
                    ready = self._select_locked(
                        now, self._draining) is not None
                    if not ready:
                        timeout = self._next_wake_locked(now)
                        self._work.wait(timeout)
                        continue
                self.poll()
        except BaseException as e:
            self._on_worker_crash(threading.current_thread(), cause=e)
            # The crash is recorded (cause retained in stats) and
            # replaced, not re-raised: a daemon thread's traceback on
            # stderr is noise the respawn already answered.

    def _on_worker_crash(self, thread: threading.Thread,
                         cause: "BaseException | None" = None) -> None:
        """Account a dispatcher-worker death and spawn its replacement.

        The respawn gate matches ``_run``'s own exit condition
        (``_closed and _depth == 0``) rather than ``_closed`` alone: a
        worker that crashes DURING ``shutdown(drain=True)`` still has
        queued work to complete, and skipping the respawn there would
        hang the drain (and its futures) forever.

        Consecutive crashes back the replacement off exponentially
        (the NEW worker sleeps before entering its loop; reset by the
        next successful dispatch): a persistent crash cause — an armed
        unbounded ``serve.worker`` fault, a deterministic bug in the
        loop — degrades to a ~2 s-period respawn heartbeat instead of
        a tight thread-create/crash spin pegging a core.

        A STORM of crashes (streak >= 2: the replacement died too, so
        the dispatcher may never dispatch again) additionally fails the
        queued requests whose deadline has already passed, typed
        DeadlineExceeded — the respawn heartbeat becomes the resolution
        cadence, so even under a permanent crash cause every
        finite-deadline future resolves within ~2 s of its deadline
        instead of hanging (and ``drain()``/``shutdown(drain=True)``
        terminate once the last deadline expires). A single crash does
        NOT sweep: its respawn normally drains the queue, and a
        late-but-successful dispatch still returns its result."""
        expired: "list[_Pending]" = []
        with self._lock:
            self.counters.bump("worker_crashes")
            if cause is not None:
                # Retain the cause for the operator: a deterministic
                # bug respawn-loops at the heartbeat, and without this
                # stats() would show worker_crashes climbing with no
                # trace of WHY (the exact swallowed-failure pattern
                # DHQR006 bans). Last crash wins — a storm has one
                # cause.
                self._last_crash = "".join(traceback.format_exception(
                    type(cause), cause, cause.__traceback__))[-2000:]
            if self._closed and self._depth == 0:
                return
            self._crash_streak += 1
            if self._crash_streak >= 2:
                now = self._clock()
                for group in self._groups.values():
                    if any(now >= p.deadline_at for p in group.queue):
                        expired.extend(p for p in group.queue
                                       if now >= p.deadline_at)
                        group.queue = collections.deque(
                            p for p in group.queue if now < p.deadline_at)
                if expired:
                    self._depth -= len(expired)
                    self._idle.notify_all()
            delay = min(0.01 * (2 ** min(self._crash_streak - 1, 8)), 2.0)
            replacement = threading.Thread(
                target=self._respawned_run, args=(delay,),
                name=thread.name, daemon=True)
            try:
                self._threads[self._threads.index(thread)] = replacement
            except ValueError:  # unmanaged caller thread: still recover
                self._threads.append(replacement)
        # Respawn FIRST: resolving the swept futures below can run
        # client callbacks, and nothing they raise may cost the pool
        # its replacement.
        replacement.start()
        for p in expired:
            # Claim before resolving, exactly like _flush: a client that
            # cancelled a queued future drops out here, and a claimed
            # (or already-claimed requeued) future can no longer be
            # cancelled, so set_exception cannot raise InvalidStateError
            # inside the crash handler.
            if p.claimed or p.future.set_running_or_notify_cancel():
                dead = DeadlineExceeded(
                    "deadline passed while the dispatcher was "
                    "crash-looping (worker died repeatedly before the "
                    "request could flush)")
                dead.__cause__ = cause
                self._fail(p, dead)
            else:
                self.counters.bump("cancelled")

    def _respawned_run(self, delay: float) -> None:
        if delay > 0:
            time.sleep(delay)   # wall clock: crash-loop damping only
        self._run()

    # ------------------------------------------------------- lifecycle/stats

    def drain(self, timeout: "float | None" = None) -> None:
        """Flush and complete everything queued, regardless of deadlines
        (flush reason "drain"). Blocks until the queue and in-flight
        dispatches are empty. Works with or without the dispatcher
        thread (manual mode drains inline)."""
        with self._lock:
            threads = list(self._threads)
        if not any(t.is_alive() for t in threads):
            with self._lock:
                self._draining = True
            try:
                self.poll()
            finally:
                with self._lock:
                    self._draining = False
            return
        # dhqr: ignore[DHQR008] drain's timeout bounds a REAL hang (wedged dispatch); it must keep ticking even under an injected scheduler clock
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            self._draining = True
            self._work.notify()
            while self._depth or self._inflight:
                left = None if deadline is None else \
                    deadline - time.monotonic()  # dhqr: ignore[DHQR008] same wall-clock hang bound as above
                if left is not None and left <= 0:
                    self._draining = False
                    raise TimeoutError(
                        f"drain timed out with {self._depth} queued, "
                        f"{self._inflight} in flight")
                if not self._idle.wait(left if left is None else
                                       min(left, 0.05)):
                    self._work.notify()
            self._draining = False

    def shutdown(self, drain: bool = True,
                 timeout: "float | None" = None) -> None:
        """Stop accepting work and stop the dispatcher. ``drain=True``
        (default) completes everything already accepted first;
        ``drain=False`` cancels queued futures. Admission closes BEFORE
        the drain: a submit racing shutdown either lands fully (drained)
        or is rejected — it can never slip into the queue after the
        drain and hang forever."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if drain:
            self.drain(timeout=timeout)
        cancelled: "list[_Pending]" = []
        with self._lock:
            if not drain:
                for group in self._groups.values():
                    while group.queue:
                        cancelled.append(group.queue.popleft())
                        self._depth -= 1
            self._work.notify_all()
            threads = list(self._threads)
        # Futures resolve OUTSIDE the lock: Future.cancel() /
        # set_exception() run done-callbacks synchronously, and a fleet
        # router's relay callback takes the router lock and may resubmit
        # into a SIBLING replica's scheduler lock — two concurrent
        # drain=False shutdowns resolving under their own locks would be
        # a classic ABBA deadlock window (DHQR602).
        for p in cancelled:
            if not p.future.cancel():
                # A requeued retry is already claimed (RUNNING) and
                # cannot be cancelled — resolve it typed instead; the
                # contract is that no submitted future EVER hangs.
                self.counters.bump("failed")
                p.future.set_exception(ServeError(
                    "scheduler shut down (drain=False) "
                    "before the request's retry could run"))
        for t in threads:
            if t.is_alive():
                t.join(timeout=5.0)

    def __enter__(self) -> "AsyncScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def queue_depth(self) -> int:
        with self._lock:
            return self._depth

    @property
    def healthy(self) -> bool:
        """Whether a fleet router should keep routing NEW work here
        (round 22): open for admission and not crash-storming. The
        storm threshold is the same two-consecutive-crashes mark the
        sweep in :meth:`_on_worker_crash` uses — one crash is a
        respawnable blip, two in a row is a replica the router should
        drain around until the streak clears."""
        with self._lock:
            return not self._closed and self._crash_streak < 2

    #: The scheduler counters the registry exports (``serve.sched.<name>``)
    #: and stats() mirrors — ONE spelling for both surfaces.
    _METRIC_COUNTERS = (
        "submitted", "completed", "failed", "rejected",
        "rejected_unmeetable", "cancelled", "deadline_misses",
        "dispatches", "flush_failures", "retries", "bisections",
        "numeric_failures", "poisoned", "worker_crashes",
    )

    def metrics_snapshot(self) -> dict:
        """The registry-facing flat snapshot: every counter above plus
        queue occupancy, flush reasons (``flush.<reason>``), and the
        latency histogram's summary (``latency.p99_ms``...). Exported
        process-wide as ``serve.sched.*`` by ``dhqr_tpu.obs.metrics``;
        :meth:`stats` reshapes the same numbers for the round-11
        callers."""
        snap = self.counters.snapshot()
        with self._lock:
            depth, inflight = self._depth, self._inflight
        out: dict = {name: int(snap.get(name, 0))
                     for name in self._METRIC_COUNTERS}
        out["queue_depth"] = depth
        out["inflight"] = inflight
        for reason in ("full", "deadline", "interval", "drain"):
            out[f"flush.{reason}"] = int(snap.get(f"flush_{reason}", 0))
        for key, val in self.latency.snapshot().items():
            out[f"latency.{key}"] = val
        return out

    def stats(self) -> dict:
        """JSON-ready operational snapshot: admission/flush counters,
        queue depth, latency percentiles, per-bucket EWMA dispatch
        seconds, and the executable cache's own stats. Since round 14 a
        thin compatibility view over :meth:`metrics_snapshot` — the
        numbers ARE the ``serve.sched.*`` registry metrics, reshaped to
        the round-11 dict layout existing tests and benchmarks read."""
        m = self.metrics_snapshot()
        with self._lock:
            last_crash = self._last_crash
            ewma_ms = {
                b.label: round((e.value or 0.0) * 1e3, 3)
                for b, e in sorted(self._ewma.items())
            }
        out = {name: m[name] for name in
               ("queue_depth", "inflight") + self._METRIC_COUNTERS}
        out["last_worker_crash"] = last_crash
        out["flushes"] = {
            reason: m[f"flush.{reason}"]
            for reason in ("full", "deadline", "interval", "drain")
        }
        out["latency"] = {
            key: m[f"latency.{key}"]
            for key in ("count", "mean_ms", "p50_ms", "p99_ms")
        }
        out["bucket_ewma_ms"] = ewma_ms
        out["cache"] = self._cache.stats()
        return out


def dispatch_program(kind: str, config: Optional[DHQRConfig] = None,
                     **overrides):
    """The traced program one async flush dispatches — BY CONSTRUCTION
    the engine's own :func:`dhqr_tpu.serve.engine.bucket_program` (the
    scheduler owns no second lowering path; this alias exists so the
    lint jaxpr pass can trace "the async dispatch path" by name and the
    comms contracts keep covering it)."""
    return _engine.bucket_program(kind, config, **overrides)
