"""Async serving front-end: deadline-aware continuous micro-batching.

The batched tier (``serve.engine``) answers "here is a pre-collected
request list" — but production traffic from many concurrent users is a
STREAM: requests arrive one at a time, each with its own latency budget
and tenant, and nobody upstream collects them into convenient lists.
This module is the admission layer that turns the existing bucket
lattice / AOT executable cache / per-bucket tuned plans into a service
(arXiv 2112.09017 frames TPU dense linear algebra as exactly this kind
of serving workload):

* :meth:`AsyncScheduler.submit` accepts one request — ``(kind, A, b)``
  plus ``deadline`` / ``tenant`` / ``policy`` / ``plan`` — validates it
  with the sync tier's own checks, and returns a
  ``concurrent.futures.Future``;
* queued requests coalesce per (kind, bucket, resolved-config) group —
  the same grouping ``batched_lstsq`` computes for a list — and a
  dispatcher loop launches a group as ONE stacked dispatch when it
  reaches the batch cap ("full"), when its oldest request's deadline
  minus the bucket's EWMA dispatch latency approaches ("deadline"), or
  when its oldest request has waited the flush interval ("interval");
* within an oversubscribed flush, requests are picked by smooth weighted
  round-robin across tenants (``SchedulerConfig.tenant_weights``), so a
  flooding tenant cannot starve the others out of a bucket;
* past ``SchedulerConfig.queue_depth`` total queued requests, ``submit``
  rejects with :class:`BackpressureError` carrying a ``retry_after``
  hint — bounded queues keep the tail latency bounded;
* :meth:`AsyncScheduler.drain` / :meth:`AsyncScheduler.shutdown` flush
  and complete everything in flight, so rolling restarts never drop
  accepted work.

ONE dispatch path, by construction: a flush calls the engine's own
``_dispatch_groups`` with consumers built by the engine's own
``_scatter_lstsq`` / ``_scatter_qr``, and cache keys are minted by the
engine's ``_plan_key`` — this module owns no lowering, no key scheme,
and no padding logic of its own, so steady state stays zero-recompile
against a cache prewarmed for the sync tier (pinned by
tests/test_scheduler.py key-parity and by the lint jaxpr pass, which
refuses to trace the async entry if the functions diverge).

Latency accounting rides ``utils.profiling``: a bounded
:class:`~dhqr_tpu.utils.profiling.LatencyHistogram` of submit→complete
seconds (p50/p99 in :meth:`AsyncScheduler.stats`), one
:class:`~dhqr_tpu.utils.profiling.Ewma` of dispatch seconds per bucket
(the deadline-flush lead time), and flush-reason / admission
:class:`~dhqr_tpu.utils.profiling.Counters`.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Optional

from dhqr_tpu.serve import engine as _engine
from dhqr_tpu.serve.buckets import Bucket, plan_bucket
from dhqr_tpu.serve.cache import ExecutableCache, default_cache
from dhqr_tpu.utils.config import DHQRConfig, SchedulerConfig, ServeConfig
from dhqr_tpu.utils.profiling import (
    Counters,
    Ewma,
    LatencyHistogram,
    sync as _sync,
)

# Deadline-flush lead time: dispatch is launched when
#   now >= deadline - (_LEAD_FACTOR * ewma + _LEAD_FLOOR_S)
# so the expected dispatch latency plus a 25% EWMA-noise margin still
# fits inside the budget. Not a config knob: the measurable quantity is
# the EWMA; the margin only absorbs its variance.
_LEAD_FACTOR = 1.25
_LEAD_FLOOR_S = 1e-3


class BackpressureError(RuntimeError):
    """Raised by :meth:`AsyncScheduler.submit` past the queue-depth
    high-water mark. ``retry_after`` (seconds) estimates when capacity
    frees up — the 429-with-Retry-After of this tier."""

    def __init__(self, message: str, retry_after: float) -> None:
        super().__init__(message)
        self.retry_after = float(retry_after)


@dataclasses.dataclass
class _Pending:
    """One queued request (everything the flush stage needs)."""

    seq: int
    A: object
    b: object
    tenant: str
    submitted_at: float
    deadline_at: float
    future: Future


class _Group:
    """Pending requests sharing (kind, bucket, resolved config) — the
    unit the dispatcher flushes as one stacked micro-batch."""

    __slots__ = ("kind", "bucket", "cfg", "pol", "qr_solve_args", "queue",
                 "credits")

    def __init__(self, kind, bucket, cfg, pol, qr_solve_args):
        self.kind = kind
        self.bucket = bucket
        self.cfg = cfg
        self.pol = pol
        self.qr_solve_args = qr_solve_args
        self.queue: "collections.deque[_Pending]" = collections.deque()
        # Smooth-WRR credit per tenant, persisted ACROSS flushes (a light
        # tenant that loses an oversubscribed flush is ahead next flush).
        self.credits: "dict[str, float]" = {}


class AsyncScheduler:
    """Thread-safe admission queue + micro-batching dispatcher over the
    batched serving tier.

    >>> sched = AsyncScheduler(block_size=8)
    >>> fut = sched.submit("lstsq", A, b, deadline=0.05, tenant="acme")
    >>> x = fut.result()            # the same x batched_lstsq returns
    >>> sched.stats()["latency"]    # p50/p99, flush reasons, EWMA, ...
    >>> sched.shutdown()            # drains, then stops the dispatcher

    Construction mirrors ``batched_lstsq``: ``config``/``**overrides``
    are the base :class:`DHQRConfig` knobs every request inherits
    (per-request ``policy=``/``plan=`` override them, each combination
    coalescing as its own group), ``serve_config`` the bucket lattice,
    ``cache`` the executable cache (the process default when omitted, so
    a cache prewarmed for the sync tier serves the queue too).

    ``start=False`` skips the dispatcher thread: nothing flushes until
    :meth:`poll` (or :meth:`drain`) is called, and ``clock`` can be a
    fake — that is how tests pin deadline/fairness decisions without
    wall-clock races. The default is a daemon dispatcher thread driven
    by ``time.monotonic``.
    """

    def __init__(
        self,
        config: Optional[DHQRConfig] = None,
        serve_config: Optional[ServeConfig] = None,
        sched_config: Optional[SchedulerConfig] = None,
        cache: Optional[ExecutableCache] = None,
        clock=time.monotonic,
        start: bool = True,
        workers: int = 2,
        **overrides,
    ) -> None:
        self._scfg = serve_config or ServeConfig.from_env()
        self._kcfg = sched_config or SchedulerConfig.from_env()
        self._cache = cache if cache is not None else default_cache()
        self._base_config = config
        self._overrides = dict(overrides)
        # Fail fast on a bad base config (same checks the sync tier runs)
        # rather than on the first submit; also seeds the resolution memo.
        self._resolved: dict = {}
        self._resolve(None, None, "lstsq")

        self._clock = clock
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._groups: "dict[tuple, _Group]" = {}
        self._depth = 0            # queued, not yet popped for dispatch
        self._inflight = 0         # popped, dispatch not yet completed
        self._seq = 0
        self._draining = False
        self._closed = False

        self.counters = Counters()
        self.latency = LatencyHistogram()
        self._ewma: "dict[Bucket, Ewma]" = {}
        self.keys_seen: set = set()

        # Dispatcher pool: each worker runs the same select→take→flush
        # loop against the shared lock, so two ready groups flush
        # CONCURRENTLY — worker B's host-side padding/scatter overlaps
        # worker A's device execution (XLA releases the GIL; measured
        # worth ~15-20% requests/s on the CPU open-loop ladder, where
        # one dispatch is ~half host prep). Request-level ordering needs
        # nothing from the workers: each flush owns its popped requests,
        # and group selection under the lock is atomic.
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self._threads: "list[threading.Thread]" = []
        if start:
            self._threads = [
                threading.Thread(target=self._run,
                                 name=f"dhqr-serve-dispatch-{i}", daemon=True)
                for i in range(workers)
            ]
            for t in self._threads:
                t.start()

    # ------------------------------------------------------------ admission

    def _resolve(self, policy, plan, kind):
        """Resolve (policy, plan, kind) -> (cfg, pol, qr_solve_args) via
        the engine's own resolver, memoized per combination (resolution
        is pure given the base config)."""
        try:
            memo_key = (kind, policy, plan)
            hit = self._resolved.get(memo_key)
        except TypeError:           # unhashable policy/plan object
            memo_key, hit = None, None
        if hit is not None:
            return hit
        ov = dict(self._overrides)
        if policy is not None:
            ov["policy"] = policy
        if plan is not None:
            ov["plan"] = plan
        resolved = _engine._resolve_dispatch_cfg(kind, self._base_config, ov)
        if memo_key is not None:
            self._resolved[memo_key] = resolved
        return resolved

    def submit(
        self,
        kind: str,
        A,
        b=None,
        *,
        deadline: "float | None" = None,
        tenant: str = "default",
        policy=None,
        plan=None,
    ) -> Future:
        """Queue one request; returns a Future resolving to exactly what
        the sync tier returns for it (``x`` for ``kind="lstsq"``, a
        ``QRFactorization`` for ``kind="qr"``).

        ``deadline`` is the request's latency budget in SECONDS from now
        (``SchedulerConfig.slo_ms`` when omitted) — the dispatcher
        flushes the request's bucket early enough that the bucket's
        expected dispatch latency still fits inside it. Raises
        :class:`BackpressureError` past the queue-depth high-water mark
        and ``RuntimeError`` after :meth:`shutdown`.
        """
        cfg, pol, qr_solve_args = self._resolve(policy, plan, kind)
        if kind == "lstsq":
            if b is None:
                raise ValueError("kind='lstsq' needs a right-hand side b")
            _engine._validate_requests([A], [b])
        else:
            if b is not None:
                raise ValueError("kind='qr' takes no right-hand side")
            _engine._validate_requests([A], None)
        bucket = plan_bucket(A.shape[0], A.shape[1], A.dtype, self._scfg)
        if deadline is None:
            deadline = self._kcfg.slo_ms / 1e3
        elif not deadline > 0:
            raise ValueError(f"deadline must be > 0 seconds, got {deadline}")

        now = self._clock()
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("scheduler is shut down")
            if self._depth >= self._kcfg.queue_depth:
                self.counters.bump("rejected")
                retry = self._retry_after_locked()
                raise BackpressureError(
                    f"admission queue full ({self._depth} >= "
                    f"{self._kcfg.queue_depth}); retry in ~{retry:.3f}s",
                    retry_after=retry)
            gkey = (kind, bucket, cfg, qr_solve_args)
            group = self._groups.get(gkey)
            if group is None:
                group = self._groups[gkey] = _Group(
                    kind, bucket, cfg, pol, qr_solve_args)
            self._seq += 1
            group.queue.append(_Pending(
                self._seq, A, b, tenant, now, now + deadline, fut))
            self._depth += 1
            self.counters.bump("submitted")
            self._work.notify()
        return fut

    def _retry_after_locked(self) -> float:
        """Backpressure hint: queue depth over the average dispatch
        latency's implied drain rate, floored at the flush interval."""
        lat = [e.value for e in self._ewma.values() if e.value is not None]
        avg = sum(lat) / len(lat) if lat else 0.0
        batches = -(-self._depth // max(1, self._scfg.max_batch))
        return max(self._kcfg.flush_interval_ms / 1e3, batches * avg)

    # ----------------------------------------------------------- flush policy

    def _lead_s(self, bucket: Bucket) -> float:
        ewma = self._ewma.get(bucket)
        val = ewma.value if ewma is not None else None
        return _LEAD_FACTOR * (val or 0.0) + _LEAD_FLOOR_S

    def _flush_reason(self, group: _Group, now: float) -> "str | None":
        if len(group.queue) >= self._scfg.max_batch:
            return "full"
        oldest = group.queue[0]
        if now >= oldest.deadline_at - self._lead_s(group.bucket):
            return "deadline"
        if now - oldest.submitted_at >= self._kcfg.flush_interval_ms / 1e3:
            return "interval"
        return None

    def _next_wake_locked(self, now: float) -> "float | None":
        """Seconds until the earliest future flush condition, None when
        nothing is queued."""
        soonest = None
        for group in self._groups.values():
            if not group.queue:
                continue
            oldest = group.queue[0]
            t = min(
                oldest.deadline_at - self._lead_s(group.bucket),
                oldest.submitted_at + self._kcfg.flush_interval_ms / 1e3,
            )
            soonest = t if soonest is None else min(soonest, t)
        if soonest is None:
            return None
        return max(soonest - now, 0.0)

    def _select_locked(self, now: float, drain: bool):
        """Pick the most urgent ready group: earliest oldest-deadline
        first (EDF) among ready groups. Returns (group, reason) or None."""
        best, best_reason = None, None
        for group in self._groups.values():
            if not group.queue:
                continue
            reason = "drain" if drain else self._flush_reason(group, now)
            if reason is None:
                continue
            if best is None or \
                    group.queue[0].deadline_at < best.queue[0].deadline_at:
                best, best_reason = group, reason
        return (best, best_reason) if best is not None else None

    def _take_locked(self, group: _Group, count: int) -> "list[_Pending]":
        """Pop up to ``count`` requests: the group's oldest request — the
        one whose deadline/interval triggered the flush — is ALWAYS in
        the taken head, the rest by smooth weighted round-robin across
        tenants (FIFO within a tenant). Each round every tenant with
        pending work gains its weight of credit, the richest tenant
        (ties to the oldest head request) yields its head and pays the
        active total back; credit persists on the group across flushes
        (``_Group.credits``) so a light tenant that loses one
        oversubscribed partial flush starts the next one ahead instead
        of from zero — without persistence a 5:1 flooder holding 2+ deep
        backlog starves a light tenant's head request past its deadline
        on every cycle. Credit for tenants with nothing left queued is
        dropped (classic smooth-WRR idle reset). With equal weights this
        is plain FIFO interleaving; with 3:1 a flooding tenant keeps 3/4
        of an oversubscribed flush and the light tenant still lands
        1/4."""
        by_tenant: "dict[str, collections.deque[_Pending]]" = {}
        for p in group.queue:
            by_tenant.setdefault(p.tenant, collections.deque()).append(p)
        if len(by_tenant) == 1:     # fast path: nothing to arbitrate
            taken = [group.queue.popleft()
                     for _ in range(min(count, len(group.queue)))]
            self._depth -= len(taken)
            group.credits.clear()
            return taken
        credit = group.credits
        taken: "list[_Pending]" = []

        def take_round(forced: "str | None" = None) -> None:
            total = sum(self._kcfg.weight_for(t) for t in by_tenant)
            winner = forced
            for t in by_tenant:
                credit[t] = credit.get(t, 0.0) + self._kcfg.weight_for(t)
                if forced is None and (
                        winner is None or credit[t] > credit[winner] or (
                            credit[t] == credit[winner]
                            and by_tenant[t][0].seq
                            < by_tenant[winner][0].seq)):
                    winner = t
            credit[winner] = credit.get(winner, 0.0) - total
            taken.append(by_tenant[winner].popleft())
            if not by_tenant[winner]:
                del by_tenant[winner]

        # Head-of-line guarantee: take the group's oldest request first,
        # charged to its tenant like a won round.
        take_round(forced=group.queue[0].tenant)
        while len(taken) < count and by_tenant:
            take_round()
        taken_ids = {id(p) for p in taken}
        remaining = [p for p in group.queue if id(p) not in taken_ids]
        group.queue.clear()
        group.queue.extend(remaining)
        still_active = {p.tenant for p in remaining}
        for t in [t for t in credit if t not in still_active]:
            del credit[t]
        self._depth -= len(taken)
        return taken

    # ------------------------------------------------------------- dispatch

    def _flush(self, group: _Group, taken: "list[_Pending]",
               reason: str) -> None:
        """Dispatch one popped micro-batch through the engine's shared
        path. Runs OUTSIDE the scheduler lock (a compile or a slow
        dispatch must not block admission)."""
        # Claim every future before dispatch: a client that already
        # called fut.cancel() drops out here, and a claimed (RUNNING)
        # future can no longer be cancelled, so the set_result /
        # set_exception below can never raise InvalidStateError (which
        # would kill the dispatcher worker).
        live: "list[_Pending]" = []
        for p in taken:
            if p.future.set_running_or_notify_cancel():
                live.append(p)
            else:
                self.counters.bump("cancelled")
        if not live:
            return
        taken = live
        self.counters.bump(f"flush_{reason}")
        self.counters.bump("dispatches")
        As = [p.A for p in taken]
        resolved: "list[tuple[int, object]]" = []
        raw_outs: "list[object]" = []
        emit = lambda i, val: resolved.append((i, val))  # noqa: E731
        if group.kind == "lstsq":
            bs = [p.b for p in taken]
            consume_inner = _engine._scatter_lstsq(As, emit)
        else:
            bs = None
            consume_inner = _engine._scatter_qr(As, emit,
                                                group.qr_solve_args)

        def consume(chunk, key, outs):
            self.keys_seen.add(key)
            raw_outs.append(outs)
            consume_inner(chunk, key, outs)

        t0 = self._clock()
        try:
            _engine._dispatch_groups(
                group.kind, As, bs, group.cfg, self._scfg, self._cache,
                consume, pol=group.pol)
            out: "list[object | None]" = [None] * len(taken)
            for i, val in resolved:
                out[i] = val
            # Fence on the STACKED program outputs (O(1) arrays per
            # chunk), not the per-request slices (O(batch) readback
            # kernels — measured ~10 ms/flush on CPU): once the stack is
            # ready, the truncating slices the futures carry are views
            # of completed work.
            _sync(raw_outs)
        except Exception as e:
            self.counters.bump("failed", len(taken))
            for p in taken:
                p.future.set_exception(e)
            return
        finally:
            seconds = self._clock() - t0
            chunks = -(-len(taken) // self._scfg.max_batch)
            # Under the lock: _retry_after_locked and stats() iterate
            # _ewma, and a first-dispatch setdefault would resize the
            # dict mid-iteration.
            with self._lock:
                self._ewma.setdefault(group.bucket, Ewma()).update(
                    seconds / max(1, chunks))
        done = self._clock()
        for p, val in zip(taken, out):
            self.latency.record(done - p.submitted_at)
            if done > p.deadline_at:
                self.counters.bump("deadline_misses")
            self.counters.bump("completed")
            p.future.set_result(val)

    def _flush_count(self, reason: str, queued: int) -> int:
        """How many requests a flush takes. Full groups take the batch
        cap; a drain takes everything (the engine chunks past the cap).
        A deadline/interval flush of a PARTIAL group takes the largest
        power of two <= queued instead of all of it: the batch axis is
        pow2-bucketed (``serve.buckets.bucket_batch``), so flushing 19
        requests pads to 32 — 13 identity fillers factored at full cost.
        16 now + the (newest, latest-deadline) remainder next flush costs
        20 batch rows instead of 32; the deadline-triggering oldest
        request is always in the taken head, and steady state only ever
        dispatches the pow2 batch keys prewarm mints. Measured: this is
        the difference between ~0.6x and ~0.9x of the sync ceiling on
        the round-11 CPU open-loop ladder."""
        if reason == "drain":
            return queued
        if queued >= self._scfg.max_batch:
            return self._scfg.max_batch
        return 1 << (queued.bit_length() - 1)

    def poll(self) -> int:
        """Flush every currently-ready group once; returns the number of
        flushes performed. The manual-mode twin of the dispatcher thread
        (same selection logic), for tests driving a fake clock."""
        flushed = 0
        while True:
            with self._lock:
                pick = self._select_locked(self._clock(), self._draining)
                if pick is None:
                    if flushed:
                        self._idle.notify_all()
                    return flushed
                group, reason = pick
                count = self._flush_count(reason, len(group.queue))
                taken = self._take_locked(group, count)
                self._inflight += len(taken)
            try:
                self._flush(group, taken, reason)
            finally:
                with self._lock:
                    self._inflight -= len(taken)
                    self._idle.notify_all()
            flushed += 1

    def _run(self) -> None:
        """Dispatcher thread: wait for work or the next flush horizon,
        flush what is ready, repeat."""
        while True:
            with self._lock:
                if self._closed and self._depth == 0:
                    return
                now = self._clock()
                ready = self._select_locked(now, self._draining) is not None
                if not ready:
                    timeout = self._next_wake_locked(now)
                    self._work.wait(timeout)
                    continue
            self.poll()

    # ------------------------------------------------------- lifecycle/stats

    def drain(self, timeout: "float | None" = None) -> None:
        """Flush and complete everything queued, regardless of deadlines
        (flush reason "drain"). Blocks until the queue and in-flight
        dispatches are empty. Works with or without the dispatcher
        thread (manual mode drains inline)."""
        if not any(t.is_alive() for t in self._threads):
            with self._lock:
                self._draining = True
            try:
                self.poll()
            finally:
                with self._lock:
                    self._draining = False
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            self._draining = True
            self._work.notify()
            while self._depth or self._inflight:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    self._draining = False
                    raise TimeoutError(
                        f"drain timed out with {self._depth} queued, "
                        f"{self._inflight} in flight")
                if not self._idle.wait(left if left is None else
                                       min(left, 0.05)):
                    self._work.notify()
            self._draining = False

    def shutdown(self, drain: bool = True,
                 timeout: "float | None" = None) -> None:
        """Stop accepting work and stop the dispatcher. ``drain=True``
        (default) completes everything already accepted first;
        ``drain=False`` cancels queued futures. Admission closes BEFORE
        the drain: a submit racing shutdown either lands fully (drained)
        or is rejected — it can never slip into the queue after the
        drain and hang forever."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if drain:
            self.drain(timeout=timeout)
        with self._lock:
            if not drain:
                for group in self._groups.values():
                    while group.queue:
                        p = group.queue.popleft()
                        self._depth -= 1
                        p.future.cancel()
            self._work.notify_all()
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=5.0)

    def __enter__(self) -> "AsyncScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def queue_depth(self) -> int:
        with self._lock:
            return self._depth

    def stats(self) -> dict:
        """JSON-ready operational snapshot: admission/flush counters,
        queue depth, latency percentiles, per-bucket EWMA dispatch
        seconds, and the executable cache's own stats."""
        snap = self.counters.snapshot()
        with self._lock:
            depth, inflight = self._depth, self._inflight
            ewma_ms = {
                f"{b.m}x{b.n}:{b.dtype}": round((e.value or 0.0) * 1e3, 3)
                for b, e in sorted(self._ewma.items())
            }
        return {
            "queue_depth": depth,
            "inflight": inflight,
            "submitted": int(snap.get("submitted", 0)),
            "completed": int(snap.get("completed", 0)),
            "failed": int(snap.get("failed", 0)),
            "rejected": int(snap.get("rejected", 0)),
            "cancelled": int(snap.get("cancelled", 0)),
            "deadline_misses": int(snap.get("deadline_misses", 0)),
            "dispatches": int(snap.get("dispatches", 0)),
            "flushes": {
                reason: int(snap.get(f"flush_{reason}", 0))
                for reason in ("full", "deadline", "interval", "drain")
            },
            "latency": self.latency.snapshot(),
            "bucket_ewma_ms": ewma_ms,
            "cache": self._cache.stats(),
        }


def dispatch_program(kind: str, config: Optional[DHQRConfig] = None,
                     **overrides):
    """The traced program one async flush dispatches — BY CONSTRUCTION
    the engine's own :func:`dhqr_tpu.serve.engine.bucket_program` (the
    scheduler owns no second lowering path; this alias exists so the
    lint jaxpr pass can trace "the async dispatch path" by name and the
    comms contracts keep covering it)."""
    return _engine.bucket_program(kind, config, **overrides)
