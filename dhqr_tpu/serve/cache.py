"""AOT executable cache — keep compiled programs resident, count everything.

The serving tier's throughput contract is "a repeated request stream
never recompiles": every bucket dispatch goes through this cache, which
does ``jit(...).lower(shapes).compile()`` ONCE per key and then hands
back the resident executable. The same machinery is the one code path
bench.py's compile-cache prewarm child uses (its keys are bench stage
names), so "prewarm compiles what measuring runs" is enforced by
construction rather than by two call sites staying in sync.

Accounting rides the shared profiling utilities
(:class:`dhqr_tpu.utils.profiling.Counters` for hit/miss/eviction
counts, :class:`~dhqr_tpu.utils.profiling.PhaseTimer` for per-compile
wall seconds), so benchmarks, the dry run and operators all read the
numbers the engine itself maintains (``cache_stats()``; OPERATIONS.md
has the runbook).

Eviction is LRU with a bound from ``ServeConfig.cache_size``
(``DHQR_SERVE_CACHE_SIZE``). Evicting drops only the in-process
executable handle; when a persistent jax compilation cache is enabled
(utils/platform.enable_compile_cache) a re-miss recompiles cheaply from
the serialized artifact instead of from scratch.

Round 22 adds an optional FLEET disk tier underneath
(``serve.store.ExecutableStore``, attached when ``DHQR_FLEET_STORE``
names a directory): a miss first tries to DESERIALIZE a sibling
replica's persisted executable (zero compiles on a warm fleet), a
successful compile writes through, and quarantine verdicts adopted
from the shared fleet state are honored next to the local ones. With
no store configured every line of that is absent — the per-process
behavior, keys and counters are unchanged.

Failed compiles QUARANTINE their key (round 12): a program whose
compile raised is not retried for ``ServeConfig.quarantine_s`` —
requests that land on it inside the cooldown get a typed
:class:`~dhqr_tpu.serve.errors.Quarantined` with a positive
``retry_after`` instead of re-paying a compile that is going to fail
again on every flush of the poison bucket. The compile failure itself
surfaces as :class:`~dhqr_tpu.serve.errors.CompileFailed` with the
original exception chained.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, NamedTuple

# serve loads during ``import dhqr_tpu`` itself, so this import must
# stay acyclic — it is safe because nothing under dhqr_tpu.faults
# imports serve (the harness is deliberately dependency-free); keep it
# that way when touching faults/__init__.py.
from dhqr_tpu.faults import harness as _faults
# obs.metrics only reads utils/* (providers import their subjects
# lazily), so this import stays acyclic like the faults one above.
from dhqr_tpu.obs import metrics as _obs_metrics
# obs.xray imports only obs.flops at module level (compat/platform are
# reached lazily from capture paths) — acyclic for the same reason.
from dhqr_tpu.obs import xray as _obs_xray
from dhqr_tpu.serve.errors import CompileFailed, Quarantined
from dhqr_tpu.utils import lockwitness as _lockwitness
from dhqr_tpu.utils.config import ServeConfig
from dhqr_tpu.utils.profiling import Counters, PhaseTimer


class CacheKey(NamedTuple):
    """Everything that selects a distinct serve program.

    ``kind`` is the program family ("lstsq" | "qr" | "sketch");
    ``batch``/``m``/``n``/``dtype`` the bucketed stacked shape; the rest
    the engine knobs that are static arguments of the underlying jit (a
    knob that changed the traced program but not the key would silently
    serve stale executables — keep this in sync with
    ``engine._lower_for_key``). ``sketch`` (round 17) is the sketched
    kind's ``(s, seed, operator)`` triple — the operator arrays are
    drawn deterministically from it and baked into the program as
    constants, so two processes agreeing on the key agree on the
    executable bit-for-bit; None for the direct kinds (the default
    keeps every pre-round-17 key spelling valid).
    """

    kind: str
    batch: int
    m: int
    n: int
    dtype: str
    block_size: int
    precision: str
    trailing_precision: "str | None"
    apply_precision: "str | None"
    refine: int
    norm: str
    panel_impl: str
    sketch: "tuple | None" = None


class ExecutableCache:
    """LRU-bounded map from hashable keys to compiled executables.

    ``get_or_compile(key, lower_fn)`` is the only entry point:
    ``lower_fn`` must return a ``jax.stages.Lowered`` (or any object
    with ``.compile()``); the cache owns the compile, its timing, and
    the counters. Keys are usually :class:`CacheKey`, but any hashable
    works (bench.py's prewarm stages use plain tuples).
    """

    def __init__(self, max_size: "int | None" = None,
                 quarantine_s: "float | None" = None,
                 clock=time.monotonic, store="auto") -> None:
        if max_size is None or quarantine_s is None:
            scfg = ServeConfig.from_env()
            max_size = scfg.cache_size if max_size is None else max_size
            quarantine_s = scfg.quarantine_s if quarantine_s is None \
                else quarantine_s
        if store == "auto":
            # The fleet disk tier (round 22): attach the process-default
            # ExecutableStore when DHQR_FLEET_STORE names a directory;
            # unset, store is None and this cache is byte-for-byte the
            # per-process pre-round-22 tier (same keys, same counters,
            # same dispatch results). Tests pass store=None to force
            # isolation or an ExecutableStore instance to share one.
            from dhqr_tpu.serve import store as _store_mod

            store = _store_mod.default_store()
        self._store = store
        if max_size < 1:
            raise ValueError(f"max_size must be >= 1, got {max_size}")
        if not quarantine_s > 0:
            raise ValueError(
                f"quarantine_s must be > 0, got {quarantine_s}")
        self.max_size = int(max_size)
        self.quarantine_s = float(quarantine_s)
        self._clock = clock
        # guarded by: _lock
        self._entries: "OrderedDict[object, object]" = OrderedDict()
        # key -> cooldown expiry (clock seconds) after a failed compile.
        self._quarantine: "dict[object, float]" = {}  # guarded by: _lock
        # canonical key spelling -> cooldown expiry, INHERITED from
        # another replica via the shared fleet state (round 22). Kept
        # separate from the local dict: local keys are CacheKey objects,
        # adopted verdicts arrive as cross-process strings, and the
        # lookup below only pays the canonical rendering when this map
        # is non-empty (zero cost for per-process serving).
        self._quarantine_adopted: "dict[str, float]" = {}  # guarded by: _lock
        self.counters = Counters()
        self.timer = PhaseTimer()
        # One lock for lookup + insert + evict + counters: a serving tier
        # is driven from concurrent request threads, and an unlocked
        # hit/evict interleaving can KeyError a request that should have
        # been a hit. Compiles hold the lock too — serializing concurrent
        # compiles of the SAME key is the point (one compile, N waiters),
        # and concurrent compiles of different keys would contend on
        # XLA's own compilation locks anyway.
        self._lock = _lockwitness.make_rlock("ExecutableCache._lock")
        # Unified metrics (round 14): every cache's numbers roll up
        # under serve.cache.* dotted names. Weakly held — a test-scoped
        # cache leaves the registry with garbage collection.
        _obs_metrics.registry().register("serve.cache", self)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def get_or_compile(self, key, lower_fn: Callable[[], object]):
        """Return the executable for ``key``, compiling on first miss.

        A compile that raises (organic, or the ``serve.compile``
        injection site) surfaces as :class:`CompileFailed` with the
        cause chained, and quarantines ``key`` for ``quarantine_s``:
        until the cooldown expires, further requests for the key raise
        :class:`Quarantined` (with the remaining cooldown as a positive
        ``retry_after``) WITHOUT compiling — one failed compile per
        cooldown window, however hot the poison bucket is.
        """
        with self._lock:
            if key in self._entries:
                self.counters.bump("hits")
                self._entries.move_to_end(key)
                return self._entries[key]
            until = self._quarantine.get(key)
            if until is not None:
                now = self._clock()
                if now < until:
                    self.counters.bump("quarantine_hits")
                    # Quarantined clamps retry_after positive; the clamp
                    # matters at the expiry boundary, where until - now
                    # underflows toward zero.
                    raise Quarantined(key, until - now)
                del self._quarantine[key]  # cooldown over: one retry
            if self._quarantine_adopted:
                ks = self._canonical(key)
                until = None if ks is None else \
                    self._quarantine_adopted.get(ks)
                if until is not None:
                    now = self._clock()
                    if now < until:
                        self.counters.bump("quarantine_hits")
                        raise Quarantined(key, until - now)
                    del self._quarantine_adopted[ks]
            self.counters.bump("misses")
            if self._store is not None:
                # Fleet disk tier (round 22): a sibling replica already
                # paid this compile — deserialize its blob instead. A
                # miss/corrupt/skewed blob returns (None, reason) with
                # the store counting it (disk_misses /
                # deserialize_failures) and we fall through to the plain
                # compile: the disk tier can make a miss cheaper, never
                # make one fail.
                exe, _reason = self._store.load(key)
                if exe is not None:
                    self._entries[key] = exe
                    while len(self._entries) > self.max_size:
                        # Memory eviction only — the disk blob stays (a
                        # re-miss re-deserializes); store.evict() is the
                        # explicit disk-side deletion.
                        self._entries.popitem(last=False)
                        self.counters.bump("evictions")
                    return exe
            before = self.timer.total("aot_compile")
            try:
                with self.timer.measure("aot_compile"):
                    _faults.fire("serve.compile")
                    # dhqr: ignore[DHQR603] compile-under-lock is the design: one compile per key, N waiters (see the _lock comment above)
                    exe = lower_fn().compile()
            except Exception as e:
                self.counters.bump("compile_failures")
                self._quarantine[key] = self._clock() + self.quarantine_s
                raise CompileFailed(key, e) from e
            # The timer is the ONE source of compile wall time; the
            # counter mirrors it so stats() stays a flat JSON dict.
            compile_s = self.timer.total("aot_compile") - before
            self.counters.bump("compile_seconds", compile_s)
            # dhqr-xray (round 15): armed capture introspects the fresh
            # executable's cost/memory analysis HERE — the one compile
            # entry of the serving stack — so every compiled program
            # gets a report without a second code path. On the MISS
            # branch only: disarmed (and on every warm hit) this line
            # never runs; armed, the sub-ms capture rides a
            # seconds-scale compile. capture() never raises.
            xray_store = _obs_xray.active()
            if xray_store is not None:
                xray_store.capture(key, exe, compile_seconds=compile_s)
            if self._store is not None:
                # Write-through: the blob this process just paid for is
                # every future replica's zero-compile warm start. Purely
                # best-effort — an unserializable executable or a full
                # disk costs a counted reason (fleet.store
                # serialize_failures), never the dispatch.
                self._store.save(key, exe)
            self._entries[key] = exe
            while len(self._entries) > self.max_size:
                self._entries.popitem(last=False)
                self.counters.bump("evictions")
            return exe

    def stats(self) -> dict:
        """Counter snapshot + occupancy, JSON-ready (the benchmark
        artifact, the dry run and the async scheduler's stats endpoint
        embed this verbatim). Since round 14 this is a thin
        compatibility view over :meth:`metrics_snapshot` — the same
        names the metrics registry exports as ``serve.cache.*``.

        The whole snapshot is taken under ONE acquisition of the cache
        lock — counters and occupancy are a single consistent cut, so
        invariants like ``misses >= size + evictions`` (every resident
        entry and every eviction was once a miss) hold in every snapshot
        a concurrent reader takes, never just in quiescence
        (tests/test_serve.py pins this under a writer storm).
        """
        return self.metrics_snapshot()

    def metrics_snapshot(self) -> dict:
        """The registry-facing snapshot (``serve.cache.<name>`` under
        the process registry, ``dhqr_tpu.obs.metrics``); identical to
        :meth:`stats` by construction — one set of numbers."""
        with self._lock:
            snap = self.counters.snapshot()
            now = self._clock()
            for k in [k for k, t in self._quarantine.items() if now >= t]:
                del self._quarantine[k]  # expired: not "in quarantine"
            for k in [k for k, t in self._quarantine_adopted.items()
                      if now >= t]:
                del self._quarantine_adopted[k]
            return {
                "size": len(self._entries),
                "max_size": self.max_size,
                "hits": int(snap.get("hits", 0)),
                "misses": int(snap.get("misses", 0)),
                "evictions": int(snap.get("evictions", 0)),
                "compile_seconds": round(
                    float(snap.get("compile_seconds", 0)), 3),
                "compile_failures": int(snap.get("compile_failures", 0)),
                "quarantined": (len(self._quarantine)
                                + len(self._quarantine_adopted)),
                "quarantine_hits": int(snap.get("quarantine_hits", 0)),
            }

    @staticmethod
    def _canonical(key) -> "str | None":
        """``key``'s canonical cross-process spelling, or None where it
        has none (the store/state machinery then skips the key)."""
        from dhqr_tpu.serve.store import canonical_key

        try:
            return canonical_key(key)
        except ValueError:
            return None

    def export_quarantines(self, wall=time.time) -> "dict[str, float]":
        """Active quarantines as {canonical key spelling: WALL-clock
        expiry} — the shared-fleet-state spelling (round 22). Wall
        clock, not this cache's (possibly fake/monotonic) clock: the
        consumer is another process whose monotonic epoch is unrelated.
        Adopted cooldowns re-export, so verdicts survive N hops of
        replica succession, not just one."""
        now = self._clock()
        wall_now = wall()
        out: "dict[str, float]" = {}
        with self._lock:
            local = list(self._quarantine.items())
            adopted = list(self._quarantine_adopted.items())
        for key, until in local:
            remaining = until - now
            if remaining <= 0:
                continue
            ks = self._canonical(key)
            if ks is not None:
                out[ks] = max(out.get(ks, 0.0), wall_now + remaining)
        for ks, until in adopted:
            remaining = until - now
            if remaining > 0:
                out[ks] = max(out.get(ks, 0.0), wall_now + remaining)
        return out

    def adopt_quarantines(self, mapping: "dict[str, float]",
                          wall=time.time) -> int:
        """Inherit another replica's quarantine verdicts ({canonical
        spelling: wall-clock expiry}); returns how many are now active.
        Later expiries win (a verdict can only be extended by newer
        evidence, never silently shortened)."""
        now = self._clock()
        wall_now = wall()
        adopted = 0
        with self._lock:
            for ks, expiry in mapping.items():
                try:
                    remaining = float(expiry) - wall_now
                except (TypeError, ValueError):
                    continue
                if remaining <= 0:
                    continue
                until = now + remaining
                prev = self._quarantine_adopted.get(str(ks))
                if prev is None or until > prev:
                    self._quarantine_adopted[str(ks)] = until
                adopted += 1
        return adopted

    def clear(self) -> None:
        """Drop every resident executable and every active quarantine
        (counters keep accumulating — they are lifetime telemetry, not
        occupancy). The fleet disk tier is NOT touched: clearing memory
        is an in-process operation, deleting shared blobs is
        ``store.evict()``/``store.clear()`` — an explicit, separate
        decision."""
        with self._lock:
            self._entries.clear()
            self._quarantine.clear()
            self._quarantine_adopted.clear()


# The process-default cache every public serve entry point dispatches
# through — created LAZILY on first serve use, not at import: a
# malformed DHQR_SERVE_* variable must fail the serve call that reads
# it, never `import dhqr_tpu` for users who don't touch the tier, and
# DHQR_SERVE_CACHE_SIZE set programmatically before first use must
# still take effect. Tests that need isolation construct their own
# ExecutableCache and pass it in.
_DEFAULT_CACHE: "ExecutableCache | None" = None
_DEFAULT_CACHE_LOCK = _lockwitness.make_lock("cache._DEFAULT_CACHE_LOCK")


def default_cache() -> ExecutableCache:
    """The process-default serve cache (created on first use)."""
    global _DEFAULT_CACHE
    if _DEFAULT_CACHE is None:
        with _DEFAULT_CACHE_LOCK:
            if _DEFAULT_CACHE is None:
                _DEFAULT_CACHE = ExecutableCache()
    return _DEFAULT_CACHE


def cache_stats() -> dict:
    """Stats of the process-default serve cache."""
    return default_cache().stats()


def clear_cache() -> None:
    """Clear the process-default serve cache."""
    default_cache().clear()
