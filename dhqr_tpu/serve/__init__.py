"""Batched serving tier: shape buckets + AOT executable cache + vmapped
throughput engine.

The reference factors one matrix at a time; this subsystem is the
framework's answer to the serving workload — many small/medium problems
with heterogeneous shapes, where throughput comes from (a) keeping
compiled programs resident (``serve.cache``) and (b) feeding them
stacked work (``serve.engine``), with shapes rounded onto a small
padded-bucket lattice so both stay finite (``serve.buckets``).

    >>> from dhqr_tpu.serve import batched_lstsq, prewarm, cache_stats
    >>> xs = batched_lstsq(As, bs)             # list in, list out, exact
    >>> prewarm([(32, 512, 256)])              # compile before traffic
    >>> cache_stats()                          # hits/misses/compile s

See docs/DESIGN.md "Serving tier" for the bucket-lattice rationale and
docs/OPERATIONS.md for the cache runbook.
"""

from dhqr_tpu.serve.buckets import (
    Bucket,
    bucket_batch,
    bucket_dim,
    plan_bucket,
)
from dhqr_tpu.serve.cache import (
    CacheKey,
    ExecutableCache,
    cache_stats,
    clear_cache,
    default_cache,
)
from dhqr_tpu.serve.engine import (
    batched_lstsq,
    batched_qr,
    bucket_program,
    prewarm,
)

__all__ = [
    "Bucket",
    "CacheKey",
    "ExecutableCache",
    "default_cache",
    "batched_lstsq",
    "batched_qr",
    "bucket_batch",
    "bucket_dim",
    "bucket_program",
    "cache_stats",
    "clear_cache",
    "plan_bucket",
    "prewarm",
]
