"""Batched serving tier: shape buckets + AOT executable cache + vmapped
throughput engine.

The reference factors one matrix at a time; this subsystem is the
framework's answer to the serving workload — many small/medium problems
with heterogeneous shapes, where throughput comes from (a) keeping
compiled programs resident (``serve.cache``) and (b) feeding them
stacked work (``serve.engine``), with shapes rounded onto a small
padded-bucket lattice so both stay finite (``serve.buckets``).

    >>> from dhqr_tpu.serve import batched_lstsq, prewarm, cache_stats
    >>> xs = batched_lstsq(As, bs)             # list in, list out, exact
    >>> prewarm([(32, 512, 256)])              # compile before traffic
    >>> cache_stats()                          # hits/misses/compile s

For a LIVE request stream (one request at a time, each with its own
latency budget and tenant) the async front-end coalesces arrivals into
the same buckets through the same dispatch path (``serve.scheduler``):

    >>> from dhqr_tpu.serve import AsyncScheduler
    >>> sched = AsyncScheduler()
    >>> fut = sched.submit("lstsq", A, b, deadline=0.05, tenant="acme")
    >>> x = fut.result()

Failure behavior is typed (round 12): every failed serve call or
future carries a :class:`ServeError` subclass — ``CompileFailed`` /
``DispatchFailed`` / ``DeadlineExceeded`` / ``Quarantined`` /
``BackpressureError`` — and the scheduler retries, quarantines,
bisects poison batches and respawns crashed workers so every submitted
future resolves (``dhqr_tpu.faults`` injects the failures that prove
it). See docs/DESIGN.md "Serving tier" / "Async serving" / "Fault
model" for the rationale and docs/OPERATIONS.md for the cache, SLO
and fault-triage runbooks.

Round 22 adds the FLEET tier: a persistent executable store
(``serve.store`` — serialized compiled programs on disk, keyed by a
canonical cross-process key string, so a NEW process warm-starts at
zero compiles), shared fleet state (quarantines / plan demotions /
armor wire trips published and adopted via the PlanDB last-write-wins
discipline) and a replica :class:`Router` over K in-process schedulers
with tenant-aware smooth-WRR balancing, fleet-level backpressure
composition and typed failover (``ReplicaLost``):

    >>> from dhqr_tpu.serve import Router
    >>> router = Router(replicas=3)
    >>> x = router.submit("lstsq", A, b, tenant="acme").result()
"""

from dhqr_tpu.serve.buckets import (
    Bucket,
    bucket_batch,
    bucket_dim,
    plan_bucket,
)
from dhqr_tpu.serve.cache import (
    CacheKey,
    ExecutableCache,
    cache_stats,
    clear_cache,
    default_cache,
)
from dhqr_tpu.serve.engine import (
    batched_lstsq,
    batched_qr,
    batched_sketched_lstsq,
    bucket_program,
    prewarm,
)
from dhqr_tpu.serve.errors import (
    BackpressureError,
    CompileFailed,
    DeadlineExceeded,
    DispatchFailed,
    Quarantined,
    ReplicaLost,
    ServeError,
)
from dhqr_tpu.serve.router import Router
from dhqr_tpu.serve.scheduler import AsyncScheduler
from dhqr_tpu.serve.store import (
    ExecutableStore,
    canonical_key,
    default_store,
    load_fleet_state,
    save_fleet_state,
)

__all__ = [
    "AsyncScheduler",
    "BackpressureError",
    "Bucket",
    "CacheKey",
    "CompileFailed",
    "DeadlineExceeded",
    "DispatchFailed",
    "ExecutableCache",
    "ExecutableStore",
    "Quarantined",
    "ReplicaLost",
    "Router",
    "ServeError",
    "canonical_key",
    "default_cache",
    "default_store",
    "load_fleet_state",
    "save_fleet_state",
    "batched_lstsq",
    "batched_qr",
    "batched_sketched_lstsq",
    "bucket_batch",
    "bucket_dim",
    "bucket_program",
    "cache_stats",
    "clear_cache",
    "plan_bucket",
    "prewarm",
]
