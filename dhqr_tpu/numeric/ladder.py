"""Condition-aware fallback ladder: breakdown detection + typed escalation.

dhqr-tune routes tall-skinny solves to ``cholqr2`` for its measured
4.6-11.8x wins — but CholeskyQR2 breaks down (NaN factors) once
``cond(A)`` approaches ``1/sqrt(eps)`` (ops/cholqr.py), and a
production stream sees ill-conditioned, rank-deficient and NaN-bearing
matrices daily. This module is the runtime answer, the
accuracy-vs-speed engine laddering of the TPU linear-algebra paper
(arXiv 2112.09017) made automatic:

* :func:`guarded_lstsq` / :func:`guarded_qr` screen the input
  (``numeric.guards`` — non-finite scan, zero-column detection), run
  the requested engine, health-check the output, and on detected
  breakdown ESCALATE down a fixed engine ladder::

      cholqr2 -> cholqr3 (shifted, +1 pass) -> tsqr -> householder

  followed by POLICY escalation on the stable engine (``fast`` ->
  ``accurate`` -> ``accurate`` + one more refinement sweep). Every
  rung is recorded (:class:`Attempt`), the taken path rides on the
  returned :class:`GuardedResult`, and a rung-0 failure under an
  active plan is reported to ``dhqr_tpu.tune`` so a plan whose gate
  keeps failing is demoted out of ``plan="auto"`` resolution.
* Exhausting the ladder raises TYPED
  (:mod:`dhqr_tpu.numeric.errors`): ``Breakdown`` when factors went
  non-finite, ``IllConditioned`` when the cheap condition lower bound
  implicates conditioning (or the input is structurally singular),
  ``ResidualGateFailed`` when every rung returned finite-but-wrong
  (``guards="full"`` only — the probe is one host LAPACK solve).

Fallback rungs deliberately do NOT inherit a policy's trailing-GEMM
split: Gram rounding is SQUARED through Cholesky (ops/cholqr.py), so a
cheap syrk narrows the very conditioning window the ladder is escaping.
A fallback rung runs the policy's PANEL precision with full-precision
composition math; refinement sweeps carry over where the engine
supports them (tsqr has no reusable factorization — its rung runs
refine=0 and leans on the residual gate).

Zero-recompile steady state: every guard program is a tiny jitted
reduction cached per shape, and the engines the rungs dispatch are the
SAME jitted impls the unguarded API uses — a warm repeat of a guarded
call (including one that recovered via fallback) compiles nothing
(pinned by tests/test_numeric.py and the ``_dryrun`` numeric stage).

Deterministic testing: the ``numeric.nan`` fault site fires at the
input screen (as if the scan had found a NaN) and ``numeric.breakdown``
fires per rung (as if that rung's factors came back non-finite) —
``dhqr_tpu.faults`` schedules make every escalation path replayable
without crafting a matrix for it.
"""

from __future__ import annotations

import dataclasses

from dhqr_tpu.armor.errors import ArmorError
from dhqr_tpu.faults import harness as _faults
from dhqr_tpu.numeric import guards as _guards
from dhqr_tpu.numeric.errors import (
    Breakdown,
    IllConditioned,
    NonFiniteInput,
    NumericalError,
    ResidualGateFailed,
)
from dhqr_tpu.obs import trace as _obs
from dhqr_tpu.utils.profiling import Counters

#: Process-wide guardrail accounting, exported by the metrics registry
#: as ``numeric.*`` (``dhqr_tpu.obs.metrics``): ``guarded_calls``
#: (entries into a guarded_* call), ``screen_rejects`` (typed refusals
#: at the input screen), ``fallbacks`` (ladder rungs that FAILED —
#: breakdown/inapplicable/residual-gate — whether or not a later rung
#: recovered; a structural ``zero_pivot`` rung is NOT a fallback, the
#: call refuses instead of escalating), ``recovered`` (guarded calls
#: that escalated and still answered), ``exhausted`` (post-screen
#: typed refusals: the ladder ran dry, or a structural rank
#: deficiency — an exactly-zero R pivot — that no rung could ever
#: answer, so escalation was never attempted). Always-on like every
#: other subsystem's Counters — the registry view must not depend on
#: tracing being armed.
COUNTERS = Counters()

#: Escalation order per starting engine: strictly toward stability
#: (each step trades GEMM throughput for conditioning headroom).
ENGINE_LADDER = {
    "cholqr2": ("cholqr3", "tsqr", "householder"),
    "cholqr3": ("tsqr", "householder"),
    "tsqr": ("householder",),
    "householder": (),
    # Round 17: a sketched solve that breaks down (or fails the
    # residual probe — a pathological embedding draw) escalates
    # straight to the stable direct engine; there is no intermediate
    # randomized rung worth paying for.
    "sketch": ("householder",),
}

GUARD_MODES = ("screen", "fallback", "full")


@dataclasses.dataclass(frozen=True)
class Attempt:
    """One ladder rung's outcome.

    ``outcome`` is "ok", "breakdown" (non-finite output — organic, or
    injected when ``detail`` says so), "inapplicable" (the engine
    rejected the problem shape/knobs — e.g. tsqr needs genuinely tall
    row blocks, the m < n path takes no refinement), "residual_gate"
    (finite but over the 8x criterion; ratio in ``residual_ratio``),
    "corruption" (round 19: the armor seam's typed
    ``CorruptionDetected``/``ShardFailure`` after its own recovery
    ladder ran dry — the rung's TRANSPORT failed, so the guarded
    ladder escalates to the next engine exactly as for a breakdown),
    or "zero_pivot" (``guarded_qr``: finite factors with an
    exactly-zero R diagonal entry). Anything else a rung raises
    propagates immediately — the ladder absorbs numerical failures,
    not bugs."""

    engine: str
    policy: str
    outcome: str
    detail: "str | None" = None
    residual_ratio: "float | None" = None


@dataclasses.dataclass(frozen=True)
class GuardedResult:
    """A guarded call's value plus its provenance.

    ``value`` is what the unguarded API returns (``x`` for lstsq, a
    ``QRFactorization`` for qr); ``engine``/``policy`` name the rung
    that produced it; ``attempts`` is the full per-rung record (length
    1 when nothing escalated); ``residual_ratio`` is the probe's
    measurement when ``guards="full"`` ran it (None otherwise);
    ``cond_estimate`` is the R-diagonal condition lower bound when the
    mode computed one (``guarded_qr`` under ``"full"``)."""

    value: object
    engine: str
    policy: str
    attempts: "tuple[Attempt, ...]"
    residual_ratio: "float | None" = None
    cond_estimate: "float | None" = None
    # Round 14: the obs trace id of this guarded call (None when tracing
    # was disarmed) — ``dhqr_tpu.obs.flight_dump(result.trace_id)``
    # replays the screen/rung path the attempts tuple summarizes.
    trace_id: "int | None" = None

    @property
    def x(self):
        """The solution array (lstsq spelling of ``value``)."""
        return self.value

    @property
    def factorization(self):
        """The factorization (qr spelling of ``value``)."""
        return self.value

    @property
    def escalations(self) -> int:
        """How many rungs failed before the one that answered."""
        return len(self.attempts) - 1


def _trace_guarded(kind: str, engine: str, mode: str, shape):
    """Mint a call-scoped trace id for a guarded entry point and record
    its admission span. Returns ``(recorder, trace_id)`` — None/None
    when tracing is disarmed (the one global-read check the cold path
    pays; every helper below no-ops on recorder None)."""
    rec = _obs.active()
    if rec is None:
        return None, None
    tid = rec.mint()
    rec.event(tid, "submit", kind=kind, engine=engine, mode=mode,
              m=int(shape[0]), n=int(shape[1]))
    return rec, tid


def _trace_rung(rec, tid, att: Attempt) -> None:
    """One ladder rung as a span (recorded in real time, where the rung
    ran — the GuardedResult's attempts tuple is the summary, this is
    the timeline)."""
    if rec is None:
        return
    attrs = {"engine": att.engine, "policy": att.policy,
             "outcome": att.outcome}
    if att.detail:
        attrs["detail"] = att.detail[:200]
    if att.residual_ratio is not None:
        attrs["residual_ratio"] = round(att.residual_ratio, 4)
    rec.event(tid, "rung", **attrs)


def _trace_refusal(rec, tid, exc: BaseException) -> None:
    """Close a guarded call's path with its typed refusal: the resolve
    span, the trace id on the error, and the on_error auto-dump hook."""
    if rec is None:
        return
    rec.event(tid, "resolve", outcome=type(exc).__name__,
              error=str(exc)[:200])
    rec.on_error(exc, tid)


def _attempt_recorder(attempts: list, rec, tid):
    """The one place a ladder rung is recorded — summary (attempts),
    accounting (``numeric.fallbacks`` counts the rungs that did not
    answer, recovered or not), and the real-time rung span. Shared by
    ``guarded_lstsq`` and ``guarded_qr`` so the counters and the trace
    can never desynchronize."""
    def _att(att: Attempt) -> None:
        attempts.append(att)
        if att.outcome in ("breakdown", "inapplicable", "residual_gate",
                           "corruption"):
            COUNTERS.bump("fallbacks")
        _trace_rung(rec, tid, att)
    return _att


def _refuse(rec, tid, err: BaseException) -> "BaseException":
    """The typed-refusal epilogue every dead-end shares: count it,
    close the trace, hand the error back for ``raise``."""
    COUNTERS.bump("exhausted")
    _trace_refusal(rec, tid, err)
    return err


def _mode(cfg) -> str:
    mode = cfg.guards or "fallback"
    if mode not in GUARD_MODES:
        raise ValueError(
            f"guards must be one of {GUARD_MODES} or None, got {mode!r}"
        )
    return mode


def _policy_desc(pol, cfg) -> str:
    """Compact policy spelling for Attempt/GuardedResult records — the
    tune DB's own ``policy_tag`` rendering in BOTH branches (classic
    knobs are folded into a PrecisionPolicy first), so descriptions,
    plan keys, and the escalation-rung dedupe can never diverge."""
    from dhqr_tpu.tune.db import policy_tag

    if pol is None:
        from dhqr_tpu.precision import PrecisionPolicy

        pol = PrecisionPolicy(
            panel=cfg.precision, trailing=cfg.trailing_precision,
            apply=cfg.apply_precision, refine=cfg.refine)
    return policy_tag(pol)


def _screen(A, b, engine_hint: "str | None") -> None:
    """Input screening: typed raises, nothing else. The ``numeric.nan``
    fault site fires here — an injected trigger is treated exactly as a
    detected non-finite entry."""
    try:
        _faults.fire("numeric.nan")
    except _faults.FaultInjected as e:
        raise NonFiniteInput(
            "non-finite input detected (injected numeric.nan fault)",
            engine=engine_hint) from e
    bad_A, zero_col, bad_b = _guards.screen_input(A, b)
    if bad_A or bad_b:
        which = "A" if bad_A else "b"
        raise NonFiniteInput(
            f"input {which} carries non-finite entries; no engine can "
            "recover a poisoned input — clean or drop the request",
            engine=engine_hint)
    if zero_col:
        raise IllConditioned(
            "input has an exactly-zero column (structurally "
            "rank-deficient, cond = inf); regularize or drop the column",
            engine=engine_hint, cond_estimate=float("inf"))


def _resolve_start(A, cfg, mesh):
    """Mirror ``lstsq``'s own policy/plan resolution so rung 0 runs the
    byte-identical program the unguarded call would have dispatched.
    Returns ``(cfg0, pol, plan_active)`` — ``plan_active`` is True only
    when a stored/explicit plan ACTUALLY landed on the config (a DB
    miss falling back to the static default must never feed plan
    demotion)."""
    from dhqr_tpu.models import qr_model as _qm

    cfg, pol = _qm._resolve_policy_cfg(cfg)
    if pol is not None and pol.refine:
        cfg = dataclasses.replace(cfg, refine=pol.refine)
    applied: list = []
    cfg = _qm._resolve_plan_cfg(cfg, "lstsq", A.shape, A.dtype, mesh, pol,
                                applied=applied)
    return cfg, pol, bool(applied)


def _fallback_cfg(engine: str, pol, base, mesh):
    """Config for a FALLBACK rung: the stable engine's defaults plus
    the caller's accuracy-relevant knobs (panel precision, norm,
    refinement where the engine supports it). Trailing/apply splits and
    plan-selected knobs are deliberately dropped — see the module
    docstring."""
    from dhqr_tpu.utils.config import DHQRConfig

    refine = pol.refine if pol is not None else base.refine
    if engine == "tsqr" or (mesh is not None
                            and engine in ("cholqr2", "cholqr3")):
        refine = 0  # unsupported there (tsqr tree; mesh cholqr)
    return DHQRConfig(
        engine=engine,
        precision=(pol.panel if pol is not None else base.precision),
        norm=base.norm, mesh_axis=base.mesh_axis, refine=refine,
    )


def _escalation_policies(pol, base):
    """The policy-escalation tail on the stable engine — the
    ``fast -> accurate -> refine+1`` laddering, derived in
    :func:`dhqr_tpu.precision.escalation_policies` (the precision
    module owns what "cheaper than accurate" means)."""
    from dhqr_tpu.precision import escalation_policies

    if pol is not None:
        return escalation_policies(pol)
    cheap = bool(base.trailing_precision or base.apply_precision
                 or base.precision != "highest")
    return escalation_policies(base_refine=base.refine, cheap=cheap)


def _note_plan_failure(A, mesh, pol) -> None:
    """Rung 0 failed under an active plan: report to tune so
    ``plan=\"auto\"`` demotes a repeat offender (tune/search.py)."""
    from dhqr_tpu.tune.search import note_gate_failure

    nproc = 1
    if mesh is not None:
        import numpy as np

        nproc = int(np.prod(list(mesh.shape.values())))
    note_gate_failure("lstsq", A.shape[0], A.shape[1], A.dtype,
                      nproc=nproc, policy=pol)


def _classify_exhausted(A, attempts, probe_ran: bool):
    """Build the typed error once every rung has failed."""
    broken = [a for a in attempts if a.outcome == "breakdown"]
    gated = [a for a in attempts if a.outcome == "residual_gate"]
    first_engine = attempts[0].engine if attempts else None
    if broken:
        est = _guards.estimate_condition(A)
        window = None
        eng = broken[0].engine
        if eng in ("cholqr2", "cholqr3"):
            from dhqr_tpu.ops.cholqr import cholqr_max_cond

            window = cholqr_max_cond(A.dtype, shift=eng == "cholqr3")
        if est is not None and window is not None and est > window:
            return IllConditioned(
                f"{eng} broke down and the condition lower bound "
                f"{est:.3e} exceeds its window (~{window:.1e}); "
                f"{len(attempts)} rung(s) tried without success",
                engine=first_engine, cond_estimate=est, attempts=attempts)
        return Breakdown(
            f"factorization broke down on every applicable rung "
            f"({len(attempts)} tried; condition lower bound "
            f"{'unavailable' if est is None else format(est, '.3e')})",
            engine=first_engine, cond_estimate=est, attempts=attempts)
    if gated and probe_ran:
        worst = max(a.residual_ratio for a in gated
                    if a.residual_ratio is not None)
        return ResidualGateFailed(
            f"every rung returned a finite solution above the "
            f"8x-LAPACK residual criterion (worst ratio {worst:.2f}x); "
            "refusing to return silent garbage",
            engine=first_engine,
            cond_estimate=_guards.estimate_condition(A),
            attempts=attempts, residual_ratio=worst)
    # Nothing ran at all (every rung inapplicable) — a shape no engine
    # takes would have raised from rung 0 already, so this is a ladder
    # bug surfacing loudly rather than silently.
    return Breakdown(
        f"no ladder rung was applicable ({len(attempts)} recorded)",
        engine=first_engine, attempts=attempts)


def guarded_lstsq(
    A,
    b,
    config=None,
    mesh=None,
    **overrides,
) -> GuardedResult:
    """Least squares with numeric guardrails: screen -> run -> health
    check -> escalate -> typed refusal.

    The guard mode comes from ``config.guards`` (or ``guards=`` in
    overrides): ``"screen"`` = input screening only (one scan, then the
    unguarded call); ``"fallback"`` (the default here) = screening +
    breakdown detection + the engine/policy ladder; ``"full"`` =
    fallback + the one-shot residual probe on every rung's output
    (costs one host LAPACK solve per CALL — acceptance benchmarks and
    "no silent garbage" deployments). The public
    ``lstsq(A, b, guards=...)`` routes here and returns ``.x``; call
    this directly for the provenance (:class:`GuardedResult`).
    """
    import jax.numpy as jnp

    from dhqr_tpu.utils.config import DHQRConfig

    cfg = dataclasses.replace(config or DHQRConfig(), **overrides)
    mode = _mode(cfg)
    cfg = dataclasses.replace(cfg, guards=None)
    A = jnp.asarray(A)
    b = jnp.asarray(b)

    from dhqr_tpu.models.qr_model import lstsq as _lstsq

    rec, tid = _trace_guarded("guarded_lstsq", cfg.engine, mode, A.shape)
    COUNTERS.bump("guarded_calls")
    try:
        _screen(A, b, cfg.engine)
    except NumericalError as e:
        COUNTERS.bump("screen_rejects")
        _trace_refusal(rec, tid, e)
        raise
    if rec is not None:
        rec.event(tid, "screen", outcome="ok")
    if mode == "screen":
        x = _lstsq(A, b, config=cfg, mesh=mesh)
        pol_desc = _policy_desc(None, cfg) if cfg.policy is None else \
            str(cfg.policy)
        att = Attempt(cfg.engine, pol_desc, "ok")
        _trace_rung(rec, tid, att)
        if rec is not None:
            rec.event(tid, "resolve", outcome="ok", engine=cfg.engine)
        return GuardedResult(x, cfg.engine, pol_desc, (att,),
                             trace_id=tid)

    cfg0, pol, plan_active = _resolve_start(A, cfg, mesh)
    probe = mode == "full"
    m, n = A.shape

    # Rung list: (engine, config, policy-description). Rung 0 is the
    # caller's resolved route verbatim; the m < n minimum-norm path has
    # exactly one engine, so its "ladder" is policy escalation only.
    rungs: "list[tuple[str, object, str]]" = [
        (cfg0.engine, cfg0, _policy_desc(pol, cfg0))]
    if m >= n:
        for eng in ENGINE_LADDER.get(cfg0.engine, ()):
            fcfg = _fallback_cfg(eng, pol, cfg0, mesh)
            rungs.append((eng, fcfg, _policy_desc(None, fcfg)))
    from dhqr_tpu.tune.db import policy_tag

    for esc in _escalation_policies(pol, cfg0):
        ecfg = dataclasses.replace(
            _fallback_cfg("householder", None, cfg0, mesh),
            precision=DHQRConfig.precision, refine=0, policy=esc)
        desc = policy_tag(esc)
        # Dedupe against EVERY rung already queued (the engine ladder's
        # own householder rung included), not just rung 0 — an
        # identical config must never be factored twice.
        if all((eng, d) != ("householder", desc)
               for eng, _, d in rungs):
            rungs.append(("householder", ecfg, desc))

    attempts: "list[Attempt]" = []
    last_armor: "ArmorError | None" = None
    _att = _attempt_recorder(attempts, rec, tid)
    for i, (eng, rcfg, desc) in enumerate(rungs):
        try:
            _faults.fire("numeric.breakdown")
        except _faults.FaultInjected:
            _att(Attempt(eng, desc, "breakdown",
                         detail="injected numeric.breakdown"))
            if i == 0 and plan_active:
                _note_plan_failure(A, mesh, pol)
            continue
        try:
            x = _lstsq(A, b, config=rcfg, mesh=mesh)
        except ValueError as e:
            if i == 0:
                raise  # the caller's own config error — never masked
            _att(Attempt(eng, desc, "inapplicable", detail=str(e)))
            continue
        except ArmorError as e:
            # Round 19: the armor seam refused the rung's TRANSPORT
            # (corrupted collective / lost shard, its own
            # re-dispatch/degrade ladder dry). The next rung dispatches
            # a DIFFERENT program — exactly what escalation is for.
            last_armor = e
            _att(Attempt(eng, desc, "corruption", detail=str(e)[:200]))
            if i == 0 and plan_active:
                _note_plan_failure(A, mesh, pol)
            continue
        if _guards.any_nonfinite(x):
            _att(Attempt(eng, desc, "breakdown"))
            if i == 0 and plan_active:
                _note_plan_failure(A, mesh, pol)
            continue
        ratio = None
        if probe:
            ratio = _guards.residual_ratio(A, b, x)
            from dhqr_tpu.utils.testing import TOLERANCE_FACTOR

            if ratio > TOLERANCE_FACTOR:
                _att(Attempt(eng, desc, "residual_gate",
                             residual_ratio=ratio))
                if i == 0 and plan_active:
                    _note_plan_failure(A, mesh, pol)
                continue
        _att(Attempt(eng, desc, "ok", residual_ratio=ratio))
        if len(attempts) > 1:
            COUNTERS.bump("recovered")
        if rec is not None:
            rec.event(tid, "resolve", outcome="ok", engine=eng,
                      escalations=len(attempts) - 1)
        return GuardedResult(x, eng, desc, tuple(attempts),
                             residual_ratio=ratio, trace_id=tid)
    if last_armor is not None and not any(
            a.outcome in ("breakdown", "residual_gate") for a in attempts):
        # Every failure was transport: the armor error IS the right
        # typed refusal (it carries the collective label / shard index
        # / trace id the runbook triages by); attempts ride along.
        last_armor.attempts = tuple(attempts)
        raise _refuse(rec, tid, last_armor)
    raise _refuse(rec, tid, _classify_exhausted(A, tuple(attempts), probe))


def guarded_qr(
    A,
    config=None,
    mesh=None,
    **overrides,
) -> GuardedResult:
    """Packed QR with numeric guardrails.

    ``qr()`` supports exactly one engine family (householder — the
    packed-reflector contract), so the ladder here is POLICY
    escalation only: the caller's configuration, then ``accurate``.
    Screening and typed refusal match :func:`guarded_lstsq`; a
    structurally singular factorization (an exactly-zero R diagonal
    entry — every later solve would divide by it) raises
    :class:`IllConditioned` rather than returning. ``guards="full"``
    additionally records the R-diagonal condition lower bound on the
    result (no residual probe — a factorization has no residual).
    ``donate=True`` is rejected: escalation must be able to re-read A.
    """
    import jax.numpy as jnp

    from dhqr_tpu.utils.config import DHQRConfig

    cfg = dataclasses.replace(config or DHQRConfig(), **overrides)
    mode = _mode(cfg)
    cfg = dataclasses.replace(cfg, guards=None)
    A = jnp.asarray(A)

    from dhqr_tpu.models.qr_model import qr as _qr
    from dhqr_tpu.precision import PRECISION_POLICIES

    rec, tid = _trace_guarded("guarded_qr", cfg.engine, mode, A.shape)
    COUNTERS.bump("guarded_calls")
    try:
        _screen(A, None, cfg.engine)
    except NumericalError as e:
        COUNTERS.bump("screen_rejects")
        _trace_refusal(rec, tid, e)
        raise
    if rec is not None:
        rec.event(tid, "screen", outcome="ok")
    if mode == "screen":
        fact = _qr(A, config=cfg, mesh=mesh)
        desc = _policy_desc(None, cfg) if cfg.policy is None else \
            str(cfg.policy)
        att = Attempt(cfg.engine, desc, "ok")
        _trace_rung(rec, tid, att)
        if rec is not None:
            rec.event(tid, "resolve", outcome="ok", engine=cfg.engine)
        return GuardedResult(fact, cfg.engine, desc, (att,),
                             trace_id=tid)

    rungs: "list[tuple[object, str]]" = [(cfg, "caller")]
    defaults = DHQRConfig()
    # The "accurate" escalation rung exists only when the caller's
    # FACTOR program is actually cheaper than it — a policy whose
    # factor knobs already match accurate (e.g. policy="accurate", or
    # one that only changes solve-stage fields) would re-factor the
    # byte-identical program on the breakdown path.
    pol0 = None
    if cfg.policy is not None:
        from dhqr_tpu.precision import resolve_policy

        pol0 = resolve_policy(cfg.policy)
    factor_cheap = (
        (pol0 is not None and (pol0.panel != "highest"
                               or pol0.split_trailing() is not None))
        or (pol0 is None and (cfg.precision != defaults.precision
                              or cfg.trailing_precision is not None))
        or cfg.norm != defaults.norm)
    if factor_cheap:
        acc = dataclasses.replace(
            defaults, policy=PRECISION_POLICIES["accurate"],
            mesh_axis=cfg.mesh_axis, block_size=cfg.block_size)
        rungs.append((acc, "accurate"))

    attempts: "list[Attempt]" = []
    last_armor: "ArmorError | None" = None
    _att = _attempt_recorder(attempts, rec, tid)
    for i, (rcfg, desc) in enumerate(rungs):
        try:
            _faults.fire("numeric.breakdown")
        except _faults.FaultInjected:
            _att(Attempt("householder", desc, "breakdown",
                         detail="injected numeric.breakdown"))
            continue
        try:
            fact = _qr(A, config=rcfg, mesh=mesh)  # config errors propagate
        except ArmorError as e:
            # Round 19: transport refusal from the armor seam — the
            # policy-escalation rung re-dispatches a fresh program.
            last_armor = e
            _att(Attempt("householder", desc, "corruption",
                         detail=str(e)[:200]))
            continue
        if _guards.any_nonfinite(fact.H, fact.alpha):
            _att(Attempt("householder", desc, "breakdown"))
            continue
        if bool(jnp.any(jnp.abs(fact.alpha) == 0)):
            # Record the rung that OBSERVED the zero pivot — the
            # attempts contract is "what was tried before the refusal".
            _att(Attempt("householder", desc, "zero_pivot"))
            raise _refuse(rec, tid, IllConditioned(
                "R has an exactly-zero diagonal entry (rank-deficient "
                "to working precision); solves from this factorization "
                "would divide by zero",
                engine="householder", cond_estimate=float("inf"),
                attempts=tuple(attempts)))
        _att(Attempt("householder", desc, "ok"))
        if len(attempts) > 1:
            COUNTERS.bump("recovered")
        if rec is not None:
            rec.event(tid, "resolve", outcome="ok", engine="householder",
                      escalations=len(attempts) - 1)
        cond = (_guards.diag_condition_bound(fact.alpha)
                if mode == "full" else None)
        return GuardedResult(fact, "householder", desc, tuple(attempts),
                             cond_estimate=cond, trace_id=tid)
    if last_armor is not None and not any(
            a.outcome == "breakdown" for a in attempts):
        # Every failure was transport (same rule as guarded_lstsq):
        # the armor error carries the label/shard/trace-id provenance
        # the runbook triages by, and its type routes the scheduler.
        last_armor.attempts = tuple(attempts)
        raise _refuse(rec, tid, last_armor)
    raise _refuse(rec, tid, Breakdown(
        f"householder factorization broke down on every rung "
        f"({len(attempts)} tried) — a finite input should never do "
        "this; suspect hardware or an injected fault left armed",
        engine="householder", attempts=tuple(attempts)))


__all__ = [
    "Attempt",
    "COUNTERS",
    "ENGINE_LADDER",
    "GUARD_MODES",
    "GuardedResult",
    "guarded_lstsq",
    "guarded_qr",
]
