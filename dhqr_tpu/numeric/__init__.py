"""Numerical guardrails (``dhqr_tpu.numeric``) — round 13.

Breakdown detection, a condition-aware fallback ladder, and typed
degradation for the QR core — the numerics sibling of the round-12
infrastructure fault model:

    >>> from dhqr_tpu.numeric import guarded_lstsq
    >>> res = guarded_lstsq(A, b, engine="cholqr2", guards="full")
    >>> res.x                 # the solution (8x-LAPACK gated)
    >>> res.engine            # the rung that answered ("tsqr", ...)
    >>> res.attempts          # per-rung record of the taken path

or through the public API, which returns plain values::

    >>> x = dhqr_tpu.lstsq(A, b, engine="cholqr2", guards="fallback")

Detected breakdown escalates ``cholqr2 -> cholqr3 -> tsqr ->
householder`` and then ``fast -> accurate -> accurate+refine``; a
problem no rung can answer raises one of the typed
:class:`NumericalError` subclasses (``NonFiniteInput``, ``Breakdown``,
``IllConditioned``, ``ResidualGateFailed``) carrying the condition
estimate, the failing engine, and the per-rung attempt record. The
``numeric.breakdown`` / ``numeric.nan`` fault sites
(``dhqr_tpu.faults``) make every escalation path deterministically
replayable. See docs/DESIGN.md "Numerical robustness" and
docs/OPERATIONS.md "Triaging a red residual gate".
"""

from dhqr_tpu.numeric.errors import (
    Breakdown,
    IllConditioned,
    NonFiniteInput,
    NumericalError,
    ResidualGateFailed,
)
from dhqr_tpu.numeric.guards import (
    any_nonfinite,
    checked_cholesky,
    diag_condition_bound,
    estimate_condition,
    residual_ratio,
    screen_input,
)
from dhqr_tpu.numeric.ladder import (
    ENGINE_LADDER,
    GUARD_MODES,
    Attempt,
    GuardedResult,
    guarded_lstsq,
    guarded_qr,
)

__all__ = [
    "Attempt",
    "Breakdown",
    "ENGINE_LADDER",
    "GUARD_MODES",
    "GuardedResult",
    "IllConditioned",
    "NonFiniteInput",
    "NumericalError",
    "ResidualGateFailed",
    "any_nonfinite",
    "checked_cholesky",
    "diag_condition_bound",
    "estimate_condition",
    "guarded_lstsq",
    "guarded_qr",
    "residual_ratio",
    "screen_input",
]
