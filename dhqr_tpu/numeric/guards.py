"""Device-side numerical guards: cheap screening + health checks.

Three kinds of check, each costing one tiny compiled program (cached per
shape/dtype by jit — a warm serving loop re-runs them with ZERO
recompiles) and one scalar readback:

* **input screen** (:func:`screen_input`): any-non-finite scan over A
  (and b) plus zero-column detection, fused into one program — the
  checks that must run BEFORE a factorization is paid for, because no
  engine recovers a poisoned or structurally singular input;
* **output health** (:func:`any_nonfinite`): the breakdown detector —
  CholeskyQR fails LOUDLY (NaN) outside its conditioning window
  (ops/cholqr.py), so finiteness of the result is the cheap, exact
  post-factorization gate the fallback ladder keys on;
* **residual probe** (:func:`residual_ratio`): the one-shot 8x-LAPACK
  normal-equations gate — the SAME criterion the tune accuracy gate and
  the test suite enforce (utils/testing.py) — for callers who want "no
  silent garbage" at the cost of one host LAPACK solve per call
  (``guards="full"``; the ladder documents when to pay it).

This module also owns :func:`checked_cholesky` — THE package's one
sanctioned route to ``lax.linalg.cholesky`` (lint rule DHQR007 flags
any other call site): the wrapper is where the breakdown contract is
written down, so every Cholesky in the package inherits it.

Import discipline: jax/jnp only at module top (no ops/models imports —
``ops/cholqr.py`` imports this module at ITS top, so anything heavier
here would cycle).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def checked_cholesky(G: jax.Array) -> jax.Array:
    """Upper-level Cholesky routing point: ``L`` with ``L L^H = G``.

    ``lax.linalg.cholesky`` does not raise on a non-positive-definite
    input — it returns NaN rows from the first failed pivot on. That
    NaN-loudness IS the in-program breakdown signal the numeric layer
    keys on (a compiled program cannot raise): callers must either
    gate their outputs through :func:`any_nonfinite` (the fallback
    ladder does), or document why breakdown is impossible on their
    inputs. Package code calls Cholesky ONLY through here — lint rule
    DHQR007 flags direct ``*.linalg.cholesky`` calls anywhere else in
    ``dhqr_tpu/`` — so the contract cannot silently decay.
    """
    return lax.linalg.cholesky(G)


@jax.jit
def _screen_impl(A):
    finite = jnp.all(jnp.isfinite(A))
    # Exact equality, NOT a sum of squares: |a|^2 underflows to 0 for
    # finite tiny-magnitude columns (~1e-25 in f32), and the screen
    # must never typed-refuse a valid input the engines can solve.
    zero_col = jnp.any(jnp.all(A == 0, axis=0))
    return jnp.stack([~finite, zero_col])


@jax.jit
def _screen_rhs_impl(b):
    return ~jnp.all(jnp.isfinite(b))


def screen_input(A, b=None) -> "tuple[bool, bool, bool]":
    """One fused device scan: ``(A_nonfinite, zero_column, b_nonfinite)``.

    O(mn) elementwise work in one tiny program per (shape, dtype) —
    negligible against any factorization — and a single scalar
    readback. A zero column means cond(A) is exactly infinite: raising
    typed BEFORE factoring beats letting back-substitution divide by
    zero three engines down the ladder.
    """
    flags = _screen_impl(jnp.asarray(A))
    bad_b = False
    if b is not None:
        bad_b = bool(_screen_rhs_impl(jnp.asarray(b)))
    return bool(flags[0]), bool(flags[1]), bad_b


@jax.jit
def _nonfinite_impl(x):
    return ~jnp.all(jnp.isfinite(x))


def any_nonfinite(*arrays) -> bool:
    """True when any entry of any given array is NaN/Inf — the
    post-factorization breakdown detector (one tiny jitted reduction
    per array shape, one readback per call)."""
    return any(bool(_nonfinite_impl(jnp.asarray(a))) for a in arrays)


@jax.jit
def _diag_cond_impl(d):
    mag = jnp.abs(d)
    return jnp.max(mag) / jnp.min(mag)


def diag_condition_bound(diag) -> float:
    """Cheap LOWER bound on cond_2 from an R diagonal:
    ``max|r_ii| / min|r_ii|`` (the
    :meth:`~dhqr_tpu.models.qr_model.QRFactorization.condition_estimate`
    rule, usable on any engine's R diagonal). Never overestimates; can
    underestimate badly without pivoting (Kahan matrices) — which is
    the right polarity for a guard: if even the lower bound exceeds an
    engine's window, do not route there."""
    return float(_diag_cond_impl(jnp.asarray(diag)))


def estimate_condition(A) -> "float | None":
    """Cheap condition LOWER bound for classification on failure paths:
    one blocked Householder QR of A, then the R-diagonal ratio.

    Costs a full (stable) factorization, so the ladder computes it only
    AFTER something already failed — steady state never pays it. None
    when the estimate itself comes back non-finite (a poisoned input
    that slipped past screening, or an overflowing problem).
    """
    from dhqr_tpu.ops import blocked as _blocked

    A = jnp.asarray(A)
    nb = min(_blocked.DEFAULT_BLOCK_SIZE, A.shape[1])
    _, alpha = _blocked._blocked_qr_impl(A, nb, precision="highest",
                                         pallas=False)
    est = diag_condition_bound(alpha)
    import math

    return est if math.isfinite(est) else None


def residual_ratio(A, b, x) -> float:
    """The one-shot residual probe: this solution's normal-equations
    residual over the LAPACK oracle's own (utils/testing.py — the
    reference's acceptance metric, runtests.jl:49-62). The gate passes
    at ``<= TOLERANCE_FACTOR`` (8.0).

    Cost: one host LAPACK QR solve of (A, b) — the same oracle the
    tune accuracy gate pays per candidate. That is deliberate: the
    probe exists for "no silent garbage" deployments and acceptance
    benchmarks (``guards="full"``, benchmarks/condition_sweep.py), not
    for every hot-path call.
    """
    import numpy as np

    from dhqr_tpu.utils.testing import (
        normal_equations_residual,
        oracle_residual,
    )

    res = normal_equations_residual(A, np.asarray(x), b)
    ref = oracle_residual(np.asarray(A), np.asarray(b))
    if ref > 0:
        return float(res / ref)
    return 0.0 if res == 0 else float("inf")
