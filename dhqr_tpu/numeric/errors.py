"""Typed numerical-failure taxonomy (the round-13 numeric guardrails).

The serving stack already refuses to fail anonymously at the
*infrastructure* layer: every compile/dispatch/queue failure resolves to
one :class:`~dhqr_tpu.serve.errors.ServeError` subclass. This module is
the NUMERICS sibling of that taxonomy — the failure modes that arrive
INSIDE the matrices rather than around them: a NaN-bearing input, a
CholeskyQR breakdown past its conditioning window
(``cond(A) >~ 1/sqrt(eps)`` — ops/cholqr.py), a rank-deficient problem,
a solution that came back finite but missed the 8x-LAPACK residual
criterion.

Every type carries enough state for the caller's next decision: which
``engine`` observed the failure, the cheap ``cond_estimate`` lower
bound when one was computed (None when not), and — for failures raised
after the fallback ladder ran dry — the per-rung ``attempts`` record
(``dhqr_tpu.numeric.ladder.Attempt`` tuples), so a production log line
can say *what was tried* before the typed refusal.

Deliberately a SIBLING of ``ServeError``, not a subclass: a numerical
failure is a property of the *request's data* — retrying, re-routing to
another worker, or backing off cannot fix it, which is exactly the
opposite of the transient-infrastructure contract ``ServeError``
retry/backoff machinery assumes. The async scheduler therefore passes a
``NumericalError`` straight to bisect-isolation (no retry budget spent)
so one bad matrix degrades itself, never its batch neighbors
(``serve/scheduler.py``). Both roots subclass ``RuntimeError``.
"""

from __future__ import annotations


class NumericalError(RuntimeError):
    """Base of every typed numerical failure.

    Attributes:
      engine: the engine family that observed the failure ("cholqr2",
        "tsqr", "householder", ...) or None when the failure precedes
        engine selection (input screening).
      cond_estimate: cheap LOWER bound on cond_2(A) when one was
        computed (``max|r_ii| / min|r_ii|`` — see
        :meth:`dhqr_tpu.QRFactorization.condition_estimate` for the
        caveats); None when no estimate was available. ``float("inf")``
        for structurally singular inputs (a zero column).
      attempts: the fallback ladder's per-rung record (tuple of
        ``dhqr_tpu.numeric.ladder.Attempt``) for failures raised after
        escalation ran dry; ``()`` for pre-ladder failures.
    """

    def __init__(self, message: str, engine: "str | None" = None,
                 cond_estimate: "float | None" = None,
                 attempts: tuple = ()) -> None:
        super().__init__(message)
        self.engine = engine
        self.cond_estimate = (None if cond_estimate is None
                              else float(cond_estimate))
        self.attempts = tuple(attempts)


class NonFiniteInput(NumericalError):
    """The input matrix (or right-hand side) carries NaN/Inf entries.
    Raised by the device-side input screen BEFORE any factorization is
    paid for — no engine, however stable, recovers a poisoned input,
    so the ladder never runs."""


class Breakdown(NumericalError):
    """A factorization broke down: the engine returned non-finite
    factors or a non-finite solution from a finite input — the LOUD
    CholeskyQR failure mode (a non-positive-definite first Gram pass),
    or an injected ``numeric.breakdown`` fault. The condition estimate,
    when present, did NOT implicate conditioning (see
    :class:`IllConditioned` for the case where it did)."""


class IllConditioned(NumericalError):
    """The problem's conditioning exceeds what the (remaining) engines
    can handle: a structurally singular input (zero column —
    ``cond_estimate`` is inf), or a breakdown whose cheap condition
    lower bound already exceeds the failing engine's documented window
    (``dhqr_tpu.ops.cholqr.cholqr_max_cond``). The caller's options are
    data-side: regularize, re-scale, or drop the deficient columns."""


class ResidualGateFailed(NumericalError):
    """Every ladder rung returned a FINITE solution that still missed
    the 8x-LAPACK normal-equations criterion (the one-shot residual
    probe, ``guards="full"``). The worst observed ratio rides in
    ``residual_ratio`` (residual / oracle residual; the gate is 8.0).
    This is the "no silent garbage" guarantee: without the probe these
    cells would have RETURNED."""

    def __init__(self, message: str, engine: "str | None" = None,
                 cond_estimate: "float | None" = None,
                 attempts: tuple = (),
                 residual_ratio: "float | None" = None) -> None:
        super().__init__(message, engine=engine,
                         cond_estimate=cond_estimate, attempts=attempts)
        self.residual_ratio = (None if residual_ratio is None
                               else float(residual_ratio))
