"""Pass 3 (dhqr-audit) — multi-device communication-contract analyzer.

The jaxpr sanitizer (pass 2) traces the sharded engines under a 1-device
mesh, which is exactly where a collective-shaped regression is invisible:
an accidental ``all_gather`` of the trailing matrix, a resharding slipped
in by pjit, a donation that silently stopped aliasing — none of them
change a 1-device program's correctness, all of them burn a TPU session.
This pass forces a P-device CPU topology (P ∈ {2, 4, 8} by default),
abstractly traces every sharded engine, walks every sub-jaxpr with loop
trip counts carried as multipliers, and classifies every collective with
its byte volume computed from avals. Per-engine **comms contracts**
(``comms_contracts.json``, committed) pin what the papers say actually
decides distributed dense-linear-algebra performance — collective choice
and volume (arXiv:2112.09017, arXiv:2112.01075):

* **DHQR301** — a collective family the engine's contract does not allow
  (e.g. any ``all_to_all`` in blocked QR, any collective at all in the
  batched serving dispatch).
* **DHQR302** — traced collective volume exceeds the analytic budget
  (:mod:`dhqr_tpu.analysis.cost_model`) by more than the contract's
  slack factor — or a collective hides inside a ``while`` loop whose
  trip count the walk cannot bound.
* **DHQR303** — an intermediate aval inside a ``shard_map`` body larger
  than the contract's multiple of the per-shard input working set: a
  replicated/gathered blow-up the mesh exists to avoid.
* **DHQR304** — a ``donate_argnums`` entry point whose compiled CPU
  executable reports no input-output aliasing (the donation contract of
  ``ops/blocked._blocked_qr_impl_donate`` / ``_batched_qr_impl_donate``).
* **DHQR305** — a sharded entry point whose jaxpr differs across two
  traces of the same (shape, dtype, P, policy) key: cache-key
  instability that means recompiles in serving.

Tracing is abstract (``make_jaxpr`` — nothing executes); only the two
DHQR304 donation probes compile, on the CPU AOT path at toy shapes. The
preset sweep runs at the smallest P (presets change precision attributes,
not comms structure — topology regressions are caught by the P sweep,
preset regressions by the preset sweep; the matrix of both would only
re-trace identical programs).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

from dhqr_tpu.analysis.findings import Finding
from dhqr_tpu.analysis.jaxpr_pass import _ensure_cpu_backend, sub_jaxprs
from dhqr_tpu.analysis.cost_model import budget_bytes, tiered_budget_bytes

#: The mesh axis name that marks the slow tier of a two-tier pod mesh
#: (parallel/topology.DCN_AXIS — literal copy, stdlib-only tier; pinned
#: by tests/test_topology.py). A collective whose axes include it
#: crosses the data-center network; everything else is ICI-local.
DCN_AXIS = "dcn"

DEFAULT_DEVICE_COUNTS = (2, 4, 8)

# Data-moving collective primitives, classified by family name. axis_index
# is deliberately absent (it names the mesh but moves no words — pass 2's
# DHQR103 covers its axis discipline).
COMMS_COLLECTIVES = (
    "psum", "pmin", "pmax", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pbroadcast",
)

CONTRACTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "comms_contracts.json")

#: This pass's rule-catalogue rows (assembled by analysis/cli.py —
#: round 21 retired the CLI's hand-kept copy).
RULES = (
    ("DHQR301", "collective family outside the engine's comms contract",
     "comms"),
    ("DHQR302", "traced collective volume exceeds the analytic budget "
     "(per-tier cross-DCN column on *_pod contracts)", "comms"),
    ("DHQR303", "shard_map intermediate exceeds the per-shard working "
     "set", "comms"),
    ("DHQR304", "donated entry point compiled without input-output "
     "aliasing", "comms"),
    ("DHQR305", "jaxpr differs across two traces of one cache key",
     "comms"),
)


# ---------------------------------------------------------------------------
# Collective census over a traced program


@dataclasses.dataclass(frozen=True)
class CollectiveUse:
    """One collective eqn: ``launches`` is the static launch count with
    enclosing scan trip counts multiplied in; ``payload_bytes`` the byte
    size of its output avals for ONE launch. ``bounded=False`` marks a
    use under a ``while`` loop — its true launch count is unknowable, so
    it participates in family classification (DHQR301) but is excluded
    from every count/volume aggregate (the DHQR302 opacity finding
    covers it; folding a trips-ignored guess into the totals would make
    the traced-vs-budget number the triage runbook reads silently
    wrong)."""

    prim: str
    launches: int
    payload_bytes: int
    bounded: bool = True
    #: Mesh axis names the collective runs over, as traced from the eqn
    #: params (``axes`` for the reductions, ``axis_name`` for gathers;
    #: empty when the primitive carries neither). Round 20: the tier
    #: split reads this — ``DCN_AXIS in axes`` means the payload
    #: crosses the slow tier.
    axes: "tuple[str, ...]" = ()

    @property
    def volume_bytes(self) -> int:
        return self.launches * self.payload_bytes

    @property
    def crosses_dcn(self) -> bool:
        return DCN_AXIS in self.axes


@dataclasses.dataclass
class BodyStats:
    """One ``shard_map`` body: per-shard input bytes vs the largest
    intermediate aval produced inside it (sub-jaxprs included)."""

    input_bytes: int
    max_aval_bytes: int
    max_aval_desc: str


@dataclasses.dataclass
class CommsStats:
    """Census of one traced entry point."""

    uses: "list[CollectiveUse]" = dataclasses.field(default_factory=list)
    bodies: "list[BodyStats]" = dataclasses.field(default_factory=list)
    opaque_loop_collectives: "list[str]" = dataclasses.field(
        default_factory=list)

    def launches(self) -> "dict[str, int]":
        out: "dict[str, int]" = {}
        for u in self.uses:
            if u.bounded:
                out[u.prim] = out.get(u.prim, 0) + u.launches
        return out

    def volume(self) -> "dict[str, int]":
        out: "dict[str, int]" = {}
        for u in self.uses:
            if u.bounded:
                out[u.prim] = out.get(u.prim, 0) + u.volume_bytes
        return out

    def total_volume_bytes(self) -> int:
        return sum(u.volume_bytes for u in self.uses if u.bounded)

    def dcn_volume_bytes(self) -> int:
        """Traced bytes that cross the DCN tier (round 20): the volume
        of every bounded collective whose axes include
        :data:`DCN_AXIS`. Zero on any 1-D mesh — the split degrades to
        'everything is ICI', which keeps the pre-pod contracts
        byte-identical."""
        return sum(u.volume_bytes for u in self.uses
                   if u.bounded and u.crosses_dcn)

    def families(self) -> "set[str]":
        return {u.prim for u in self.uses}


def _eqn_axes(eqn) -> "tuple[str, ...]":
    """Mesh axis names of one collective eqn: the reductions carry
    ``axes``, the gathers ``axis_name``; either may be a single name or
    a tuple (the flat-on-2-D schedule reduces over both tiers in one
    collective)."""
    val = eqn.params.get("axes")
    if val is None:
        val = eqn.params.get("axis_name")
    if val is None:
        return ()
    if isinstance(val, (tuple, list)):
        return tuple(str(a) for a in val)
    return (str(val),)


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    size = 1
    for d in shape:
        size *= int(d)
    return size * dtype.itemsize


def collect_comms(closed_jaxpr) -> CommsStats:
    """Walk a closed jaxpr (and every sub-jaxpr) collecting the
    collective census. ``scan`` bodies multiply launch counts by the
    scan's trip count; a collective under a ``while`` has no static trip
    count and is recorded as opaque (DHQR302 material). ``shard_map``
    bodies additionally record per-shard input bytes and the largest
    intermediate aval (DHQR303 material)."""
    stats = CommsStats()

    def walk(jaxpr, mult, body, in_while):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars
                            if hasattr(v, "aval"))
            if body is not None and out_bytes > body.max_aval_bytes:
                body.max_aval_bytes = out_bytes
                avals = [str(getattr(v, "aval", "?")) for v in eqn.outvars]
                body.max_aval_desc = f"{prim} -> {', '.join(avals)}"
            if prim in COMMS_COLLECTIVES:
                if in_while:
                    stats.opaque_loop_collectives.append(prim)
                stats.uses.append(CollectiveUse(prim, mult, out_bytes,
                                                bounded=not in_while,
                                                axes=_eqn_axes(eqn)))
            sub_mult = mult
            if prim == "scan":
                sub_mult = mult * int(eqn.params.get("length", 1))
            sub_while = in_while or prim == "while"
            if prim == "shard_map":
                inner = eqn.params.get("jaxpr")
                for j in sub_jaxprs(inner):
                    new_body = BodyStats(
                        input_bytes=sum(_aval_bytes(v.aval)
                                        for v in j.invars),
                        max_aval_bytes=0, max_aval_desc="")
                    stats.bodies.append(new_body)
                    walk(j, sub_mult, new_body, sub_while)
                continue
            for val in eqn.params.values():
                for j in sub_jaxprs(val):
                    walk(j, sub_mult, body, sub_while)

    walk(closed_jaxpr.jaxpr, 1, None, False)
    return stats


def issue_order(closed_jaxpr, nb: int) -> "list[str]":
    """Program-order event stream of one traced engine: ``"psum"`` per
    collective launch and ``"wide_dot"`` per trailing-update GEMM
    (a ``dot_general`` whose output is wider than the panel width
    ``nb`` — panel-interior and narrow lookahead-apply dots are at most
    ``nb`` columns wide by construction). Sub-jaxprs (pjit/shard_map
    bodies, custom-vjp calls) are inlined at their call site, so the
    stream reflects the order XLA receives the operations in — the
    round-23 pipeline property ("panel q+k's broadcast issues before
    panel q's trailing GEMM") is a statement about exactly this
    stream. ``scan`` bodies contribute one iteration's events (the
    walk does not unroll trip counts), so order audits should trace
    shapes the engine unrolls."""
    events: "list[str]" = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim in COMMS_COLLECTIVES:
                events.append("psum" if prim == "psum" else prim)
            elif prim == "dot_general":
                out_aval = getattr(eqn.outvars[0], "aval", None)
                shape = getattr(out_aval, "shape", ())
                if shape and int(shape[-1]) > nb:
                    events.append("wide_dot")
            for val in eqn.params.values():
                for j in sub_jaxprs(val):
                    walk(j)

    walk(closed_jaxpr.jaxpr)
    return events


def overlap_distance(closed_jaxpr, nb: int) -> "int | None":
    """Measured broadcast-ahead distance of a traced blocked-QR
    schedule: for the j-th trailing-update GEMM, count the panel
    broadcasts (psum PAIRS — the factor launches two one-hot psums per
    panel) already issued before it; the panel being trailed is panel
    j, so ``pairs_before - (j + 1)`` is how many panels PAST it were
    already broadcast. The minimum over all identifiable trailing GEMMs
    is the schedule's guaranteed overlap depth: 0 for the classic
    blocking schedule, 1 for the one-panel lookahead, k for the
    round-23 depth-k pipeline. None when the trace exposes no trailing
    GEMM wider than ``nb`` (shape too narrow to audit)."""
    events = issue_order(closed_jaxpr, nb)
    psums = 0
    dist = None
    j = 0
    in_group = False
    for ev in events:
        if ev == "psum":
            psums += 1
            in_group = False
        elif ev == "wide_dot":
            # One trailing update lowers to several consecutive wide
            # dots (W^H C, T @ _, W @ _) with no collective between
            # them — coalesce the run and date the group by its first
            # dot (the earliest the GEMM could issue).
            if not in_group:
                d = psums // 2 - (j + 1)
                dist = d if dist is None else min(dist, d)
                j += 1
            in_group = True
    return dist


# ---------------------------------------------------------------------------
# Contracts


def load_contracts(path: "str | None" = None) -> dict:
    """Load the committed per-engine comms contracts
    (``analysis/comms_contracts.json`` by default)."""
    with open(path or CONTRACTS_PATH, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return data["engines"]


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """The engine-shape key the analytic budget is evaluated at."""

    m: int
    n: int
    nb: int
    P: int
    itemsize: int = 4
    nrhs: int = 1
    #: Round 20: ``(dcn_size, ici_size)`` of the two-tier pod mesh the
    #: engine was traced on, or None for a 1-D mesh. Non-None switches
    #: DHQR302 to the per-tier budgets
    #: (:func:`dhqr_tpu.analysis.cost_model.tiered_budget_bytes`) and
    #: arms the cross-DCN volume column.
    topology: "tuple[int, int] | None" = None


def check_comms(closed_jaxpr, label: str, contract: dict,
                params: EngineParams) -> "list[Finding]":
    """DHQR301/302/303 for one traced engine against its contract."""
    stats = collect_comms(closed_jaxpr)
    findings = []
    allowed = set(contract.get("collectives", ()))
    for prim in sorted(stats.families() - allowed):
        findings.append(Finding(
            "DHQR301", label, 0,
            f"collective family '{prim}' is not in the engine's comms "
            f"contract (allowed: {sorted(allowed) or 'none'}): a new "
            "collective in a pinned-communication engine is a scaling "
            "regression until the contract is re-derived",
            snippet=prim,
        ))
    comms = contract.get("comms")
    slack = float(contract.get("slack", 1.5))
    if params.topology is not None:
        tiered = tiered_budget_bytes(
            contract["model"], params.m, params.n, params.nb, params.P,
            params.itemsize, nrhs=params.nrhs, comms=comms,
            topology=params.topology,
            hierarchical=bool(contract.get("hierarchical", True)))
        budget = tiered["total"]
    else:
        tiered = None
        budget = budget_bytes(contract["model"], params.m, params.n,
                              params.nb, params.P, params.itemsize,
                              nrhs=params.nrhs, comms=comms)
    traced = stats.total_volume_bytes()
    if traced > budget * slack:
        wire = f", wire={comms}" if comms else ""
        findings.append(Finding(
            "DHQR302", label, 0,
            f"traced collective volume {traced} B exceeds the analytic "
            f"budget {budget} B (model '{contract['model']}' at m="
            f"{params.m}, n={params.n}, nb={params.nb}, P={params.P}"
            f"{wire}) x slack {slack}: the engine moves more "
            + ("bytes than its compressed wire format is contracted to "
               "— the claimed volume reduction regressed"
               if comms else
               "words than its communication pattern is contracted to"),
            snippet="volume",
        ))
    if tiered is not None:
        # Round 20: the cross-DCN column is its own contract — the
        # hierarchical schedule exists to shrink THIS number ici_size-
        # fold, so a total-volume check alone would let a schedule
        # regression hide inside the (much larger) ICI share.
        dcn_slack = float(contract.get("dcn_slack", slack))
        dcn_traced = stats.dcn_volume_bytes()
        if dcn_traced > tiered["dcn"] * dcn_slack:
            findings.append(Finding(
                "DHQR302", label, 0,
                f"traced cross-DCN volume {dcn_traced} B exceeds the "
                f"tier budget {tiered['dcn']} B (model "
                f"'{contract['model']}', topology "
                f"{params.topology[0]}x{params.topology[1]}"
                + (f", wire={comms}" if comms else "")
                + f") x slack {dcn_slack}: the hierarchical schedule "
                "stopped isolating the slow tier — the ici_size-fold "
                "cross-DCN reduction this engine is contracted to "
                "deliver regressed",
                snippet="dcn-volume",
            ))
    for prim in sorted(set(stats.opaque_loop_collectives)):
        findings.append(Finding(
            "DHQR302", label, 0,
            f"collective '{prim}' inside a while-loop: its trip count is "
            "not statically boundable, so the volume budget cannot be "
            "checked — use scan/unrolled schedules for collectives",
            snippet=f"while:{prim}",
        ))
    factor = float(contract.get("replicated_factor", 1.75))
    for body in stats.bodies:
        if body.input_bytes and body.max_aval_bytes > factor * body.input_bytes:
            findings.append(Finding(
                "DHQR303", label, 0,
                f"intermediate aval of {body.max_aval_bytes} B inside a "
                f"shard_map body ({body.max_aval_desc}) exceeds "
                f"{factor}x the per-shard input working set "
                f"({body.input_bytes} B): a replicated/gathered blow-up "
                "— the memory the mesh exists to shard",
                snippet=f"aval:{body.max_aval_desc.split(' -> ')[0]}",
            ))
    return findings


# ---------------------------------------------------------------------------
# DHQR304 — donation aliasing on the CPU AOT path

_HLO_ALIAS_PAIR_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(may-alias|must-alias)\)")


def input_output_aliases(compiled) -> "list[tuple[int, str]]":
    """(parameter number, alias kind) pairs the compiled executable
    reports. Prefers a native ``compiled.input_output_aliases`` accessor
    where the jax version ships one; otherwise parses the optimized
    HLO's ``input_output_alias={...}`` entry (present on the CPU AOT
    path for donated-and-used buffers, absent when XLA dropped the
    donation)."""
    native = getattr(compiled, "input_output_aliases", None)
    if native is not None:
        return list(native)
    try:
        txt = compiled.as_text()
    except Exception:
        return []
    idx = txt.find("input_output_alias=")
    if idx < 0:
        return []
    # The alias map lives on the (single) HLO module header line; entries
    # nest braces ({0}: (0, {}, may-alias)), so bound the scan by the
    # line, not by a regex over the braces.
    end = txt.find("\n", idx)
    seg = txt[idx:end if end > 0 else len(txt)]
    return [(int(p), kind) for p, kind in _HLO_ALIAS_PAIR_RE.findall(seg)]


def _donation_entries():
    """The package's donate=True dispatch units, with toy AOT shapes.
    Each entry: (label, jitted fn, args). Both outputs are input-shaped
    by construction, so a healthy compile MUST alias parameter 0."""
    import jax
    import jax.numpy as jnp

    from dhqr_tpu.ops.blocked import (
        _batched_qr_impl_donate,
        _blocked_qr_impl_donate,
    )

    f32 = jnp.float32
    yield ("ops/blocked._blocked_qr_impl_donate", _blocked_qr_impl_donate,
           (jax.ShapeDtypeStruct((16, 8), f32), 4))
    yield ("ops/blocked._batched_qr_impl_donate", _batched_qr_impl_donate,
           (jax.ShapeDtypeStruct((2, 16, 8), f32), 4))


def check_donation(entries=None) -> "list[Finding]":
    """DHQR304: AOT-compile each donated entry point on CPU and require
    the executable to report input-output aliasing. ``entries``
    overrides the package list (tests plant a donation-less twin)."""
    findings = []
    for label, fn, args in (entries if entries is not None
                            else _donation_entries()):
        try:
            compiled = fn.lower(*args).compile()
        except Exception as e:
            findings.append(Finding(
                "DHQR304", label, 0,
                f"donated entry point failed to AOT-compile on CPU: "
                f"{type(e).__name__}: {e}",
                snippet=label,
            ))
            continue
        if not input_output_aliases(compiled):
            findings.append(Finding(
                "DHQR304", label, 0,
                "compiled executable reports no input-output aliasing: "
                "the donate_argnums contract silently stopped holding, so "
                "every dispatch pays a full extra matrix buffer of HBM",
                snippet=label,
            ))
    return findings


# ---------------------------------------------------------------------------
# The engine matrix


def _column_shape(P: int) -> "tuple[int, int, int]":
    """(m, n, nb) for the column-sharded engines at mesh size P: 4 panels
    at P <= 4, n scaled so the panel width still divides the local block
    at P = 8 (constraint: nb | n/P)."""
    n = 16 if P <= 4 else 4 * P
    return 2 * n, n, 4


_ROW_M, _ROW_N, _ROW_NB = 256, 8, 8
_BATCH_B, _BATCH_M, _BATCH_N, _BATCH_NB = 8, 16, 8, 4


def _comms_builders(P: int, preset: str, pol):
    """The trace-construction mechanisms, keyed by the builder names the
    route registry's ``comms_trace`` specs cite (tune/registry.py — THE
    engine-matrix enumeration since round 21; this map owns only HOW to
    build each thunk, never WHICH engines exist). Each builder returns a
    zero-arg thunk producing a closed jaxpr.

    Conventions the builders preserve from the hand matrix they retire:
    the preset-swept engines fold ``policy=preset``; the classic sharded
    engines take precision knobs (``pol.panel`` / ``pol.resolved_apply``)
    instead; the wire rungs (dhqr-wire, round 18) trace with only the
    ``comms`` seam armed — the tightened bf16 slack in the contract is
    what machine-enforces the >= 1.8x traced-volume reduction; the pod
    engines (dhqr-pod, round 20) trace on a (2, P/2) two-tier mesh with
    ``axis_name`` spanning both tiers."""
    import jax
    import jax.numpy as jnp

    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq
    from dhqr_tpu.parallel.sharded_qr import (
        sharded_blocked_qr,
        sharded_householder_qr,
    )
    from dhqr_tpu.parallel.sharded_solve import sharded_solve
    from dhqr_tpu.parallel.sharded_tsqr import row_mesh, sharded_tsqr_lstsq

    m, n, nb = _column_shape(P)
    mesh_box = {}

    # Lazy meshes (like pod() below): constructing a 2+-device mesh on a
    # 1-device host raises, and the atlas coverage pass (DHQR501) needs
    # this map's KEYS anywhere the registry is enumerable — the meshes
    # only have to exist once a thunk actually traces.
    def cmesh():
        if "c" not in mesh_box:
            mesh_box["c"] = column_mesh(P)
        return mesh_box["c"]

    def rmesh():
        if "r" not in mesh_box:
            mesh_box["r"] = row_mesh(P)
        return mesh_box["r"]

    A = jnp.zeros((m, n), jnp.float32)
    H = jnp.zeros((m, n), jnp.float32)
    alpha = jnp.zeros((n,), jnp.float32)
    b = jnp.zeros((m,), jnp.float32)
    At = jnp.zeros((_ROW_M, _ROW_N), jnp.float32)
    bt = jnp.zeros((_ROW_M,), jnp.float32)
    pod_box = {}

    def pod():
        # Lazy (2, P/2) pod mesh — only the min_devices>=4 routes reach
        # it, and only when the registry offered them at this P.
        if "mesh" not in pod_box:
            from dhqr_tpu.parallel.mesh import pod_mesh

            pod_box["mesh"], pod_box["axes"] = pod_mesh(
                P, topo=f"2x{P // 2}")
        return pod_box["mesh"], pod_box["axes"]

    def jx(fn, *args):
        return lambda: jax.make_jaxpr(fn)(*args)

    def blocked(layout=None, lookahead=False, agg_panels=None,
                comms=None, pod_mesh=False, overlap_depth=None):
        kw = {}
        if layout:
            kw["layout"] = layout
        if lookahead:
            kw["lookahead"] = True
        if agg_panels:
            kw["agg_panels"] = agg_panels
        if overlap_depth:
            # Round 23 (dhqr-pipeline): the engine clamps the depth to
            # num_panels - 1 at the trace shape, so the pipeline4 route
            # traces the deepest ring the shape admits — exactly what a
            # caller passing the same depth would run.
            kw["overlap_depth"] = overlap_depth
        if pod_mesh:
            pmesh, taxes = pod()
            return jx(lambda A: sharded_blocked_qr(
                A, pmesh, block_size=nb, axis_name=taxes, **kw), A)
        if comms:
            return jx(lambda A: sharded_blocked_qr(
                A, cmesh(), block_size=nb, comms=comms, **kw), A)
        return jx(lambda A: sharded_blocked_qr(
            A, cmesh(), block_size=nb, policy=preset, **kw), A)

    def unblocked(comms=None, pod_mesh=False):
        if pod_mesh:
            pmesh, taxes = pod()
            return jx(lambda A: sharded_householder_qr(
                A, pmesh, axis_name=taxes), A)
        if comms:
            return jx(lambda A: sharded_householder_qr(
                A, cmesh(), comms=comms), A)
        return jx(lambda A: sharded_householder_qr(
            A, cmesh(), precision=pol.panel), A)

    def solve(comms=None, pod_mesh=False):
        if pod_mesh:
            pmesh, taxes = pod()
            kw = {"comms": comms} if comms else {}
            return jx(lambda H, a, b: sharded_solve(
                H, a, b, pmesh, block_size=nb, axis_name=taxes, **kw),
                H, alpha, b)
        if comms:
            return jx(lambda H, a, b: sharded_solve(
                H, a, b, cmesh(), block_size=nb, comms=comms), H, alpha, b)
        return jx(lambda H, a, b: sharded_solve(
            H, a, b, cmesh(), block_size=nb,
            precision=pol.resolved_apply()), H, alpha, b)

    def tsqr(comms=None, pod_mesh=False):
        if pod_mesh:
            pmesh, taxes = pod()
            kw = {"comms": comms} if comms else {}
            return jx(lambda A, b: sharded_tsqr_lstsq(
                A, b, pmesh, block_size=_ROW_NB, axis_name=taxes, **kw),
                At, bt)
        if comms:
            return jx(lambda A, b: sharded_tsqr_lstsq(
                A, b, rmesh(), block_size=_ROW_NB, comms=comms), At, bt)
        return jx(lambda A, b: sharded_tsqr_lstsq(
            A, b, rmesh(), block_size=_ROW_NB, precision=pol.panel), At, bt)

    def cholqr(comms=None, pod_mesh=False):
        if pod_mesh:
            pmesh, taxes = pod()
            return jx(lambda A, b: sharded_cholqr_lstsq(
                A, b, pmesh, axis_name=taxes), At, bt)
        if comms:
            return jx(lambda A, b: sharded_cholqr_lstsq(
                A, b, rmesh(), comms=comms), At, bt)
        return jx(lambda A, b: sharded_cholqr_lstsq(
            A, b, rmesh(), precision=pol.panel), At, bt)

    def bucket_sharded(policy=None):
        # The serving dispatch, traced with its batch axis sharded over
        # the mesh: the contract is ZERO collectives — any psum/gather
        # means the vmapped engine stopped being embarrassingly parallel
        # over requests (and under a wire policy: compression must never
        # introduce one).
        from jax.sharding import NamedSharding, PartitionSpec
        from dhqr_tpu.parallel.mesh import DEFAULT_AXIS
        from dhqr_tpu.serve.engine import bucket_program

        As = jnp.zeros((_BATCH_B, _BATCH_M, _BATCH_N), jnp.float32)
        bs = jnp.zeros((_BATCH_B, _BATCH_M), jnp.float32)
        sh = NamedSharding(cmesh(), PartitionSpec(DEFAULT_AXIS))
        fn = bucket_program("lstsq", block_size=_BATCH_NB,
                            policy=policy if policy is not None else preset)
        return jx(jax.jit(fn, in_shardings=(sh, sh)), As, bs)

    builders = {
        "blocked": blocked,
        "unblocked": unblocked,
        "solve": solve,
        "tsqr": tsqr,
        "cholqr": cholqr,
        "bucket_sharded": bucket_sharded,
    }

    def params_for(shape: str, pod_topology: bool) -> EngineParams:
        topo = (2, P // 2) if pod_topology else None
        if shape == "row":
            return EngineParams(_ROW_M, _ROW_N, _ROW_NB, P, topology=topo)
        if shape == "batch":
            return EngineParams(_BATCH_M, _BATCH_N, _BATCH_NB, P)
        return EngineParams(m, n, nb, P, topology=topo)

    return builders, params_for


def _unexpressible_comms(route_name: str, builder: str):
    """Thunk for a registry comms spec citing a builder this pass has no
    mechanism for: raising (-> DHQR104) makes the drift a finding, not a
    silent drop."""
    def thunk():
        raise RuntimeError(
            f"route {route_name!r} cites comms builder {builder!r} which "
            "analysis/comms_pass implements no mechanism for: implement "
            "the builder or fix the registry spec (tune/registry.py)")
    return thunk


def _engine_specs(P: int, preset: str, pol, sweep_presets: bool):
    """(engine, label, thunk, params) per traced entry point at mesh
    size P — ``engine`` is the comms-contract key the census is priced
    against. ``sweep_presets=False`` restricts to the preset-insensitive
    census (presets change precision attributes, not comms structure —
    see the module docstring); the policy-parameterized engines are
    yielded only when sweeping.

    Round 21 (dhqr-atlas): the enumeration is the route registry
    (tune/registry.comms_routes) — this function only resolves each
    route's declarative ``comms_trace`` spec against the builder
    mechanisms above, so a new sharded engine registers once and is
    audited here automatically (DHQR501/502 fail lint if it is not)."""
    from dhqr_tpu.tune.registry import comms_routes

    builders, params_for = _comms_builders(P, preset, pol)
    tag = f"[P={P},{preset}]" if sweep_presets else f"[P={P}]"
    for route in comms_routes(P, sweep=sweep_presets):
        spec = dict(route.comms_trace)
        spec.pop("sweep", None)
        label = f"comms::{spec.pop('label', route.name)}{tag}"
        shape = spec.pop("shape", "col")
        pod_topology = bool(spec.pop("pod", False))
        name = spec.pop("builder")
        build = builders.get(name)
        if build is None:
            yield (route.contract, label,
                   _unexpressible_comms(route.name, name),
                   params_for(shape, pod_topology))
            continue
        if pod_topology:
            spec["pod_mesh"] = True
        yield (route.contract, label, build(**spec),
               params_for(shape, pod_topology))


def trace_engine(engine: str, P: int, preset: str = "accurate"):
    """Trace one engine of the matrix and return its
    ``(CommsStats, EngineParams)`` — the golden-assertion surface
    (tests/test_comms.py)."""
    _ensure_cpu_backend()
    from dhqr_tpu.precision import PRECISION_POLICIES

    pol = PRECISION_POLICIES[preset]
    for sweep in (True, False):
        for name, _label, thunk, params in _engine_specs(
                P, preset, pol, sweep_presets=sweep):
            if name == engine:
                return collect_comms(thunk()), params
    raise KeyError(f"unknown comms engine {engine!r}")


class InsufficientDevices(RuntimeError):
    """The forced CPU topology did not materialize (backend already
    initialized with fewer devices) — rerun in a subprocess."""


def run_comms_pass(presets=None, device_counts=DEFAULT_DEVICE_COUNTS,
                   contracts_path=None, stability: bool = True,
                   donation: bool = True) -> "list[Finding]":
    """Run the full comms audit: the engine matrix at every mesh size in
    ``device_counts`` (preset sweep at the smallest), DHQR304 donation
    probes, and DHQR305 double-trace stability at the smallest P.

    Requires ``max(device_counts)`` CPU devices — raise
    :class:`InsufficientDevices` otherwise (the CLI falls back to a
    subprocess with ``--xla_force_host_platform_device_count`` forced;
    see ``run_comms_pass_auto``).
    """
    _ensure_cpu_backend()
    import jax

    from dhqr_tpu.precision import PRECISION_POLICIES

    device_counts = tuple(sorted(set(int(p) for p in device_counts)))
    if not device_counts:
        raise ValueError("device_counts must name at least one mesh size")
    navail = len(jax.devices())
    if navail < max(device_counts):
        raise InsufficientDevices(
            f"comms pass needs {max(device_counts)} CPU devices, have "
            f"{navail}: the backend initialized before the topology could "
            "be forced (XLA_FLAGS is read once, at first backend init)"
        )
    names = list(presets) if presets is not None \
        else list(PRECISION_POLICIES)
    contracts = load_contracts(contracts_path)
    findings: "list[Finding]" = []
    if donation:
        findings.extend(check_donation())

    def run_specs(P, preset, pol, sweep):
        for engine, label, thunk, params in _engine_specs(
                P, preset, pol, sweep_presets=sweep):
            contract = contracts.get(engine)
            if contract is None:
                findings.append(Finding(
                    "DHQR301", label, 0,
                    f"engine '{engine}' has no committed comms contract "
                    "(analysis/comms_contracts.json): every sharded "
                    "engine must pin its communication pattern",
                    snippet=engine,
                ))
                continue
            try:
                closed = thunk()
            except Exception as e:  # a trace failure IS the regression
                findings.append(Finding(
                    "DHQR104", label, 0,
                    f"sharded entry point failed to trace: "
                    f"{type(e).__name__}: {e}",
                ))
                continue
            findings.extend(check_comms(closed, label, contract, params))
            yield engine, label, thunk, closed

    def check_stability(label, thunk, closed):
        # The re-trace must not be able to crash the gate: a second
        # trace that RAISES is exactly the nondeterministic-builder bug
        # DHQR305 hunts, so it becomes a finding like any other.
        try:
            second = thunk()
        except Exception as e:
            findings.append(Finding(
                "DHQR104", label, 0,
                f"sharded entry point failed to RE-trace for the "
                f"stability check: {type(e).__name__}: {e}",
            ))
            return
        if str(second.jaxpr) != str(closed.jaxpr):
            findings.append(_instability(label))

    p_sweep = device_counts[0]
    for P in device_counts:
        # Preset-parameterized engines: full preset sweep at the smallest
        # mesh, canonical preset at the larger ones.
        sweep_names = names if P == p_sweep else names[:1]
        for preset in sweep_names:
            pol = PRECISION_POLICIES[preset]
            for engine, label, thunk, closed in run_specs(
                    P, preset, pol, sweep=True):
                if stability and P == p_sweep and preset == names[0]:
                    check_stability(label, thunk, closed)
        pol = PRECISION_POLICIES[names[0]]
        for engine, label, thunk, closed in run_specs(
                P, names[0], pol, sweep=False):
            if stability and P == p_sweep:
                check_stability(label, thunk, closed)
    return findings


def _instability(label: str) -> Finding:
    return Finding(
        "DHQR305", label, 0,
        "two traces of the same (shape, dtype, P, policy) key produced "
        "different jaxprs: cache-key instability — in serving this is a "
        "recompile per request",
        snippet="jaxpr-instability",
    )


def run_comms_pass_auto(presets=None, device_counts=DEFAULT_DEVICE_COUNTS,
                        contracts_path=None) -> "list[Finding]":
    """In-process when the CPU topology is wide enough, else re-run the
    pass in a subprocess with the topology forced via XLA_FLAGS (the
    ``jax.config`` route cannot widen an already-initialized backend on
    this jax) and parse its JSON findings."""
    try:
        return run_comms_pass(presets=presets, device_counts=device_counts,
                              contracts_path=contracts_path)
    except InsufficientDevices:
        return _run_comms_subprocess(presets, device_counts, contracts_path)


def _run_comms_subprocess(presets, device_counts, contracts_path):
    import subprocess
    import sys

    import dhqr_tpu

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DHQR_LINT_KEEP_PLATFORM", None)
    flags = env.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count="
        f"{max(device_counts)}"
    ).strip()
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        dhqr_tpu.__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "dhqr_tpu.analysis", "comms", "--json"]
    for p in (presets or ()):
        cmd += ["--preset", p]
    for d in device_counts:
        cmd += ["--devices", str(d)]
    if contracts_path:
        cmd += ["--contracts", contracts_path]
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          timeout=600)
    if proc.returncode not in (0, 1):
        tail = (proc.stderr or "").strip().splitlines()[-5:]
        return [Finding(
            "DHQR104", "comms::subprocess", 0,
            f"comms-pass subprocess failed (exit {proc.returncode}): "
            + " | ".join(tail),
        )]
    data = json.loads(proc.stdout)
    keys = {f.name for f in dataclasses.fields(Finding)}
    return [Finding(**{k: v for k, v in entry.items() if k in keys})
            for entry in data["findings"]]
