"""Finding records, inline suppressions, and the committed baseline.

One Finding type serves both passes (AST rules and the jaxpr sanitizer)
so the CLI, the baseline file and the tier-1 self-scan all speak the same
shape. Fingerprints are line-number-independent (rule + path + source
snippet) so a baseline survives unrelated edits above a finding.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re

# ``# dhqr: ignore[DHQR002] reason`` — one or more rule IDs, comma
# separated; the reason is free text, required by policy (docs/DESIGN.md
# "Static invariants"). The parser still tolerates its absence — the
# suppression takes effect so the author's intent is honored — but a
# reason-less directive is no longer silent: it reports as a warn-only
# DHQR000 finding (:func:`missing_reason_findings`, round 21), so the
# policy is machine-checked instead of review-checked.
_SUPPRESS_RE = re.compile(
    r"#\s*dhqr:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*(.*?)\s*$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location (or traced entry point).

    ``path`` is the display path (posix, repo-relative where possible);
    ``line`` is 1-based (0 for whole-file / traced-program findings);
    ``snippet`` is the stripped source line, used for the baseline
    fingerprint; ``suppressed``/``reason`` record an inline
    ``# dhqr: ignore[...]`` that matched this finding. ``severity`` is
    ``"error"`` (gates the lint exit code) or ``"warning"`` (reported,
    baseline-able, never red on its own — the missing-reason DHQR000).
    """

    rule: str
    path: str
    line: int
    message: str
    snippet: str = ""
    suppressed: bool = False
    reason: str = ""
    severity: str = "error"

    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.snippet or self.message}"
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]

    def render(self) -> str:
        sup = f"  [suppressed: {self.reason or 'no reason given'}]" \
            if self.suppressed else ""
        sev = " (warning)" if self.severity == "warning" else ""
        return (f"{self.path}:{self.line}: {self.rule}{sev} "
                f"{self.message}{sup}")

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "snippet": self.snippet,
            "suppressed": self.suppressed,
            "reason": self.reason,
            "severity": self.severity,
            "fingerprint": self.fingerprint(),
        }


def parse_suppressions(lines: "list[str]") -> "dict[int, tuple[set, str]]":
    """Map 1-based line number -> (rule ids, reason) for every inline
    ``# dhqr: ignore[...]`` directive in ``lines``."""
    out = {}
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
            out[i] = (rules, m.group(2).strip())
    return out


def missing_reason_findings(lines: "list[str]",
                            path: str) -> "list[Finding]":
    """Warn-only DHQR000 for every ``# dhqr: ignore[...]`` directive
    whose reason parsed to the empty string (round 21, satellite of
    dhqr-atlas): the suppression still works, but the DESIGN.md
    "reason required" policy is now machine-checked. Callers
    (ast_rules.scan_source) run this AFTER :func:`apply_suppressions` —
    a reason-less ``ignore[DHQR000]`` must not suppress its own
    missing-reason report."""
    out = []
    for line, (rules, reason) in parse_suppressions(lines).items():
        if reason:
            continue
        out.append(Finding(
            "DHQR000", path, line,
            f"suppression directive for {', '.join(sorted(rules))} "
            "carries no reason: the suppression still applies, but "
            "docs/DESIGN.md requires every inline ignore to say why — "
            "append the justification after the bracket",
            snippet=lines[line - 1].strip(),
            severity="warning",
        ))
    return out


def apply_suppressions(findings, suppressions) -> "list[Finding]":
    """Mark findings suppressed when the directive sits on the finding's
    line or the line immediately above (multi-line calls report the call's
    first line, so a directive above the statement also matches)."""
    out = []
    for f in findings:
        sup = None
        for ln in (f.line, f.line - 1):
            entry = suppressions.get(ln)
            if entry and f.rule in entry[0]:
                sup = entry
                break
        if sup is not None:
            f = dataclasses.replace(f, suppressed=True, reason=sup[1])
        out.append(f)
    return out


def load_baseline(path) -> "dict[str, int]":
    """Accepted fingerprints -> occurrence count. A multiset, not a set:
    two identical violation lines in one file share a fingerprint, and
    baselining one must not silently accept a later second one."""
    import collections

    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return collections.Counter(
        entry["fingerprint"] for entry in data.get("findings", []))


def prune_baseline(path, findings) -> "tuple[int, int]":
    """Rewrite the baseline at ``path`` keeping only entries whose
    fingerprint still matches a current finding — multiset-aware, like
    :func:`load_baseline`: N accepted occurrences survive only while N
    current findings still match. Returns ``(kept, removed)`` so the CLI
    can report how many stale entries were dropped. The file's own
    structure (comment, per-entry rule/path/snippet context) is
    preserved for the surviving entries."""
    import collections

    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    current = collections.Counter(
        f.fingerprint() for f in findings if not f.suppressed)
    kept, removed = [], 0
    for entry in data.get("findings", []):
        fp = entry.get("fingerprint")
        if current.get(fp, 0) > 0:
            current[fp] -= 1
            kept.append(entry)
        else:
            removed += 1
    if removed:
        data["findings"] = kept
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=True)
            fh.write("\n")
    return len(kept), removed


def write_baseline(path, findings) -> None:
    """Write the unsuppressed findings as the new accepted baseline."""
    payload = {
        "comment": (
            "dhqr-lint baseline: accepted pre-existing findings, keyed by "
            "line-independent fingerprint. Regenerate with "
            "`python -m dhqr_tpu.analysis check ... --write-baseline "
            "<file>` (docs/OPERATIONS.md). The shipped baseline is EMPTY "
            "by policy: new findings are fixed or inline-suppressed with "
            "a reason, not baselined."
        ),
        "findings": [
            {
                "fingerprint": f.fingerprint(),
                "rule": f.rule,
                "path": f.path,
                "snippet": f.snippet or f.message,
            }
            for f in findings
            if not f.suppressed
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
