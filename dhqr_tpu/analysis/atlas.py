"""Pass 6 (dhqr-atlas, round 21) — DHQR5xx cross-subsystem drift audit.

The route registry (``tune/registry.py``) is the ONE enumeration of the
execution-route space; the jaxpr pass, the comms audit, the tune grid,
the serve cache keys and the bench stages all iterate it. This pass
proves the consumers have not drifted from the registry — the failure
class PRs 12-16 kept re-opening by hand-widening four subsystems per
route (the unaudited-route / unpriced-collective hazard of
arXiv 2112.09017's per-route cost accounting, and the silent-recompile
hazard XLA serving tiers pay per under-keyed cache entry):

* DHQR501 — route coverage: every registered route reachable by the
  audit ladder, every trace spec resolvable against a pass's builder
  map, every traced label registered (two-way, via ``traced_labels``).
* DHQR502 — contract pricing: registry ``contract`` fields and
  ``comms_contracts.json`` rows are bijective; every row names a known
  cost model, known collectives, and a wire rung some claiming route
  actually runs. A dead row is a finding, not tidiness.
* DHQR503 — under-keyed caches: mint the serve CacheKey for every
  registered probe cell; any two cells colliding on one key must trace
  to the IDENTICAL program (a collision with distinct jaxprs is a
  recompile per dispatch in steady-state serving). The tune-side twin:
  distinct grid candidates must not share a ``describe()`` tag (the
  plan-DB key). The fleet-side twin (round 22): the disk store's
  canonical key spelling (``serve.store.canonical_key``) must stay
  injective over distinct CacheKeys, or a warm start deserializes the
  wrong executable.
* DHQR504 — donation audit: ``donated`` routes and the DHQR304
  AOT-aliasing probes (``comms_pass._donation_entries``) are bijective.
* DHQR505 — grid drift: every ``candidate_plans`` emission at a probe
  grid maps onto a registered route (``registry.grid_route_for``), and
  every bench stage names a registered route of the right kind.

Every check takes its enumerations as injectable arguments (tests seed
drifts without touching the committed registry) and returns plain
:class:`Finding` records, so the baseline/suppression machinery and the
CLI gate treat atlas findings exactly like AST ones. The committed tree
holds ZERO findings — the gate ships with an empty baseline by policy.
"""

from __future__ import annotations

from dhqr_tpu.analysis.findings import Finding
from dhqr_tpu.tune import registry

RULES = (
    ("DHQR501",
     "registered route invisible to an analysis pass, or a traced "
     "label with no registered route", "atlas"),
    ("DHQR502",
     "comms contract row and registry route sets are not bijective, "
     "or a contract row is unpriceable", "atlas"),
    ("DHQR503",
     "under-keyed cache: distinct route cells collide on one cache "
     "key with different traced programs", "atlas"),
    ("DHQR504",
     "donated routes and DHQR304 donation probes have drifted apart",
     "atlas"),
    ("DHQR505",
     "tune-grid candidate or bench stage escapes the route registry",
     "atlas"),
)

#: (kind, m, n, nproc, topology, platform) probe grids DHQR503/505 run
#: ``candidate_plans`` over — chosen to arm every emission rule: the nb
#: ladder + panel variants (tall n>=64 single-host), the mesh levers +
#: flat wire rungs (nproc=4), the dcn rungs (two-tier topology), the
#: alt engines (aspect >= TSQR_MIN_ASPECT), and all three serve kinds.
GRID_PROBES = (
    ("lstsq", 4096, 64, 1, None, "tpu"),
    ("lstsq", 2048, 64, 4, None, "cpu"),
    ("lstsq", 8192, 64, 4, (2, 2), "tpu"),
    ("qr", 256, 128, 1, None, "tpu"),
    ("qr", 512, 128, 4, None, "cpu"),
    ("serve_lstsq", 64, 16, 1, None, "cpu"),
    ("serve_qr", 64, 16, 1, None, "cpu"),
    ("serve_sketch", 512, 8, 1, None, "cpu"),
)

#: Request shapes DHQR503 mints serve keys at, per program kind. The
#: lstsq/qr probe must be large enough that the loop and recursive
#: panel interiors trace DIFFERENT programs at the bucketed shape (they
#: are identical below nb=64 buckets — verified empirically), so a
#: dropped ``panel_impl`` key field produces a collision this pass can
#: actually convict.
SERVE_PROBE_SHAPES = {"lstsq": (256, 128), "qr": (256, 128),
                      "sketch": (512, 8)}


def _f(rule, path, message, snippet):
    return Finding(rule, path, 0, message, snippet=snippet)


# ---------------------------------------------------------------------------
# DHQR501 — registry structure + route coverage


def registry_findings() -> "list[Finding]":
    """The registry's own structural invariants, as gate findings."""
    return [_f("DHQR501", "atlas::registry", problem, snippet=problem)
            for problem in registry.self_check()]


def expected_jaxpr_labels(routes=None,
                          devices: int = 8) -> "set[str]":
    """Every trace label the jaxpr pass owes the registry across the
    full preset sweep, at the audit ladder's widest mesh."""
    from dhqr_tpu.precision import PRECISION_POLICIES

    out = set()
    for r in (registry.routes() if routes is None else routes):
        for spec in r.jaxpr:
            for preset in PRECISION_POLICIES:
                if r.presets == "accurate" and preset != "accurate":
                    continue
                if r.schedule == "pod" and devices < r.min_devices:
                    continue
                out.add(spec["label"].format(preset=preset))
    return out


def check_route_coverage(routes=None, jaxpr_builders=None,
                         comms_builders=None,
                         traced_labels=None) -> "list[Finding]":
    """DHQR501. Static coverage: every jaxpr/comms trace spec must name
    a builder its pass can resolve (an unknown name would trace as a
    DHQR104/DHQR305 unexpressible-route finding at runtime — this
    catches it without tracing), and every route must sit inside the
    audit ladder's reach. ``traced_labels`` (when given — the CLI
    passes the labels the jaxpr pass actually produced) is checked
    two-way against :func:`expected_jaxpr_labels`: a label traced but
    unregistered is exactly the hand-enumerated drift the registry
    retired."""
    findings = []
    routes = registry.routes() if routes is None else routes
    if jaxpr_builders is None or comms_builders is None:
        from dhqr_tpu.analysis import comms_pass, jaxpr_pass
        from dhqr_tpu.precision import PRECISION_POLICIES

        jaxpr_pass._ensure_cpu_backend()
        pol = PRECISION_POLICIES["accurate"]
        if jaxpr_builders is None:
            jaxpr_builders = set(jaxpr_pass._builders("accurate", pol))
        if comms_builders is None:
            comms_builders = set(
                comms_pass._comms_builders(2, "accurate", pol)[0])
    from dhqr_tpu.analysis.comms_pass import DEFAULT_DEVICE_COUNTS

    ladder_max = max(DEFAULT_DEVICE_COUNTS)
    for r in routes:
        for spec in r.jaxpr:
            if spec["builder"] not in jaxpr_builders:
                findings.append(_f(
                    "DHQR501", "atlas::coverage",
                    f"route {r.name!r} jaxpr spec names builder "
                    f"{spec['builder']!r} the jaxpr pass has no "
                    "mechanism for — it would trace as an "
                    "unexpressible-route DHQR104, so register the "
                    "builder or drop the spec",
                    snippet=f"{r.name}:jaxpr:{spec['builder']}"))
        if r.comms_trace is not None \
                and r.comms_trace["builder"] not in comms_builders:
            findings.append(_f(
                "DHQR501", "atlas::coverage",
                f"route {r.name!r} comms_trace names builder "
                f"{r.comms_trace['builder']!r} the comms audit has no "
                "mechanism for",
                snippet=f"{r.name}:comms:{r.comms_trace['builder']}"))
        if r.min_devices > ladder_max:
            findings.append(_f(
                "DHQR501", "atlas::coverage",
                f"route {r.name!r} needs {r.min_devices} devices but "
                f"the audit ladder tops out at {ladder_max} "
                f"(comms_pass.DEFAULT_DEVICE_COUNTS) — the route would "
                "never be traced by any pass",
                snippet=f"{r.name}:min_devices"))
    if traced_labels is not None:
        traced = set(traced_labels)
        expected = expected_jaxpr_labels(routes)
        for lab in sorted(traced - expected):
            findings.append(_f(
                "DHQR501", "atlas::coverage",
                f"jaxpr pass traced label {lab!r} that no registered "
                "route declares — a hand-enumerated route outside the "
                "registry; register it (tune/registry.py) so the grid, "
                "the serve keys and the contracts see it too",
                snippet=f"unregistered:{lab}"))
        for lab in sorted(expected - traced):
            findings.append(_f(
                "DHQR501", "atlas::coverage",
                f"registered trace label {lab!r} was never produced by "
                "the jaxpr pass — the route is registered but "
                "unaudited",
                snippet=f"untraced:{lab}"))
    return findings


# ---------------------------------------------------------------------------
# DHQR502 — contract pricing bijection


def check_contract_pricing(routes=None,
                           contracts=None) -> "list[Finding]":
    """DHQR502. The comms audit prices what it traces against
    ``comms_contracts.json`` — so a registry route naming a missing row
    ships an unpriced collective, and a row no route claims is a dead
    contract (its budget silently stopped binding anything). Rows must
    also be self-consistent: a known cost model, known collective
    primitives, and a wire rung matching some claiming route."""
    from dhqr_tpu.analysis.comms_pass import (COMMS_COLLECTIVES,
                                              load_contracts)
    from dhqr_tpu.analysis.cost_model import MODELS
    from dhqr_tpu.precision import COMMS_MODES

    findings = []
    routes = registry.routes() if routes is None else routes
    contracts = load_contracts() if contracts is None else contracts
    claims = {}
    for r in routes:
        if not r.contract:
            continue
        claims.setdefault(r.contract, []).append(r)
        if r.contract not in contracts:
            findings.append(_f(
                "DHQR502", "atlas::contracts",
                f"route {r.name!r} prices its census against contract "
                f"{r.contract!r}, which is not a row of "
                "comms_contracts.json — the route's collectives ship "
                "unpriced",
                snippet=f"missing-row:{r.contract}"))
    for key, row in sorted(contracts.items()):
        if key not in claims:
            findings.append(_f(
                "DHQR502", "atlas::contracts",
                f"contract row {key!r} is claimed by no registered "
                "route — a dead budget; delete the row or register the "
                "route that should be held to it",
                snippet=f"dead-row:{key}"))
            continue
        model = row.get("model")
        if model not in MODELS:
            findings.append(_f(
                "DHQR502", "atlas::contracts",
                f"contract row {key!r} names unknown cost model "
                f"{model!r} (have {sorted(MODELS)})",
                snippet=f"model:{key}"))
        unknown = sorted(set(row.get("collectives", ()))
                         - set(COMMS_COLLECTIVES))
        if unknown:
            findings.append(_f(
                "DHQR502", "atlas::contracts",
                f"contract row {key!r} allows unknown collective "
                f"primitives {unknown} — the census would never match "
                "them, so the allowance is dead",
                snippet=f"collectives:{key}"))
        rung = row.get("comms")
        if rung is not None:
            if rung not in COMMS_MODES:
                findings.append(_f(
                    "DHQR502", "atlas::contracts",
                    f"contract row {key!r} names unknown wire rung "
                    f"{rung!r} (have {COMMS_MODES})",
                    snippet=f"rung:{key}"))
            elif not any(r.comms == rung for r in claims[key]):
                findings.append(_f(
                    "DHQR502", "atlas::contracts",
                    f"contract row {key!r} prices wire rung {rung!r} "
                    "but no claiming route runs that rung — the "
                    "compressed budget binds nothing",
                    snippet=f"rung-unclaimed:{key}"))
    return findings


# ---------------------------------------------------------------------------
# DHQR503 — under-keyed caches / recompile hazard


def check_cache_keys(routes=None, key_fn=None,
                     trace: bool = True) -> "list[Finding]":
    """DHQR503. Serve side: mint the CacheKey for every registered
    probe cell through the ONE key mint (``serve.engine._plan_key``, or
    the injected ``key_fn`` twin a test plants); cells that collide on
    a key are then traced (``trace=True``) and convicted only if their
    programs differ — colliding-by-design cells (the wire-policy twin)
    stay green because their programs are identical. Tune side: grid
    candidates keyed identically in the plan DB (``describe()``) must
    BE identical."""
    from dhqr_tpu.serve.engine import (_plan_key, _resolve_serve_cfg,
                                       bucket_program)
    from dhqr_tpu.utils.config import ServeConfig

    findings = []
    key_fn = _plan_key if key_fn is None else key_fn
    scfg = ServeConfig()
    cells = []
    route_list = (registry.serve_routes() if routes is None
                  else [r for r in routes if r.serve is not None])
    for r in route_list:
        kind = r.serve["kind"]
        m, n = SERVE_PROBE_SHAPES.get(kind, (256, 128))
        for overrides in r.serve["cells"]:
            try:
                cfg, _pol = _resolve_serve_cfg(None, dict(overrides))
                key, _bucket = key_fn(kind, 2, m, n, "float32", cfg,
                                      scfg)
            except Exception as e:
                findings.append(_f(
                    "DHQR503", "atlas::serve-keys",
                    f"route {r.name!r} serve cell {overrides!r} failed "
                    f"to mint a cache key: {type(e).__name__}: {e}",
                    snippet=f"mint:{r.name}"))
                continue
            cells.append((r.name, kind, overrides, key))
    groups = {}
    for name, kind, overrides, key in cells:
        groups.setdefault(key, []).append((name, kind, overrides))
    for key, members in sorted(groups.items(),
                               key=lambda kv: repr(kv[0])):
        if len(members) < 2 or not trace:
            continue
        import jax
        import jax.numpy as jnp

        programs = {}
        for name, kind, overrides in members:
            fn = bucket_program(kind, **dict(overrides))
            A = jnp.zeros((key.batch, key.m, key.n), jnp.float32)
            args = (A,) if kind == "qr" \
                else (A, jnp.zeros((key.batch, key.m), jnp.float32))
            programs[name] = str(jax.make_jaxpr(fn)(*args))
        if len(set(programs.values())) > 1:
            names = sorted(n for n, _, _ in members)
            findings.append(_f(
                "DHQR503", "atlas::serve-keys",
                f"cache key collision with distinct programs: route "
                f"cells {names} share one serve CacheKey but trace to "
                f"{len(set(programs.values()))} different jaxprs at "
                f"bucket ({key.batch}, {key.m}, {key.n}) — the serve "
                "cache would recompile on every alternation; add the "
                "distinguishing config field to CacheKey/_plan_key",
                snippet="servekey:" + ",".join(names)))
    # Fleet side (round 22): the disk executable store addresses blobs
    # by the CANONICAL string spelling of the CacheKey
    # (serve.store.canonical_key). The spelling must stay INJECTIVE
    # over distinct keys: two different in-memory CacheKeys flattening
    # to one canonical string would hand process B the wrong
    # executable on a warm start — silently, since the header's
    # key-match check would pass.
    from dhqr_tpu.serve.store import canonical_key

    canon: dict = {}
    for name, kind, overrides, key in cells:
        try:
            spelled = canonical_key(key)
        except Exception as e:
            findings.append(_f(
                "DHQR503", "atlas::fleet-keys",
                f"route {r.name!r} serve key {key!r} failed the "
                f"canonical spelling: {type(e).__name__}: {e} — the "
                "disk store cannot address this cell's executable",
                snippet=f"canon-mint:{name}"))
            continue
        prior = canon.setdefault(spelled, (name, key))
        if prior[1] != key:
            findings.append(_f(
                "DHQR503", "atlas::fleet-keys",
                f"canonical key collision: distinct CacheKeys for "
                f"route cells {sorted([prior[0], name])} both spell "
                f"{spelled!r} — a warm start would deserialize the "
                "wrong executable; add the distinguishing field to "
                "serve.store.canonical_key",
                snippet=f"canon:{spelled}"))
    # Tune side: the plan DB keys measurements on Plan.describe().
    from dhqr_tpu.tune.search import candidate_plans

    for kind, m, n, nproc, topology, platform in GRID_PROBES:
        seen = {}
        for plan in candidate_plans(kind, m, n, "float32", nproc=nproc,
                                    platform=platform, budget=10_000,
                                    topology=topology):
            tag = plan.describe()
            if tag in seen and seen[tag] != plan:
                findings.append(_f(
                    "DHQR503", "atlas::plan-keys",
                    f"two distinct grid candidates share describe() "
                    f"tag {tag!r} at kind={kind} ({m}x{n}, "
                    f"nproc={nproc}) — the plan DB would conflate "
                    "their measurements under one key",
                    snippet=f"plan:{kind}:{tag}"))
            seen.setdefault(tag, plan)
    return findings


# ---------------------------------------------------------------------------
# DHQR504 — donation audit


def check_donation_routes(routes=None,
                          entries=None) -> "list[Finding]":
    """DHQR504. Routes flagged ``donated`` carry the
    ``comms_pass._donation_entries`` label their dispatch compiles
    through; the two sets must be bijective, or a donated dispatch
    ships with no AOT-aliasing probe (and DHQR304 audits a phantom)."""
    findings = []
    routes = registry.routes() if routes is None else routes
    declared = {r.donation: r.name for r in routes if r.donation}
    if entries is None:
        from dhqr_tpu.analysis.comms_pass import (_donation_entries,
                                                  _ensure_cpu_backend)

        _ensure_cpu_backend()
        probed = {label for label, _fn, _args in _donation_entries()}
    else:
        probed = set(entries)
    for label in sorted(set(declared) - probed):
        findings.append(_f(
            "DHQR504", "atlas::donation",
            f"route {declared[label]!r} declares donation entry "
            f"{label!r} but comms_pass._donation_entries has no such "
            "probe — the donated dispatch ships without its DHQR304 "
            "aliasing audit",
            snippet=f"unprobed:{label}"))
    for label in sorted(probed - set(declared)):
        findings.append(_f(
            "DHQR504", "atlas::donation",
            f"donation probe {label!r} matches no registered route's "
            "donation field — DHQR304 audits an entry the registry "
            "does not know exists",
            snippet=f"unregistered:{label}"))
    return findings


# ---------------------------------------------------------------------------
# DHQR505 — grid / bench drift


def check_grid_drift(routes=None, probes=None,
                     stages=None) -> "list[Finding]":
    """DHQR505. Run the real ``candidate_plans`` over the probe grids
    and require every emission to map onto a registered route via
    ``registry.grid_route_for`` — an unmappable candidate is a route
    the tuner would measure and serve that no pass audits. Bench stages
    must likewise name registered routes of the right kind."""
    from dhqr_tpu.tune.search import candidate_plans

    findings = []
    routes = registry.routes() if routes is None else routes
    known = {r.name: r for r in routes}
    for kind, m, n, nproc, topology, platform in (
            GRID_PROBES if probes is None else probes):
        for plan in candidate_plans(kind, m, n, "float32", nproc=nproc,
                                    platform=platform, budget=10_000,
                                    topology=topology):
            name = registry.grid_route_for(kind, plan, nproc=nproc)
            if name is None or name not in known:
                findings.append(_f(
                    "DHQR505", "atlas::grid",
                    f"grid candidate {plan.describe()!r} at kind="
                    f"{kind} ({m}x{n}, nproc={nproc}) maps to "
                    f"{'no route' if name is None else name!r} in the "
                    "registry — the tuner would measure an unaudited "
                    "route; register it or prune the emission",
                    snippet=f"grid:{kind}:{plan.describe()}"))
    for s in (registry.bench_stages() if stages is None else stages):
        r = known.get(s.route)
        if r is None:
            findings.append(_f(
                "DHQR505", "atlas::grid",
                f"bench stage {s.config} ({s.metric}) names "
                f"unregistered route {s.route!r}",
                snippet=f"stage:{s.config}:{s.route}"))
            continue
        if r.kind != s.kind:
            findings.append(_f(
                "DHQR505", "atlas::grid",
                f"bench stage {s.config} ({s.metric}) is a {s.kind} "
                f"benchmark but route {s.route!r} is registered as "
                f"kind {r.kind!r}",
                snippet=f"stage-kind:{s.config}:{s.route}"))
    return findings


# ---------------------------------------------------------------------------
# Orchestrator


def run_atlas_pass(trace: bool = True) -> "list[Finding]":
    """All DHQR5xx checks with the committed enumerations. Runs at any
    device count (the coverage check is static; the serve-key tracing
    is single-device); ``trace=False`` skips the jaxpr comparisons for
    collided keys (AST-speed, used by ``--fast``'s dryrun twin — note
    the CLI's ``--fast`` skips the pass entirely)."""
    from dhqr_tpu.analysis.jaxpr_pass import _ensure_cpu_backend

    _ensure_cpu_backend()
    findings = registry_findings()
    findings.extend(check_route_coverage())
    findings.extend(check_contract_pricing())
    findings.extend(check_cache_keys(trace=trace))
    findings.extend(check_donation_routes())
    findings.extend(check_grid_drift())
    return findings
