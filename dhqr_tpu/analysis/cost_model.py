"""Analytic communication budgets for the sharded engines (dhqr-audit).

Each model returns the engine's *intended* per-device collective payload
in words, parameterized exactly like the engines themselves (m, n, nb,
P, nrhs). The formulas are the unrolled-schedule volumes — the schedule
the comms pass traces — so at the pass's own shapes the traced volume
matches the budget to the word for the exact engines, and the contract's
slack factor only has to absorb the deliberate schedule variations
(super-block row frames, lookahead's one-panel-taller psum, the
aggregated gather's packed group). Anything past slack is a regression:
an accidental ``all_gather`` of the trailing matrix traced at P=2 is
~P·m·n/2 words per panel, orders of magnitude over any of these.

The arguments mirror the papers' cost accounting: arXiv:2112.09017
(TPU distributed linear algebra: collective volume, not flops, sets the
scaling) and arXiv:2112.01075 (collective *choice* decides redistribution
cost) — which is why the budget is a static contract and not a benchmark.

Volume convention: a collective's payload is the byte size of its OUTPUT
aval on one device (what the jaxpr walk can see) — for ``psum`` that is
the reduced operand, for ``all_gather`` the gathered (P·local) result.
"""

from __future__ import annotations


def unblocked_qr_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """One m-word column psum per column (the reference's per-column
    reflector broadcast, src:141-143): n·m words."""
    return n * m


def blocked_qr_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """One psum per nb-wide panel of the shrinking (m-k, nb) factored
    panel plus its nb-word alpha block (sharded_qr._blocked_shard_body,
    unrolled schedule)."""
    return sum((m - k) * nb + nb for k in range(0, n, nb))


def sharded_solve_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """Q^H apply: one shrinking (m-k, nb) panel psum per panel; panel
    back-substitution: one packed (n, nrhs) psum per panel
    (sharded_solve._apply_qt_shard_body / _backsub_shard_body)."""
    apply_qt = sum((m - k) * nb for k in range(0, n, nb))
    backsub = (n // nb) * n * nrhs
    return apply_qt + backsub


def tsqr_lstsq_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """Exactly one all_gather of the (n, n) R heads and the (n, nrhs)
    reduced rhs: P·n·(n + nrhs) words gathered per device
    (sharded_tsqr._tsqr_shard_body)."""
    return P * n * (n + nrhs)


def cholqr_lstsq_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """One (n, n) Gram psum per CholeskyQR2 pass plus one (n, nrhs) psum
    for Q^H b (sharded_cholqr._cholqr_shard_body, shift=False)."""
    return 2 * n * n + n * nrhs


def no_comms_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """Engines contracted to run collective-free (the batched serving
    dispatch): any traced collective volume at all is a regression."""
    return 0


MODELS = {
    "unblocked_qr": unblocked_qr_words,
    "blocked_qr": blocked_qr_words,
    "sharded_solve": sharded_solve_words,
    "tsqr_lstsq": tsqr_lstsq_words,
    "cholqr_lstsq": cholqr_lstsq_words,
    "none": no_comms_words,
}


def budget_bytes(model: str, m: int, n: int, nb: int, P: int,
                 itemsize: int, nrhs: int = 1) -> int:
    """Analytic per-device collective budget in bytes for ``model``
    (a key of :data:`MODELS`) at the given engine parameters."""
    try:
        fn = MODELS[model]
    except KeyError:
        raise KeyError(
            f"unknown comms cost model {model!r} (have {sorted(MODELS)}); "
            "comms_contracts.json names a model this version does not ship"
        ) from None
    return fn(m, n, nb, P, nrhs=nrhs) * itemsize
