"""Analytic communication budgets for the sharded engines (dhqr-audit).

Each model returns the engine's *intended* per-device collective payload
in words, parameterized exactly like the engines themselves (m, n, nb,
P, nrhs). The formulas are the unrolled-schedule volumes — the schedule
the comms pass traces — so at the pass's own shapes the traced volume
matches the budget to the word for the exact engines, and the contract's
slack factor only has to absorb the deliberate schedule variations
(super-block row frames, lookahead's one-panel-taller psum, the
aggregated gather's packed group). Anything past slack is a regression:
an accidental ``all_gather`` of the trailing matrix traced at P=2 is
~P·m·n/2 words per panel, orders of magnitude over any of these.

The arguments mirror the papers' cost accounting: arXiv:2112.09017
(TPU distributed linear algebra: collective volume, not flops, sets the
scaling) and arXiv:2112.01075 (collective *choice* decides redistribution
cost) — which is why the budget is a static contract and not a benchmark.

Volume convention: a collective's payload is the byte size of its OUTPUT
aval on one device (what the jaxpr walk can see) — for ``psum`` that is
the reduced operand, for ``all_gather`` the gathered (P·local) result.
"""

from __future__ import annotations


def unblocked_qr_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """One m-word column psum per column (the reference's per-column
    reflector broadcast, src:141-143): n·m words."""
    return n * m


def blocked_qr_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """One psum per nb-wide panel of the shrinking (m-k, nb) factored
    panel plus its nb-word alpha block (sharded_qr._blocked_shard_body,
    unrolled schedule). The round-23 depth-k pipeline keeps this SAME
    budget: launch count is unchanged (two one-hot psums per panel) and
    the only volume delta is the delayed trailing update's frame — each
    pf psum ships up to ``depth * nb`` extra rows of already-finished R
    (the lookahead schedule already ships ``nb``), which the pipeline
    contracts' slack absorbs rather than a new model pricing in
    (analysis/comms_contracts.json, 'blocked_qr_pipeline*')."""
    return sum((m - k) * nb + nb for k in range(0, n, nb))


def sharded_solve_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """Q^H apply: one shrinking (m-k, nb) panel psum per panel; panel
    back-substitution: one packed (n, nrhs) psum per panel
    (sharded_solve._apply_qt_shard_body / _backsub_shard_body)."""
    apply_qt = sum((m - k) * nb for k in range(0, n, nb))
    backsub = (n // nb) * n * nrhs
    return apply_qt + backsub


def tsqr_lstsq_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """Exactly one all_gather of the (n, n) R heads and the (n, nrhs)
    reduced rhs: P·n·(n + nrhs) words gathered per device
    (sharded_tsqr._tsqr_shard_body)."""
    return P * n * (n + nrhs)


def cholqr_lstsq_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """One (n, n) Gram psum per CholeskyQR2 pass plus one (n, nrhs) psum
    for Q^H b (sharded_cholqr._cholqr_shard_body, shift=False)."""
    return 2 * n * n + n * nrhs


def no_comms_words(m: int, n: int, nb: int, P: int, nrhs: int = 1) -> int:
    """Engines contracted to run collective-free (the batched serving
    dispatch): any traced collective volume at all is a regression."""
    return 0


#: CSNE correction sweeps the row engines run per COMPRESSED solve
#: (parallel/wire.CSNE_SWEEPS — kept in sync by test): each sweep adds
#: one (n, nrhs) correction psum on top of the combine exchange.
CSNE_SWEEPS = 2


def tsqr_lstsq_wire_words(m: int, n: int, nb: int, P: int,
                          nrhs: int = 1) -> int:
    """Compressed TSQR (dhqr-wire): the one all_gather pair of
    :func:`tsqr_lstsq_words` plus :data:`CSNE_SWEEPS` corrected-semi-
    normal (n, nrhs) psums (sharded_tsqr._tsqr_shard_body, comms set).
    The correction psums stay on the F32 wire by design, so their words
    are counted DOUBLE here: ``budget_bytes`` prices every word of a
    bf16 contract at 2 bytes, and 2 x 2 B = the 4 B the f32 correction
    actually moves (int8 contracts under-price them 2x — absorbed by
    their slack). They are O(1/(P*n)) of the gather at real shapes;
    the model carries them so audit-scale shapes stay exact."""
    return (tsqr_lstsq_words(m, n, nb, P, nrhs=nrhs)
            + 2 * CSNE_SWEEPS * n * nrhs)


def cholqr_lstsq_wire_words(m: int, n: int, nb: int, P: int,
                            nrhs: int = 1) -> int:
    """Compressed CholeskyQR2 (dhqr-wire): the Gram/Q^Hb psums of
    :func:`cholqr_lstsq_words` plus :data:`CSNE_SWEEPS` corrected-semi-
    normal (n, nrhs) psums — f32-wire, double-counted exactly as in
    :func:`tsqr_lstsq_wire_words` (sharded_cholqr._cholqr_shard_body)."""
    return (cholqr_lstsq_words(m, n, nb, P, nrhs=nrhs)
            + 2 * CSNE_SWEEPS * n * nrhs)


MODELS = {
    "unblocked_qr": unblocked_qr_words,
    "blocked_qr": blocked_qr_words,
    "sharded_solve": sharded_solve_words,
    "tsqr_lstsq": tsqr_lstsq_words,
    "cholqr_lstsq": cholqr_lstsq_words,
    "tsqr_lstsq_wire": tsqr_lstsq_wire_words,
    "cholqr_lstsq_wire": cholqr_lstsq_wire_words,
    "none": no_comms_words,
}


#: Wire bytes per word under each dhqr-wire comms mode (round 18).
#: Deliberately a LITERAL COPY of dhqr_tpu.precision.WIRE_ITEMSIZE:
#: importing precision would pull the package __init__ (and jax) into
#: the stdlib-only regress tier that imports this module. The copies
#: are pinned against each other by
#: tests/test_wire.py::test_wire_modes_validation_and_vocab_parity.
#: The dcn:* rungs (round 20) price the DCN LEG of the hierarchical
#: schedule; their ICI legs are f32 (see tiered_budget_bytes below —
#: flat budget_bytes with a dcn:* mode prices the whole volume at the
#: DCN itemsize, which is only meaningful per-tier).
WIRE_ITEMSIZE = {"bf16": 2, "int8": 1, "dcn:bf16": 2, "dcn:int8": 1}


def budget_bytes(model: str, m: int, n: int, nb: int, P: int,
                 itemsize: int, nrhs: int = 1,
                 comms: "str | None" = None) -> int:
    """Analytic per-device collective budget in bytes for ``model``
    (a key of :data:`MODELS`) at the given engine parameters.

    ``comms`` (a dhqr-wire mode, round 18) prices the budget at the
    COMPRESSED wire itemsize instead of the array itemsize — words are
    schedule-invariant, so the same volume formula covers every wire
    format. The int8 rung's per-column f32 scale sidecars and the
    bf16 1-D fallbacks are deliberately NOT modeled (they are O(1/rows)
    relative); the compressed contracts' slack absorbs them, and a
    tightened slack on the bf16 entries is exactly what machine-checks
    the >= 1.8x traced-volume reduction (4 / (2 x 1.1) > 1.8)."""
    try:
        fn = MODELS[model]
    except KeyError:
        raise KeyError(
            f"unknown comms cost model {model!r} (have {sorted(MODELS)}); "
            "comms_contracts.json names a model this version does not ship"
        ) from None
    if comms is not None:
        try:
            itemsize = WIRE_ITEMSIZE[comms]
        except KeyError:
            raise KeyError(
                f"unknown comms wire format {comms!r} (have "
                f"{sorted(WIRE_ITEMSIZE)}); comms_contracts.json names a "
                "wire format this version does not ship"
            ) from None
    return fn(m, n, nb, P, nrhs=nrhs) * itemsize


# ---------------------------------------------------------------------------
# Two-tier (DCN x ICI) budgets — dhqr-pod, round 20.
#
# The word models above are schedule-invariant totals; a two-tier
# contract needs the per-TIER split, which depends on the collective
# SCHEDULE (hierarchical vs flat) and on the per-leg wire format (the
# dcn:* rungs compress only the DCN crossing). The split is derived
# from the same per-collective payload sequence the engines trace, so
# at the pass's own shapes the per-tier traced volume matches the
# budget to the byte for the exact engines.

#: Literal copy of parallel/wire._DCN_TIERED (same stdlib-only-tier
#: reasoning as WIRE_ITEMSIZE above; pinned by the vocab-parity test).
DCN_TIERED = {"dcn:bf16": "bf16", "dcn:int8": "int8"}


def payload_schedule(model: str, m: int, n: int, nb: int, P: int,
                     nrhs: int = 1):
    """The engine's per-collective payload sequence:
    ``(kind, rows, cols, f32_wire, onehot)`` tuples where ``kind`` is
    ``"psum"`` or ``"gather"``, ``(rows, cols)`` the payload shape on
    one device, ``f32_wire`` marks the CSNE correction reductions that
    stay on the exact f32 wire at every rung, and ``onehot`` the
    one-hot-broadcast invariant (dense reductions refuse int8 at the
    seam — the tiered pricing mirrors that refusal). Summing
    ``rows * cols`` over the sequence reproduces the word models above
    exactly."""
    if model == "unblocked_qr":
        return [("psum", m, 1, False, True)] * n
    if model == "blocked_qr":
        out = []
        for k in range(0, n, nb):
            out.append(("psum", m - k, nb, False, True))
            out.append(("psum", nb, 1, False, True))
        return out
    if model == "sharded_solve":
        out = [("psum", m - k, nb, False, True) for k in range(0, n, nb)]
        out += [("psum", n, nrhs, False, True)] * (n // nb)
        return out
    if model in ("tsqr_lstsq", "tsqr_lstsq_wire"):
        out = [("gather", n, n, False, True),
               ("gather", n, nrhs, False, True)]
        if model == "tsqr_lstsq_wire":
            out += [("psum", n, nrhs, True, False)] * CSNE_SWEEPS
        return out
    if model in ("cholqr_lstsq", "cholqr_lstsq_wire"):
        out = [("psum", n, n, False, False)] * 2
        out.append(("psum", n, nrhs, False, False))
        if model == "cholqr_lstsq_wire":
            out += [("psum", n, nrhs, True, False)] * CSNE_SWEEPS
        return out
    if model == "none":
        return []
    raise KeyError(
        f"unknown comms cost model {model!r} (have {sorted(MODELS)}); "
        "comms_contracts.json names a model this version does not ship")


def _leg_itemsize(mode: "str | None", itemsize: int, onehot: bool) -> int:
    """Wire bytes/word for one leg: f32 passthrough at ``itemsize``,
    int8 dense reductions degrade to bf16 exactly as at the seam."""
    if mode is None:
        return itemsize
    if mode == "int8" and not onehot:
        return WIRE_ITEMSIZE["bf16"]
    return WIRE_ITEMSIZE[mode]


def tiered_budget_bytes(model: str, m: int, n: int, nb: int, P: int,
                        itemsize: int, nrhs: int = 1,
                        comms: "str | None" = None,
                        topology: "tuple[int, int] | None" = None,
                        hierarchical: bool = True) -> "dict[str, int]":
    """Per-tier analytic collective budget ``{"ici": B, "dcn": B,
    "total": B}`` for ``model`` on a ``topology = (dcn_size,
    ici_size)`` mesh (dhqr-pod, round 20).

    Pricing mirrors the traced census byte-for-byte (output-aval
    convention, module docstring): a hierarchical ``psum`` is an ICI
    reduce (wire itemsize), a DCN chunk exchange of ``ceil(rows /
    ici_size)`` rows (DCN-leg itemsize — the ici_size-fold cross-DCN
    cut this round exists for), and an f32 ICI broadcast-back gather of
    the row-padded payload; a hierarchical ``gather`` exchanges only
    the local share across DCN then gathers the stacks over ICI in
    f32. The flat baseline (``hierarchical=False``) runs ONE joint-axis
    collective whose full payload crosses DCN — counted entirely on
    the DCN tier, which is exactly the comparison the serving_pod
    benchmark publishes. ``topology=None`` (a 1-D mesh) has no DCN
    tier at all; the ``dcn:*`` rungs degrade to f32 wherever no
    isolated DCN leg exists, mirroring the seam."""
    sched = payload_schedule(model, m, n, nb, P, nrhs=nrhs)
    if topology is None:
        total = budget_bytes(
            model, m, n, nb, P, itemsize, nrhs=nrhs,
            comms=None if comms in DCN_TIERED else comms)
        return {"ici": total, "dcn": 0, "total": total}
    dcn_size, ici_size = topology
    if dcn_size * ici_size != P:
        raise ValueError(
            f"topology {topology} does not factor P={P}")
    if comms in DCN_TIERED:
        ici_mode, dcn_mode = None, DCN_TIERED[comms]
    else:
        ici_mode = dcn_mode = comms
    ici = dcn = 0
    for kind, rows, cols, f32_wire, onehot in sched:
        im = None if f32_wire else ici_mode
        dm = None if f32_wire else dcn_mode
        if not hierarchical:
            # One joint-axis collective: the full payload crosses DCN.
            # dcn:* has no isolated DCN leg on the flat schedule -> f32.
            fm = None if comms in DCN_TIERED or f32_wire else comms
            isz = _leg_itemsize(fm, itemsize, onehot)
            words = P * rows * cols if kind == "gather" else rows * cols
            dcn += words * isz
            continue
        if kind == "psum":
            ici += rows * cols * _leg_itemsize(im, itemsize, onehot)
            if dcn_size > 1:
                rp = -(-rows // ici_size) * ici_size
                dcn += (rp // ici_size) * cols * _leg_itemsize(
                    dm, itemsize, onehot)
                ici += rp * cols * itemsize     # f32 broadcast-back
        else:  # gather
            if dcn_size > 1:
                dcn += (dcn_size * rows * cols
                        * _leg_itemsize(dm, itemsize, onehot))
                if ici_size > 1:
                    ici += P * rows * cols * itemsize
            else:
                ici += P * rows * cols * _leg_itemsize(
                    im, itemsize, onehot)
    return {"ici": ici, "dcn": dcn, "total": ici + dcn}
