"""dhqr-lint — static analysis enforcing the framework's TPU/JAX discipline.

Two passes over two program representations (docs/DESIGN.md "Static
invariants"):

* **Pass 1 (AST)** — :mod:`dhqr_tpu.analysis.ast_rules` walks the source
  tree with rule classes DHQR001-DHQR010: private-jax import hygiene, MXU
  precision annotations on every contraction, config/env mutation
  containment, host syncs inside traced bodies, collective axis-name
  discipline inside ``shard_map`` bodies, swallowed-exception bans, and
  Cholesky-call containment (every Cholesky routes through the numeric
  layer's guarded wrapper).
* **Pass 2 (jaxpr)** — :mod:`dhqr_tpu.analysis.jaxpr_pass` abstractly
  traces the public entry points under every precision-policy preset (and
  the sharded engines under a 1-device mesh) and sanitizes the jaxpr:
  no f64 intermediates from f32 inputs, no host callbacks, every
  collective's axis name resolvable against the mesh (DHQR101-DHQR104).
* **Pass 3 (comms, "dhqr-audit")** — :mod:`dhqr_tpu.analysis.comms_pass`
  forces multi-device CPU topologies (P ∈ {2, 4, 8}), traces every
  sharded engine, and enforces the committed per-engine communication
  contracts (``comms_contracts.json`` + the analytic budgets in
  :mod:`dhqr_tpu.analysis.cost_model`): collective families, byte
  volume, replicated-intermediate bounds, donation aliasing, and
  trace-stability (DHQR301-DHQR305).

Plus an API-consistency check (DHQR201/DHQR202): everything in
``dhqr_tpu.__all__`` imports cleanly and is documented in docs/DESIGN.md.

Findings support inline suppressions
(``# dhqr: ignore[DHQR002] <reason>``) and a committed baseline file; the
CLI is ``python -m dhqr_tpu.analysis check [paths] [--json] [--baseline
FILE]`` and a tier-1 test (tests/test_analysis.py) self-scans the package
so a new violation fails the suite.
"""

from dhqr_tpu.analysis.findings import (
    Finding,
    load_baseline,
    prune_baseline,
    write_baseline,
)
from dhqr_tpu.analysis.ast_rules import (
    AST_RULES,
    scan_paths,
    scan_source,
)

__all__ = [
    "Finding",
    "AST_RULES",
    "scan_paths",
    "scan_source",
    "load_baseline",
    "prune_baseline",
    "write_baseline",
]
