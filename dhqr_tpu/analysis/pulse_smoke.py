"""DHQR402: the pulse runtime-comms smoke (round 16).

DHQR401 proves the DEVICE-observability seam (xray capture at the
serve compile entry) produces evidence before a TPU window; this is
its comms twin: one tiny sharded dispatch with pulse capture armed
must yield a :class:`~dhqr_tpu.obs.pulse.PulseReport` whose measured /
analytic / skew / DHQR306 fields are populated (or null WITH a
reason), and whose accounting registers under the ``comms.*`` dotted
names. A refactor that silently disconnects the seam (drops the
``observed_dispatch`` hook from an engine, breaks the trace parser,
unregisters the provider) fails lint here instead of costing ROADMAP
item 3's compressed-collectives work its before/after evidence.

The smoke adapts to the backend's width: with >= 2 CPU devices (the
tools/lint.sh topology) it dispatches on a P=2 mesh and REQUIRES a
measured collective census; on a 1-device backend it still exercises
the full seam and accepts the reasoned null (XLA elides P=1
collectives) — a narrow backend weakens the assertion, it never
false-greens a disconnected seam.
"""

from __future__ import annotations

from dhqr_tpu.analysis.findings import Finding

_PATH = "dhqr_tpu/obs/pulse.py"

#: This pass's rule-catalogue rows (assembled by analysis/cli.py —
#: round 21 retired the CLI's hand-kept copy). DHQR306 rides here: the
#: measured-vs-priced gate is pulse-side even though its budget comes
#: from the comms contracts.
RULES = (
    ("DHQR306", "measured collective time unexplainable by volume "
     "/ interconnect bandwidth x slack (priced per ICI/DCN tier "
     "on two-tier meshes)", "pulse"),
    ("DHQR402", "pulse runtime-comms profiling smoke failed", "pulse"),
)


def run_pulse_smoke() -> "list[Finding]":
    """Dispatch one tiny sharded factorization with pulse armed; every
    broken invariant is one DHQR402 finding (an infrastructure crash
    is one finding too — a smoke that cannot run must not pass)."""
    findings = []

    def bad(msg: str) -> None:
        findings.append(Finding("DHQR402", _PATH, 0, msg))

    try:
        import jax
        import jax.numpy as jnp

        from dhqr_tpu.obs import pulse as _pulse
        from dhqr_tpu.obs import registry
        from dhqr_tpu.parallel.mesh import column_mesh
        from dhqr_tpu.parallel.sharded_qr import sharded_blocked_qr

        P = 2 if len(jax.devices()) >= 2 else 1
        mesh = column_mesh(P)
        A = jnp.ones((16, 8), jnp.float32)
        with _pulse.pulsed() as store:
            H, alpha = sharded_blocked_qr(A, mesh, block_size=4)
            jax.block_until_ready((H, alpha))
            reports = store.reports()
            if not reports:
                bad("armed pulse capture recorded no report for a "
                    "sharded dispatch — the observed_dispatch seam is "
                    "disconnected from the engine")
                return findings
            report = reports[0]
            if report.measured is None and not report.measured_unavailable:
                bad("measured collective census is None WITHOUT a "
                    "reason — the null-with-reason contract dropped")
            if P >= 2 and report.measured is None:
                bad("no measured collective census on a P=2 CPU "
                    "topology (the profiler/trace-parse path is "
                    f"broken: {report.measured_unavailable})")
            if report.analytic is None and not report.analytic_unavailable:
                bad("analytic census is None without a reason — the "
                    "comms_pass.collect_comms bridge dropped")
            if P >= 2 and not (report.analytic or {}).get("psum"):
                bad("the traced analytic census lost the blocked "
                    "engine's psum family")
            if report.dhqr306 is None or "status" not in report.dhqr306:
                bad("DHQR306 verdict block missing from the report")
            elif not report.dhqr306_pass:
                # The runtime contract itself gets its own rule id: a
                # red measured-vs-analytic verdict is a comms
                # regression, not a broken seam.
                findings.append(Finding(
                    "DHQR306", _PATH, 0,
                    "measured collective time is not explainable by "
                    "traced volume / interconnect bandwidth x slack "
                    f"on the smoke dispatch: {report.dhqr306}"))
            row = report.to_json()
            for field in ("measured", "analytic", "skew", "dhqr306",
                          "dhqr306_pass"):
                if field not in row:
                    bad(f"PulseReport.to_json() lost the {field!r} "
                        "field the artifact rows and the pulse CLI "
                        "key on")
            # Warm repeat: the same label must NOT re-measure (the
            # armed-overhead contract lives on capture-once).
            captures = store.stats()["captures"]
            H2, _ = sharded_blocked_qr(A, mesh, block_size=4)
            jax.block_until_ready(H2)
            if store.stats()["captures"] != captures:
                bad("a warm repeat of the same label re-measured — "
                    "the capture-once discipline (and with it the "
                    ">= 0.95 armed-overhead bar) is broken")
            snap = registry().snapshot()
            if not snap.get("comms.captures"):
                bad("the metrics registry snapshot carries no armed "
                    "comms.captures — the pulse provider is "
                    "unregistered")
    except Exception as e:
        bad(f"pulse smoke crashed: {type(e).__name__}: {e}")
    return findings
