"""dhqr-lint CLI: ``python -m dhqr_tpu.analysis check [paths] ...``.

Exit status 0 iff no unsuppressed, un-baselined findings. The AST pass
runs on every named path; the jaxpr sanitizer, the API-consistency
check, the multi-device comms-contract audit (dhqr-audit,
``analysis/comms_pass.py``), the xray introspection smoke
(``analysis/xray_smoke.py``, DHQR401), and the pulse runtime-comms
smoke (``analysis/pulse_smoke.py``, DHQR402), and the route-registry drift
audit (dhqr-atlas, ``analysis/atlas.py``, DHQR501-DHQR505), and the
lock-discipline & deadlock-order pass (dhqr-warden,
``analysis/concurrency_pass.py``, DHQR601-DHQR604) run
whenever the dhqr_tpu package itself is among the scan targets (they
validate the package, not arbitrary files), unless disabled with
``--no-jaxpr`` / ``--no-api`` / ``--no-comms`` / ``--no-xray`` /
``--no-pulse`` / ``--no-atlas`` / ``--no-concurrency`` — or all at
once with ``--fast`` (AST-only, for edit loops; the concurrency pass's
static half still runs, only its runtime lock-witness burst is
skipped). ``--format {text,json}`` selects the
output shape (``--json`` is the legacy alias). ``comms`` is the audit
alone (the subprocess vehicle ``check`` uses when the backend
initialized before the multi-device CPU topology could be forced).
``--list-rules`` prints the full DHQR rule catalogue so the docs table
cannot drift from the code (tests/test_analysis.py asserts parity with
docs/DESIGN.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _scans_package(paths) -> bool:
    """Do the scan targets cover the installed dhqr_tpu package — by
    name, or as an ancestor directory ('.', the repo root)? The jaxpr
    and API passes validate the package itself, so they must run for
    any target that contains it."""
    import dhqr_tpu

    pkg = os.path.realpath(os.path.dirname(os.path.abspath(
        dhqr_tpu.__file__)))
    for p in paths:
        rp = os.path.realpath(p)
        if rp == pkg or (os.path.isdir(rp)
                         and pkg.startswith(rp + os.sep)):
            return True
    return False


def rule_catalogue() -> "list[tuple[str, str, str]]":
    """(rule id, one-line summary, pass) for every DHQR rule — THE
    list ``--list-rules`` prints and the docs-parity test checks, so a
    rule cannot ship without a catalogue row. Round 21 (dhqr-atlas)
    retired the hand-kept copy: each pass module owns its ``RULES``
    tuple and this function only assembles them, so a new pass rule
    registers once, next to its implementation."""
    from dhqr_tpu.analysis import (
        api_check,
        atlas,
        comms_pass,
        concurrency_pass,
        jaxpr_pass,
        pulse_smoke,
        xray_smoke,
    )
    from dhqr_tpu.analysis.ast_rules import AST_RULES

    rows = [("DHQR000", "source file failed to parse, or a suppression "
             "directive carries no reason (warn-only)", "ast")]
    rows += [(r.id, r.title, "ast") for r in AST_RULES]
    # (DHQR009 — the dhqr-wire seam rule — rides in AST_RULES like the
    # other pass-1 rows.)
    for mod in (jaxpr_pass, api_check, comms_pass, pulse_smoke,
                xray_smoke, atlas, concurrency_pass):
        rows += list(mod.RULES)
    return sorted(rows, key=lambda row: row[0])


def _force_multidevice_env(count: int) -> None:
    """Arm the multi-device CPU topology the comms audit traces under.
    XLA_FLAGS is only read at first backend init, so setting it here —
    before any device touch — makes the in-process path work; if some
    caller already initialized the backend narrower, the audit falls
    back to a subprocess (comms_pass.run_comms_pass_auto)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # dhqr: ignore[DHQR003] lint CLI entry owns its process: the comms audit needs a multi-device CPU topology and XLA_FLAGS is read exactly once, at backend init
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={count}"
        ).strip()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dhqr_tpu.analysis",
        description="dhqr-lint: AST + jaxpr + comms-contract static "
        "analysis enforcing the framework's TPU/JAX discipline "
        "(docs/DESIGN.md 'Static invariants' and 'Comms contracts').",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the full DHQR rule catalogue (ID, summary, pass) "
        "and exit",
    )
    sub = parser.add_subparsers(dest="command")
    check = sub.add_parser("check", help="run the lint passes")
    check.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to scan (default: dhqr_tpu tests)",
    )
    check.add_argument("--json", action="store_true",
                       help="emit findings as JSON (alias for "
                       "--format json)")
    check.add_argument(
        "--format", choices=("text", "json"), default=None,
        help="output format (default text; json is the machine shape "
        "tools/lint.sh --format json forwards)",
    )
    check.add_argument(
        "--fast", action="store_true",
        help="AST-only lint: skip every traced/compiled pass (jaxpr, "
        "api, comms, xray, pulse, atlas) — seconds instead of minutes, "
        "for edit loops; the full gate still runs in CI/tools/lint.sh",
    )
    check.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="accepted-findings file: matching fingerprints do not fail "
        "the run (shipped baseline: tools/lint_baseline.json, empty)",
    )
    check.add_argument(
        "--write-baseline", default=None, metavar="FILE",
        help="write the current unsuppressed findings as a new baseline "
        "and exit 0 (docs/OPERATIONS.md: regenerating the baseline)",
    )
    check.add_argument(
        "--prune-baseline", action="store_true",
        help="rewrite --baseline dropping fingerprints that no longer "
        "match any current finding, then gate against the pruned file",
    )
    check.add_argument("--no-jaxpr", action="store_true",
                       help="skip the jaxpr sanitizer pass")
    check.add_argument("--no-api", action="store_true",
                       help="skip the public-API consistency check")
    check.add_argument("--no-comms", action="store_true",
                       help="skip the multi-device comms-contract audit")
    check.add_argument("--no-xray", action="store_true",
                       help="skip the xray introspection smoke (DHQR401)")
    check.add_argument("--no-pulse", action="store_true",
                       help="skip the pulse runtime-comms smoke (DHQR402)")
    check.add_argument("--no-atlas", action="store_true",
                       help="skip the route-registry drift audit "
                       "(DHQR501-DHQR505)")
    check.add_argument("--no-concurrency", action="store_true",
                       help="skip the lock-discipline & deadlock-order "
                       "pass (DHQR601-DHQR604)")
    check.add_argument(
        "--preset", action="append", default=None,
        help="restrict the jaxpr/comms passes to these policy presets "
        "(repeatable; default: all)",
    )
    check.add_argument(
        "--devices", action="append", type=int, default=None,
        metavar="P",
        help="comms-audit mesh sizes (repeatable; default: 2 4 8)",
    )
    check.add_argument(
        "--contracts", default=None, metavar="FILE",
        help="comms-contract file (default: the committed "
        "analysis/comms_contracts.json)",
    )
    comms = sub.add_parser(
        "comms",
        help="run only the comms-contract audit (dhqr-audit) — also the "
        "subprocess vehicle `check` uses when the jax backend "
        "initialized before the multi-device topology could be forced",
    )
    comms.add_argument("--json", action="store_true",
                       help="emit findings as JSON")
    comms.add_argument("--preset", action="append", default=None)
    comms.add_argument("--devices", action="append", type=int,
                       default=None, metavar="P")
    comms.add_argument("--contracts", default=None, metavar="FILE")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, summary, pass_name in rule_catalogue():
            print(f"{rule}  {pass_name:<5}  {summary}")
        return 0
    if not args.command:
        parser.error("a command is required (check, comms) "
                     "unless --list-rules is given")

    from dhqr_tpu.analysis.comms_pass import DEFAULT_DEVICE_COUNTS

    device_counts = tuple(args.devices) if args.devices \
        else DEFAULT_DEVICE_COUNTS

    if args.command == "comms":
        _force_multidevice_env(max(device_counts))
        from dhqr_tpu.analysis.comms_pass import (
            InsufficientDevices,
            run_comms_pass,
        )

        try:
            findings = run_comms_pass(presets=args.preset,
                                      device_counts=device_counts,
                                      contracts_path=args.contracts)
        except InsufficientDevices as e:
            print(f"dhqr-audit: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps({"findings": [f.to_json() for f in findings]},
                             indent=2))
        else:
            for f in findings:
                print(f.render())
            print(f"dhqr-audit: {len(findings)} finding(s)",
                  file=sys.stderr)
        return 1 if findings else 0

    from dhqr_tpu.analysis.ast_rules import scan_paths
    from dhqr_tpu.analysis.findings import (
        load_baseline,
        prune_baseline,
        write_baseline,
    )

    paths = args.paths or ["dhqr_tpu", "tests"]
    if args.fast:
        args.no_jaxpr = args.no_api = args.no_comms = True
        args.no_xray = args.no_pulse = args.no_atlas = True
        # The concurrency pass's STATIC half stays on even under --fast
        # (it is AST-speed); only the runtime lock-witness burst — which
        # compiles and dispatches — is skipped.
    if _scans_package(paths) and not args.no_comms:
        # Before ANY jax device touch (the jaxpr pass initializes the
        # backend), so the comms audit can run in-process.
        _force_multidevice_env(max(device_counts))
    try:
        findings = scan_paths(paths)
    except FileNotFoundError as e:
        print(f"dhqr-lint: {e}", file=sys.stderr)
        return 2

    if _scans_package(paths) and not args.no_jaxpr:
        from dhqr_tpu.analysis.jaxpr_pass import run_jaxpr_pass

        findings.extend(run_jaxpr_pass(presets=args.preset))
    if _scans_package(paths) and not args.no_api:
        from dhqr_tpu.analysis.api_check import check_api

        findings.extend(check_api())
    if _scans_package(paths) and not args.no_comms:
        from dhqr_tpu.analysis.comms_pass import run_comms_pass_auto

        findings.extend(run_comms_pass_auto(presets=args.preset,
                                            device_counts=device_counts,
                                            contracts_path=args.contracts))
    if _scans_package(paths) and not args.no_xray:
        from dhqr_tpu.analysis.xray_smoke import run_xray_smoke

        findings.extend(run_xray_smoke())
    if _scans_package(paths) and not args.no_pulse:
        from dhqr_tpu.analysis.pulse_smoke import run_pulse_smoke

        findings.extend(run_pulse_smoke())
    if _scans_package(paths) and not args.no_atlas:
        from dhqr_tpu.analysis.atlas import run_atlas_pass

        findings.extend(run_atlas_pass())
    if _scans_package(paths) and not args.no_concurrency:
        from dhqr_tpu.analysis.concurrency_pass import run_concurrency_pass

        findings.extend(run_concurrency_pass(witness=not args.fast))

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"baseline written: {args.write_baseline} "
              f"({sum(1 for f in findings if not f.suppressed)} findings)")
        return 0

    if args.prune_baseline:
        if not args.baseline:
            print("dhqr-lint: --prune-baseline requires --baseline FILE",
                  file=sys.stderr)
            return 2
        kept, removed = prune_baseline(args.baseline, findings)
        print(f"dhqr-lint: baseline pruned — {removed} stale "
              f"entr{'y' if removed == 1 else 'ies'} removed, "
              f"{kept} kept", file=sys.stderr)

    baseline = dict(load_baseline(args.baseline)) if args.baseline else {}
    active, baselined = [], []
    suppressed = [f for f in findings if f.suppressed]
    for f in findings:
        if f.suppressed:
            continue
        fp = f.fingerprint()
        if baseline.get(fp, 0) > 0:  # multiset: each accepted occurrence
            baseline[fp] -= 1        # absorbs exactly one finding
            baselined.append(f)
        else:
            active.append(f)

    # Severity split (round 21): warn-only findings (the missing-reason
    # DHQR000) are reported — and baseline-able above — but never gate
    # the exit code on their own.
    errors = [f for f in active if f.severity != "warning"]
    warnings = [f for f in active if f.severity == "warning"]

    if args.json or args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in errors],
            "warnings": [f.to_json() for f in warnings],
            "suppressed": [f.to_json() for f in suppressed],
            "baselined": [f.to_json() for f in baselined],
        }, indent=2))
    else:
        for f in errors:
            print(f.render())
        for f in warnings:
            print(f.render())
        print(f"dhqr-lint: {len(errors)} finding(s), "
              f"{len(warnings)} warning(s), "
              f"{len(suppressed)} suppressed, {len(baselined)} baselined",
              file=sys.stderr)
    return 1 if errors else 0
