"""dhqr-lint CLI: ``python -m dhqr_tpu.analysis check [paths] ...``.

Exit status 0 iff no unsuppressed, un-baselined findings. The AST pass
runs on every named path; the jaxpr sanitizer and the API-consistency
check run whenever the dhqr_tpu package itself is among the scan targets
(they validate the package, not arbitrary files), unless disabled with
``--no-jaxpr`` / ``--no-api``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _scans_package(paths) -> bool:
    """Do the scan targets cover the installed dhqr_tpu package — by
    name, or as an ancestor directory ('.', the repo root)? The jaxpr
    and API passes validate the package itself, so they must run for
    any target that contains it."""
    import dhqr_tpu

    pkg = os.path.realpath(os.path.dirname(os.path.abspath(
        dhqr_tpu.__file__)))
    for p in paths:
        rp = os.path.realpath(p)
        if rp == pkg or (os.path.isdir(rp)
                         and pkg.startswith(rp + os.sep)):
            return True
    return False


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dhqr_tpu.analysis",
        description="dhqr-lint: AST + jaxpr static analysis enforcing the "
        "framework's TPU/JAX discipline (docs/DESIGN.md 'Static "
        "invariants').",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    check = sub.add_parser("check", help="run the lint passes")
    check.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to scan (default: dhqr_tpu tests)",
    )
    check.add_argument("--json", action="store_true",
                       help="emit findings as JSON")
    check.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="accepted-findings file: matching fingerprints do not fail "
        "the run (shipped baseline: tools/lint_baseline.json, empty)",
    )
    check.add_argument(
        "--write-baseline", default=None, metavar="FILE",
        help="write the current unsuppressed findings as a new baseline "
        "and exit 0 (docs/OPERATIONS.md: regenerating the baseline)",
    )
    check.add_argument("--no-jaxpr", action="store_true",
                       help="skip the jaxpr sanitizer pass")
    check.add_argument("--no-api", action="store_true",
                       help="skip the public-API consistency check")
    check.add_argument(
        "--preset", action="append", default=None,
        help="restrict the jaxpr pass to these policy presets "
        "(repeatable; default: all)",
    )
    args = parser.parse_args(argv)

    from dhqr_tpu.analysis.ast_rules import scan_paths
    from dhqr_tpu.analysis.findings import load_baseline, write_baseline

    paths = args.paths or ["dhqr_tpu", "tests"]
    try:
        findings = scan_paths(paths)
    except FileNotFoundError as e:
        print(f"dhqr-lint: {e}", file=sys.stderr)
        return 2

    if _scans_package(paths) and not args.no_jaxpr:
        from dhqr_tpu.analysis.jaxpr_pass import run_jaxpr_pass

        findings.extend(run_jaxpr_pass(presets=args.preset))
    if _scans_package(paths) and not args.no_api:
        from dhqr_tpu.analysis.api_check import check_api

        findings.extend(check_api())

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"baseline written: {args.write_baseline} "
              f"({sum(1 for f in findings if not f.suppressed)} findings)")
        return 0

    baseline = dict(load_baseline(args.baseline)) if args.baseline else {}
    active, baselined = [], []
    suppressed = [f for f in findings if f.suppressed]
    for f in findings:
        if f.suppressed:
            continue
        fp = f.fingerprint()
        if baseline.get(fp, 0) > 0:  # multiset: each accepted occurrence
            baseline[fp] -= 1        # absorbs exactly one finding
            baselined.append(f)
        else:
            active.append(f)

    if args.json:
        print(json.dumps({
            "findings": [f.to_json() for f in active],
            "suppressed": [f.to_json() for f in suppressed],
            "baselined": [f.to_json() for f in baselined],
        }, indent=2))
    else:
        for f in active:
            print(f.render())
        print(f"dhqr-lint: {len(active)} finding(s), "
              f"{len(suppressed)} suppressed, {len(baselined)} baselined",
              file=sys.stderr)
    return 1 if active else 0
