"""Public-API consistency check (DHQR201/DHQR202).

Everything ``dhqr_tpu.__all__`` promises must (a) import cleanly —
``getattr`` succeeds on a fresh import — and (b) appear in
docs/DESIGN.md, which carries the public-API table. A name that fails
(a) is a broken export (the import graph moved under the facade); a name
that fails (b) is an undocumented surface users will find only by
reading source.
"""

from __future__ import annotations

import os
import re

from dhqr_tpu.analysis.findings import Finding

_INIT_PATH = "dhqr_tpu/__init__.py"

#: This pass's rule-catalogue rows (assembled by analysis/cli.py —
#: round 21 retired the CLI's hand-kept copy).
RULES = (
    ("DHQR201", "__all__ export does not import cleanly", "api"),
    ("DHQR202", "public name undocumented in docs/DESIGN.md", "api"),
)


def check_api(design_md: "str | None" = None) -> "list[Finding]":
    """Validate ``dhqr_tpu.__all__`` against the import surface and the
    design doc. ``design_md`` defaults to docs/DESIGN.md next to the
    package's repo root."""
    import dhqr_tpu

    if design_md is None:
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(dhqr_tpu.__file__)))
        design_md = os.path.join(repo, "docs", "DESIGN.md")
    findings = []
    try:
        with open(design_md, "r", encoding="utf-8") as fh:
            doc = fh.read()
    except OSError as e:
        return [Finding("DHQR202", _INIT_PATH, 0,
                        f"cannot read design doc {design_md}: {e}")]
    # Search the "## Public API" section only: common names (qr, solve)
    # occur all over the prose, so a whole-document match would accept a
    # table with the entry deleted. No section at all -> everything is
    # undocumented.
    m = re.search(r"^## Public API\s*$(.*?)(?=^## |\Z)", doc,
                  re.MULTILINE | re.DOTALL)
    doc = m.group(1) if m else ""
    if not m:
        findings.append(Finding(
            "DHQR202", _INIT_PATH, 0,
            "docs/DESIGN.md has no '## Public API' section — the API "
            "table the consistency check validates against is missing",
        ))
    for name in dhqr_tpu.__all__:
        if name.startswith("__"):  # dunders (__version__) are metadata
            continue
        try:
            getattr(dhqr_tpu, name)
        except Exception as e:
            findings.append(Finding(
                "DHQR201", _INIT_PATH, 0,
                f"__all__ entry {name!r} does not import cleanly: "
                f"{type(e).__name__}: {e}",
                snippet=name,
            ))
            continue
        if not re.search(rf"\b{re.escape(name)}\b", doc):
            findings.append(Finding(
                "DHQR202", _INIT_PATH, 0,
                f"__all__ entry {name!r} is absent from the "
                "'## Public API' table in docs/DESIGN.md — add it",
                snippet=name,
            ))
    return findings
