"""``python -m dhqr_tpu.analysis`` entry point."""

import sys

from dhqr_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
