"""DHQR401: the xray introspection smoke (round 15).

``check .`` (and the dry run) must prove — before any TPU window — that
the device-observability seam actually produces evidence on this
backend: one tiny bucket program compiled through the serving tier's
ONE compile entry with capture armed yields an :class:`XrayReport`
whose analytic/measured/roofline fields are populated (or null WITH a
reason), and whose accounting registers under the ``xray.*`` dotted
names. A refactor that silently disconnects the capture hook (moves
the compile entry, breaks the compat shim, drops the registry
provider) fails lint here instead of costing the next hardware
window its per-executable accounting.
"""

from __future__ import annotations

from dhqr_tpu.analysis.findings import Finding

_PATH = "dhqr_tpu/obs/xray.py"

#: This pass's rule-catalogue rows (assembled by analysis/cli.py —
#: round 21 retired the CLI's hand-kept copy).
RULES = (
    ("DHQR401", "compiled-program xray introspection smoke failed",
     "xray"),
)


def run_xray_smoke() -> "list[Finding]":
    """Compile one tiny serve bucket with xray capture armed; every
    broken invariant is one DHQR401 finding (an infrastructure crash is
    one finding too — a smoke that cannot run must not pass)."""
    findings = []

    def bad(msg: str) -> None:
        findings.append(Finding("DHQR401", _PATH, 0, msg))

    try:
        from functools import partial

        from dhqr_tpu.obs import registry
        from dhqr_tpu.obs import xray as _xray
        from dhqr_tpu.serve.cache import ExecutableCache
        from dhqr_tpu.serve.engine import _lower_for_key, _plan_key
        from dhqr_tpu.utils.config import DHQRConfig, ServeConfig

        with _xray.captured() as store:
            cache = ExecutableCache(max_size=4)
            key, _bucket = _plan_key(
                "lstsq", 1, 24, 8, "float32",
                DHQRConfig(block_size=8), ServeConfig())
            cache.get_or_compile(key, partial(_lower_for_key, key))
            reports = store.reports()
            if not reports:
                bad("armed capture recorded no report for a compile "
                    "through ExecutableCache.get_or_compile — the "
                    "cache-side hook is disconnected")
                return findings
            report = reports[0]
            if not report.analytic_flops or report.analytic_flops <= 0:
                bad("XrayReport.analytic_flops missing for a serve "
                    "CacheKey — the obs.flops closed-form derivation "
                    "is disconnected")
            if report.measured is None and not report.measured_unavailable:
                bad("cost_analysis is None WITHOUT a reason — the "
                    "compat shim dropped its null-with-reason contract")
            row = report.to_json()
            for field in ("analytic_flops", "measured_cost_analysis",
                          "roofline_bound"):
                if field not in row:
                    bad(f"XrayReport.to_json() lost the {field!r} field "
                        "the artifact rows and the regress gate key on")
            if report.roofline_bound is None and not report.roofline_reason:
                bad("roofline_bound is None without a roofline_reason")
            # MFU machinery: a known chip must yield a number; this
            # backend (CPU in lint) must refuse with None, never crash.
            mfu = report.mfu(1.0)
            if report.peak_tflops is None and mfu is not None:
                bad("mfu computed without a known device peak")
            snap = registry().snapshot()
            if not snap.get("xray.captures"):
                bad("the metrics registry snapshot carries no armed "
                    "xray.captures — the xray provider is unregistered")
    except Exception as e:
        bad(f"xray smoke crashed: {type(e).__name__}: {e}")
    return findings
