"""dhqr-warden — lock-discipline & deadlock-order static analysis (DHQR6xx).

The serving tier is a genuinely multi-threaded system (scheduler worker
pools with respawn, the compile/quarantine cache path, the replica
router with mid-flight failover, the obs recorder ring, the weakref
metrics registry) and every race found before this pass existed was
caught by hand in review. This pass machine-checks the intra-process
lock discipline the same way the comms volumes (DHQR302) and cache keys
(DHQR503) already are:

* **DHQR601 — guarded-field discipline.** A *thread-shared class* (one
  that constructs a lock in ``__init__``) must declare every mutable
  container attribute with a ``# guarded by: <lock-attr>`` comment on
  its ``__init__`` assignment (or ``# guarded by: frozen`` when the
  binding and container membership never change after construction).
  Any read or write of a lock-guarded attribute outside a ``with
  self.<lock>`` block convicts — constructor scope is exempt, and
  private (``_``-prefixed) helpers inherit the locks held at EVERY one
  of their intra-class call sites (an entry-held fixpoint, so the
  ``*_locked`` helper convention needs no annotations). ``frozen``
  attributes convict only on post-``__init__`` writes.
* **DHQR602 — lock-order.** Every nested acquisition is extracted
  statically (lexical nesting plus one call level deep through
  self-method / same-module-function resolution) into the package-wide
  acquisition-order digraph. The committed edge list
  (``analysis/lock_order.json``, next to ``comms_contracts.json``)
  must match the extracted static edges BOTH ways — a new edge is a
  deliberate commit, a vanished edge is stale — and the committed
  union (static + runtime-witnessed sources) must be acyclic. The
  runtime witness gate (below) reports under the same rule id.
* **DHQR603 — blocking-while-locked.** ``Future.result()``, ``sleep``,
  ``flock``, the ``subprocess`` family, and the compile/dispatch entry
  points (``.compile()``, ``checked_dispatch``) invoked with a lock
  held lexically.
* **DHQR604 — unsynchronized publication.** A post-``__init__``
  assignment creating a NEW attribute on a thread-shared class outside
  any lock — the classic publish-without-a-fence shape.

The static graph is validated by execution (the DHQR306
traced-vs-measured two-sided pattern): with
:mod:`dhqr_tpu.utils.lockwitness` armed, a seeded multi-threaded
workload (two schedulers behind a router sharing a cache, tracing
armed) runs and the gate asserts every witnessed edge is present in
the committed graph, the witnessed graph is acyclic, and no held-set
violations occurred.

Scope of the self-scan: ``serve/``, ``obs/``, ``faults/``, ``armor/``,
``tune/db.py``, ``utils/lockwitness.py`` — the package's thread-shared
tier. Ships with an EMPTY baseline (the DHQR5xx precedent): every
finding is a real fix or a reasoned inline suppression.
"""

from __future__ import annotations

import ast
import json
import os
import re

from dhqr_tpu.analysis.findings import (
    Finding,
    apply_suppressions,
    parse_suppressions,
)

RULES = (
    ("DHQR601",
     "guarded-field discipline: '# guarded by:' declared and honored",
     "conc"),
    ("DHQR602",
     "lock-order: nested acquisitions committed, union graph acyclic",
     "conc"),
    ("DHQR603",
     "blocking call (result/sleep/flock/subprocess/compile) under a lock",
     "conc"),
    ("DHQR604",
     "unsynchronized publication: new attribute created outside any lock",
     "conc"),
)

#: The committed acquisition-order digraph (lives next to
#: comms_contracts.json so new edges are deliberate, reviewed commits).
EDGES_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "lock_order.json")
EDGES_SCHEMA = "dhqr-lock-order"
EDGES_VERSION = 1

#: The self-scan scope: the package's thread-shared tier.
SCOPE_DIRS = ("serve", "obs", "faults", "armor")
SCOPE_FILES = (os.path.join("tune", "db.py"),
               os.path.join("utils", "lockwitness.py"))

_GUARDED_RE = re.compile(r"#\s*guarded by:\s*([A-Za-z_]\w*)")

#: Lock-constructor spellings (raw primitives and the lockwitness seam).
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_SEAM_CTORS = ("make_lock", "make_rlock")
_CONDITION_CTORS = {"threading.Condition", "Condition"}

#: Container constructors whose attributes are forced-annotation
#: candidates in a thread-shared class.
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "OrderedDict",
                    "defaultdict", "Counter"}

#: Blocking-call matchers for DHQR603. ``.compile`` excludes the
#: ``re``/``ast`` modules (pattern compilation is not XLA compilation).
_SLEEP_NAMES = {"sleep", "_sleep", "_sleeper", "sleeper"}
_SUBPROCESS_NAMES = {"Popen", "check_call", "check_output", "call"}
_COMPILE_EXEMPT_VALUES = {"re", "ast", "sre_compile"}


def _dotted(node) -> str:
    """Best-effort dotted spelling of a Name/Attribute chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_self_attr(node) -> "str | None":
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _snippet(lines, lineno) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


def _guard_comment(lines, lineno) -> "str | None":
    """The ``# guarded by: X`` annotation for the assignment at
    ``lineno`` — on the line itself or the line directly above."""
    for ln in (lineno, lineno - 1):
        if not 1 <= ln <= len(lines):
            continue
        line = lines[ln - 1]
        if ln != lineno and not line.lstrip().startswith("#"):
            continue  # line-above form must be a comment-only line
        m = _GUARDED_RE.search(line)
        if m:
            return m.group(1)
    return None


def _is_lock_ctor(value) -> "str | None":
    """'lock' / 'condition' / None for an ``__init__`` assignment
    value. The lockwitness seam (make_lock/make_rlock) counts; its
    string argument, when literal, becomes the node name."""
    if not isinstance(value, ast.Call):
        return None
    dotted = _dotted(value.func)
    leaf = dotted.rsplit(".", 1)[-1]
    if dotted in _LOCK_CTORS or leaf in _SEAM_CTORS:
        return "lock"
    if dotted in _CONDITION_CTORS:
        return "condition"
    return None


def _seam_name(value) -> "str | None":
    """The literal name passed to make_lock/make_rlock, if any."""
    if isinstance(value, ast.Call) and value.args and \
            isinstance(value.args[0], ast.Constant) and \
            isinstance(value.args[0].value, str):
        return value.args[0].value
    return None


def _is_container_init(value) -> bool:
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.SetComp, ast.DictComp)):
        return True
    if isinstance(value, ast.BinOp):
        # [False] * k and friends
        return _is_container_init(value.left) or \
            _is_container_init(value.right)
    if isinstance(value, ast.Call):
        leaf = _dotted(value.func).rsplit(".", 1)[-1]
        return leaf in _CONTAINER_CTORS
    return False


class _ClassInfo:
    """Everything DHQR601/604 need to know about one class."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.lock_attrs: "dict[str, str]" = {}   # attr -> node name
        self.cond_alias: "dict[str, str]" = {}   # condition attr -> lock attr
        self.guarded: "dict[str, str]" = {}      # attr -> lock attr | frozen
        self.init_assigned: "set[str]" = set()
        self.candidates: "dict[str, int]" = {}   # unannotated attr -> line
        self.methods: "dict[str, ast.FunctionDef]" = {}

    @property
    def thread_shared(self) -> bool:
        return bool(self.lock_attrs)

    def lock_node(self, attr: str) -> "str | None":
        """The graph node a ``with self.<attr>`` acquisition maps to,
        through the Condition alias (``Condition(self._lock)`` shares
        its underlying lock's node)."""
        attr = self.cond_alias.get(attr, attr)
        return self.lock_attrs.get(attr)


def _harvest_class(cls: ast.ClassDef, lines) -> _ClassInfo:
    info = _ClassInfo(cls.name)
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[stmt.name] = stmt
    init = info.methods.get("__init__")
    if init is None:
        return info
    for node in ast.walk(init):
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for target in targets:
            attr = _is_self_attr(target)
            if attr is None:
                continue
            info.init_assigned.add(attr)
            if value is None:
                continue
            kind = _is_lock_ctor(value)
            if kind == "lock":
                info.lock_attrs[attr] = \
                    _seam_name(value) or f"{info.name}.{attr}"
            elif kind == "condition":
                arg = value.args[0] if value.args else None
                aliased = _is_self_attr(arg) if arg is not None else None
                if aliased is not None:
                    info.cond_alias[attr] = aliased
                else:
                    info.lock_attrs[attr] = f"{info.name}.{attr}"
            guard = _guard_comment(lines, node.lineno)
            if guard is not None:
                info.guarded[attr] = guard
            elif _is_container_init(value) and kind is None:
                info.candidates.setdefault(attr, node.lineno)
    return info


def _harvest_module_locks(tree: ast.Module, modbase: str) -> "dict[str, str]":
    """Module-global lock names -> graph node names."""
    locks = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            if _is_lock_ctor(stmt.value) in ("lock", "condition"):
                name = stmt.targets[0].id
                locks[name] = _seam_name(stmt.value) or \
                    f"{modbase}.{name}"
    return locks


class _FileScan:
    """One file's scan state: findings, extracted edges, call sites."""

    def __init__(self, path: str, text: str) -> None:
        self.path = path
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        self.modbase = os.path.splitext(os.path.basename(path))[0]
        if self.modbase == "__init__":      # armor/__init__.py -> "armor"
            self.modbase = os.path.basename(os.path.dirname(path))
        self.module_locks = _harvest_module_locks(self.tree, self.modbase)
        self.classes = {
            c.name: _harvest_class(c, self.lines)
            for c in self.tree.body if isinstance(c, ast.ClassDef)
        }
        self.findings: "list[Finding]" = []
        # (from, to) -> "path:line" of the acquiring site
        self.edges: "dict[tuple[str, str], str]" = {}
        # Deferred DHQR601 convictions: (cls, method, needed lock node,
        # line, message) — filtered by the entry-held fixpoint.
        self._deferred: list = []
        # (cls, callee) -> list of (caller_method, frozenset(held))
        self._call_sites: "dict[tuple[str, str], list]" = {}
        # Per-function direct acquisitions, for one-call-level edges:
        # key ("C", "m") or (None, "f") -> {(node, line), ...}
        self._fn_acquires: "dict[tuple, set]" = {}

    # ---------------------------------------------------------- resolution

    def _resolve_acquisition(self, expr, cls: "_ClassInfo | None"
                             ) -> "str | None":
        """The graph node a with-item acquires, or None."""
        attr = _is_self_attr(expr)
        if attr is not None and cls is not None:
            return cls.lock_node(attr)
        if isinstance(expr, ast.Name):
            return self.module_locks.get(expr.id)
        if isinstance(expr, ast.Call):
            func = expr.func
            if isinstance(func, ast.Attribute) and \
                    func.attr == "_file_lock":
                owner = _is_self_attr(func)
                if owner is not None and cls is not None:
                    return f"{cls.name}._file_lock"
                if isinstance(func.value, ast.Name):
                    return f"{func.value.id}._file_lock"
        return None

    # ------------------------------------------------------------- findings

    def _f(self, rule, line, message):
        self.findings.append(Finding(
            rule, self.path, line, message,
            snippet=_snippet(self.lines, line)))

    # ------------------------------------------------------------- walking

    def scan(self) -> None:
        for info in self.classes.values():
            if not info.thread_shared:
                continue
            for attr, line in sorted(info.candidates.items(),
                                     key=lambda kv: kv[1]):
                # Annotated on ANOTHER __init__ assignment (e.g. the
                # empty default before a conditional re-assignment).
                if attr in info.guarded:
                    continue
                self._f("DHQR601", line,
                        f"mutable attribute 'self.{attr}' of "
                        f"thread-shared class {info.name} has no "
                        "'# guarded by: <lock-attr>' (or 'frozen') "
                        "annotation")
        # Pre-pass: every function's direct acquisitions (for the
        # one-call-level DHQR602 resolution).
        for cls_name, fn in self._iter_functions():
            cls = self.classes.get(cls_name) if cls_name else None
            acquires = set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        resolved = self._resolve_acquisition(
                            item.context_expr, cls)
                        if resolved:
                            acquires.add((resolved, item.context_expr
                                          .lineno))
            self._fn_acquires[(cls_name, fn.name)] = acquires
        # Main walk.
        for cls_name, fn in self._iter_functions():
            cls = self.classes.get(cls_name) if cls_name else None
            held = frozenset()
            for stmt in fn.body:
                self._walk_stmt(stmt, held, cls, fn.name)
        self._resolve_entry_held()

    def _iter_functions(self):
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield None, stmt
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        yield stmt.name, sub

    def _walk_stmt(self, stmt, held, cls, method) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                self._check_expr(item.context_expr, held, cls, method)
                node = self._resolve_acquisition(item.context_expr, cls)
                if node is not None:
                    site = f"{self.path}:{item.context_expr.lineno}"
                    for held_node in held:
                        self.edges.setdefault((held_node, node), site)
                    acquired.append(node)
            new_held = held | frozenset(acquired)
            for sub in stmt.body:
                self._walk_stmt(sub, new_held, cls, method)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def runs later, on whatever thread calls it —
            # conservatively an unlocked scope (its own with-blocks
            # still track).
            for sub in stmt.body:
                self._walk_stmt(sub, frozenset(), cls, method)
            return
        for field, value in ast.iter_fields(stmt):
            if isinstance(value, list):
                for child in value:
                    if isinstance(child, ast.stmt):
                        self._walk_stmt(child, held, cls, method)
                    elif isinstance(child, ast.excepthandler):
                        for sub in child.body:
                            self._walk_stmt(sub, held, cls, method)
                    elif isinstance(child, ast.expr):
                        self._check_expr(child, held, cls, method)
            elif isinstance(value, ast.expr):
                self._check_expr(value, held, cls, method)

    def _check_expr(self, expr, held, cls, method) -> None:
        lambdas = []
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                lambdas.append(node.body)
                continue
            stack.extend(ast.iter_child_nodes(node))
            if isinstance(node, ast.Attribute):
                self._check_attribute(node, held, cls, method)
            elif isinstance(node, ast.Call):
                self._check_call(node, held, cls, method)
        for body in lambdas:
            self._check_expr(body, frozenset(), cls, method)

    def _check_attribute(self, node, held, cls, method) -> None:
        if cls is None or not cls.thread_shared or method == "__init__":
            return
        attr = _is_self_attr(node)
        if attr is None:
            return
        is_write = isinstance(node.ctx, (ast.Store, ast.Del))
        guard = cls.guarded.get(attr)
        if guard == "frozen":
            if is_write:
                self._f("DHQR601", node.lineno,
                        f"write to frozen attribute 'self.{attr}' of "
                        f"{cls.name} outside __init__ (declared "
                        "'# guarded by: frozen')")
            return
        if guard is not None:
            needed = cls.lock_node(guard) or f"{cls.name}.{guard}"
            if needed not in held:
                access = "write to" if is_write else "read of"
                self._deferred.append((
                    cls.name, method, needed, node.lineno,
                    f"{access} 'self.{attr}' (guarded by "
                    f"'{guard}') outside 'with self.{guard}' in "
                    f"{cls.name}.{method}"))
            return
        if is_write and attr not in cls.init_assigned and not held:
            self._f("DHQR604", node.lineno,
                    f"post-__init__ publication of new attribute "
                    f"'self.{attr}' on thread-shared class "
                    f"{cls.name} outside any lock")

    def _check_call(self, node, held, cls, method) -> None:
        # Intra-class call sites (entry-held fixpoint input) and
        # one-call-level DHQR602 edges.
        callee_key = None
        attr = _is_self_attr(node.func)
        if attr is not None and cls is not None and \
                attr in cls.methods:
            callee_key = (cls.name, attr)
            self._call_sites.setdefault(callee_key, []).append(
                (method, held))
        elif isinstance(node.func, ast.Name):
            key = (None, node.func.id)
            if key in self._fn_acquires:
                callee_key = key
        if held and callee_key is not None:
            for acquired, line in self._fn_acquires.get(callee_key, ()):
                site = f"{self.path}:{node.lineno}"
                for held_node in held:
                    if held_node != acquired:
                        self.edges.setdefault((held_node, acquired),
                                              site)
        if held:
            self._check_blocking(node, held)

    def _check_blocking(self, node, held) -> None:
        func = node.func
        dotted = _dotted(func)
        leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
        blocked = None
        if isinstance(func, ast.Attribute) and func.attr == "result":
            blocked = "Future.result()"
        elif leaf in _SLEEP_NAMES or (isinstance(func, ast.Attribute)
                                      and func.attr in _SLEEP_NAMES):
            blocked = "sleep"
        elif leaf == "flock" or (isinstance(func, ast.Attribute)
                                 and func.attr == "flock"):
            blocked = "flock"
        elif dotted.startswith("subprocess.") or \
                leaf in _SUBPROCESS_NAMES:
            blocked = "subprocess"
        elif isinstance(func, ast.Attribute) and func.attr == "compile":
            value_root = _dotted(func.value).split(".", 1)[0]
            if value_root not in _COMPILE_EXEMPT_VALUES:
                blocked = "compile()"
        elif leaf == "checked_dispatch":
            blocked = "checked_dispatch"
        if blocked is not None:
            self._f("DHQR603", node.lineno,
                    f"blocking call ({blocked}) while holding "
                    f"{', '.join(sorted(held))}")

    # ------------------------------------------------------ entry-held

    def _resolve_entry_held(self) -> None:
        """Fixpoint over private methods: a ``_helper`` inherits the
        intersection of the lock sets held at every intra-class call
        site (callers' own entry-held included), so the ``*_locked``
        convention needs no annotation. Deferred DHQR601 convictions
        whose needed lock is entry-held are dropped."""
        entry: "dict[tuple[str, str], frozenset]" = {}
        for info in self.classes.values():
            universe = frozenset(info.lock_attrs.values())
            for name in info.methods:
                if name.startswith("_") and not name.startswith("__"):
                    sites = self._call_sites.get((info.name, name))
                    entry[(info.name, name)] = \
                        universe if sites else frozenset()
        for _ in range(len(entry) + 1):
            changed = False
            for (cls_name, name), current in entry.items():
                sites = self._call_sites.get((cls_name, name), ())
                if not sites:
                    continue
                new = None
                for caller, held in sites:
                    # Locks held at the call site lexically, plus
                    # whatever the CALLER itself is entry-held under —
                    # so helper-calls-helper chains resolve (e.g. a
                    # `_locked` helper calling a second one).
                    site_held = frozenset(held) | entry.get(
                        (cls_name, caller), frozenset())
                    new = site_held if new is None else (new & site_held)
                new = new or frozenset()
                if new != current:
                    entry[(cls_name, name)] = new
                    changed = True
            if not changed:
                break
        for cls_name, method, needed, line, message in self._deferred:
            if needed in entry.get((cls_name, method), frozenset()):
                continue
            self._f("DHQR601", line, message)


def _scan_text(text: str, path: str):
    """(findings, edges) for one file's source. Findings come back
    suppression-applied (``# dhqr: ignore[DHQR60x] reason``)."""
    scan = _FileScan(path, text)
    scan.scan()
    scan.findings.sort(key=lambda f: (f.line, f.rule))
    suppressions = parse_suppressions(scan.lines)
    return apply_suppressions(scan.findings, suppressions), scan.edges


def scan_concurrency_source(text: str, path: str) -> "list[Finding]":
    """Static DHQR6xx findings for one source text (fixture tests; the
    package-level graph comparison and witness gate live in
    :func:`run_concurrency_pass`)."""
    findings, _edges = _scan_text(text, path)
    return findings


# ---------------------------------------------------------------------------
# Package-level graph: extraction, committed comparison, cycles.

def _scope_files(pkg_root: str) -> "list[str]":
    out = []
    for sub in SCOPE_DIRS:
        base = os.path.join(pkg_root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    for rel in SCOPE_FILES:
        path = os.path.join(pkg_root, rel)
        if os.path.exists(path):
            out.append(path)
    return sorted(out)


def load_edges(path: "str | None" = None) -> "list[dict]":
    """The committed lock-order edge list (raises on a malformed file —
    the graph is a contract, not telemetry)."""
    path = path or EDGES_PATH
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    if raw.get("schema") != EDGES_SCHEMA or \
            raw.get("version") != EDGES_VERSION:
        raise ValueError(f"{path}: not a {EDGES_SCHEMA} v{EDGES_VERSION} "
                         "file")
    edges = raw.get("edges")
    if not isinstance(edges, list):
        raise ValueError(f"{path}: 'edges' must be a list")
    for edge in edges:
        if not isinstance(edge, dict) or not edge.get("from") or \
                not edge.get("to") or edge.get("source") not in (
                    "static", "runtime"):
            raise ValueError(f"{path}: malformed edge {edge!r}")
    return edges


def find_cycle(edges) -> "list[str] | None":
    """One cycle (as a node path) in the digraph, or None. Iterative
    DFS with colors; deterministic over sorted adjacency."""
    adj: "dict[str, list[str]]" = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    for k in adj:
        adj[k].sort()
    color: "dict[str, int]" = {}
    parent: "dict[str, str]" = {}
    for root in sorted(adj):
        if color.get(root):
            continue
        stack = [(root, iter(adj[root]))]
        color[root] = 1
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                state = color.get(nxt, 0)
                if state == 0:
                    color[nxt] = 1
                    parent[nxt] = node
                    stack.append((nxt, iter(adj[nxt])))
                    advanced = True
                    break
                if state == 1:
                    path = [nxt, node]
                    cur = node
                    while cur != nxt and cur in parent:
                        cur = parent[cur]
                        path.append(cur)
                    path.reverse()
                    return path
            if not advanced:
                color[node] = 2
                stack.pop()
        continue
    return None


def _graph_findings(extracted: "dict[tuple, str]", committed,
                    edges_rel: str) -> "list[Finding]":
    findings = []
    committed_static = {(e["from"], e["to"]) for e in committed
                        if e["source"] == "static"}
    committed_all = {(e["from"], e["to"]) for e in committed}
    for (a, b), site in sorted(extracted.items()):
        if (a, b) not in committed_static:
            path, _, line = site.rpartition(":")
            findings.append(Finding(
                "DHQR602", path, int(line),
                f"uncommitted lock-order edge {a} -> {b}: add it to "
                f"analysis/lock_order.json deliberately (source "
                f"\"static\") or restructure the nesting",
                snippet=f"{a} -> {b}"))
    for (a, b) in sorted(committed_static - set(extracted)):
        findings.append(Finding(
            "DHQR602", edges_rel, 0,
            f"stale committed static edge {a} -> {b}: no longer "
            "extracted from the source — remove it",
            snippet=f"{a} -> {b}"))
    cycle = find_cycle(committed_all | set(extracted))
    if cycle is not None:
        findings.append(Finding(
            "DHQR602", edges_rel, 0,
            "lock-order cycle (deadlock hazard): "
            + " -> ".join(cycle),
            snippet=" -> ".join(cycle)))
    return findings


# ---------------------------------------------------------------------------
# Runtime witness gate.

def _witness_workload(requests: int = 8, seed: int = 0,
                      m: int = 48, n: int = 16,
                      submit_threads: int = 2,
                      arm_faults: bool = False,
                      kill_replica: bool = False):
    """One seeded multi-threaded serving burst under an armed lock
    witness: two real schedulers behind a Router sharing one
    ExecutableCache, tracing armed (the recorder lock is exercised
    under the scheduler lock), concurrent submitters, drain, shutdown.
    Returns the witness. ``arm_faults`` configures a never-firing
    fault site so the harness lock is visited on the compile path;
    ``kill_replica`` exercises the mid-flight failover relay."""
    import threading

    import numpy as np

    from dhqr_tpu.faults import harness as _faults
    from dhqr_tpu.obs import trace as _trace
    from dhqr_tpu.serve.cache import ExecutableCache
    from dhqr_tpu.serve.router import Router
    from dhqr_tpu.serve.scheduler import AsyncScheduler
    from dhqr_tpu.utils import lockwitness
    from dhqr_tpu.utils.config import (
        FaultConfig,
        FleetConfig,
        ObsConfig,
        ServeConfig,
    )

    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((m,)), jnp.float32)
    scfg = ServeConfig(min_dim=16, ratio=1.5, max_batch=4, cache_size=8)

    def _burst(witness):
        cache = ExecutableCache(max_size=8)
        reps = [AsyncScheduler(serve_config=scfg, cache=cache,
                               block_size=8, workers=1)
                for _ in range(2)]
        router = Router(replicas=reps,
                        fleet=FleetConfig(replicas=2, failovers=1))
        futs = []
        errors = []

        def submit_stream(count):
            try:
                for _ in range(count):
                    futs.append(router.submit("lstsq", A, b,
                                              deadline=60.0))
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=submit_stream,
                                    args=(requests // submit_threads,),
                                    name=f"witness-submit-{i}")
                   for i in range(submit_threads)]
        for t in threads:
            t.start()
        if kill_replica:
            router.kill(0)
        for t in threads:
            t.join()
        for rep in reps:
            rep.drain(timeout=60.0)
        results = [f.result(timeout=60.0) for f in list(futs)]
        router.shutdown()
        if errors:
            raise errors[0]
        return results

    with lockwitness.witnessing() as witness:
        with _trace.observed(ObsConfig(enabled=True)):
            if arm_faults:
                # prob-0 site: the harness lock is VISITED on the
                # compile path (witnessing the cache->harness edge)
                # but never fires.
                with _faults.injected(FaultConfig(
                        seed=seed,
                        sites=(("serve.compile", 0.0, None),))):
                    _burst(witness)
            else:
                _burst(witness)
    return witness


def _witness_findings(witness, committed, edges_rel: str
                      ) -> "list[Finding]":
    findings = []
    known = {(e["from"], e["to"]) for e in committed}
    witnessed = witness.edges()
    for (a, b) in witnessed:
        if (a, b) not in known:
            findings.append(Finding(
                "DHQR602", edges_rel, 0,
                f"witnessed lock-order edge {a} -> {b} absent from the "
                "committed graph: the static pass (or the committed "
                "runtime edge list) is missing a real nesting",
                snippet=f"{a} -> {b}"))
    for violation in witness.violations():
        findings.append(Finding(
            "DHQR602", edges_rel, 0,
            f"lock-witness held-set violation: {violation}",
            snippet=str(violation)))
    cycle = find_cycle(witnessed)
    if cycle is not None:
        findings.append(Finding(
            "DHQR602", edges_rel, 0,
            "witnessed acquisition-order graph is cyclic: "
            + " -> ".join(cycle),
            snippet=" -> ".join(cycle)))
    return findings


def run_concurrency_pass(witness: bool = True,
                         edges_path: "str | None" = None
                         ) -> "list[Finding]":
    """The full DHQR6xx pass: static self-scan over the thread-shared
    tier, two-way committed-graph comparison, acyclicity, and (unless
    ``witness=False`` — the ``--fast`` twin) the runtime lock-witness
    gate over a seeded multi-threaded serving burst."""
    import dhqr_tpu

    pkg_root = os.path.dirname(os.path.abspath(dhqr_tpu.__file__))
    repo_root = os.path.dirname(pkg_root)
    edges_path = edges_path or EDGES_PATH
    edges_rel = os.path.relpath(edges_path, repo_root)
    findings: "list[Finding]" = []
    extracted: "dict[tuple, str]" = {}
    for path in _scope_files(pkg_root):
        with open(path, "r", encoding="utf-8") as fh:
            text = fh.read()
        rel = os.path.relpath(path, repo_root)
        file_findings, file_edges = _scan_text(text, rel)
        findings.extend(file_findings)
        for edge, site in file_edges.items():
            extracted.setdefault(edge, site)
    try:
        committed = load_edges(edges_path)
    except (OSError, ValueError) as e:
        findings.append(Finding(
            "DHQR602", edges_rel, 0,
            f"committed lock-order graph unreadable: {e}",
            snippet=""))
        return findings
    findings.extend(_graph_findings(extracted, committed, edges_rel))
    if witness:
        w = _witness_workload(arm_faults=True)
        findings.extend(_witness_findings(w, committed, edges_rel))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
