"""Pass 2 — jaxpr sanitizer over the public entry points.

Abstractly traces every public entry point (single-device tiers, plus
the sharded engines under a 1-device mesh) for each precision-policy
preset, and walks the closed jaxpr — including every sub-jaxpr riding in
eqn params (pjit, scan, shard_map, custom_jvp, ...) — asserting three
program-representation invariants:

* **DHQR101** — no float64/complex128 intermediate from float32 inputs.
  Traced under ``jax.experimental.enable_x64()`` (a thread-local
  context, not process-global mutation) so an accidental promotion —
  a bare python-float ladder, an np scalar, an explicit astype — is
  visible even in processes that run with x64 off, where jax would mask
  the leak by clamping. On TPU an f64 intermediate is emulated at >10x
  cost; on CPU it silently doubles memory traffic.
* **DHQR102** — no ``pure_callback`` / ``io_callback`` / other host
  callbacks: a callback is a host round-trip per execution, and its
  executable is not safely deserializable across processes (the
  interpret-mode Pallas cache incident, ops/blocked._pallas_cache_guard).
* **DHQR103** — every collective's axis name resolves against the mesh
  the entry point was traced under (and no collective at all in
  mesh-free programs).

Trace failures are findings too (**DHQR104**), not crashes: a policy
preset that no longer traces is exactly the regression this pass exists
to catch. Tracing is abstract — nothing compiles, nothing executes, so
the pass is safe to run even where backend bring-up is fragile (the
CLI forces the CPU backend first; see ``_ensure_cpu_backend``).
"""

from __future__ import annotations

from dhqr_tpu.analysis.findings import Finding

# Shapes small enough to trace in milliseconds but large enough to
# exercise the blocked/panelled paths (two 4-wide panels per 8 columns).
_M, _N, _NB = 16, 8, 4

# One genuinely tall-skinny case: m/n = 8 is past the autotuner's
# cholqr2 gate (tune/search.py: cholqr2 at m/n >= 8) and the serve
# bucketing's tall regime, so the cholqr2/tsqr plan routes from round 9
# are traced at an aspect ratio that actually selects them — the m/n = 2
# default shape never would.
_M_TALL, _N_TALL = 64, 8

_F64_DTYPES = ("float64", "complex128")


def _ensure_cpu_backend() -> None:
    """Pin the CPU backend before any device touch. Some hosts pin a
    remote TPU plugin via sitecustomize (JAX_PLATFORMS in the env LOSES —
    tests/conftest.py has the story), and a wedged relay hangs at
    backend_init; an abstract-tracing lint gate must never take that
    risk. Set DHQR_LINT_KEEP_PLATFORM=1 to trace on the ambient backend.
    """
    import os

    if os.environ.get("DHQR_LINT_KEEP_PLATFORM") == "1":
        return
    import jax

    # dhqr: ignore[DHQR003] lint CLI/test entry owns its process: abstract tracing must not init a remote TPU backend
    jax.config.update("jax_platforms", "cpu")


def sub_jaxprs(val):
    """Yield every (open) jaxpr held by one eqn-param value — a
    ClosedJaxpr/Jaxpr, or any list/tuple/dict nesting of them. Shared
    with the comms pass (comms_pass.collect_comms)."""
    from jax import core

    if isinstance(val, core.ClosedJaxpr):
        yield val.jaxpr
    elif isinstance(val, core.Jaxpr):
        yield val
    elif isinstance(val, (list, tuple)):
        for v in val:
            yield from sub_jaxprs(v)
    elif isinstance(val, dict):
        for v in val.values():
            yield from sub_jaxprs(v)


def iter_jaxprs(jaxpr):
    """Yield ``jaxpr`` and every sub-jaxpr reachable through eqn params."""
    stack = [jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for val in eqn.params.values():
                stack.extend(sub_jaxprs(val))


def _collect_axis_names(params) -> "set[str]":
    """Axis names named by a collective eqn's params (axes/axis_name,
    string or tuple-of-strings)."""
    out = set()
    for key in ("axes", "axis_name"):
        val = params.get(key)
        if val is None:
            continue
        if isinstance(val, (tuple, list)):
            out.update(str(v) for v in val)
        else:
            out.add(str(val))
    return out


_COLLECTIVE_PRIMS = {
    "psum", "pmin", "pmax", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "axis_index",
    "pbroadcast",
}


def check_jaxpr(closed_jaxpr, label: str, mesh_axes=()) -> "list[Finding]":
    """Sanitize one traced program; ``label`` names the entry point in
    findings (rendered as the finding's path)."""
    findings = []
    mesh_axes = set(mesh_axes)
    seen_f64 = set()
    for jaxpr in iter_jaxprs(closed_jaxpr.jaxpr):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                dtype = str(getattr(aval, "dtype", ""))
                if dtype in _F64_DTYPES and (prim, dtype) not in seen_f64:
                    seen_f64.add((prim, dtype))
                    findings.append(Finding(
                        "DHQR101", label, 0,
                        f"{dtype} intermediate from f32 inputs "
                        f"(primitive '{prim}'): f64 is emulated >10x slow "
                        "on TPU — find and remove the promotion",
                        snippet=f"{prim}->{dtype}",
                    ))
            if "callback" in prim:
                findings.append(Finding(
                    "DHQR102", label, 0,
                    f"host callback primitive '{prim}' in the traced "
                    "program: one host round-trip per execution, and the "
                    "executable cannot be cached across processes",
                    snippet=prim,
                ))
            if prim in _COLLECTIVE_PRIMS:
                for axis in _collect_axis_names(eqn.params):
                    if axis not in mesh_axes:
                        findings.append(Finding(
                            "DHQR103", label, 0,
                            f"collective '{prim}' over axis {axis!r} "
                            f"which the mesh does not declare "
                            f"(mesh axes: {sorted(mesh_axes) or 'none'})",
                            snippet=f"{prim}[{axis}]",
                        ))
    return findings


def _entry_points(preset: str, pol):
    """(label, thunk, mesh_axes) triples: thunk returns a closed jaxpr.

    Inputs are f32 and tiny; every thunk traces abstractly (make_jaxpr) —
    no compile, no execution, no device transfer of real data.
    """
    import jax
    import jax.numpy as jnp

    import dhqr_tpu
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq
    from dhqr_tpu.parallel.sharded_qr import (
        sharded_blocked_qr,
        sharded_householder_qr,
    )
    from dhqr_tpu.parallel.sharded_tsqr import row_mesh, sharded_tsqr_lstsq

    A = jnp.zeros((_M, _N), jnp.float32)
    b = jnp.zeros((_M,), jnp.float32)
    cmesh = column_mesh(1)
    rmesh = row_mesh(1)

    def jx(fn, *args):
        return lambda: jax.make_jaxpr(fn)(*args)

    yield (f"qr[{preset}]",
           jx(lambda A: dhqr_tpu.qr(A, policy=preset), A), ())
    yield (f"lstsq[{preset}]",
           jx(lambda A, b: dhqr_tpu.lstsq(A, b, policy=preset), A, b), ())
    # The tuned dispatch path (round 9): lstsq with an explicit Plan
    # exercises plan resolution + apply_plan_to_config under every
    # policy preset — the exact code the plan DB routes production calls
    # through. An explicit Plan (not "auto") keeps the trace abstract:
    # no DB read, no timing, deterministic across hosts. The recursive
    # panel interior is the plan-only knob with the most distinct
    # program structure, so regressions in the tuned route surface here.
    from dhqr_tpu.tune import Plan

    yield (f"lstsq_plan[{preset}]",
           jx(lambda A, b: dhqr_tpu.lstsq(
               A, b, plan=Plan(block_size=_NB, panel_impl="recursive"),
               policy=preset), A, b), ())
    if preset == "accurate":
        # Alt-engine plan routing is policy-free by pruning rule 5 —
        # trace it once, on the tall-skinny problem whose aspect ratio
        # the plan gates actually select (see _M_TALL above).
        At = jnp.zeros((_M_TALL, _N_TALL), jnp.float32)
        bt = jnp.zeros((_M_TALL,), jnp.float32)
        yield ("lstsq_tall",
               jx(lambda A, b: dhqr_tpu.lstsq(A, b), At, bt), ())
        yield ("lstsq_plan_tsqr",
               jx(lambda A, b: dhqr_tpu.lstsq(
                   A, b, plan=Plan(engine="tsqr")), At, bt), ())
        yield ("lstsq_plan_cholqr2",
               jx(lambda A, b: dhqr_tpu.lstsq(
                   A, b, plan=Plan(engine="cholqr2")), At, bt), ())
    yield (f"tsqr_r[{preset}]",
           jx(lambda A: dhqr_tpu.tsqr_r(A, n_blocks=2, policy=preset), A),
           ())
    yield (f"cholesky_qr2[{preset}]",
           jx(lambda A: dhqr_tpu.cholesky_qr2(A, policy=preset), A), ())
    # The serving tier's bucket dispatch unit (serve/engine.py): the same
    # traced program batched_lstsq compiles per bucket, via the engine's
    # own config/policy resolution — a policy preset that stops tracing
    # through the vmapped path is a DHQR104 regression like any other.
    from dhqr_tpu.serve.engine import bucket_program

    As = jnp.zeros((2, _M, _N), jnp.float32)
    bs = jnp.zeros((2, _M), jnp.float32)
    yield (f"batched_lstsq[{preset}]",
           jx(bucket_program("lstsq", block_size=_NB, policy=preset),
              As, bs), ())
    # The async scheduler's dispatch path (round 11): must be the SAME
    # bucket_program the comms pass contracts — the scheduler owns no
    # second lowering/key scheme. The thunk asserts function-identity
    # parity BEFORE tracing, so a drift (someone giving the scheduler
    # its own _plan_key or dispatch loop) surfaces as a DHQR104 finding
    # on this entry rather than as silent steady-state recompiles.
    from dhqr_tpu.serve import engine as _serve_engine
    from dhqr_tpu.serve import scheduler as _serve_sched

    def async_thunk():
        # The drift this guards against is scheduler.py growing its OWN
        # lowering helpers (a module-level _plan_key / _dispatch_groups /
        # bucket_program shadowing the engine's), so check the
        # scheduler's namespace — comparing engine attributes to
        # themselves through the module alias would be a tautology.
        shadowed = {"_plan_key", "_dispatch_groups", "bucket_program"} \
            & set(vars(_serve_sched))
        assert _serve_sched._engine is _serve_engine and not shadowed, (
            "async scheduler dispatch path diverged from serve.engine "
            f"(shadowed: {sorted(shadowed)}): cache-key parity (one "
            "_plan_key, one _dispatch_groups) is the zero-recompile "
            "contract")
        return jax.make_jaxpr(_serve_sched.dispatch_program(
            "lstsq", block_size=_NB, policy=preset))(As, bs)

    yield (f"async_lstsq[{preset}]", async_thunk, ())
    # The round-17 solver families, BOTH traced under every preset
    # (the ISSUE-13 acceptance bar): the sketched engine through its
    # ops-level entry (operator drawn host-side at trace time — the
    # trace stays abstract, nothing executes) and through the serve
    # tier's "sketch" bucket program; the updatable-QR family through
    # its exposed solve/update program builders (an UpdatableQR
    # CONSTRUCTION would execute a guarded factorization — the program
    # builders exist precisely so this pass never has to).
    from dhqr_tpu.solvers.sketch import sketched_lstsq as _sketched
    from dhqr_tpu.solvers.update import solve_program, update_program

    At_ = jnp.zeros((_M_TALL, _N_TALL), jnp.float32)
    bt_ = jnp.zeros((_M_TALL,), jnp.float32)
    yield (f"sketched_lstsq[{preset}]",
           jx(lambda A, b: _sketched(A, b, policy=preset), At_, bt_), ())
    Ask = jnp.zeros((2, _M_TALL, _N_TALL), jnp.float32)
    bsk = jnp.zeros((2, _M_TALL), jnp.float32)
    yield (f"batched_sketch[{preset}]",
           jx(bucket_program("sketch", policy=preset), Ask, bsk), ())
    Gu = jnp.zeros((_N_TALL, _N_TALL), jnp.float32)
    uu_ = jnp.zeros((_M_TALL,), jnp.float32)
    vv_ = jnp.zeros((_N_TALL,), jnp.float32)
    sg_ = jnp.zeros((), jnp.float32)
    yield (f"update_solve[{preset}]",
           jx(solve_program(refine=max(1, pol.refine),
                            precision=pol.panel), At_, Gu, bt_), ())
    yield (f"update_rank1[{preset}]",
           jx(update_program(), At_, Gu, Gu, uu_, vv_, sg_), ())
    yield (f"sharded_blocked_qr[{preset}]",
           jx(lambda A: sharded_blocked_qr(A, cmesh, block_size=_NB,
                                           policy=preset), A),
           ("cols",))
    # The remaining sharded engines take the classic precision knobs, not
    # a policy object — trace them at the preset's panel precision.
    yield (f"sharded_householder_qr[{preset}]",
           jx(lambda A: sharded_householder_qr(A, cmesh,
                                               precision=pol.panel), A),
           ("cols",))
    yield (f"lstsq_mesh[{preset}]",
           jx(lambda A, b: dhqr_tpu.lstsq(A, b, mesh=cmesh,
                                          block_size=_NB, policy=preset),
              A, b),
           ("cols",))
    yield (f"sharded_tsqr_lstsq[{preset}]",
           jx(lambda A, b: sharded_tsqr_lstsq(A, b, rmesh, block_size=_NB,
                                              precision=pol.panel), A, b),
           ("rows",))
    yield (f"sharded_cholqr_lstsq[{preset}]",
           jx(lambda A, b: sharded_cholqr_lstsq(A, b, rmesh,
                                                precision=pol.panel),
              A, b),
           ("rows",))
    # Two-tier pod routes (round 20, dhqr-pod): the hierarchical
    # schedules trace over BOTH axes of a ("dcn", "ici") mesh, and the
    # dcn:* rungs add compressed DCN legs — sanitize each once (the
    # schedule is preset-independent; the rungs enumerate here so a
    # mode that stops tracing fails DHQR104 and a collective escaping
    # the declared axes fails DHQR103). Needs a 2x2 factorization —
    # skipped quietly on narrower backends (the comms audit's pod
    # matrix covers those via its own subprocess vehicle).
    if preset == "accurate" and len(jax.devices()) >= 4:
        from dhqr_tpu.parallel.mesh import pod_mesh

        pmesh, _taxes = pod_mesh(4, topo="2x2")
        yield ("sharded_blocked_qr_pod",
               jx(lambda A: sharded_blocked_qr(A, pmesh, block_size=_NB),
                  A),
               ("dcn", "ici"))
        for _mode in ("dcn:bf16", "dcn:int8"):
            yield (f"lstsq_pod[{_mode}]",
                   jx(lambda A, b, _m=_mode: dhqr_tpu.lstsq(
                       A, b, mesh=pmesh, block_size=_NB, comms=_m),
                      A, b),
                   ("dcn", "ici"))


def run_jaxpr_pass(presets=None) -> "list[Finding]":
    """Trace and sanitize every entry point for every policy preset."""
    _ensure_cpu_backend()
    import jax

    from dhqr_tpu.precision import PRECISION_POLICIES

    names = list(presets) if presets is not None \
        else list(PRECISION_POLICIES)
    findings = []
    with jax.experimental.enable_x64():
        for preset in names:
            pol = PRECISION_POLICIES[preset]
            for label, thunk, mesh_axes in _entry_points(preset, pol):
                try:
                    closed = thunk()
                except Exception as e:  # a preset that fails to trace IS
                    findings.append(Finding(   # the regression (DHQR104)
                        "DHQR104", label, 0,
                        f"entry point failed to trace: "
                        f"{type(e).__name__}: {e}",
                    ))
                    continue
                findings.extend(check_jaxpr(closed, label, mesh_axes))
    return findings
