"""Pass 2 — jaxpr sanitizer over the public entry points.

Abstractly traces every public entry point (single-device tiers, plus
the sharded engines under a 1-device mesh) for each precision-policy
preset, and walks the closed jaxpr — including every sub-jaxpr riding in
eqn params (pjit, scan, shard_map, custom_jvp, ...) — asserting three
program-representation invariants:

* **DHQR101** — no float64/complex128 intermediate from float32 inputs.
  Traced under ``jax.experimental.enable_x64()`` (a thread-local
  context, not process-global mutation) so an accidental promotion —
  a bare python-float ladder, an np scalar, an explicit astype — is
  visible even in processes that run with x64 off, where jax would mask
  the leak by clamping. On TPU an f64 intermediate is emulated at >10x
  cost; on CPU it silently doubles memory traffic.
* **DHQR102** — no ``pure_callback`` / ``io_callback`` / other host
  callbacks: a callback is a host round-trip per execution, and its
  executable is not safely deserializable across processes (the
  interpret-mode Pallas cache incident, ops/blocked._pallas_cache_guard).
* **DHQR103** — every collective's axis name resolves against the mesh
  the entry point was traced under (and no collective at all in
  mesh-free programs).

Trace failures are findings too (**DHQR104**), not crashes: a policy
preset that no longer traces is exactly the regression this pass exists
to catch. Tracing is abstract — nothing compiles, nothing executes, so
the pass is safe to run even where backend bring-up is fragile (the
CLI forces the CPU backend first; see ``_ensure_cpu_backend``).
"""

from __future__ import annotations

from dhqr_tpu.analysis.findings import Finding

#: This pass's rule-catalogue rows (assembled by analysis/cli.py —
#: round 21 retired the CLI's hand-kept copy).
RULES = (
    ("DHQR101", "f64/c128 intermediate traced from f32 inputs", "jaxpr"),
    ("DHQR102", "host callback primitive in a traced program", "jaxpr"),
    ("DHQR103", "collective axis name unresolvable against the mesh",
     "jaxpr"),
    ("DHQR104", "entry point failed to trace under a policy preset",
     "jaxpr"),
)

# Shapes small enough to trace in milliseconds but large enough to
# exercise the blocked/panelled paths (two 4-wide panels per 8 columns).
_M, _N, _NB = 16, 8, 4

# One genuinely tall-skinny case: m/n = 8 is past the autotuner's
# cholqr2 gate (tune/search.py: cholqr2 at m/n >= 8) and the serve
# bucketing's tall regime, so the cholqr2/tsqr plan routes from round 9
# are traced at an aspect ratio that actually selects them — the m/n = 2
# default shape never would.
_M_TALL, _N_TALL = 64, 8

_F64_DTYPES = ("float64", "complex128")


def _ensure_cpu_backend() -> None:
    """Pin the CPU backend before any device touch. Some hosts pin a
    remote TPU plugin via sitecustomize (JAX_PLATFORMS in the env LOSES —
    tests/conftest.py has the story), and a wedged relay hangs at
    backend_init; an abstract-tracing lint gate must never take that
    risk. Set DHQR_LINT_KEEP_PLATFORM=1 to trace on the ambient backend.
    """
    import os

    if os.environ.get("DHQR_LINT_KEEP_PLATFORM") == "1":
        return
    import jax

    # dhqr: ignore[DHQR003] lint CLI/test entry owns its process: abstract tracing must not init a remote TPU backend
    jax.config.update("jax_platforms", "cpu")


def sub_jaxprs(val):
    """Yield every (open) jaxpr held by one eqn-param value — a
    ClosedJaxpr/Jaxpr, or any list/tuple/dict nesting of them. Shared
    with the comms pass (comms_pass.collect_comms)."""
    from jax import core

    if isinstance(val, core.ClosedJaxpr):
        yield val.jaxpr
    elif isinstance(val, core.Jaxpr):
        yield val
    elif isinstance(val, (list, tuple)):
        for v in val:
            yield from sub_jaxprs(v)
    elif isinstance(val, dict):
        for v in val.values():
            yield from sub_jaxprs(v)


def iter_jaxprs(jaxpr):
    """Yield ``jaxpr`` and every sub-jaxpr reachable through eqn params."""
    stack = [jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for val in eqn.params.values():
                stack.extend(sub_jaxprs(val))


def _collect_axis_names(params) -> "set[str]":
    """Axis names named by a collective eqn's params (axes/axis_name,
    string or tuple-of-strings)."""
    out = set()
    for key in ("axes", "axis_name"):
        val = params.get(key)
        if val is None:
            continue
        if isinstance(val, (tuple, list)):
            out.update(str(v) for v in val)
        else:
            out.add(str(val))
    return out


_COLLECTIVE_PRIMS = {
    "psum", "pmin", "pmax", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "axis_index",
    "pbroadcast",
}


def check_jaxpr(closed_jaxpr, label: str, mesh_axes=()) -> "list[Finding]":
    """Sanitize one traced program; ``label`` names the entry point in
    findings (rendered as the finding's path)."""
    findings = []
    mesh_axes = set(mesh_axes)
    seen_f64 = set()
    for jaxpr in iter_jaxprs(closed_jaxpr.jaxpr):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                dtype = str(getattr(aval, "dtype", ""))
                if dtype in _F64_DTYPES and (prim, dtype) not in seen_f64:
                    seen_f64.add((prim, dtype))
                    findings.append(Finding(
                        "DHQR101", label, 0,
                        f"{dtype} intermediate from f32 inputs "
                        f"(primitive '{prim}'): f64 is emulated >10x slow "
                        "on TPU — find and remove the promotion",
                        snippet=f"{prim}->{dtype}",
                    ))
            if "callback" in prim:
                findings.append(Finding(
                    "DHQR102", label, 0,
                    f"host callback primitive '{prim}' in the traced "
                    "program: one host round-trip per execution, and the "
                    "executable cannot be cached across processes",
                    snippet=prim,
                ))
            if prim in _COLLECTIVE_PRIMS:
                for axis in _collect_axis_names(eqn.params):
                    if axis not in mesh_axes:
                        findings.append(Finding(
                            "DHQR103", label, 0,
                            f"collective '{prim}' over axis {axis!r} "
                            f"which the mesh does not declare "
                            f"(mesh axes: {sorted(mesh_axes) or 'none'})",
                            snippet=f"{prim}[{axis}]",
                        ))
    return findings


def _builders(preset: str, pol):
    """The trace-construction mechanisms, keyed by the builder names the
    route registry's jaxpr specs cite (tune/registry.py — THE route
    enumeration since round 21; this map owns only HOW to build each
    thunk, never WHICH routes exist). Each builder returns a zero-arg
    thunk producing a closed jaxpr. Inputs are f32 and tiny; every thunk
    traces abstractly (make_jaxpr) — no compile, no execution, no device
    transfer of real data."""
    import jax
    import jax.numpy as jnp

    import dhqr_tpu
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq
    from dhqr_tpu.parallel.sharded_qr import (
        sharded_blocked_qr,
        sharded_householder_qr,
    )
    from dhqr_tpu.parallel.sharded_tsqr import row_mesh, sharded_tsqr_lstsq
    from dhqr_tpu.serve.engine import bucket_program
    from dhqr_tpu.solvers.sketch import sketched_lstsq as _sketched
    from dhqr_tpu.solvers.update import solve_program, update_program

    A = jnp.zeros((_M, _N), jnp.float32)
    b = jnp.zeros((_M,), jnp.float32)
    At = jnp.zeros((_M_TALL, _N_TALL), jnp.float32)
    bt = jnp.zeros((_M_TALL,), jnp.float32)
    As = jnp.zeros((2, _M, _N), jnp.float32)
    bs = jnp.zeros((2, _M), jnp.float32)
    Ask = jnp.zeros((2, _M_TALL, _N_TALL), jnp.float32)
    bsk = jnp.zeros((2, _M_TALL), jnp.float32)
    cmesh = column_mesh(1)
    rmesh = row_mesh(1)
    pod_box = {}

    def pmesh():
        # Lazy: only the pod routes (device-gated by the registry) need
        # a 2x2 factorization.
        if "mesh" not in pod_box:
            from dhqr_tpu.parallel.mesh import pod_mesh

            pod_box["mesh"], _ = pod_mesh(4, topo="2x2")
        return pod_box["mesh"]

    def jx(fn, *args):
        return lambda: jax.make_jaxpr(fn)(*args)

    def api_qr():
        return jx(lambda A: dhqr_tpu.qr(A, policy=preset), A)

    def api_lstsq(tall=False):
        if tall:
            # Engine auto-selection on a genuinely tall problem —
            # policy-free, like the plan gates it exercises.
            return jx(lambda A, b: dhqr_tpu.lstsq(A, b), At, bt)
        return jx(lambda A, b: dhqr_tpu.lstsq(A, b, policy=preset), A, b)

    def api_lstsq_plan(plan, tall=False):
        # The tuned dispatch path (round 9): lstsq with an explicit Plan
        # exercises plan resolution + apply_plan_to_config — the exact
        # code the plan DB routes production calls through. An explicit
        # Plan (not "auto") keeps the trace abstract: no DB read, no
        # timing, deterministic across hosts. Alt-engine plans are
        # policy-free by pruning rule 5; householder plans sweep the
        # preset like the rest of the tier.
        kw = {"policy": preset} if plan.engine == "householder" else {}
        Ax, bx = (At, bt) if tall else (A, b)
        return jx(lambda A, b: dhqr_tpu.lstsq(A, b, plan=plan, **kw),
                  Ax, bx)

    def tsqr_r():
        return jx(lambda A: dhqr_tpu.tsqr_r(A, n_blocks=2, policy=preset),
                  A)

    def cholesky_qr2():
        return jx(lambda A: dhqr_tpu.cholesky_qr2(A, policy=preset), A)

    def bucket(kind):
        # The serving tier's bucket dispatch units (serve/engine.py):
        # the same traced programs each bucket compiles, via the
        # engine's own config/policy resolution — a preset that stops
        # tracing through a vmapped path is a DHQR104 regression.
        if kind == "sketch":
            return jx(bucket_program("sketch", policy=preset), Ask, bsk)
        if kind == "qr":
            return jx(bucket_program("qr", block_size=_NB, policy=preset),
                      As)
        return jx(bucket_program("lstsq", block_size=_NB, policy=preset),
                  As, bs)

    def async_bucket():
        # The async scheduler's dispatch path (round 11): must be the
        # SAME bucket_program the comms pass contracts — the scheduler
        # owns no second lowering/key scheme. Asserts function-identity
        # parity BEFORE tracing, so a drift (someone giving the
        # scheduler its own _plan_key or dispatch loop) surfaces as a
        # DHQR104 finding rather than as steady-state recompiles.
        from dhqr_tpu.serve import engine as _serve_engine
        from dhqr_tpu.serve import scheduler as _serve_sched

        def thunk():
            # The drift this guards against is scheduler.py growing its
            # OWN lowering helpers shadowing the engine's — check the
            # scheduler's namespace (comparing engine attributes to
            # themselves through the module alias would be a tautology).
            shadowed = {"_plan_key", "_dispatch_groups", "bucket_program"} \
                & set(vars(_serve_sched))
            assert _serve_sched._engine is _serve_engine and not shadowed, (
                "async scheduler dispatch path diverged from serve.engine "
                f"(shadowed: {sorted(shadowed)}): cache-key parity (one "
                "_plan_key, one _dispatch_groups) is the zero-recompile "
                "contract")
            return jax.make_jaxpr(_serve_sched.dispatch_program(
                "lstsq", block_size=_NB, policy=preset))(As, bs)

        return thunk

    def sketched():
        return jx(lambda A, b: _sketched(A, b, policy=preset), At, bt)

    def upd_solve():
        G = jnp.zeros((_N_TALL, _N_TALL), jnp.float32)
        return jx(solve_program(refine=max(1, pol.refine),
                                precision=pol.panel), At, G, bt)

    def upd_rank1():
        G = jnp.zeros((_N_TALL, _N_TALL), jnp.float32)
        u = jnp.zeros((_M_TALL,), jnp.float32)
        v = jnp.zeros((_N_TALL,), jnp.float32)
        s = jnp.zeros((), jnp.float32)
        return jx(update_program(), At, G, G, u, v, s)

    def sharded_blocked(pod=False):
        if pod:
            # The hierarchical schedule is preset-independent — traced
            # once (the registry gates the route to one preset).
            return jx(lambda A: sharded_blocked_qr(
                A, pmesh(), block_size=_NB), A)
        return jx(lambda A: sharded_blocked_qr(
            A, cmesh, block_size=_NB, policy=preset), A)

    def sharded_unblocked():
        # The classic sharded engines take precision knobs, not a policy
        # object — trace at the preset's panel precision.
        return jx(lambda A: sharded_householder_qr(
            A, cmesh, precision=pol.panel), A)

    def lstsq_mesh():
        return jx(lambda A, b: dhqr_tpu.lstsq(
            A, b, mesh=cmesh, block_size=_NB, policy=preset), A, b)

    def lstsq_pod(mode):
        # dcn:* rungs add compressed DCN legs: a mode that stops tracing
        # fails DHQR104, a collective escaping the declared axes DHQR103.
        return jx(lambda A, b: dhqr_tpu.lstsq(
            A, b, mesh=pmesh(), block_size=_NB, comms=mode), A, b)

    def sharded_tsqr():
        return jx(lambda A, b: sharded_tsqr_lstsq(
            A, b, rmesh, block_size=_NB, precision=pol.panel), A, b)

    def sharded_cholqr():
        return jx(lambda A, b: sharded_cholqr_lstsq(
            A, b, rmesh, precision=pol.panel), A, b)

    return {
        "api_qr": api_qr,
        "api_lstsq": api_lstsq,
        "api_lstsq_plan": api_lstsq_plan,
        "tsqr_r": tsqr_r,
        "cholesky_qr2": cholesky_qr2,
        "bucket": bucket,
        "async_bucket": async_bucket,
        "sketched": sketched,
        "update_solve": upd_solve,
        "update_rank1": upd_rank1,
        "sharded_blocked": sharded_blocked,
        "sharded_unblocked": sharded_unblocked,
        "lstsq_mesh": lstsq_mesh,
        "lstsq_pod": lstsq_pod,
        "sharded_tsqr": sharded_tsqr,
        "sharded_cholqr": sharded_cholqr,
    }


def _unexpressible(route_name: str, builder: str):
    """Thunk for a registry jaxpr spec citing a builder this pass does
    not implement: raising (-> DHQR104) makes the drift a finding, not a
    silent drop — the round-21 contract for both directions of
    registry/pass skew."""
    def thunk():
        raise RuntimeError(
            f"route {route_name!r} cites jaxpr builder {builder!r} which "
            "analysis/jaxpr_pass implements no mechanism for: implement "
            "the builder or fix the registry spec (tune/registry.py)")
    return thunk


def _entry_points(preset: str, pol):
    """(label, thunk, mesh_axes) triples: thunk returns a closed jaxpr.

    Round 21 (dhqr-atlas): the enumeration is the route registry
    (tune/registry.jaxpr_routes) — this function only resolves each
    route's declarative spec against the builder mechanisms above, so a
    new route registers once and is traced here automatically (DHQR501
    fails lint if it is not).
    """
    import jax

    from dhqr_tpu.tune.registry import jaxpr_routes

    builders = _builders(preset, pol)
    for route in jaxpr_routes(preset, devices=len(jax.devices())):
        for spec in route.jaxpr:
            spec = dict(spec)
            label = spec.pop("label").format(preset=preset)
            axes = spec.pop("axes", ())
            name = spec.pop("builder")
            build = builders.get(name)
            if build is None:
                yield (label, _unexpressible(route.name, name), axes)
                continue
            yield (label, build(**spec), axes)


def run_jaxpr_pass(presets=None) -> "list[Finding]":
    """Trace and sanitize every entry point for every policy preset."""
    _ensure_cpu_backend()
    import jax

    from dhqr_tpu.precision import PRECISION_POLICIES

    names = list(presets) if presets is not None \
        else list(PRECISION_POLICIES)
    findings = []
    with jax.experimental.enable_x64():
        for preset in names:
            pol = PRECISION_POLICIES[preset]
            for label, thunk, mesh_axes in _entry_points(preset, pol):
                try:
                    closed = thunk()
                except Exception as e:  # a preset that fails to trace IS
                    findings.append(Finding(   # the regression (DHQR104)
                        "DHQR104", label, 0,
                        f"entry point failed to trace: "
                        f"{type(e).__name__}: {e}",
                    ))
                    continue
                findings.extend(check_jaxpr(closed, label, mesh_axes))
    return findings
