"""Pass 1 — AST lint rules DHQR001-DHQR010.

Each rule is a small class with an id, a scope predicate over the
(posix) file path, and a ``check(module)`` hook receiving a
:class:`ModuleContext` built once per file (parent links, traced-function
sets, declared axis names). The rules encode the round-5 hazard classes
(ADVICE.md) as machine-checkable invariants; the rationale per rule lives
in docs/DESIGN.md "Static invariants".

This module deliberately imports no jax: the AST pass must run (and run
fast) in any python, including environments where backend bring-up would
hang (docs/OPERATIONS.md, the wedged-relay hazard).
"""

from __future__ import annotations

import ast
import os

from dhqr_tpu.analysis.findings import (
    Finding,
    apply_suppressions,
    missing_reason_findings,
    parse_suppressions,
)

# Directories never scanned (fixture files are deliberate violations).
EXCLUDED_PARTS = ("__pycache__", ".jax_cache", "fixtures")

# DHQR003's sanctioned config/env mutation sites: the test bring-up, the
# bench/probe tier (each probe is a process that owns its environment),
# and utils/platform.py — the library's ONE documented config authority
# (its docstring: "written down exactly once").
SANCTIONED_CONFIG_PATHS = (
    "tests/conftest.py",
    "bench.py",
    "dhqr_tpu/utils/platform.py",
)
SANCTIONED_CONFIG_DIRS = ("benchmarks/",)

_CONTRACTION_ATTRS = {"matmul", "einsum", "dot_general", "dot",
                      "tensordot", "vdot"}
_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "axis_index",
}
# Collective -> index of the positional axis-name argument.
_COLLECTIVE_AXIS_ARG = {name: 1 for name in _COLLECTIVES}
_COLLECTIVE_AXIS_ARG["axis_index"] = 0
_HOST_SYNC_ATTRS = {"item", "block_until_ready"}
_NUMPY_ALIASES = {"np", "numpy", "_np", "onp"}


def _posix(path: str) -> str:
    return path.replace(os.sep, "/")


def _in_package(path: str) -> bool:
    return "dhqr_tpu/" in path or path.startswith("dhqr_tpu")


def _call_name(node: ast.AST) -> str:
    """Rightmost identifier of a call target (Name or dotted Attribute)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted spelling ('jax.config.update') for matching."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class ModuleContext:
    """Everything the rules need, computed once per file."""

    def __init__(self, tree: ast.Module, lines: "list[str]", path: str):
        self.tree = tree
        self.lines = lines
        self.path = path
        self.parents: "dict[ast.AST, ast.AST]" = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.functions = self._collect_functions()
        self.partial_aliases = self._collect_partial_aliases()
        self.jit_functions = self._collect_jit_functions()
        self.shard_bodies = self._collect_shard_bodies()
        self.declared_axes = self._collect_declared_axes()

    # -- context collectors --------------------------------------------------
    def _collect_functions(self):
        funcs: "dict[str, list]" = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, []).append(node)
        return funcs

    def _collect_partial_aliases(self):
        """name -> wrapped function name, for ``body = partial(fn, ...)``."""
        aliases = {}
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            val = node.value
            if (isinstance(val, ast.Call)
                    and _call_name(val.func) == "partial" and val.args
                    and isinstance(val.args[0], ast.Name)):
                aliases[node.targets[0].id] = val.args[0].id
        return aliases

    @staticmethod
    def _is_jit_ref(node: ast.AST) -> bool:
        return _call_name(node) == "jit"

    def _collect_jit_functions(self):
        """FunctionDef nodes that trace under jit: decorated with jit /
        partial(jit, ...), or passed by name to a jit(...) call."""
        out = set()
        for defs in self.functions.values():
            for fn in defs:
                for dec in fn.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if self._is_jit_ref(target):
                        out.add(fn)
                    elif (isinstance(dec, ast.Call)
                          and _call_name(dec.func) == "partial" and dec.args
                          and self._is_jit_ref(dec.args[0])):
                        out.add(fn)
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Call) and self._is_jit_ref(node.func)
                    and node.args and isinstance(node.args[0], ast.Name)):
                for fn in self.functions.get(node.args[0].id, ()):
                    out.add(fn)
        return out

    def _collect_shard_bodies(self):
        """(FunctionDef | Lambda) nodes that run as shard_map bodies —
        directly, via partial(fn, ...), or via a ``body = partial(fn, ..)``
        alias."""
        bodies = set()
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and _call_name(node.func) == "shard_map"):
                continue
            args = list(node.args)
            if not args and node.keywords:
                args = [kw.value for kw in node.keywords if kw.arg == "f"]
            if not args:
                continue
            arg = args[0]
            names = []
            if isinstance(arg, ast.Lambda):
                bodies.add(arg)
            elif isinstance(arg, ast.Name):
                names.append(self.partial_aliases.get(arg.id, arg.id))
            elif (isinstance(arg, ast.Call)
                  and _call_name(arg.func) == "partial" and arg.args
                  and isinstance(arg.args[0], ast.Name)):
                names.append(arg.args[0].id)
            for name in names:
                for fn in self.functions.get(name, ()):
                    bodies.add(fn)
        return bodies

    def _collect_declared_axes(self):
        """Axis names this module legitimately references: *_AXIS string
        constants, string literals inside mesh/spec constructors, and
        string defaults of axis/axis_name parameters."""
        axes = set()
        spec_ctors = {"P", "PartitionSpec", "Mesh", "NamedSharding",
                      "column_mesh", "row_mesh", "make_mesh"}
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id.endswith("_AXIS"):
                        axes.add(node.value.value)
            elif (isinstance(node, ast.Call)
                  and _call_name(node.func) in spec_ctors):
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Constant)
                            and isinstance(sub.value, str)):
                        axes.add(sub.value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                pos = args.posonlyargs + args.args
                pairs = list(zip(pos[len(pos) - len(args.defaults):],
                                 args.defaults))
                pairs += [(a, d) for a, d in
                          zip(args.kwonlyargs, args.kw_defaults)
                          if d is not None]
                for a, d in pairs:
                    if (a.arg in ("axis", "axis_name")
                            and isinstance(d, ast.Constant)
                            and isinstance(d.value, str)):
                        axes.add(d.value)
        return axes

    # -- shared helpers ------------------------------------------------------
    def inside_import_guard(self, node: ast.AST) -> bool:
        """Is ``node`` within a try: whose handlers catch ImportError (or
        broader)? That is the sanctioned spelling for version-dependent
        private-jax access (ops/blocked._pallas_cache_guard)."""
        guard_names = {"ImportError", "ModuleNotFoundError", "Exception"}
        cur = node
        while cur in self.parents:
            parent = self.parents[cur]
            if isinstance(parent, ast.Try) and cur in parent.body:
                for handler in parent.handlers:
                    types = []
                    if handler.type is None:
                        return True
                    if isinstance(handler.type, ast.Tuple):
                        types = handler.type.elts
                    else:
                        types = [handler.type]
                    for t in types:
                        if _call_name(t) in guard_names:
                            return True
            cur = parent
        return False

    def traced_subtree_nodes(self, roots):
        """All AST nodes inside the given traced function/lambda roots
        (nested closures are traced too when the body calls them)."""
        seen = set()
        for root in roots:
            for node in ast.walk(root):
                seen.add(node)
        return seen


class Rule:
    id = "DHQR000"
    title = ""

    def applies(self, path: str) -> bool:
        return True

    def check(self, ctx: ModuleContext) -> "list[Finding]":
        raise NotImplementedError

    def _finding(self, ctx: ModuleContext, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        snippet = ctx.lines[line - 1].strip() if 0 < line <= len(ctx.lines) \
            else ""
        return Finding(self.id, ctx.path, line, message, snippet=snippet)


class PrivateJaxImports(Rule):
    """DHQR001 — ``jax._src`` is private API: a jax upgrade may remove it
    without notice, turning every import of the module into a crash
    (ADVICE r5 item 1 — the _pallas_cache_guard near-miss). Allowed only
    in utils/compat.py (the one version-shim surface) or behind a
    try/except ImportError that degrades gracefully."""

    id = "DHQR001"
    title = "unguarded private jax._src import"

    def applies(self, path: str) -> bool:
        return not path.endswith("utils/compat.py")

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            module = ""
            if isinstance(node, ast.Import):
                module = ",".join(a.name for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                module = node.module or ""
            if not module.startswith("jax._src"):
                continue
            if ctx.inside_import_guard(node):
                continue
            out.append(self._finding(
                ctx, node,
                f"unguarded private import '{module}': private jax API "
                "must live in utils/compat.py or behind try/except "
                "ImportError with a graceful fallback",
            ))
        return out


class UnannotatedContractions(Rule):
    """DHQR002 — every MXU contraction must name its precision. The TPU
    matmul default is bf16 passes (~1e-4 relative error); one bare
    ``jnp.matmul`` silently reintroduces the accuracy/perf ambiguity the
    PrecisionPolicy subsystem exists to control (docs/DESIGN.md
    "Precision is the accuracy budget"). The ``@`` operator cannot carry
    a precision argument at all — spell the call out, route it through a
    policy, or suppress with the reason it is host-side math."""

    id = "DHQR002"
    title = "contraction without precision/preferred_element_type"

    def applies(self, path: str) -> bool:
        return _in_package(path)

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                          ast.MatMult):
                out.append(self._finding(
                    ctx, node,
                    "'@' carries no precision= — use jnp.matmul(..., "
                    "precision=...) / a PrecisionPolicy route, or suppress "
                    "with the reason this is host-side (numpy) math",
                ))
            elif isinstance(node, ast.Call):
                name = _call_name(node.func)
                if name not in _CONTRACTION_ATTRS:
                    continue
                kws = {kw.arg for kw in node.keywords}
                if kws & {"precision", "preferred_element_type"}:
                    continue
                out.append(self._finding(
                    ctx, node,
                    f"{name}() without precision= or "
                    "preferred_element_type=: the TPU default is bf16 "
                    "passes — name the precision (or route a "
                    "PrecisionPolicy through the caller)",
                ))
        return out


class GlobalConfigMutation(Rule):
    """DHQR003 — ``jax.config.update`` / env mutation is process-global
    state: in a library it races every concurrent trace and leaks into
    the caller's process (ADVICE round 5: the process-global
    compilation-cache toggle). Only process-owning entry points may
    mutate it: tests/conftest.py, bench.py, benchmarks/, and
    utils/platform.py (the documented config authority)."""

    id = "DHQR003"
    title = "process-global config/env mutation outside sanctioned modules"

    def applies(self, path: str) -> bool:
        # Anchored matching: 'tests/test_bench.py' must NOT inherit
        # bench.py's sanction, nor 'my_benchmarks/' the benchmarks/ one.
        if any(path == p or path.endswith("/" + p)
               for p in SANCTIONED_CONFIG_PATHS):
            return False
        parts = path.split("/")
        return not any(d.rstrip("/") in parts[:-1]
                       for d in SANCTIONED_CONFIG_DIRS)

    @staticmethod
    def _is_environ(node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and node.attr == "environ"

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted.endswith("config.update"):
                    out.append(self._finding(
                        ctx, node,
                        "jax.config.update mutates process-global state: "
                        "route through utils/platform.py (or suppress: "
                        "process-owning entry points only)",
                    ))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in ("setdefault", "update", "pop",
                                             "clear")
                      and self._is_environ(node.func.value)):
                    out.append(self._finding(
                        ctx, node,
                        f"os.environ.{node.func.attr}() mutates the "
                        "process environment: sanctioned modules only",
                    ))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "putenv"):
                    out.append(self._finding(
                        ctx, node,
                        "os.putenv mutates the process environment: "
                        "sanctioned modules only",
                    ))
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if (isinstance(t, ast.Subscript)
                            and self._is_environ(t.value)):
                        out.append(self._finding(
                            ctx, node,
                            "os.environ[...] assignment mutates the "
                            "process environment: sanctioned modules only",
                        ))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and self._is_environ(t.value)):
                        out.append(self._finding(
                            ctx, node,
                            "del os.environ[...] mutates the process "
                            "environment: sanctioned modules only",
                        ))
        return out


class HostSyncInTracedBody(Rule):
    """DHQR004 — ``float()``, ``.item()``, ``np.asarray``,
    ``.block_until_ready()`` or ``jax.device_get`` inside a jit- or
    shard_map-traced body either fails at trace time (tracer leak) or,
    worse, silently forces a host round-trip per call on paths that must
    stay on-device (the reference's @spawnat round-trips are exactly
    what this framework exists to eliminate)."""

    id = "DHQR004"
    title = "host sync inside a traced (jit/shard_map) body"

    def check(self, ctx):
        roots = set(ctx.jit_functions) | set(ctx.shard_bodies)
        if not roots:
            return []
        traced = ctx.traced_subtree_nodes(roots)
        out = []
        for node in traced:
            if not isinstance(node, ast.Call):
                continue
            fname = _call_name(node.func)
            if isinstance(node.func, ast.Name) and fname == "float" \
                    and node.args:
                out.append(self._finding(
                    ctx, node,
                    "float() inside a traced body forces a host readback "
                    "(or a tracer leak) — keep the value on device",
                ))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_SYNC_ATTRS:
                out.append(self._finding(
                    ctx, node,
                    f".{node.func.attr}() inside a traced body is a host "
                    "sync — keep the value on device",
                ))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "asarray"
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id in _NUMPY_ALIASES):
                out.append(self._finding(
                    ctx, node,
                    "np.asarray inside a traced body pulls the array to "
                    "host — use jnp.asarray (device) or hoist out of the "
                    "traced region",
                ))
            elif fname == "device_get":
                out.append(self._finding(
                    ctx, node,
                    "jax.device_get inside a traced body is a host sync",
                ))
        return out


class CollectiveAxisName(Rule):
    """DHQR005 — a hard-coded axis-name string inside a shard_map body is
    a latent mismatch: the mesh is declared elsewhere, and a rename (or a
    caller-supplied axis) silently breaks the collective at run time.
    Axis names must be threaded as parameters, or be literals that match
    an axis the module itself declares (``*_AXIS`` constants, mesh/spec
    constructors)."""

    id = "DHQR005"
    title = "collective axis name not resolvable against the mesh"

    def check(self, ctx):
        if not ctx.shard_bodies:
            return []
        traced = ctx.traced_subtree_nodes(ctx.shard_bodies)
        out = []
        for node in traced:
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name not in _COLLECTIVES:
                continue
            axis_node = None
            idx = _COLLECTIVE_AXIS_ARG[name]
            if len(node.args) > idx:
                axis_node = node.args[idx]
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis"):
                    axis_node = kw.value
            if axis_node is None:
                continue
            if isinstance(axis_node, ast.Constant) \
                    and isinstance(axis_node.value, str) \
                    and axis_node.value not in ctx.declared_axes:
                out.append(self._finding(
                    ctx, node,
                    f"{name}() axis name {axis_node.value!r} matches no "
                    "axis declared in this module — thread the axis name "
                    "as a parameter (or declare the *_AXIS constant the "
                    "mesh actually uses)",
                ))
        return out


class SwallowedException(Rule):
    """DHQR006 — an ``except ...: pass`` (or bare-``...`` body) in
    package code silently discards a failure: the round-12 fault model
    depends on every failure path SURFACING typed (retry, quarantine,
    bisection, worker respawn all key on seeing the exception), and one
    swallowed ``except`` upstream turns a designed failure into a
    silent wrong answer or a hang. Where discarding really is the
    intent (a best-effort cleanup, an optional probe), suppress with
    the reason — the reason is the documentation the bare ``pass``
    was hiding."""

    id = "DHQR006"
    title = "swallowed exception (except: pass) without a suppression"

    def applies(self, path: str) -> bool:
        return _in_package(path)

    @staticmethod
    def _is_noop(stmt: ast.stmt) -> bool:
        return isinstance(stmt, ast.Pass) or (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not all(self._is_noop(s) for s in node.body):
                continue
            caught = "everything" if node.type is None else (
                _call_name(node.type) or "multiple exception types")
            out.append(self._finding(
                ctx, node,
                f"except block catching {caught} swallows the error "
                "with a bare pass — handle it, reraise typed, or "
                "suppress with the reason discarding is safe here",
            ))
        return out


class UnguardedCholesky(Rule):
    """DHQR007 — every Cholesky in package code routes through the one
    guarded wrapper, ``dhqr_tpu.numeric.guards.checked_cholesky``.
    ``lax.linalg.cholesky`` does not raise on a non-positive-definite
    input — it returns NaN rows from the first failed pivot on, which
    is exactly how CholeskyQR2 breaks down past its conditioning
    window (ops/cholqr.py). The wrapper is where that breakdown
    contract is written down (callers gate their outputs or document
    why breakdown is impossible); a direct call silently opts out of
    the round-13 numeric guardrails, so one engine tweak could
    reintroduce the silent-NaN hazard the fallback ladder exists to
    close."""

    id = "DHQR007"
    title = "direct cholesky call outside numeric.guards.checked_cholesky"

    def applies(self, path: str) -> bool:
        # The wrapper module itself is the one sanctioned call site.
        return _in_package(path) and not path.endswith("numeric/guards.py")

    def check(self, ctx):
        # Every spelling reaches the same primitive, so every spelling
        # is flagged: dotted *.linalg.cholesky, a bare name bound by
        # `from <...linalg...> import cholesky [as x]`, and a module
        # alias (`import jax.lax.linalg as lin; lin.cholesky(G)`).
        flagged_names: "set[str]" = set()
        module_aliases: "set[str]" = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if "linalg" in (node.module or "") \
                            and alias.name == "cholesky":
                        flagged_names.add(alias.asname or "cholesky")
                    elif alias.name == "linalg" and alias.asname:
                        # `from jax.lax import linalg as la` — la is a
                        # linalg module; without an asname the dotted
                        # form already ends with linalg.cholesky.
                        module_aliases.add(alias.asname)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if "linalg" in alias.name and alias.asname:
                        module_aliases.add(alias.asname)
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            dotted = _dotted(node.func)
            if name == "cholesky" and isinstance(node.func, ast.Name):
                if name not in flagged_names:
                    continue  # a local wrapper named cholesky
            elif name == "cholesky":
                prefix = dotted[:-len(".cholesky")] if "." in dotted else ""
                if not (dotted.endswith("linalg.cholesky")
                        or prefix in module_aliases):
                    continue  # checked_cholesky-style wrappers pass
            elif name in flagged_names and isinstance(node.func, ast.Name):
                pass  # `from ...linalg import cholesky as chol; chol(G)`
            else:
                continue
            out.append(self._finding(
                ctx, node,
                f"direct {dotted}() call: route through "
                "dhqr_tpu.numeric.guards.checked_cholesky (the guarded "
                "wrapper carrying the NaN-breakdown contract), or "
                "suppress with the reason breakdown is impossible here",
            ))
        return out


class RawWallClock(Rule):
    """DHQR008 — a raw wall-clock READ (``time.time()`` /
    ``time.monotonic()`` / ``time.perf_counter()`` and their ``_ns``
    twins) in package code bypasses the injectable-clock seams the
    stack is built on: the scheduler, the executable cache's
    quarantine, the fault harness and the round-14 trace recorder all
    take ``clock=`` precisely so deadline/backoff/cooldown/span
    decisions replay deterministically under a fake clock in tests and
    the dry run. One stray ``time.monotonic()`` on such a path is a
    wall-clock dependency a fake-clock test cannot see — it surfaces
    as flakes. The sanctioned spellings are (a) passing the callable
    as an injectable default (``clock=time.monotonic`` — a reference,
    not a read; this rule flags CALLS only) and (b) a reasoned
    suppression where a real wall clock IS the point (measuring
    actual compile/device seconds, damping a crash-loop, bounding a
    drain against real hangs)."""

    id = "DHQR008"
    title = "raw wall-clock read outside an injectable-clock seam"

    _CLOCK_NAMES = {
        "time", "monotonic", "perf_counter",
        "time_ns", "monotonic_ns", "perf_counter_ns",
    }

    def applies(self, path: str) -> bool:
        return _in_package(path)

    def check(self, ctx):
        # Every spelling that reaches the wall clock is flagged:
        # `from time import monotonic [as now]` (a bare name), and
        # `import time [as _time]` (a dotted read through any alias).
        flagged_names: "set[str]" = set()
        module_aliases: "set[str]" = {"time"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name in self._CLOCK_NAMES:
                        flagged_names.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time" and alias.asname:
                        module_aliases.add(alias.asname)
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            prefix, _, attr = dotted.rpartition(".")
            via_module = prefix in module_aliases \
                and attr in self._CLOCK_NAMES
            bare = isinstance(node.func, ast.Name) \
                and node.func.id in flagged_names
            if not via_module and not bare:
                continue
            out.append(self._finding(
                ctx, node,
                f"raw wall-clock read {dotted or _call_name(node.func)}(): "
                "route through the subsystem's injectable clock "
                "(clock=/self._clock) so fake-clock tests stay "
                "deterministic, or suppress with the reason a real "
                "wall clock is the point here",
            ))
        return out


class RawCollectiveOutsideSeam(Rule):
    """DHQR009 — a raw data-moving ``lax`` collective in the sharded
    tier (``dhqr_tpu/parallel/``) bypasses the dhqr-wire compression
    seam (``parallel/wire.py``, round 18). The seam is the ONE place a
    collective's wire format is chosen: ``wire_psum``/``wire_all_gather``
    are verbatim passthroughs at ``comms=None`` (the accurate tier
    stays bit-identical by construction) and bf16/int8 quantizers on
    the compressed rungs, priced by the DHQR302 compressed-mode
    budgets. A raw ``lax.psum``/``lax.all_gather`` on a panel-broadcast
    or combine path is a collective the ``comms`` policy field can
    never compress — the engine silently drops out of the compressed
    contract while the plan grid keeps offering the mode. The seam
    module itself is the sanctioned call site; ``axis_index`` moves no
    words and stays DHQR005's business."""

    id = "DHQR009"
    title = "raw lax collective in the sharded tier outside the wire seam"

    # Data-moving collectives only (COMMS_COLLECTIVES minus nothing —
    # axis_index is excluded by construction).
    _WIRE_COLLECTIVES = {
        "psum", "pmean", "pmax", "pmin", "psum_scatter", "reduce_scatter",
        "all_gather", "all_to_all", "ppermute", "pshuffle", "pbroadcast",
    }

    def applies(self, path: str) -> bool:
        return ("parallel/" in path
                and _in_package(path)
                and not path.endswith("parallel/wire.py"))

    def check(self, ctx):
        # Same spelling coverage as DHQR007: dotted lax.<name> through
        # any module alias of jax.lax, and bare names bound by
        # `from jax.lax import psum [as p]`.
        flagged_names: "set[str]" = set()
        lax_aliases: "set[str]" = {"lax"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    if mod.endswith("lax") \
                            and alias.name in self._WIRE_COLLECTIVES:
                        flagged_names.add(alias.asname or alias.name)
                    elif alias.name == "lax" and alias.asname:
                        lax_aliases.add(alias.asname)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.endswith(".lax") and alias.asname:
                        lax_aliases.add(alias.asname)
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name not in self._WIRE_COLLECTIVES \
                    and name not in flagged_names:
                continue
            dotted = _dotted(node.func)
            prefix, _, _attr = dotted.rpartition(".")
            via_module = prefix.split(".")[-1] in lax_aliases if prefix \
                else False
            bare = isinstance(node.func, ast.Name) and name in flagged_names
            if not via_module and not bare:
                continue  # wire_psum-style wrappers pass
            out.append(self._finding(
                ctx, node,
                f"raw collective {dotted}() on a sharded-tier path: "
                "route through dhqr_tpu.parallel.wire "
                "(wire_psum/wire_all_gather — a verbatim passthrough at "
                "comms=None) so the comms policy field can compress it "
                "and the DHQR302 compressed budgets can price it, or "
                "suppress with the reason the wire format cannot apply",
            ))
        return out


class ShardedDispatchOutsideArmor(Rule):
    """DHQR010 — a sharded-tier entry point dispatches collective
    results without the armor verification seam (round 19). The
    ``sharded_*`` entry points in ``dhqr_tpu/parallel/`` are the ONE
    place factor-carrying collective results surface to callers; each
    one that builds a compiled sharded program (a ``_build_*`` call)
    must route its dispatch through ``dhqr_tpu.armor.checked_dispatch``
    (behind the ``armor.active()`` None check) so that, when armed,
    every factor/solve crossing the mesh is checksum-verified and the
    recovery ladder — re-dispatch, wire degrade, typed
    ``CorruptionDetected``/``ShardFailure`` — applies. An entry point
    that dispatches bare reintroduces exactly the silent-garbage
    window the armor tier closes: a corrupted collective returns a
    plausible wrong factor with no detection, no recovery, and no
    typed refusal. Internal chaining helpers (no ``_build_*`` call of
    their own) are exempt — they verify at the top level."""

    id = "DHQR010"
    title = ("sharded entry point dispatches collective results "
             "outside the armor verification seam")

    def applies(self, path: str) -> bool:
        return ("parallel/" in path
                and _in_package(path)
                and not path.endswith("parallel/wire.py"))

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef) \
                    or not node.name.startswith("sharded_"):
                continue
            builds = False
            armored = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = _call_name(sub.func)
                    if name.startswith("_build_"):
                        builds = True
                    if name == "checked_dispatch":
                        armored = True
                elif isinstance(sub, ast.Attribute) \
                        and sub.attr == "checked_dispatch":
                    armored = True
            if builds and not armored:
                out.append(self._finding(
                    ctx, node,
                    f"sharded entry point {node.name}() compiles a "
                    "sharded program (_build_* call) but never routes "
                    "its dispatch through armor.checked_dispatch: when "
                    "the armor tier is armed this dispatch returns "
                    "unverified collective results — wrap the launch "
                    "in the checked_dispatch seam behind the "
                    "armor.active() None check, or suppress with the "
                    "reason no factor-carrying result crosses here",
                ))
        return out


AST_RULES = (
    PrivateJaxImports(),
    UnannotatedContractions(),
    GlobalConfigMutation(),
    HostSyncInTracedBody(),
    CollectiveAxisName(),
    SwallowedException(),
    UnguardedCholesky(),
    RawWallClock(),
    RawCollectiveOutsideSeam(),
    ShardedDispatchOutsideArmor(),
)


def scan_source(text: str, path: str, rules=AST_RULES) -> "list[Finding]":
    """Run the AST rules over one file's source. ``path`` is the posix
    path used for scoping and display — tests pass virtual paths so
    fixture files exercise package-scoped rules."""
    path = _posix(path)
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [Finding("DHQR000", path, e.lineno or 0,
                        f"syntax error: {e.msg}")]
    lines = text.splitlines()
    ctx = ModuleContext(tree, lines, path)
    findings = []
    for rule in rules:
        if rule.applies(path):
            findings.extend(rule.check(ctx))
    findings.sort(key=lambda f: (f.line, f.rule))
    out = apply_suppressions(findings, parse_suppressions(lines))
    # After apply_suppressions, and never routed through it: a
    # reason-less `# dhqr: ignore[DHQR000]` must not suppress its own
    # missing-reason report (round 21 — warn-only, severity="warning").
    out.extend(missing_reason_findings(lines, path))
    return out


def iter_python_files(paths):
    """Expand files/directories into .py files, skipping excluded parts.

    A named path that does not exist raises: a typo'd CI target must
    fail loudly, not scan zero files and report a green gate."""
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        if not os.path.isdir(path):
            raise FileNotFoundError(
                f"lint target {path!r} is neither a file nor a directory")
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in EXCLUDED_PARTS)
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def scan_paths(paths, rules=AST_RULES, rel_to=None) -> "list[Finding]":
    """Scan files/directories; display paths are made relative to
    ``rel_to`` (default: cwd) where possible."""
    rel_to = rel_to or os.getcwd()
    findings = []
    for fpath in iter_python_files(paths):
        try:
            rel = os.path.relpath(fpath, rel_to)
        except ValueError:
            rel = fpath
        if rel.startswith(".."):
            rel = fpath
        with open(fpath, "r", encoding="utf-8") as fh:
            text = fh.read()
        findings.extend(scan_source(text, rel))
    return findings
