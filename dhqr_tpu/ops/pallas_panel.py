"""Pallas TPU kernel: fused in-VMEM panel factorization (layer L0).

The hot serial region of blocked QR is the panel factorization — nb
dependent column steps, each a small norm + scale + rank-1 update. Run
through XLA, every step round-trips the panel through HBM; the whole panel
loop is latency-bound exactly like the reference's per-column broadcast loop
(reference src/DistributedHouseholderQR.jl:127-148, flagged "this is most
expensive" at src:141). This kernel is the TPU counterpart of the
reference's hand-written SIMD micro-kernels (``partialdot``/``hotloop!``,
src:42-59, 150-196): it keeps the entire panel resident in VMEM and runs all
nb column steps in one kernel launch.

Layout: the panel is processed *transposed* — ``At`` is (nb, m), one panel
column per sublane row — because Pallas/Mosaic supports dynamic indexing on
the second-to-last (sublane) axis, while the contraction and rank-1 update
vectorize along the m-length lane axis. Per column j:

    row_j = At[j, :]                     (dynamic sublane read)
    s     = ||row_j masked to i >= j||
    v     = f * (row_j - alpha_j e_j)    (reference scaling, ||v||^2 = 2)
    W     = At @ v                       (all partial dots at once)
    At   -= W[:, None] * v[None, :]      (all rank-1 axpys at once)

with row masks ``i >= j`` and row masks ``jj > j`` replacing the ragged
ranges. The reflector formulas match :func:`dhqr_tpu.ops.householder`
(alpha sign rule src:8-9, ``f = 1/sqrt(s(s+|a_jj|))`` src:131), and the
column norm uses the same compensated-accumulation standard as the XLA
engine's tree (``ops/summation.py``), spelled in Mosaic-legal vector ops:
Dekker TwoProduct makes each square exact (``x*x = p + e`` with no FMA
required, via a Veltkamp split), and a contiguous-halving TwoSum tree
compensates the additions of the ``p`` plane (:func:`_sumsq_compensated`).

Float32 and complex64. Mosaic has no complex dtype, so the complex64
kernel runs PLANAR arithmetic — separate real/imaginary (nb, m) f32 planes,
the TPU-level analogue of the reference's reinterpret-to-Float64-lanes
trick in its hand-SIMD ComplexF64 ``hotloop!`` (src:162-196): each complex
partial dot becomes four real contractions

    Wr =  Ar vr + Ai vi        (re of conj(v) . x)
    Wi =  Ai vr - Ar vi        (im of conj(v) . x)

and the rank-1 update two real outer-product pairs. Float64/complex128 stay
on the XLA path (TPU f64 is emulated anyway). The panel must fit in VMEM —
callers gate via :func:`pallas_panel_supported`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

# VMEM working-set model for the transposed panel. Defaults are
# conservative (12 MiB budget, TWO assumed resident panel copies — the step
# body's ``at - W*v`` chain could materialize a second panel-sized value if
# Mosaic does not fuse it). On hardware where larger residency was MEASURED
# to compile and run, the per-device-kind table below overrides: round-3
# probe on a v5e ("TPU v5 lite") ran single-copy panels up to (32768, 512)
# = 67 MB with correct reflector norms (tpu_r3_vmem_probe2.jsonl ran the
# ladder to 33.6 MB, tpu_r3_scale.jsonl extended it to 67 MB), i.e. Mosaic
# does fuse the chain and v5e VMEM is far larger than the generic ~16 MB
# planning number. DHQR_PALLAS_VMEM_BYTES / DHQR_PALLAS_PANEL_COPIES
# override both. They are read per TRACE, not per execution: the gate is
# consulted inside jitted entry points, so a cached trace (same shapes,
# same static args) keeps its original gate decision — flip the env
# BEFORE first use of a shape, or use a fresh process for experiments.
import os as _os

_MEASURED_VMEM_KINDS = {
    # device_kind -> (budget_bytes, resident_copies), hardware-validated
    "TPU v5 lite": (68 * 1024 * 1024, 1),
}


_WARNED_UNMEASURED_KINDS: set = set()


def _gate_params(device=None) -> tuple:
    """(budget_bytes, assumed_copies) for ``device`` (default backend if None).

    ``device`` lets callers size the gate for the EXECUTION device rather
    than the process default backend — a TPU mesh driven from a CPU-default
    process must get the mesh chip's measured gate, not the planning
    fallback. TPU device kinds absent from :data:`_MEASURED_VMEM_KINDS`
    get the conservative planning gate (12 MiB, 2 resident copies) —
    correct but likely far below the hardware's real ceiling — and, unless
    the operator has already overridden via env, a one-time warning per
    kind saying so and how to override (VERDICT r3 weak #6: no silent
    pessimization on unmeasured ground)."""
    budget, copies = 12 * 1024 * 1024, 2
    env_budget = _os.environ.get("DHQR_PALLAS_VMEM_BYTES")
    env_copies = _os.environ.get("DHQR_PALLAS_PANEL_COPIES")
    try:
        if device is None and jax.default_backend() == "tpu":
            device = jax.devices()[0]
        if device is not None and device.platform == "tpu":
            kind = getattr(device, "device_kind", "")
            if kind in _MEASURED_VMEM_KINDS:
                budget, copies = _MEASURED_VMEM_KINDS[kind]
            elif not (env_budget or env_copies) \
                    and kind not in _WARNED_UNMEASURED_KINDS:
                _WARNED_UNMEASURED_KINDS.add(kind)
                import warnings

                warnings.warn(
                    f"TPU device kind {kind!r} has no measured VMEM gate "
                    f"(dhqr_tpu.ops.pallas_panel._MEASURED_VMEM_KINDS): "
                    f"using the conservative {budget >> 20} MiB / "
                    f"{copies}-copy planning gate, which caps the fused "
                    f"panel kernel at narrow widths and likely leaves "
                    f"performance on the table. Probe your chip "
                    f"(benchmarks/tpu_vmem_probe.py) and set "
                    f"DHQR_PALLAS_VMEM_BYTES / DHQR_PALLAS_PANEL_COPIES "
                    f"(or add the kind to _MEASURED_VMEM_KINDS).",
                    stacklevel=3,
                )
    # dhqr: ignore[DHQR006] best-effort unknown-chip WARNING only: the conservative budget below is already chosen, and a failure probing device_kind must not break planning
    except Exception:
        pass
    if env_budget:
        budget = int(env_budget)
    if env_copies:
        copies = int(env_copies)
    return budget, copies


def pallas_panel_supported(m: int, nb: int, dtype, device=None) -> bool:
    """True when the fused kernel can factor an (m, nb) panel in VMEM.

    Supported dtypes: float32 (direct) and complex64 (planar re/im — two
    f32 planes, so twice the resident bytes). ``device`` sizes the gate
    for a specific execution device (see :func:`_gate_params`).
    """
    dt = jnp.dtype(dtype)
    if dt == jnp.float32:
        planes = 1
    elif dt == jnp.complex64:
        planes = 2
    else:
        return False
    budget, copies = _gate_params(device)
    return planes * (copies * m * nb * 4 + 4 * m * 4) <= budget


def _sumsq_compensated(x):
    """Compensated sum of squares of a (1, w) f32 row — scalar f32 result.

    In-VMEM counterpart of ``ops/summation.tree_sum`` over ``x*x``, built
    from Mosaic-legal vector ops only (no strided slices, no reshapes):

    * Dekker TwoProduct via a Veltkamp split (f32 constant ``2^12 + 1``)
      makes each square exact: ``x*x == p + e`` in rounded f32 arithmetic,
      no FMA required (overflow-safe for ``|x| < ~8e34``);
    * the ``p`` plane is zero-padded on the lane axis to the next power of
      two (zeros are exact under TwoSum; the pad is one (1, w) row, ~16 KB
      at worst — noise next to the panel), so the halving tree below slices
      ONLY at power-of-two offsets >= 128, i.e. always lane-tile-aligned,
      for every panel height the blocked engine produces;
    * a contiguous-halving TwoSum tree then compensates the additions of
      the ``p`` plane down to a 128-wide slab, the per-level error folded
      into a scalar side channel (error terms are tiny; a plain reduce of
      them is fine — same reasoning as summation.py's ``err`` channel);
    * the final 128-wide slab goes through the hardware lane-tree reduce,
      whose few levels contribute ~1 ulp.
    """
    p = x * x
    c = x * 4097.0
    hi = c - (c - x)
    lo = x - hi
    e = ((hi * hi - p) + 2.0 * hi * lo) + lo * lo
    err = jnp.sum(e)
    w = p.shape[1]
    if w >= 256:
        w2 = 1 << (w - 1).bit_length()  # next power of two
        if w2 != w:
            p = jnp.pad(p, ((0, 0), (0, w2 - w)))
            w = w2
        while w > 128:
            h = w // 2
            a = p[:, :h]
            b = p[:, h:]
            s = a + b
            z = s - a
            err = err + jnp.sum((a - (s - z)) + (b - z))  # Knuth TwoSum error
            p = s
            w = h
    return jnp.sum(p) + err


def _panel_kernel(off_ref, at_ref, out_ref, alpha_ref, *, nb: int, m: int):
    """Factor the transposed panel At (nb, m) IN PLACE; alpha out is (nb, 1).

    ``off_ref`` (SMEM scalar) is the panel's row offset: the reflector for
    local column j starts at row ``off + j``. Rows above it hold R entries
    of earlier panels and are preserved. Offset 0 = standalone panel.

    ``at_ref`` is aliased to ``out_ref`` (``input_output_aliases`` in the
    ``pallas_call``), and the column loop mutates ``out_ref`` directly
    rather than carrying the panel as a loop value — the HBM in/out
    buffers are shared; step temporaries may still hold a second panel
    copy in VMEM (see :func:`pallas_panel_supported`).
    """
    lane = lax.broadcasted_iota(jnp.int32, (1, m), 1)  # (1, m) panel row index
    off = off_ref[0]
    out_ref[:, :] = at_ref[:, :]  # no-op when aliased

    def step(jloc, acc):
        from jax.experimental import pallas as pl

        j = off + jloc  # diagonal row of this reflector
        at = out_ref[:, :]
        row = out_ref[pl.dslice(jloc, 1), :]  # (1, m)
        rmask = lane >= j
        rowm = jnp.where(rmask, row, 0.0)
        s = jnp.sqrt(_sumsq_compensated(rowm))
        a_jj = jnp.sum(jnp.where(lane == j, row, 0.0))
        alpha_j = jnp.where(a_jj >= 0, -s, s)  # s * alphafactor(a_jj) (src:8-9)
        denom = s * (s + jnp.abs(a_jj))
        f = jnp.where(denom > 0, 1.0 / jnp.sqrt(jnp.where(denom > 0, denom, 1.0)), 0.0)
        v = (rowm - alpha_j * (lane == j)) * f  # (1, m), ||v||^2 = 2
        # All partial dots at once: W[jj] = <v, At[jj, :]> (contraction over m).
        # HIGHEST: full-f32 MXU passes — same reason as DEFAULT_PRECISION in
        # ops/householder.py; bf16 passes here would poison every reflector.
        W = jax.lax.dot_general(
            at, v,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (nb, 1)
        row_ids = lax.broadcasted_iota(jnp.int32, (nb, 1), 0)
        W = jnp.where(row_ids > jloc, W, 0.0)  # update only trailing columns
        # rank-1: the reference hotloop! over all jj (src:150-160), then the
        # reflector overwrites row jloc (the old column content).
        out_ref[:, :] = at - W * v
        out_ref[pl.dslice(jloc, 1), :] = jnp.where(rmask, v, row)
        # Mosaic forbids scalar stores to VMEM — alpha rides the loop carry
        # as an (nb, 1) vector select and is stored once after the sweep.
        return jnp.where(row_ids == jloc, alpha_j, acc)

    alpha_ref[:, :] = lax.fori_loop(
        0, nb, step, jnp.zeros((nb, 1), jnp.float32)
    )


def _panel_kernel_c64(off_ref, ar_ref, ai_ref, or_ref, oi_ref,
                      alr_ref, ali_ref, *, nb: int, m: int):
    """Complex64 twin of :func:`_panel_kernel`, planar re/im f32 planes.

    The reference ships its complex fast kernel ACTIVE in the hot path
    (src:174-196, 4-wide f64 lanes with shuffle/sign vectors); here the
    complex algebra is spelled as real plane arithmetic so the VPU/MXU see
    only f32: conj(v).x = (vr.xr + vi.xi) + i(vr.xi - vi.xr), and the
    rank-1 update  x -= W v  splits into two real outer-product pairs.
    """
    from jax.experimental import pallas as pl

    lane = lax.broadcasted_iota(jnp.int32, (1, m), 1)
    off = off_ref[0]
    or_ref[:, :] = ar_ref[:, :]  # no-ops when aliased
    oi_ref[:, :] = ai_ref[:, :]

    def _dot(a, b):  # (nb, m) x (1, m) -> (nb, 1), contraction over m
        return jax.lax.dot_general(
            a, b, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )

    def step(jloc, acc):
        accr, acci = acc
        j = off + jloc
        atr = or_ref[:, :]
        ati = oi_ref[:, :]
        rowr = or_ref[pl.dslice(jloc, 1), :]
        rowi = oi_ref[pl.dslice(jloc, 1), :]
        rmask = lane >= j
        rowmr = jnp.where(rmask, rowr, 0.0)
        rowmi = jnp.where(rmask, rowi, 0.0)
        s = jnp.sqrt(_sumsq_compensated(rowmr) + _sumsq_compensated(rowmi))
        ar_jj = jnp.sum(jnp.where(lane == j, rowr, 0.0))
        ai_jj = jnp.sum(jnp.where(lane == j, rowi, 0.0))
        mag = jnp.sqrt(ar_jj * ar_jj + ai_jj * ai_jj)
        # alpha = s * (-a/|a|), with the reference's zero-pivot guard -> -1
        # (alphafactor, src:8-9 / ops/householder.py).
        inv = jnp.where(mag > 0, 1.0 / jnp.where(mag > 0, mag, 1.0), 0.0)
        alr = s * jnp.where(mag > 0, -ar_jj * inv, -1.0)
        ali = s * jnp.where(mag > 0, -ai_jj * inv, 0.0)
        denom = s * (s + mag)
        f = jnp.where(denom > 0, 1.0 / jnp.sqrt(jnp.where(denom > 0, denom, 1.0)), 0.0)
        ej = (lane == j).astype(jnp.float32)
        vr = (rowmr - alr * ej) * f
        vi = (rowmi - ali * ej) * f
        # W[jj] = conj(v) . At[jj, :]  (four real contractions)
        Wr = _dot(atr, vr) + _dot(ati, vi)
        Wi = _dot(ati, vr) - _dot(atr, vi)
        row_ids = lax.broadcasted_iota(jnp.int32, (nb, 1), 0)
        trail = row_ids > jloc
        Wr = jnp.where(trail, Wr, 0.0)
        Wi = jnp.where(trail, Wi, 0.0)
        # x -= W v  (complex rank-1; the reference's SIMD hotloop!, src:174-196)
        or_ref[:, :] = atr - (Wr * vr - Wi * vi)
        oi_ref[:, :] = ati - (Wr * vi + Wi * vr)
        or_ref[pl.dslice(jloc, 1), :] = jnp.where(rmask, vr, rowr)
        oi_ref[pl.dslice(jloc, 1), :] = jnp.where(rmask, vi, rowi)
        # Scalar VMEM stores are illegal in Mosaic — alpha planes ride the
        # loop carry as (nb, 1) vector selects, stored once after the sweep.
        return (jnp.where(row_ids == jloc, alr, accr),
                jnp.where(row_ids == jloc, ali, acci))

    zero = jnp.zeros((nb, 1), jnp.float32)
    alr_ref[:, :], ali_ref[:, :] = lax.fori_loop(0, nb, step, (zero, zero))


def _panel_qr_pallas_impl(panel, offset, interpret=False):
    """Guarded entry: interpret-mode compiles stay out of the persistent
    cache (``ops.blocked._pallas_cache_guard`` — host-callback executables
    are process-local). When called inside another jit's trace the guard
    is a harmless no-op (the real compile happens later at the outer jit,
    whose own entry point carries the guard)."""
    from dhqr_tpu.ops.blocked import _pallas_cache_guard

    with _pallas_cache_guard(interpret):
        return _panel_qr_pallas_jit(panel, offset, interpret=interpret)


@partial(jax.jit, static_argnames=("interpret",))
def _panel_qr_pallas_jit(panel, offset, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, nb = panel.shape
    off = jnp.asarray(offset, dtype=jnp.int32).reshape((1,))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)

    if panel.dtype == jnp.complex64:
        atr = jnp.real(panel).T  # (nb, m) planes: column j -> sublane row j
        ati = jnp.imag(panel).T
        kernel = partial(_panel_kernel_c64, nb=nb, m=m)
        outr, outi, alr, ali = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((nb, m), jnp.float32),
                jax.ShapeDtypeStruct((nb, m), jnp.float32),
                jax.ShapeDtypeStruct((nb, 1), jnp.float32),
                jax.ShapeDtypeStruct((nb, 1), jnp.float32),
            ),
            in_specs=[smem, vmem, vmem],
            out_specs=(vmem, vmem, vmem, vmem),
            input_output_aliases={1: 0, 2: 1},  # planes factored in place
            interpret=interpret,
        )(off, atr, ati)
        out = jax.lax.complex(outr.T, outi.T)
        return out, jax.lax.complex(alr[:, 0], ali[:, 0])

    at = panel.T  # (nb, m): column j -> sublane row j
    kernel = partial(_panel_kernel, nb=nb, m=m)
    out, alpha = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((nb, m), panel.dtype),
            jax.ShapeDtypeStruct((nb, 1), panel.dtype),
        ),
        in_specs=[smem, vmem],
        out_specs=(vmem, vmem),
        input_output_aliases={1: 0},  # factor the panel in place
        interpret=interpret,
    )(off, at)
    return out.T, alpha[:, 0]


def panel_qr_pallas(panel: jax.Array, interpret: bool = False):
    """Factor an (m, nb) float32/complex64 panel with the fused VMEM kernel.

    Returns ``(pf, alpha)`` in the same packed storage as
    :func:`dhqr_tpu.ops.householder.householder_qr`. ``interpret=True`` runs
    the Pallas interpreter (CPU testing — the moral equivalent of the
    reference exercising its SIMD kernels in serial tests, SURVEY.md §4).
    """
    m, nb = panel.shape
    if m < nb:
        raise ValueError(f"panel_qr_pallas requires m >= nb, got {panel.shape}")
    if panel.dtype not in (jnp.float32, jnp.complex64):
        raise ValueError(
            f"panel_qr_pallas supports float32/complex64, got {panel.dtype}"
        )
    return _panel_qr_pallas_impl(panel, 0, interpret=interpret)
