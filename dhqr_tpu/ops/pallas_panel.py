"""Pallas TPU kernel: fused in-VMEM panel factorization (layer L0).

The hot serial region of blocked QR is the panel factorization — nb
dependent column steps, each a small norm + scale + rank-1 update. Run
through XLA, every step round-trips the panel through HBM; the whole panel
loop is latency-bound exactly like the reference's per-column broadcast loop
(reference src/DistributedHouseholderQR.jl:127-148, flagged "this is most
expensive" at src:141). This kernel is the TPU counterpart of the
reference's hand-written SIMD micro-kernels (``partialdot``/``hotloop!``,
src:42-59, 150-196): it keeps the entire panel resident in VMEM and runs all
nb column steps in one kernel launch.

Layout: the panel is processed *transposed* — ``At`` is (nb, m), one panel
column per sublane row — because Pallas/Mosaic supports dynamic indexing on
the second-to-last (sublane) axis, while the contraction and rank-1 update
vectorize along the m-length lane axis. Per column j:

    row_j = At[j, :]                     (dynamic sublane read)
    s     = ||row_j masked to i >= j||
    v     = f * (row_j - alpha_j e_j)    (reference scaling, ||v||^2 = 2)
    W     = At @ v                       (all partial dots at once)
    At   -= W[:, None] * v[None, :]      (all rank-1 axpys at once)

with row masks ``i >= j`` and row masks ``jj > j`` replacing the ragged
ranges. The reflector formulas match :func:`dhqr_tpu.ops.householder`
(alpha sign rule src:8-9, ``f = 1/sqrt(s(s+|a_jj|))`` src:131), but the
column norm is a plain f32 sum of squares, NOT the compensated tree of
``ops/summation.py`` — rounding differs from the XLA engine by a few ulps
per column, which is why the kernel stays opt-in (``use_pallas="always"``)
until its backward error is validated on hardware.

Float32 only (TPU-native dtype; f64 stays on the XLA path, complex is
unsupported by Mosaic), and the panel must fit in VMEM — callers gate via
:func:`pallas_panel_supported`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

# VMEM working-set budget for the transposed panel (bytes). The chip has
# ~16 MiB per core; the kernel factors the panel IN PLACE (the input block
# is aliased to the output, see ``input_output_aliases`` below) so only one
# panel copy plus the per-step reflector/dot scratch is resident.
_VMEM_PANEL_BUDGET = 12 * 1024 * 1024


def pallas_panel_supported(m: int, nb: int, dtype) -> bool:
    """True when the fused kernel can factor an (m, nb) f32 panel in VMEM."""
    if jnp.dtype(dtype) != jnp.float32:
        return False
    # The panel is factored in place (input aliased to output), but the
    # step body still materializes panel-sized intermediates (the W*v
    # outer product and the updated panel value) unless Mosaic fuses the
    # chain — so budget TWO resident panel copies until the single-copy
    # limit is validated on hardware.
    return 2 * m * nb * 4 + 4 * m * 4 <= _VMEM_PANEL_BUDGET


def _panel_kernel(off_ref, at_ref, out_ref, alpha_ref, *, nb: int, m: int):
    """Factor the transposed panel At (nb, m) IN PLACE; alpha out is (nb, 1).

    ``off_ref`` (SMEM scalar) is the panel's row offset: the reflector for
    local column j starts at row ``off + j``. Rows above it hold R entries
    of earlier panels and are preserved. Offset 0 = standalone panel.

    ``at_ref`` is aliased to ``out_ref`` (``input_output_aliases`` in the
    ``pallas_call``), and the column loop mutates ``out_ref`` directly
    rather than carrying the panel as a loop value — the HBM in/out
    buffers are shared; step temporaries may still hold a second panel
    copy in VMEM (see :func:`pallas_panel_supported`).
    """
    lane = lax.broadcasted_iota(jnp.int32, (1, m), 1)  # (1, m) panel row index
    off = off_ref[0]
    out_ref[:, :] = at_ref[:, :]  # no-op when aliased

    def step(jloc, _):
        from jax.experimental import pallas as pl

        j = off + jloc  # diagonal row of this reflector
        at = out_ref[:, :]
        row = out_ref[pl.dslice(jloc, 1), :]  # (1, m)
        rmask = lane >= j
        rowm = jnp.where(rmask, row, 0.0)
        s = jnp.sqrt(jnp.sum(rowm * rowm))
        a_jj = jnp.sum(jnp.where(lane == j, row, 0.0))
        alpha_j = jnp.where(a_jj >= 0, -s, s)  # s * alphafactor(a_jj) (src:8-9)
        denom = s * (s + jnp.abs(a_jj))
        f = jnp.where(denom > 0, 1.0 / jnp.sqrt(jnp.where(denom > 0, denom, 1.0)), 0.0)
        v = (rowm - alpha_j * (lane == j)) * f  # (1, m), ||v||^2 = 2
        # All partial dots at once: W[jj] = <v, At[jj, :]> (contraction over m).
        # HIGHEST: full-f32 MXU passes — same reason as DEFAULT_PRECISION in
        # ops/householder.py; bf16 passes here would poison every reflector.
        W = jax.lax.dot_general(
            at, v,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (nb, 1)
        row_ids = lax.broadcasted_iota(jnp.int32, (nb, 1), 0)
        W = jnp.where(row_ids > jloc, W, 0.0)  # update only trailing columns
        # rank-1: the reference hotloop! over all jj (src:150-160), then the
        # reflector overwrites row jloc (the old column content).
        out_ref[:, :] = at - W * v
        out_ref[pl.dslice(jloc, 1), :] = jnp.where(rmask, v, row)
        alpha_ref[jloc, 0] = alpha_j
        return 0

    lax.fori_loop(0, nb, step, 0)


@partial(jax.jit, static_argnames=("interpret",))
def _panel_qr_pallas_impl(panel, offset, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, nb = panel.shape
    at = panel.T  # (nb, m): column j -> sublane row j
    off = jnp.asarray(offset, dtype=jnp.int32).reshape((1,))
    kernel = partial(_panel_kernel, nb=nb, m=m)
    out, alpha = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((nb, m), panel.dtype),
            jax.ShapeDtypeStruct((nb, 1), panel.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        input_output_aliases={1: 0},  # factor the panel in place
        interpret=interpret,
    )(off, at)
    return out.T, alpha[:, 0]


def panel_qr_pallas(panel: jax.Array, interpret: bool = False):
    """Factor an (m, nb) Float32 panel with the fused VMEM kernel.

    Returns ``(pf, alpha)`` in the same packed storage as
    :func:`dhqr_tpu.ops.householder.householder_qr`. ``interpret=True`` runs
    the Pallas interpreter (CPU testing — the moral equivalent of the
    reference exercising its SIMD kernels in serial tests, SURVEY.md §4).
    """
    m, nb = panel.shape
    if m < nb:
        raise ValueError(f"panel_qr_pallas requires m >= nb, got {panel.shape}")
    if panel.dtype != jnp.float32:
        raise ValueError(f"panel_qr_pallas is float32-only, got {panel.dtype}")
    return _panel_qr_pallas_impl(panel, 0, interpret=interpret)
