"""TSQR — communication-avoiding QR for tall-skinny matrices.

The reference never partitions rows (its hard invariant, reference
src/DistributedHouseholderQR.jl:33): a 65536 x 256 least-squares problem on
its column layout puts at most 256 columns across workers and leaves the
long dimension serial. TSQR is the TPU-right algorithm for m >> n and goes
*beyond* the reference's capability set deliberately (SURVEY.md §6 lists
tall-skinny 65536x256 as a target config):

    leaf stage:    split rows into blocks; QR each block independently
                   (perfectly parallel, each an MXU-dense blocked QR);
    combine stage: stack the per-block R factors (pn x n, tiny) and QR once.

For least squares the orthogonal factors never materialize: each stage also
carries c = Q^H b, so ``x = R^{-1} c[:n]`` drops out of the tree — the same
"never form Q" discipline as the reference's solve path (src:215-294).

This module is the single-device engine (row blocks looped in one program);
``dhqr_tpu.parallel.sharded_tsqr`` runs the leaves on a row-sharded mesh
with one small all-gather as the combine.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dhqr_tpu.ops.blocked import (
    DEFAULT_BLOCK_SIZE,
    _apply_qt_impl,
    _blocked_qr_impl,
)
from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.ops.solve import as_matrix_rhs, back_substitute, r_matrix


def _leaf_factor(Ai, bi, nb, precision, pallas=False, interpret=False,
                 pallas_flat=None, trailing_precision=None):
    """One row block: packed QR + Q^H b, reduced to the (n, n) / (n, k) heads.

    ``pallas`` routes the leaf's panel factorizations through the fused
    VMEM kernel (vmap over leaves batches the kernel onto a Pallas grid) —
    the leaf panel loop is exactly the latency-bound region the kernel
    exists for: round-3 hardware measured the XLA leaf loop at 0.24-0.73 s
    per 65536x256 factorization while CholeskyQR2 (all GEMM) took 0.9 ms.
    """
    n = Ai.shape[1]
    H, alpha = _blocked_qr_impl(Ai, nb, precision=precision, pallas=pallas,
                                pallas_interpret=interpret,
                                pallas_flat=pallas_flat,
                                trailing_precision=trailing_precision)
    R = r_matrix(H, alpha)
    c = _apply_qt_impl(H, bi, nb, precision=precision)[:n]
    return R, c


def _combine_factor(Rstack, cstack, nb, precision, pallas=False,
                    interpret=False, pallas_flat=None,
                    trailing_precision=None):
    """Combine stage, factored form: QR the stacked heads, reduce the
    rhs. Returns ``(H2, alpha2, c2)`` — what :func:`_combine_solve`
    back-substitutes, and what the COMPRESSED sharded combine
    (parallel/sharded_tsqr, round 18) keeps so its CSNE sweeps can
    reuse the combine R; one spelling for both so the paths cannot
    numerically diverge."""
    H2, alpha2 = _blocked_qr_impl(Rstack, nb, precision=precision,
                                  pallas=pallas, pallas_interpret=interpret,
                                  pallas_flat=pallas_flat,
                                  trailing_precision=trailing_precision)
    c2 = _apply_qt_impl(H2, cstack, nb, precision=precision)
    return H2, alpha2, c2


def _combine_solve(Rstack, cstack, nb, precision, pallas=False,
                   interpret=False, pallas_flat=None,
                   trailing_precision=None):
    """Combine stage: QR the stacked heads, then solve R x = (Q^H c)[:n]."""
    H2, alpha2, c2 = _combine_factor(Rstack, cstack, nb, precision, pallas,
                                     interpret, pallas_flat,
                                     trailing_precision)
    return back_substitute(H2, alpha2, c2)


@partial(jax.jit, static_argnames=("n_blocks", "block_size", "precision",
                                   "pallas", "interpret", "pallas_flat",
                                   "trailing_precision"))
def _tsqr_lstsq_impl(A, b, n_blocks, block_size, precision, pallas=False,
                     interpret=False, pallas_flat=None,
                     trailing_precision=None):
    m, n = A.shape
    rows = m // n_blocks
    nb = min(block_size, n)
    B, restore = as_matrix_rhs(b)
    k = B.shape[1]
    # Leaves: vmapped over row blocks — XLA batches the block QRs.
    Ab = A.reshape(n_blocks, rows, n)
    bb = B.reshape(n_blocks, rows, k)
    Rs, cs = jax.vmap(
        lambda Ai, bi: _leaf_factor(Ai, bi, nb, precision, pallas, interpret,
                                    pallas_flat, trailing_precision)
    )(Ab, bb)
    # Combine: one QR of the stacked R factors (n_blocks*n x n — tiny).
    Rstack = Rs.reshape(n_blocks * n, n)
    cstack = cs.reshape(n_blocks * n, k)
    return restore(_combine_solve(Rstack, cstack, nb, precision, pallas,
                                  interpret, pallas_flat,
                                  trailing_precision))


def tsqr_lstsq(
    A: jax.Array,
    b: jax.Array,
    n_blocks: int = 8,
    block_size: int = DEFAULT_BLOCK_SIZE,
    precision: str = DEFAULT_PRECISION,
    use_pallas: str = "auto",
    trailing_precision: "str | None" = None,
    policy=None,
) -> jax.Array:
    """Least squares via TSQR: ``x = argmin ||A x - b||`` for m >> n.

    ``b`` may be a vector (m,) or a block of right-hand sides (m, k).
    Requires m divisible by ``n_blocks`` with each block still tall
    (m / n_blocks >= n). Unconditionally stable (Householder at both
    levels), unlike semi-normal-equation shortcuts.

    ``use_pallas`` routes the leaf/combine panel factorizations through the
    fused VMEM kernel (same semantics as
    :func:`dhqr_tpu.ops.blocked.blocked_householder_qr`): "auto" resolves
    to the kernel on TPU for supported leaf shapes.

    ``trailing_precision`` / ``policy`` split the leaf and combine QRs'
    trailing-update GEMM precision exactly as on the blocked engine
    (``policy.panel`` -> ``precision``, ``policy.trailing`` -> this knob).
    ``policy.refine`` must be 0: the TSQR tree never materializes a
    reusable factorization, so refinement would repeat the full
    factorization cost per sweep — route refined solves to the
    householder or cholqr engines. (The numeric fallback ladder's tsqr
    rung runs refine=0 for exactly this reason and leans on the
    residual gate instead — dhqr_tpu/numeric/ladder.py.)
    """
    from dhqr_tpu.precision import (apply_policy_to_factor_args,
                                    resolve_policy)
    from dhqr_tpu.utils.platform import ensure_complex_supported

    if policy is not None and resolve_policy(policy).refine:
        raise ValueError(
            "policy.refine > 0 is not supported with TSQR (no reusable "
            "factorization in the tree); use the householder or cholqr "
            "engines, or a refine=0 policy"
        )
    precision, trailing_precision = apply_policy_to_factor_args(
        policy, precision, trailing_precision,
        default_precision=DEFAULT_PRECISION)
    m, n = A.shape
    _check_tsqr_shape(m, n, n_blocks)
    ensure_complex_supported(A.dtype)
    pallas, interpret = _resolve_tsqr_pallas(use_pallas, m // int(n_blocks),
                                             n, int(block_size), A.dtype)
    from dhqr_tpu.ops.blocked import (PALLAS_FLAT_WIDTH,
                                        _pallas_cache_guard)

    with _pallas_cache_guard(interpret):
        return _tsqr_lstsq_impl(A, b, int(n_blocks), int(block_size),
                                precision, pallas=pallas,
                                interpret=interpret,
                                pallas_flat=PALLAS_FLAT_WIDTH,
                                trailing_precision=trailing_precision)


def _resolve_tsqr_pallas(mode, leaf_rows, n, block_size, dtype):
    """Resolve ``use_pallas`` against the LEAF shape (the tall stage).

    The combine stack re-gates per super-block inside ``_blocked_qr_impl``
    (``pallas_panel_supported``), so one leaf-level decision suffices.
    """
    from dhqr_tpu.ops.blocked import _resolve_pallas

    return _resolve_pallas(mode, leaf_rows, min(block_size, n), dtype)


@partial(jax.jit, static_argnames=("n_blocks", "block_size", "precision",
                                   "pallas", "interpret", "pallas_flat",
                                   "trailing_precision"))
def _tsqr_r_impl(A, n_blocks, block_size, precision, pallas=False,
                 interpret=False, pallas_flat=None, trailing_precision=None):
    m, n = A.shape
    rows = m // n_blocks
    nb = min(block_size, n)
    Ab = A.reshape(n_blocks, rows, n)
    Rs = jax.vmap(
        lambda Ai: r_matrix(*_blocked_qr_impl(
            Ai, nb, precision=precision, pallas=pallas,
            pallas_interpret=interpret, pallas_flat=pallas_flat,
            trailing_precision=trailing_precision))
    )(Ab)
    H2, alpha2 = _blocked_qr_impl(Rs.reshape(n_blocks * n, n), nb,
                                  precision=precision, pallas=pallas,
                                  pallas_interpret=interpret,
                                  pallas_flat=pallas_flat,
                                  trailing_precision=trailing_precision)
    return r_matrix(H2, alpha2)


def tsqr_r(
    A: jax.Array,
    n_blocks: int = 8,
    block_size: int = DEFAULT_BLOCK_SIZE,
    precision: str = DEFAULT_PRECISION,
    use_pallas: str = "auto",
    trailing_precision: "str | None" = None,
    policy=None,
) -> jax.Array:
    """The n x n triangular factor of A via TSQR (R up to row signs).

    Note: Householder QR fixes R's diagonal signs by the alpha rule
    (src:8-9), so R here may differ from another QR's R by a diagonal +-1
    factor — ``R^H R = A^H A`` holds regardless.

    ``trailing_precision`` / ``policy`` as in :func:`tsqr_lstsq`; the
    solve-stage policy fields (``apply``, ``refine``) do not apply to a
    factor-only entry point and are ignored by contract.
    """
    from dhqr_tpu.precision import apply_policy_to_factor_args
    from dhqr_tpu.utils.platform import ensure_complex_supported

    precision, trailing_precision = apply_policy_to_factor_args(
        policy, precision, trailing_precision,
        default_precision=DEFAULT_PRECISION)
    m, n = A.shape
    _check_tsqr_shape(m, n, n_blocks)
    ensure_complex_supported(A.dtype)
    pallas, interpret = _resolve_tsqr_pallas(use_pallas, m // int(n_blocks),
                                             n, int(block_size), A.dtype)
    from dhqr_tpu.ops.blocked import (PALLAS_FLAT_WIDTH,
                                        _pallas_cache_guard)

    with _pallas_cache_guard(interpret):
        return _tsqr_r_impl(A, int(n_blocks), int(block_size), precision,
                            pallas=pallas, interpret=interpret,
                            pallas_flat=PALLAS_FLAT_WIDTH,
                            trailing_precision=trailing_precision)


def _check_tsqr_shape(m: int, n: int, n_blocks: int) -> None:
    if m % n_blocks != 0:
        raise ValueError(f"m={m} must be divisible by n_blocks={n_blocks}")
    if m // n_blocks < n:
        raise ValueError(
            f"row blocks must stay tall: m/n_blocks = {m // n_blocks} < n = {n}; "
            f"use fewer blocks"
        )
