"""TSQR — communication-avoiding QR for tall-skinny matrices.

The reference never partitions rows (its hard invariant, reference
src/DistributedHouseholderQR.jl:33): a 65536 x 256 least-squares problem on
its column layout puts at most 256 columns across workers and leaves the
long dimension serial. TSQR is the TPU-right algorithm for m >> n and goes
*beyond* the reference's capability set deliberately (SURVEY.md §6 lists
tall-skinny 65536x256 as a target config):

    leaf stage:    split rows into blocks; QR each block independently
                   (perfectly parallel, each an MXU-dense blocked QR);
    combine stage: stack the per-block R factors (pn x n, tiny) and QR once.

For least squares the orthogonal factors never materialize: each stage also
carries c = Q^H b, so ``x = R^{-1} c[:n]`` drops out of the tree — the same
"never form Q" discipline as the reference's solve path (src:215-294).

This module is the single-device engine (row blocks looped in one program);
``dhqr_tpu.parallel.sharded_tsqr`` runs the leaves on a row-sharded mesh
with one small all-gather as the combine.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dhqr_tpu.ops.blocked import (
    DEFAULT_BLOCK_SIZE,
    _apply_qt_impl,
    _blocked_qr_impl,
)
from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.ops.solve import as_matrix_rhs, back_substitute, r_matrix


def _leaf_factor(Ai, bi, nb, precision):
    """One row block: packed QR + Q^H b, reduced to the (n, n) / (n, k) heads."""
    n = Ai.shape[1]
    H, alpha = _blocked_qr_impl(Ai, nb, precision=precision)
    R = r_matrix(H, alpha)
    c = _apply_qt_impl(H, bi, nb, precision=precision)[:n]
    return R, c


def _combine_solve(Rstack, cstack, nb, precision):
    """Combine stage: QR the stacked heads, then solve R x = (Q^H c)[:n]."""
    H2, alpha2 = _blocked_qr_impl(Rstack, nb, precision=precision)
    c2 = _apply_qt_impl(H2, cstack, nb, precision=precision)
    return back_substitute(H2, alpha2, c2)


@partial(jax.jit, static_argnames=("n_blocks", "block_size", "precision"))
def _tsqr_lstsq_impl(A, b, n_blocks, block_size, precision):
    m, n = A.shape
    rows = m // n_blocks
    nb = min(block_size, n)
    B, restore = as_matrix_rhs(b)
    k = B.shape[1]
    # Leaves: vmapped over row blocks — XLA batches the block QRs.
    Ab = A.reshape(n_blocks, rows, n)
    bb = B.reshape(n_blocks, rows, k)
    Rs, cs = jax.vmap(lambda Ai, bi: _leaf_factor(Ai, bi, nb, precision))(Ab, bb)
    # Combine: one QR of the stacked R factors (n_blocks*n x n — tiny).
    Rstack = Rs.reshape(n_blocks * n, n)
    cstack = cs.reshape(n_blocks * n, k)
    return restore(_combine_solve(Rstack, cstack, nb, precision))


def tsqr_lstsq(
    A: jax.Array,
    b: jax.Array,
    n_blocks: int = 8,
    block_size: int = DEFAULT_BLOCK_SIZE,
    precision: str = DEFAULT_PRECISION,
) -> jax.Array:
    """Least squares via TSQR: ``x = argmin ||A x - b||`` for m >> n.

    ``b`` may be a vector (m,) or a block of right-hand sides (m, k).
    Requires m divisible by ``n_blocks`` with each block still tall
    (m / n_blocks >= n). Unconditionally stable (Householder at both
    levels), unlike semi-normal-equation shortcuts.
    """
    m, n = A.shape
    _check_tsqr_shape(m, n, n_blocks)
    return _tsqr_lstsq_impl(A, b, int(n_blocks), int(block_size), precision)


@partial(jax.jit, static_argnames=("n_blocks", "block_size", "precision"))
def _tsqr_r_impl(A, n_blocks, block_size, precision):
    m, n = A.shape
    rows = m // n_blocks
    nb = min(block_size, n)
    Ab = A.reshape(n_blocks, rows, n)
    Rs = jax.vmap(
        lambda Ai: r_matrix(*_blocked_qr_impl(Ai, nb, precision=precision))
    )(Ab)
    H2, alpha2 = _blocked_qr_impl(Rs.reshape(n_blocks * n, n), nb,
                                  precision=precision)
    return r_matrix(H2, alpha2)


def tsqr_r(
    A: jax.Array,
    n_blocks: int = 8,
    block_size: int = DEFAULT_BLOCK_SIZE,
    precision: str = DEFAULT_PRECISION,
) -> jax.Array:
    """The n x n triangular factor of A via TSQR (R up to row signs).

    Note: Householder QR fixes R's diagonal signs by the alpha rule
    (src:8-9), so R here may differ from another QR's R by a diagonal +-1
    factor — ``R^H R = A^H A`` holds regardless.
    """
    m, n = A.shape
    _check_tsqr_shape(m, n, n_blocks)
    return _tsqr_r_impl(A, int(n_blocks), int(block_size), precision)


def _check_tsqr_shape(m: int, n: int, n_blocks: int) -> None:
    if m % n_blocks != 0:
        raise ValueError(f"m={m} must be divisible by n_blocks={n_blocks}")
    if m // n_blocks < n:
        raise ValueError(
            f"row blocks must stay tall: m/n_blocks = {m // n_blocks} < n = {n}; "
            f"use fewer blocks"
        )
