"""Solver engine — apply Q^H, back-substitute R (layer L3 of SURVEY.md §1).

TPU-native equivalent of the reference solve path
(reference src/DistributedHouseholderQR.jl:215-294): stage 1 applies
``Q^H = H_n ... H_1`` to b column by column (src:215-224), stage 2
back-substitutes with R whose diagonal lives in ``alpha`` and whose strict
upper triangle lives in H (src:244-254). Here stage 2 is a single
``lax.linalg.triangular_solve`` on the assembled R — a dense blocked sweep
that feeds the MXU instead of the reference's n sequential rounds of
scalar reductions (src:256-282).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.ops.summation import accurate_vdot


def _reflector_column(H: jax.Array, j: jax.Array) -> jax.Array:
    """Extract reflector v_j: column j of H with rows < j zeroed."""
    m = H.shape[0]
    col = lax.dynamic_slice_in_dim(H, j, 1, axis=1)[:, 0]
    return jnp.where(lax.iota(jnp.int32, m) >= j, col, jnp.zeros_like(col))


def as_matrix_rhs(b):
    """(B, restore): view a vector RHS as an (m, 1) block and a function
    restoring the original rank — the one shared spelling of the
    vector/multi-RHS adapter used across the solve/TSQR/CholQR engines."""
    if b.ndim == 1:
        return b[:, None], lambda x: x[:, 0]
    return b, lambda x: x


@partial(jax.jit, static_argnames=("precision",))
def apply_qt(
    H: jax.Array, alpha: jax.Array, b: jax.Array, precision: str = DEFAULT_PRECISION
) -> jax.Array:
    """b <- Q^H b by applying reflectors j = 0..n-1 in order.

    Per step: ``s = v_j^H b; b -= v_j s`` — the reference's
    ``partialdot`` + batched axpy (src:215-224), with the ragged ``j:m``
    range replaced by the structural zeros of the masked reflector.
    For a single right-hand side the dot runs through the compensated
    pairwise tree (:func:`dhqr_tpu.ops.summation.accurate_vdot`) — the L0
    accuracy tier in the same position the reference uses ``partialdot``
    (src:218); a block of right-hand sides (m, k) uses one GEMV per step.
    """
    del alpha  # R's diagonal is not needed to apply Q^H (parity with src:215)
    n = H.shape[1]
    vec = b.ndim == 1
    B = b[:, None] if vec else b
    single = B.shape[1] == 1

    def step(j, B):
        v = _reflector_column(H, j)
        # conj(v)·b per rhs, reference partialdot (src:51-59)
        if single:
            s = accurate_vdot(v, B[:, 0])[None]
        else:
            s = jnp.matmul(jnp.conj(v), B, precision=precision)
        return B - v[:, None] * s[None, :]

    out = lax.fori_loop(0, n, step, B)
    return out[:, 0] if vec else out


@partial(jax.jit, static_argnames=("precision",))
def apply_q(
    H: jax.Array, alpha: jax.Array, b: jax.Array, precision: str = DEFAULT_PRECISION
) -> jax.Array:
    """b <- Q b by applying reflectors in reverse order (reconstruction aid).

    The reference never materializes Q; this is the standard companion used
    by our tests to form ``Q @ R`` and check the backward error ||QR - A||.
    ``b`` may be a vector (m,) or a block (m, k).
    """
    del alpha
    n = H.shape[1]
    vec = b.ndim == 1
    B = b[:, None] if vec else b

    def step(k, B):
        j = n - 1 - k
        v = _reflector_column(H, j)
        s = jnp.matmul(jnp.conj(v), B, precision=precision)
        return B - v[:, None] * s[None, :]

    out = lax.fori_loop(0, n, step, B)
    return out[:, 0] if vec else out


def r_matrix(H: jax.Array, alpha: jax.Array) -> jax.Array:
    """Assemble the n x n upper-triangular R from packed storage.

    R's strict upper triangle is in H's first n rows, its diagonal in
    ``alpha`` (reference storage scheme, src:244-254, 296-309).
    """
    n = H.shape[1]
    return jnp.triu(H[:n, :], k=1) + jnp.diag(alpha)


@jax.jit
def back_substitute(H: jax.Array, alpha: jax.Array, c: jax.Array) -> jax.Array:
    """Solve ``R x = c[:n]`` with R packed as (strict upper of H, alpha).

    Replaces the reference's n sequential rounds of partial row-dot
    reductions (src:256-282) with one dense triangular solve, which XLA
    lowers to a blocked MXU-friendly sweep. ``c`` may be a vector (m,) or a
    block of right-hand sides (m, k).
    """
    n = H.shape[1]
    with jax.named_scope("back_substitute"):  # the reference's t2 (src:291-292)
        R = r_matrix(H, alpha)
        vec = c.ndim == 1
        C = c[:n][:, None] if vec else c[:n]
        x = lax.linalg.triangular_solve(
            R, C, left_side=True, lower=False, conjugate_a=False
        )
    return x[:, 0] if vec else x


def solve_least_squares(H: jax.Array, alpha: jax.Array, b: jax.Array) -> jax.Array:
    """x = argmin ||A x - b|| given the packed factorization of A.

    Orchestrates stage 1 (Q^H apply) then stage 2 (back-substitution) and
    truncates to n — the reference's ``solve_householder!`` (src:284-294).
    """
    c = apply_qt(H, alpha, b)
    return back_substitute(H, alpha, c)
