"""Blocked compact-WY Householder QR — the MXU path (SURVEY.md §7 stage 3).

The reference's trailing update is a per-column rank-1 axpy
(reference src/DistributedHouseholderQR.jl:150-213), which is memory-bound by
design. On TPU the FLOPs must flow through the MXU as large GEMMs, so this
engine accumulates ``nb`` reflectors per panel and applies the panel's
aggregate transform

    H_nb ... H_1 = I - Y T^H Y^H        (each H_i = I - v_i v_i^H, ||v||^2=2)

to the trailing matrix as two GEMMs plus a small triangular solve. Because
the reference's scaling convention makes every tau equal 1, the T factor has
the closed form ``T = (I + triu(Y^H Y, 1))^{-1}`` — we never invert it,
applying ``T^H`` via a unit-diagonal triangular solve instead.

Program size is BOUNDED regardless of n (XLA traces everything once, so an
unrolled panel loop would grow the program — and TPU compile time — by
O(n/nb)): when there are more than :data:`MAX_UNROLLED_PANELS` panels, the
panel loop runs as a two-level scheme — an outer Python loop over at most
``MAX_UNROLLED_PANELS`` statically-sliced super-blocks (each re-slices rows
and columns, keeping the flop overhead to ~1/MAX_UNROLLED_PANELS), with a
``lax.scan`` over uniform-shape panels inside each super-block (panel
position passed as a traced row offset into the masked panel factorization).
Small problems keep the fully-unrolled shrinking-slice path, which does the
exact textbook flop count.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dhqr_tpu.ops.householder import (
    DEFAULT_PRECISION,
    _householder_qr_impl,
    _panel_qr_masked,
)

DEFAULT_BLOCK_SIZE = 128

# Max distinct panel/super-block program regions per trace. Program size and
# compile time scale with this constant, NOT with n; flop overhead of the
# scanned path scales with 1/MAX_UNROLLED_PANELS (each super-block's scan
# works on the super-block's full trailing shape instead of per-panel
# shrinking slices). DHQR_MAX_PANELS tunes the compile-time/flop-overhead
# trade for hardware experiments (read once at import).
import os as _os

MAX_UNROLLED_PANELS = int(_os.environ.get("DHQR_MAX_PANELS", "8"))


def wy_upper(Y: jax.Array, precision=DEFAULT_PRECISION) -> jax.Array:
    """U = I + triu(Y^H Y, 1), the inverse of the compact-WY T factor.

    Derivation: with tau_i = 1, T satisfies the larft recurrence
    ``T[:i, i] = -T[:i, :i] (Y[:, :i]^H y_i)``, whose inverse is the unit
    upper-triangular matrix carrying the strictly-upper part of Y^H Y.
    One (nb x m)@(m x nb) GEMM — MXU work, not a scalar recurrence.
    """
    nb = Y.shape[1]
    S = jnp.matmul(jnp.conj(Y.T), Y, precision=precision)
    return jnp.eye(nb, dtype=Y.dtype) + jnp.triu(S, k=1)


def apply_block_reflector_h(
    Y: jax.Array, C: jax.Array, precision=DEFAULT_PRECISION,
    gemm_precision=None,
) -> jax.Array:
    """C <- (I - Y T^H Y^H) C, i.e. apply H_nb ... H_1 (the Q^H direction).

    ``gemm_precision`` (default: same as ``precision``) applies to the two
    panel-sized GEMMs only; the T-factor (``wy_upper``) always uses
    ``precision`` — it is an nb x nb dependent recurrence whose error every
    later column inherits, while the big GEMMs' rounding is not amplified.
    """
    gp = precision if gemm_precision is None else gemm_precision
    U = wy_upper(Y, precision)
    W = jnp.matmul(jnp.conj(Y.T), C, precision=gp)
    Z = lax.linalg.triangular_solve(
        U, W, left_side=True, lower=False, transpose_a=True, conjugate_a=True,
        unit_diagonal=True,
    )
    return C - jnp.matmul(Y, Z, precision=gp)


def apply_block_reflector(
    Y: jax.Array, C: jax.Array, precision=DEFAULT_PRECISION
) -> jax.Array:
    """C <- (I - Y T Y^H) C, i.e. apply H_1 ... H_nb (the Q direction)."""
    U = wy_upper(Y, precision)
    W = jnp.matmul(jnp.conj(Y.T), C, precision=precision)
    Z = lax.linalg.triangular_solve(
        U, W, left_side=True, lower=False, transpose_a=False, conjugate_a=False,
        unit_diagonal=True,
    )
    return C - jnp.matmul(Y, Z, precision=precision)


def shifted_tril(pf: jax.Array, offset) -> jax.Array:
    """Zero entries above the shifted diagonal: keep rows >= offset + col.

    Extracts the Y factor from a factored panel whose reflector for local
    column jj starts at row ``offset + jj`` (``offset`` may be traced).
    ``offset=0`` is ``jnp.tril``.
    """
    rows = lax.iota(jnp.int32, pf.shape[0])[:, None]
    cols = lax.iota(jnp.int32, pf.shape[1])[None, :]
    return jnp.where(rows >= offset + cols, pf, jnp.zeros_like(pf))


def _panels_schedule(n: int, nb: int) -> tuple[int, int, int]:
    """(num_full_panels, remainder_width, panels_per_super_block)."""
    num_full = n // nb
    rem = n - num_full * nb
    ppo = -(-num_full // MAX_UNROLLED_PANELS) if num_full else 1  # ceil div
    return num_full, rem, ppo


def _panel_factor(panel, offset, precision, norm, panel_impl):
    """Panel-interior engine selector: "loop" = the masked fori_loop
    (reference-shaped numerics, one GEMV + rank-1 per column); "recursive" =
    geqrt3-style divide and conquer (panel interior on the MXU, see
    ops/householder._panel_qr_recursive); "reconstruct" = explicit QR +
    Householder reconstruction (real dtypes; see
    ops/householder._panel_qr_reconstruct)."""
    from dhqr_tpu.ops.householder import (
        _panel_qr_masked,
        _panel_qr_reconstruct,
        _panel_qr_recursive,
    )

    if panel_impl == "recursive":
        return _panel_qr_recursive(panel, offset, precision=precision,
                                   norm=norm)
    if panel_impl.startswith("reconstruct"):
        # Trace-time guard on the ONE chokepoint every route (qr, the
        # jitted lstsq core, sharded bodies) passes through — a complex
        # panel would otherwise produce silently wrong reflectors (the
        # sign/LU identities below assume real arithmetic).
        if jnp.issubdtype(panel.dtype, jnp.complexfloating):
            raise ValueError(
                "panel_impl='reconstruct' supports real dtypes only (the "
                "complex variant needs the phase-tracking modified LU — "
                "LAPACK zunhr_col; use 'loop' or 'recursive' for complex)"
            )
        return _panel_qr_reconstruct(panel, offset,
                                     tree_chunk=_reconstruct_chunk(panel_impl))
    if panel_impl == "loop":
        return _panel_qr_masked(panel, offset, precision=precision, norm=norm)
    raise ValueError(
        f"panel_impl must be 'loop', 'recursive', 'reconstruct' or "
        f"'reconstruct:<chunk>', got {panel_impl!r}")


def _reconstruct_chunk(panel_impl: str) -> int:
    """Row-chunk size from the ``reconstruct[:<chunk>]`` spelling (0 =
    direct QR). Raises on malformed spellings so a typo cannot silently
    select the direct path."""
    if panel_impl == "reconstruct":
        return 0
    try:
        chunk = int(panel_impl.split(":", 1)[1])
        if chunk <= 0:
            raise ValueError
        return chunk
    except (IndexError, ValueError):
        raise ValueError(
            f"malformed reconstruct spelling {panel_impl!r}: expected "
            "'reconstruct' or 'reconstruct:<positive chunk>'"
        ) from None


# Widest panel the fused kernel factors FLAT; wider panels split into
# base-width kernel calls + compact-WY applies (_panel_factor_pallas).
# The phase probe (benchmarks/results/tpu_r3_phase.jsonl) measured the
# kernel's serial column sweep at ~1.1-1.2 TFLOP/s useful rate — ~1/3 of
# total QR time at nb=512 — so splitting at 256 models ~0.57x the panel
# cost. The default stays 512 (every committed nb=512 hardware number was
# measured with FLAT 512 panels; the split is enabled by lowering this —
# DHQR_PALLAS_FLAT_WIDTH=256 — once its ladder is measured on hardware).
PALLAS_FLAT_WIDTH = int(_os.environ.get("DHQR_PALLAS_FLAT_WIDTH", "512"))


def _panel_factor_pallas(panel, offset, precision, interpret, base=None):
    """Fused-kernel panel factorization, split above ``base`` width.

    Width <= base (default :data:`PALLAS_FLAT_WIDTH`): one flat kernel
    call. Wider: the geqrt3 recursion (``householder._panel_qr_recursive``
    — left half, compact-WY GEMM apply, right half at the shifted offset)
    with the fused kernel as the leaf. Identical packed output to the
    flat kernel.
    """
    from dhqr_tpu.ops.householder import _panel_qr_recursive
    from dhqr_tpu.ops.pallas_panel import _panel_qr_pallas_impl

    if base is None:
        base = PALLAS_FLAT_WIDTH
    return _panel_qr_recursive(
        panel, offset, precision=precision, base=base,
        leaf=lambda p, off: _panel_qr_pallas_impl(p, off,
                                                  interpret=interpret),
    )


def _scan_panels(S, pcount, nb, precision, pallas, pallas_interpret,
                 norm="accurate", panel_impl="loop", gemm_precision=None,
                 pallas_flat=None):
    """Factor ``pcount`` uniform nb-wide panels of super-block S by scan.

    S is the (ms, ns) trailing submatrix whose top-left element is the
    super-block's first diagonal entry; panel q lives at rows/cols q*nb.
    Each iteration factors one panel (masked, traced row offset) and applies
    its compact-WY transform full-width, masked to columns right of the
    panel. One scan body total — program size O(1) in pcount.
    """
    ms, ns = S.shape

    def body(S, q):
        c = q * nb
        panel = lax.dynamic_slice(S, (jnp.int32(0), c), (ms, nb))
        if pallas:
            pf, alpha_k = _panel_factor_pallas(
                panel, c, precision, pallas_interpret, base=pallas_flat
            )
        else:
            pf, alpha_k = _panel_factor(panel, c, precision, norm, panel_impl)
        S = lax.dynamic_update_slice(S, pf, (jnp.int32(0), c))
        with jax.named_scope("trailing_update"):
            Y = shifted_tril(pf, c)
            C_new = apply_block_reflector_h(Y, S, precision,
                                            gemm_precision=gemm_precision)
            cmask = lax.iota(jnp.int32, ns) >= c + nb
            S = jnp.where(cmask[None, :], C_new, S)
        return S, alpha_k

    S, alphas = lax.scan(body, S, jnp.arange(pcount, dtype=jnp.int32))
    return S, alphas.reshape(pcount * nb)


def _factor_group(G, c0, gsize, nb, factor, precision, gemm_precision):
    """Factor a gathered ``gsize * nb``-wide panel group in place.

    The shared core of the aggregated schedules (single-device
    :func:`_scan_panels_grouped` and the mesh tier's
    ``sharded_qr._blocked_shard_agg``): panels factor left to right at
    the nb grain, each applying its compact-WY transform to the group's
    remaining columns only — ``c`` is a STATIC unrolled offset, so the
    interior applies slice the not-yet-factored columns directly with no
    masked-flop waste (unlike the per-panel scan, whose traced offset
    forces full-width compute + mask). ``c0`` is the group's diagonal row
    offset within G (traced in scanned callers). Returns the factored
    group and its concatenated alpha block.
    """
    ms, W = G.shape
    alphas = []
    for j in range(gsize):
        c = j * nb
        with jax.named_scope("panel_factor"):
            pf, a_j = factor(lax.slice(G, (0, c), (ms, c + nb)), c0 + c)
            G = lax.dynamic_update_slice(G, pf,
                                         (jnp.int32(0), jnp.int32(c)))
        alphas.append(a_j)
        if j < gsize - 1:
            with jax.named_scope("group_interior_update"):
                Y = shifted_tril(pf, c0 + c)
                Gr = lax.slice(G, (0, c + nb), (ms, W))
                G = G.at[:, c + nb :].set(
                    apply_block_reflector_h(Y, Gr, precision,
                                            gemm_precision=gemm_precision))
    return G, jnp.concatenate(alphas)


def _scan_panels_grouped(S, pcount, nb, k, precision, pallas,
                         pallas_interpret, norm="accurate", panel_impl="loop",
                         gemm_precision=None, pallas_flat=None):
    """Aggregated-update twin of :func:`_scan_panels` (same contract).

    Panels still factor at ``nb`` width (the Pallas/VMEM-optimal grain),
    but the trailing matrix right of a GROUP of ``k`` consecutive panels
    is updated ONCE per group, by the group's aggregated compact-WY
    transform — with the tau=1 convention the aggregate factor is just
    ``wy_upper`` of the k panels' packed reflectors side by side, so no
    new recurrence is needed. Wide trailing passes drop k-fold (each one
    carries fixed per-pass cost: T-factor, masking, fusion overhead — the
    "other" bucket in docs/DESIGN.md's 16384^2 ceiling arithmetic), and
    the in-group interior applies shrink from full remaining width to the
    group's k*nb columns (removing the masked-flop waste of the per-panel
    scan). The price is the larger aggregate T-solve and Y^H Y GEMM,
    ~O(m (k nb)^2) per group — a few percent of total flops at k <= 4.

    Groups of k panels run under ``lax.scan`` (panel loop unrolled k-wide
    inside the body — program size scales with k, not pcount); a
    remainder of ``pcount % k`` panels falls back to the per-panel scan.
    """
    ms, ns = S.shape

    def factor(panel, off):
        if pallas:
            return _panel_factor_pallas(panel, off, precision,
                                        pallas_interpret, base=pallas_flat)
        return _panel_factor(panel, off, precision, norm, panel_impl)

    ngroups, rem = pcount // k, pcount % k
    W = k * nb

    def body(S, g):
        cg = g * W  # group's first column (and diagonal row) within S
        G = lax.dynamic_slice(S, (jnp.int32(0), cg), (ms, W))
        G, alphas = _factor_group(G, cg, k, nb, factor, precision,
                                  gemm_precision)
        S = lax.dynamic_update_slice(S, G, (jnp.int32(0), cg))
        with jax.named_scope("trailing_update_agg"):
            Yg = shifted_tril(G, cg)  # all k panels' reflectors, tau=1
            C_new = apply_block_reflector_h(
                Yg, S, precision, gemm_precision=gemm_precision)
            cmask = lax.iota(jnp.int32, ns) >= cg + W
            S = jnp.where(cmask[None, :], C_new, S)
        return S, alphas

    alpha_parts = []
    if ngroups:
        S, alphas = lax.scan(body, S, jnp.arange(ngroups, dtype=jnp.int32))
        alpha_parts.append(alphas.reshape(ngroups * W))
    if rem:
        # Trailing pcount % k panels: per-panel scan on the remaining
        # slice (rows/cols past the grouped region), exactly the default
        # path's semantics.
        C0 = ngroups * W
        S_rem = lax.slice(S, (C0, C0), (ms, ns))
        S_rem, alpha_rem = _scan_panels(
            S_rem, rem, nb, precision, pallas, pallas_interpret, norm=norm,
            panel_impl=panel_impl, gemm_precision=gemm_precision,
            pallas_flat=pallas_flat,
        )
        S = S.at[C0:, C0:].set(S_rem)
        alpha_parts.append(alpha_rem)
    return S, jnp.concatenate(alpha_parts)


def _scan_panels_lookahead(S, pcount, nb, precision, pallas, pallas_interpret,
                           norm="accurate", panel_impl="loop",
                           gemm_precision=None, pallas_flat=None):
    """One-panel-lookahead twin of :func:`_scan_panels` (same contract).

    Standard lookahead reorders each step so the NEXT panel's
    factorization sits between the pending panel's two trailing pieces:
    panel q's transform is applied to panel q+1's columns only, panel q+1
    is factored immediately, and only then is panel q's transform applied
    to everything further right. The factored panel q+1 is written AFTER
    the wide apply, so the wide GEMM depends only on panel q — on the
    sharded tier that leaves the psum of panel q+1 with no consumer until
    the next scan iteration, letting XLA's latency-hiding scheduler
    overlap the collective with the wide trailing GEMM (the region the
    reference's author flags "this is most expensive", src:141-143).
    Column-wise the arithmetic is identical to the non-lookahead order:
    every column still receives panel transforms 0, 1, 2, ... in sequence.

    A final fix-up applies the last panel's transform to the columns right
    of the super-block (the non-lookahead scan does that inside its last
    iteration); the NEXT super-block's panel 0 is then already fully
    updated when its own lookahead sweep factors it up front — the
    super-block boundary is a one-panel bubble with no overlap.
    """
    ms, ns = S.shape

    def factor(panel, off):
        if pallas:
            return _panel_factor_pallas(panel, off, precision,
                                        pallas_interpret, base=pallas_flat)
        return _panel_factor(panel, off, precision, norm, panel_impl)

    with jax.named_scope("panel_factor"):
        pf0, a0 = factor(lax.slice(S, (0, 0), (ms, nb)), 0)
        S = lax.dynamic_update_slice(S, pf0, (jnp.int32(0), jnp.int32(0)))

    def body(carry, q):
        S, pf = carry
        c = q * nb          # pending panel q's diagonal offset
        c1 = c + nb         # panel q+1's start
        Y = shifted_tril(pf, c)
        with jax.named_scope("lookahead_update"):
            C1 = lax.dynamic_slice(S, (jnp.int32(0), c1), (ms, nb))
            C1 = apply_block_reflector_h(Y, C1, precision,
                                         gemm_precision=gemm_precision)
        with jax.named_scope("panel_factor"):
            pf1, a1 = factor(C1, c1)
        with jax.named_scope("trailing_update"):
            # Reads the PRE-pf1 S: the wide GEMM must not depend on panel
            # q+1's factorization (or, sharded, its psum) — the column
            # sets are disjoint, so the masked select and the pf1 write
            # commute.
            C_new = apply_block_reflector_h(Y, S, precision,
                                            gemm_precision=gemm_precision)
            cmask = lax.iota(jnp.int32, ns) >= c1 + nb
            S = jnp.where(cmask[None, :], C_new, S)
        S = lax.dynamic_update_slice(S, pf1, (jnp.int32(0), c1))
        return (S, pf1), a1

    (S, pf_last), alphas = lax.scan(
        body, (S, pf0), jnp.arange(pcount - 1, dtype=jnp.int32))
    with jax.named_scope("trailing_update"):
        c = (pcount - 1) * nb
        Y = shifted_tril(pf_last, c)
        C_new = apply_block_reflector_h(Y, S, precision,
                                        gemm_precision=gemm_precision)
        cmask = lax.iota(jnp.int32, ns) >= pcount * nb
        S = jnp.where(cmask[None, :], C_new, S)
    alphas = jnp.concatenate([a0, alphas.reshape((pcount - 1) * nb)])
    return S, alphas


def _unrolled_lookahead(A, nb, precision, pallas, pallas_interpret, norm,
                        panel_impl, tprec, flat):
    """One-panel-lookahead order on the fully-unrolled shrinking-slice path
    (see :func:`_scan_panels_lookahead` for the scheme and why): factor
    panel k+1 from its lookahead-updated columns BEFORE the pending panel
    k's wide trailing GEMM. Handles the ragged final panel (widths vary in
    the unrolled path)."""
    from dhqr_tpu.ops.pallas_panel import pallas_panel_supported

    m, n = A.shape
    H = A
    alpha = jnp.zeros((n,), dtype=A.dtype)

    def factor(panel, off, height, width):
        if pallas and pallas_panel_supported(height, min(width, flat),
                                             A.dtype):
            return _panel_factor_pallas(panel, off, precision,
                                        pallas_interpret, base=flat)
        return _panel_factor(panel, off, precision, norm, panel_impl)

    b0 = min(nb, n)
    with jax.named_scope("panel_factor"):
        pf, alpha_k = factor(lax.slice(H, (0, 0), (m, b0)), 0, m, b0)
        H = H.at[:, :b0].set(pf)
        alpha = alpha.at[:b0].set(alpha_k)
    kp, bp = 0, b0  # pending (already factored, not yet applied) panel
    for k1 in range(b0, n, nb):
        b1 = min(nb, n - k1)
        Y = jnp.tril(pf)  # pending reflectors; rows of pf start at row kp
        with jax.named_scope("lookahead_update"):
            C1 = lax.slice(H, (kp, k1), (m, k1 + b1))
            C1 = apply_block_reflector_h(Y, C1, precision,
                                         gemm_precision=tprec)
        with jax.named_scope("panel_factor"):
            # Diagonal of panel k1 sits at row k1 = kp + bp, i.e. offset
            # bp within the (m - kp)-tall slice.
            pf1, alpha_k = factor(C1, bp, m - kp, b1)
            H = H.at[kp:, k1 : k1 + b1].set(pf1)
            alpha = alpha.at[k1 : k1 + b1].set(alpha_k)
            # Carry the pending panel in its OWN row frame (rows k1:m, diag
            # at local row 0) so the next iteration's jnp.tril is correct.
            pf1 = lax.slice(pf1, (bp, 0), (m - kp, b1))
        if k1 + b1 < n:
            with jax.named_scope("trailing_update"):
                C2 = lax.slice(H, (kp, k1 + b1), (m, n))
                H = H.at[kp:, k1 + b1 :].set(
                    apply_block_reflector_h(Y, C2, precision,
                                            gemm_precision=tprec)
                )
        pf, kp, bp = pf1, k1, b1
    return H, alpha


@partial(
    jax.jit,
    static_argnames=("block_size", "precision", "pallas", "pallas_interpret",
                     "norm", "panel_impl", "trailing_precision",
                     "pallas_flat", "lookahead", "agg_panels"),
)
def _blocked_qr_impl(
    A, block_size, precision=DEFAULT_PRECISION, pallas=False,
    pallas_interpret=False, norm="accurate", panel_impl="loop",
    trailing_precision=None, pallas_flat=None, lookahead=False,
    agg_panels=None,
):
    from dhqr_tpu.ops.pallas_panel import pallas_panel_supported

    m, n = A.shape
    nb = min(block_size, n)
    num_full, rem, ppo = _panels_schedule(n, nb)
    # Static so it participates in the jit cache key (a module-global read
    # inside the trace would bake the import-time value into cached traces
    # and silently ignore later changes).
    flat = PALLAS_FLAT_WIDTH if pallas_flat is None else pallas_flat
    # Trailing-update GEMMs may run at a cheaper MXU precision than the
    # panel/T-factor math: the trailing update holds ~all the flops, while
    # the accuracy-critical dependent chains (reflector norms/dots, the
    # T-factor recurrence) stay at ``precision``. None = no split.
    tprec = precision if trailing_precision is None else trailing_precision

    if num_full + (1 if rem else 0) <= MAX_UNROLLED_PANELS:
        if lookahead and n > nb:
            return _unrolled_lookahead(
                A, nb, precision, pallas, pallas_interpret, norm, panel_impl,
                tprec, flat,
            )
        # Fully-unrolled shrinking-slice path: exact flops, small program.
        H = A
        alpha = jnp.zeros((n,), dtype=A.dtype)
        for k in range(0, n, nb):
            b = min(nb, n - k)
            # phase names = the reference's t1a (panel math) / t1b (trailing
            # update) timers (src:126-146), visible in XLA/perfetto traces.
            with jax.named_scope("panel_factor"):
                panel = lax.slice(H, (k, k), (m, k + b))
                if pallas and pallas_panel_supported(
                        m - k, min(b, flat), A.dtype):
                    pf, alpha_k = _panel_factor_pallas(
                        panel, 0, precision, pallas_interpret, base=flat
                    )
                else:
                    pf, alpha_k = _panel_factor(panel, 0, precision, norm,
                                                panel_impl)
                H = H.at[k:, k : k + b].set(pf)
                alpha = alpha.at[k : k + b].set(alpha_k)
            if k + b < n:
                with jax.named_scope("trailing_update"):
                    Y = jnp.tril(pf)  # reflectors incl. diagonal; R masked off
                    C = lax.slice(H, (k, k + b), (m, n))
                    H = H.at[k:, k + b :].set(
                        apply_block_reflector_h(Y, C, precision,
                                                gemm_precision=tprec)
                    )
        return H, alpha

    # Two-level path: outer Python loop over <= MAX_UNROLLED_PANELS
    # super-blocks (static row/col shrinkage), inner scan over uniform
    # panels. The scan's trailing update spans ALL columns right of the
    # panel — including later super-blocks — so no outer-level update pass
    # is needed; the outer loop exists purely to re-slice shapes.
    H = A
    alpha = jnp.zeros((n,), dtype=A.dtype)
    # With aggregation, the super-block size must admit at least one full
    # k-panel group, or every super-block falls into the grouped scan's
    # remainder fallback and agg_panels silently measures the default
    # schedule (code-review r5): round ppo UP to a multiple of k. The
    # super-block count only shrinks, so program size stays bounded.
    if agg_panels and agg_panels > 1:
        ppo = -(-ppo // agg_panels) * agg_panels
    for ob in range(0, num_full, ppo):
        pcount = min(ppo, num_full - ob)
        K = ob * nb
        S = lax.slice(H, (K, K), (m, n))
        blk_pallas = pallas and pallas_panel_supported(
            m - K, min(nb, flat), A.dtype)
        if agg_panels and agg_panels > 1:
            S, alpha_blk = _scan_panels_grouped(
                S, pcount, nb, agg_panels, precision, blk_pallas,
                pallas_interpret, norm=norm, panel_impl=panel_impl,
                gemm_precision=tprec, pallas_flat=flat,
            )
        else:
            scan_fn = _scan_panels_lookahead if lookahead else _scan_panels
            S, alpha_blk = scan_fn(
                S, pcount, nb, precision, blk_pallas, pallas_interpret,
                norm=norm, panel_impl=panel_impl, gemm_precision=tprec,
                pallas_flat=flat,
            )
        H = H.at[K:, K:].set(S)
        alpha = alpha.at[K : K + pcount * nb].set(alpha_blk)
    if rem:
        K = num_full * nb
        with jax.named_scope("panel_factor"):
            pf, alpha_k = _panel_factor(
                lax.slice(H, (K, K), (m, n)), 0, precision, norm, panel_impl
            )
        H = H.at[K:, K:].set(pf)
        alpha = alpha.at[K:].set(alpha_k)
    return H, alpha


_blocked_qr_impl_donate = partial(
    jax.jit,
    static_argnames=("block_size", "precision", "pallas", "pallas_interpret",
                     "norm", "panel_impl", "trailing_precision",
                     "pallas_flat", "lookahead", "agg_panels"),
    donate_argnums=(0,),
)(_blocked_qr_impl.__wrapped__)


@partial(
    jax.jit,
    static_argnames=("block_size", "precision", "norm", "panel_impl",
                     "trailing_precision"),
    donate_argnums=(0,),
)
def _batched_qr_impl_donate(A, block_size, precision=DEFAULT_PRECISION,
                            norm="accurate", panel_impl="loop",
                            trailing_precision=None):
    """Serve-tier batched dispatch unit: vmap of the blocked engine over a
    stacked ``(B, m, n)`` input, with the stack DONATED.

    The packed output H is exactly input-shaped, so XLA aliases the
    donated buffer (pinned on CPU via ``unsafe_buffer_pointer`` in
    tests/test_serve.py) — one matrix stack of HBM for the whole batch,
    the batched analogue of :data:`_blocked_qr_impl_donate`. The fused
    Pallas panel kernel is deliberately never engaged here
    (``pallas=False``): it is a single-problem VMEM tier, while batched
    throughput at small n lives on the vmapped XLA MXU path (the point of
    the serving tier — see ``dhqr_tpu.serve``).
    """
    def one(a):
        return _blocked_qr_impl(
            a, block_size, precision=precision, pallas=False, norm=norm,
            panel_impl=panel_impl, trailing_precision=trailing_precision,
        )

    return jax.vmap(one)(A)


from functools import lru_cache as _lru_cache


@_lru_cache(maxsize=None)
def _pallas_lowers_on_this_backend(dtype_name: str) -> bool:
    """One-time probe: does the fused panel kernel actually COMPILE here?

    Interpret-mode tests cannot catch Mosaic lowering rejections (round 3
    found one on real hardware that every CPU test had passed), so "auto"
    verifies lowering once per process with a tiny panel before routing any
    real work through the kernel; on failure auto degrades to the XLA path
    instead of crashing the caller. "always" still raises, by design.
    """
    try:
        from dhqr_tpu.ops.pallas_panel import _panel_qr_pallas_jit

        probe = jnp.zeros((128, 8), dtype=jnp.dtype(dtype_name))
        _panel_qr_pallas_jit.lower(probe, 0, interpret=False).compile()
        return True
    except Exception:
        return False


_CACHE_GUARD_WARNED = []


def _cache_guard_is_thread_local() -> bool:
    """Does this jax scope ``enable_compilation_cache(False)`` to the
    calling thread? jax >= 0.4.35's config ``State`` context manager
    swaps a thread-local value (``State.swap_local``/``set_local``), so
    entering the guard on one thread leaves compiles on other threads
    fully cached. On older jax (or if the holder API changes) the
    context may fall back to process-global semantics — see the
    concurrency note on :func:`_pallas_cache_guard`."""
    try:
        from jax._src.config import enable_compilation_cache
    except ImportError:
        return False
    return (hasattr(enable_compilation_cache, "swap_local")
            and hasattr(enable_compilation_cache, "set_local"))


def _pallas_cache_guard(interpret: bool):
    """Keep interpret-mode Pallas programs OUT of the persistent
    compilation cache (wrap the jit CALL, where the compile happens).

    Interpret mode lowers the kernel to host callbacks, and an executable
    carrying callbacks is not safely deserializable in another process —
    the callback registry indices are process-local, so a cross-process
    cache hit can segfault the reader inside
    ``compilation_cache.get_executable_and_time`` (measured 2026-08-01:
    ``tests/test_sharded.py`` Pallas tests crashed reproducibly at file
    scope reading entries written by a differently-ordered process, while
    passing in isolation). Interpret mode is a CPU test vehicle, so the
    cost is only a per-process recompile of the interpret programs; the
    hardware path (``interpret=False``) keeps full caching.

    Concurrency note (ADVICE r5 item 2, closed round 7): on the pinned
    jax (0.4.37) the ``enable_compilation_cache(False)`` context swaps a
    THREAD-LOCAL config value (``State.swap_local``/``set_local`` —
    verified by :func:`_cache_guard_is_thread_local` and pinned by
    ``tests/test_analysis.py::test_cache_guard_scope_is_thread_local``),
    so a non-interpret compile on another thread during the guard window
    keeps full persistent caching. On a jax whose config holder predates
    thread-local scoping, the probe returns False and the guard degrades
    to the old process-global semantics: single-threaded compilation is
    then assumed — a concurrent compile on another thread would silently
    lose that one compile's caching (numerically harmless).

    The flag toggle lives behind a PRIVATE jax import
    (``jax._src.config.enable_compilation_cache`` — there is no public
    per-scope disable). A jax upgrade removing it must degrade to "cache
    not suppressed" (a fresh process may then segfault reading a stale
    interpret-mode entry — clear the cache dir if so), never to an
    ImportError on every CPU test path (ADVICE r5 items 1-2).
    """
    from contextlib import nullcontext

    if not interpret:
        return nullcontext()
    try:
        from jax._src.config import enable_compilation_cache
    except ImportError:
        if not _CACHE_GUARD_WARNED:
            _CACHE_GUARD_WARNED.append(True)
            import warnings

            warnings.warn(
                "jax._src.config.enable_compilation_cache is gone in this "
                "jax version: interpret-mode Pallas programs can no longer "
                "be kept out of the persistent compilation cache. Their "
                "host-callback executables are not safely deserializable "
                "across processes — if another process segfaults reading "
                "the cache, clear the cache directory.",
                stacklevel=2,
            )
        return nullcontext()
    return enable_compilation_cache(False)


def _resolve_pallas(mode: str, m: int, nb: int, dtype,
                    platform: "str | None" = None,
                    device=None) -> tuple[bool, bool]:
    """Map a ``use_pallas`` config value to (enabled, interpret) for a shape.

    "always" forces the fused panel kernel, using the Pallas interpreter
    off-TPU (the CPU test path); "never" disables it. "auto" resolves to the
    fused kernel on TPU for supported shapes (f32/c64 panels that fit VMEM)
    — the analogue of the reference dispatching its hand-SIMD complex
    hotloop unconditionally in the hot path (src:174-196). The kernel's
    column norm carries the same compensated-accumulation standard as the
    XLA engine (``pallas_panel._sumsq_compensated``), so routing is a
    performance choice, not an accuracy trade. Off-TPU, "auto" stays on the
    XLA path (the interpreter is a test vehicle, orders of magnitude slower).
    ``DHQR_PALLAS_AUTO=0`` vetoes auto-routing without touching call sites
    (an escape hatch if hardware benchmarking shows XLA panels faster).

    ``device`` (preferred) or ``platform`` is the execution target that
    "auto"/"always" resolve against — pass the MESH's device for sharded
    callers (a TPU mesh driven from a CPU-default process must still get
    the kernel, sized by the mesh chip's measured VMEM gate, and a virtual
    CPU mesh on a TPU host must not); ``None`` means the process default
    backend.
    """
    from dhqr_tpu.ops.pallas_panel import pallas_panel_supported

    if mode == "never":
        return False, False
    # Panels wider than PALLAS_FLAT_WIDTH are factored by recursive
    # splitting into base-width kernel calls (_panel_factor_pallas), so
    # VMEM only ever has to admit the base width. The gate is sized for
    # the execution device when one is given.
    supported = pallas_panel_supported(m, min(nb, PALLAS_FLAT_WIDTH), dtype,
                                       device=device)
    if device is not None:
        platform = device.platform
    if platform is None:
        platform = jax.default_backend()
    on_tpu = platform == "tpu"
    if mode == "always":
        if not supported:
            raise ValueError(
                f"use_pallas='always' but an ({m}, {nb}) {jnp.dtype(dtype).name} "
                "panel is unsupported (float32/complex64 only, the "
                f"{min(nb, PALLAS_FLAT_WIDTH)}-wide kernel base must fit VMEM)"
            )
        return True, not on_tpu
    if mode == "auto":
        veto = _os.environ.get("DHQR_PALLAS_AUTO", "") == "0"
        enabled = supported and on_tpu and not veto
        # The lowering probe compiles on the PROCESS default backend — only
        # meaningful when that is the platform we are resolving for.
        if enabled and platform == jax.default_backend() and \
                not _pallas_lowers_on_this_backend(jnp.dtype(dtype).name):
            enabled = False  # Mosaic rejected the kernel here — XLA path
        return enabled, False
    raise ValueError(f"use_pallas must be 'auto', 'always' or 'never', got {mode!r}")


def auto_block_size(m: int, dtype, use_pallas: str = "auto") -> int:
    """Panel width when the caller leaves ``block_size`` unset.

    Round-3 hardware sweeps (benchmarks/results/tpu_r3_longchain_stages.jsonl,
    tpu_r3_tune2.jsonl, tpu_r3_vmem_probe2.jsonl, tpu_r3_scale.jsonl): with
    the fused Pallas panel kernel and the hardware-validated single-copy
    VMEM gate, all-Pallas nb=256 won at 4096^2 and 8192^2 (10.3 / 10.9
    TFLOP/s vs 8.5 / 8.8 at nb=512), while from 12288^2 up the panel-count
    halving flips the order: nb=512 measured 13.0 vs 11.3 TFLOP/s at
    12288^2 and 12.9 vs 12.2 at 16384^2. So: 512 where m >= 12288 and the
    gate admits a 512-wide tallest panel; else 256 where the gate admits
    256; else 128. Off-TPU (or with the kernel vetoed) the panel loop is
    latency-bound either way: stay at 128.
    """
    if use_pallas == "never":
        return DEFAULT_BLOCK_SIZE
    for nb in (512, 256):
        if nb == 512 and m < 12288:
            continue
        try:
            # The one routing predicate (_resolve_pallas) decides —
            # duplicating its supported/on-TPU/veto/lowering-probe logic
            # here would let the two sites drift.
            enabled, interpret = _resolve_pallas(use_pallas, m, nb, dtype)
        except ValueError:  # "always" but an nb-wide panel is unsupported
            continue
        if enabled and not interpret:
            return nb
    return DEFAULT_BLOCK_SIZE


def blocked_householder_qr(
    A: jax.Array,
    block_size: "int | None" = None,
    donate: bool = False,
    precision: str = DEFAULT_PRECISION,
    use_pallas: str = "auto",
    norm: str = "accurate",
    panel_impl: str = "loop",
    trailing_precision: "str | None" = None,
    lookahead: bool = False,
    agg_panels: "int | None" = None,
    overlap_depth: "int | None" = None,
    policy=None,
):
    """Factor ``A`` (m x n, m >= n): returns ``(H, alpha)`` in packed storage.

    Identical storage and numerics to :func:`householder_qr` (reflectors with
    ||v||^2 = 2 below/on the diagonal, R strict-upper in H, R diagonal in
    alpha — reference src:122-148, 296-309), but organized panel-wise so the
    trailing update runs on the MXU.

    ``block_size=None`` (the default) auto-selects the panel width for the
    backend and shape (:func:`auto_block_size`): 256 on TPU where the
    Pallas kernel admits 256-wide panels, else 128.

    ``norm`` selects the column-norm accumulation on the XLA panel path
    (ops/summation.sumsq); panels taken by the Pallas kernel use the
    kernel's in-VMEM compensated accumulation
    (pallas_panel._sumsq_compensated) regardless.

    With ``donate=True`` the input buffer is donated to XLA — the functional
    spelling of the reference's in-place ``householder!`` (src:113), halving
    peak memory; the caller's array is invalidated, so it is opt-in.

    ``trailing_precision`` (default: same as ``precision``) sets the MXU
    precision of the trailing-update GEMMs ONLY — the panel factorization and
    the compact-WY T-factor keep ``precision``. The trailing update holds
    ~all the flops, so e.g. ``precision="highest", trailing_precision="high"``
    trades MXU passes (6 -> 3) on the bulk work while keeping the dependent
    reflector chains at full accuracy. Measure the backward error for your
    sizes before relying on it; the library default remains un-split.

    ``lookahead=True`` factors each panel from its lookahead-updated
    columns BEFORE the previous panel's wide trailing GEMM (classic
    one-panel lookahead; see :func:`_scan_panels_lookahead`). Column-wise
    the arithmetic is order-identical, so results match the default
    schedule to roundoff in the GEMM column split; the scheduling freedom
    matters most on the sharded tier, where it lets the panel psum overlap
    the trailing GEMM.

    ``agg_panels=k`` (k > 1) applies the trailing update once per k
    consecutive panels as the group's aggregated compact-WY transform
    (see :func:`_scan_panels_grouped`): k-fold fewer wide trailing
    passes, at ~O(m (k nb)^2) extra aggregate-T flops per group. Takes
    effect on the scanned (two-level) path — small problems on the
    fully-unrolled path ignore it; mutually exclusive with ``lookahead``.

    ``policy`` (a :class:`dhqr_tpu.precision.PrecisionPolicy`, preset name
    or spec string) is the one-object spelling of the precision pair:
    it sets ``precision`` from ``policy.panel`` and ``trailing_precision``
    from ``policy.trailing`` (mutually exclusive with passing those
    explicitly). The solve-stage fields (``apply``, ``refine``) do not
    apply to a factor-only entry point and are ignored by contract —
    use ``qr()``/``lstsq()`` for a refined solve under the same policy.
    """
    from dhqr_tpu.precision import apply_policy_to_factor_args
    from dhqr_tpu.utils.platform import ensure_complex_supported

    precision, trailing_precision = apply_policy_to_factor_args(
        policy, precision, trailing_precision,
        default_precision=DEFAULT_PRECISION)
    m, n = A.shape
    if m < n:
        raise ValueError(f"blocked_householder_qr requires m >= n, got {A.shape}")
    if norm not in ("accurate", "fast"):
        raise ValueError(f"norm must be 'accurate' or 'fast', got {norm!r}")
    if agg_panels is not None and agg_panels < 2:
        raise ValueError(f"agg_panels must be >= 2 (got {agg_panels}); "
                         "None means per-panel updates")
    if agg_panels and lookahead:
        raise ValueError(
            "agg_panels and lookahead are mutually exclusive on the "
            "single-device engine (both only add flops here); the mesh "
            "tier composes them as grouped lookahead — use qr()/lstsq() "
            "with mesh= (parallel/sharded_qr._blocked_shard_agg)"
        )
    if overlap_depth is not None:
        raise ValueError(
            "overlap_depth is mesh-only: the depth-k pipeline exists to "
            "keep panel-broadcast collectives in flight, and a single "
            "device has no collective to hide — use qr()/lstsq() with "
            "mesh= (parallel/sharded_qr._blocked_shard_pipeline)"
        )
    # (complex + panel_impl='reconstruct' is rejected at the _panel_factor
    # chokepoint — every XLA-path route converges there, and the Pallas
    # path legitimately ignores panel_impl, so no wrapper-level guard.)
    ensure_complex_supported(A.dtype)
    nb = auto_block_size(m, A.dtype, use_pallas) if block_size is None \
        else int(block_size)
    pallas, interpret = _resolve_pallas(use_pallas, m, min(nb, n), A.dtype)
    impl = _blocked_qr_impl_donate if donate else _blocked_qr_impl
    with _pallas_cache_guard(interpret):
        return impl(A, nb, precision=precision, pallas=pallas,
                    pallas_interpret=interpret, norm=norm,
                    panel_impl=panel_impl,
                    trailing_precision=trailing_precision,
                    # explicit (not the in-trace default) so the module
                    # global participates in the jit cache key via this
                    # wrapper
                    pallas_flat=PALLAS_FLAT_WIDTH, lookahead=lookahead,
                    agg_panels=agg_panels)


@partial(jax.jit, static_argnames=("block_size", "precision"))
def _apply_qt_impl(H, b, block_size, precision=DEFAULT_PRECISION):
    m, n = H.shape
    nb = min(block_size, n)
    num_full, rem, _ = _panels_schedule(n, nb)
    vec = b.ndim == 1
    B = b[:, None] if vec else b
    if num_full + (1 if rem else 0) <= MAX_UNROLLED_PANELS:
        for k in range(0, n, nb):
            bsz = min(nb, n - k)
            Y = jnp.tril(lax.slice(H, (k, k), (m, k + bsz)))
            B = B.at[k:].set(apply_block_reflector_h(Y, B[k:], precision))
        return B[:, 0] if vec else B

    def body(B, q):
        k = q * nb
        Y = shifted_tril(lax.dynamic_slice(H, (jnp.int32(0), k), (m, nb)), k)
        # Y is zero above row k, so only rows k: change — no slicing needed.
        return apply_block_reflector_h(Y, B, precision), None

    B, _ = lax.scan(body, B, jnp.arange(num_full, dtype=jnp.int32))
    if rem:
        k = num_full * nb
        Y = jnp.tril(lax.slice(H, (k, k), (m, n)))
        B = B.at[k:].set(apply_block_reflector_h(Y, B[k:], precision))
    return B[:, 0] if vec else B


def blocked_apply_qt(
    H: jax.Array,
    alpha: jax.Array,
    b: jax.Array,
    block_size: int = DEFAULT_BLOCK_SIZE,
    precision: str = DEFAULT_PRECISION,
) -> jax.Array:
    """b <- Q^H b using the compact-WY form, panel by panel.

    Blocked counterpart of the reference's stage-1 solve (src:215-242);
    accepts a vector (m,) or a block of right-hand sides (m, k).
    """
    del alpha
    return _apply_qt_impl(H, b, int(block_size), precision=precision)


@partial(jax.jit, static_argnames=("block_size", "precision"))
def _apply_q_impl(H, b, block_size, precision=DEFAULT_PRECISION):
    m, n = H.shape
    nb = min(block_size, n)
    num_full, rem, _ = _panels_schedule(n, nb)
    vec = b.ndim == 1
    B = b[:, None] if vec else b
    if num_full + (1 if rem else 0) <= MAX_UNROLLED_PANELS:
        for k in reversed(range(0, n, nb)):
            bsz = min(nb, n - k)
            Y = jnp.tril(lax.slice(H, (k, k), (m, k + bsz)))
            B = B.at[k:].set(apply_block_reflector(Y, B[k:], precision))
        return B[:, 0] if vec else B

    # Reverse order: the remainder panel is the last factored, so Q applies
    # it first; then the full panels from last to first.
    if rem:
        k = num_full * nb
        Y = jnp.tril(lax.slice(H, (k, k), (m, n)))
        B = B.at[k:].set(apply_block_reflector(Y, B[k:], precision))

    def body(B, q):
        k = q * nb
        Y = shifted_tril(lax.dynamic_slice(H, (jnp.int32(0), k), (m, nb)), k)
        return apply_block_reflector(Y, B, precision), None

    B, _ = lax.scan(
        body, B, jnp.arange(num_full - 1, -1, -1, dtype=jnp.int32)
    )
    return B[:, 0] if vec else B


def blocked_apply_q(
    H: jax.Array,
    alpha: jax.Array,
    b: jax.Array,
    block_size: int = DEFAULT_BLOCK_SIZE,
    precision: str = DEFAULT_PRECISION,
) -> jax.Array:
    """b <- Q b using the compact-WY form, panels in reverse order."""
    del alpha
    return _apply_q_impl(H, b, int(block_size), precision=precision)


# Donation contract (dhqr-audit DHQR304): _blocked_qr_impl_donate and
# _batched_qr_impl_donate must AOT-compile with input-output aliasing
# (the packed H is input-shaped by construction) — checked statically on
# the CPU path by analysis/comms_pass.check_donation, and dynamically by
# the buffer-pointer pin in tests/test_serve.py.
