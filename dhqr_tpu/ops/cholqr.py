"""CholeskyQR2 — the all-GEMM tall-skinny QR fast path (beyond the reference).

The reference factors strictly by Householder reflections (reference
src/DistributedHouseholderQR.jl:122-213). On TPU the throughput-optimal QR
for m >> n is CholeskyQR2 (Fukaya et al., "CholeskyQR2: a simple and
communication-avoiding algorithm"; used at pod scale in "Large Scale
Distributed Linear Algebra With Tensor Processing Units",
arxiv 2112.09017): every flop is a GEMM / rank-k update on the MXU, the
only non-GEMM work is an n x n Cholesky, and the distributed form needs ONE
psum per pass.

    G  = A^H A                (syrk — MXU)
    R1 = chol(G)^H            (upper)
    Q1 = A R1^{-1}            (triangular solve, n x n against m rows)
    ... repeat on Q1 ...      (second pass restores orthogonality)
    R  = R2 R1

One pass loses orthogonality as cond(A)^2 * eps; the second pass repairs it
to O(eps) PROVIDED the first Cholesky succeeds, which needs roughly
cond(A) < 1/sqrt(eps) (~3e3 in f32, ~7e7 in f64). A Fukaya-style diagonal
shift keeps the first factorization positive-definite near that edge
(shifted CholeskyQR3 degenerates to our 2-pass form when the shift is 0).
Outside that regime use the Householder engines or TSQR — this module
checks and reports rather than silently degrading.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dhqr_tpu.numeric.guards import checked_cholesky
from dhqr_tpu.ops.householder import DEFAULT_PRECISION, _real_dtype
from dhqr_tpu.ops.solve import as_matrix_rhs


def cholqr_max_cond(dtype, shift: bool = False) -> float:
    """Approximate upper edge of the CholeskyQR conditioning window.

    Plain CholeskyQR2 needs the first Gram pass positive-definite,
    which holds while roughly ``cond(A) < 1/sqrt(eps)`` (~3e3 in f32,
    ~7e7 in f64); Fukaya et al.'s diagonal shift (``shift=True``, our
    cholqr3) widens it toward ``cond(A) ~ 1/eps``. These are order-of-
    magnitude guides, not guarantees — the numeric fallback ladder
    uses them to CLASSIFY a breakdown (``IllConditioned`` vs
    ``Breakdown``), never to promise success inside the window.
    """
    eps = float(jnp.finfo(_real_dtype(jnp.dtype(dtype))).eps)
    return (0.1 / eps) if shift else 1.0 / math.sqrt(eps)


def _chol_upper(G: jax.Array, shift: bool) -> jax.Array:
    """Upper-triangular R with R^H R = G (+ optional stabilizing shift).

    The shift follows Fukaya et al.'s shifted CholeskyQR: a multiple of
    eps * trace(G) added to the diagonal, large enough to keep the
    factorization positive-definite for cond(A) up to ~1/sqrt(eps) while
    perturbing R by O(eps * ||A||^2) — repaired by the second pass.

    The Cholesky itself routes through the package's one guarded
    wrapper (``numeric.guards.checked_cholesky``, lint rule DHQR007):
    breakdown past the window surfaces as NaN factors, which the
    numeric layer's health checks catch and escalate.
    """
    n = G.shape[0]
    if shift:
        rdtype = _real_dtype(G.dtype)
        eps = jnp.finfo(rdtype).eps
        s = 11.0 * (n + 16) * eps * jnp.real(jnp.trace(G)) / n
        G = G + s * jnp.eye(n, dtype=G.dtype)
    L = checked_cholesky(G)  # lower
    return jnp.conj(L.T)


def _cholqr_passes(A, gram, precision, shift):
    """Shared pass driver: (Q, R) from repeated Gram/Cholesky passes.

    ``gram(X)`` returns X^H X — a local syrk on one device, syrk + psum in
    the row-sharded form (parallel/sharded_cholqr.py); everything else is
    identical between the two so they cannot numerically diverge.

    shift=False: plain CholeskyQR2 — fails LOUDLY (NaN) outside its
    conditioning window. shift=True: shifted CholeskyQR3 — the shifted
    first pass widens the window but leaves Q1 only O(eps*cond)
    orthogonal, so a THIRD pass is required to restore O(eps) (Fukaya et
    al.; a shifted two-pass form would return finite-but-wrong factors).
    """

    def one_pass(X, do_shift):
        R = _chol_upper(gram(X), do_shift)
        # Q = X R^{-1}  <=>  solve q R = X for q (right-hand tri solve)
        Q = lax.linalg.triangular_solve(R, X, left_side=False, lower=False)
        return Q, R

    Q, R = one_pass(A, shift)
    Q, R2 = one_pass(Q, False)
    R = jnp.matmul(R2, R, precision=precision)
    if shift:
        Q, R3 = one_pass(Q, False)
        R = jnp.matmul(R3, R, precision=precision)
    return Q, R


@partial(jax.jit, static_argnames=("precision", "shift", "gram_precision"))
def _cholesky_qr2_impl(A, precision, shift, gram_precision=None):
    # The Gram syrk holds ~all the flops (the "trailing" analogue of the
    # householder engines); its precision may be split away from the
    # n x n composition math. None = no split.
    gp = precision if gram_precision is None else gram_precision
    gram = lambda X: jnp.matmul(jnp.conj(X.T), X, precision=gp)
    return _cholqr_passes(A, gram, precision, shift)


def cholesky_qr2(
    A: jax.Array,
    precision: str = DEFAULT_PRECISION,
    shift: bool = False,
    gram_precision: "str | None" = None,
    policy=None,
):
    """Thin QR of a tall matrix via Cholesky passes: ``A = Q R``.

    Returns explicit ``(Q, R)`` with Q (m, n) orthonormal and R (n, n)
    upper-triangular (diagonal real-positive — note this differs from the
    Householder engines, whose R diagonal carries the alpha sign rule;
    ``R^H R == A^H A`` either way). All flops are GEMMs: this is the MXU
    throughput ceiling for m >> n.

    ``shift=False`` (default) is CholeskyQR2: applicable while
    cond(A) < ~1/sqrt(eps) (~3e3 in f32, ~7e7 in f64); outside that window
    the first Cholesky is non-PD and the result is NaN — a LOUD failure to
    catch with ``jnp.isfinite`` and reroute to the Householder engines or
    :func:`dhqr_tpu.ops.tsqr.tsqr_lstsq`. ``shift=True`` is shifted
    CholeskyQR3 (three passes, ~1.5x the flops): the stabilizing shift
    widens the window toward cond(A) ~ 1/eps and the extra pass restores
    O(eps) orthogonality that the shift alone would forfeit.

    ``gram_precision`` / ``policy`` split the A^H A syrk's MXU precision
    away from the composition math (``policy.trailing`` maps onto the
    syrk — it is where ~all the flops are). Gram rounding is SQUARED
    through Cholesky, so a cheaper syrk narrows the conditioning window
    accordingly; the solve surface's ``refine`` buys the residual back
    (see :func:`cholesky_qr_lstsq`). The solve-stage policy fields
    (``apply``, ``refine``) do not apply to this factor-only entry point
    and are ignored by contract.
    """
    from dhqr_tpu.precision import apply_policy_to_factor_args
    from dhqr_tpu.utils.platform import ensure_complex_supported

    precision, gram_precision = apply_policy_to_factor_args(
        policy, precision, gram_precision,
        default_precision=DEFAULT_PRECISION)
    m, n = A.shape
    if m < n:
        raise ValueError(f"cholesky_qr2 requires m >= n, got {A.shape}")
    ensure_complex_supported(A.dtype)
    return _cholesky_qr2_impl(A, precision, bool(shift),
                              gram_precision=gram_precision)


@partial(jax.jit, static_argnames=("precision", "shift", "refine",
                                   "gram_precision"))
def _cholqr_lstsq_impl(A, b, precision, shift, refine=0,
                       gram_precision=None):
    Q, R = _cholesky_qr2_impl(A, precision, shift,
                              gram_precision=gram_precision)
    B, restore = as_matrix_rhs(b)

    def qr_solve(C):
        W = jnp.matmul(jnp.conj(Q.T), C, precision=precision)
        return lax.linalg.triangular_solve(R, W, left_side=True, lower=False)

    X = qr_solve(B)
    for _ in range(refine):
        # One refinement step reuses Q, R: r = b - A x, x += solve(r).
        # Residual matvec at full precision — its accuracy IS the point.
        Rres = B - jnp.matmul(A, X, precision="highest")
        X = X + qr_solve(Rres)
    return restore(X)


def cholesky_qr_lstsq(
    A: jax.Array,
    b: jax.Array,
    precision: str = DEFAULT_PRECISION,
    shift: bool = False,
    refine: int = 0,
    gram_precision: "str | None" = None,
    policy=None,
) -> jax.Array:
    """Least squares via CholeskyQR2 — the all-GEMM fast path for m >> n.

    ``refine`` adds that many iterative-refinement sweeps (each one
    A-matvec + one reuse of the factorization — all GEMMs): it sharpens
    the residual toward the Householder-grade answer near the edge of the
    conditioning window at a few percent of the cost. It does NOT move
    the window's NaN boundary itself — a failed Cholesky stays failed;
    route those problems to the Householder engines.

    ``gram_precision`` / ``policy`` as in :func:`cholesky_qr2`; a policy
    additionally supplies ``refine`` (mutually exclusive with passing it
    explicitly) — the pairing that makes a cheap Gram syrk a candidate
    rather than an accuracy regression. ``policy.apply`` is not split
    here: the solve's Q^H GEMMs stay at the panel precision.
    """
    from dhqr_tpu.precision import (apply_policy_to_factor_args,
                                    resolve_policy)
    from dhqr_tpu.utils.platform import ensure_complex_supported

    if policy is not None:
        pol = resolve_policy(policy)
        if refine:
            raise ValueError(
                "pass either policy= or refine=, not both "
                f"(policy sets refine={pol.refine})"
            )
        refine = pol.refine
    precision, gram_precision = apply_policy_to_factor_args(
        policy, precision, gram_precision,
        default_precision=DEFAULT_PRECISION)
    if A.shape[0] < A.shape[1]:
        raise ValueError(f"lstsq requires m >= n, got {A.shape}")
    if int(refine) < 0:
        raise ValueError(f"refine must be >= 0, got {refine}")
    ensure_complex_supported(A.dtype)
    return _cholqr_lstsq_impl(A, b, precision, bool(shift), int(refine),
                              gram_precision=gram_precision)
