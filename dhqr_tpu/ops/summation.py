"""Accurate vectorized reductions (layer L0 of SURVEY.md §1).

The reference's L0 is hand-vectorized SIMD micro-kernels for dot products
(reference src/DistributedHouseholderQR.jl:42-59, 162-196). On TPU the raw
throughput comes for free from XLA, but *accuracy* does not: XLA's
``reduce-sum`` carries O(10-100) ulp error, and in Householder QR the column
norm's error is amplified by ~sqrt(m) in the trailing update, costing two
digits of backward error versus LAPACK. These helpers restore ~1 ulp
reductions using a compensated pairwise (TwoSum) tree: fully vectorized,
log2(m) levels, static shapes — no sequential carry chain, so it maps onto
the VPU cleanly.

TwoSum has no multiplies, so XLA's FMA contraction cannot break the error
algebra; XLA performs no other unsafe floating-point reassociation on an
explicit op graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _two_sum(a: jax.Array, b: jax.Array):
    """Knuth TwoSum: s + e == a + b exactly (s = fl(a+b))."""
    s = a + b
    z = s - a
    e = (a - (s - z)) + (b - z)
    return s, e


def tree_sum(x: jax.Array) -> jax.Array:
    """Compensated pairwise sum of a 1-D vector, accurate to ~1 ulp."""
    n = x.shape[0]
    if n == 0:
        return jnp.zeros((), x.dtype)
    err = jnp.zeros_like(x)
    while n > 1:
        if n % 2:
            x = jnp.concatenate([x, jnp.zeros((1,), x.dtype)])
            err = jnp.concatenate([err, jnp.zeros((1,), err.dtype)])
            n += 1
        s, e = _two_sum(x[0::2], x[1::2])
        err = err[0::2] + err[1::2] + e  # error terms are tiny; plain add is fine
        x = s
        n //= 2
    return x[0] + err[0]


def sumsq(x: jax.Array, mode: str = "accurate") -> jax.Array:
    """sum(|x|^2) (real result, works for real and complex x).

    ``mode="accurate"``: compensated pairwise tree, ~1 ulp. ``mode="fast"``:
    plain XLA reduce — itself tree-shaped on TPU/CPU, so for a sum of
    SQUARES (condition number 1, no cancellation possible) the error
    difference is a few ulps (measured: backward error 7.3e-7 vs 7.5e-7 at
    1024^2 f32 against a 1e-5 target) while skipping the compensation's
    O(log m) strided-slice levels in hot panel loops.
    """
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        y = jnp.real(x) ** 2 + jnp.imag(x) ** 2
    else:
        y = x * x
    if mode == "fast":
        return jnp.sum(y)
    if mode != "accurate":
        raise ValueError(f"norm mode must be 'accurate' or 'fast', got {mode!r}")
    return tree_sum(y)


def accurate_sumsq(x: jax.Array) -> jax.Array:
    """sum(|x|^2) to ~1 ulp (real result, works for real and complex x)."""
    return sumsq(x, "accurate")


def accurate_norm(x: jax.Array) -> jax.Array:
    """||x||_2 to ~1 ulp — the reference's ``norm(view(Hl, j:m, j))`` (src:129)."""
    return jnp.sqrt(accurate_sumsq(x))


def norm2(x: jax.Array, mode: str = "accurate") -> jax.Array:
    """||x||_2 with selectable accumulation (see :func:`sumsq`)."""
    return jnp.sqrt(sumsq(x, mode))


def accurate_vdot(a: jax.Array, b: jax.Array) -> jax.Array:
    """conj(a)·b with a compensated pairwise sum over the products.

    The reference's ``partialdot`` (src:42-59); ragged ranges are handled by
    masking the inputs to structural zeros before calling. Product rounding
    (one ulp each, uncompensated) is below the tree's accumulation error for
    non-cancelling data.
    """
    return tree_sum(jnp.conj(a) * b)
