"""Unblocked Householder QR — the factorization engine (layer L2 of SURVEY.md §1).

TPU-native re-design of the reference panel loop
(reference src/DistributedHouseholderQR.jl:122-213): one traced
``lax.fori_loop`` over columns with masked (static-shape) row ranges instead of
the reference's ragged ``j:m`` views, and the whole-column trailing update as a
single GEMV + rank-1 update instead of the reference's per-column
``partialdot``/``hotloop!`` pair (src:198-213).

Numerics follow the reference exactly:

* sign choice ``alpha = s * alphafactor(a_jj)`` avoiding cancellation
  (src:8-9, 130);
* reflector scale ``f = 1 / sqrt(s * (s + |a_jj|))`` (src:131), which makes
  the stored reflector satisfy ``||v||^2 = 2`` so each elementary reflector is
  exactly ``H_j = I - v_j v_j^H`` — no tau array is needed;
* the reflector (including its diagonal entry) overwrites column j's rows
  ``j:m`` in place; R's strict upper triangle stays in H; R's *diagonal* lives
  in ``alpha`` (src:296-309).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dhqr_tpu.ops.summation import norm2

# Matmul precision for the accuracy-critical contractions. TPU MXU default
# is bf16 multiplication (~1e-4 relative error) which destroys the <1e-5
# backward-error target in Float32; HIGHEST requests full-f32 passes. On CPU
# and for f64 inputs it is a no-op, so it is safe as the global default.
DEFAULT_PRECISION = "highest"


def alphafactor(x: jax.Array) -> jax.Array:
    """Sign factor for the Householder diagonal shift (reference src:8-9).

    Real: ``-sign(x)``; complex: ``-exp(i * angle(x)) = -x / |x|``.
    For ``x == 0`` the reference's real path returns ``-0`` (and would then
    divide by zero); we return ``-1`` in both the real and complex cases,
    which matches the complex path's ``-exp(i*angle(0)) = -1`` and keeps the
    factorization finite on a zero pivot.
    """
    if jnp.iscomplexobj(x):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, -jnp.ones_like(x), -x / jnp.where(mag == 0, 1, mag))
    return jnp.where(x >= 0, -jnp.ones_like(x), jnp.ones_like(x))


def _real_dtype(dtype) -> jnp.dtype:
    return jnp.finfo(dtype).dtype if not jnp.issubdtype(dtype, jnp.complexfloating) \
        else jnp.zeros((), dtype).real.dtype


def householder_reflector(col: jax.Array, j: jax.Array, norm: str = "accurate"):
    """Compute one Householder reflector from (the full m-vector of) column j.

    ``col`` is the whole column; rows above ``j`` are R entries belonging to
    previous steps and are masked out. Returns ``(v, alpha_j)`` where ``v`` is
    the m-vector reflector (zero in rows < j, ``||v||^2 = 2``) and ``alpha_j``
    is R's diagonal entry. Mirrors reference src:129-135 with masks in place
    of the ragged ``j:m`` range.
    """
    m = col.shape[0]
    dtype = col.dtype
    rdtype = _real_dtype(dtype)
    rows = lax.iota(jnp.int32, m)
    mask = rows >= j
    colm = jnp.where(mask, col, jnp.zeros_like(col))
    # s = ||A[j:m, j]||  (reference src:129). XLA's reduce-sum carries
    # O(10-100) ulps and the error is amplified by ~sqrt(m) in the trailing
    # update, so use the compensated tree reduction (see ops/summation.py).
    s = norm2(colm, norm).astype(rdtype)
    a_jj = col[j]
    alpha_j = (s.astype(dtype) * alphafactor(a_jj)).astype(dtype)
    denom = s * (s + jnp.abs(a_jj).astype(rdtype))
    # f = 1/sqrt(s(s+|a_jj|)) (src:131); guarded so a zero column yields v=0.
    # NB: not lax.rsqrt — its ~1e2-ulp error makes each reflector slightly
    # non-unitary and costs a digit of backward error over n reflectors.
    f = jnp.where(denom > 0, 1.0 / jnp.sqrt(jnp.where(denom > 0, denom, 1)), 0).astype(rdtype)
    shifted = colm - alpha_j * (rows == j).astype(dtype)  # H[j,j] -= alpha (src:132)
    v = (shifted * f.astype(dtype)).astype(dtype)  # scale rows j:m by f (src:133-135)
    return v, alpha_j


def _panel_step(jj: jax.Array, carry, offset, precision=DEFAULT_PRECISION,
                norm="accurate"):
    """One column step on a panel: reflector + whole-panel trailing update.

    ``jj`` is the local column index within the panel; the reflector's
    diagonal sits at row ``offset + jj`` (``offset`` may be traced — the
    blocked engine's scan passes the panel's position within its
    super-block). The trailing update ``P[:, jj+1:] -= v (v^H P[:, jj+1:])``
    is expressed full-width with a column mask so shapes stay static under
    ``jit``; the GEMV + rank-1 pair is what XLA fuses onto the MXU/VPU. This
    replaces the reference's broadcast + per-column hot loop (src:141-143,
    198-213).
    """
    P, alpha = carry
    m, n = P.shape
    j = offset + jj  # row of the diagonal entry
    col = lax.dynamic_slice_in_dim(P, jj, 1, axis=1)[:, 0]
    v, alpha_j = householder_reflector(col, j, norm)
    rows = lax.iota(jnp.int32, m)
    # Column jj now stores the reflector in rows j:m; rows < j keep R entries.
    newcol = jnp.where(rows >= j, v, col)
    P = lax.dynamic_update_slice_in_dim(P, newcol[:, None], jj, axis=1)
    alpha = lax.dynamic_update_slice_in_dim(alpha, alpha_j[None], jj, axis=0)
    # Trailing update on local columns > jj (masked; v is zero in rows < j).
    # (n,) partial dots — reference's partialdot (src:42-59)
    w = jnp.matmul(jnp.conj(v), P, precision=precision)
    cmask = lax.iota(jnp.int32, n) > jj
    w = jnp.where(cmask, w, jnp.zeros_like(w))
    P = P - v[:, None] * w[None, :]  # reference's hotloop! axpy (src:150-196)
    return P, alpha


def _panel_qr_masked(panel, offset, precision=DEFAULT_PRECISION,
                     norm="accurate"):
    """Masked panel QR: reflector for local column jj starts at row offset+jj.

    ``offset`` may be a traced scalar; rows above the (shifted) diagonal are
    preserved — they hold R entries of columns factored by earlier panels.
    With ``offset=0`` this IS the unblocked engine on the whole matrix.
    """
    nb = panel.shape[1]
    alpha = jnp.zeros((nb,), dtype=panel.dtype)
    step = partial(_panel_step, offset=offset, precision=precision, norm=norm)
    return lax.fori_loop(0, nb, step, (panel, alpha))


def _lu_nopivot(M, base: int = 32):
    """Unpivoted LU of a square matrix, packed: tril(P,-1)+I = L, triu(P) = U.

    Recursion (left LU → two triangular solves → Schur update → right LU)
    keeps the work in GEMMs; the base case is the textbook elimination
    sweep. NO pivoting by design: the only caller factors ``Q1_top - S``
    with ``S = -sign(diag Q1)``, whose diagonal is bounded away from zero
    (|Q1_ii| + 1 in magnitude — Ballard et al., "Reconstructing
    Householder Vectors from TSQR", the stability result behind LAPACK's
    dorhr_col).
    """
    b = M.shape[0]
    if b <= base:
        def step(j, P):
            piv = P[j, j]
            idx = lax.iota(jnp.int32, b)
            l = jnp.where(idx > j, P[:, j] / piv, 0)
            urow = jnp.where(idx > j, P[j, :], 0)
            P = P - jnp.outer(l, urow)
            return P.at[:, j].set(jnp.where(idx > j, l, P[:, j]))

        return lax.fori_loop(0, b - 1, step, M)
    h = b // 2
    P11 = _lu_nopivot(M[:h, :h], base)
    L11 = jnp.tril(P11, -1) + jnp.eye(h, dtype=M.dtype)
    U11 = jnp.triu(P11)
    U12 = lax.linalg.triangular_solve(L11, M[:h, h:], left_side=True,
                                      lower=True, unit_diagonal=True)
    L21 = lax.linalg.triangular_solve(U11, M[h:, :h], left_side=False,
                                      lower=False)
    S22 = M[h:, h:] - jnp.matmul(L21, U12, precision="highest")
    P22 = _lu_nopivot(S22, base)
    return jnp.block([[P11, U12], [L21, P22]])


def _explicit_qr_tree(active, chunk: int):
    """Reduced QR of ``active`` (m x b, zero rows allowed) via a two-level
    TSQR tree: batched per-chunk QRs, one combine QR of the stacked R
    factors, and a batched GEMM assembling Q — the tall-matrix work
    becomes batched-QR + GEMM instead of one long Householder sweep.
    Rows are zero-padded to a chunk multiple; Householder-based chunk QRs
    keep zero rows zero, so the padded Q's bottom rows vanish and the
    slice back to m rows stays exactly orthonormal.
    """
    m, b = active.shape
    chunk = max(chunk, b)
    pad = (-m) % chunk
    Ap = jnp.concatenate([active, jnp.zeros((pad, b), active.dtype)]) \
        if pad else active
    C = Ap.shape[0] // chunk
    blocks = Ap.reshape(C, chunk, b)
    Qs, Rs = jax.vmap(lambda x: jnp.linalg.qr(x, mode="reduced"))(blocks)
    Q2, R = jnp.linalg.qr(Rs.reshape(C * b, b), mode="reduced")
    Q1 = jnp.matmul(Qs, Q2.reshape(C, b, b),
                    precision="highest").reshape(C * chunk, b)
    return Q1[:m], R


def _panel_qr_reconstruct(panel, offset, tree_chunk: int = 0):
    """Panel QR via explicit-Q factorization + Householder reconstruction.

    Instead of the serial column sweep, factor the panel with the
    backend's explicit QR (``jnp.linalg.qr`` — GEMM-rich internally),
    then RECONSTRUCT the packed reflectors (our ``||v||^2 = 2``, tau = 1
    storage) from Q: with ``S = -sign(diag Q_top)``, the unpivoted LU
    ``Q_top - S = L (-W)`` yields unit-triangular Householder directions
    ``Y = [L; -Q_bot W^{-1}]`` and real scales ``tau_i = W_ii / s_i``;
    ``v_i = Y[:, i] sqrt(tau_i)`` then satisfies our convention exactly
    (Ballard/Demmel/Grigori et al. 2014; LAPACK dorhr_col). Real dtypes
    only — the complex variant needs the modified LU that tracks the
    diagonal phases during elimination (LAPACK zunhr_col), not shipped.

    ``offset`` may be traced: the panel is rolled so its active rows
    (``offset:``) sit on top, the stale bottom rows are zeroed (zero rows
    leave reflectors untouched), and the preserved R rows are restored
    after rolling back.

    (No ``precision`` knob, unlike the loop/recursive engines:
    ``jnp.linalg.qr`` exposes none, and the reconstruction's dependent
    triangular solves and the Schur GEMM inside :func:`_lu_nopivot` run
    at "highest" unconditionally — they sit on the accuracy-critical
    path.)

    ``tree_chunk > 0`` computes the explicit QR through a two-level TSQR
    tree with that row-chunk size (:func:`_explicit_qr_tree`) instead of
    one direct ``jnp.linalg.qr`` — batched chunk QRs map better onto
    accelerators whose monolithic tall-matrix QR lowering is slow.
    Selected via the ``panel_impl="reconstruct:<chunk>"`` spelling, which
    rides the existing static string through every jit cache key.
    """
    m, b = panel.shape
    rows = lax.iota(jnp.int32, m)
    rolled = jnp.roll(panel, -offset, axis=0)
    live = (rows < m - offset)[:, None]
    active = jnp.where(live, rolled, jnp.zeros_like(rolled))
    if tree_chunk:
        Q1, R1 = _explicit_qr_tree(active, tree_chunk)
    else:
        Q1, R1 = jnp.linalg.qr(active, mode="reduced")
    d = jnp.diagonal(Q1[:b])
    s = jnp.where(d >= 0, -jnp.ones_like(d), jnp.ones_like(d))
    M = Q1[:b] - jnp.diag(s)
    P = _lu_nopivot(M)
    L1 = jnp.tril(P, -1) + jnp.eye(b, dtype=P.dtype)
    W = -jnp.triu(P)
    tau = jnp.diagonal(W) / s
    # Y2 = -Q1_bot W^{-1} (right-side upper-triangular solve)
    Y2 = lax.linalg.triangular_solve(W, -Q1[b:], left_side=False,
                                     lower=False)
    scale = jnp.sqrt(jnp.maximum(tau, 0))[None, :]
    V = jnp.concatenate([L1, Y2], axis=0) * scale
    Rh = s[:, None] * R1
    alpha = jnp.diagonal(Rh)
    cols = lax.iota(jnp.int32, b)
    top = jnp.where(cols[:b, None] < cols[None, :], Rh, V[:b])
    packed = jnp.concatenate([top, V[b:]], axis=0)
    merged = jnp.where(live, packed, rolled)
    return jnp.roll(merged, offset, axis=0), alpha


RECURSIVE_BASE_WIDTH = 32


def _panel_qr_recursive(panel, offset, precision=DEFAULT_PRECISION,
                        norm="accurate", base=RECURSIVE_BASE_WIDTH,
                        leaf=None):
    """Divide-and-conquer panel QR (the LAPACK geqrt3 recursion, TPU-style).

    Left half by recursion; the left reflectors applied to the right half as
    ONE compact-WY transform (two GEMMs + a small triangular solve — MXU
    work); right half by recursion at row offset ``offset + h``. Identical
    packed output and reflector numerics to :func:`_panel_qr_masked`; what
    changes is the *shape* of the trailing work inside the panel — per-column
    GEMV + rank-1 pairs survive only below ``base`` width, everything above
    becomes GEMMs. The reference's equivalent region is its per-column
    broadcast + hotloop chain (src:141-143, 198-213), which is memory-bound
    by construction; this is the panel-interior analogue of SURVEY.md §7
    stage 3. ``offset`` may be traced (the blocked engine's scan path).

    ``leaf(panel, offset)`` factors a base-width panel (default: the masked
    XLA loop). The same recursion body also serves the split-Pallas panel
    (``ops.blocked._panel_factor_pallas``) with the fused kernel as leaf —
    one divide-and-conquer to maintain, two leaf engines.
    """
    m, b = panel.shape
    if b <= base:
        if leaf is not None:
            return leaf(panel, offset)
        return _panel_qr_masked(panel, offset, precision=precision, norm=norm)
    from dhqr_tpu.ops.blocked import apply_block_reflector_h, shifted_tril

    h = b // 2
    left = lax.slice_in_dim(panel, 0, h, axis=1)
    right = lax.slice_in_dim(panel, h, b, axis=1)
    left_f, alpha_l = _panel_qr_recursive(left, offset, precision, norm, base,
                                          leaf)
    Y = shifted_tril(left_f, offset)
    right = apply_block_reflector_h(Y, right, precision)
    right_f, alpha_r = _panel_qr_recursive(right, offset + h, precision, norm,
                                           base, leaf)
    return (jnp.concatenate([left_f, right_f], axis=1),
            jnp.concatenate([alpha_l, alpha_r]))


@partial(jax.jit, static_argnames=("precision", "norm"))
def _householder_qr_impl(A, precision=DEFAULT_PRECISION, norm="accurate"):
    return _panel_qr_masked(A, 0, precision=precision, norm=norm)


def householder_qr(A: jax.Array, precision: str = DEFAULT_PRECISION,
                   norm: str = "accurate"):
    """Factor ``A`` (m x n, m >= n) in place: returns ``(H, alpha)``.

    ``H`` holds the reflectors (rows j:m of column j, ``||v||^2 = 2``) and R's
    strict upper triangle; ``alpha`` holds R's diagonal. Equivalent of
    reference ``householder!``/``_householder!`` (src:113-148) as one compiled
    ``fori_loop`` program.
    """
    from dhqr_tpu.utils.platform import ensure_complex_supported

    m, n = A.shape
    if m < n:
        raise ValueError(f"householder_qr requires m >= n, got {A.shape}")
    ensure_complex_supported(A.dtype)
    return _householder_qr_impl(A, precision=precision, norm=norm)
