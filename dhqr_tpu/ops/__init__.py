"""Compute ops: factorization engines, solvers, Pallas kernels (layers L0, L2, L3)."""
