"""Differentiable least squares — custom VJP through the QR factorization.

The reference is a pure numerical package with no autodiff story; in a JAX
framework ``lstsq`` should compose with ``grad``/``vmap``/``jit``. Naive
reverse-mode through the factorization's ``fori_loop`` would checkpoint
every panel step (O(n) copies of the matrix); instead we register the
closed-form VJP of the full-rank least-squares solution

    x(A, b) = argmin ||A x - b||,     dx = A+ (db - dA x) + (A^H A)^{-1} dA^H r

with r = b - A x and A+ = R^{-1} Q^H, giving cotangents

    b_bar = Q R^{-H} x_bar
    A_bar = -b_bar x^H + r w^H,    w = R^{-1} R^{-H} x_bar

— everything computed from the packed factors (H, alpha) of the forward
pass: two triangular solves with R and one compact-WY Q application. No
normal-equations matrix is ever formed.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dhqr_tpu.ops.blocked import (
    DEFAULT_BLOCK_SIZE,
    _apply_q_impl,
    _apply_qt_impl,
    _blocked_qr_impl,
)
from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.ops.solve import back_substitute, r_matrix


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def lstsq_diff(
    A, b, block_size=DEFAULT_BLOCK_SIZE, precision=DEFAULT_PRECISION,
    pallas=False, pallas_interpret=False,
):
    """``x = argmin ||A x - b||`` with an O(1)-memory reverse pass.

    Forward = the blocked engine pipeline (factor, Q^H b, back-substitute);
    backward = the closed-form least-squares VJP above. ``b`` may be (m,) or
    (m, k).
    """
    x, _ = _lstsq_fwd(A, b, block_size, precision, pallas, pallas_interpret)
    return x


def _lstsq_fwd(A, b, block_size, precision, pallas=False, pallas_interpret=False):
    H, alpha = _blocked_qr_impl(
        A, block_size, precision=precision,
        pallas=pallas, pallas_interpret=pallas_interpret,
    )
    c = _apply_qt_impl(H, b, block_size, precision=precision)
    x = back_substitute(H, alpha, c)
    return x, (A, b, H, alpha, x)


def _lstsq_bwd(block_size, precision, pallas, pallas_interpret, residuals, x_bar):
    del pallas, pallas_interpret  # forward-only choices
    A, b, H, alpha, x = residuals
    m, n = A.shape
    R = r_matrix(H, alpha)
    vec = x_bar.ndim == 1
    # JAX's cotangent convention for non-holomorphic functions: the incoming
    # cotangent is conjugated relative to the mathematical adjoint, and the
    # outgoing cotangents must be conjugated back (no-ops for real dtypes).
    x_bar = jnp.conj(x_bar)
    Xb = x_bar[:, None] if vec else x_bar
    X = x[:, None] if vec else x
    B = b[:, None] if vec else b
    # z = R^{-H} x_bar  (solve R^H z = x_bar)
    z = lax.linalg.triangular_solve(
        R, Xb, left_side=True, lower=False, transpose_a=True, conjugate_a=True
    )
    # b_bar = Q [z; 0]
    z_full = jnp.concatenate([z, jnp.zeros((m - n, z.shape[1]), z.dtype)])
    b_bar = _apply_q_impl(H, z_full, block_size, precision=precision)
    # w = R^{-1} z
    w = lax.linalg.triangular_solve(R, z, left_side=True, lower=False)
    r = B - jnp.matmul(A, X, precision=precision)
    A_bar = -jnp.matmul(b_bar, jnp.conj(X.T), precision=precision) + jnp.matmul(
        r, jnp.conj(w.T), precision=precision
    )
    A_bar = jnp.conj(A_bar)
    b_bar = jnp.conj(b_bar)
    return A_bar, b_bar[:, 0] if vec else b_bar


lstsq_diff.defvjp(_lstsq_fwd, _lstsq_bwd)
