"""Differentiable least squares — custom derivative through the QR pipeline.

The reference is a pure numerical package with no autodiff story; in a JAX
framework ``lstsq`` should compose with ``grad``/``jacfwd``/``vmap``/``jit``.
Naive autodiff through the factorization's loops would checkpoint every
panel step (O(n) copies of the matrix); instead we register the closed-form
differential of the full-rank least-squares solution

    x(A, b) = argmin ||A x - b||
    dx = A+ (db - dA x) + (A^H A)^{-1} dA^H r,   r = b - A x,  A+ = R^{-1} Q^H

as a ``jax.custom_jvp`` rule. The rule is *linear in the tangents* (dA, db)
and built only from transposable primitives (GEMMs with primal constants,
triangular solves against R, the compact-WY Q^H apply), so JAX derives
reverse-mode by transposition — one rule serves ``jax.jvp``/``jacfwd`` AND
``jax.grad``/``jacrev``/``jax.vjp``. (Round 1 used a ``custom_vjp``, which
silently removed forward-mode; its closed-form cotangents

    b_bar = Q R^{-H} x_bar;  A_bar = -b_bar x^H + r w^H,  w = R^{-1} R^{-H} x_bar

are exactly what transposing this JVP produces.) Everything is computed from
the packed factors (H, alpha) of the forward pass; no normal-equations
matrix is ever formed.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dhqr_tpu.ops.blocked import (
    DEFAULT_BLOCK_SIZE,
    _apply_qt_impl,
    _blocked_qr_impl,
)
from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.ops.solve import back_substitute, r_matrix


@partial(jax.custom_jvp,
         nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13))
def lstsq_diff(
    A, b, block_size=DEFAULT_BLOCK_SIZE, precision=DEFAULT_PRECISION,
    pallas=False, pallas_interpret=False, norm="accurate",
    panel_impl="loop", refine=0, pallas_flat=None, trailing_precision=None,
    lookahead=False, agg_panels=None, apply_precision=None,
):
    """``x = argmin ||A x - b||`` with closed-form O(1)-memory derivatives.

    Forward = the blocked engine pipeline (factor, Q^H b, back-substitute);
    derivatives = the closed-form least-squares differential above, in both
    forward and reverse mode. ``b`` may be (m,) or (m, k).

    ``refine`` adds that many iterative-refinement sweeps, each reusing the
    factorization (``x += A+ (b - A x)``, residual at full precision). The
    JVP rule is untouched by it: the rule is the differential of the exact
    minimizer, which refinement approaches rather than changes.

    ``apply_precision`` (default: ``precision``) is the solve stage's
    matmul precision — the Q^H applies feeding the triangular solves
    (the policy subsystem's ``apply`` field; factorization precision is
    unchanged by it).
    """
    x, _ = _lstsq_fwd(A, b, block_size, precision, pallas, pallas_interpret,
                      norm, panel_impl, refine, pallas_flat,
                      trailing_precision, lookahead, agg_panels,
                      apply_precision)
    return x


def _lstsq_fwd(A, b, block_size, precision, pallas=False,
               pallas_interpret=False, norm="accurate", panel_impl="loop",
               refine=0, pallas_flat=None, trailing_precision=None,
               lookahead=False, agg_panels=None, apply_precision=None):
    if pallas_flat is None:
        # Resolve the module global HERE (call time), not via
        # _blocked_qr_impl's in-trace default — the explicit static arg
        # keys the jit cache, so a PALLAS_FLAT_WIDTH change is honored on
        # the next call instead of silently reusing a stale trace (the
        # pattern blocked_householder_qr already follows).
        from dhqr_tpu.ops.blocked import PALLAS_FLAT_WIDTH
        pallas_flat = PALLAS_FLAT_WIDTH
    H, alpha = _blocked_qr_impl(
        A, block_size, precision=precision,
        pallas=pallas, pallas_interpret=pallas_interpret, norm=norm,
        panel_impl=panel_impl, pallas_flat=pallas_flat,
        trailing_precision=trailing_precision, lookahead=lookahead,
        agg_panels=agg_panels,
    )

    ap = precision if apply_precision is None else apply_precision

    def qr_solve(rhs):
        return back_substitute(
            H, alpha, _apply_qt_impl(H, rhs, block_size, precision=ap)
        )

    x = qr_solve(b)
    for _ in range(refine):
        r = b - jnp.matmul(A, x, precision="highest")
        x = x + qr_solve(r)
    return x, (A, b, H, alpha, x)


@lstsq_diff.defjvp
def _lstsq_jvp(block_size, precision, pallas, pallas_interpret, norm,
               panel_impl, refine, pallas_flat, trailing_precision,
               lookahead, agg_panels, apply_precision, primals, tangents):
    A, b = primals
    dA, db = tangents
    x, (_, _, H, alpha, _) = _lstsq_fwd(
        A, b, block_size, precision, pallas, pallas_interpret, norm,
        panel_impl, refine, pallas_flat, trailing_precision, lookahead,
        agg_panels, apply_precision
    )
    m, n = A.shape
    vec = x.ndim == 1
    X = x[:, None] if vec else x
    B = b[:, None] if vec else b
    dB = db[:, None] if vec else db
    R = r_matrix(H, alpha)
    # dx1 = A+ (db - dA x): Q^H through the compact-WY apply, then R^{-1}.
    U = dB - jnp.matmul(dA, X, precision=precision)
    dx1 = back_substitute(
        H, alpha, _apply_qt_impl(H, U, block_size, precision=precision)
    )
    # dx2 = (A^H A)^{-1} dA^H r via two triangular solves with R.
    r = B - jnp.matmul(A, X, precision=precision)
    Z = jnp.matmul(jnp.conj(dA.T), r, precision=precision)
    W = lax.linalg.triangular_solve(
        R, Z, left_side=True, lower=False, transpose_a=True, conjugate_a=True
    )
    dx2 = lax.linalg.triangular_solve(R, W, left_side=True, lower=False)
    dX = dx1 + dx2
    return x, (dX[:, 0] if vec else dX)
