"""Correctness + benchmark harness — the reference's de-facto CLI (L5).

The reference's only operational entry point is ``julia test/runtests.jl
<np>`` (reference test/runtests.jl:4): it spins up ``np`` workers, sweeps
problem sizes and element types, checks the normal-equations residual
against LAPACK with tolerance factor 8, and prints slowdown ratios
(runtests.jl:41-93). This module is that harness, TPU-native:

    python -m dhqr_tpu.harness [n_devices]
        [--sizes 110x100,1100x1000] [--dtypes float32,float64,complex128]
        [--layout block|cyclic] [--profile-dir DIR]

``n_devices`` plays the role of ``ARGS[1] = np``; without TPU hardware it is
satisfied with a virtual CPU mesh (``--xla_force_host_platform_device_count``),
the moral equivalent of the reference's local-process fake cluster
(``addprocs(np)``, runtests.jl:9).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time


def _parse_sizes(text: str):
    out = []
    for tok in text.split(","):
        m, n = tok.lower().split("x")
        out.append((int(m), int(n)))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dhqr_tpu.harness",
        description="Correctness sweep + LAPACK-relative benchmark "
        "(the reference's runtests.jl harness, TPU-native).",
    )
    parser.add_argument(
        "n_devices", nargs="?", type=int, default=2,
        help="mesh size (reference ARGS[1] = worker count; default 2)",
    )
    parser.add_argument(
        "--sizes", default="110x100,550x500,1100x1000",
        help="comma-separated mxn problem sizes (reference sweeps m=1.1n)",
    )
    parser.add_argument(
        "--dtypes", default="float64,complex128",
        help="comma-separated dtypes (reference: Float64, ComplexF64)",
    )
    # Engine-option defaults are None sentinels: precedence is
    # CLI flag > DHQR_* env var (DHQRConfig.from_env) > library default.
    parser.add_argument("--layout", default=None, choices=["block", "cyclic"])
    parser.add_argument(
        "--engine", default=None,
        choices=["householder", "tsqr", "cholqr2", "cholqr3"],
        help="least-squares engine family (tsqr/cholqr shard ROWS; their "
        "mesh uses the same device count)",
    )
    parser.add_argument("--block-size", type=int, default=None)

    def _panel_impl_arg(raw: str) -> str:
        # Parse-time validation mirroring qr_model._check_panel_impl, so
        # the reconstruct:<chunk> spelling is CLI-reachable and a typo
        # dies as a usage error before backend bring-up.
        if raw in ("loop", "recursive"):
            return raw
        if raw.startswith("reconstruct"):
            from dhqr_tpu.ops.blocked import _reconstruct_chunk

            try:
                _reconstruct_chunk(raw)
            except ValueError as e:
                raise argparse.ArgumentTypeError(str(e))
            return raw
        raise argparse.ArgumentTypeError(
            f"must be loop, recursive, reconstruct or reconstruct:<chunk>, "
            f"got {raw!r}")

    parser.add_argument(
        "--panel-impl", default=None, type=_panel_impl_arg,
        help="panel-interior algorithm for the blocked householder "
        "engines: loop, recursive, reconstruct, or reconstruct:<chunk> "
        "(explicit QR + Householder reconstruction, optionally via a "
        "TSQR tree; real dtypes only)",
    )
    parser.add_argument(
        "--trailing-precision", default=None,
        choices=["default", "high", "highest"],
        help="MXU precision for the trailing-update GEMMs only (blocked "
        "householder engines; the panel/T-factor precision stays at the "
        "DHQR_PRECISION env setting, default 'highest')",
    )
    parser.add_argument(
        "--lookahead", action="store_true", default=None,
        help="one-panel-lookahead schedule on the blocked householder "
        "engines (panel psum overlaps the trailing GEMM; same per-column "
        "arithmetic — see DHQRConfig.lookahead)",
    )
    def _agg_panels_arg(raw: str) -> int:
        # Parse-time validation so a bad value dies as a clean usage error
        # BEFORE backend bring-up; "0" means off, matching the
        # DHQR_AGG_PANELS env spelling (config.py). The 0 survives to the
        # overrides merge (so an explicit --agg-panels 0 cancels an
        # ambient env value) and is normalized to None after.
        v = int(raw)
        if v == 1 or v < 0:
            raise argparse.ArgumentTypeError(
                f"must be 0 (off) or >= 2, got {v}")
        return v

    parser.add_argument(
        "--agg-panels", type=_agg_panels_arg, default=None,
        help="aggregate the trailing update over this many consecutive "
        "panels; 0 = off (blocked householder engines, single-device and "
        "sharded; see DHQRConfig.agg_panels)",
    )
    parser.add_argument(
        "--guards", default=None,
        choices=["screen", "fallback", "full"],
        help="numeric guardrails for every solve in the sweep "
        "(dhqr_tpu.numeric, round 13): 'screen' = input screening only, "
        "'fallback' adds breakdown detection + the engine/policy "
        "fallback ladder, 'full' adds the one-shot 8x-LAPACK residual "
        "probe; a problem no rung answers fails TYPED instead of "
        "printing a silent-garbage row",
    )
    parser.add_argument(
        "--profile-dir", default=None,
        help="write a jax.profiler trace here (the @profilehtml analogue)",
    )
    parser.add_argument(
        "--bench", action="store_true",
        help="also time factor+solve and print slowdown vs numpy/LAPACK "
        "(reference runtests.jl:84-89)",
    )
    args = parser.parse_args(argv)

    # Decide the platform before first backend use: a real TPU only on
    # explicit request, else a virtual CPU mesh of the requested size.
    # "Explicit" means JAX_PLATFORMS=tpu or DHQR_HARNESS_TPU=1 — the axon
    # hosts pin JAX_PLATFORMS=axon ambiently (the TPU tunnel plugin), and
    # an ambient pin must not silently put a correctness sweep on the
    # shared chip; DHQR_HARNESS_TPU=1 is how to run the CLI on it.
    plats = os.environ.get("JAX_PLATFORMS", "").lower()
    force_cpu = not ("tpu" in plats
                     or os.environ.get("DHQR_HARNESS_TPU") == "1")
    if force_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            # dhqr: ignore[DHQR003] CLI entry point owns its process; XLA_FLAGS is only read at first backend init, which is still ahead
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={args.n_devices}"
            ).strip()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dhqr_tpu.utils.platform import enable_compile_cache, force_cpu_platform

    if force_cpu:
        force_cpu_platform()
    enable_compile_cache()

    if jax.default_backend() == "cpu":
        # dhqr: ignore[DHQR003] CLI entry point owns its process; x64 gives the reference's Float64/ComplexF64 parity sweep
        jax.config.update("jax_enable_x64", True)

    import dhqr_tpu
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.utils.profiling import PhaseTimer, sync, trace
    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        lapack_lstsq,
        normal_equations_residual,
        oracle_residual,
        random_problem,
    )

    from dhqr_tpu.utils.config import DHQRConfig

    ndev = min(args.n_devices, len(jax.devices()))
    mesh = column_mesh(ndev) if ndev > 1 else None
    overrides = {k: v for k, v in {
        "layout": args.layout, "engine": args.engine,
        "block_size": args.block_size, "panel_impl": args.panel_impl,
        "trailing_precision": args.trailing_precision,
        "lookahead": args.lookahead,
        "agg_panels": args.agg_panels,
        "guards": args.guards,
    }.items() if v is not None}
    cfg = DHQRConfig.from_env(**overrides)
    if cfg.agg_panels == 0:  # explicit --agg-panels 0 = off (see above)
        cfg = dataclasses.replace(cfg, agg_panels=None)
    # block_size=None stays None: lstsq resolves it per backend/shape
    # (ops/blocked.auto_block_size - the measured nb=256/512 TPU optimum).
    row_engine = cfg.engine != "householder"
    if row_engine and cfg.layout != "block":
        if args.layout is not None:
            # Explicit flag conflict: hard error.
            parser.error(f"--layout={cfg.layout} applies to the householder "
                         f"engines only (engine={cfg.engine})")
        # Env-sourced (an ambient DHQR_LAYOUT=cyclic in the shell must not
        # abort a tsqr/cholqr run that predates the layout check — ADVICE
        # r3): warn and fall back to the row engines' only layout.
        print(f"# warning: DHQR_LAYOUT={cfg.layout} ignored — layout "
              f"applies to the householder engines only "
              f"(engine={cfg.engine}); using 'block'", file=sys.stderr)
        cfg = dataclasses.replace(cfg, layout="block")
    if cfg.trailing_precision is not None and (
            cfg.engine != "householder" or not cfg.blocked):
        # Same treatment as layout: explicit flag conflict errors, an
        # ambient DHQR_TRAILING_PRECISION warns and is dropped — the sweep
        # must not die in the first lstsq call's validation. The knob
        # needs the BLOCKED householder engines, so an env-sourced
        # DHQR_BLOCKED=false conflicts exactly like a row engine does.
        why = (f"engine={cfg.engine}" if cfg.engine != "householder"
               else "blocked=False")
        if args.trailing_precision is not None:
            parser.error(f"--trailing-precision applies to the blocked "
                         f"householder engines only ({why})")
        print(f"# warning: DHQR_TRAILING_PRECISION="
              f"{cfg.trailing_precision} ignored — it applies to the "
              f"blocked householder engines only ({why})",
              file=sys.stderr)
        cfg = dataclasses.replace(cfg, trailing_precision=None)
    if cfg.lookahead and (cfg.engine != "householder" or not cfg.blocked):
        # Same split as trailing_precision: explicit flag conflict errors,
        # ambient DHQR_LOOKAHEAD warns and is dropped.
        why = (f"engine={cfg.engine}" if cfg.engine != "householder"
               else "blocked=False")
        if args.lookahead is not None:
            parser.error(f"--lookahead applies to the blocked householder "
                         f"engines only ({why})")
        print(f"# warning: DHQR_LOOKAHEAD ignored — it applies to the "
              f"blocked householder engines only ({why})", file=sys.stderr)
        cfg = dataclasses.replace(cfg, lookahead=False)
    if cfg.agg_panels and cfg.lookahead and ndev == 1:
        # Mutually exclusive on ONE device (on a mesh the pair is the
        # grouped-lookahead composition and passes through). Same
        # ambient-vs-flag split as the other knobs: two explicit flags is
        # a hard usage error; an env-sourced half of the conflict is
        # dropped with a warning so an ambient leftover (e.g.
        # DHQR_LOOKAHEAD=1 from a prior sweep) cannot abort the run
        # mid-sweep with a raw ValueError.
        if args.agg_panels is not None and args.lookahead is not None:
            parser.error("--agg-panels and --lookahead are mutually "
                         "exclusive schedules on one device (a mesh "
                         "composes them as grouped lookahead)")
        if args.agg_panels is not None:  # lookahead came from the env
            print("# warning: DHQR_LOOKAHEAD ignored — mutually exclusive "
                  "with the explicit --agg-panels on one device",
                  file=sys.stderr)
            cfg = dataclasses.replace(cfg, lookahead=False)
        else:  # agg came from the env (lookahead explicit or also env)
            print("# warning: DHQR_AGG_PANELS ignored — mutually exclusive "
                  "with lookahead on one device", file=sys.stderr)
            cfg = dataclasses.replace(cfg, agg_panels=None)
    # agg_panels runs on BOTH tiers since round-5 session 2 (the sharded
    # aggregated engine, parallel/sharded_qr._blocked_shard_agg) — only
    # the non-householder / unblocked engines still reject it.
    if cfg.agg_panels and (cfg.engine != "householder" or not cfg.blocked):
        why = (f"engine={cfg.engine}" if cfg.engine != "householder"
               else "blocked=False")
        if args.agg_panels is not None:
            parser.error(f"--agg-panels applies to the blocked "
                         f"householder engines only ({why})")
        print(f"# warning: DHQR_AGG_PANELS ignored — it applies to the "
              f"blocked householder engines only ({why})",
              file=sys.stderr)
        cfg = dataclasses.replace(cfg, agg_panels=None)
    print(f"# devices: {len(jax.devices())} ({jax.default_backend()}), "
          f"mesh size: {ndev}, engine: {cfg.engine}"
          + ("" if row_engine else f", layout: {cfg.layout}"))

    failures = 0
    for dtype_name in args.dtypes.split(","):
        dtype = np.dtype(dtype_name.strip())
        if jax.default_backend() == "tpu" and dtype.itemsize * (
            2 if np.issubdtype(dtype, np.complexfloating) else 1
        ) > 4:
            print(f"# skip {dtype_name} on TPU (f64/c128 are emulated)")
            continue
        for m, n in _parse_sizes(args.sizes):
            # The householder mesh engines pad arbitrary n internally
            # (parallel/sharded_qr._pad_cols_orthogonal) — sizes run as
            # given. Row engines still need m divisible (local blocks tall).
            if mesh is not None and row_engine and m % ndev:
                m += ndev - m % ndev
            size_mesh = mesh
            if (mesh is not None and cfg.engine == "tsqr"
                    and m // ndev < n):  # local row blocks must stay tall
                print(f"# {m}x{n}: m/P < n, tsqr runs single-device")
                size_mesh = None
            A, b = random_problem(m, n, dtype, seed=0)
            Aj, bj = jnp.asarray(A), jnp.asarray(b)
            timer = PhaseTimer()
            try:
                with timer.measure("factor+solve"):
                    x = dhqr_tpu.lstsq(Aj, bj, config=cfg, mesh=size_mesh)
                    timer.observe(x)
            except dhqr_tpu.NumericalError as e:
                # Guards armed (--guards): the ladder ran dry and
                # refused typed — a FAIL row with the classification,
                # never a silent-garbage residual line.
                failures += 1
                print(f"FAIL  {m}x{n} {dtype_name:<10} typed "
                      f"{type(e).__name__}: {e}")
                continue
            res = normal_equations_residual(A, np.asarray(x), b)
            ref = oracle_residual(A, b)
            # EXACTLY the reference's acceptance rule: normal-equations
            # residual < 8x LAPACK's (runtests.jl:62,81). No escape hatch.
            tol = TOLERANCE_FACTOR * ref
            ok = res < tol
            status = "ok" if ok else "FAIL"
            failures += 0 if ok else 1
            print(
                f"{status}  {m}x{n} {dtype_name:<10} residual {res:.3e} "
                f"(LAPACK {ref:.3e}, tol {tol:.3e})  "
                f"t={timer.total('factor+solve'):.3f}s"
            )
            if args.bench:
                # dhqr: ignore[DHQR008] benchmarking the LAPACK oracle's real wall time — the CLI owns its clock
                t0 = time.perf_counter()
                x_np = lapack_lstsq(A, b)
                # dhqr: ignore[DHQR008] same measurement, closing read
                t_lapack = time.perf_counter() - t0
                del x_np
                # warm (compile-cached) run — the first timing above includes
                # XLA compilation, which the reference has no analogue of
                with timer.measure("warm"):
                    x = dhqr_tpu.lstsq(Aj, bj, config=cfg, mesh=size_mesh)
                    timer.observe(x)
                t_ours = timer.total("warm")
                # reference prints "slowdown of distributed+threaded vs
                # stdlib" (runtests.jl:88); same ratio here
                print(f"      slowdown vs LAPACK (warm): "
                      f"{t_ours / max(t_lapack, 1e-9):.2f}x")

    if args.profile_dir:
        A, b = random_problem(512, 256, np.float32, seed=1)
        with trace(args.profile_dir):
            x = dhqr_tpu.lstsq(jnp.asarray(A), jnp.asarray(b), mesh=mesh)
            sync(x)
        print(f"# profiler trace written to {args.profile_dir}")

    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
