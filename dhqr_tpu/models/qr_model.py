"""Factorization object + public API (layer L4 of SURVEY.md §1).

TPU-native equivalent of the reference's user surface
(reference src/DistributedHouseholderQR.jl:296-321):

* ``DistributedHouseholderQRStruct{T1,T2}(A, alpha)``  ->  :class:`QRFactorization`
  — a pytree dataclass holding the overwritten matrix (reflectors below the
  diagonal, R's strict upper triangle above) and ``alpha`` (R's diagonal);
* ``qr!(A)``  ->  :func:`qr` — functional (JAX arrays are immutable; XLA
  donation recovers the in-place behavior under jit);
* ``H \\ b``  ->  :meth:`QRFactorization.solve` / :func:`solve` /
  :func:`lstsq`.

Where the reference picks its execution tier by array type (Matrix /
SharedArray / DArray multiple dispatch, src:113-120), the TPU framework picks
it by configuration and sharding: the same functions run unblocked, blocked
compact-WY, or mesh-sharded (see ``dhqr_tpu.parallel``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from dhqr_tpu.ops import blocked as _blocked
from dhqr_tpu.ops import householder as _hh
from dhqr_tpu.ops import solve as _solve
from dhqr_tpu.utils.config import DHQRConfig

LSTSQ_ENGINES = ("householder", "tsqr", "cholqr2", "cholqr3", "sketch")


def _check_sched_knobs(cfg: DHQRConfig, mesh=None) -> None:
    """Shared schedule-knob validation for qr() and lstsq() — the ops-level
    wrapper also checks, but lstsq's jitted route bypasses it, and a bad
    value must not be silently ignored there."""
    if cfg.agg_panels is not None and cfg.agg_panels < 2:
        raise ValueError(
            f"agg_panels must be >= 2 (got {cfg.agg_panels}); "
            "None means per-panel updates"
        )
    if cfg.agg_panels and cfg.lookahead and mesh is None:
        raise ValueError(
            "agg_panels and lookahead are mutually exclusive on the "
            "single-device tier (both only add flops there); on a mesh "
            "the pair is the grouped-lookahead composition — pass mesh= "
            "(see parallel/sharded_qr._blocked_shard_agg)"
        )
    if cfg.overlap_depth is not None:
        if cfg.overlap_depth < 1:
            raise ValueError(
                f"overlap_depth must be >= 1 (got {cfg.overlap_depth}); "
                "None means the default schedule"
            )
        if not cfg.lookahead:
            raise ValueError(
                "overlap_depth generalizes the lookahead order and "
                "requires lookahead=True (depth 1 IS the one-panel "
                "lookahead)"
            )
        if cfg.agg_panels:
            raise ValueError(
                "overlap_depth and agg_panels are mutually exclusive "
                "(the grouped-lookahead composition already overlaps "
                "one full group per collective)"
            )
        if mesh is None:
            raise ValueError(
                "overlap_depth is mesh-only: a deeper pipeline exists "
                "to keep panel-broadcast collectives in flight, and a "
                "single device has no collective to hide — pass mesh= "
                "(see parallel/sharded_qr._blocked_shard_pipeline)"
            )


def _resolve_policy_cfg(cfg: DHQRConfig):
    """Resolve ``cfg.policy`` into the classic precision knobs (shared by
    ``qr`` and ``lstsq``).

    Returns ``(cfg', policy-or-None)``: the returned config carries
    ``precision``/``trailing_precision`` from the policy and
    ``policy=None``; the solve-stage fields (``apply``, ``refine``) ride
    back on the policy object for the caller to place — ``qr`` records
    them on the factorization, ``lstsq`` maps refine into ``cfg.refine``
    and apply into the solve impls. A policy is mutually exclusive with
    setting the knobs it resolves (a call naming both spellings is
    ambiguous and refuses loudly rather than letting one silently win).
    """
    from dhqr_tpu.precision import resolve_comms

    # Normalize the classic comms knob FIRST (every qr/lstsq/serve call
    # passes through here): "f32"/"none" collapse to None and an
    # invalid wire format refuses loudly on EVERY path — without this,
    # a bad DHQR_COMMS only surfaced on the mesh tier, and the "f32"
    # spelling read as truthy to the CSNE-floor logic downstream.
    if cfg.comms is not None:
        cfg = dataclasses.replace(cfg, comms=resolve_comms(cfg.comms))
    if cfg.policy is None:
        return cfg, None
    from dhqr_tpu.precision import (apply_policy_to_factor_args,
                                    resolve_policy)

    pol = resolve_policy(cfg.policy)
    # precision/trailing exclusivity lives in the shared factor-args
    # merge (the same contract every ops-level entry point applies); the
    # solve-stage fields are config-only, so their checks live here.
    precision, trailing = apply_policy_to_factor_args(
        pol, cfg.precision, cfg.trailing_precision,
        default_precision=DHQRConfig.precision)
    if cfg.refine:
        raise ValueError(
            "pass either policy= or refine=, not both "
            f"(policy sets refine={pol.refine})"
        )
    if cfg.apply_precision is not None:
        raise ValueError(
            "pass either policy= or apply_precision=, not both "
            f"(policy resolves apply to {pol.resolved_apply()!r})"
        )
    if cfg.comms is not None:
        raise ValueError(
            "pass either policy= or comms=, not both "
            f"(policy sets the wire format to {pol.comms!r})"
        )
    apply = pol.resolved_apply()
    cfg = dataclasses.replace(
        cfg, precision=precision, trailing_precision=trailing,
        apply_precision=None if apply == pol.panel else apply,
        comms=pol.comms, policy=None,
    )
    return cfg, pol


def _resolve_plan_cfg(cfg: DHQRConfig, kind: str, shape, dtype, mesh,
                      pol, applied: "list | None" = None) -> DHQRConfig:
    """Resolve ``cfg.plan`` into the classic engine-selection knobs
    (shared by ``qr`` and ``lstsq``; the serve tier has its own
    per-bucket twin in ``serve.engine``).

    ``"auto"`` looks the (kind, shape, dtype, mesh, policy) key up in
    the plan database — tuning on a miss per ``TuneConfig.on_miss`` — a
    :class:`dhqr_tpu.tune.Plan` applies verbatim, and ``"default"``
    (or None) keeps the static knobs. A plan names the whole
    engine-selection tuple at once, so it is mutually exclusive with
    setting any of those knobs explicitly (same refuse-loudly contract
    as ``policy=``). Runs AFTER policy resolution: plans are keyed
    under the policy, and a policy-set ``trailing_precision`` always
    wins over the plan's (``tune.apply_plan_to_config``).

    ``applied`` (optional list) receives the :class:`Plan` when one
    ACTUALLY lands on the config — a "auto" DB miss with
    ``on_miss="default"``, an m < n shape, or ``plan="default"`` all
    leave it untouched. The numeric ladder keys plan demotion on this
    (a rung-0 failure must never demote a key that served the static
    default).
    """
    spec = cfg.plan
    if spec is None:
        return cfg
    if isinstance(spec, str) and spec == "default":
        return dataclasses.replace(cfg, plan=None)
    from dhqr_tpu.tune import Plan, apply_plan_to_config, resolve_plan

    m, n = shape
    if m < n:
        # The minimum-norm path supports exactly one configuration —
        # there is nothing for a plan to select.
        return dataclasses.replace(cfg, plan=None)
    if not cfg.blocked:
        raise ValueError(
            "plan= applies to the blocked/alt engines only: the "
            "unblocked reference-parity engine (blocked=False) has no "
            "plan knobs to select"
        )
    defaults = DHQRConfig()
    # use_pallas is in the list although it is not a Plan field: plans
    # are measured under the "auto" resolution, so pinning the kernel
    # choice while asking for a tuned plan would apply knobs to a
    # program family the tuner never timed — refuse loudly instead.
    for knob in ("engine", "block_size", "panel_impl", "lookahead",
                 "agg_panels", "overlap_depth", "use_pallas"):
        if getattr(cfg, knob) != getattr(defaults, knob):
            raise ValueError(
                f"pass either plan= or {knob}=, not both (a plan names "
                f"the engine-selection knobs at once; got "
                f"{knob}={getattr(cfg, knob)!r} with plan={spec!r})"
            )
    if isinstance(spec, Plan):
        if spec.trailing_precision and cfg.trailing_precision is not None:
            raise ValueError(
                "the plan carries trailing_precision="
                f"{spec.trailing_precision!r} but the policy/config "
                f"already set {cfg.trailing_precision!r} — drop one"
            )
        plan = spec
    elif isinstance(spec, str) and spec == "auto":
        plan = resolve_plan(kind, m, n, dtype, mesh=mesh, policy=pol)
        if plan is None:  # DB miss with on_miss="default"
            return dataclasses.replace(cfg, plan=None)
    else:
        raise ValueError(
            f"plan must be 'auto', 'default', None or a dhqr_tpu.tune.Plan,"
            f" got {spec!r}"
        )
    if applied is not None:
        applied.append(plan)
    return apply_plan_to_config(cfg, plan)


def _check_panel_impl(cfg: DHQRConfig) -> None:
    """Shared panel_impl validation for qr() and lstsq()."""
    if cfg.panel_impl.startswith("reconstruct"):
        from dhqr_tpu.ops.blocked import _reconstruct_chunk

        _reconstruct_chunk(cfg.panel_impl)  # raises on a malformed spelling
    elif cfg.panel_impl not in ("loop", "recursive"):
        raise ValueError(
            f"panel_impl must be 'loop', 'recursive', 'reconstruct' or "
            f"'reconstruct:<chunk>', got {cfg.panel_impl!r}"
        )
    if cfg.panel_impl != "loop" and not cfg.blocked:
        raise ValueError(
            "panel_impl applies to the blocked engines only "
            f"(got panel_impl={cfg.panel_impl!r} with blocked=False)"
        )


def _csne_refine(A, R, x, b, steps: int):
    """Corrected semi-normal refinement: ``x += (R^H R)^{-1} A^H (b -
    A x)``, residual and Gram-side matvecs at full precision. No
    ``M r*`` fixed-point bias (``A^H r* = 0`` exactly at the
    least-squares solution), so it converges for factorizations whose
    R carries wire-level rounding — the compressed-comms recovery path
    (dhqr-wire, round 18; Björck's CSNE as in ``solvers.update``)."""
    from jax import lax

    vec = x.ndim == 1
    X = x[:, None] if vec else x
    B = b[:, None] if vec else b
    for _ in range(steps):
        resid = B - jnp.matmul(A, X, precision="highest")
        G = jnp.matmul(jnp.conj(A.T), resid, precision="highest")
        Y = lax.linalg.triangular_solve(R, G, left_side=True, lower=False,
                                        transpose_a=True, conjugate_a=True)
        X = X + lax.linalg.triangular_solve(R, Y, left_side=True,
                                            lower=False)
    return X[:, 0] if vec else X


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QRFactorization:
    """Packed Householder QR factorization of a tall matrix A (m x n, m >= n).

    Fields (the reference's exact storage scheme, src:296-309):
      H: (m, n) — reflectors v_j (||v_j||^2 = 2) in rows j:m of column j;
         R's strict upper triangle in rows < j.
      alpha: (n,) — R's diagonal.
      block_size: compact-WY panel width used to *apply* Q/Q^H in solves
        (static aux data, not a leaf).
      mesh: optional — when set, H is column-sharded over this mesh and
        solves run the distributed engines (the DArray tier of reference
        src:115-120, selected here by placement rather than array type).
      precision: matmul precision used when applying Q/Q^H in solves (the
        precision policy's ``apply`` field when built via
        ``qr(A, policy=...)``).
      layout: distributed column layout used for mesh solves ("block" or
        "cyclic"); H itself is always stored in natural column order.
      refine: iterative-refinement sweeps :meth:`solve` runs by default —
        each reuses this factorization (``r = b - A x; x += solve(r)``,
        residual matvec at full precision), which is what lets a
        low-precision factor (``policy.trailing``) buy its backward error
        back at a few percent of the factorization cost.
      matrix: the original A, kept ONLY when refinement was requested at
        factor time (``qr(A, policy=...)`` with ``policy.refine > 0``) —
        the residual must be measured against the true A, not against the
        factor's own Q R (whose defect is exactly the error being
        corrected). A pytree leaf when present; None otherwise (arrays
        are immutable, so keeping the reference costs nothing).
      comms: collective wire format for mesh solves (dhqr-wire, round
        18): the solve stage's panel broadcasts ride the same
        compressed wire the factor stage used, so a bf16-wire
        factorization's solves stay on the bf16-wire program (one
        compiled program per mode; single-device solves launch no
        collectives and ignore it by contract).
    """

    H: jax.Array
    alpha: jax.Array
    block_size: int = _blocked.DEFAULT_BLOCK_SIZE
    mesh: object = None
    precision: str = _hh.DEFAULT_PRECISION
    layout: str = "block"
    refine: int = 0
    matrix: Optional[jax.Array] = None
    comms: "str | None" = None

    # -- pytree plumbing ---------------------------------------------------
    def tree_flatten(self):
        # ``matrix`` rides as a child: None flattens to an empty subtree,
        # so presence lives in the treedef and jit caching stays correct.
        return (self.H, self.alpha, self.matrix), (
            self.block_size, self.mesh, self.precision, self.layout,
            self.refine, self.comms,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        H, alpha, matrix = leaves
        return cls(
            H, alpha,
            block_size=aux[0], mesh=aux[1], precision=aux[2], layout=aux[3],
            refine=aux[4], comms=aux[5], matrix=matrix,
        )

    # -- derived quantities ------------------------------------------------
    @property
    def shape(self):
        return self.H.shape

    @property
    def dtype(self):
        return self.H.dtype

    def r_matrix(self) -> jax.Array:
        """Dense n x n upper-triangular R."""
        return _solve.r_matrix(self.H, self.alpha)

    def q_columns(self, k: Optional[int] = None) -> jax.Array:
        """Materialize the first k columns of Q (default n) — test/debug aid;
        the reference never forms Q explicitly."""
        m, n = self.H.shape
        k = n if k is None else k
        eye = jnp.eye(m, k, dtype=self.H.dtype)
        return _blocked.blocked_apply_q(
            self.H, self.alpha, eye, self.block_size, precision=self.precision
        )

    def condition_estimate(self) -> jax.Array:
        """Cheap LOWER bound on cond_2(A): ``max|r_ii| / min|r_ii|``.

        R's diagonal magnitudes bound the extreme singular values
        (``sigma_max >= max|r_ii|``, ``sigma_min <= min|r_ii|``), so the
        ratio never overestimates. Without column pivoting it can
        UNDERESTIMATE badly on adversarial matrices (a famous example:
        the Kahan matrix), but it is the right cheap pre-check for the
        CholeskyQR window (``cond(A) < ~1/sqrt(eps)`` — ops/cholqr.py):
        if even the lower bound exceeds the window, do not route there.
        O(n), no extra factorization work.
        """
        d = jnp.abs(self.alpha)
        return jnp.max(d) / jnp.min(d)

    def rank(self, rtol: Optional[float] = None) -> jax.Array:
        """Numerical rank estimate: ``#{i : |r_ii| > rtol * max|r_ii|}``.

        Default rtol = ``max(m, n) * eps`` of the dtype (the numpy
        ``matrix_rank`` convention). Same caveat as
        :meth:`condition_estimate`: without pivoting the R diagonal can
        hide deficiency — treat as a diagnostic, not a guarantee.
        """
        m, n = self.H.shape
        d = jnp.abs(self.alpha)
        if rtol is None:
            rtol = max(m, n) * float(jnp.finfo(d.dtype).eps)
        return jnp.sum(d > rtol * jnp.max(d))

    # -- solves ------------------------------------------------------------
    def _solve_once(self, b: jax.Array) -> jax.Array:
        """One raw solve pass (no refinement) on the recorded tier."""
        if self.mesh is not None:
            from dhqr_tpu.parallel.sharded_solve import sharded_solve

            return sharded_solve(
                self.H, self.alpha, b, self.mesh,
                block_size=self.block_size, precision=self.precision,
                layout=self.layout, comms=self.comms,
            )
        c = _blocked.blocked_apply_qt(
            self.H, self.alpha, b, self.block_size, precision=self.precision
        )
        return _solve.back_substitute(self.H, self.alpha, c)

    def solve(self, b: jax.Array, refine: Optional[int] = None) -> jax.Array:
        """Least-squares solve ``x = argmin ||A x - b||`` — reference ``H \\ b``
        (src:317-321): apply Q^H, back-substitute R, truncate to n. Routes to
        the distributed engines when the factorization is mesh-sharded.

        ``refine`` (default: the factorization's recorded ``refine``
        count) runs that many iterative-refinement sweeps reusing this
        factorization — the solve-side half of a precision policy: a
        factor built with a cheap trailing precision plus one sweep here
        recovers the full-precision backward error. Requires the
        factorization to carry the original ``matrix`` (``qr`` keeps it
        whenever the resolved policy refines).
        """
        steps = self.refine if refine is None else int(refine)
        x = self._solve_once(b)
        if steps:
            if self.matrix is None:
                raise ValueError(
                    "refinement needs the original matrix: factor with "
                    "qr(A, policy=...) (policy.refine > 0 keeps A on the "
                    "factorization), or pass refine=0"
                )
            if self.comms is not None:
                # dhqr-wire (round 18): a compressed-wire factorization
                # carries ~wire-eps error, and plain residual refinement
                # stalls at its fixed-point bias M r* (the solve's
                # perturbed Q^H does not annihilate the TRUE residual,
                # which is O(1) for inconsistent systems). Corrected
                # semi-normal sweeps have no such bias — A^H r* = 0
                # exactly at the solution — so refine through the
                # normal equations with this factorization's R instead
                # (Björck's CSNE, the same recovery solvers.update and
                # the compressed row engines use).
                return _csne_refine(self.matrix, _solve.r_matrix(
                    self.H, self.alpha), x, b, steps)
            for _ in range(steps):
                r = b - jnp.matmul(self.matrix, x, precision="highest")
                x = x + self._solve_once(r)
        return x

    def matmul_q(self, b: jax.Array) -> jax.Array:
        """Q @ b (b of length m, or (m, k))."""
        return _blocked.blocked_apply_q(
            self.H, self.alpha, b, self.block_size, precision=self.precision
        )

    def matmul_qt(self, b: jax.Array) -> jax.Array:
        """Q^H @ b."""
        return _blocked.blocked_apply_qt(
            self.H, self.alpha, b, self.block_size, precision=self.precision
        )


def qr(
    A: jax.Array,
    config: Optional[DHQRConfig] = None,
    donate: bool = False,
    mesh=None,
    **overrides,
) -> QRFactorization:
    """Factor A: the reference's ``qr!(A)`` (src:311-315), tier chosen by config.

    >>> fact = qr(A)                       # blocked compact-WY (MXU path)
    >>> fact = qr(A, blocked=False)        # unblocked reference-parity path
    >>> fact = qr(A, donate=True)          # true in-place: A's buffer is reused
    ...                                    # (and invalidated), like qr!'s overwrite
    >>> fact = qr(A, mesh=column_mesh(8))  # distributed: the DArray tier

    ``policy=`` (a :class:`dhqr_tpu.precision.PrecisionPolicy`, preset
    name or spec string) names the whole precision tuple at once: panel
    and trailing precision go to the factor engines, ``apply`` becomes
    the factorization's solve precision, and ``refine > 0`` arms
    solve-side iterative refinement — the factorization keeps a reference
    to A (free; arrays are immutable) so every later ``.solve(b)`` can
    buy a cheap factor's backward error back against the true matrix.
    """
    from dhqr_tpu.utils.platform import ensure_complex_supported

    cfg = dataclasses.replace(config or DHQRConfig(), **overrides)
    if cfg.guards is not None:
        # Numeric guardrails (round 13): screening, breakdown
        # detection, policy escalation, typed refusal — the provenance
        # surface is dhqr_tpu.numeric.guarded_qr; this facade returns
        # the factorization only.
        if donate:
            raise ValueError(
                "donate=True cannot be combined with guards=: escalation "
                "must be able to re-read A, which donation invalidates"
            )
        from dhqr_tpu.numeric.ladder import guarded_qr

        return guarded_qr(A, config=cfg, mesh=mesh).factorization
    cfg, pol = _resolve_policy_cfg(cfg)
    cfg = _resolve_plan_cfg(cfg, "qr", A.shape, A.dtype, mesh, pol)
    if cfg.engine != "householder":
        if cfg.engine not in LSTSQ_ENGINES:
            raise ValueError(
                f"unknown engine {cfg.engine!r}: expected one of {LSTSQ_ENGINES}"
            )
        raise ValueError(
            f"qr() supports only engine='householder' (got {cfg.engine!r}): "
            "the factorization object stores packed reflectors; the "
            "tsqr/cholqr/sketch engines are lstsq-only fast paths"
        )
    _check_panel_impl(cfg)
    _check_sched_knobs(cfg, mesh)
    if cfg.refine:
        raise ValueError(
            "refine applies to lstsq() only — qr() returns the raw "
            "factorization; call fact.solve and refine around it, use "
            "lstsq(A, b, refine=...), or pass a policy= with refine > 0 "
            "(which arms refinement on the factorization's solves)"
        )
    solve_refine = pol.refine if pol is not None else 0
    apply_prec = cfg.apply_precision or cfg.precision
    if solve_refine and donate:
        raise ValueError(
            "donate=True cannot be combined with a refining policy: "
            "refinement must keep the original A, which donation "
            "invalidates"
        )
    ensure_complex_supported(A.dtype)
    # Resolve the auto panel width once, up front: the factorization object
    # must record a concrete nb (its solves reuse it), and the mesh planner
    # needs an int. None = backend/shape auto (ops/blocked.auto_block_size);
    # the mesh tier keeps the 128 default (the kernel's VMEM gate applies
    # per-shard there, and padding planning is nb-coupled).
    if cfg.block_size is None:
        bs = (_blocked.auto_block_size(A.shape[0], A.dtype, cfg.use_pallas)
              if mesh is None and cfg.blocked
              else _blocked.DEFAULT_BLOCK_SIZE)
        cfg = dataclasses.replace(cfg, block_size=bs)
    if mesh is not None:
        if donate:
            raise ValueError(
                "donate=True is not supported on the mesh path (the input is "
                "re-placed onto the mesh, so donation cannot honor its contract)"
            )
        from dhqr_tpu.parallel import sharded_qr as _sharded
        from dhqr_tpu.parallel.layout import plan_padding
        from dhqr_tpu.parallel.mesh import DEFAULT_AXIS

        from dhqr_tpu.parallel import topology as _topo

        col_axis = cfg.mesh_axis or DEFAULT_AXIS
        # Same planning the engines do internally (arbitrary n is padded and
        # sliced back there) — recomputed here so the factorization object
        # records the panel width the solve stage will reuse. axis_size (not
        # mesh.shape[...]) so a two-tier ("dcn", "ici") pod mesh plans over
        # the full device count (dhqr-pod, round 20).
        nb, _ = plan_padding(
            A.shape[1],
            _topo.axis_size(mesh, _topo.resolve_axis(mesh, col_axis)),
            cfg.block_size)
        if cfg.blocked:
            H, alpha = _sharded.sharded_blocked_qr(
                A, mesh, block_size=nb, axis_name=col_axis,
                precision=cfg.precision, layout=cfg.layout, norm=cfg.norm,
                use_pallas=cfg.use_pallas, panel_impl=cfg.panel_impl,
                trailing_precision=cfg.trailing_precision,
                lookahead=cfg.lookahead, agg_panels=cfg.agg_panels,
                overlap_depth=cfg.overlap_depth, comms=cfg.comms,
            )
        else:
            _reject_nonblocked_knobs(cfg.use_pallas, cfg.trailing_precision,
                                     cfg.lookahead, cfg.agg_panels,
                                     cfg.overlap_depth)
            H, alpha = _sharded.sharded_householder_qr(
                A, mesh, axis_name=col_axis, precision=cfg.precision,
                layout=cfg.layout, norm=cfg.norm, comms=cfg.comms,
            )
        return QRFactorization(
            H, alpha, block_size=nb, mesh=mesh, precision=apply_prec,
            layout=cfg.layout, refine=solve_refine,
            matrix=A if solve_refine else None, comms=cfg.comms,
        )
    if cfg.blocked:
        H, alpha = _blocked.blocked_householder_qr(
            A, cfg.block_size, donate=donate, precision=cfg.precision,
            use_pallas=cfg.use_pallas, norm=cfg.norm,
            panel_impl=cfg.panel_impl,
            trailing_precision=cfg.trailing_precision,
            lookahead=cfg.lookahead, agg_panels=cfg.agg_panels,
        )
    else:
        if donate:
            raise ValueError("donate=True is only supported on the blocked path")
        _reject_nonblocked_knobs(cfg.use_pallas, cfg.trailing_precision,
                                 cfg.lookahead, cfg.agg_panels,
                                 cfg.overlap_depth)
        H, alpha = _hh.householder_qr(A, precision=cfg.precision, norm=cfg.norm)
    return QRFactorization(
        H, alpha, block_size=cfg.block_size, precision=apply_prec,
        refine=solve_refine, matrix=A if solve_refine else None,
    )


def solve(fact: QRFactorization, b: jax.Array) -> jax.Array:
    """Functional form of ``fact.solve(b)`` — the reference's ``\\`` operator."""
    return fact.solve(b)


def qr_explicit(
    A: jax.Array,
    config: Optional[DHQRConfig] = None,
    mesh=None,
    **overrides,
):
    """Explicit reduced factors ``(Q, R)`` — the ``jnp.linalg.qr`` shape.

    Convenience for callers migrating from ``jnp.linalg.qr(A)``: Q is
    (m, n) with orthonormal columns, R (n, n) upper-triangular. The packed
    form (:func:`qr`) is cheaper when you only need solves/applies — the
    reference never forms Q at all (src:215-294). ``mesh=`` factors
    distributed, then materializes the factors (Q formed by the
    single-program blocked apply).
    """
    fact = qr(A, config=config, mesh=mesh, **overrides)
    return fact.q_columns(), fact.r_matrix()


def _reject_nonblocked_knobs(use_pallas: str,
                             trailing_precision: "str | None",
                             lookahead: bool = False,
                             agg_panels: "int | None" = None,
                             overlap_depth: "int | None" = None) -> None:
    """Refuse blocked-only knobs on an unblocked path — one place, so a
    future blocked-only knob (or message tweak) cannot silently drift
    between the qr/lstsq tiers (code-review r4)."""
    if use_pallas != "auto":
        raise ValueError(
            "use_pallas applies to the blocked engines only "
            f"(got use_pallas={use_pallas!r} with blocked=False)"
        )
    if trailing_precision is not None:
        raise ValueError(
            "trailing_precision applies to the blocked engines only "
            f"(got {trailing_precision!r} with blocked=False)"
        )
    if lookahead:
        raise ValueError(
            "lookahead applies to the blocked engines only (the unblocked "
            "panel loop has no panel-level schedule to reorder)"
        )
    if agg_panels:
        raise ValueError(
            "agg_panels applies to the blocked engines only (the unblocked "
            "panel loop has no panel-level updates to aggregate)"
        )
    if overlap_depth:
        raise ValueError(
            "overlap_depth applies to the blocked engines only (the "
            "unblocked panel loop has no panel-level schedule to pipeline)"
        )


def _validate_alt_engine_cfg(cfg: DHQRConfig) -> None:
    """Option rejections shared by every route into the alt engines (the
    plain path AND the refine path — adding refine must never change
    whether a config error is reported)."""
    if cfg.layout != "block":
        raise ValueError(
            f"layout applies only to the householder engines; "
            f"engine={cfg.engine!r} shards rows (layout={cfg.layout!r})"
        )
    if cfg.engine != "tsqr" and cfg.use_pallas != "auto":
        raise ValueError(
            f"use_pallas applies to engines with panel loops (householder, "
            f"tsqr); engine={cfg.engine!r} is all-GEMM "
            f"(use_pallas={cfg.use_pallas!r})"
        )
    if cfg.trailing_precision is not None:
        raise ValueError(
            "trailing_precision applies to the blocked householder engines "
            f"only (engine={cfg.engine!r}; the ops-level entry points "
            "accept a policy= directly — tsqr_lstsq, cholesky_qr_lstsq)"
        )
    if cfg.apply_precision is not None:
        raise ValueError(
            "apply_precision applies to the householder engines only "
            f"(engine={cfg.engine!r})"
        )
    if cfg.lookahead:
        raise ValueError(
            "lookahead applies to the blocked householder engines only "
            f"(engine={cfg.engine!r})"
        )
    if cfg.agg_panels:
        raise ValueError(
            "agg_panels applies to the blocked householder engines only "
            f"(engine={cfg.engine!r})"
        )
    if cfg.overlap_depth:
        raise ValueError(
            "overlap_depth applies to the blocked householder engines "
            f"only (engine={cfg.engine!r})"
        )


def _lstsq_sketch(A, b, cfg: DHQRConfig, mesh):
    """Route ``lstsq`` to the randomized sketched engine
    (``dhqr_tpu.solvers.sketch``, round 17): compress to an s x n core,
    QR the core, recover accuracy with R-preconditioned CGLS against the
    true A. Single-device only — the sketch's point is that the core is
    SMALL; shard upstream and sketch the shards if m outgrows a device.

    Knob mapping: ``precision``/``trailing_precision``/``norm`` steer
    the CORE factorization (it runs the blocked engine); ``block_size``
    its panel width; ``refine`` — when explicitly > 0 (or set via a
    policy) — ADDS CGLS iterations on top of the
    :class:`~dhqr_tpu.utils.config.SketchConfig` baseline (the baseline
    is what holds the 8x gate; extra sweeps buy margin)."""
    from dhqr_tpu.solvers.sketch import sketched_lstsq
    from dhqr_tpu.utils.config import SketchConfig

    if mesh is not None:
        raise ValueError(
            "engine='sketch' is single-device: the sketch core is "
            "already small — shard the stream, not the sketch"
        )
    if cfg.layout != "block":
        raise ValueError(
            f"layout applies only to the householder engines "
            f"(engine='sketch', layout={cfg.layout!r})"
        )
    if cfg.use_pallas != "auto":
        raise ValueError(
            "use_pallas applies to engines with single-problem panel "
            f"loops (got use_pallas={cfg.use_pallas!r} with "
            "engine='sketch'; the sketch core runs the vmapped-scale "
            "XLA path)"
        )
    if cfg.apply_precision is not None:
        raise ValueError(
            "apply_precision applies to the householder engines only "
            "(engine='sketch')"
        )
    if cfg.panel_impl != "loop":
        raise ValueError(
            "panel_impl applies to the blocked householder engines "
            f"(engine='sketch', panel_impl={cfg.panel_impl!r})"
        )
    if cfg.lookahead or cfg.agg_panels or cfg.overlap_depth:
        raise ValueError(
            "lookahead/agg_panels/overlap_depth apply to the blocked "
            "householder engines only (engine='sketch')"
        )
    if not cfg.blocked:
        raise ValueError(
            "engine='sketch' factors its core with the blocked engine "
            "(got blocked=False)"
        )
    if cfg.refine < 0:
        raise ValueError(f"refine must be >= 0, got {cfg.refine}")
    scfg = SketchConfig.from_env()
    return sketched_lstsq(
        A, b, scfg,
        precision=cfg.precision,
        trailing_precision=cfg.trailing_precision,
        norm=cfg.norm,
        refine=scfg.refine + cfg.refine,
        block_size=cfg.block_size,
    )


def _lstsq_refined(A, b, cfg: DHQRConfig, mesh):
    """``refine`` steps of QR-based iterative refinement around one
    factorization: ``x += solve(b - A x)``, residual matvec at full
    precision. Single-device householder rides the differentiable core
    (refinement inside ``lstsq_diff``'s forward, gradients intact); the
    mesh path factors once via ``qr()`` and loops the sharded solve; the
    cholqr family reuses its explicit (Q, R) inside
    :func:`dhqr_tpu.ops.cholqr.cholesky_qr_lstsq`. tsqr is rejected: its
    tree never materializes a reusable factorization, so each step would
    repeat the full factorization cost.
    """
    if cfg.refine < 0:
        raise ValueError(f"refine must be >= 0, got {cfg.refine}")
    if cfg.engine == "tsqr":
        raise ValueError(
            "refine is not supported with engine='tsqr' (no reusable "
            "factorization in the tree); use householder or cholqr"
        )
    if cfg.engine in ("cholqr2", "cholqr3"):
        _validate_alt_engine_cfg(cfg)  # same rejections as the refine=0 path
        if mesh is not None:
            raise ValueError(
                "refine with the cholqr engines is single-device only"
            )
        from dhqr_tpu.ops.cholqr import cholesky_qr_lstsq

        return cholesky_qr_lstsq(
            A, b, precision=cfg.precision, shift=cfg.engine == "cholqr3",
            refine=cfg.refine,
        )
    if mesh is None:
        with _blocked._pallas_cache_guard(_lstsq_interp(A, cfg)):
            return _lstsq_impl(
                A, b, cfg.block_size, cfg.blocked, cfg.precision,
                cfg.use_pallas, norm=cfg.norm, panel_impl=cfg.panel_impl,
                refine=cfg.refine, pallas_flat=_blocked.PALLAS_FLAT_WIDTH,
                trailing_precision=cfg.trailing_precision,
                lookahead=cfg.lookahead, agg_panels=cfg.agg_panels,
                apply_precision=cfg.apply_precision,
            )
    # qr() already records cfg.apply_precision as the factorization's
    # solve precision, so the refinement loop inherits it.
    fact = qr(A, config=dataclasses.replace(cfg, refine=0), mesh=mesh)
    x = fact.solve(b)
    if cfg.comms is not None:
        # Compressed wire: plain residual refinement stalls at its
        # M r* bias (see QRFactorization.solve) — refine through the
        # normal equations with the factorization's R instead.
        return _csne_refine(A, _solve.r_matrix(fact.H, fact.alpha), x, b,
                            cfg.refine)
    for _ in range(cfg.refine):
        r = b - jnp.matmul(A, x, precision="highest")
        x = x + fact.solve(r)
    return x


def _lstsq_alt_engine(A, b, cfg: DHQRConfig, mesh):
    """Route ``lstsq`` to the non-Householder engine families.

    "tsqr": row-parallel communication-avoiding tree (m >> n); on a mesh
    the rows ride the mesh axis (one all-gather). "cholqr2"/"cholqr3":
    all-GEMM Cholesky passes (see ops/cholqr.py for the conditioning
    windows); on a mesh, one n x n psum per pass. These engines return x
    only — ``qr()`` stays Householder-packed by design.

    Both families shard ROWS over the mesh axis — an explicitly-passed
    ``mesh_axis``, else the sole axis of a 1-D mesh, else an axis named
    "rows" — unlike the Householder mesh path, which shards columns.
    """
    _validate_alt_engine_cfg(cfg)
    axis = None
    if mesh is not None:
        from dhqr_tpu.parallel.sharded_tsqr import ROW_AXIS

        if cfg.mesh_axis is not None:  # explicit user choice
            if cfg.mesh_axis not in mesh.shape:
                raise ValueError(
                    f"mesh axes {tuple(mesh.shape)} do not include "
                    f"mesh_axis={cfg.mesh_axis!r}"
                )
            axis = cfg.mesh_axis
        elif len(mesh.shape) == 1:
            axis = next(iter(mesh.shape))
        elif ROW_AXIS in mesh.shape:
            axis = ROW_AXIS
        elif tuple(mesh.axis_names) == ("dcn", "ici"):
            # A two-tier pod mesh is unambiguous: the engines resolve the
            # default row axis to both tiers jointly (parallel/topology
            # .resolve_axis), running the hierarchical schedule.
            axis = ROW_AXIS
        else:
            # Never guess among multiple axes — sharding rows over a
            # column-sharding name while silently replicating over the
            # rest would waste the pod.
            raise ValueError(
                f"ambiguous row axis on mesh axes {tuple(mesh.shape)} for "
                f"engine={cfg.engine!r}: pass mesh_axis= to pick one"
            )
    if cfg.engine == "tsqr":
        from dhqr_tpu.ops.tsqr import tsqr_lstsq

        if mesh is not None:
            from dhqr_tpu.parallel.sharded_tsqr import sharded_tsqr_lstsq

            return sharded_tsqr_lstsq(
                A, b, mesh, block_size=cfg.block_size,
                axis_name=axis, precision=cfg.precision,
                use_pallas=cfg.use_pallas, comms=cfg.comms,
            )
        n_blocks = max(1, min(8, A.shape[0] // max(A.shape[1], 1)))
        while n_blocks > 1 and A.shape[0] % n_blocks:
            n_blocks -= 1
        return tsqr_lstsq(
            A, b, n_blocks=n_blocks, block_size=cfg.block_size,
            precision=cfg.precision, use_pallas=cfg.use_pallas,
        )
    if cfg.engine in ("cholqr2", "cholqr3"):
        shift = cfg.engine == "cholqr3"
        if mesh is not None:
            from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq

            return sharded_cholqr_lstsq(
                A, b, mesh, axis_name=axis,
                precision=cfg.precision, shift=shift, comms=cfg.comms,
            )
        from dhqr_tpu.ops.cholqr import cholesky_qr_lstsq

        return cholesky_qr_lstsq(A, b, precision=cfg.precision, shift=shift)
    raise ValueError(
        f"unknown engine {cfg.engine!r}: expected one of {LSTSQ_ENGINES}"
    )


def _lstsq_interp(A, cfg) -> bool:
    """Will ``_lstsq_impl`` trace an interpret-mode Pallas kernel? Same
    resolution the impl performs inside its jit, evaluated pre-call so the
    compile can be kept out of the persistent cache (see
    ``ops.blocked._pallas_cache_guard``)."""
    if not cfg.blocked:
        return False
    return _blocked._resolve_pallas(
        cfg.use_pallas, A.shape[0], min(cfg.block_size, A.shape[1]), A.dtype
    )[1]


@partial(jax.jit, static_argnames=(
    "block_size", "blocked", "precision", "use_pallas", "norm", "panel_impl",
    "refine", "pallas_flat", "trailing_precision", "lookahead", "agg_panels",
    "apply_precision"))
def _lstsq_impl(A, b, block_size, blocked, precision, use_pallas,
                norm="accurate", panel_impl="loop", refine=0,
                pallas_flat=None, trailing_precision=None, lookahead=False,
                agg_panels=None, apply_precision=None):
    if blocked:
        from dhqr_tpu.ops.differentiable import lstsq_diff

        pallas, interp = _blocked._resolve_pallas(
            use_pallas, A.shape[0], min(block_size, A.shape[1]), A.dtype
        )
        # custom-JVP core: identical forward (incl. refinement sweeps),
        # closed-form O(1)-memory gradients — jax.grad works through the
        # public lstsq at every refine level
        return lstsq_diff(A, b, block_size, precision, pallas, interp, norm,
                          panel_impl, refine, pallas_flat, trailing_precision,
                          lookahead, agg_panels, apply_precision)
    _reject_nonblocked_knobs(use_pallas, trailing_precision, lookahead,
                             agg_panels)
    H, alpha = _hh.householder_qr(A, precision=precision, norm=norm)
    ap = precision if apply_precision is None else apply_precision

    def qr_solve(rhs):
        return _solve.back_substitute(
            H, alpha, _solve.apply_qt(H, alpha, rhs, precision=ap)
        )

    x = qr_solve(b)
    for _ in range(refine):
        r = b - jnp.matmul(A, x, precision="highest")
        x = x + qr_solve(r)
    return x


@partial(jax.jit, static_argnames=("block_size", "precision", "norm"))
def _minimum_norm_impl(A, b, block_size, precision, norm="accurate"):
    """Underdetermined (m < n, full row rank): the minimum-norm solution.

    Factor A^H = Q R (tall, the engines' home turf); then A = R^H Q^H and
    ``x = Q R^{-H} b`` solves A x = b exactly with the smallest ||x||.
    Beyond the reference (which is tall-only, src:33) but expected of a
    least-squares surface; the blocked engine + compact-WY Q-apply keep it
    on the MXU.
    """
    m, n = A.shape  # m < n
    H, alpha = _blocked._blocked_qr_impl(
        jnp.conj(A.T), block_size, precision=precision, norm=norm
    )
    R = _solve.r_matrix(H, alpha)  # (m, m) upper; A = R^H Q^H
    vec = b.ndim == 1
    B = b[:, None] if vec else b
    Y = jax.lax.linalg.triangular_solve(
        R, B, left_side=True, lower=False, transpose_a=True, conjugate_a=True
    )  # R^H Y = b
    Yp = jnp.zeros((n,) + Y.shape[1:], dtype=Y.dtype).at[:m].set(Y)
    X = _blocked._apply_q_impl(H, Yp, block_size, precision=precision)
    return X[:, 0] if vec else X


_EMBEDDING_WARNED = []


def _use_real_embedding(dtype) -> bool:
    """True when lstsq should route complex64 through the real embedding:
    the backend has no complex support, but the equivalent real system
    runs at the same component precision (f32). complex128 still raises
    (f64 on such backends is emulated — silently delivering a much slower
    path would not be a faithful answer)."""
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating):
        return False
    if jnp.dtype(dtype) != jnp.complex64:
        return False
    from dhqr_tpu.utils.platform import complex_supported_on_backend

    return not complex_supported_on_backend()


def _lstsq_via_real_embedding(A, b, cfg: DHQRConfig, mesh):
    """Complex least squares on a complexless backend, exactly.

    For complex ``A x = b`` the residual satisfies
    ``[re(r); im(r)] = [[Ar, -Ai], [Ai, Ar]] [xr; xi] - [br; bi]``,
    so ``argmin ||A x - b||`` over C^n equals the REAL least-squares
    solution of the (2m, 2n) embedded system — singular values are those
    of A, each doubled, so conditioning is unchanged, and the minimum-norm
    property carries over for m < n (||[xr; xi]|| = ||x||). This gives the
    reference's ComplexF64 capability (c64 here — same component
    precision as the f32 path) a route onto TPU backends whose compiler
    has no complex support at MXU shapes (the axon relay,
    benchmarks/results/tpu_r3_disambig.jsonl) — including the fused
    Pallas panel kernel, which sees only f32. Cost: the embedded QR does
    2x the real flops of a native complex QR (16 vs 8 mn^2).

    Differentiation caveat: the concrete-input path round-trips through
    the host (deliberately — see below), so it is not differentiable;
    ``jax.grad`` through a complex lstsq requires a complex-capable
    backend (where the native differentiable core runs instead).
    """
    import warnings

    if not _EMBEDDING_WARNED:
        _EMBEDDING_WARNED.append(True)
        warnings.warn(
            "complex64 lstsq: this backend has no complex support — "
            "solving the equivalent real embedded system (same f32 "
            "component precision, ~2x flops). Silence this warning by "
            "embedding explicitly, or run on a complex-capable backend.",
            stacklevel=3,
        )
    m, n = A.shape
    traced = isinstance(A, jax.core.Tracer) or isinstance(b, jax.core.Tracer)
    if traced:
        # Traced values: stay on-device (a jit caller on a complexless
        # backend was already unsupported; nothing safer exists here).
        Ar, Ai = jnp.real(A), jnp.imag(A)
        br, bi = jnp.real(b), jnp.imag(b)
    else:
        # Concrete arrays: extract components on the HOST. On the very
        # backends this path exists for, even elementwise complex ops can
        # fail UNIMPLEMENTED — and a FAILED complex op poisons the relay's
        # compile helper (tpu_r3_disambig.jsonl), so the embedding must
        # never issue device complex compute. Transfers are fine.
        import numpy as _np

        Ah, bh = _np.asarray(A), _np.asarray(b)
        Ar, Ai = jnp.asarray(Ah.real.copy()), jnp.asarray(Ah.imag.copy())
        br, bi = jnp.asarray(bh.real.copy()), jnp.asarray(bh.imag.copy())
    E = jnp.concatenate(
        [jnp.concatenate([Ar, -Ai], axis=1),
         jnp.concatenate([Ai, Ar], axis=1)], axis=0
    )  # (2m, 2n) float32
    be = jnp.concatenate([br, bi], axis=0)  # (2m, ...)
    xe = lstsq(E, be, config=cfg, mesh=mesh)
    if traced:
        return xe[:n] + 1j * xe[n:]
    # Concrete path: recombine on the HOST too — `xr + 1j*xi` on device-
    # resident planes would issue the very complex64 device ops this route
    # exists to avoid (and whose failure poisons the relay helper).
    import numpy as _np

    xh = _np.asarray(xe)
    return jnp.asarray((xh[:n] + 1j * xh[n:]).astype(_np.complex64))


def lstsq(
    A: jax.Array,
    b: jax.Array,
    config: Optional[DHQRConfig] = None,
    mesh=None,
    **overrides,
) -> jax.Array:
    """One-shot least squares ``x = qr(A) \\ b`` as a single jitted program.

    With ``mesh=`` the whole pipeline runs distributed (the reference's
    ``DHQR.qr!(A3) \\ b`` DArray path, runtests.jl:77-78). For m < n the
    result is the minimum-norm solution of the underdetermined system
    (single-device householder engine only).

    ``policy=`` names the whole precision tuple at once (see
    :class:`dhqr_tpu.precision.PrecisionPolicy`): panel/trailing go to
    the factor stage, ``apply`` to the Q^H-apply of the solve stage, and
    ``refine`` into the iterative-refinement loop — the pairing that
    lets a cheap trailing precision keep the full-precision backward
    error.
    """
    from dhqr_tpu.utils.platform import ensure_complex_supported

    cfg = dataclasses.replace(config or DHQRConfig(), **overrides)
    if cfg.guards is not None:
        # Numeric guardrails (round 13): screen -> run -> health check
        # -> condition-aware fallback ladder -> typed refusal. The
        # provenance surface (taken path, condition estimate) is
        # dhqr_tpu.numeric.guarded_lstsq; this facade returns x only.
        from dhqr_tpu.numeric.ladder import guarded_lstsq

        return guarded_lstsq(A, b, config=cfg, mesh=mesh).x
    cfg, pol = _resolve_policy_cfg(cfg)
    if pol is not None and pol.refine:
        cfg = dataclasses.replace(cfg, refine=pol.refine)
    cfg = _resolve_plan_cfg(cfg, "lstsq", A.shape, A.dtype, mesh, pol)
    if cfg.norm not in ("accurate", "fast"):
        raise ValueError(
            f"norm must be 'accurate' or 'fast', got {cfg.norm!r}"
        )
    _check_panel_impl(cfg)
    _check_sched_knobs(cfg, mesh)
    if cfg.engine not in LSTSQ_ENGINES:
        raise ValueError(
            f"unknown engine {cfg.engine!r}: expected one of {LSTSQ_ENGINES}"
        )
    if _use_real_embedding(A.dtype):
        # complex64 on a backend with no complex support (the axon relay):
        # solve the exactly-equivalent real system instead of raising —
        # same component precision (f32), runs on the MXU path.
        return _lstsq_via_real_embedding(A, b, cfg, mesh)
    ensure_complex_supported(A.dtype)
    if cfg.engine == "sketch":
        # Routed BEFORE the block_size default resolution: block_size
        # stays None here so the sketch engine applies its own
        # core-sized default (SKETCH_DEFAULT_BLOCK — the s x n core is
        # serve-bucket sized, where narrow panels measured fastest).
        if A.shape[0] < A.shape[1]:
            raise ValueError(
                f"m < n (got {A.shape}) is supported only on the "
                "single-device householder path (minimum-norm solve)"
            )
        return _lstsq_sketch(A, b, cfg, mesh)
    if cfg.block_size is None:
        # Same resolution rule as qr(): auto width only where the Pallas
        # kernel can actually take the panels — the single-device blocked
        # householder path with m >= n (the m < n minimum-norm path factors
        # A^H with the kernel unset, so it keeps the 128 default, as do the
        # mesh and alt-engine tiers).
        if (mesh is None and cfg.engine == "householder" and cfg.blocked
                and A.shape[0] >= A.shape[1]):
            bs = _blocked.auto_block_size(A.shape[0], A.dtype, cfg.use_pallas)
        else:
            bs = _blocked.DEFAULT_BLOCK_SIZE
        cfg = dataclasses.replace(cfg, block_size=bs)
    if A.shape[0] < A.shape[1]:
        if mesh is not None or cfg.engine != "householder":
            raise ValueError(
                f"m < n (got {A.shape}) is supported only on the "
                "single-device householder path (minimum-norm solve)"
            )
        if not cfg.blocked or cfg.use_pallas != "auto" \
                or cfg.trailing_precision is not None or cfg.lookahead \
                or cfg.agg_panels or cfg.overlap_depth \
                or cfg.apply_precision is not None:
            raise ValueError(
                "m < n supports only the default blocked XLA path "
                f"(got blocked={cfg.blocked}, use_pallas={cfg.use_pallas!r}, "
                f"trailing_precision={cfg.trailing_precision!r}, "
                f"lookahead={cfg.lookahead}, agg_panels={cfg.agg_panels}, "
                f"overlap_depth={cfg.overlap_depth}, "
                f"apply_precision={cfg.apply_precision!r})"
            )
        if cfg.refine:
            raise ValueError(
                "refine is not supported for m < n (the minimum-norm "
                "solve is already exact to working precision)"
            )
        return _minimum_norm_impl(
            A, b, cfg.block_size, cfg.precision, norm=cfg.norm
        )
    if (cfg.comms is not None and mesh is not None
            and cfg.engine == "householder"):
        # dhqr-wire (round 18): a compressed-wire mesh solve includes
        # CSNE recovery BY CONTRACT — the same in-body sweeps the
        # compressed row engines run (parallel/wire.CSNE_SWEEPS), so
        # lstsq holds the 8x normal-equations bar at every rung and a
        # tuned comms plan is admissible under the accuracy gate. A
        # caller's refine only ever adds margin on top of the floor
        # (per-mode: int8's coarser step needs more contractions).
        from dhqr_tpu.parallel.wire import CSNE_MODEL_SWEEPS

        floor = CSNE_MODEL_SWEEPS.get(cfg.comms, 2)
        if cfg.refine < floor:
            cfg = dataclasses.replace(cfg, refine=floor)
    if cfg.refine:
        return _lstsq_refined(A, b, cfg, mesh)
    if cfg.engine != "householder":
        return _lstsq_alt_engine(A, b, cfg, mesh)
    if mesh is not None:
        from dhqr_tpu.parallel.layout import plan_padding
        from dhqr_tpu.parallel.mesh import DEFAULT_AXIS
        from dhqr_tpu.parallel.sharded_qr import (
            _pad_cols_orthogonal,
            sharded_householder_qr,
        )
        from dhqr_tpu.parallel.sharded_solve import sharded_lstsq, sharded_solve

        col_axis = cfg.mesh_axis or DEFAULT_AXIS
        if not cfg.blocked:
            _reject_nonblocked_knobs(cfg.use_pallas, cfg.trailing_precision,
                                     cfg.lookahead, cfg.agg_panels,
                                     cfg.overlap_depth)
            from dhqr_tpu.parallel import topology as _topo

            m, n = A.shape
            nb, n_pad = plan_padding(
                n,
                _topo.axis_size(mesh, _topo.resolve_axis(mesh, col_axis)),
                cfg.block_size)
            if n_pad != n:
                # Pad once so the factor->solve store-layout chaining holds
                # (see sharded_lstsq for the blocked twin of this dance).
                A = _pad_cols_orthogonal(A, n_pad)
                b = jnp.pad(b, [(0, n_pad - n)] + [(0, 0)] * (b.ndim - 1))
            # store_nb=nb + store-layout chaining: factor and solve share one
            # storage order, avoiding cross-device column permutes in between.
            H, alpha = sharded_householder_qr(
                A, mesh, axis_name=col_axis, precision=cfg.precision,
                layout=cfg.layout, store_nb=nb, _store_layout_output=True,
                norm=cfg.norm, comms=cfg.comms,
            )
            x = sharded_solve(
                H, alpha, b, mesh,
                block_size=nb, axis_name=col_axis,
                precision=cfg.apply_precision or cfg.precision,
                layout=cfg.layout, _H_in_store_layout=True, comms=cfg.comms,
            )
            return x[:n]
        return sharded_lstsq(
            A, b, mesh,
            block_size=cfg.block_size, axis_name=col_axis,
            precision=cfg.precision, layout=cfg.layout, norm=cfg.norm,
            use_pallas=cfg.use_pallas, panel_impl=cfg.panel_impl,
            trailing_precision=cfg.trailing_precision,
            lookahead=cfg.lookahead, agg_panels=cfg.agg_panels,
            overlap_depth=cfg.overlap_depth,
            apply_precision=cfg.apply_precision, comms=cfg.comms,
        )
    with _blocked._pallas_cache_guard(_lstsq_interp(A, cfg)):
        return _lstsq_impl(
            A, b, cfg.block_size, cfg.blocked, cfg.precision, cfg.use_pallas,
            norm=cfg.norm, panel_impl=cfg.panel_impl,
            pallas_flat=_blocked.PALLAS_FLAT_WIDTH,
            trailing_precision=cfg.trailing_precision,
            lookahead=cfg.lookahead, agg_panels=cfg.agg_panels,
            apply_precision=cfg.apply_precision,
        )
