"""User-facing factorization objects and solve API (layer L4 of SURVEY.md §1)."""
