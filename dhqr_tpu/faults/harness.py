"""Deterministic, seedable fault injection for the serving stack.

Production failure behavior must be DESIGNED and TESTED, not discovered
(the TPU linear-algebra paper's multi-chip jobs lose whole sessions to
one wedged device init — the exact relay failure mode recorded in
BENCH_r04/r05). This module is how the repo injects those failures on
demand: a small registry of named injection points threaded through the
serving tier, armed by a :class:`~dhqr_tpu.utils.config.FaultConfig`
(``DHQR_FAULTS`` in the environment, or :func:`install` / the
:func:`injected` context manager programmatically).

Design constraints, in order:

* **Zero overhead when disabled.** Every injection point compiles down
  to one module-global read and a ``None`` check
  (:func:`fire` / :func:`latency`); no config parse, no RNG draw, no
  lock. ``DHQR_FAULTS`` unset means the serving tier runs the PR-6
  code byte-for-byte.
* **Deterministic.** Each site draws from its own ``random.Random``
  stream seeded by (config seed, site name), so the schedule at one
  site never depends on how often other sites were visited, and the
  same seed replays the same schedule for the same visit sequence.
  Cross-thread visit ORDER at a single site is the one residual
  nondeterminism — configs that need exactness (tests, the dry run)
  use ``prob=1.0`` with a ``max_triggers`` count, which is
  interleaving-independent.
* **Accounted.** Triggers land on a shared
  :class:`~dhqr_tpu.utils.profiling.Counters` (``fired_<site>`` /
  ``visits_<site>``), snapshot via :meth:`FaultHarness.stats` — the
  chaos benchmark's "injected fault rate" is read from the harness
  itself, not re-derived.

Sites (the registry is closed on purpose — an unknown site name in a
config is a spelled-wrong experiment, and it fails at install time):

====================  ======  ==============================================
site                  action  where it is threaded
====================  ======  ==============================================
``serve.compile``     raise   ``serve.cache.ExecutableCache.get_or_compile``,
                              inside the compile block — surfaces as
                              :class:`~dhqr_tpu.serve.errors.CompileFailed`
                              and quarantines the key like a real one
``serve.dispatch``    raise   ``serve.engine._dispatch_groups``, at the
                              compiled-program call — surfaces as
                              :class:`~dhqr_tpu.serve.errors.DispatchFailed`
``serve.worker``      raise   ``serve.scheduler.AsyncScheduler._run``, top
                              of the dispatcher-worker loop — kills the
                              worker thread; crash detection respawns it
``serve.store``       raise   ``serve.store.ExecutableStore.load``, inside
                              the read/deserialize block — models a corrupt
                              or version-skewed persisted executable; the
                              store CATCHES it and degrades to a counted
                              plain recompile (``deserialize_failures``),
                              so firing this site must never surface as an
                              error on a dispatch path (round 22)
``serve.latency``     sleep   ``serve.engine._dispatch_groups``, before the
                              dispatch — models a slow device/host without
                              failing anything
``numeric.nan``       raise   ``numeric.ladder._screen``, at the input
                              screen — treated exactly as a detected
                              non-finite input, surfaces as
                              :class:`~dhqr_tpu.numeric.NonFiniteInput`
``numeric.breakdown`` raise   ``numeric.ladder`` guarded entry points, per
                              ladder rung — treated exactly as that rung's
                              factors coming back non-finite, so the
                              fallback ladder escalates deterministically
``parallel.collective.corrupt``
                      wire    ``parallel/wire.py`` — consulted at TRACE
                              time, once per traced collective; a trigger
                              bakes a large additive corruption into the
                              payload crossing that collective (round 19;
                              the ``:k`` segment picks WHICH collective)
``parallel.collective.nan``   wire — as above, poisoning one payload
                              element NaN (a bit-flip landing in the
                              exponent field)
``parallel.collective.drop``  wire — as above, zeroing the payload (a
                              dropped shard contribution: the psum/gather
                              completes, the owner's words never arrive)
====================  ======  ==============================================
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
import zlib
from typing import Iterator, Optional

from dhqr_tpu.utils import lockwitness as _lockwitness
from dhqr_tpu.utils.config import FaultConfig
from dhqr_tpu.utils.profiling import Counters

# site name -> action kind. "raise" sites throw FaultInjected when they
# trigger; "sleep" sites block for FaultConfig.latency_ms; "wire" sites
# (round 19) are payload mutators consulted by the dhqr-wire seam at
# TRACE time (parallel/wire.py) — a trigger bakes the corruption into
# the traced collective, so one "visit" is one traced collective, not
# one dispatch (the armor seam busts the engine build caches per fault
# epoch so schedules re-draw per re-trace).
SITES = {
    "serve.compile": "raise",
    "serve.dispatch": "raise",
    "serve.worker": "raise",
    "serve.store": "raise",
    "serve.latency": "sleep",
    "numeric.nan": "raise",
    "numeric.breakdown": "raise",
    "parallel.collective.corrupt": "wire",
    "parallel.collective.nan": "wire",
    "parallel.collective.drop": "wire",
}


class FaultInjected(RuntimeError):
    """The exception a triggered ``raise``-kind site throws. Carries the
    site name so downstream classification (and tests) can tell an
    injected failure from an organic one."""

    def __init__(self, site: str) -> None:
        super().__init__(f"injected fault at {site!r}")
        self.site = site


class _SiteState:
    __slots__ = ("prob", "remaining", "rng", "from_visit", "visits")

    def __init__(self, prob: float, max_triggers: "int | None",
                 rng: random.Random,
                 from_visit: "int | None" = None) -> None:
        self.prob = prob
        self.remaining = max_triggers  # None = unbounded
        self.rng = rng
        # Fire-on-kth-visit schedules (round 19, the :k config segment):
        # the first from_visit - 1 visits never trigger; prob/count
        # apply from visit from_visit onward. None = from the first.
        self.from_visit = from_visit
        self.visits = 0


class FaultHarness:
    """One armed fault schedule. Normally managed through the module
    globals (:func:`install` / :func:`injected`); constructed directly
    only by tests that probe determinism.

    ``sleeper`` is injectable so latency-site tests don't wall-clock
    sleep.
    """

    def __init__(self, config: FaultConfig,
                 sleeper=time.sleep) -> None:
        self.config = config
        self.counters = Counters()
        self._sleep = sleeper
        self._lock = _lockwitness.make_lock("FaultHarness._lock")
        # Dict SHAPE is frozen after __init__ (sites never appear or
        # vanish); the per-site _SiteState fields mutate under _lock.
        self._sites: "dict[str, _SiteState]" = {}  # guarded by: frozen
        for entry in config.sites:
            site, prob, count = entry[0], entry[1], entry[2]
            from_visit = entry[3] if len(entry) == 4 else None
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r}; registered sites: "
                    f"{', '.join(sorted(SITES))}")
            # One independent stream per site, derived stably from
            # (seed, site): crc32 rather than hash() so the schedule
            # survives PYTHONHASHSEED randomization.
            rng = random.Random(
                (config.seed << 32) ^ zlib.crc32(site.encode("utf-8")))
            self._sites[site] = _SiteState(prob, count, rng, from_visit)

    def should_fire(self, site: str) -> bool:
        """Draw the site's next decision (and account the visit)."""
        state = self._sites.get(site)
        if state is None:
            return False
        with self._lock:
            self.counters.bump(f"visits_{site}")
            state.visits += 1
            if state.from_visit is not None \
                    and state.visits < state.from_visit:
                return False    # the :k segment: silent before visit k
            if state.remaining is not None and state.remaining <= 0:
                return False
            if state.prob < 1.0 and state.rng.random() >= state.prob:
                return False
            if state.remaining is not None:
                state.remaining -= 1
            self.counters.bump(f"fired_{site}")
            return True

    def fire(self, site: str) -> None:
        """Raise :class:`FaultInjected` if the site triggers this visit."""
        if SITES.get(site) != "raise":
            raise ValueError(f"{site!r} is not a raise-kind fault site")
        if self.should_fire(site):
            raise FaultInjected(site)

    def latency(self, site: str) -> None:
        """Sleep ``latency_ms`` if the site triggers this visit."""
        if SITES.get(site) != "sleep":
            raise ValueError(f"{site!r} is not a sleep-kind fault site")
        if self.should_fire(site) and self.config.latency_ms > 0:
            self._sleep(self.config.latency_ms / 1e3)

    def stats(self) -> dict:
        """JSON-ready visit/trigger counts per configured site."""
        snap = self.counters.snapshot()
        return {
            site: {
                "visits": int(snap.get(f"visits_{site}", 0)),
                "fired": int(snap.get(f"fired_{site}", 0)),
            }
            for site in self._sites
        }


# The one armed harness (or None — the fast path). Assignment is atomic
# under the GIL; injection points read it exactly once per visit.
_ACTIVE: "FaultHarness | None" = None
_INSTALL_LOCK = _lockwitness.make_lock("harness._INSTALL_LOCK")
# Monotone arm/disarm generation (round 19). The "wire"-kind sites fire
# at TRACE time inside lru-cached engine builds (parallel/wire.py), so
# re-arming a schedule must re-key those caches or a stale baked fault
# would replay forever; dhqr_tpu.armor folds this into its seam token.
_EPOCH = 0


def epoch() -> int:
    """The harness arm/disarm generation — bumped by every
    :func:`install` / :func:`uninstall` (and :func:`injected` scope
    exit), never reset. Cache-key material for trace-time seams."""
    return _EPOCH


def wire_sites_armed() -> bool:
    """Whether the armed harness (if any) configures a trace-time
    ``parallel.collective.*`` site — the wire seam's one-read guard."""
    harness = _ACTIVE
    return harness is not None and any(
        site.startswith("parallel.collective.")
        for site in harness._sites)


def install(config: "FaultConfig | None" = None,
            sleeper=time.sleep) -> FaultHarness:
    """Arm the process-wide harness from ``config`` (default: the
    environment's ``DHQR_FAULTS*``). Replaces any previously armed
    harness. Returns the harness so callers can read its stats."""
    global _ACTIVE, _EPOCH
    cfg = config if config is not None else FaultConfig.from_env()
    harness = FaultHarness(cfg, sleeper=sleeper)
    with _INSTALL_LOCK:
        _ACTIVE = harness if cfg.enabled else None
        _EPOCH += 1
    return harness


def uninstall() -> None:
    """Disarm: every injection point reverts to the zero-overhead path."""
    global _ACTIVE, _EPOCH
    with _INSTALL_LOCK:
        _ACTIVE = None
        _EPOCH += 1


# Suspension depth (round 19): while the CALLING thread's depth > 0,
# active() reads None so no injection point fires OR accounts a visit.
# The pulse census retrace (obs/pulse.measure's abstract() ->
# jax.make_jaxpr) re-traces shard bodies whose wire seams would
# otherwise consume trace-time schedule visits against a DISCARDED
# jaxpr — breaking the "one visit = one traced collective of a real
# program" replay contract. THREAD-local, not process-global: another
# thread concurrently tracing a REAL armed program (an AsyncScheduler
# worker) must keep its schedule firing and its visit indices intact.
_SUSPEND = threading.local()


@contextlib.contextmanager
def suspended() -> Iterator[None]:
    """Scope during which every injection point on THIS thread is inert
    and unvisited (nests; other threads' schedules are untouched)."""
    _SUSPEND.depth = getattr(_SUSPEND, "depth", 0) + 1
    try:
        yield
    finally:
        _SUSPEND.depth -= 1


def active() -> Optional[FaultHarness]:
    """The currently armed harness, or None (also None inside the
    calling thread's :func:`suspended` scope)."""
    if getattr(_SUSPEND, "depth", 0):
        return None
    return _ACTIVE


@contextlib.contextmanager
def injected(config: FaultConfig, sleeper=time.sleep) -> Iterator[FaultHarness]:
    """Scope a fault schedule: arm on entry, disarm on exit (restoring
    whatever was armed before — scopes nest)."""
    global _ACTIVE, _EPOCH
    with _INSTALL_LOCK:
        previous = _ACTIVE
    harness = install(config, sleeper=sleeper)
    try:
        yield harness
    finally:
        with _INSTALL_LOCK:
            _ACTIVE = previous
            _EPOCH += 1


def fire(site: str) -> None:
    """Injection point for ``raise``-kind sites: no-op unless a harness
    is armed AND the site triggers, in which case :class:`FaultInjected`
    propagates. THE hot-path entry — one :func:`active` read when
    disarmed (which honors :func:`suspended`: a suspended scope must
    silence raise/sleep sites too, not just the wire kind)."""
    harness = active()
    if harness is not None:
        harness.fire(site)


def latency(site: str = "serve.latency") -> None:
    """Injection point for ``sleep``-kind sites: no-op unless armed and
    triggered (inert inside a :func:`suspended` scope), in which case
    the configured latency is slept."""
    harness = active()
    if harness is not None:
        harness.latency(site)
