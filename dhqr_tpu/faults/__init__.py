"""Deterministic fault injection (``dhqr_tpu.faults``) — round 12.

Arm a seeded fault schedule against the serving stack's named injection
points and prove the failure behavior is designed, not discovered:

    >>> from dhqr_tpu.faults import injected
    >>> from dhqr_tpu.utils.config import FaultConfig
    >>> cfg = FaultConfig(sites=(("serve.dispatch", 1.0, 1),), seed=0)
    >>> with injected(cfg) as harness:
    ...     xs = batched_lstsq(As, bs)      # first dispatch fails, typed
    >>> harness.stats()["serve.dispatch"]["fired"]
    1

Environment arming: ``DHQR_FAULTS="serve.dispatch:0.05,serve.latency:0.2"``
(+ ``DHQR_FAULTS_SEED`` / ``DHQR_FAULTS_LATENCY_MS``) then
``faults.install()``. With nothing configured every injection point is a
single module-global ``None`` check — see ``faults/harness.py`` for the
site registry and guarantees, docs/DESIGN.md "Fault model" for the
taxonomy the serving tier resolves injected failures into.

Round 13 adds the NUMERIC sites — ``numeric.nan`` (fires at the
guarded entry points' input screen, as if the scan found a NaN) and
``numeric.breakdown`` (fires per fallback-ladder rung, as if that
rung's factors came back non-finite) — so every escalation path of
``dhqr_tpu.numeric`` is deterministically replayable without crafting
an ill-conditioned matrix for it.

Round 19 adds the COLLECTIVE sites — ``parallel.collective.corrupt``
/ ``.nan`` / ``.drop``, "wire"-kind entries consulted at TRACE time
inside the dhqr-wire seam (one visit per traced collective) — and the
optional ``:k`` schedule segment (``site:prob[:count[:k]]``: silent
for the first k-1 visits), so "corrupt exactly the 3rd panel
broadcast" is a replayable experiment the armor chaos grid sweeps
(``dhqr_tpu.armor``, benchmarks/serving_armor.py).
"""

from dhqr_tpu.faults.harness import (
    SITES,
    FaultHarness,
    FaultInjected,
    active,
    epoch,
    fire,
    injected,
    install,
    latency,
    suspended,
    uninstall,
    wire_sites_armed,
)

__all__ = [
    "SITES",
    "FaultHarness",
    "FaultInjected",
    "active",
    "epoch",
    "fire",
    "injected",
    "install",
    "latency",
    "suspended",
    "uninstall",
    "wire_sites_armed",
]
