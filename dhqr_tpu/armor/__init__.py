"""dhqr-armor — ABFT detection and typed self-healing for the sharded
tier (round 19).

The sharded engines (``dhqr_tpu/parallel``) assume every collective is
perfect: a bit-flipped panel broadcast or a dropped shard contribution
produces a *plausible, finite, wrong* factor — silent garbage at exactly
the tier ROADMAP items 1-3 send to real hardware, where preemption and
silent data corruption are operational facts (arXiv 2112.09017 scale),
and PR-13's compressed wire widens the surface (quantized payloads and
scale sidecars are the bytes a flaky link corrupts undetectably). This
package closes the loop, end to end:

* **Detection** — checksum-augmented verification
  (:mod:`dhqr_tpu.armor.checks`): every armored sharded dispatch is
  followed by an O(mn) weighted-checksum invariant over the factors it
  already produced (``u^H A`` vs ``(Q^H u)^H R``; normal-equations
  identity for solves) — no re-factorization — plus per-payload
  integrity tags on COMPRESSED collectives at the ``parallel/wire.py``
  seam (a mismatch at decompression poisons the payload NaN-loud, so
  the post-hoc check cannot miss it).
* **Injection** — deterministic ``parallel.collective.{corrupt,nan,
  drop}`` fault sites fire inside the wire seam per seeded per-site
  streams (``dhqr_tpu.faults``; the ``:k`` schedule segment picks
  *which* traced collective), so every detection and recovery path
  replays on CPU topologies.
* **Recovery** — a typed ladder: verify -> single re-dispatch ->
  degrade ``comms`` to the f32 passthrough for the offending label ->
  typed :class:`CorruptionDetected` / :class:`ShardFailure` (NumericalError
  siblings carrying engine, collective label, shard index, trace id),
  which the PR-8 guarded ladder escalates past and the async scheduler
  routes (ShardFailure -> retry/bisect like infrastructure;
  CorruptionDetected -> bisect isolation). Repeated verification trips
  on a compressed dispatch demote the key's compressed plans out of
  ``tune``'s ``plan="auto"`` resolution.

The PR-7 arming discipline throughout: ``DHQR_ARMOR*`` env vars
CONFIGURE (:class:`~dhqr_tpu.utils.config.ArmorConfig`), only
:func:`arm` / the :func:`armored` scope ARMS; disarmed, every sharded
dispatch pays one module-global ``None`` check and compiles the
pre-round-19 programs byte-for-byte, and warm armed loops are
zero-recompile (every check a shape-cached jitted reduction).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Iterator, Optional

from dhqr_tpu.armor.errors import (
    ArmorError,
    CorruptionDetected,
    ShardFailure,
)
from dhqr_tpu.armor import checks
from dhqr_tpu.faults import harness as _faults
from dhqr_tpu.obs import trace as _obs
from dhqr_tpu.utils import lockwitness as _lockwitness
from dhqr_tpu.utils.config import ArmorConfig
from dhqr_tpu.utils.profiling import Counters

__all__ = [
    "ArmorConfig",
    "ArmorError",
    "ArmorState",
    "CorruptionDetected",
    "ShardFailure",
    "active",
    "arm",
    "armored",
    "checked_dispatch",
    "checks",
    "degraded_labels",
    "disarm",
    "effective_comms",
    "reset_wire_trips",
    "seam_token",
    "wire_demoted",
    "wire_tags_armed",
    "wire_trips",
]


#: Checksum tolerance for COMPRESSED dispatches: wire rounding puts an
#: honest compressed invariant at ~1e-3..1e-2 (measured on the
#: committed grid — blocked-qr factor gaps are the worst at ~9e-3),
#: while corruption lands at O(1)+. One decade of headroom each way.
WIRE_RTOL = 0.1


class ArmorState:
    """One armed verification seam (config + accounting). Managed via
    :func:`arm` / :func:`disarm` / :func:`armored`; the counters are
    exported process-wide as ``armor.*`` by ``dhqr_tpu.obs.metrics``."""

    _GEN = [0]

    def __init__(self, config: ArmorConfig) -> None:
        self.config = config
        self.counters = Counters()
        ArmorState._GEN[0] += 1
        #: arm generation — seam-token material (a re-arm must re-key
        #: the engine build caches so tag programs re-trace).
        self.epoch = ArmorState._GEN[0]

    def metrics_snapshot(self) -> dict:
        out = {name: 0 for name in (
            "verifications", "detections", "recovered_redispatch",
            "recovered_degrade", "typed_failures")}
        out.update(self.counters.snapshot())
        # Under the lock: a concurrent trip inserting a NEW key while
        # sum() iterates would raise "dict changed size during
        # iteration" — telemetry must never take the caller down.
        with _TRIP_LOCK:
            out["degraded_labels"] = len(_DEGRADED)
            out["wire_trips"] = sum(_WIRE_TRIPS.values())
        return out


_ACTIVE: "ArmorState | None" = None
_ARM_LOCK = _lockwitness.make_lock("armor._ARM_LOCK")

# Persistent (module-lifetime, like tune's gate failures) transport
# health memory: labels degraded to the f32 wire, and per-plan-key
# verification-trip counts feeding tune's compressed-plan demotion.
_DEGRADED: "set[str]" = set()
_WIRE_TRIPS: "dict[tuple, int]" = {}
_TRIP_LOCK = _lockwitness.make_lock("armor._TRIP_LOCK")

# Bumped before every recovery re-dispatch WHILE wire fault sites are
# armed: the trace-time fault schedules bake into the lru-cached engine
# builds, so the re-dispatch must re-key them to re-draw (a harness
# whose site is exhausted then traces a CLEAN program — that is what
# makes single re-dispatch recovery replayable on CPU).
_NONCE = [0]


def arm(config: "ArmorConfig | None" = None) -> "ArmorState | None":
    """Arm the process-wide verification seam from ``config`` (default:
    the environment's ``DHQR_ARMOR*``). Returns the state, or None when
    the config says disabled (mirrors ``obs.arm``)."""
    global _ACTIVE
    cfg = config if config is not None else ArmorConfig.from_env()
    with _ARM_LOCK:
        _ACTIVE = ArmorState(cfg) if cfg.enabled else None
    return _ACTIVE


def disarm() -> None:
    """Back to the zero-overhead path (the degrade/trip memory is kept —
    transport health outlives one armed scope; ``reset_wire_trips``
    clears it)."""
    global _ACTIVE
    with _ARM_LOCK:
        _ACTIVE = None


def active() -> Optional[ArmorState]:
    """The armed state, or None — THE one read every disarmed sharded
    dispatch pays."""
    return _ACTIVE


@contextlib.contextmanager
def armored(config: "ArmorConfig | None" = None) -> Iterator[ArmorState]:
    """Scope the verification seam: arm on entry, restore on exit."""
    global _ACTIVE
    with _ARM_LOCK:
        previous = _ACTIVE
    state = arm(config if config is not None
                else ArmorConfig(enabled=True))
    try:
        yield state
    finally:
        with _ARM_LOCK:
            _ACTIVE = previous


def wire_tags_armed() -> bool:
    """Whether the wire seam should add integrity tags to compressed
    payloads (armed AND ``ArmorConfig.wire_tags``) — read at TRACE time
    by ``parallel/wire.py``."""
    state = _ACTIVE
    return state is not None and state.config.wire_tags


def seam_token(comms: "str | None" = None):
    """Cache-key material the engine ``_build_*`` lru caches append.

    None — the common case: no wire fault sites armed and no armor tag
    programs in play — keeps every existing cache key byte-identical
    (disarmed runs compile the pre-round-19 programs). Non-None when
    the traced program can differ from the plain one: wire fault sites
    armed (trace-time injection; the nonce re-keys per recovery
    re-dispatch so schedules re-draw), or armor tags armed on a
    compressed wire.
    """
    f_ep = _faults.epoch() if _faults.wire_sites_armed() else 0
    state = _ACTIVE
    a_ep = state.epoch if (state is not None and comms is not None
                           and state.config.wire_tags) else 0
    if not f_ep and not a_ep:
        return None
    # The recovery nonce rides ONLY while wire fault sites are armed
    # (its whole job is re-drawing baked trace-time schedules). With
    # faults disarmed — production armor — a re-dispatch deliberately
    # reuses the compiled program (a real transient SDC wants the same
    # program run again), and one label's recovery must not invalidate
    # every other armed label's build cache.
    return (f_ep, _NONCE[0] if f_ep else 0, a_ep)


def effective_comms(label: str, comms: "str | None") -> "str | None":
    """The wire format ``label`` should actually dispatch with: the
    caller's ``comms`` unless the recovery ladder degraded this label
    to the f32 passthrough (then None, until the process restarts or
    :func:`reset_wire_trips` clears the memory). Disarmed, the caller's
    value passes through untouched."""
    if comms is None or _ACTIVE is None:
        return comms
    with _TRIP_LOCK:
        return None if label in _DEGRADED else comms


def degraded_labels() -> "tuple[str, ...]":
    with _TRIP_LOCK:
        return tuple(sorted(_DEGRADED))


def note_wire_trip(kind: str, m: int, n: int, dtype, nproc: int) -> int:
    """Record one verification trip of a COMPRESSED dispatch against
    the (kind, shape, dtype, nproc) key; returns the running count.
    ``dhqr_tpu.tune.resolve_plan`` consults :func:`wire_demoted` and
    strips ``comms`` from stored plans once the count reaches the
    demotion threshold — compressed plans whose labels keep tripping
    verification stop being offered."""
    key = (str(kind), int(m), int(n), str(dtype), int(nproc))
    with _TRIP_LOCK:
        _WIRE_TRIPS[key] = _WIRE_TRIPS.get(key, 0) + 1
        return _WIRE_TRIPS[key]


def wire_trips(kind: str, m: int, n: int, dtype, nproc: int) -> int:
    with _TRIP_LOCK:
        return _WIRE_TRIPS.get(
            (str(kind), int(m), int(n), str(dtype), int(nproc)), 0)


def wire_demoted(kind: str, m: int, n: int, dtype, nproc: int) -> bool:
    """Whether the key's compressed plans are demoted (trips >= tune's
    ``PLAN_DEMOTE_AFTER`` — one threshold for both demotion flavors)."""
    from dhqr_tpu.tune.search import PLAN_DEMOTE_AFTER

    return wire_trips(kind, m, n, dtype, nproc) >= PLAN_DEMOTE_AFTER


def export_wire_trips() -> "dict[str, int]":
    """Wire-trip counts in the shared-fleet-state spelling (round 22):
    ``"kind|m|n|dtype|nproc" -> count``. The flat string key crosses
    process/JSON boundaries losslessly; :func:`adopt_wire_trips` parses
    it back."""
    with _TRIP_LOCK:
        return {"|".join(str(part) for part in key): count
                for key, count in _WIRE_TRIPS.items()}


def adopt_wire_trips(trips: "dict[str, int]") -> None:
    """Inherit another replica's wire-trip counts, merged by MAX per
    key (monotone evidence, like tune's gate-failure adoption): a key
    at/over the demotion threshold after adoption answers
    :func:`wire_demoted` True immediately, so replica N+1 stops
    offering the tripped compressed plans without re-tripping them
    against live traffic. Malformed entries are skipped — the state
    file is loaded tolerantly end to end."""
    with _TRIP_LOCK:
        for key_str, count in trips.items():
            parts = str(key_str).split("|")
            if len(parts) != 5:
                continue
            try:
                key = (parts[0], int(parts[1]), int(parts[2]), parts[3],
                       int(parts[4]))
                count = int(count)
            except (TypeError, ValueError):
                continue
            if count > _WIRE_TRIPS.get(key, 0):
                _WIRE_TRIPS[key] = count


def reset_wire_trips() -> None:
    """Clear the degrade/trip memory (tests; or after a link repair)."""
    with _TRIP_LOCK:
        _WIRE_TRIPS.clear()
        _DEGRADED.clear()


def _bump_nonce() -> None:
    _NONCE[0] += 1


def _classify(gap: float):
    """NaN-loud detections (inf gap — wire-tag poisoning, an injected
    NaN) are payload corruption; a finite over-threshold gap is a
    shard's contribution arriving wrong/missing as a unit."""
    return CorruptionDetected if gap == float("inf") else ShardFailure


def checked_dispatch(
    label: str,
    dispatch: Callable[[], object],
    verify: Callable[[object], "tuple[float, int | None]"],
    *,
    engine: str,
    comms: "str | None" = None,
    degrade: "Callable[[], object] | None" = None,
    shard_of: "Callable[[int], int | None] | None" = None,
    plan_shape: "tuple | None" = None,
) -> object:
    """The armored dispatch seam: run ``dispatch``, verify its result
    against the checksum invariant, and on detection walk the recovery
    ladder — re-dispatch (``ArmorConfig.redispatch`` times, re-keying
    the build caches so injected trace-time faults re-draw), degrade
    the label's wire to the f32 passthrough (compressed dispatches
    only; the degrade sticks for the label and feeds tune's
    compressed-plan demotion), then raise typed.

    ``verify(result) -> (gap, worst_col)`` returns the relative
    checksum gap (inf = NaN-loud) and the localizing column (None when
    the invariant does not localize); ``shard_of(worst_col)`` maps it
    to the mesh position. ``plan_shape = (kind, m, n, dtype, nproc)``
    keys the wire-trip accounting. Callers guard with
    :func:`active` — this function assumes an armed state.
    """
    state = _ACTIVE
    if state is None:       # disarmed between the caller's check and now
        return dispatch()
    cfg = state.config
    rec = _obs.active()
    tid = rec.mint() if rec is not None else None
    if rec is not None:
        rec.event(tid, "submit", kind="armor", label=label, engine=engine,
                  comms=comms or "f32")

    last_tol = [cfg.rtol]

    def _verify(out, stage: str, wire: "str | None"):
        # Per-STAGE tolerance: compressed dispatches carry honest
        # wire-rounding in their invariants (~1e-3..1e-2 measured),
        # so they verify against WIRE_RTOL; the degrade stage runs the
        # f32 passthrough and drops back to the tight cfg.rtol.
        tol = cfg.rtol if wire is None else max(cfg.rtol, WIRE_RTOL)
        last_tol[0] = tol
        state.counters.bump("verifications")
        gap, worst = verify(out)
        ok = gap <= tol
        if rec is not None:
            rec.event(tid, "verify", stage=stage, ok=bool(ok),
                      rtol=tol,
                      gap=(round(gap, 8) if gap != float("inf")
                           else "inf"))
        return ok, gap, worst

    out = dispatch()
    ok, gap, worst = _verify(out, "dispatch", comms)
    if ok:
        if rec is not None:
            rec.event(tid, "resolve", outcome="ok")
        return out

    state.counters.bump("detections")
    first_cls = _classify(gap)
    shard = shard_of(worst) if (shard_of is not None
                                and worst is not None
                                and gap != float("inf")) else None
    if comms is not None and plan_shape is not None:
        note_wire_trip(*plan_shape)
    recovery: "list[str]" = []

    for attempt in range(cfg.redispatch):
        recovery.append("redispatch")
        _bump_nonce()       # re-key the builds: injected schedules re-draw
        if rec is not None:
            rec.event(tid, "redispatch", attempt=attempt + 1)
        out = dispatch()
        ok, gap, worst = _verify(out, f"redispatch{attempt + 1}", comms)
        if ok:
            state.counters.bump("recovered_redispatch")
            if rec is not None:
                rec.event(tid, "resolve", outcome="ok",
                          recovery="redispatch")
            return out

    if comms is not None and degrade is not None:
        recovery.append("degrade")
        with _TRIP_LOCK:
            _DEGRADED.add(label)
        _bump_nonce()
        if rec is not None:
            rec.event(tid, "degrade", label=label, from_comms=comms)
        out = degrade()
        ok, gap, worst = _verify(out, "degrade", None)
        if ok:
            state.counters.bump("recovered_degrade")
            if rec is not None:
                rec.event(tid, "resolve", outcome="ok",
                          recovery="degrade")
            return out

    state.counters.bump("typed_failures")
    cls = _classify(gap) if gap == float("inf") else first_cls
    noun = ("corrupted collective payload"
            if cls is CorruptionDetected else "shard contribution lost")
    err = cls(
        f"{noun} at {label!r}: checksum invariant failed "
        f"(gap {gap:.3e} > rtol {last_tol[0]:.0e}) and recovery "
        f"({' -> '.join(recovery) or 'none configured'}) did not "
        "produce a verifiable result",
        engine=engine, label=label, shard_index=shard, trace_id=tid,
        recovery=tuple(recovery))
    if rec is not None:
        rec.attach(err, tid)
        rec.event(tid, "resolve", outcome=type(err).__name__,
                  error=str(err)[:200])
        rec.on_error(err, tid)
    raise err
