"""Typed fault-tolerance taxonomy for the sharded tier (round 19).

The PR-12 fault model types every INFRASTRUCTURE failure
(:class:`~dhqr_tpu.serve.errors.ServeError`) and the PR-13 guardrails
every DATA failure (:class:`~dhqr_tpu.numeric.errors.NumericalError`).
This module types the third population — failures of the *transport*
between shards: a corrupted collective payload, a dropped shard
contribution, a bit-flip landing in a compressed panel broadcast. Both
types are NumericalError SIBLINGS inside the taxonomy (they arrive as
wrong numbers, and the PR-8 guarded ladder can escalate past them), but
they carry transport provenance the data types cannot: WHICH collective
label, WHICH shard, and the obs trace id of the armored dispatch that
caught them.

The scheduler distinguishes them (``serve/scheduler.py``):
:class:`ShardFailure` is presumed TRANSIENT — a flaky link, a wedged
device, a preempted worker — so it takes the retry/bisect machinery
like a ``DispatchFailed`` (re-dispatching genuinely can fix it), while
:class:`CorruptionDetected` keeps the NumericalError bisect-isolation
route (by the time the armor recovery ladder has re-dispatched and
degraded the wire without success, retrying the same program is not the
fix).
"""

from __future__ import annotations

from dhqr_tpu.numeric.errors import NumericalError


class ArmorError(NumericalError):
    """Base of the armor taxonomy: a sharded-tier result whose ABFT
    invariants failed verification (or whose wire integrity tags
    poisoned it), after the recovery ladder ran dry.

    Attributes (beyond :class:`NumericalError`'s ``engine`` /
    ``cond_estimate`` / ``attempts``):
      label: the collective dispatch label of the armored entry point
        (the same spelling dhqr-pulse uses, e.g.
        ``"blocked_qr[P=4,64x32,nb=8,block]"``) — the unit the
        recovery ladder degrades.
      shard_index: the shard (mesh position) the checksum discrepancy
        localizes to, when the invariant localizes (column-sharded
        factor checks do; row-sharded solve residuals do not — None
        then).
      trace_id: the obs trace id of the armored dispatch (None when
        tracing was disarmed) — ``python -m dhqr_tpu.obs dump``
        replays the verify -> re-dispatch -> degrade path.
      recovery: the recovery rungs tried before the refusal, in order
        (e.g. ``("redispatch", "degrade")``).
    """

    def __init__(self, message: str, engine: "str | None" = None,
                 label: "str | None" = None,
                 shard_index: "int | None" = None,
                 trace_id: "int | None" = None,
                 recovery: tuple = ()) -> None:
        super().__init__(message, engine=engine)
        self.label = label
        self.shard_index = (None if shard_index is None
                            else int(shard_index))
        self.trace_id = trace_id
        self.recovery = tuple(recovery)


class CorruptionDetected(ArmorError):
    """A collective payload arrived CORRUPTED: a wire integrity tag
    mismatched at decompression (the payload was poisoned NaN-loud at
    the seam), or the post-hoc weighted-checksum invariant found a
    non-finite or checksum-breaking factor, and neither a re-dispatch
    nor degrading the wire to the f32 passthrough produced a verifiable
    result. The failure tracks the DATA PATH, not the request's data —
    the matrix itself screened clean — so the scheduler
    bisect-isolates rather than blind-retrying (the armor ladder
    already spent the re-dispatches that could have helped)."""


class ShardFailure(ArmorError):
    """A shard's contribution is MISSING or wrong as a unit: the
    invariant discrepancy localizes to one shard's columns (or the
    result is exactly the all-but-one-shard value — the dropped-psum
    signature), the collective completed, and recovery could not buy
    the words back. Presumed transient infrastructure (preemption, a
    flaky ICI link): the async scheduler routes this through the
    SAME retry/backoff/bisect machinery as a
    :class:`~dhqr_tpu.serve.errors.DispatchFailed`."""
