"""ABFT checksum invariants — O(mn) post-hoc verification (round 19).

Algorithm-based fault tolerance for QR (Huang & Abraham's checksum
idea, applied factor-side): a weighted checksum row ``u^H A`` of the
input must equal the same weighted row pushed through the factors,
``(Q^H u)^H R`` — and computing ``Q^H u`` is one reflector sweep over a
VECTOR, O(mn), not a re-factorization. For solve surfaces the invariant
is the normal-equations identity ``A^H (b - A x) ~ 0`` — two matvecs,
O(mn) again. Both discrepancies sit at the backward-error level
(~f32 eps, ~wire eps under a compressed ladder) for honest results and
at O(1) for a corrupted panel broadcast, a dropped shard contribution,
or a bit-flipped compressed payload — a >2-decade separation the
``ArmorConfig.rtol`` threshold splits.

Every check here is a small jitted reduction cached per shape (the
PR-8 guards discipline): a warm armored loop compiles nothing, and the
check reads the FACTORS the dispatch already produced — never the
engine internals — so it composes identically over all five sharded
engines.

The factor check localizes: the checksum gap is a per-COLUMN vector,
and the worst column's owner (under the engine's column layout) is the
implicated shard — :class:`~dhqr_tpu.armor.errors.ShardFailure` carries
it. Row-sharded solve residuals do not localize (every shard touches
every entry of ``x``); their errors carry ``shard_index=None``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: Additive floor inside relative denominators (never divide by an
#: all-zero column/problem).
_TINY = 1e-30


def _weights(m: int, dtype):
    """The deterministic checksum weight vector: a 1 + i/m ramp.
    Uniform weights are blind to sign-symmetric corruption (two equal
    and opposite hits cancel in the sum); the ramp breaks the symmetry
    while keeping every weight O(1), so no row dominates the sum and
    the relative threshold stays meaningful."""
    return (1.0 + jnp.arange(m, dtype=jnp.float32) / m).astype(dtype)


@partial(jax.jit, static_argnames=("block_size", "precision"))
def _qr_gap_impl(H, alpha, A, block_size, precision="highest"):
    """Per-column relative checksum gap of a packed factorization.

    ``u^H A`` vs ``(Q^H u)[:n]^H R`` with R unpacked from (strict upper
    H, alpha) — the packing every householder-family engine shares.
    Returns ``(gap_per_column, worst_column)``.
    """
    from dhqr_tpu.ops import blocked as _blocked

    m, n = A.shape
    u = _weights(m, A.dtype)
    s_in = jnp.matmul(jnp.conj(u), A, precision="highest")        # (n,)
    c = _blocked._apply_qt_impl(H, u, block_size, precision=precision)
    R = jnp.triu(H[:n, :n], k=1) + jnp.diag(alpha[:n])
    s_fact = jnp.matmul(jnp.conj(c[:n]), R, precision="highest")  # (n,)
    unorm = jnp.linalg.norm(u)
    colnorm = jnp.sqrt(jnp.sum(jnp.abs(A) ** 2, axis=0))
    gap = jnp.abs(s_in - s_fact) / (unorm * colnorm + _TINY)
    # NaN anywhere in the factors is a detection too (wire tags poison
    # NaN-loud): force those columns' gap to +inf so NaN can never
    # compare itself invisible (NaN > rtol is False).
    finite = jnp.isfinite(jnp.sum(H, axis=0)) & jnp.isfinite(alpha[:n])
    gap = jnp.where(finite & jnp.isfinite(gap), gap, jnp.inf)
    return gap, jnp.argmax(gap)


@jax.jit
def _lstsq_gap_impl(A, b, x):
    """Scalar normal-equations checksum gap of a solve:
    ``||A^H (b - A x)|| / (||A||_F (||A||_F ||x|| + ||b||))``."""
    B = b if b.ndim == 2 else b[:, None]
    X = x if x.ndim == 2 else x[:, None]
    r = B - jnp.matmul(A, X, precision="highest")
    g = jnp.matmul(jnp.conj(A.T), r, precision="highest")
    anorm = jnp.linalg.norm(A)
    gap = jnp.linalg.norm(g) / (
        anorm * (anorm * jnp.linalg.norm(X) + jnp.linalg.norm(B)) + _TINY)
    return jnp.where(jnp.isfinite(gap), gap, jnp.inf)


def _unmeshed(a):
    """Drop a multi-device sharding before the jitted reduction: the
    check operands arrive MIXED (the dispatch's mesh-replicated result
    next to the caller's local A), and a mixed-sharding jit re-commits
    the LARGE operand onto the mesh on every call — measured 10x the
    check's own cost at 1024x256. The reductions are single-device
    O(mn) work by design; local operands keep them that way."""
    import numpy as np

    sharding = getattr(a, "sharding", None)
    if sharding is not None and len(getattr(sharding, "device_set",
                                            (None,))) > 1:
        return jnp.asarray(np.asarray(a))
    return a


def qr_gap(H, alpha, A, block_size: int,
           precision: str = "highest") -> "tuple[float, int]":
    """Host-side wrapper: the factor checksum gap and the worst column
    (the localization the engines map to a shard index)."""
    gap, worst = _qr_gap_impl(_unmeshed(H), _unmeshed(alpha),
                              _unmeshed(A), int(block_size),
                              precision=precision)
    return float(jnp.max(gap)), int(worst)


def lstsq_gap(A, b, x) -> float:
    """Host-side wrapper: the solve checksum gap (scalar; no
    localization — see the module docstring)."""
    return float(_lstsq_gap_impl(_unmeshed(A), _unmeshed(b),
                                 _unmeshed(x)))


def finite_gap(*arrays) -> float:
    """Degenerate invariant for surfaces with no checkable identity
    (a standalone ``sharded_solve`` is handed factors, not A): 0.0
    when every output entry is finite, +inf otherwise — still catches
    every NaN-loud detection (wire-tag poisoning, injected NaN)."""
    from dhqr_tpu.numeric import guards as _guards

    return float("inf") if _guards.any_nonfinite(*arrays) else 0.0
