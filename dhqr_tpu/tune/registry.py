"""dhqr-atlas — THE declarative ExecutionPlan route registry (round 21).

Every analysis pass in this repo audits a route space (engine family x
panel interior x precision/comms rung x mesh/topology schedule x
donation/batching mode) that used to be re-enumerated by hand in four
subsystems — the tune grid (tune/search.py), the serve cache keys
(serve/engine.py), the jaxpr/comms lint passes (analysis/), and the
bench stage descriptors (benchmarks/run.py). PRs 12-16 each widened all
four by hand again, which at TPU scale is exactly how a route ships
unaudited and a collective ships unpriced (the per-route failure mode
arXiv 2112.09017 prices; the compressed rungs' EQuARX-style budgets,
arXiv 2506.17615). This module is the ONE enumeration:

* :data:`ROUTES` — one :class:`Route` record per reachable execution
  route, with declarative reachability (``min_devices``, ``presets``)
  and per-subsystem hooks: ``jaxpr`` trace specs (consumed by
  ``analysis/jaxpr_pass._entry_points``), a ``comms_trace`` spec +
  ``contract`` key (consumed by ``analysis/comms_pass._engine_specs``
  and checked bijective against ``comms_contracts.json`` by DHQR502),
  a ``serve`` cache-key cell (DHQR503), and a ``donation`` entry label
  (DHQR504).
* the grid axes (:data:`GRID_ALT_ENGINES`, :data:`GRID_MESH_LEVERS`,
  :data:`GRID_WIRE_PLANS`, ...) ``tune.search.candidate_plans``
  iterates, and :func:`grid_route_for` — the mapping DHQR505 uses to
  prove the emitted grid is a subset of the registry.
* :data:`BENCH_STAGES` — the benchmark stage catalogue
  ``benchmarks/run.py`` iterates (also DHQR505 material).

A new route registers HERE once; the jaxpr pass, the comms audit, the
tune grid, the serve keys and the bench stages pick it up automatically,
and the DHQR5xx atlas passes (``analysis/atlas.py``) fail lint when any
consumer drifts. The specs are declarative data (builder name + kwargs)
— the passes own the small builder *mechanism* maps; this module owns
*which routes exist*. Deliberately jax-free at import (like
``precision`` and ``analysis/cost_model``): the registry must be
enumerable anywhere, including hosts where backend bring-up would hang.

Not route-distinguishing by design: ``block_size`` (a ladder knob — the
same program schedule at every rung), ``trailing_precision`` (covered
by the policy-preset sweep, rule 4 pairs it with nothing else), and the
serve batch (bucketing reshapes, it does not reroute).
"""

from __future__ import annotations

import dataclasses

from dhqr_tpu.precision import COMMS_MODES, PrecisionPolicy
from dhqr_tpu.tune.plan import PLAN_ENGINES, Plan

#: The tune-DB kinds (moved here round 21 — re-exported by tune.search
#: for compatibility): the serve kinds never route engines, they batch
#: the blocked householder engine / the sketched program.
TUNE_KINDS = ("qr", "lstsq", "serve_qr", "serve_lstsq", "serve_sketch")

#: The serve bucket-program families (serve/engine.bucket_program and
#: the CacheKey ``kind`` field validate against THIS tuple).
SERVE_PROGRAM_KINDS = ("lstsq", "qr", "sketch")

#: Rule-5 alt-engine offer order (lstsq-only, policy-free, aspect-gated
#: — the gates themselves are thresholds, not routes, and live with the
#: grid in tune/search.py).
GRID_ALT_ENGINES = ("cholqr2", "tsqr", "sketch")

#: Rule-6 mesh schedule levers, in offer order (applied to the widest
#: ladder rung by candidate_plans).
GRID_MESH_LEVERS = (
    {"lookahead": True},
    {"agg_panels": 2},
    {"agg_panels": 4},
    {"agg_panels": 2, "lookahead": True},
)

#: Rule-6d (round 23, dhqr-pipeline) depth-k pipelined panel-broadcast
#: rungs, in offer order. Depth 1 IS the plain lookahead lever above —
#: these are the deeper ring schedules, offered only where the
#: pulse-measured exposed comms floor says there is collective time the
#: one-panel lookahead could not hide (tune/search.candidate_plans).
GRID_OVERLAP_PLANS = (
    {"lookahead": True, "overlap_depth": 2},
    {"lookahead": True, "overlap_depth": 4},
)

#: Rule-6b flat compressed-collective rungs for the householder mesh
#: path, in offer order.
GRID_WIRE_PLANS = (
    {"comms": "bf16"},
    {"agg_panels": 2, "comms": "bf16"},
    {"comms": "int8"},
)

#: Rule-6b alt-engine wire rungs: (engine, comms) in offer order.
GRID_ALT_WIRE = (("cholqr2", "bf16"), ("tsqr", "bf16"))

#: Rule-6c topology-tiered rungs (two-tier pod meshes only).
GRID_DCN_PLANS = ({"comms": "dcn:bf16"}, {"comms": "dcn:int8"})

#: Rule-6c alt-engine tiered rungs.
GRID_ALT_DCN = (("tsqr", "dcn:bf16"),)


@dataclasses.dataclass(frozen=True, eq=False)
class Route:
    """One execution route. Identity per the atlas contract: (engine
    ``family``, ``panel_impl``, ``comms`` rung, ``schedule`` +
    ``layout``/``lookahead``/``agg_panels`` schedule levers,
    ``donated``/``batched`` dispatch mode).

    Per-subsystem hooks (all optional, all declarative):

    * ``jaxpr`` — tuple of trace specs ``{"label": ..., "builder": ...,
      "axes": (...), **kwargs}`` the jaxpr sanitizer builds thunks from.
    * ``contract`` + ``comms_trace`` — the comms-audit engine spec;
      ``contract`` names the ``comms_contracts.json`` row the traced
      census is priced against (DHQR502 keeps the two bijective).
    * ``serve`` — cache-key probe cells ``{"kind": ..., "cells":
      (overrides, ...)}`` DHQR503 mints CacheKeys for; any two cells
      (across all routes) colliding on one key must trace to identical
      programs. The nb-pinned twin cells exist so dropping a key field
      (the classic recompile-hazard edit) produces a collision whose
      programs genuinely differ at the probe bucket.
    * ``donation`` — the ``analysis/comms_pass._donation_entries`` label
      this route's donated dispatch compiles through (DHQR504).
    """

    name: str
    family: str                       # one of PLAN_ENGINES + internals
    kind: str                         # "qr" | "lstsq" | "solve" | "update"
    schedule: str                     # "single"|"column"|"row"|"batched"|"pod"
    panel_impl: str = "loop"
    comms: "str | None" = None
    layout: str = "block"
    lookahead: bool = False
    agg_panels: int = 0
    overlap_depth: int = 0
    donated: bool = False
    batched: bool = False
    min_devices: int = 1
    presets: str = "all"              # "all" | "accurate"
    contract: "str | None" = None
    jaxpr: "tuple[dict, ...]" = ()
    comms_trace: "dict | None" = None
    serve: "dict | None" = None
    donation: "str | None" = None


_FAMILIES = tuple(PLAN_ENGINES) + ("update", "solve")
_SCHEDULES = ("single", "column", "row", "batched", "pod")


def _j(label, builder, axes=(), **kw):
    """One jaxpr trace spec (see Route.jaxpr)."""
    return dict(label=label, builder=builder, axes=tuple(axes), **kw)


ROUTES: "tuple[Route, ...]" = (
    # -- single-device API tier --------------------------------------------
    Route("householder_single", "householder", "qr", "single",
          jaxpr=(_j("qr[{preset}]", "api_qr"),
                 _j("lstsq[{preset}]", "api_lstsq"))),
    Route("householder_recursive", "householder", "lstsq", "single",
          panel_impl="recursive",
          jaxpr=(_j("lstsq_plan[{preset}]", "api_lstsq_plan",
                    plan=Plan(block_size=4, panel_impl="recursive")),)),
    # Round 21: the reconstruct panel interior gets its own trace — it
    # was a grid candidate (rule 3) with no jaxpr coverage before the
    # registry forced the question.
    Route("householder_reconstruct", "householder", "lstsq", "single",
          panel_impl="reconstruct", presets="accurate",
          jaxpr=(_j("lstsq_plan_reconstruct", "api_lstsq_plan",
                    plan=Plan(block_size=4, panel_impl="reconstruct")),)),
    Route("lstsq_auto_engine", "householder", "lstsq", "single",
          presets="accurate",
          jaxpr=(_j("lstsq_tall", "api_lstsq", tall=True),)),
    Route("tsqr_plan", "tsqr", "lstsq", "single", presets="accurate",
          jaxpr=(_j("lstsq_plan_tsqr", "api_lstsq_plan",
                    plan=Plan(engine="tsqr"), tall=True),)),
    Route("cholqr2_plan", "cholqr2", "lstsq", "single", presets="accurate",
          jaxpr=(_j("lstsq_plan_cholqr2", "api_lstsq_plan",
                    plan=Plan(engine="cholqr2"), tall=True),)),
    Route("tsqr_r_single", "tsqr", "qr", "single",
          jaxpr=(_j("tsqr_r[{preset}]", "tsqr_r"),)),
    Route("cholesky_qr2_single", "cholqr2", "qr", "single",
          jaxpr=(_j("cholesky_qr2[{preset}]", "cholesky_qr2"),)),
    Route("sketched_lstsq", "sketch", "lstsq", "single",
          jaxpr=(_j("sketched_lstsq[{preset}]", "sketched"),)),
    Route("update_solve", "update", "solve", "single",
          jaxpr=(_j("update_solve[{preset}]", "update_solve"),)),
    Route("update_rank1", "update", "update", "single",
          jaxpr=(_j("update_rank1[{preset}]", "update_rank1"),)),
    Route("blocked_qr_donate", "householder", "qr", "single", donated=True,
          donation="ops/blocked._blocked_qr_impl_donate"),
    # -- serving tier (batched bucket programs) ----------------------------
    Route("batched_lstsq", "householder", "lstsq", "batched", batched=True,
          contract="batched_lstsq",
          jaxpr=(_j("batched_lstsq[{preset}]", "bucket", kind="lstsq"),),
          comms_trace=dict(builder="bucket_sharded", shape="batch",
                           sweep=True),
          serve=dict(kind="lstsq", cells=({}, {"block_size": 64}))),
    Route("batched_lstsq_recursive", "householder", "lstsq", "batched",
          panel_impl="recursive", batched=True,
          serve=dict(kind="lstsq",
                     cells=({"panel_impl": "recursive",
                             "block_size": 64},))),
    Route("batched_lstsq_wire_bf16", "householder", "lstsq", "batched",
          comms="bf16", batched=True, contract="batched_lstsq",
          comms_trace=dict(builder="bucket_sharded", shape="batch",
                           label="batched_lstsq_wire_bf16",
                           policy=PrecisionPolicy(comms="bf16")),
          # cfg.comms is deliberately NOT a serve key field (the bucket
          # programs launch zero collectives) — this cell must collide
          # with batched_lstsq's key AND trace to the identical program.
          serve=dict(kind="lstsq",
                     cells=({"policy": PrecisionPolicy(comms="bf16")},))),
    Route("batched_qr", "householder", "qr", "batched", donated=True,
          batched=True, donation="ops/blocked._batched_qr_impl_donate",
          jaxpr=(_j("batched_qr[{preset}]", "bucket", kind="qr"),),
          serve=dict(kind="qr", cells=({}, {"block_size": 64}))),
    Route("batched_qr_recursive", "householder", "qr", "batched",
          panel_impl="recursive", donated=True, batched=True,
          serve=dict(kind="qr",
                     cells=({"panel_impl": "recursive",
                             "block_size": 64},))),
    Route("async_lstsq", "householder", "lstsq", "batched", batched=True,
          jaxpr=(_j("async_lstsq[{preset}]", "async_bucket"),)),
    Route("batched_sketch", "sketch", "lstsq", "batched", batched=True,
          jaxpr=(_j("batched_sketch[{preset}]", "bucket", kind="sketch"),),
          serve=dict(kind="sketch", cells=({},))),
    # -- sharded column tier -----------------------------------------------
    Route("unblocked_qr", "householder", "qr", "column", min_devices=2,
          contract="unblocked_qr",
          jaxpr=(_j("sharded_householder_qr[{preset}]", "sharded_unblocked",
                    axes=("cols",)),),
          comms_trace=dict(builder="unblocked", shape="col")),
    Route("blocked_qr", "householder", "qr", "column", min_devices=2,
          contract="blocked_qr",
          jaxpr=(_j("sharded_blocked_qr[{preset}]", "sharded_blocked",
                    axes=("cols",)),),
          comms_trace=dict(builder="blocked", shape="col", sweep=True)),
    Route("blocked_qr_cyclic", "householder", "qr", "column",
          layout="cyclic", min_devices=2, contract="blocked_qr_cyclic",
          comms_trace=dict(builder="blocked", shape="col", sweep=True,
                           layout="cyclic")),
    Route("blocked_qr_lookahead", "householder", "qr", "column",
          lookahead=True, min_devices=2, contract="blocked_qr_lookahead",
          comms_trace=dict(builder="blocked", shape="col", sweep=True,
                           lookahead=True)),
    # Round 23 (dhqr-pipeline): the depth-k double-buffered panel
    # broadcast — identical per-column arithmetic and launch count to
    # the lookahead schedule, with k panel broadcasts in flight ahead of
    # the trailing GEMM (the pf psum frames are up to depth*nb rows of R
    # taller; the blocked_qr slack absorbs that like it absorbs
    # lookahead's one-panel-taller frame).
    Route("blocked_qr_pipeline2", "householder", "qr", "column",
          lookahead=True, overlap_depth=2, min_devices=2,
          contract="blocked_qr_pipeline2",
          comms_trace=dict(builder="blocked", shape="col", sweep=True,
                           lookahead=True, overlap_depth=2)),
    Route("blocked_qr_pipeline4", "householder", "qr", "column",
          lookahead=True, overlap_depth=4, min_devices=2,
          contract="blocked_qr_pipeline4",
          comms_trace=dict(builder="blocked", shape="col", sweep=True,
                           lookahead=True, overlap_depth=4)),
    Route("blocked_qr_agg", "householder", "qr", "column", agg_panels=2,
          min_devices=2, contract="blocked_qr_agg",
          comms_trace=dict(builder="blocked", shape="col", sweep=True,
                           agg_panels=2)),
    Route("blocked_qr_agg_lookahead", "householder", "qr", "column",
          agg_panels=2, lookahead=True, min_devices=2,
          contract="blocked_qr_agg_lookahead",
          comms_trace=dict(builder="blocked", shape="col", sweep=True,
                           agg_panels=2, lookahead=True)),
    Route("lstsq_mesh", "householder", "lstsq", "column", min_devices=2,
          jaxpr=(_j("lstsq_mesh[{preset}]", "lstsq_mesh",
                    axes=("cols",)),)),
    Route("sharded_solve", "solve", "solve", "column", min_devices=2,
          contract="sharded_solve",
          comms_trace=dict(builder="solve", shape="col")),
    Route("tsqr_lstsq", "tsqr", "lstsq", "row", min_devices=2,
          contract="tsqr_lstsq",
          jaxpr=(_j("sharded_tsqr_lstsq[{preset}]", "sharded_tsqr",
                    axes=("rows",)),),
          comms_trace=dict(builder="tsqr", shape="row")),
    Route("cholqr_lstsq", "cholqr2", "lstsq", "row", min_devices=2,
          contract="cholqr_lstsq",
          jaxpr=(_j("sharded_cholqr_lstsq[{preset}]", "sharded_cholqr",
                    axes=("rows",)),),
          comms_trace=dict(builder="cholqr", shape="row")),
    # -- compressed wire rungs (dhqr-wire, round 18) -----------------------
    Route("blocked_qr_wire_bf16", "householder", "qr", "column",
          comms="bf16", min_devices=2, contract="blocked_qr_wire_bf16",
          comms_trace=dict(builder="blocked", shape="col", comms="bf16")),
    Route("blocked_qr_wire_int8", "householder", "qr", "column",
          comms="int8", min_devices=2, contract="blocked_qr_wire_int8",
          comms_trace=dict(builder="blocked", shape="col", comms="int8")),
    Route("blocked_qr_agg_wire_bf16", "householder", "qr", "column",
          agg_panels=2, comms="bf16", min_devices=2,
          contract="blocked_qr_agg_wire_bf16",
          comms_trace=dict(builder="blocked", shape="col", agg_panels=2,
                           comms="bf16")),
    # Round 23: the pipeline ring runs THROUGH the round-18 wire seam —
    # one traced rung proves compressed broadcasts pipeline too (the
    # contract's slack is widened to absorb the ring's taller psum
    # frames on top of the bf16 wire budget; see comms_contracts.json).
    Route("blocked_qr_pipeline2_wire_bf16", "householder", "qr", "column",
          comms="bf16", lookahead=True, overlap_depth=2, min_devices=2,
          contract="blocked_qr_pipeline2_wire_bf16",
          comms_trace=dict(builder="blocked", shape="col", comms="bf16",
                           lookahead=True, overlap_depth=2)),
    Route("unblocked_qr_wire_bf16", "householder", "qr", "column",
          comms="bf16", min_devices=2, contract="unblocked_qr_wire_bf16",
          comms_trace=dict(builder="unblocked", shape="col", comms="bf16")),
    Route("sharded_solve_wire_bf16", "solve", "solve", "column",
          comms="bf16", min_devices=2, contract="sharded_solve_wire_bf16",
          comms_trace=dict(builder="solve", shape="col", comms="bf16")),
    Route("tsqr_lstsq_wire_bf16", "tsqr", "lstsq", "row", comms="bf16",
          min_devices=2, contract="tsqr_lstsq_wire_bf16",
          comms_trace=dict(builder="tsqr", shape="row", comms="bf16")),
    Route("tsqr_lstsq_wire_int8", "tsqr", "lstsq", "row", comms="int8",
          min_devices=2, contract="tsqr_lstsq_wire_int8",
          comms_trace=dict(builder="tsqr", shape="row", comms="int8")),
    Route("cholqr_lstsq_wire_bf16", "cholqr2", "lstsq", "row",
          comms="bf16", min_devices=2, contract="cholqr_lstsq_wire_bf16",
          comms_trace=dict(builder="cholqr", shape="row", comms="bf16")),
    # -- two-tier pod tier (dhqr-pod, round 20) ----------------------------
    Route("unblocked_qr_pod", "householder", "qr", "pod", min_devices=4,
          contract="unblocked_qr_pod",
          comms_trace=dict(builder="unblocked", shape="col", pod=True)),
    Route("blocked_qr_pod", "householder", "qr", "pod", min_devices=4,
          presets="accurate", contract="blocked_qr_pod",
          jaxpr=(_j("sharded_blocked_qr_pod", "sharded_blocked",
                    axes=("dcn", "ici"), pod=True),),
          comms_trace=dict(builder="blocked", shape="col", pod=True)),
    Route("sharded_solve_pod", "solve", "solve", "pod", min_devices=4,
          contract="sharded_solve_pod",
          comms_trace=dict(builder="solve", shape="col", pod=True)),
    Route("tsqr_lstsq_pod", "tsqr", "lstsq", "pod", min_devices=4,
          contract="tsqr_lstsq_pod",
          comms_trace=dict(builder="tsqr", shape="row", pod=True)),
    Route("cholqr_lstsq_pod", "cholqr2", "lstsq", "pod", min_devices=4,
          contract="cholqr_lstsq_pod",
          comms_trace=dict(builder="cholqr", shape="row", pod=True)),
    Route("sharded_solve_pod_dcn_bf16", "solve", "solve", "pod",
          comms="dcn:bf16", min_devices=4,
          contract="sharded_solve_pod_dcn_bf16",
          comms_trace=dict(builder="solve", shape="col", pod=True,
                           comms="dcn:bf16")),
    Route("tsqr_lstsq_pod_dcn_bf16", "tsqr", "lstsq", "pod",
          comms="dcn:bf16", min_devices=4,
          contract="tsqr_lstsq_pod_dcn_bf16",
          comms_trace=dict(builder="tsqr", shape="row", pod=True,
                           comms="dcn:bf16")),
    Route("lstsq_pod_dcn_bf16", "householder", "lstsq", "pod",
          comms="dcn:bf16", min_devices=4, presets="accurate",
          jaxpr=(_j("lstsq_pod[dcn:bf16]", "lstsq_pod",
                    axes=("dcn", "ici"), mode="dcn:bf16"),)),
    Route("lstsq_pod_dcn_int8", "householder", "lstsq", "pod",
          comms="dcn:int8", min_devices=4, presets="accurate",
          jaxpr=(_j("lstsq_pod[dcn:int8]", "lstsq_pod",
                    axes=("dcn", "ici"), mode="dcn:int8"),)),
)


# ---------------------------------------------------------------------------
# Queries


def routes() -> "tuple[Route, ...]":
    return ROUTES


def route(name: str) -> Route:
    for r in ROUTES:
        if r.name == name:
            return r
    raise KeyError(f"no registered route {name!r}")


def route_names() -> "set[str]":
    return {r.name for r in ROUTES}


def reachable(r: Route, devices: int = 1, preset: str = "accurate") -> bool:
    """Evaluate a route's reachability predicate for one audit context."""
    if devices < r.min_devices:
        return False
    if r.presets == "accurate" and preset != "accurate":
        return False
    return True


def jaxpr_routes(preset: str, devices: int = 1) -> "list[Route]":
    """Routes the jaxpr sanitizer traces under ``preset`` with
    ``devices`` visible. The sharded engines trace under a 1-device
    mesh here (that blind spot is the comms pass's reason to exist), so
    only the pod routes — which need a real 2x2 factorization — carry a
    device floor for this pass."""
    out = []
    for r in ROUTES:
        if not r.jaxpr:
            continue
        if r.presets == "accurate" and preset != "accurate":
            continue
        if r.schedule == "pod" and devices < r.min_devices:
            continue
        out.append(r)
    return out


def comms_routes(P: int, sweep: bool) -> "list[Route]":
    """Routes the comms audit traces at mesh size ``P``;
    ``sweep`` selects the preset-parameterized half of the matrix (see
    comms_pass module docstring)."""
    out = []
    for r in ROUTES:
        spec = r.comms_trace
        if spec is None or bool(spec.get("sweep")) != sweep:
            continue
        if P < r.min_devices:
            continue
        out.append(r)
    return out


def contract_names() -> "set[str]":
    """Every comms_contracts.json row some registered route prices its
    census against — DHQR502 requires this to equal the committed file's
    key set exactly."""
    return {r.contract for r in ROUTES if r.contract}


def serve_routes() -> "list[Route]":
    return [r for r in ROUTES if r.serve is not None]


def donated_routes() -> "list[Route]":
    return [r for r in ROUTES if r.donated]


def grid_route_for(kind: str, plan: Plan, nproc: int = 1) -> "str | None":
    """Map one tune-grid candidate onto its registered route name, or
    None when the registry cannot express it (a DHQR505 finding).

    ``block_size`` / ``trailing_precision`` are deliberately not
    route-distinguishing (module docstring), so the map folds them."""
    serve = kind.startswith("serve_")
    if kind == "serve_sketch":
        # The sketched serving kind is its own program family — its one
        # grid candidate is the default (householder) plan whose ladder
        # tunes the core QR, so the ENGINE field does not route here.
        return "batched_sketch"
    if plan.engine == "sketch":
        return "batched_sketch" if serve else "sketched_lstsq"
    if plan.engine == "tsqr":
        if plan.comms == "bf16":
            return "tsqr_lstsq_wire_bf16"
        if plan.comms == "dcn:bf16":
            return "tsqr_lstsq_pod_dcn_bf16"
        if plan.comms == "int8":
            return "tsqr_lstsq_wire_int8"
        if plan.comms is not None:
            return None
        return "tsqr_lstsq" if nproc > 1 else "tsqr_plan"
    if plan.engine == "cholqr2":
        if plan.comms == "bf16":
            return "cholqr_lstsq_wire_bf16"
        if plan.comms is not None:
            return None
        return "cholqr_lstsq" if nproc > 1 else "cholqr2_plan"
    if plan.engine != "householder":
        return None
    if serve:
        return "batched_qr" if kind == "serve_qr" else "batched_lstsq"
    if nproc > 1:
        if plan.comms == "dcn:bf16":
            return "lstsq_pod_dcn_bf16"
        if plan.comms == "dcn:int8":
            return "lstsq_pod_dcn_int8"
        if plan.comms == "bf16":
            if plan.overlap_depth:
                return ("blocked_qr_pipeline2_wire_bf16"
                        if plan.overlap_depth == 2 else None)
            return "blocked_qr_agg_wire_bf16" if plan.agg_panels \
                else "blocked_qr_wire_bf16"
        if plan.comms == "int8":
            return "blocked_qr_wire_int8"
        if plan.comms is not None:
            return None
        if plan.overlap_depth:
            return {2: "blocked_qr_pipeline2",
                    4: "blocked_qr_pipeline4"}.get(plan.overlap_depth)
        if plan.agg_panels and plan.lookahead:
            return "blocked_qr_agg_lookahead"
        if plan.agg_panels:
            return "blocked_qr_agg"
        if plan.lookahead:
            return "blocked_qr_lookahead"
        return "blocked_qr"
    if plan.comms is not None:
        return None
    if plan.panel_impl == "recursive":
        return "householder_recursive"
    if plan.panel_impl.startswith("reconstruct"):
        return "householder_reconstruct"
    return "householder_single"


# ---------------------------------------------------------------------------
# Bench stage catalogue (BASELINE.md configs — benchmarks/run.py iterates)


@dataclasses.dataclass(frozen=True, eq=False)
class BenchStage:
    """One benchmark stage: the BASELINE.md config number, the metric
    name stem ``run.py`` reports under, the registered route the stage
    exercises, and the nominal (pod-scale) problem shape."""

    config: int
    metric: str
    route: str
    m: int
    n: int
    kind: str                  # "qr" | "lstsq"
    engine: "str | None" = None
    layout: str = "block"


BENCH_STAGES: "tuple[BenchStage, ...]" = (
    BenchStage(1, "dense_qr", "householder_single", 1024, 1024, "qr"),
    BenchStage(2, "tall_skinny_lstsq", "tsqr_lstsq", 65536, 256, "lstsq",
               engine="tsqr"),
    BenchStage(3, "square_qr_f32", "blocked_qr_cyclic", 16384, 16384,
               "qr", layout="cyclic"),
    BenchStage(4, "blocked_wy_qr_f32", "householder_single", 32768, 4096,
               "qr"),
    BenchStage(5, "overdetermined_lstsq_f32", "lstsq_mesh", 131072, 512,
               "lstsq", engine="householder"),
)


def bench_stages() -> "tuple[BenchStage, ...]":
    return BENCH_STAGES


# ---------------------------------------------------------------------------
# Structural self-check (the _dryrun atlas stage and DHQR501 run this)


def self_check() -> "list[str]":
    """Registry-internal invariants. Returns human-readable problem
    strings (empty on a healthy registry) — the atlas pass converts
    them into findings, the dryrun stage asserts on them."""
    problems = []
    names = [r.name for r in ROUTES]
    for name in sorted({n for n in names if names.count(n) > 1}):
        problems.append(f"duplicate route name {name!r}")
    known = set(names)
    for r in ROUTES:
        where = f"route {r.name!r}"
        if r.family not in _FAMILIES:
            problems.append(f"{where}: unknown family {r.family!r}")
        if r.schedule not in _SCHEDULES:
            problems.append(f"{where}: unknown schedule {r.schedule!r}")
        if r.comms is not None and r.comms not in COMMS_MODES:
            problems.append(f"{where}: unknown comms rung {r.comms!r}")
        if r.presets not in ("all", "accurate"):
            problems.append(f"{where}: unknown preset gate {r.presets!r}")
        if r.schedule == "pod" and r.min_devices < 4:
            problems.append(
                f"{where}: pod schedules need min_devices >= 4 "
                "(a 2x2 DCN x ICI factorization)")
        if r.schedule in ("column", "row", "pod") and r.min_devices < 2:
            problems.append(
                f"{where}: sharded schedules need min_devices >= 2")
        if r.overlap_depth:
            if r.overlap_depth < 2:
                problems.append(
                    f"{where}: overlap_depth must be >= 2 (depth 1 IS "
                    "the lookahead route) or 0")
            if not r.lookahead:
                problems.append(
                    f"{where}: pipeline routes require lookahead — the "
                    "ring generalizes the lookahead broadcast")
            if r.agg_panels:
                problems.append(
                    f"{where}: overlap_depth is mutually exclusive with "
                    "agg_panels (the aggregated schedule groups panels "
                    "its own way)")
        if r.comms_trace is not None and not r.contract:
            problems.append(
                f"{where}: comms-traced routes must name a contract")
        if r.contract and r.comms_trace is None:
            problems.append(
                f"{where}: names contract {r.contract!r} but carries no "
                "comms_trace spec to price it with")
        for spec in r.jaxpr:
            if "label" not in spec or "builder" not in spec:
                problems.append(
                    f"{where}: jaxpr spec needs 'label' and 'builder'")
        if r.serve is not None:
            if r.serve.get("kind") not in SERVE_PROGRAM_KINDS:
                problems.append(
                    f"{where}: serve cell kind must be one of "
                    f"{SERVE_PROGRAM_KINDS}")
            if not r.serve.get("cells"):
                problems.append(
                    f"{where}: serve spec needs at least one probe cell")
        if r.donated and not (r.donation or r.serve):
            problems.append(
                f"{where}: donated routes must name their donation entry")
        # Every route must be auditable by SOMETHING — a record no pass
        # consumes is exactly the unaudited-route drift the atlas exists
        # to prevent (DHQR501 reports these through the lint gate too).
        if not (r.jaxpr or r.comms_trace or r.serve or r.donation):
            problems.append(
                f"{where}: no audit surface (jaxpr, comms_trace, serve "
                "or donation)")
    labels = [spec["label"] for r in ROUTES for spec in r.jaxpr]
    for lab in sorted({l for l in labels if labels.count(l) > 1}):
        problems.append(f"duplicate jaxpr trace label {lab!r}")
    configs = [s.config for s in BENCH_STAGES]
    for c in sorted({c for c in configs if configs.count(c) > 1}):
        problems.append(f"duplicate bench stage config {c}")
    for s in BENCH_STAGES:
        if s.route not in known:
            problems.append(
                f"bench stage {s.config} names unregistered route "
                f"{s.route!r}")
        if s.m < s.n or s.n < 1:
            problems.append(f"bench stage {s.config}: bad shape "
                            f"{s.m}x{s.n}")
        if s.kind not in ("qr", "lstsq"):
            problems.append(f"bench stage {s.config}: unknown kind "
                            f"{s.kind!r}")
    return problems
