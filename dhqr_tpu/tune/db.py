"""Persistent, versioned plan database.

One JSON file maps tuning keys — ``(platform, kind, m, n, dtype, nproc,
policy)`` rendered as a string — to the measured-best :class:`Plan` plus
measurement metadata. Three properties carry the operational weight:

* **Tolerant loading.** A corrupt file, a stale/unknown schema version,
  or an individually malformed entry degrades to "no stored plan" with a
  ONE-TIME warning — never an exception. A plan DB is a cache of
  measurements; losing it costs a re-tune, while crashing on it costs
  the serving process. (OPERATIONS.md has the poisoned-entry runbook.)
* **Last-write-wins merging.** ``save()`` re-reads the file it is about
  to replace and merges (disk entries first, this process's entries on
  top), then writes atomically via ``os.replace``. Two concurrent tuner
  processes therefore union their keys; on a genuinely contended key the
  later writer wins — acceptable, because both values are measured
  winners for the same key.
* **Shipped seeds.** ``default_db()`` layers the packaged
  ``default_plans.json`` (the r1–r8 CPU/TPU ladder measurements turned
  machine-usable) UNDER the operator's writable DB: cold processes
  benefit from the committed trajectory, and any local measurement
  shadows the seed.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading
import warnings
from typing import Optional

from dhqr_tpu.tune.plan import Plan
from dhqr_tpu.utils import lockwitness as _lockwitness

SCHEMA = "dhqr-plan-db"
SCHEMA_VERSION = 1

#: Packaged seed database (committed, read-only).
SEED_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "default_plans.json")

# One warning per (path, reason) per process: a serving loop that polls
# a corrupt DB must not drown its own logs.
_WARNED: "set[tuple[str, str]]" = set()
_WARN_LOCK = _lockwitness.make_lock("db._WARN_LOCK")


def _warn_once(path: str, reason: str, detail: str) -> None:
    with _WARN_LOCK:
        if (path, reason) in _WARNED:
            return
        _WARNED.add((path, reason))
    warnings.warn(
        f"plan DB {path}: {detail} — continuing with no stored plans "
        "from this file (delete or re-tune to rebuild)",
        stacklevel=3,
    )


def plan_key(kind: str, m: int, n: int, dtype, nproc: int = 1,
             policy_tag: str = "-", platform: Optional[str] = None) -> str:
    """Render a tuning key. ``platform`` defaults to the current jax
    default backend — plans are hardware measurements, so a CPU-tuned
    winner must never shadow the TPU entry for the same shape."""
    import numpy as np

    if platform is None:
        import jax

        platform = jax.default_backend()
    return (f"{platform}:{kind}:{int(m)}x{int(n)}:"
            f"{np.dtype(dtype).name}:p{int(nproc)}:{policy_tag or '-'}")


def policy_tag(pol) -> str:
    """Canonical tag for the policy component of a key ("-" = no policy).
    Tags the RESOLVED precision tuple, not the preset name, so two
    spellings of the same tuple share their tuned plans. A comms wire
    format (dhqr-wire, round 18) appends a ``/w<mode>`` segment — only
    when set, so every pre-round-18 key (and the shipped seed DB)
    keeps matching."""
    if pol is None:
        return "-"
    return (f"{pol.panel}/{pol.trailing or '-'}/"
            f"{pol.apply or '-'}/r{pol.refine}"
            + (f"/w{pol.comms}" if getattr(pol, "comms", None) else ""))


def _check_entry(entry: dict) -> Plan:
    """Validate one DB entry payload; raises on any malformation."""
    if not isinstance(entry, dict):
        raise ValueError(f"entry must be a dict, got {type(entry)}")
    return Plan.from_dict(entry["plan"])


class PlanDB:
    """In-memory view of one plan-DB file (plus optional read-only seeds).

    ``entries`` maps key-string -> entry dict (``{"plan": {...}, ...
    metadata}``). Thread-safe for the get/record/save surface.
    """

    def __init__(self, path: "str | None" = None,
                 seed_path: "str | None" = None) -> None:
        self.path = path
        self._lock = _lockwitness.make_rlock("PlanDB._lock")
        self.entries: "dict[str, dict]" = {}   # guarded by: _lock
        self._seeds: "dict[str, dict]" = {}    # guarded by: frozen
        if seed_path:
            self._seeds = self._load_file(seed_path)
        if path:
            self.entries = self._load_file(path)

    # -- loading -----------------------------------------------------------
    @staticmethod
    def _load_file(path: str) -> "dict[str, dict]":
        """Tolerantly read one DB file into a key->entry dict."""
        if not os.path.exists(path):
            return {}
        try:
            with open(path, "r", encoding="utf-8") as fh:
                raw = json.load(fh)
        except (OSError, ValueError) as e:
            _warn_once(path, "corrupt", f"unreadable ({type(e).__name__}: {e})")
            return {}
        if not isinstance(raw, dict) or raw.get("schema") != SCHEMA:
            _warn_once(path, "schema",
                       "not a dhqr plan database (missing/foreign schema tag)")
            return {}
        if raw.get("version") != SCHEMA_VERSION:
            _warn_once(path, "version",
                       f"schema version {raw.get('version')!r} != "
                       f"{SCHEMA_VERSION} (stale or future file)")
            return {}
        plans = raw.get("plans")
        if not isinstance(plans, dict):
            _warn_once(path, "plans", "'plans' is not an object")
            return {}
        out = {}
        for key, entry in plans.items():
            try:
                _check_entry(entry)
            except Exception as e:
                _warn_once(path, f"entry:{key}",
                           f"dropping malformed entry {key!r} "
                           f"({type(e).__name__}: {e})")
                continue
            out[str(key)] = entry
        return out

    # -- read --------------------------------------------------------------
    def get(self, key: str) -> "Plan | None":
        """The stored plan for ``key`` (local entries shadow seeds)."""
        entry = self.get_entry(key)
        return None if entry is None else Plan.from_dict(entry["plan"])

    def get_entry(self, key: str) -> "dict | None":
        with self._lock:
            entry = self.entries.get(key)
            if entry is None:
                entry = self._seeds.get(key)
            return None if entry is None else dict(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self.entries)

    def keys(self) -> "list[str]":
        """Local + seed keys (local shadowing), sorted for determinism."""
        with self._lock:
            return sorted(set(self._seeds) | set(self.entries))

    # -- write -------------------------------------------------------------
    def record(self, key: str, plan: Plan, **meta) -> dict:
        """Store a winner in memory (``save()`` persists). ``meta`` is
        free-form measurement metadata (speedup, seconds, source...)."""
        if not isinstance(plan, Plan):
            raise ValueError(
                f"record() takes a Plan, got {type(plan).__name__}"
            )
        entry = {"plan": plan.to_dict(), **meta}
        _check_entry(entry)  # never record what load() would drop
        with self._lock:
            self.entries[key] = entry
        return entry

    def forget(self, key: str) -> bool:
        """Drop a (possibly poisoned) local entry; True if it existed."""
        with self._lock:
            return self.entries.pop(key, None) is not None

    @staticmethod
    @contextlib.contextmanager
    def _file_lock(path: str):
        """Advisory inter-process lock for the read-merge-replace window.

        Without it, two savers that both read the pre-state before
        either replaces the file would silently drop each other's
        DISJOINT keys (last-write-wins is for contended keys only).
        flock is advisory and POSIX-only; where unavailable the save
        degrades to the unlocked race rather than failing.
        """
        try:
            import fcntl
        except ImportError:  # non-POSIX: keep working, racy
            yield
            return
        fd = os.open(path + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            # The witness sees the flock window as a lock-like region,
            # so a threading acquisition inside it records an edge.
            with _lockwitness.witness_region("PlanDB._file_lock"):
                yield
        finally:
            os.close(fd)  # closing releases the flock

    def save(self, path: "str | None" = None) -> str:
        """Merge-write the local entries to disk (last-write-wins).

        Re-reads the destination first so concurrent writers UNION their
        keys (this process's entries win contended keys — it is the
        later writer), then replaces the file atomically. The
        read-merge-replace window holds an advisory file lock so a
        concurrent saver cannot lose this one's keys.
        """
        path = path or self.path
        if not path:
            raise ValueError("no path: pass save(path) or construct "
                             "PlanDB(path=...)")
        with self._lock:
            ours = {k: dict(v) for k, v in self.entries.items()}
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        with self._file_lock(path):
            merged = self._load_file(path)
            merged.update(ours)
            payload = {"schema": SCHEMA, "version": SCHEMA_VERSION,
                       "plans": {k: merged[k] for k in sorted(merged)}}
            fd, tmp = tempfile.mkstemp(prefix=".plandb-", dir=directory)
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as fh:
                    json.dump(payload, fh, indent=1, sort_keys=True)
                    fh.write("\n")
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                # dhqr: ignore[DHQR006] best-effort temp-file cleanup on the error path; the original exception reraises below
                except OSError:
                    pass
                raise
        with self._lock:
            self.entries = merged
        return path


# -- process default -------------------------------------------------------
_DEFAULT_DB: "PlanDB | None" = None
_DEFAULT_DB_LOCK = _lockwitness.make_lock("db._DEFAULT_DB_LOCK")


def default_db() -> PlanDB:
    """The process-default plan DB: ``TuneConfig.db_path``
    (``DHQR_TUNE_DB``) layered over the shipped seeds. Created lazily on
    first use, like the serve executable cache."""
    global _DEFAULT_DB
    if _DEFAULT_DB is None:
        with _DEFAULT_DB_LOCK:
            if _DEFAULT_DB is None:
                from dhqr_tpu.utils.config import TuneConfig

                cfg = TuneConfig.from_env()
                _DEFAULT_DB = PlanDB(
                    cfg.db_path,
                    seed_path=SEED_PATH if cfg.use_seeds else None)
    return _DEFAULT_DB


def reset_default_db() -> None:
    """Drop the cached process-default DB (tests; or after changing
    ``DHQR_TUNE_DB``) — the next ``default_db()`` re-reads the env."""
    global _DEFAULT_DB
    with _DEFAULT_DB_LOCK:
        _DEFAULT_DB = None
