"""Measurement-driven execution-plan search.

``tune()`` times a PRUNED candidate grid of :class:`Plan`\\ s for one
(kind, shape, dtype, mesh, policy) key on the actual backend and records
the winner in the plan database; ``resolve_plan()`` is the lookup (+
tune-on-miss) the ``plan="auto"`` API paths call.

Pruning rules (the grid, in deterministic order — docs/DESIGN.md "Plan
autotuner" carries the same table):

1. The static default ``Plan()`` is always candidate 0 — every tune
   measures the baseline it claims to beat, and the recorded entry
   carries the measured speedup.
2. nb ladder: powers of two from 8 to 256 with ``nb <= n`` (a panel
   wider than the matrix is the same program as ``nb = n``), on the
   blocked-householder engine.
3. Panel-interior variants (``recursive``, ``reconstruct``) only at
   ``n >= 64`` and only for ``nb >= 64`` — they restructure the panel
   interior, which is negligible under narrow panels.
   ``reconstruct`` additionally requires a real dtype (the no-pivot-LU
   reconstruction identity is real-only here).
4. ``trailing_precision="high"`` only on TPU (on CPU every precision
   collapses to native f32 — a split is pure key noise) and only when
   the caller did NOT fix precision via a policy (a plan must not
   silently move the error bar a policy pinned).
5. Alt engines (``tsqr``, ``cholqr2``, ``sketch``) are lstsq-only,
   policy-free candidates, gated on aspect ratio: ``cholqr2`` at
   ``m/n >= 8`` (all-GEMM wins once the trailing update dominates; its
   conditioning window is the caller's responsibility — see DESIGN),
   ``tsqr`` at ``m/n >= 32`` (the communication-avoiding tree needs
   genuinely tall blocks), ``sketch`` at ``m/n >=
   SketchConfig.min_aspect`` (default 64 — the randomized compressed
   core only amortizes its O(mn) pass + CGLS sweeps past that; round
   17). The serve kinds never route engines (``serve_qr``/
   ``serve_lstsq`` batch the blocked householder engine;
   ``serve_sketch`` is its own program family whose ladder tunes the
   CORE QR's panel width).
6. Mesh schedule levers (``lookahead``, ``agg_panels``, their grouped
   composition) only when the mesh axis has ``nproc > 1`` devices — on
   one device there is no collective to hide (the same degenerate case
   ``sharded_blocked_qr`` warns about). Round 23 (dhqr-pipeline) adds
   the depth-k pipelined broadcast rungs (``overlap_depth`` in {2, 4},
   riding lookahead) here, gated on MEASUREMENT rather than a policy:
   ``tune()`` pulse-probes the lookahead schedule first
   (``obs.pulse.measure`` -> ``obs.netmodel.comms_roofline``) and the
   deeper rungs are offered only when the measured ``exposed_floor_s``
   is positive — collective time the one-panel lookahead could not
   hide. A compute-bound probe (floor 0) prunes them: a deeper ring
   cannot hide comms under compute that already covers it. When no
   pulse measurement exists (profiler refused, stubbed searches, pure
   ``candidate_plans`` calls) the rungs are offered — the timer and
   accuracy gate still decide, measurement only PRUNES. The probe's
   headroom/floor numbers are recorded into the plan-DB entry so a
   shipped DB documents why the depth axis was (not) searched per key.
   Round 18 adds the
   compressed-comms rungs here (``comms="bf16"``/``"int8"``, plain and
   composed with ``agg_panels``, plus bf16 twins of the aspect-gated
   alt engines for lstsq): offered only when the caller did NOT pin
   precision via a policy — the same contract as rule 4 — with the
   accuracy gate deciding admissibility per candidate, so a plan can
   select compressed comms per-platform only after beating the
   8x-LAPACK bar on that backend.
7. The grid is truncated at ``TuneConfig.budget`` candidates — from the
   END (defaults and the nb ladder come first, so a tight budget still
   measures the highest-value axis).

Every timed lstsq candidate is VERIFIED against the reference acceptance
rule — normal-equations residual within 8x the LAPACK oracle — and a
failing candidate is disqualified no matter how fast it ran (qr
candidates gate on factor backward error vs. the default plan instead).
A plan database can therefore only ever route callers to configurations
that met the repo's accuracy bar on this very backend.

``use_pallas`` is deliberately NOT a plan axis: candidates run through
the public entry points with the "auto" resolution, which on TPU routes
supported panels through the fused kernel — i.e. the tuner measures the
program family the public API (and bench.py's pallas stages, at those
sizes) actually dispatch, and the platform prefix in the DB key keeps
those measurements from answering for any other backend. Callers who
pin ``use_pallas`` explicitly are off the tuned path by construction
(``plan=`` is mutually exclusive with the knobs it selects, and the
kernel silently bypasses ``panel_impl`` — plans never encode it).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional

from dhqr_tpu.tune.db import PlanDB, default_db, plan_key, policy_tag
from dhqr_tpu.tune.plan import DEFAULT_PLAN, Plan
from dhqr_tpu.tune.registry import (
    GRID_ALT_DCN,
    GRID_ALT_ENGINES,
    GRID_ALT_WIRE,
    GRID_DCN_PLANS,
    GRID_MESH_LEVERS,
    GRID_OVERLAP_PLANS,
    GRID_WIRE_PLANS,
    TUNE_KINDS,
)

#: Gate failures on one plan key before ``resolve_plan`` demotes the
#: stored plan (falls back to the static default instead of replaying
#: it). Three strikes: one failure can be one adversarial matrix; a
#: plan whose route keeps breaking down is mis-tuned for the traffic.
PLAN_DEMOTE_AFTER = 3

# key -> numeric-gate failure count, reported by the numeric fallback
# ladder (dhqr_tpu.numeric.ladder._note_plan_failure) whenever rung 0
# of a guarded call failed UNDER AN ACTIVE PLAN. In-memory only, by
# design: a demotion is evidence about the live traffic mix, not a
# measurement to persist (the DB keeps only measured winners).
_GATE_FAILURES: "dict[str, int]" = {}
_GATE_LOCK = threading.Lock()
_DEMOTED_LOOKUPS = [0]
# resolve_plan lookups answered with a comms-stripped plan because the
# armor seam demoted the key's compressed wire (round 19).
_WIRE_DEMOTED_LOOKUPS = [0]


def note_gate_failure(kind: str, m: int, n: int, dtype="float32", *,
                      nproc: int = 1, policy=None) -> int:
    """Record one numeric-gate failure against the plan key for this
    (kind, shape, dtype, nproc, policy); returns the running count.
    After :data:`PLAN_DEMOTE_AFTER` failures, ``resolve_plan`` stops
    replaying the stored plan for the key (demotion)."""
    from dhqr_tpu.precision import resolve_policy

    pol = resolve_policy(policy) if policy is not None else None
    key = plan_key(kind, m, n, dtype, nproc=nproc,
                   policy_tag=policy_tag(pol))
    with _GATE_LOCK:
        _GATE_FAILURES[key] = _GATE_FAILURES.get(key, 0) + 1
        return _GATE_FAILURES[key]


def plan_gate_stats() -> dict:
    """JSON-ready snapshot of the numeric-gate / demotion state:
    per-key failure counts, the demotion threshold, and how many
    ``resolve_plan`` lookups were answered with the static default
    because their key was demoted."""
    with _GATE_LOCK:
        return {
            "failures": dict(_GATE_FAILURES),
            "demote_after": PLAN_DEMOTE_AFTER,
            "demoted_lookups": _DEMOTED_LOOKUPS[0],
            "wire_demoted_lookups": _WIRE_DEMOTED_LOOKUPS[0],
        }


def adopt_gate_failures(failures: "dict[str, int]") -> None:
    """Inherit another replica's numeric-gate failure counts (the
    shared fleet state, round 22): per plan key, merge by MAX — a count
    is monotone evidence against the key's stored plan, so adopting can
    raise this process's count to the fleet's but never forget a local
    strike. A key at/over :data:`PLAN_DEMOTE_AFTER` after adoption is
    demoted on its next ``resolve_plan`` lookup exactly as if this
    process had witnessed the failures itself."""
    with _GATE_LOCK:
        for key, count in failures.items():
            try:
                count = int(count)
            except (TypeError, ValueError):
                continue
            if count > _GATE_FAILURES.get(str(key), 0):
                _GATE_FAILURES[str(key)] = count


def reset_gate_failures() -> None:
    """Clear the demotion state (tests; or after re-tuning a key)."""
    with _GATE_LOCK:
        _GATE_FAILURES.clear()
        _DEMOTED_LOOKUPS[0] = 0
        _WIRE_DEMOTED_LOOKUPS[0] = 0


def _demoted(key: str) -> bool:
    with _GATE_LOCK:
        if _GATE_FAILURES.get(key, 0) >= PLAN_DEMOTE_AFTER:
            _DEMOTED_LOOKUPS[0] += 1
            return True
        return False

#: Batch the serve kinds are timed at. The round-8 vmapped nb ladder was
#: flat in B (nb=32 won at B=16 and B=4 alike): the batch axis reshapes
#: every candidate's GEMMs identically, so one nominal batch ranks them.
TUNE_SERVE_BATCH = 8

_NB_LADDER = (8, 16, 32, 64, 128, 256)

#: Aspect-ratio gates for the alt-engine candidates (rule 5).
CHOLQR_MIN_ASPECT = 8
TSQR_MIN_ASPECT = 32


@dataclasses.dataclass(frozen=True)
class Measurement:
    """One timed candidate (``seconds=None`` -> disqualified)."""

    plan: Plan
    seconds: "float | None"
    residual: "float | None" = None
    reason: "str | None" = None


@dataclasses.dataclass(frozen=True)
class TuneResult:
    """Outcome of one ``tune()`` call."""

    key: str
    plan: Plan
    seconds: float
    baseline_seconds: float
    measurements: "tuple[Measurement, ...]"

    @property
    def speedup(self) -> float:
        """Measured default-plan time / winner time (>= 1 by
        construction: the default is always a candidate)."""
        return self.baseline_seconds / self.seconds


def _is_real(dtype) -> bool:
    import numpy as np

    return not np.issubdtype(np.dtype(dtype), np.complexfloating)


def _mesh_topology(mesh) -> "tuple[int, int] | None":
    """``(dcn_size, ici_size)`` when ``mesh`` is a two-tier pod mesh
    (axis names exactly ``("dcn", "ici")`` — the only spelling
    ``parallel/mesh.pod_mesh`` constructs), else None. Pure attribute
    reads — no device access, so :func:`candidate_plans` stays pure."""
    names = tuple(getattr(mesh, "axis_names", ()) or ())
    if names == ("dcn", "ici"):
        return (int(mesh.shape["dcn"]), int(mesh.shape["ici"]))
    return None


def candidate_plans(kind: str, m: int, n: int, dtype="float32",
                    nproc: int = 1, policy=None,
                    platform: "str | None" = None,
                    budget: "int | None" = None,
                    topology: "tuple[int, int] | None" = None,
                    exposed_floor_s: "float | None" = None) -> List[Plan]:
    """The pruned, deterministically-ordered candidate grid (module
    docstring rules 1-7). Pure — no timing, no device access (pass
    ``platform`` explicitly to keep it that way; None asks jax).
    ``topology`` (round 20, dhqr-pod) is the mesh's ``(dcn_size,
    ici_size)`` factorization when it is a two-tier pod mesh — it arms
    the rule-6b ``dcn:*`` tiered-compression rungs, which are pointless
    on a 1-D mesh (the seam degrades them to the exact f32
    passthrough there, so a candidate would time a duplicate of the
    uncompressed plan). ``exposed_floor_s`` (round 23, dhqr-pipeline)
    is the pulse-measured exposed collective floor of the lookahead
    schedule at this key — a measured 0.0 (compute already covers the
    comms) prunes the deeper ``overlap_depth`` rungs; None (no
    measurement) keeps them on offer."""
    if kind not in TUNE_KINDS:
        raise ValueError(f"kind must be one of {TUNE_KINDS}, got {kind!r}")
    if n < 1 or m < n:
        raise ValueError(
            f"tuning covers tall problems (m >= n >= 1), got ({m}, {n})"
        )
    if platform is None:
        import jax

        platform = jax.default_backend()
    if budget is None:
        from dhqr_tpu.utils.config import TuneConfig

        budget = TuneConfig.from_env().budget
    if kind == "serve_sketch":
        # The sketched bucket program has no panel loop — its core is
        # one Gram syrk + Cholesky, so nb is not a knob and a ladder
        # would time identical programs. One candidate: plan="auto" on
        # the sketch kind resolves fast and the DB records a measured
        # baseline rather than a fake grid.
        return [DEFAULT_PLAN]
    out: List[Plan] = [DEFAULT_PLAN]
    serve = kind.startswith("serve_")
    # Rule 2 — nb ladder. The serve tier's measured optimum lives at the
    # narrow end (round 8), so its ladder starts at 8; the single-problem
    # tiers start at 32 (sub-sublane panels only add panel-loop trips).
    ladder = [v for v in _NB_LADDER if v <= n and (serve or v >= 32)]
    out.extend(Plan(block_size=v) for v in ladder)
    # Rule 3 — panel-interior variants at GEMM-sized widths.
    if not serve and n >= 64:
        impls = ["recursive"]
        if _is_real(dtype):
            impls.append("reconstruct")
        for impl in impls:
            out.extend(Plan(block_size=v, panel_impl=impl)
                       for v in ladder if v >= 64)
    # Rule 4 — trailing split, TPU only, never under a policy.
    if not serve and platform == "tpu" and policy is None:
        out.extend(Plan(block_size=v, trailing_precision="high")
                   for v in ladder if v >= 64)
    # Rule 5 — alt engines, lstsq-only, policy-free, aspect-gated.
    # The engine axis and its offer order are the registry's
    # (GRID_ALT_ENGINES — tune/registry.py); the aspect THRESHOLDS stay
    # here with the rest of the grid's pruning policy. The sketched
    # gate rides SketchConfig.min_aspect (default 64 — below it the
    # O(mn) sketch pass + CGLS sweeps cannot amortize against the
    # direct GEMMs); the accuracy gate still decides per-shape
    # admissibility like for every other candidate.
    if kind == "lstsq" and policy is None:
        aspect = m / n
        from dhqr_tpu.utils.config import SketchConfig

        min_aspect = {"cholqr2": CHOLQR_MIN_ASPECT,
                      "tsqr": TSQR_MIN_ASPECT,
                      "sketch": SketchConfig.from_env().min_aspect}
        for engine in GRID_ALT_ENGINES:
            if aspect >= min_aspect[engine]:
                out.append(Plan(engine=engine))
    # Rule 6 — mesh schedule levers (axis order: GRID_MESH_LEVERS).
    if not serve and nproc > 1:
        base_nb = ladder[-1] if ladder else None
        out.extend(Plan(block_size=base_nb, **lever)
                   for lever in GRID_MESH_LEVERS)
        # Rule 6d (round 23, dhqr-pipeline) — deeper broadcast rings,
        # measurement-pruned: a pulse-probed lookahead whose exposed
        # collective floor is 0 proved compute already hides the comms,
        # so a deeper ring would only time duplicates of the lookahead
        # winner. Depth 1 is the lookahead lever above; the engine
        # clamps depth to num_panels - 1 at dispatch, so narrow shapes
        # stay safe to offer.
        if exposed_floor_s is None or exposed_floor_s > 0.0:
            out.extend(Plan(block_size=base_nb, **lever)
                       for lever in GRID_OVERLAP_PLANS)
        # Rule 6b (round 18) — compressed collectives (dhqr-wire),
        # lstsq-only (the solve surfaces carry CSNE recovery by
        # contract, so a compressed candidate can actually hold the
        # accuracy gate; a factor-only compressed plan would be refused
        # every time) and only when the caller did not pin precision
        # via a policy (the rule-4 contract). The gate still decides
        # admissibility per candidate/backend. Composed with agg: fewer
        # launches AND fewer bytes per launch is the schedule
        # EQuARX-style wire compression rewards most.
        if policy is None and kind == "lstsq":
            out.extend(Plan(block_size=base_nb, **wire)
                       for wire in GRID_WIRE_PLANS)
            aspect = m / n
            alt_gate = {"cholqr2": CHOLQR_MIN_ASPECT,
                        "tsqr": TSQR_MIN_ASPECT}
            out.extend(Plan(engine=engine, comms=comms)
                       for engine, comms in GRID_ALT_WIRE
                       if aspect >= alt_gate[engine])
            # Rule 6c (round 20, dhqr-pod) — topology-tiered rungs,
            # offered only on a genuinely two-tier mesh (dcn_size > 1):
            # f32 inside the ICI domain, compressed + armor-tagged only
            # at the one DCN crossing of the hierarchical schedule. The
            # same 8x-LAPACK accuracy gate decides admissibility; the
            # dcn:int8 rung is viable where flat int8 is not because
            # the payload quantizes exactly once (no per-panel ring
            # accumulation — parallel/wire.CSNE_MODEL_SWEEPS note).
            if topology is not None and topology[0] > 1:
                out.extend(Plan(block_size=base_nb, **dcn)
                           for dcn in GRID_DCN_PLANS)
                out.extend(Plan(engine=engine, comms=comms)
                           for engine, comms in GRID_ALT_DCN
                           if aspect >= alt_gate[engine])
    # Dedupe preserving order (Plan() and the ladder can collide at tiny
    # n), then rule 7 — budget truncation from the end.
    seen = set()
    unique = []
    for p in out:
        if p not in seen:
            seen.add(p)
            unique.append(p)
    return unique[:max(1, int(budget))]


def apply_plan_to_config(cfg, plan: Plan):
    """Fold a plan's knobs into a :class:`DHQRConfig` (``plan`` cleared).

    ``trailing_precision`` already set on the config (a resolved policy)
    wins over the plan's — candidate grids never pair the two (rule 4),
    and a stored plan replayed under a new policy must not override it.
    """
    trailing = (cfg.trailing_precision
                if cfg.trailing_precision is not None
                else plan.trailing_precision)
    comms = cfg.comms if cfg.comms is not None else plan.comms
    return dataclasses.replace(
        cfg, engine=plan.engine, block_size=plan.block_size,
        panel_impl=plan.panel_impl, trailing_precision=trailing,
        lookahead=plan.lookahead, agg_panels=plan.agg_panels,
        overlap_depth=plan.overlap_depth, comms=comms, plan=None,
    )


def _build_runner(kind: str, plan: Plan, policy, mesh) -> Callable:
    """A ``runner(*arrays) -> output-pytree`` executing ``kind`` under
    ``plan`` through the same impls the public API dispatches."""
    from dhqr_tpu.utils.config import DHQRConfig

    if kind in ("qr", "lstsq"):
        from dhqr_tpu.models import qr_model

        cfg = apply_plan_to_config(DHQRConfig(policy=policy), plan)
        if kind == "qr":
            def runner(A):
                fact = qr_model.qr(A, config=cfg, mesh=mesh)
                return (fact.H, fact.alpha)
        else:
            def runner(A, b):
                return qr_model.lstsq(A, b, config=cfg, mesh=mesh)
        return runner
    # Serve kinds: the bucket-dispatch unit (the very program the serve
    # cache compiles per bucket), timed NON-donating — donation only
    # aliases buffers, it does not reorder the math, so it cannot change
    # the candidate ranking, while a donated timing loop would have to
    # re-stage its input every repeat. The policy's program knobs
    # (precision split, in-program refinement) ride along so a tuned
    # entry keyed under a policy measured the program that policy runs.
    import jax

    from dhqr_tpu.ops import blocked as _blocked
    from dhqr_tpu.precision import resolve_policy
    from dhqr_tpu.serve.engine import SERVE_DEFAULT_BLOCK, _batched_lstsq_impl

    pol = resolve_policy(policy) if policy is not None else None
    panel_prec = pol.panel if pol is not None else "highest"
    trailing = pol.split_trailing() if pol is not None else None
    # block_size=None must resolve EXACTLY as the serving tier resolves
    # it (engine._plan_key: min(SERVE_DEFAULT_BLOCK, n)) — otherwise the
    # baseline candidate times a program serving never runs, and a
    # None-block winner would replay as a never-measured program.
    nb = plan.block_size if plan.block_size is not None \
        else SERVE_DEFAULT_BLOCK
    if kind == "serve_sketch":
        # Round 17: the serve tier's sketched bucket program. Shapes
        # arrive with the arrays, and the sketch operator is baked into
        # the program per (m, s, seed), so programs are memoized per
        # stacked shape — the timing loop's repeats hit one compile.
        from dhqr_tpu.solvers import sketch as _sk
        from dhqr_tpu.utils.config import SketchConfig

        skcfg = SketchConfig.from_env()
        refine = skcfg.refine + (pol.refine if pol is not None else 0)
        progs: dict = {}

        def runner(A, b):
            pk = (A.shape, str(A.dtype))
            if pk not in progs:
                _, pm, pn = A.shape
                s = _sk.sketch_dim(pm, pn, factor=skcfg.factor)
                op = _sk.resolve_operator(skcfg.operator, pm)
                progs[pk] = jax.jit(_sk.batched_sketch_program(
                    pm, pn, s, skcfg.seed, op, nb,
                    precision=panel_prec, trailing_precision=trailing,
                    refine=refine, dtype=A.dtype))
            return progs[pk](A, b)

        return runner
    if kind == "serve_lstsq":
        refine = pol.refine if pol is not None else 0
        # Same None-when-unsplit resolution the serve config performs,
        # so the timed program's static args match the served ones.
        apply_prec = (None if pol is None
                      or pol.resolved_apply() == pol.panel
                      else pol.resolved_apply())

        def runner(A, b):
            w = min(nb or A.shape[2], A.shape[2])
            return _batched_lstsq_impl(A, b, w, precision=panel_prec,
                                       trailing_precision=trailing,
                                       apply_precision=apply_prec,
                                       refine=refine,
                                       panel_impl=plan.panel_impl)
    else:
        def runner(A):
            w = min(nb or A.shape[2], A.shape[2])
            return jax.vmap(
                lambda a: _blocked._blocked_qr_impl(
                    a, w, precision=panel_prec,
                    trailing_precision=trailing,
                    panel_impl=plan.panel_impl)
            )(A)
        runner = jax.jit(runner)
    return runner


def _problem(kind: str, m: int, n: int, dtype, seed: int):
    """Deterministic tune inputs for ``kind`` at (m, n)."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)

    def draw(shape):
        a = rng.standard_normal(shape)
        if not _is_real(dtype):
            a = a + 1j * rng.standard_normal(shape)
        return jnp.asarray(a.astype(np.dtype(dtype)))

    if kind == "qr":
        return (draw((m, n)),)
    if kind == "lstsq":
        return draw((m, n)), draw((m,))
    if kind == "serve_qr":
        return (draw((TUNE_SERVE_BATCH, m, n)),)
    return draw((TUNE_SERVE_BATCH, m, n)), draw((TUNE_SERVE_BATCH, m))


def _analytic_flops(kind: str, m: int, n: int) -> "float | None":
    """Closed-form useful-work flops for one timed call of ``kind``
    (dhqr_tpu.obs.flops — the serve kinds time a TUNE_SERVE_BATCH
    stacked dispatch)."""
    from dhqr_tpu.obs import flops as _oflops

    if kind == "qr":
        return _oflops.qr_flops(m, n)
    if kind == "lstsq":
        return _oflops.lstsq_flops(m, n)
    if kind == "serve_qr":
        return _oflops.batched_qr_flops(TUNE_SERVE_BATCH, m, n)
    if kind == "serve_lstsq":
        return _oflops.batched_lstsq_flops(TUNE_SERVE_BATCH, m, n)
    if kind == "serve_sketch":
        from dhqr_tpu.solvers.sketch import sketch_dim
        from dhqr_tpu.utils.config import SketchConfig

        skcfg = SketchConfig.from_env()
        return TUNE_SERVE_BATCH * _oflops.sketched_lstsq_flops(
            m, n, sketch_dim(m, n, factor=skcfg.factor),
            refine=skcfg.refine)
    return None


def _measure_wall(plan: Plan, runner: Callable, args, repeats: int) -> float:
    """Min wall seconds over ``repeats`` timed calls (after the
    warmup/compile call), fenced with the shared value-dependent sync.
    ``plan`` rides along for signature parity with injected stubs (a
    test stub keys its fixed timings on it)."""
    from dhqr_tpu.utils.profiling import sync

    sync(runner(*args))  # compile + warm
    best = float("inf")
    for _ in range(max(1, repeats)):
        # dhqr: ignore[DHQR008] the tuner MEASURES real wall seconds per candidate — tests inject `timing=` a level up instead
        t0 = time.perf_counter()
        sync(runner(*args))
        # dhqr: ignore[DHQR008] same measurement, closing read
        best = min(best, time.perf_counter() - t0)
    return best


def _verify(kind: str, out, args, baseline_err: "float | None"):
    """(ok, err) accuracy gate for one candidate's warmup output.

    lstsq kinds: normal-equations residual within 8x the LAPACK oracle
    (the reference acceptance rule, per batch row for serve). qr kinds:
    factor backward error within 8x the default plan's own (passed as
    ``baseline_err``; the default itself gates only on finiteness).
    """
    import numpy as np

    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        normal_equations_residual,
        oracle_residual,
    )

    if kind in ("lstsq", "serve_lstsq", "serve_sketch"):
        if kind == "lstsq":
            rows = [(args[0], args[1], out)]
        else:
            rows = [(args[0][i], args[1][i], out[i])
                    for i in range(args[0].shape[0])]
        worst = 0.0
        for A, b, x in rows:
            if not np.all(np.isfinite(np.asarray(x))):
                return False, float("inf")
            res = normal_equations_residual(A, np.asarray(x), b)
            ref = oracle_residual(np.asarray(A), np.asarray(b))
            ratio = res / ref if ref > 0 else float(res > 0)
            worst = max(worst, ratio)
            if res > TOLERANCE_FACTOR * ref:
                return False, worst
        return True, worst
    # qr kinds: reassemble QR and compare to A.
    H, alpha = out
    Hn, an = np.asarray(H), np.asarray(alpha)
    if not (np.all(np.isfinite(Hn)) and np.all(np.isfinite(an))):
        return False, float("inf")
    if Hn.ndim == 3:  # serve_qr: gate on the first stacked problem
        Hn, an, A = Hn[0], an[0], np.asarray(args[0][0])
    else:
        A = np.asarray(args[0])
    n = Hn.shape[1]
    R = np.triu(Hn[:n, :n], 1) + np.diag(an)
    # Cheap backward-error proxy that needs no packed-Q apply:
    # ||A^H A - R^H R|| / ||A^H A|| — Q-orthogonality makes the two Gram
    # matrices equal, so a broken or precision-degraded R (the
    # plan-sensitive output) shows up here at f64 working precision.
    gram_a = np.matmul(A.conj().T, A)  # dhqr: ignore[DHQR002] host-side f64 numpy oracle, no MXU involved
    gram_r = np.matmul(R.conj().T, R)  # dhqr: ignore[DHQR002] host-side f64 numpy oracle, no MXU involved
    gram_err = np.linalg.norm(gram_a - gram_r) / max(
        np.linalg.norm(gram_a), 1e-30)
    if baseline_err is None:
        # No measured baseline yet (this IS the default candidate, or
        # the default failed to run): gate on an absolute bar instead of
        # passing unconditionally — 8x the max(m,n)*eps healthy-QR level
        # (the rank() tolerance convention). A broken R sits at O(1).
        eps = float(np.finfo(R.dtype).eps)
        bar = 8.0 * max(A.shape) * eps
        return gram_err <= max(bar, 1e-6), float(gram_err)
    return gram_err <= max(8.0 * baseline_err, 1e-5), float(gram_err)


def _probe_overlap_headroom(kind: str, m: int, n: int, dtype, mesh,
                            nproc: int, policy, seed: int) -> "dict | None":
    """Pulse-probe the one-panel lookahead schedule at this key and
    return its measured comms roofline (``obs.netmodel.comms_roofline``
    fields — ``overlap_headroom_s``, ``exposed_floor_s``,
    ``comms_fraction``), or None when the measurement degrades (no
    profiler on this backend, no collective events, probe raised).

    This is the round-23 tune signal: the exposed floor is the
    collective time a perfectly-overlapped one-panel lookahead still
    cannot hide, i.e. exactly what a DEEPER broadcast ring exists to
    attack — so the grid's ``overlap_depth`` rungs are offered (and the
    DB entry annotated) from measurement, not from a heuristic."""
    from dhqr_tpu.obs import pulse as _pulse

    try:
        runner = _build_runner(kind, Plan(lookahead=True), policy, mesh)
        args = _problem(kind, m, n, dtype, seed)
        _, report = _pulse.measure(
            f"tune_probe[{kind},{m}x{n},P={nproc}]",
            lambda: runner(*args), n_devices=nproc)
    # dhqr: ignore[DHQR006] the probe is advisory — a backend where it cannot run must degrade to the unpruned grid, never fail the tune
    except Exception:
        return None
    comms = report.comms
    if not comms or comms.get("comms_bound") is None:
        return None
    return comms


def tune(kind: str, m: int, n: int, dtype="float32", *,
         mesh=None, policy=None, db: "PlanDB | None" = None,
         budget: "int | None" = None, repeats: "int | None" = None,
         measure: "Callable | None" = None, seed: int = 0,
         save: bool = True) -> TuneResult:
    """Time the candidate grid for one key; record + persist the winner.

    ``measure(plan, runner, args, repeats) -> seconds`` is injectable
    (tests use a deterministic stub keyed on ``plan``; stubbed searches
    skip the accuracy gate, which needs real outputs). ``save=False``
    records in memory only.
    """
    import numpy as np

    from dhqr_tpu.precision import resolve_policy
    from dhqr_tpu.utils.config import TuneConfig

    tcfg = TuneConfig.from_env()
    budget = tcfg.budget if budget is None else budget
    repeats = tcfg.repeats if repeats is None else repeats
    pol = resolve_policy(policy) if policy is not None else None
    nproc = 1
    topology = None
    if mesh is not None:
        nproc = int(np.prod(list(mesh.shape.values())))
        topology = _mesh_topology(mesh)
    key = plan_key(kind, m, n, dtype, nproc=nproc, policy_tag=policy_tag(pol))
    stubbed = measure is not None
    # Round 23 (dhqr-pipeline): measure before enumerating — the
    # lookahead probe's comms roofline gates the overlap_depth rungs
    # and annotates the recorded entry. Stubbed searches skip it (a
    # stub's grid must stay deterministic and device-free).
    headroom = None
    if not stubbed and mesh is not None and nproc > 1 \
            and kind in ("qr", "lstsq"):
        headroom = _probe_overlap_headroom(kind, m, n, dtype, mesh,
                                           nproc, policy, seed)
    candidates = candidate_plans(
        kind, m, n, dtype, nproc=nproc, policy=pol, budget=budget,
        topology=topology,
        exposed_floor_s=(headroom.get("exposed_floor_s")
                         if headroom is not None else None))
    timer = measure or _measure_wall
    args = None if stubbed else _problem(kind, m, n, dtype, seed)
    rows: "list[Measurement]" = []
    baseline_err = None
    baseline_seconds = None
    for plan in candidates:
        runner = _build_runner(kind, plan, policy, mesh)
        try:
            if not stubbed:
                out = runner(*args)
                ok, err = _verify(kind, out, args, baseline_err)
                if plan == DEFAULT_PLAN and kind in ("qr", "serve_qr"):
                    baseline_err = err
                if not ok:
                    rows.append(Measurement(plan, None, residual=err,
                                            reason="accuracy"))
                    continue
            else:
                err = None
            seconds = timer(plan, runner, args, repeats)
            rows.append(Measurement(plan, float(seconds), residual=err))
        except Exception as e:  # a candidate that cannot run is skipped,
            rows.append(Measurement(  # never fatal to the search
                plan, None, reason=f"{type(e).__name__}: {e}"))
        if plan == DEFAULT_PLAN and rows and rows[-1].seconds is not None:
            baseline_seconds = rows[-1].seconds
    timed = [r for r in rows if r.seconds is not None]
    if not timed:
        raise RuntimeError(
            f"tune({key}): no candidate survived "
            f"({[(r.plan.describe(), r.reason) for r in rows]})"
        )
    if baseline_seconds is None:
        # Default plan failed to time (rare — e.g. stub raising): the
        # speedup is meaningless, so anchor at the winner (speedup 1).
        baseline_seconds = min(r.seconds for r in timed)
    winner = min(timed, key=lambda r: (r.seconds, candidates.index(r.plan)))
    if db is None:
        db = default_db()
    extra = {}
    if not stubbed:
        # dhqr-xray (round 15): measured entries carry their analytic
        # throughput — useful-work flops (obs.flops closed forms) over
        # the winner's measured seconds — so a shipped plan DB reads as
        # GF/s per key, comparable across rounds/platforms, not just as
        # relative speedups. Stubbed searches skip it (fake seconds
        # would mint fake GF/s).
        analytic = _analytic_flops(kind, m, n)
        if analytic and winner.seconds > 0:
            extra["analytic_flops"] = analytic
            extra["gflops"] = round(analytic / winner.seconds / 1e9, 2)
    if headroom is not None:
        # dhqr-pipeline (round 23): the probe's roofline rides the DB
        # entry so a shipped DB documents, per key, the measured
        # overlap headroom / exposed floor that gated (or pruned) the
        # overlap_depth axis on this backend.
        for field in ("overlap_headroom_s", "exposed_floor_s",
                      "comms_fraction"):
            if headroom.get(field) is not None:
                extra[field] = headroom[field]
    db.record(
        key, winner.plan,
        seconds=round(winner.seconds, 6),
        baseline_seconds=round(baseline_seconds, 6),
        speedup=round(baseline_seconds / winner.seconds, 4),
        candidates=len(candidates),
        source="stub" if stubbed else "measured",
        **extra,
    )
    if save and db.path:
        db.save()
    return TuneResult(key=key, plan=winner.plan, seconds=winner.seconds,
                      baseline_seconds=baseline_seconds,
                      measurements=tuple(rows))


def resolve_plan(kind: str, m: int, n: int, dtype="float32", *,
                 nproc: int = 1, mesh=None, policy=None,
                 db: "PlanDB | None" = None,
                 on_miss: "str | None" = None,
                 **tune_kwargs) -> "Plan | None":
    """The ``plan="auto"`` resolution: DB hit -> stored plan; miss ->
    tune now (``on_miss="tune"``) or None (``on_miss="default"``, the
    caller keeps its static knobs). ``nproc`` is inferred from ``mesh``
    when one is passed.

    A key with :data:`PLAN_DEMOTE_AFTER` or more recorded numeric-gate
    failures (:func:`note_gate_failure` — the numeric fallback ladder
    reports rung-0 failures under an active plan) is DEMOTED: the
    lookup returns None (static default) without consulting or
    re-tuning the DB, because the stored winner was measured on
    well-conditioned probes and the live traffic keeps refusing it.
    ``reset_gate_failures()`` (or a process restart) re-admits it;
    :func:`plan_gate_stats` is the observable."""
    import numpy as np

    from dhqr_tpu.precision import resolve_policy
    from dhqr_tpu.utils.config import TuneConfig

    pol = resolve_policy(policy) if policy is not None else None
    if mesh is not None:
        nproc = int(np.prod(list(mesh.shape.values())))
    if db is None:
        db = default_db()
    key = plan_key(kind, m, n, dtype, nproc=nproc, policy_tag=policy_tag(pol))
    if _demoted(key):
        return None
    hit = db.get(key)
    if hit is not None:
        if hit.comms:
            # Round 19 (dhqr-armor): a COMPRESSED plan whose key keeps
            # tripping the armor verification seam is demoted to its
            # uncompressed twin — the stored winner was measured on a
            # healthy wire, and the live transport keeps refusing it.
            # Same in-memory-evidence philosophy as _demoted above;
            # armor.reset_wire_trips() (or a restart) re-admits it.
            from dhqr_tpu import armor as _armor

            if _armor.wire_demoted(kind, m, n, dtype, nproc):
                with _GATE_LOCK:
                    _WIRE_DEMOTED_LOOKUPS[0] += 1
                return dataclasses.replace(hit, comms=None)
        return hit
    if on_miss is None:
        on_miss = TuneConfig.from_env().on_miss
    if on_miss == "default":
        return None
    return tune(kind, m, n, dtype, mesh=mesh, policy=policy, db=db,
                **tune_kwargs).plan
