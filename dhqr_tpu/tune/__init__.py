"""dhqr-tune — measurement-driven execution-plan autotuning.

The engine knobs (engine family, panel width ``nb``, panel interior,
trailing precision, mesh schedule) dominate wall time and their optimum
moves with problem shape (the serve ladder's nb=32-vs-128 4.5x; the TPU
ladder's nb=256/512 escalation; tall-skinny problems belonging on
TSQR/CholQR2). This package stops guessing:

* :class:`Plan` — one executable configuration (tune/plan.py);
* :class:`PlanDB` — persistent, versioned, corrupt-tolerant JSON plan
  database with last-write-wins merging, seeded by the shipped
  ``default_plans.json`` (tune/db.py);
* :func:`tune` / :func:`resolve_plan` / :func:`candidate_plans` — the
  pruned on-device timing search and the ``plan="auto"`` lookup the
  public API threads through (tune/search.py);
* :mod:`dhqr_tpu.tune.registry` (round 21, dhqr-atlas) — THE
  declarative route registry: one :class:`Route` record per execution
  route, consumed by the grid, the serve cache keys, the lint passes
  and the bench stages, and audited by the DHQR5xx atlas pass.

Entry points: ``qr(A, plan="auto")``, ``lstsq(A, b, plan="auto")``,
``serve.prewarm(..., plan="auto")``, ``DHQR_TUNE_*`` env knobs
(:class:`dhqr_tpu.utils.config.TuneConfig`).
"""

from dhqr_tpu.tune.db import (
    PlanDB,
    SEED_PATH,
    default_db,
    plan_key,
    policy_tag,
    reset_default_db,
)
from dhqr_tpu.tune.plan import DEFAULT_PLAN, PLAN_ENGINES, Plan
from dhqr_tpu.tune.registry import (
    BenchStage,
    Route,
    SERVE_PROGRAM_KINDS,
    TUNE_KINDS,
    bench_stages,
    grid_route_for,
    route,
    route_names,
    routes,
)
from dhqr_tpu.tune.search import (
    Measurement,
    PLAN_DEMOTE_AFTER,
    TuneResult,
    apply_plan_to_config,
    candidate_plans,
    note_gate_failure,
    plan_gate_stats,
    reset_gate_failures,
    resolve_plan,
    tune,
)

__all__ = [
    "Plan",
    "DEFAULT_PLAN",
    "PLAN_ENGINES",
    "PlanDB",
    "SEED_PATH",
    "default_db",
    "reset_default_db",
    "plan_key",
    "policy_tag",
    "candidate_plans",
    "apply_plan_to_config",
    "tune",
    "resolve_plan",
    "Measurement",
    "TuneResult",
    "PLAN_DEMOTE_AFTER",
    "note_gate_failure",
    "plan_gate_stats",
    "reset_gate_failures",
    "Route",
    "BenchStage",
    "routes",
    "route",
    "route_names",
    "bench_stages",
    "grid_route_for",
    "TUNE_KINDS",
    "SERVE_PROGRAM_KINDS",
]
