"""Execution plans — the unit the autotuner searches over and caches.

A :class:`Plan` names the engine-selection knobs that dominate wall time
for one problem shape: the engine family, the compact-WY panel width
``nb``, the panel-interior algorithm, the trailing-GEMM precision split,
and (on meshes) the schedule levers ``agg_panels``/``lookahead``. It is
exactly the subset of :class:`dhqr_tpu.utils.config.DHQRConfig` the
serve-tier ladder proved shape-sensitive (round 8: ``nb=32`` beat the
static ``nb=128`` by 4.5x for vmapped 384x128 problems), made
first-class so a measurement can be recorded once and replayed on every
later call.

Accuracy knobs (``precision``, ``norm``, ``refine``, policies) are NOT
plan fields: a plan must never silently change the answer's error bar —
it is keyed UNDER the caller's policy instead (see
:func:`dhqr_tpu.tune.db.plan_key`).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Engine families a plan may name. cholqr3 is deliberately absent: the
# shifted window exists for near-rank-deficient problems, which a timing
# search cannot detect — routing there is an accuracy decision the
# caller must make via engine=. "sketch" (round 17) is the randomized
# compressed-core engine; like the other alt engines its admissibility
# is decided by the search's accuracy gate per candidate, and the grid
# only offers it past the SketchConfig.min_aspect aspect ratio.
PLAN_ENGINES = ("householder", "tsqr", "cholqr2", "sketch")

_PANEL_IMPLS = ("loop", "recursive", "reconstruct")

_TRAILING = (None, "highest", "high", "default")


@dataclasses.dataclass(frozen=True)
class Plan:
    """One executable configuration for a (shape, dtype, mesh) problem.

    Attributes:
      engine: "householder" (the packed-reflector default; the only
        engine ``qr()`` accepts), "tsqr", "cholqr2" or "sketch"
        (lstsq-only fast paths for tall-skinny problems).
      block_size: compact-WY panel width nb; None keeps the engine's
        auto resolution (``ops.blocked.auto_block_size`` single-device).
      panel_impl: panel-interior algorithm on the blocked XLA path
        ("loop" / "recursive" / "reconstruct[:chunk]").
      trailing_precision: trailing-GEMM precision split (None = no
        split). Only tuned when the caller did not already fix precision
        via a policy — see ``search.candidate_plans``.
      lookahead / agg_panels: mesh schedule levers (1-device plans keep
        the defaults; the pair composes only on multi-device meshes).
      overlap_depth: depth-k pipelined panel broadcast (dhqr-pipeline,
        round 19): None/1 = the classic one-panel lookahead, k >= 2
        keeps k panel broadcasts in flight ahead of the trailing GEMM.
        Requires ``lookahead`` and excludes ``agg_panels`` (the
        aggregated schedule has its own panel grouping). Arithmetic is
        per-column identical to the lookahead schedule, so unlike
        ``comms`` it never moves the error bar — the grid offers it
        purely on the pulse-measured exposed comms floor (see
        ``search.candidate_plans``).
      comms: collective wire format on the sharded tier (dhqr-wire,
        round 18): None = uncompressed, "bf16"/"int8" route every
        sharded collective through the compression seam
        (``dhqr_tpu.parallel.wire``). Like ``trailing_precision`` it
        CAN move the error bar, so the grid only offers it when the
        caller did not pin precision via a policy, and the search's
        8x-LAPACK accuracy gate decides admissibility per candidate —
        a compressed plan can only be recorded after beating the bar
        on this backend. Applies to every engine family with a mesh
        (householder panels, tsqr combine, cholqr Gram); meaningless
        (and rejected by the serve tier) where no collectives launch.
    """

    engine: str = "householder"
    block_size: Optional[int] = None
    panel_impl: str = "loop"
    trailing_precision: Optional[str] = None
    lookahead: bool = False
    agg_panels: Optional[int] = None
    overlap_depth: Optional[int] = None
    comms: Optional[str] = None

    def __post_init__(self):
        if self.engine not in PLAN_ENGINES:
            raise ValueError(
                f"Plan.engine must be one of {PLAN_ENGINES}, "
                f"got {self.engine!r}"
            )
        if self.block_size is not None and self.block_size < 1:
            raise ValueError(
                f"Plan.block_size must be >= 1 or None, got {self.block_size}"
            )
        base = self.panel_impl.split(":", 1)[0]
        if base not in _PANEL_IMPLS:
            raise ValueError(
                f"Plan.panel_impl must be one of {_PANEL_IMPLS} "
                f"(optionally 'reconstruct:<chunk>'), got {self.panel_impl!r}"
            )
        if self.trailing_precision not in _TRAILING:
            raise ValueError(
                f"Plan.trailing_precision must be one of {_TRAILING}, "
                f"got {self.trailing_precision!r}"
            )
        if self.agg_panels is not None and self.agg_panels < 2:
            raise ValueError(
                f"Plan.agg_panels must be >= 2 or None, got {self.agg_panels}"
            )
        if self.overlap_depth is not None:
            if self.overlap_depth < 2:
                raise ValueError(
                    "Plan.overlap_depth must be >= 2 or None (depth 1 IS "
                    f"the lookahead schedule), got {self.overlap_depth}"
                )
            if not self.lookahead:
                raise ValueError(
                    "Plan.overlap_depth requires lookahead=True: the "
                    "pipeline generalizes the lookahead broadcast, it "
                    "does not replace the blocking schedule"
                )
            if self.agg_panels:
                raise ValueError(
                    "Plan.overlap_depth is mutually exclusive with "
                    "agg_panels (the aggregated schedule already groups "
                    "panel broadcasts its own way)"
                )
        from dhqr_tpu.precision import resolve_comms

        object.__setattr__(self, "comms", resolve_comms(self.comms))
        if self.engine != "householder":
            # The alt engines have no panel loop / trailing split /
            # schedule to steer; a plan carrying those knobs anyway would
            # be rejected downstream with a confusing per-knob error.
            # (comms IS allowed: the sharded tsqr/cholqr routes have a
            # combine gather / Gram psum to compress.)
            if (self.panel_impl != "loop" or self.trailing_precision
                    or self.lookahead or self.agg_panels
                    or self.overlap_depth):
                raise ValueError(
                    f"engine={self.engine!r} plans carry only block_size "
                    "(panel_impl/trailing_precision/lookahead/agg_panels/"
                    "overlap_depth are blocked-householder knobs)"
                )

    # -- serialization -----------------------------------------------------
    def to_dict(self) -> dict:
        """JSON-ready dict (the plan-DB entry payload)."""
        out = {
            "engine": self.engine,
            "block_size": self.block_size,
            "panel_impl": self.panel_impl,
            "trailing_precision": self.trailing_precision,
            "lookahead": self.lookahead,
            "agg_panels": self.agg_panels,
        }
        # Written only when set: plan payloads without a wire format /
        # pipeline depth stay byte-identical to the pre-round-18/19
        # schema, so shipped seed DBs and older readers keep working.
        if self.overlap_depth is not None:
            out["overlap_depth"] = self.overlap_depth
        if self.comms is not None:
            out["comms"] = self.comms
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        """Inverse of :meth:`to_dict`; validates via ``__post_init__``.
        Unknown keys are rejected — a future-versioned entry must fail
        the per-entry schema check (and be skipped by the DB loader),
        not half-load."""
        if not isinstance(d, dict):
            raise ValueError(f"plan payload must be a dict, got {type(d)}")
        known = {f.name for f in dataclasses.fields(cls)}
        extra = set(d) - known
        if extra:
            raise ValueError(f"unknown plan fields {sorted(extra)}")
        kwargs = dict(d)
        for int_field in ("block_size", "agg_panels", "overlap_depth"):
            if kwargs.get(int_field) is not None:
                kwargs[int_field] = int(kwargs[int_field])
        if "lookahead" in kwargs:
            kwargs["lookahead"] = bool(kwargs["lookahead"])
        return cls(**kwargs)

    def describe(self) -> str:
        """Compact human-readable spelling for logs/JSONL rows."""
        parts = [self.engine]
        if self.block_size is not None:
            parts.append(f"nb{self.block_size}")
        if self.panel_impl != "loop":
            parts.append(self.panel_impl)
        if self.trailing_precision:
            parts.append(f"tp-{self.trailing_precision}")
        if self.lookahead:
            parts.append(
                f"la{self.overlap_depth}" if self.overlap_depth else "la"
            )
        if self.agg_panels:
            parts.append(f"agg{self.agg_panels}")
        if self.comms:
            parts.append(f"w{self.comms}")
        return "+".join(parts)


#: The static default every tier runs without a plan — spelled out so
#: benchmarks and the DB can record "the baseline" as a real Plan.
DEFAULT_PLAN = Plan()
