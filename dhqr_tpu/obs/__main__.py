"""``python -m dhqr_tpu.obs <dump|xray|regress> ...``

The observability CLIs:

* ``dump [FILE ...] [--trace-id N] [--tenant T] [--bucket B]
  [--json]`` — render flight-recorder dump files (the JSONL the
  ``on_error`` hook writes when ``ObsConfig.auto_dump`` names a
  directory — docs/OPERATIONS.md "Reading a flight-recorder dump
  after a typed error"). With no FILE, every ``flight_*.jsonl`` under
  ``DHQR_OBS_DUMP`` (when it names a directory) is rendered, newest
  first. ``--tenant``/``--bucket`` keep only traces whose span path
  carries the attribute (a noisy multi-tenant dump file narrows to
  the tenant or bucket being triaged).
* ``xray [FILE ...] [--json]`` — the per-cache-key cost/memory table
  (round 15): renders the ``xray`` blocks found in bench summary JSON,
  artifact ``*.jsonl`` rows, or ``XrayStore.export_jsonl`` files
  (docs/OPERATIONS.md "Reading an xray table").
* ``pulse [FILE ...] [--json]`` — the per-label runtime-comms table
  (round 16): renders the ``pulse`` blocks (measured per-collective
  timing, shard skew, DHQR306 verdicts) found in artifact rows or
  ``PulseStore.export_jsonl`` files (docs/OPERATIONS.md "Reading a
  pulse report").

``--json`` on both table commands emits one JSON object per row
(JSONL) instead of the rendered table — the machine-readable surface
TPU session tooling scrapes without parsing aligned text.
* ``regress [--rules FILE] [--waivers FILE] [--repo DIR] [--json]`` —
  the perf-regression gate over the committed bench trajectory
  (``dhqr_tpu.obs.regress``; wired into tools/lint.sh). Exit 0 green,
  1 on regressions, 2 on malformed inputs.

All three command MODULES are jax-free by construction (``obs.trace``
docstring has the discipline) — but the ``-m dhqr_tpu.obs`` spelling
imports the dhqr_tpu package (and therefore jax) on the way in. On a
host where jax cannot even import, run the regress gate as a file:
``python dhqr_tpu/obs/regress.py`` (what tools/lint.sh does;
regress.py is stdlib-only and has its own ``__main__``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from dhqr_tpu.obs.recorder import format_dump, read_dump_file


def _default_files() -> "list[str]":
    dest = os.environ.get("DHQR_OBS_DUMP", "").strip()
    if not dest or dest == "stderr" or not os.path.isdir(dest):
        return []
    files = glob.glob(os.path.join(dest, "flight_*.jsonl"))
    return sorted(files, key=os.path.getmtime, reverse=True)


def _span_attr_match(record: dict, attr: str, wanted: str) -> bool:
    """Does any span in the record carry ``attr == wanted``? The
    recorder indexes per-trace; tenant/bucket live as span attributes
    (submit stamps the tenant, flush/dispatch the bucket label), so a
    CLI filter is a walk over the span path."""
    return any(str(span.get(attr)) == wanted
               for span in record.get("spans", [])
               if isinstance(span, dict) and attr in span)


def _cmd_dump(args) -> int:
    files = args.files or _default_files()
    if not files:
        print("no dump files given and none found under DHQR_OBS_DUMP",
              file=sys.stderr)
        return 2
    shown = 0
    for path in files:
        try:
            records = read_dump_file(path)
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
        for rec in records:
            if args.trace_id is not None \
                    and rec.get("trace_id") != args.trace_id:
                continue
            if args.tenant is not None \
                    and not _span_attr_match(rec, "tenant", args.tenant):
                continue
            if args.bucket is not None \
                    and not _span_attr_match(rec, "bucket", args.bucket):
                continue
            shown += 1
            if args.json:
                print(json.dumps(rec))
            else:
                print(format_dump(rec))
                print()
    if not shown:
        filters = [f"trace id {args.trace_id}"
                   if args.trace_id is not None else None,
                   f"tenant {args.tenant!r}"
                   if args.tenant is not None else None,
                   f"bucket {args.bucket!r}"
                   if args.bucket is not None else None]
        which = ", ".join(f for f in filters if f) or "records"
        print(f"no {which} found in {len(files)} file(s)", file=sys.stderr)
        return 1
    return 0


def _parse_records(path: str) -> "list[dict]":
    """Bench summary JSON (one object, possibly with stage rows inside)
    or a JSONL artifact: every parseable JSON object found."""
    records = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            text = fh.read()
    except OSError as e:
        print(f"cannot read {path}: {e}", file=sys.stderr)
        return records
    try:
        whole = json.loads(text)
        return whole if isinstance(whole, list) else [whole]
    # dhqr: ignore[DHQR006] format sniffing, not error handling: a file that is not ONE json document is parsed as JSONL below
    except ValueError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


def _cmd_table(args, kind: str) -> int:
    """Shared body of the ``xray`` and ``pulse`` table commands: parse
    the named files, extract the blocks, render the aligned table or
    (``--json``) one JSON object per row."""
    if kind == "xray":
        from dhqr_tpu.obs.xray import format_table, rows_from_json
    else:
        from dhqr_tpu.obs.pulse import format_table, rows_from_json

    if not args.files:
        print(f"obs {kind}: name the file(s) to render — a bench "
              "summary JSON, an artifact *.jsonl, or a store export",
              file=sys.stderr)
        return 2
    rows = []
    for path in args.files:
        rows.extend(rows_from_json(_parse_records(path)))
    if not rows:
        print(f"no {kind} blocks found in {len(args.files)} file(s)",
              file=sys.stderr)
        return 1
    if args.json:
        for row in rows:
            print(json.dumps(row))
    else:
        print(format_table(rows))
    return 0


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dhqr_tpu.obs",
        description="Observability CLIs (dhqr-obs): flight dumps, the "
        "xray cost/memory table, the perf-regression gate.")
    sub = parser.add_subparsers(dest="command")

    dump = sub.add_parser(
        "dump", help="render flight dump files as span paths")
    dump.add_argument("files", nargs="*", metavar="FILE",
                      help="flight JSONL file(s); default: every "
                      "flight_*.jsonl under $DHQR_OBS_DUMP")
    dump.add_argument("--trace-id", type=int, default=None,
                      help="only this trace id")
    dump.add_argument("--tenant", default=None,
                      help="only traces whose span path names this "
                      "tenant (the submit span's tenant attribute)")
    dump.add_argument("--bucket", default=None,
                      help="only traces whose span path touches this "
                      "bucket label (e.g. 64x16:float32)")
    dump.add_argument("--json", action="store_true",
                      help="raw JSON records instead of formatted paths")

    xray = sub.add_parser(
        "xray", help="render the per-cache-key cost/memory table from "
        "bench summaries / artifact rows / XrayStore exports")
    xray.add_argument("files", nargs="*", metavar="FILE")
    xray.add_argument("--json", action="store_true",
                      help="one JSON row per key instead of the table")

    pulse = sub.add_parser(
        "pulse", help="render the per-label runtime-comms table "
        "(measured collectives, shard skew, DHQR306) from artifact "
        "rows / PulseStore exports")
    pulse.add_argument("files", nargs="*", metavar="FILE")
    pulse.add_argument("--json", action="store_true",
                       help="one JSON row per label instead of the table")

    regress = sub.add_parser(
        "regress", help="perf-regression gate over the committed bench "
        "trajectory (exit 1 on regressions)")
    regress.add_argument("--repo", default=None)
    regress.add_argument("--rules", default=None)
    regress.add_argument("--waivers", default=None)
    regress.add_argument("--json", action="store_true")
    regress.add_argument("--prune-waivers", action="store_true",
                         help="rewrite the waivers file dropping stale "
                         "entries (matching no current failure), then "
                         "gate against the pruned file")

    args = parser.parse_args(argv)
    if args.command == "dump":
        return _cmd_dump(args)
    if args.command in ("xray", "pulse"):
        return _cmd_table(args, args.command)
    if args.command == "regress":
        from dhqr_tpu.obs import regress as _regress

        argv2 = []
        for flag in ("repo", "rules", "waivers"):
            if getattr(args, flag):
                argv2 += [f"--{flag}", getattr(args, flag)]
        if args.json:
            argv2.append("--json")
        if args.prune_waivers:
            argv2.append("--prune-waivers")
        return _regress.main(argv2)
    parser.error("a command is required (dump | xray | pulse | regress)")
    return 2


if __name__ == "__main__":
    sys.exit(main())
