"""``python -m dhqr_tpu.obs dump [FILE ...] [--trace-id N] [--json]``

Render flight-recorder dump files (the JSONL the ``on_error`` hook
writes when ``ObsConfig.auto_dump`` names a directory — see
docs/OPERATIONS.md "Reading a flight-recorder dump after a typed
error"). With no FILE, every ``flight_*.jsonl`` under ``DHQR_OBS_DUMP``
(when it names a directory) is rendered, newest first.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from dhqr_tpu.obs.recorder import format_dump, read_dump_file


def _default_files() -> "list[str]":
    dest = os.environ.get("DHQR_OBS_DUMP", "").strip()
    if not dest or dest == "stderr" or not os.path.isdir(dest):
        return []
    files = glob.glob(os.path.join(dest, "flight_*.jsonl"))
    return sorted(files, key=os.path.getmtime, reverse=True)


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dhqr_tpu.obs",
        description="Flight-recorder dump tools (dhqr-obs).")
    sub = parser.add_subparsers(dest="command")
    dump = sub.add_parser(
        "dump", help="render flight dump files as span paths")
    dump.add_argument("files", nargs="*", metavar="FILE",
                      help="flight JSONL file(s); default: every "
                      "flight_*.jsonl under $DHQR_OBS_DUMP")
    dump.add_argument("--trace-id", type=int, default=None,
                      help="only this trace id")
    dump.add_argument("--json", action="store_true",
                      help="raw JSON records instead of formatted paths")
    args = parser.parse_args(argv)
    if args.command != "dump":
        parser.error("a command is required (dump)")

    files = args.files or _default_files()
    if not files:
        print("no dump files given and none found under DHQR_OBS_DUMP",
              file=sys.stderr)
        return 2
    shown = 0
    for path in files:
        try:
            records = read_dump_file(path)
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
        for rec in records:
            if args.trace_id is not None \
                    and rec.get("trace_id") != args.trace_id:
                continue
            shown += 1
            if args.json:
                print(json.dumps(rec))
            else:
                print(format_dump(rec))
                print()
    if not shown:
        which = f"trace id {args.trace_id}" if args.trace_id is not None \
            else "records"
        print(f"no {which} found in {len(files)} file(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
