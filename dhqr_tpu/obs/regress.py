"""dhqr-regress: the perf-regression gate over the committed bench trajectory.

``python -m dhqr_tpu.obs regress`` parses the repository's committed
measurement trajectory — the driver's ``BENCH_r*.json`` round records
and every ``benchmarks/results/*.jsonl`` artifact row — keys rows by
(metric, stage, platform, device_kind), applies the declarative
tolerance rules in ``benchmarks/regress_rules.json``, and exits
nonzero with a per-key verdict table when a round's artifacts got
WORSE than the trajectory allows (a throughput floor under the best
prior round, a residual above the accuracy bar, armed-observability
overhead past its budget). Wired into ``tools/lint.sh``, so every PR
lands against a machine-checked baseline instead of a hand-read diff
— the same promotion dhqr-lint made for static invariants.

Deliberately **stdlib-only** (no jax, no package deps beyond this
file): the gate must run in any python, including one where backend
bring-up would hang — the obs-CLI discipline (``obs.trace`` module
docstring). On a host where jax cannot even IMPORT, invoke this file
directly (``python dhqr_tpu/obs/regress.py`` — the tools/lint.sh
spelling; it has its own ``__main__``): the ``-m dhqr_tpu.obs``
convenience spelling imports the dhqr_tpu package, which pulls jax.
tests/test_regress.py pins the import-without-jax property by loading
this file with jax import-blocked.

Rule kinds (``benchmarks/regress_rules.json``; docs/DESIGN.md "Device
observability" carries the schema):

* ``min_ratio_vs_best_prior`` — group matching rows by ``key_by``
  fields; within each group, the best value of the LATEST round must
  be >= ``min_ratio`` x the best value of any PRIOR round. Groups with
  data from fewer than two rounds SKIP (the gate bites as the
  trajectory grows, it never fails vacuously).
* ``min_value`` / ``max_value`` — every matching row's ``field`` (or
  every field matching ``field_prefix``) must sit on the right side of
  the bound.
* ``require_true`` — every matching row's ``field`` must be truthy
  (verdict booleans).

Row selection: ``select.metric`` / ``metric_prefix`` /
``metric_suffix``, plus ``where`` (field must be in the listed values;
``null`` in the list accepts an absent field) and ``where_not`` (field
must NOT be in the listed values; absent passes).

Deliberate trade-offs are WAIVED, not deleted:
``benchmarks/regress_waivers.json`` lists ``{rule, key, reason}``
entries — the dhqr-lint-baseline mechanism transplanted — and the
verdict table prints the reason next to every WAIVED key, so an
accepted regression stays visible in every run instead of silently
absorbed. Stale waivers (matching nothing) are reported, and
``--prune-waivers`` (round 16) rewrites the file without them — the
``analysis check --prune-baseline`` hygiene, transplanted.

Row vintage: rows missing ``schema_version`` are treated as v0 (the
pre-round-15 artifact shape); rows missing ``round`` inherit the
``BENCH_r<N>`` filename's round or vintage 0 (the round-3 probe
artifacts predate the tag). TPU rows missing ``device_kind`` default
to "TPU v5 lite" — every committed TPU artifact was measured on the
axon v5e (the bench._best_recorded_tpu convention).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: Rows missing an explicit schema_version are this vintage (the
#: pre-round-15 artifact shape). Bump SCHEMA_VERSION in bench.py when
#: the row shape changes incompatibly; the parser here keys on it.
SCHEMA_V0 = 0

#: The documented default chip for committed TPU rows that predate the
#: device_kind field (bench._best_recorded_tpu applies the same rule).
_TPU_DEFAULT_KIND = "TPU v5 lite"

_BENCH_FILE_RE = re.compile(r"BENCH_r(\d+)\.json$")


# ------------------------------------------------------------- trajectory

def _rows_from_jsonl(path: str):
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict):
            yield row


def _rows_from_bench_json(path: str):
    """BENCH_r<N>.json: the driver's round record — its ``tail`` field
    interleaves stderr markers with the bench's emitted JSON lines;
    every parseable JSON object in it is a trajectory row, defaulting
    its round to the filename's."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return
    m = _BENCH_FILE_RE.search(os.path.basename(path))
    file_round = int(m.group(1)) if m else None
    for line in str(data.get("tail", "")).splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict):
            if file_round is not None:
                row.setdefault("round", file_round)
            yield row


def collect_trajectory(repo: str) -> "list[dict]":
    """Every committed trajectory row, normalized: ``_round`` (int
    vintage, 0 when untagged), ``_schema`` (schema_version, v0 when
    absent), ``_source`` (display basename), device_kind defaulted for
    TPU rows."""
    rows = []
    sources = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        sources.append((path, _rows_from_bench_json(path)))
    results_dir = os.path.join(repo, "benchmarks", "results")
    for path in sorted(glob.glob(os.path.join(results_dir, "*.jsonl"))):
        sources.append((path, _rows_from_jsonl(path)))
    for path, it in sources:
        base = os.path.basename(path)
        for row in it:
            row = dict(row)
            try:
                row["_round"] = int(row.get("round", 0) or 0)
            except (TypeError, ValueError):
                row["_round"] = 0
            try:
                row["_schema"] = int(row.get("schema_version", SCHEMA_V0))
            except (TypeError, ValueError):
                row["_schema"] = SCHEMA_V0
            row["_source"] = base
            if not row.get("device_kind") and row.get("platform"):
                # Vintage rows predate the device_kind field: TPU rows
                # were all measured on the axon v5e (the documented
                # bench._best_recorded_tpu default); other platforms
                # key on the platform name itself.
                row["device_kind"] = _TPU_DEFAULT_KIND \
                    if row["platform"] == "tpu" else row["platform"]
            rows.append(row)
    return rows


# ------------------------------------------------------------------ rules

class RuleError(ValueError):
    """A malformed rules/waivers file — exit 2, never a silent green."""


def _in_values(row_value, values, present: bool) -> bool:
    """Is ``row_value`` one of ``values``? ``null`` in the list accepts
    an ABSENT field (and an explicit JSON null)."""
    if not present or row_value is None:
        return None in values
    return row_value in values


def _matches(rule: dict, row: dict) -> bool:
    sel = rule.get("select", {})
    metric = str(row.get("metric", ""))
    if "metric" in sel and metric != sel["metric"]:
        return False
    if "metric_prefix" in sel and not metric.startswith(
            sel["metric_prefix"]):
        return False
    if "metric_suffix" in sel and not metric.endswith(
            sel["metric_suffix"]):
        return False
    if "metric" not in sel and "metric_prefix" not in sel \
            and "metric_suffix" not in sel:
        raise RuleError(
            f"rule {rule.get('id')!r}: select needs metric, "
            "metric_prefix or metric_suffix")
    for field, values in (sel.get("where") or {}).items():
        values = values if isinstance(values, list) else [values]
        if not _in_values(row.get(field), values, field in row):
            return False
    for field, values in (sel.get("where_not") or {}).items():
        values = values if isinstance(values, list) else [values]
        if field in row and row.get(field) in values:
            return False
    return True


def _key_of(rule: dict, row: dict) -> str:
    fields = rule.get("key_by") or ["metric", "stage", "platform",
                                    "device_kind"]
    return "|".join(str(row.get(f, "-")) for f in fields)


class Verdict:
    """One per-key outcome: PASS / FAIL / SKIP (plus WAIVED applied in
    :func:`apply_waivers`)."""

    def __init__(self, rule_id: str, key: str, status: str, detail: str,
                 reason: str = "") -> None:
        self.rule_id = rule_id
        self.key = key
        self.status = status
        self.detail = detail
        self.reason = reason

    def to_json(self) -> dict:
        out = {"rule": self.rule_id, "key": self.key,
               "status": self.status, "detail": self.detail}
        if self.reason:
            out["reason"] = self.reason
        return out


def _fields_of(rule: dict, row: dict) -> "list[tuple[str, object]]":
    if "field" in rule:
        if rule["field"] in row:
            return [(rule["field"], row[rule["field"]])]
        return []
    prefix = rule.get("field_prefix")
    if not prefix:
        raise RuleError(
            f"rule {rule.get('id')!r}: needs field or field_prefix")
    return sorted((k, v) for k, v in row.items() if k.startswith(prefix))


def _check_bound(rule: dict, rows: "list[dict]") -> "list[Verdict]":
    """Bound/boolean rules, ONE verdict per key: the worst matching
    row decides (a trajectory re-emits the same measurement many times
    — banked rows, best-so-far summaries — and a verdict per row would
    bury the table in duplicates)."""
    kind = rule["kind"]
    # key -> (worst_value, detail_row, row_count)
    worst: "dict[str, tuple]" = {}
    for row in rows:
        for name, value in _fields_of(rule, row):
            key = _key_of(rule, row) + f"|{name}"
            if kind == "require_true":
                rank = 0 if value else 1  # any falsy row wins (worst)
            elif not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue  # non-numeric field under a numeric bound
            else:
                rank = value if kind == "max_value" else -value
            prev = worst.get(key)
            count = 1 if prev is None else prev[4] + 1
            if prev is None or rank > prev[0]:
                worst[key] = (rank, name, value, row, count)
            else:
                worst[key] = prev[:4] + (count,)
    out = []
    for key in sorted(worst):
        _rank, name, value, row, count = worst[key]
        of = f", worst of {count} rows" if count > 1 else ""
        if kind == "require_true":
            out.append(Verdict(
                rule["id"], key, "PASS" if value else "FAIL",
                f"{name}={value!r} ({row['_source']}{of})"))
            continue
        bound = float(rule["max"] if kind == "max_value"
                      else rule["min"])
        ok = value <= bound if kind == "max_value" else value >= bound
        cmp = "<=" if kind == "max_value" else ">="
        out.append(Verdict(
            rule["id"], key, "PASS" if ok else "FAIL",
            f"{name}={value:g} {cmp} {bound:g} "
            f"(round {row['_round']}, {row['_source']}{of})"))
    return out


def _check_ratio(rule: dict, rows: "list[dict]") -> "list[Verdict]":
    value_field = rule.get("value_field", "value")
    min_ratio = float(rule["min_ratio"])
    groups: "dict[str, list[dict]]" = {}
    for row in rows:
        if isinstance(row.get(value_field), (int, float)) \
                and not isinstance(row.get(value_field), bool):
            groups.setdefault(_key_of(rule, row), []).append(row)
    out = []
    for key in sorted(groups):
        grows = groups[key]
        rounds = sorted({r["_round"] for r in grows})
        if len(rounds) < 2:
            out.append(Verdict(
                rule["id"], key, "SKIP",
                f"only round {rounds[0]} has qualifying rows"))
            continue
        latest = rounds[-1]
        best_latest = max(r[value_field] for r in grows
                          if r["_round"] == latest)
        best_prior = max(r[value_field] for r in grows
                         if r["_round"] < latest)
        if best_prior <= 0:
            out.append(Verdict(rule["id"], key, "SKIP",
                               f"best prior value {best_prior:g} <= 0"))
            continue
        ratio = best_latest / best_prior
        out.append(Verdict(
            rule["id"], key, "PASS" if ratio >= min_ratio else "FAIL",
            f"round {latest}: {best_latest:g} = {ratio:.3f}x best prior "
            f"{best_prior:g} (floor {min_ratio:g}x)"))
    return out


_RULE_KINDS = {
    "min_ratio_vs_best_prior": _check_ratio,
    "min_value": _check_bound,
    "max_value": _check_bound,
    "require_true": _check_bound,
}


def evaluate(rules: dict, rows: "list[dict]") -> "list[Verdict]":
    if not isinstance(rules, dict) or not isinstance(
            rules.get("rules"), list):
        raise RuleError("rules file must be {'version': ..., 'rules': [...]}")
    verdicts = []
    for rule in rules["rules"]:
        if not rule.get("id"):
            raise RuleError(f"rule without id: {rule!r}")
        kind = rule.get("kind")
        checker = _RULE_KINDS.get(kind)
        if checker is None:
            raise RuleError(
                f"rule {rule['id']!r}: unknown kind {kind!r} "
                f"(have {sorted(_RULE_KINDS)})")
        matching = [r for r in rows if _matches(rule, r)]
        if not matching:
            verdicts.append(Verdict(rule["id"], "-", "SKIP",
                                    "no trajectory rows match"))
            continue
        verdicts.extend(checker(rule, matching))
    return verdicts


def apply_waivers(verdicts: "list[Verdict]",
                  waivers: dict) -> "list[str]":
    """Convert FAILs with a matching ``{rule, key}`` waiver to WAIVED
    (reason attached); returns the STALE waiver descriptions — entries
    that matched no failing verdict — so a fixed regression's waiver is
    flagged for removal rather than lying in wait."""
    entries = list((waivers or {}).get("waivers", []))
    for entry in entries:
        if not entry.get("rule") or not entry.get("key") \
                or not entry.get("reason"):
            raise RuleError(
                f"waiver needs rule, key and reason: {entry!r}")
    used = [False] * len(entries)
    for verdict in verdicts:
        if verdict.status != "FAIL":
            continue
        for i, entry in enumerate(entries):
            if entry["rule"] == verdict.rule_id \
                    and entry["key"] == verdict.key:
                verdict.status = "WAIVED"
                verdict.reason = entry["reason"]
                used[i] = True
                break
    return [f"{e['rule']} {e['key']}" for e, u in zip(entries, used)
            if not u]


def prune_waivers(waivers_path: str,
                  verdicts: "list[Verdict]") -> "tuple[int, int]":
    """Rewrite the waivers file dropping entries that match no FAILING
    or WAIVED verdict — the ``findings.prune_baseline`` mechanism
    transplanted to the perf gate (round 16): a regression that was
    re-measured away leaves its waiver stale, and a stale waiver is a
    loaded gun (it would silently absorb the NEXT regression of that
    key). Returns ``(kept, removed)``. The comment block and any other
    top-level fields are preserved; a missing file is ``(0, 0)``."""
    if not os.path.exists(waivers_path):
        return 0, 0
    with open(waivers_path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    entries = list(data.get("waivers", []))
    # A waiver is LIVE iff some verdict it names is FAIL or WAIVED
    # (apply_waivers flips matched FAILs to WAIVED, so after a gate
    # run the live ones read WAIVED; pruning from raw verdicts —
    # before waivers applied — sees them as FAIL).
    live_keys = {(v.rule_id, v.key) for v in verdicts
                 if v.status in ("FAIL", "WAIVED")}
    kept = [e for e in entries
            if (e.get("rule"), e.get("key")) in live_keys]
    removed = len(entries) - len(kept)
    if removed:
        data["waivers"] = kept
        tmp = waivers_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, waivers_path)
    return len(kept), removed


def format_verdicts(verdicts: "list[Verdict]") -> str:
    """The readable per-key verdict table (FAILs first, then WAIVED,
    then PASS, SKIPs last)."""
    order = {"FAIL": 0, "WAIVED": 1, "PASS": 2, "SKIP": 3}
    rows = [("status", "rule", "key", "detail")]
    for v in sorted(verdicts,
                    key=lambda v: (order.get(v.status, 9), v.rule_id,
                                   v.key)):
        detail = v.detail + (f"  [waived: {v.reason}]" if v.reason else "")
        rows.append((v.status, v.rule_id, v.key, detail))
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(
            [r[0].ljust(widths[0]), r[1].ljust(widths[1]),
             r[2].ljust(widths[2]), r[3]]).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths)
                         + "  " + "-" * 6)
    return "\n".join(lines)


def run_gate(repo: str, rules_path: str,
             waivers_path: "str | None" = None,
             as_json: bool = False,
             prune: bool = False,
             out=None) -> int:
    """The CLI body: 0 green, 1 regression(s), 2 malformed inputs.
    ``prune=True`` first rewrites the waivers file dropping stale
    entries (:func:`prune_waivers`), then gates against the pruned
    file — mirroring ``analysis check --prune-baseline``."""
    out = out or sys.stdout
    try:
        with open(rules_path, "r", encoding="utf-8") as fh:
            rules = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"regress: cannot load rules {rules_path}: {e}",
              file=sys.stderr)
        return 2
    rows = collect_trajectory(repo)
    if not rows:
        print(f"regress: no trajectory rows under {repo} "
              "(BENCH_r*.json / benchmarks/results/*.jsonl)",
              file=sys.stderr)
        return 2
    try:
        verdicts = evaluate(rules, rows)
    except RuleError as e:
        print(f"regress: {e}", file=sys.stderr)
        return 2
    if prune:
        if not waivers_path:
            print("regress: --prune-waivers requires a waivers file",
                  file=sys.stderr)
            return 2
        try:
            # The same verdicts feed the prune and the gate below
            # (apply_waivers only flips FAIL -> WAIVED afterwards, and
            # the prune treats both as live).
            kept, removed = prune_waivers(waivers_path, verdicts)
        except (ValueError, OSError) as e:
            print(f"regress: cannot prune waivers {waivers_path}: {e}",
                  file=sys.stderr)
            return 2
        print(f"regress: waivers pruned — {removed} stale "
              f"entr{'y' if removed == 1 else 'ies'} removed, "
              f"{kept} kept", file=sys.stderr)
    waivers = {}
    if waivers_path and os.path.exists(waivers_path):
        try:
            with open(waivers_path, "r", encoding="utf-8") as fh:
                waivers = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"regress: cannot load waivers {waivers_path}: {e}",
                  file=sys.stderr)
            return 2
    try:
        stale = apply_waivers(verdicts, waivers)
    except RuleError as e:
        print(f"regress: {e}", file=sys.stderr)
        return 2
    failed = sum(1 for v in verdicts if v.status == "FAIL")
    if as_json:
        print(json.dumps({
            "rows": len(rows), "failed": failed,
            "waived": sum(1 for v in verdicts if v.status == "WAIVED"),
            "stale_waivers": stale,
            "verdicts": [v.to_json() for v in verdicts],
        }, indent=2), file=out)
    else:
        print(format_verdicts(verdicts), file=out)
        counts = {}
        for v in verdicts:
            counts[v.status] = counts.get(v.status, 0) + 1
        summary = ", ".join(f"{n} {s}" for s, n in sorted(counts.items()))
        print(f"\nregress: {len(rows)} trajectory rows -> {summary}",
              file=out)
        for s in stale:
            print(f"regress: STALE waiver (matched no failure): {s}",
                  file=out)
    return 1 if failed else 0


def main(argv=None) -> int:
    default_repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    parser = argparse.ArgumentParser(
        prog="python -m dhqr_tpu.obs regress",
        description="dhqr-regress: perf-regression gate over the "
        "committed bench trajectory (jax-free).")
    parser.add_argument("--repo", default=default_repo,
                        help="repository root holding BENCH_r*.json and "
                        "benchmarks/results/ (default: this checkout)")
    parser.add_argument("--rules", default=None,
                        help="rules JSON (default: "
                        "<repo>/benchmarks/regress_rules.json)")
    parser.add_argument("--waivers", default=None,
                        help="waivers JSON (default: "
                        "<repo>/benchmarks/regress_waivers.json, if "
                        "present)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable verdicts")
    parser.add_argument("--prune-waivers", action="store_true",
                        help="rewrite the waivers file dropping entries "
                        "that match no current failure, then gate "
                        "against the pruned file (mirrors `analysis "
                        "check --prune-baseline`)")
    args = parser.parse_args(argv)
    rules = args.rules or os.path.join(args.repo, "benchmarks",
                                       "regress_rules.json")
    waivers = args.waivers or os.path.join(args.repo, "benchmarks",
                                           "regress_waivers.json")
    return run_gate(args.repo, rules, waivers_path=waivers,
                    as_json=args.json, prune=args.prune_waivers)


if __name__ == "__main__":
    sys.exit(main())
