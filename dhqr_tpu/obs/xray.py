"""dhqr-xray: compiled-program cost/memory introspection + MFU/roofline.

Round 15's device-level half of observability. PR 9 (trace/metrics/
flight recorder) answers *what happened to a request*; this module
answers *where the flops and bytes go inside each compiled executable*
— the evidence ROADMAP items 1–2 need before the next TPU window, and
the per-chip fraction-of-peak accounting the TPU linear-algebra paper
(arXiv 2112.09017) reports its results in.

One :class:`XrayReport` per compiled program pairs three sources:

* the executable's own ``cost_analysis()`` / ``memory_analysis()``
  (compat-shimmed in ``utils/compat.py`` — jax-0.4 list shapes
  normalized, unsupported backends degrade to ``None`` + reason,
  NEVER a raised exception on the compile path);
* the analytic per-engine flop model (``obs.flops`` closed forms) —
  the *useful-work* numerator, so ``measured / analytic`` reads as
  padding+overhead and ``analytic / seconds / peak`` is the honest MFU;
* the ``device_kind -> peak TF/s / HBM GB/s`` table
  (``utils/platform``) — the denominators, giving the roofline
  position: arithmetic intensity vs the ridge point decides
  compute- vs memory-bound, and ``min(peak, intensity * bw)`` is the
  ceiling a perfect kernel could reach.

Capture discipline (the faults/obs pattern): the serving stack's
single compile entry (``serve.cache.ExecutableCache.get_or_compile``)
consults :func:`active` ON ITS MISS PATH ONLY — disarmed, warm serving
never reads even the module global; armed, each *compile* (already
seconds-scale) pays one sub-millisecond introspection and warm
dispatches pay nothing, so armed capture holds the <= 5% overhead bar
by construction (pinned by benchmarks/serving_xray.py). Arm via
``ObsConfig.xray`` / ``DHQR_OBS_XRAY`` + :func:`dhqr_tpu.obs.arm`, or
scope with :func:`captured`. bench.py captures its stage programs
directly through :func:`report_for` (no arming — its compiles are
counted in single digits).

This module imports no jax at module level (the table renderer and
report maths must work in any python); only :func:`report_for` touches
the compat shims, and only when handed a live executable.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Optional

from dhqr_tpu.obs import flops as _flops
from dhqr_tpu.utils import lockwitness as _lockwitness

__all__ = [
    "XrayReport",
    "XrayStore",
    "active",
    "arm",
    "captured",
    "disarm",
    "format_table",
    "report_for",
    "rows_from_json",
]


@dataclasses.dataclass(frozen=True)
class XrayReport:
    """Cost/memory introspection of ONE compiled program.

    ``measured``/``memory`` are the compat-normalized XLA analyses (or
    None, with the refusal spelled out in ``measured_unavailable`` /
    ``memory_unavailable`` respectively — "null with reason", never
    silently absent; the two analyses can fail independently). ``analytic_flops`` is
    the closed-form useful-work count for the program's engine/shape
    (None for programs the model does not cover). Roofline fields are
    populated when both the device table knows the chip AND the
    measured byte count exists; otherwise ``roofline_bound`` is None
    and ``roofline_reason`` says why. MFU needs a wall-time, which a
    compile-time capture does not have — :meth:`mfu` derives it when a
    caller pairs the report with measured seconds."""

    key: str
    analytic_flops: "float | None" = None
    measured: "dict | None" = None
    memory: "dict | None" = None
    measured_unavailable: "str | None" = None
    memory_unavailable: "str | None" = None
    device_kind: "str | None" = None
    dtype: "str | None" = None
    peak_tflops: "float | None" = None
    hbm_gbps: "float | None" = None
    intensity_flops_per_byte: "float | None" = None
    ridge_flops_per_byte: "float | None" = None
    roofline_bound: "str | None" = None
    roofline_reason: "str | None" = None
    ceiling_gflops: "float | None" = None
    compile_seconds: "float | None" = None
    # Round 16 (dhqr-pulse): the COMMS side of the roofline — the
    # ``netmodel.comms_roofline`` block a paired pulse measurement
    # fills (comms_s / compute_s / comms_fraction / comms_bound /
    # effective_gbps). None for programs with no comms measurement;
    # to_json then stamps the reason so artifact rows stay
    # null-with-reason on both halves of the roofline.
    comms: "dict | None" = None

    def mfu(self, seconds: float) -> "float | None":
        """Analytic-flops MFU for one execution taking ``seconds``
        (None without a known peak or analytic count)."""
        if not seconds or not self.analytic_flops or not self.peak_tflops:
            return None
        return (self.analytic_flops / seconds) / (self.peak_tflops * 1e12)

    def achieved_gflops(self, seconds: float) -> "float | None":
        if not seconds or not self.analytic_flops:
            return None
        return self.analytic_flops / seconds / 1e9

    def to_json(self) -> dict:
        """JSON-ready record — the shape bench summaries, artifact rows
        and the ``obs xray`` table all speak."""
        out = {"key": self.key, "analytic_flops": self.analytic_flops}
        if self.measured is not None:
            out["measured_cost_analysis"] = {
                "flops": self.measured.get("flops"),
                "bytes_accessed": self.measured.get("bytes accessed"),
            }
        else:
            out["measured_cost_analysis"] = None
            out["measured_unavailable"] = (
                self.measured_unavailable or "no analysis captured")
        if self.memory is not None:
            out["memory"] = dict(self.memory)
        else:
            out["memory"] = None
            out["memory_unavailable"] = (
                self.memory_unavailable or "no analysis captured")
        for field in ("device_kind", "dtype", "peak_tflops", "hbm_gbps",
                      "intensity_flops_per_byte", "ridge_flops_per_byte",
                      "roofline_bound", "roofline_reason",
                      "ceiling_gflops", "compile_seconds"):
            val = getattr(self, field)
            if val is not None:
                out[field] = val
        if self.roofline_bound is None and "roofline_reason" not in out:
            out["roofline_reason"] = "no roofline basis captured"
        out.setdefault("roofline_bound", None)
        if self.comms is not None:
            out["comms"] = dict(self.comms)
        else:
            out["comms"] = None
            out["comms_reason"] = ("no paired pulse measurement for this "
                                   "program (no collectives measured, "
                                   "single-device, or DHQR_OBS_PULSE "
                                   "disarmed)")
        return out


def _roofline(analytic, measured, peak_tflops, hbm_gbps):
    """(intensity, ridge, bound, reason, ceiling) from whatever subset
    of the basis exists. Intensity uses the ANALYTIC flop count over
    the MEASURED bytes: useful work per byte actually moved — the
    padding-honest reading (padded flops would flatter intensity)."""
    flops = analytic if analytic else (
        measured.get("flops") if measured else None)
    bytes_accessed = measured.get("bytes accessed") if measured else None
    if not flops or not bytes_accessed:
        return (None, None, None,
                "cost_analysis byte count unavailable", None)
    intensity = flops / bytes_accessed
    if not peak_tflops or not hbm_gbps:
        return (round(intensity, 3), None, None,
                "no published peak/bandwidth for this device_kind",
                None)
    ridge = (peak_tflops * 1e12) / (hbm_gbps * 1e9)
    bound = "compute" if intensity >= ridge else "memory"
    ceiling = min(peak_tflops * 1e3, intensity * hbm_gbps)
    return (round(intensity, 3), round(ridge, 3), bound, None,
            round(ceiling, 1))


def _analytic_for_key(key) -> "float | None":
    """Closed-form flop count for a serve :class:`CacheKey` (duck-typed
    on its fields so this module never imports serve); None for keys
    the model does not describe (bench's plain tuples pass analytic
    explicitly via :func:`report_for`)."""
    kind = getattr(key, "kind", None)
    batch = getattr(key, "batch", None)
    m, n = getattr(key, "m", None), getattr(key, "n", None)
    if None in (kind, batch, m, n):
        return None
    if kind == "qr":
        return _flops.batched_qr_flops(batch, m, n)
    if kind == "lstsq":
        return _flops.batched_lstsq_flops(
            batch, m, n, refine=getattr(key, "refine", 0) or 0)
    if kind == "sketch":
        # Round 17: the sketched serve kind — the key's sketch triple
        # carries s, and refine is the CGLS iteration count.
        sk = getattr(key, "sketch", None)
        if not sk:
            return None
        return batch * _flops.sketched_lstsq_flops(
            m, n, sk[0], refine=getattr(key, "refine", 0) or 0)
    return None


_DEVICE_KIND_CACHE: "list[tuple[str | None, str | None]]" = []


def _default_device_kind() -> "tuple[str | None, str | None]":
    """(device_kind, dtype-agnostic platform) of the default backend,
    probed lazily ONCE per process and only from capture paths where a
    backend necessarily exists (a compile just succeeded). Never
    raises; an unreachable backend reads as (None, None)."""
    if _DEVICE_KIND_CACHE:
        return _DEVICE_KIND_CACHE[0]
    try:
        import jax

        dev = jax.devices()[0]
        entry = (str(getattr(dev, "device_kind", None)),
                 str(getattr(dev, "platform", None)))
    # dhqr: ignore[DHQR006] introspection must never fail the compile that triggered it; an unprobeable backend reads as unknown-chip
    except Exception:
        entry = (None, None)
    _DEVICE_KIND_CACHE.append(entry)
    return entry


def report_for(key, compiled, *, analytic_flops: "float | None" = None,
               device_kind: "str | None" = None,
               dtype: "str | None" = None,
               compile_seconds: "float | None" = None,
               comms: "dict | None" = None) -> XrayReport:
    """Build the :class:`XrayReport` for one compiled executable.

    ``key`` is any display-able cache key (serve ``CacheKey``\\ s get
    their analytic flop count derived automatically; pass
    ``analytic_flops`` for anything else). Degrades field-by-field and
    never raises — this runs on compile paths."""
    from dhqr_tpu.utils.compat import (executable_cost_analysis,
                                       executable_memory_analysis)
    from dhqr_tpu.utils.platform import (device_hbm_gbps,
                                         device_peak_tflops)

    measured, reason = executable_cost_analysis(compiled)
    memory, mem_reason = executable_memory_analysis(compiled)
    if analytic_flops is None:
        analytic_flops = _analytic_for_key(key)
    if device_kind is None:
        device_kind, _platform = _default_device_kind()
    if dtype is None:
        dtype = str(getattr(key, "dtype", None) or "") or None
    peak = device_peak_tflops(device_kind, dtype or "float32") \
        if device_kind else None
    bw = device_hbm_gbps(device_kind) if device_kind else None
    intensity, ridge, bound, roof_reason, ceiling = _roofline(
        analytic_flops, measured, peak, bw)
    return XrayReport(
        key=str(key), analytic_flops=analytic_flops, measured=measured,
        memory=memory, measured_unavailable=reason,
        memory_unavailable=mem_reason,
        device_kind=device_kind, dtype=dtype, peak_tflops=peak,
        hbm_gbps=bw, intensity_flops_per_byte=intensity,
        ridge_flops_per_byte=ridge, roofline_bound=bound,
        roofline_reason=roof_reason, ceiling_gflops=ceiling,
        compile_seconds=(round(compile_seconds, 4)
                         if compile_seconds is not None else None),
        comms=comms,
    )


class XrayStore:
    """Bounded per-cache-key report store for one armed capture session.

    ``capture`` is called by the serve cache's compile path (under the
    cache lock, so a report's insertion order is its compile order);
    insertion past ``max_reports`` evicts the oldest (a serving tier
    must not grow introspection state per key forever — counted)."""

    def __init__(self, max_reports: int = 512) -> None:
        if max_reports < 1:
            raise ValueError(
                f"max_reports must be >= 1, got {max_reports}")
        self.max_reports = int(max_reports)
        self._lock = _lockwitness.make_lock("XrayStore._lock")
        self._reports: "dict[str, XrayReport]" = {}  # guarded by: _lock
        self._captures = 0
        self._unsupported = 0
        self._evicted = 0
        self._failed = 0

    def capture(self, key, compiled,
                compile_seconds: "float | None" = None) -> None:
        """Introspect one freshly compiled executable. Never raises."""
        try:
            report = report_for(key, compiled,
                                compile_seconds=compile_seconds)
        # dhqr: ignore[DHQR006] capture rides the serve compile path: introspection breakage must cost the report, never the executable
        except Exception:
            with self._lock:
                self._captures += 1
                self._failed += 1
            return
        with self._lock:
            self._captures += 1
            if report.measured is None:
                self._unsupported += 1
            self._reports[report.key] = report
            while len(self._reports) > self.max_reports:
                self._reports.pop(next(iter(self._reports)))
                self._evicted += 1

    def reports(self) -> "list[XrayReport]":
        """Resident reports in capture order (oldest first)."""
        with self._lock:
            return list(self._reports.values())

    def report(self, key) -> Optional[XrayReport]:
        with self._lock:
            return self._reports.get(str(key))

    def attach_comms(self, key, comms: dict) -> None:
        """Pair a pulse measurement's comms-roofline block into the
        resident report for ``key`` (round 16 — the serve dispatch
        seam calls this once, right after a label's pulse capture, so
        one table shows both sides of the roofline). A key with no
        resident report is a no-op: pairing is best-effort evidence,
        never a failure path."""
        with self._lock:
            rep = self._reports.get(str(key))
            if rep is not None:
                self._reports[str(key)] = dataclasses.replace(
                    rep, comms=dict(comms))

    def stats(self) -> dict:
        """The ``xray.*`` numbers the metrics registry exports."""
        with self._lock:
            return {
                "captures": self._captures,
                "reports": len(self._reports),
                "unsupported": self._unsupported,
                "evicted": self._evicted,
                "failed": self._failed,
                "capacity": self.max_reports,
            }

    def export_jsonl(self, path: str) -> int:
        """Append every resident report as one JSON line each (the
        file format ``python -m dhqr_tpu.obs xray`` renders); returns
        the number written."""
        reports = self.reports()
        with open(path, "a", encoding="utf-8") as fh:
            for rep in reports:
                fh.write(json.dumps({"xray": rep.to_json()}) + "\n")
        return len(reports)


# The one armed store (or None — the fast path); same module-global
# discipline as faults.harness / obs.trace.
_ACTIVE: "XrayStore | None" = None
_ARM_LOCK = _lockwitness.make_lock("xray._ARM_LOCK")


def arm(max_reports: int = 512) -> XrayStore:
    """Arm process-wide capture (normally reached via
    ``dhqr_tpu.obs.arm`` with ``ObsConfig.xray`` / ``DHQR_OBS_XRAY``)."""
    global _ACTIVE
    with _ARM_LOCK:
        _ACTIVE = XrayStore(max_reports=max_reports)
        return _ACTIVE


def disarm() -> None:
    global _ACTIVE
    with _ARM_LOCK:
        _ACTIVE = None


def active() -> Optional[XrayStore]:
    """The armed store, or None — THE hot-path read (the serve cache
    consults it on compile misses only)."""
    return _ACTIVE


class captured:
    """Scope an xray capture session (arm on entry, restore the
    previous store on exit; scopes nest):

    >>> with xray.captured() as store:
    ...     serve.prewarm(...)
    ...     store.reports()
    """

    def __init__(self, max_reports: int = 512) -> None:
        self._store = XrayStore(max_reports=max_reports)
        self._previous: "XrayStore | None" = None

    def __enter__(self) -> XrayStore:
        global _ACTIVE
        with _ARM_LOCK:
            self._previous = _ACTIVE
            _ACTIVE = self._store
        return self._store

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        with _ARM_LOCK:
            _ACTIVE = self._previous


# ------------------------------------------------------------------ table

def rows_from_json(records) -> "list[dict]":
    """Extract xray blocks from parsed JSON records (bench summaries,
    artifact rows, ``export_jsonl`` lines): any dict carrying an
    ``"xray"`` sub-dict or sub-LIST (the bench prewarm summary stamps
    the whole per-stage report list), or that IS a report (has
    ``analytic_flops``)."""
    rows = []
    for rec in records:
        if not isinstance(rec, dict):
            continue
        blk = rec.get("xray")
        blocks = blk if isinstance(blk, list) else [blk]
        matched = False
        for one in blocks:
            if isinstance(one, dict):
                matched = True
                row = dict(one)
                row.setdefault("key", rec.get("stage") or rec.get("metric")
                               or rec.get("key") or "?")
                rows.append(row)
        if not matched and "analytic_flops" in rec:
            rows.append(dict(rec))
    return rows


def _fmt_flops(value) -> str:
    if not isinstance(value, (int, float)):
        return "-"
    if value >= 1e12:
        return f"{value / 1e12:.2f}T"
    if value >= 1e9:
        return f"{value / 1e9:.2f}G"
    if value >= 1e6:
        return f"{value / 1e6:.2f}M"
    return f"{value:.0f}"


def format_table(rows: "list[dict]") -> str:
    """Aligned per-key table of xray rows (the ``obs xray`` CLI output).

    Columns: key, analytic flops, measured flops, bytes accessed,
    intensity (flop/byte), roofline bound, ceiling GF/s, MFU (when the
    row carries one), compile seconds, and — since round 16 — the comms
    side of the roofline (the paired pulse measurement's comms
    fraction, "-" for rows without one)."""
    header = ("key", "analytic", "measured", "bytes", "f/B", "bound",
              "ceilGF", "mfu", "compile_s", "f(comms)")
    table = [header]
    for row in rows:
        meas = row.get("measured_cost_analysis") or {}
        mfu = row.get("mfu")
        comms = row.get("comms") or {}
        table.append((
            str(row.get("key", "?"))[:48],
            _fmt_flops(row.get("analytic_flops")),
            _fmt_flops(meas.get("flops")),
            _fmt_flops(meas.get("bytes_accessed")),
            (f"{row['intensity_flops_per_byte']:.1f}"
             if isinstance(row.get("intensity_flops_per_byte"),
                           (int, float)) else "-"),
            str(row.get("roofline_bound") or "-"),
            (f"{row['ceiling_gflops']:.0f}"
             if isinstance(row.get("ceiling_gflops"), (int, float))
             else "-"),
            (f"{mfu:.4f}" if isinstance(mfu, (int, float)) else "-"),
            (f"{row['compile_seconds']:.2f}"
             if isinstance(row.get("compile_seconds"), (int, float))
             else "-"),
            (f"{comms['comms_fraction']:.2f}"
             if isinstance(comms.get("comms_fraction"), (int, float))
             else "-"),
        ))
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(
            c.ljust(w) if j == 0 else c.rjust(w)
            for j, (c, w) in enumerate(zip(r, widths))))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
