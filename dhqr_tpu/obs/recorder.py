"""Flight recorder: persist and render a failed request's span path.

When a future resolves as ``DeadlineExceeded`` or a guarded call
refuses ``IllConditioned``, the question is never "how many" (the
registry answers that) but "what happened to THIS request". The typed
error carries its trace id (``exc.trace_id`` / ``exc.trace_ids`` —
stamped by :meth:`~dhqr_tpu.obs.trace.TraceRecorder.attach`), the ring
buffer still holds the request's spans, and this module turns the two
into evidence:

* :func:`dump_error` — the in-process API: every affected trace id's
  full span path, JSON-ready;
* :func:`write_error_dump` — the ``on_error`` auto-dump hook's writer
  (``ObsConfig.auto_dump``): formatted to stderr, or appended as JSONL
  to ``<dir>/flight_<pid>.jsonl``;
* :func:`format_dump` — the human rendering ``python -m dhqr_tpu.obs
  dump`` prints (docs/OPERATIONS.md "Reading a flight-recorder dump"
  walks a real one).

Deliberately jax-free: rendering a dump from a crashed run must work
in any python, without backend bring-up.
"""

from __future__ import annotations

import json
import os
from typing import Iterable


def dump_error(exc: BaseException, recorder=None) -> "list[dict]":
    """Flight dumps for every trace id a typed error carries (empty
    when the error was raised untraced). ``recorder`` defaults to the
    armed one."""
    if recorder is None:
        from dhqr_tpu.obs import trace as _trace

        recorder = _trace.active()
    if recorder is None:
        return []
    tids = getattr(exc, "trace_ids", None) or ()
    if not tids and getattr(exc, "trace_id", None) is not None:
        tids = (exc.trace_id,)
    return [_error_record(recorder, exc, tid) for tid in tids]


def _error_record(recorder, exc: BaseException, trace_id: int) -> dict:
    rec = recorder.dump(trace_id)
    rec["error"] = type(exc).__name__
    rec["message"] = str(exc)[:500]
    return rec


def format_dump(record: dict) -> str:
    """One flight dump as readable lines: the error header, then the
    span path with per-hop deltas relative to the first span.

    >>> trace 17: DispatchFailed: device dispatch failed for ...
    >>>   +0.000s submit      kind=lstsq bucket=64x16:float32 ...
    >>>   +0.021s flush       reason=deadline wait_s=0.021 batch=4
    >>>   ...
    """
    spans = record.get("spans", [])
    header = f"trace {record.get('trace_id', '?')}"
    if record.get("error"):
        header += f": {record['error']}: {record.get('message', '')}"
    lines = [header]
    if not spans:
        lines.append("  (no spans resident — evicted from the ring, or "
                     "the request ran untraced)")
        return "\n".join(lines)
    t0 = spans[0].get("t", 0.0)
    for span in spans:
        attrs = " ".join(
            f"{k}={_compact(v)}" for k, v in span.items()
            if k not in ("trace_id", "seq", "t", "name"))
        lines.append(f"  +{span.get('t', t0) - t0:.3f}s "
                     f"{span.get('name', '?'):<12} {attrs}".rstrip())
    return "\n".join(lines)


def _compact(value) -> str:
    if isinstance(value, float):
        return f"{value:.4g}"
    text = str(value)
    return text if len(text) <= 120 else text[:117] + "..."


def write_error_dump(recorder, exc: BaseException,
                     trace_ids: Iterable[int], destination: str) -> None:
    """The ``on_error`` hook's writer: ``destination="stderr"`` prints
    the formatted path(s); anything else is a directory receiving one
    JSONL line per dump in ``flight_<pid>.jsonl`` (the file
    ``python -m dhqr_tpu.obs dump`` reads)."""
    records = [_error_record(recorder, exc, tid) for tid in trace_ids]
    if destination == "stderr":
        import sys

        for rec in records:
            print(format_dump(rec), file=sys.stderr, flush=True)
        return
    os.makedirs(destination, exist_ok=True)
    path = os.path.join(destination, f"flight_{os.getpid()}.jsonl")
    with open(path, "a", encoding="utf-8") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")


def read_dump_file(path: str) -> "list[dict]":
    """Parse a flight JSONL file; malformed lines are skipped with a
    count rather than failing the whole read (a dump cut off by a
    crash is still evidence)."""
    records, skipped = [], 0
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                skipped += 1
    if skipped:
        records.append({"trace_id": None, "spans": [],
                        "error": "DumpTruncated",
                        "message": f"{skipped} unparseable line(s) skipped"})
    return records
