"""dhqr-obs — request-scoped tracing, unified metrics, flight recorder.

Round 14's observability layer over the serving stack: the evidence
layer that turns "a future resolved ``DeadlineExceeded``" from a
counter increment into a reconstructable story.

    >>> from dhqr_tpu import obs
    >>> from dhqr_tpu.utils.config import ObsConfig
    >>> obs.arm(ObsConfig(enabled=True))        # or DHQR_OBS=1 + obs.arm()
    >>> fut = sched.submit("lstsq", A, b)       # fut.trace_id is minted
    >>> try:
    ...     fut.result()
    ... except dhqr_tpu.ServeError as e:
    ...     print(obs.recorder.format_dump(obs.flight_dump(e.trace_id)))
    trace 17: ...
      +0.000s submit      kind=lstsq bucket=64x16:float32 tenant=acme ...
      +0.021s flush       reason=deadline wait_s=0.021 batch=4
      +0.023s dispatch    key=lstsq:4x64x16 ...
      +0.024s retry       attempt=1 backoff_s=0.01 cause=DispatchFailed
      ...
      +0.141s resolve     outcome=DispatchFailed

    >>> obs.registry().snapshot()["serve.cache.hits"]   # unified metrics
    >>> obs.registry().export_prometheus()              # scrape format

Three pieces (each its own module):

* ``obs.trace`` — trace ids minted at admission and threaded through
  queue → coalesce → flush → retry/bisect → dispatch → resolve (and
  the sync ``batched_*`` / ``guarded_*`` paths), spans recorded on an
  injectable clock into a bounded ring buffer. Trace ids stay OUT of
  cache keys and compiled programs: warm paths are zero-recompile with
  tracing armed (key-parity pinned by tests/test_obs.py).
* ``obs.metrics`` — :class:`MetricsRegistry`: the four historical
  ``stats()`` surfaces (scheduler, cache, faults, tune plan gate) plus
  the numeric ladder under stable dotted names, with JSONL and
  Prometheus-text exporters. The old dict shapes remain as thin views
  over the same counters.
* ``obs.recorder`` — the flight recorder: typed errors carry their
  trace id(s); :func:`flight_dump` / ``python -m dhqr_tpu.obs dump``
  reconstruct the request's full span path, and the ``on_error`` hook
  (``ObsConfig.auto_dump``) persists it the moment the error resolves.
* ``obs.xray`` + ``obs.flops`` (round 15, dhqr-xray) — device-level
  observability: compiled-program ``cost_analysis()`` /
  ``memory_analysis()`` capture at the serve cache's compile entry
  (armed via ``ObsConfig.xray`` / ``DHQR_OBS_XRAY``), paired with the
  analytic per-engine flop model and the ``utils/platform`` peak
  table into :class:`XrayReport`\\ s (MFU + roofline position);
  ``python -m dhqr_tpu.obs xray`` renders the per-key table.
* ``obs.regress`` (round 15) — the jax-free perf-regression gate over
  the committed bench trajectory: ``python -m dhqr_tpu.obs regress``
  (wired into tools/lint.sh) applies ``benchmarks/regress_rules.json``
  and exits nonzero with a per-key verdict table on any unwaived
  regression.
* ``obs.pulse`` + ``obs.netmodel`` (round 16, dhqr-pulse) — runtime
  collective profiling of the sharded tier: an armed sharded dispatch
  runs once under a ``jax.profiler`` trace, parsed to per-collective-
  family wall clock + launch counts and per-shard skew, cross-checked
  against the dhqr-audit traced volumes and the interconnect table as
  the DHQR306 runtime contract (measured time explainable by volume ÷
  bandwidth × slack); armed via ``ObsConfig.pulse`` /
  ``DHQR_OBS_PULSE``, rendered by ``python -m dhqr_tpu.obs pulse``,
  exported under ``comms.*`` registry names.

Armed behind :class:`~dhqr_tpu.utils.config.ObsConfig` / ``DHQR_OBS``
with the faults-harness discipline: zero overhead disarmed (one
module-global None check), deterministic under injected clocks. See
docs/DESIGN.md "Observability" and docs/OPERATIONS.md "Reading a
flight-recorder dump after a typed error".
"""

from __future__ import annotations

from dhqr_tpu.obs import netmodel, pulse, recorder, xray
from dhqr_tpu.obs.metrics import MetricsRegistry, registry, reset_registry
from dhqr_tpu.obs.pulse import PulseReport
from dhqr_tpu.obs.xray import XrayReport
from dhqr_tpu.obs.trace import (
    Span,
    TraceRecorder,
    active,
    arm,
    disarm,
    event,
    mint,
    observed,
)
from dhqr_tpu.utils.config import ObsConfig


def flight_dump(trace_id: int) -> dict:
    """The armed recorder's flight dump for one trace id (empty span
    list when disarmed — the dump API never raises on a cold stack)."""
    armed = active()
    if armed is None:
        return {"trace_id": trace_id, "spans": []}
    return armed.dump(trace_id)


def flight_dump_error(exc: BaseException) -> "list[dict]":
    """Flight dumps for every trace id a typed error carries."""
    return recorder.dump_error(exc)


__all__ = [
    "MetricsRegistry",
    "ObsConfig",
    "PulseReport",
    "Span",
    "TraceRecorder",
    "XrayReport",
    "netmodel",
    "pulse",
    "xray",
    "active",
    "arm",
    "disarm",
    "event",
    "flight_dump",
    "flight_dump_error",
    "mint",
    "observed",
    "recorder",
    "registry",
    "reset_registry",
]
