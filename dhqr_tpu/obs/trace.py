"""Request-scoped tracing: trace ids, spans, and the bounded ring buffer.

The serving stack survives faults (round 12) and numerical breakdowns
(round 13), but its evidence was AGGREGATE — four disconnected
``stats()`` dicts with no way to reconstruct *what happened to one
request*. This module is the per-request answer: a **trace id** minted
at admission (``AsyncScheduler.submit``, or the top of a sync
``batched_*`` / ``guarded_*`` call) and threaded through
queue → coalesce → flush → retry/bisect → dispatch → resolve, with
every hop recorded as a :class:`Span` in one process-wide bounded ring
buffer. The TPU linear-algebra paper (arXiv 2112.09017) attributes its
throughput wins via exactly this kind of per-phase breakdown; here it
is the layer that makes the ROADMAP's TPU re-measurement and async
re-laddering measurable instead of guessable.

Design constraints, in order (the faults-harness discipline,
``dhqr_tpu/faults/harness.py``):

* **Zero overhead when disarmed.** Every instrumentation point reads
  one module global and checks it against ``None``
  (:func:`active` / :func:`mint` / :func:`event`); batch loops in the
  scheduler fetch the recorder ONCE and skip the whole block when it
  is None. ``DHQR_OBS`` unset means the serving tier runs the
  round-13 code byte-for-byte.
* **Out of the compiled programs.** Trace ids live on the host-side
  request records (``_Pending``, futures, exceptions) only — they are
  never part of ``_plan_key`` / ``CacheKey`` and never traced into a
  program, so warm paths stay zero-recompile with tracing armed
  (pinned by the key-parity test in tests/test_obs.py).
* **Deterministic under injected clocks.** The recorder takes an
  injectable ``clock``, and every instrumented subsystem stamps spans
  with ITS OWN clock (the scheduler passes its ``clock=`` readings),
  so a fake-clock test replays byte-identical span paths.
* **Bounded.** The ring holds ``ObsConfig.buffer_spans`` spans; the
  oldest fall off (counted in :meth:`TraceRecorder.stats`). The
  flight recorder (``obs.recorder``) snapshots a request's spans at
  error time, BEFORE later traffic can evict them.

This module deliberately imports no jax (and none of the subsystems it
observes): the dump CLI and the recorder must work in any python,
including one where backend bring-up would hang.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Iterator, NamedTuple, Optional

from dhqr_tpu.utils import lockwitness as _lockwitness
from dhqr_tpu.utils.config import ObsConfig


class Span(NamedTuple):
    """One recorded hop of one request's path.

    ``trace_id`` groups spans into a request; ``seq`` is the global
    recording order (stable tiebreak for same-timestamp spans); ``t``
    is the *instrumenting subsystem's* clock reading (the scheduler's
    injectable clock, not necessarily wall time); ``name`` is the hop
    ("submit", "flush", "dispatch", "retry", "bisect", "rung",
    "resolve", ...); ``attrs`` carries the hop's JSON-ready details
    (cause, backoff, bucket, engine, outcome...)."""

    trace_id: int
    seq: int
    t: float
    name: str
    attrs: dict

    def to_json(self) -> dict:
        return {"trace_id": self.trace_id, "seq": self.seq,
                "t": round(self.t, 6), "name": self.name, **self.attrs}


class TraceRecorder:
    """One armed tracing session: mints trace ids, records spans into a
    bounded ring, and hosts the ``on_error`` auto-dump hook. Normally
    managed through the module globals (:func:`arm` / :func:`observed`);
    constructed directly only by tests probing determinism.

    ``clock`` is the fallback timestamp source for spans recorded
    without an explicit ``t`` (instrumented subsystems with their own
    injectable clock pass ``t=`` and never consult it).
    """

    def __init__(self, config: "ObsConfig | None" = None,
                 clock=time.monotonic) -> None:
        self.config = config or ObsConfig(enabled=True)
        self._clock = clock
        self._lock = _lockwitness.make_lock("TraceRecorder._lock")
        # guarded by: _lock
        self._spans: "collections.deque[Span]" = collections.deque(
            maxlen=self.config.buffer_spans)
        # Per-trace index over the SAME bounded span set: flight dumps
        # read O(path length) instead of copying the whole ring — a
        # burst of auto-dumps must not hold the recorder lock for
        # O(buffer_spans) copies while admission threads (which record
        # their submit span under the scheduler lock) queue behind it.
        # Eviction keeps the two views exact: the globally-oldest span
        # is, within its own trace, also the oldest — deque head (a
        # deque per trace so eviction is O(1) even when one long trace
        # dominates the ring).
        self._by_trace: "dict[int, collections.deque[Span]]" = {}  # guarded by: _lock
        self._next_trace = 0
        self._next_seq = 0
        self._minted = 0
        self._recorded = 0
        self._dropped = 0
        self._error_dumps = 0

    # ------------------------------------------------------------- recording

    def mint(self) -> int:
        """A fresh trace id (monotonic per recorder; the arm/observed
        module layer additionally floors successive ARMED recorders past
        each other's high-water mark, so a re-arm mid-flight can never
        re-issue an id a still-in-flight request is recording under —
        directly-constructed recorders keep deterministic ids from 1)."""
        with self._lock:
            self._next_trace += 1
            self._minted += 1
            return self._next_trace

    def id_high_water(self) -> int:
        """The highest trace id minted so far (0 when none)."""
        with self._lock:
            return self._next_trace

    def advance_past(self, floor: int) -> None:
        """Ensure future mints exceed ``floor`` (the arm/observed
        hand-off: the successor recorder starts past its predecessor)."""
        with self._lock:
            self._next_trace = max(self._next_trace, floor)

    def event(self, trace_id: "int | None", name: str,
              t: "float | None" = None, **attrs) -> None:
        """Record one span. No-op for ``trace_id=None`` (a request
        admitted while tracing was disarmed keeps costing nothing)."""
        if trace_id is None:
            return
        if t is None:
            t = self._clock()
        with self._lock:
            self._next_seq += 1
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
                evicted = self._spans[0]
                per_trace = self._by_trace.get(evicted.trace_id)
                if per_trace:
                    per_trace.popleft()
                    if not per_trace:
                        del self._by_trace[evicted.trace_id]
            self._recorded += 1
            span = Span(trace_id, self._next_seq, float(t), name, attrs)
            self._spans.append(span)
            self._by_trace.setdefault(
                trace_id, collections.deque()).append(span)

    # ------------------------------------------------------------- reading

    def spans_for(self, trace_id: int) -> "list[Span]":
        """The request's span path, in recording order (a consistent
        snapshot, O(path length) via the per-trace index)."""
        with self._lock:
            return list(self._by_trace.get(trace_id, ()))

    def dump(self, trace_id: int) -> dict:
        """JSON-ready flight dump of one request's span path."""
        return {
            "trace_id": trace_id,
            "spans": [s.to_json() for s in self.spans_for(trace_id)],
        }

    def trace_ids(self) -> "list[int]":
        """Distinct trace ids still (partially) resident in the ring,
        oldest-resident first."""
        with self._lock:
            return list(self._by_trace)

    def stats(self) -> dict:
        """JSON-ready recorder accounting (also the ``obs.*`` metrics
        the registry exports)."""
        with self._lock:
            return {
                "minted": self._minted,
                "spans": len(self._spans),
                "recorded": self._recorded,
                "dropped": self._dropped,
                "capacity": self._spans.maxlen,
                "error_dumps": self._error_dumps,
            }

    # --------------------------------------------------------- error hook

    def attach(self, exc: BaseException, trace_id: "int | None") -> None:
        """Stamp a typed error with its request's trace id(s).

        One exception object can resolve several futures (a quarantined
        batch fails everyone with the same ``Quarantined``), so the
        error accumulates ``trace_ids`` (every affected request) while
        ``trace_id`` keeps first-writer-wins for the common
        single-request case."""
        if trace_id is None:
            return
        if getattr(exc, "trace_id", None) is None:
            exc.trace_id = trace_id
        ids = getattr(exc, "trace_ids", ())
        if trace_id not in ids:
            exc.trace_ids = tuple(ids) + (trace_id,)

    def on_error(self, exc: BaseException,
                 trace_id: "int | None" = None) -> None:
        """The auto-dump hook: when ``ObsConfig.auto_dump`` is set,
        persist (or print) the failing request's span path at the
        moment the typed error resolves — before later traffic can
        evict it from the ring. Never raises: a broken dump path must
        not turn a typed failure into a recorder crash."""
        self.attach(exc, trace_id)
        if self.config.auto_dump is None or trace_id is None:
            return
        from dhqr_tpu.obs import recorder as _recorder

        try:
            # Only THIS request's path: one error object can resolve a
            # whole batch of futures (each future's _fail calls the
            # hook with its own id), and dumping every accumulated id
            # per call would duplicate the batchmates' dumps.
            _recorder.write_error_dump(self, exc, (trace_id,),
                                       self.config.auto_dump)
            with self._lock:
                self._error_dumps += 1
        # dhqr: ignore[DHQR006] best-effort telemetry: a full disk or bad dump dir must never mask the typed error the caller is about to receive
        except Exception:
            pass


# The one armed recorder (or None — the fast path). Assignment is atomic
# under the GIL; instrumentation points read it exactly once per visit.
_ACTIVE: "TraceRecorder | None" = None
_ARM_LOCK = _lockwitness.make_lock("trace._ARM_LOCK")
# Trace-id floor across ARMED recorders: instrumentation records spans
# into whatever recorder is active AT SPAN TIME, so a request minted by
# recorder A and still in flight when recorder B arms will record its
# remaining hops into B under A's id — if B could re-mint that id, two
# unrelated requests would merge into one flight dump. Flooring every
# newly armed recorder past its predecessor's high-water mark makes the
# stale spans harmless orphans instead (they never collide with an id B
# hands out). Maintained under _ARM_LOCK.
_ID_FLOOR = 0


def _swap_active_locked(recorder: "TraceRecorder | None") -> None:
    """Replace _ACTIVE (caller holds _ARM_LOCK): bank the outgoing
    recorder's id high-water into the floor and start the incoming one
    past it."""
    global _ACTIVE, _ID_FLOOR
    if _ACTIVE is not None:
        _ID_FLOOR = max(_ID_FLOOR, _ACTIVE.id_high_water())
    if recorder is not None:
        recorder.advance_past(_ID_FLOOR)
    _ACTIVE = recorder


def arm(config: "ObsConfig | None" = None,
        clock=time.monotonic) -> "TraceRecorder | None":
    """Arm process-wide observability from ``config`` (default: the
    environment's ``DHQR_OBS*``), DECLARATIVELY: tracing iff
    ``config.enabled``, xray capture (``dhqr_tpu.obs.xray``, round 15)
    iff ``config.xray``, pulse collective profiling
    (``dhqr_tpu.obs.pulse``, round 16) iff ``config.pulse`` — each
    field disarms its subsystem when false, so ``obs.arm()`` with no
    env set is a no-op, exactly like ``faults.install()`` with no
    sites. Returns the armed trace recorder, or None when tracing is
    left disarmed."""
    from dhqr_tpu.obs import pulse as _pulse
    from dhqr_tpu.obs import xray as _xray

    cfg = config if config is not None else ObsConfig.from_env()
    recorder = TraceRecorder(cfg, clock=clock) if cfg.enabled else None
    with _ARM_LOCK:
        _swap_active_locked(recorder)
    if cfg.xray:
        _xray.arm(max_reports=cfg.xray_reports)
    else:
        _xray.disarm()
    if cfg.pulse:
        _pulse.arm(max_reports=cfg.pulse_reports)
    else:
        _pulse.disarm()
    return recorder


def disarm() -> None:
    """Back to the zero-overhead path (the ring and its spans are
    dropped with the recorder; the xray store with its reports; the
    pulse store with its measurements)."""
    from dhqr_tpu.obs import pulse as _pulse
    from dhqr_tpu.obs import xray as _xray

    with _ARM_LOCK:
        _swap_active_locked(None)
    _xray.disarm()
    _pulse.disarm()


def active() -> Optional[TraceRecorder]:
    """The armed recorder, or None. THE hot-path read: instrumented
    batch loops call this once and skip everything when disarmed."""
    return _ACTIVE


@contextlib.contextmanager
def observed(config: "ObsConfig | None" = None,
             clock=time.monotonic) -> Iterator[TraceRecorder]:
    """Scope a tracing session: arm on entry, restore whatever was
    armed before on exit (scopes nest). Yields the recorder even when
    ``config.enabled`` is falsy-armed off — tests always get an object
    to read."""
    cfg = config or ObsConfig(enabled=True)
    recorder = TraceRecorder(cfg, clock=clock)
    # One lock acquisition for capture AND swap: reading ``previous``
    # separately would let a concurrent arm() land in the gap and be
    # silently clobbered by this scope's exit restoration.
    with _ARM_LOCK:
        previous = _ACTIVE
        _swap_active_locked(recorder if cfg.enabled else None)
    try:
        yield recorder
    finally:
        with _ARM_LOCK:
            _swap_active_locked(previous)


def mint() -> "int | None":
    """Mint a trace id, or None when disarmed — the instrumentation
    points carry that None all the way (every downstream hop is a
    no-op on it), so a disarmed stack never branches again."""
    recorder = _ACTIVE
    if recorder is None:
        return None
    return recorder.mint()


def event(trace_id: "int | None", name: str, t: "float | None" = None,
          **attrs) -> None:
    """Record one span against ``trace_id``; no-op when disarmed or
    when the id is None."""
    recorder = _ACTIVE
    if recorder is None or trace_id is None:
        return
    recorder.event(trace_id, name, t=t, **attrs)
