"""dhqr-pulse: runtime collective profiling for the sharded tier.

dhqr-xray (round 15) answers *where the flops and bytes go inside one
compiled executable*; this module answers the question the sharded
tier could not until now: *what do the collectives actually cost at
runtime*. PR 5's comms contracts audit the TRACED byte volume against
analytic budgets (static, DHQR301-305); nothing measured what an
``all-reduce`` spends on the wire, how evenly the shards arrive, or
how much of the collective time the schedule hides — the before/after
evidence ROADMAP item 3's compressed collectives (EQuARX,
arXiv 2506.17615) and the portable-redistribution schedules
(arXiv 2112.01075) both need.

One :class:`PulseReport` per measured sharded dispatch pairs three
sources:

* **measured per-collective timing** — the dispatch runs once under a
  ``jax.profiler`` trace; the trace's per-device HLO-op events are
  parsed into per-collective-family wall clock + launch counts and a
  per-shard busy-time spread (max/median skew). Backends whose
  profiler refuses (or whose trace carries no device events) degrade
  to null WITH a reason — the xray compat discipline, never a raised
  exception on a dispatch path;
* **the traced analytic census** — the same jaxpr walk dhqr-audit
  uses (``analysis/comms_pass.collect_comms``), giving per-family
  launch counts and byte volumes, with while-loop opacity flagged
  exactly as in PR 5;
* **the interconnect table** — ``utils/platform.device_ici_gbps``;
  with a known wire speed the two sides close into the **DHQR306
  runtime contract**: measured collective time must be explainable by
  volume ÷ interconnect bandwidth × slack (``obs.netmodel``). CPU
  topologies have no published wire and read ``skip`` with the
  reason spelled out.

Capture discipline (the faults/xray pattern): arming is via
``ObsConfig.pulse`` / ``DHQR_OBS_PULSE`` + ``dhqr_tpu.obs.arm`` (or
the :func:`pulsed` scope); disarmed, every instrumented dispatch pays
one module-global ``None`` check. Armed, each LABEL is measured once
— the first dispatch pays one profiler trace (~ms warm; the very
first trace in a process pays the profiler's one-time init) and every
later dispatch of the same label runs the plain path, so warm
serving/benching holds the >= 0.95 armed-over-disarmed bar by
construction (pinned by benchmarks/serving_pulse.py).

Module-level imports stay jax-free (table rendering and report maths
must work in any python); only :func:`measure` touches jax, and only
when handed a live dispatch.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
import shutil
import statistics
import tempfile
import threading
from typing import Callable, Optional

from dhqr_tpu.obs import netmodel as _net
from dhqr_tpu.utils import lockwitness as _lockwitness

__all__ = [
    "DEFAULT_SLACK",
    "PulseReport",
    "PulseStore",
    "active",
    "arm",
    "collective_census",
    "disarm",
    "format_table",
    "measure",
    "observed_dispatch",
    "parse_trace_dir",
    "pulsed",
    "rows_from_json",
]

#: DHQR306 slack over the pure bandwidth bound. Deliberately wide: the
#: wire term models bandwidth only, and a real collective pays launch
#: latency, sync skew and ring hops the slack must absorb — 8x still
#: catches an order-of-magnitude schedule regression (a serialized
#: gather, a congested link) while never flagging healthy jitter.
DEFAULT_SLACK = 8.0


# ------------------------------------------------------------ trace parse

def parse_trace_dir(logdir: str) -> "list[dict]":
    """Every complete ('X') trace event from the ``*.trace.json.gz``
    files a ``jax.profiler.trace(logdir)`` session wrote (the
    TensorBoard layout: ``plugins/profile/<run>/<host>.trace.json.gz``).
    Returns ``[]`` — never raises — when the profiler wrote nothing."""
    events: "list[dict]" = []
    pattern = os.path.join(logdir, "plugins", "profile", "*",
                           "*.trace.json.gz")
    for path in sorted(glob.glob(pattern)):
        try:
            with gzip.open(path, "rt", encoding="utf-8") as fh:
                data = json.load(fh)
        # dhqr: ignore[DHQR006] a truncated/foreign trace file degrades to "no events" (null-with-reason downstream), never a dispatch-path crash
        except Exception:
            continue
        for event in data.get("traceEvents", []):
            if isinstance(event, dict) and event.get("ph") == "X":
                events.append(event)
    return events


def collective_census(events: "list[dict]") -> dict:
    """Per-collective-family timing + per-lane (per-shard) busy time
    from parsed trace events.

    Device-execution events are identified by their ``args.hlo_op``
    annotation (what the XLA CPU/TPU runtimes stamp on op-level
    events); if a backend's trace carries none, every complete event
    is considered instead (fallback — better a noisy census than a
    silent null). Returns::

        {"families": {family: {"events": N, "time_us": T}},
         "lanes": {lane_label: {"busy_us": B, "collective_us": C}},
         "hlo_events": total_device_op_events}
    """
    def walk(require_hlo: bool) -> dict:
        families: "dict[str, dict]" = {}
        lanes: "dict[str, dict]" = {}
        n_hlo = 0
        for event in events:
            args = event.get("args") or {}
            if require_hlo and "hlo_op" not in args:
                continue
            n_hlo += 1
            lane = f"{event.get('pid', '?')}/{event.get('tid', '?')}"
            dur = float(event.get("dur", 0.0) or 0.0)
            lane_row = lanes.setdefault(
                lane, {"busy_us": 0.0, "collective_us": 0.0})
            lane_row["busy_us"] += dur
            family = _net.classify_event(event.get("name", ""))
            if family:
                lane_row["collective_us"] += dur
                fam = families.setdefault(
                    family, {"events": 0, "time_us": 0.0})
                fam["events"] += 1
                fam["time_us"] += dur
        return {"families": families, "lanes": lanes, "hlo_events": n_hlo}

    census = walk(require_hlo=True)
    if not census["hlo_events"]:
        census = walk(require_hlo=False)
        census["hlo_events"] = 0  # keep the "no annotated ops" signal
    return census


# ---------------------------------------------------------------- report

@dataclasses.dataclass(frozen=True)
class PulseReport:
    """Runtime comms profile of ONE sharded dispatch.

    ``measured`` maps collective family -> per-DEVICE launch count and
    wall seconds (trace totals normalized by the lane count), or None
    with the refusal in ``measured_unavailable``. ``analytic`` is the
    jaxpr census (per-device launches + payload volume, dhqr-audit's
    convention), or None with a reason. ``skew`` carries the per-shard
    busy-second spread. ``dhqr306`` is the measured-vs-analytic
    contract verdict (``status`` ok/fail/skip + per-family checks).
    ``comms`` is the roofline block :class:`~dhqr_tpu.obs.xray
    .XrayReport` embeds so both sides of the roofline render in one
    table."""

    label: str
    n_devices: int = 1
    device_kind: "str | None" = None
    wire_format: "str | None" = None
    wall_s: "float | None" = None
    measured: "dict | None" = None
    measured_unavailable: "str | None" = None
    analytic: "dict | None" = None
    analytic_unavailable: "str | None" = None
    opaque_families: "tuple[str, ...]" = ()
    skew: "dict | None" = None
    skew_unavailable: "str | None" = None
    ici_gbps: "float | None" = None
    #: Round 20 (dhqr-pod): published DCN (cross-slice) bandwidth for
    #: the device kind, or None — absent by design on CPU and on any
    #: kind utils/platform.device_dcn_gbps does not know. The DHQR306
    #: two-tier bound reads it; a None with a non-zero cross-DCN traced
    #: share skips with the reason, never crashes.
    dcn_gbps: "float | None" = None
    dhqr306: "dict | None" = None
    comms: "dict | None" = None

    @property
    def dhqr306_pass(self) -> bool:
        """Green = not red: an ``ok`` or a reasoned ``skip`` both count
        (the acceptance convention for null-with-reason backends)."""
        return (self.dhqr306 or {}).get("status") != "fail"

    def measured_collective_s(self) -> "float | None":
        if self.measured is None:
            return None
        return sum(f["time_s"] for f in self.measured.values())

    def to_json(self) -> dict:
        """JSON-ready record — the shape the artifact rows and the
        ``obs pulse`` table speak (null WITH reason, never silently
        absent)."""
        out: dict = {"label": self.label, "n_devices": self.n_devices,
                     "device_kind": self.device_kind}
        if self.wire_format is not None:
            out["wire_format"] = self.wire_format
        if self.wall_s is not None:
            out["wall_s"] = round(self.wall_s, 6)
        out["measured"] = self.measured
        if self.measured is None:
            out["measured_unavailable"] = (
                self.measured_unavailable or "no measurement captured")
        out["analytic"] = self.analytic
        if self.analytic is None:
            out["analytic_unavailable"] = (
                self.analytic_unavailable or "no traced census captured")
        if self.opaque_families:
            out["opaque_families"] = list(self.opaque_families)
        out["skew"] = self.skew
        if self.skew is None:
            out["skew_unavailable"] = (
                self.skew_unavailable or "no per-shard lanes captured")
        if self.ici_gbps is not None:
            out["ici_gbps"] = self.ici_gbps
        if self.dcn_gbps is not None:
            out["dcn_gbps"] = self.dcn_gbps
        out["dhqr306"] = self.dhqr306
        out["dhqr306_pass"] = self.dhqr306_pass
        if self.comms is not None:
            out["comms"] = self.comms
        return out


def _analytic_census(abstract: "Callable[[], object] | None",
                     n_devices: int):
    """(families dict, opaque tuple, reason) from dhqr-audit's jaxpr
    walk over the closed jaxpr ``abstract()`` returns. Lazy import:
    analysis imports the engine matrix, and pulse must stay importable
    without it."""
    if abstract is None:
        return None, (), "no abstract trace provided for this dispatch"
    try:
        from dhqr_tpu.analysis.comms_pass import collect_comms
        from dhqr_tpu.faults import harness as _faults

        # abstract() re-traces the shard body into a DISCARDED jaxpr;
        # with trace-time fault schedules armed (the round-19
        # parallel.collective.* wire sites) that retrace would consume
        # schedule visits against a program that never runs, shifting
        # which real collective a :k schedule hits. Suspend the
        # harness for the census — one visit = one traced collective
        # of a real program.
        with _faults.suspended():
            stats = collect_comms(abstract())
    # dhqr: ignore[DHQR006] the census rides a dispatch path: a trace failure costs the analytic side of the report, never the dispatch
    except Exception as e:
        return None, (), f"abstract trace failed: {type(e).__name__}: {e}"
    families: "dict[str, dict]" = {}
    launches, volumes = stats.launches(), stats.volume()
    # Round 20 (dhqr-pod): the cross-DCN share of each primitive's
    # volume, read off the collective's own axis names — zero on any
    # 1-D mesh, so pre-pod census rows are unchanged except for the
    # constant extra key.
    dcn_volumes: "dict[str, int]" = {}
    for u in stats.uses:
        if u.bounded and u.crosses_dcn:
            dcn_volumes[u.prim] = (dcn_volumes.get(u.prim, 0)
                                   + u.volume_bytes)
    for prim in set(launches) | set(volumes):
        family = _net.PRIMITIVE_FAMILY.get(prim, prim)
        row = families.setdefault(
            family, {"launches": 0, "volume_bytes": 0,
                     "dcn_volume_bytes": 0})
        row["launches"] += launches.get(prim, 0)
        row["volume_bytes"] += volumes.get(prim, 0)
        row["dcn_volume_bytes"] += dcn_volumes.get(prim, 0)
    opaque = tuple(sorted(
        {_net.PRIMITIVE_FAMILY.get(p, p)
         for p in stats.opaque_loop_collectives}))
    return families, opaque, None


#: Measured family -> traced source families whose lowering can emit
#: it (XLA decomposes all-reduce into reduce-scatter + all-gather on
#: some backends/sizes); consulted before failing a measured family
#: with no analytic counterpart.
_DECOMPOSITION_SOURCES = {
    "reduce_scatter": ("psum",),
    "all_gather": ("psum",),
}


def _check_dhqr306(measured: "dict | None", analytic: "dict | None",
                   opaque: "tuple[str, ...]", n_devices: int,
                   ici_gbps: "float | None", slack: float,
                   contract_families: "tuple | None" = None,
                   wire_format: "str | None" = None,
                   dcn_gbps: "float | None" = None) -> dict:
    """The runtime contract verdict. Per measured family: the
    :func:`~dhqr_tpu.obs.netmodel.explain_measured` wire check against
    the analytic volume (skip with reason when no wire speed is
    published); a measured family with NO analytic counterpart — or
    outside an explicit ``contract_families`` allow-list — fails (a
    collective executing that the traced census cannot account for is
    the runtime twin of DHQR301). While-loop-opaque families skip, as
    in PR 5 (an unboundable volume cannot bound a time)."""
    verdict: dict = {"slack": slack, "checks": []}
    if wire_format is not None:
        # dhqr-wire (round 18): the analytic census already carries the
        # COMPRESSED payload avals (bf16/int8 on the wire), so the
        # explanation bound below is the compressed-volume bound — the
        # tag records which wire model priced it.
        verdict["wire_format"] = wire_format
    if measured is None:
        verdict["status"] = "skip"
        verdict["reason"] = "no measured collective timing"
        return verdict
    failed = ok = 0
    for family in sorted(measured):
        meas = measured[family]
        if contract_families is not None \
                and family not in contract_families:
            verdict["checks"].append({
                "family": family, "status": "fail",
                "reason": f"measured collective family '{family}' is "
                "outside the dispatch's contract "
                f"({sorted(contract_families) or 'none'}) — a collective "
                "executed at runtime that the contract forbids"})
            failed += 1
            continue
        if family in opaque:
            verdict["checks"].append({
                "family": family, "status": "skip",
                "reason": "family launches inside a while-loop: volume "
                "unboundable (the PR-5 opacity rule)"})
            continue
        row = (analytic or {}).get(family)
        note = None
        if row is None:
            # XLA may DECOMPOSE an all-reduce into reduce-scatter +
            # all-gather phases at lowering; a measured phase family
            # whose source family is in the census is explained by the
            # source's volume, not a runtime contract breach.
            for source in _DECOMPOSITION_SOURCES.get(family, ()):
                row = (analytic or {}).get(source)
                if row is not None:
                    note = (f"explained as an XLA decomposition phase "
                            f"of traced '{source}'")
                    break
        if row is None:
            verdict["checks"].append({
                "family": family, "status": "fail",
                "reason": f"measured collective family '{family}' has no "
                "traced analytic counterpart — the runtime executed a "
                "collective the jaxpr census cannot account for"})
            failed += 1
            continue
        check = _net.explain_measured(
            family, meas["time_s"], row["volume_bytes"], n_devices,
            ici_gbps or 0.0, slack, wire_format=wire_format,
            dcn_volume_bytes=row.get("dcn_volume_bytes", 0) or 0,
            dcn_gbps=dcn_gbps)
        if note:
            check["note"] = note
        verdict["checks"].append(check)
        if check["status"] == "fail":
            failed += 1
        elif check["status"] == "ok":
            ok += 1
    if failed:
        verdict["status"] = "fail"
    elif ok:
        verdict["status"] = "ok"
    else:
        verdict["status"] = "skip"
        verdict["reason"] = (
            "no per-family check could run (no published interconnect "
            "bandwidth, or no measured collectives)"
            if verdict["checks"] else "no collectives measured")
    return verdict


def measure(label: str, thunk: Callable[[], object], *,
            abstract: "Callable[[], object] | None" = None,
            n_devices: int = 1,
            device_kind: "str | None" = None,
            slack: float = DEFAULT_SLACK,
            contract_families: "tuple | None" = None,
            keep_trace_dir: "str | None" = None,
            wire_format: "str | None" = None):
    """Run ``thunk`` warm (once untraced — absorbing any cold compile
    — then once under a ``jax.profiler`` trace) and build its
    :class:`PulseReport`. Returns ``(thunk's result, report)``.

    Degradation contract: the dispatch ALWAYS runs and its result is
    always returned — a profiler that refuses to start (unsupported
    backend, a trace already active from ``DHQR_OBS_PROFILE``) or a
    trace with no device events costs only the measured side of the
    report, null WITH the reason. ``abstract`` (optional) returns the
    dispatch's closed jaxpr for the analytic census; ``keep_trace_dir``
    preserves the raw trace for offline tooling instead of deleting
    the temp dir."""
    import time as _time

    import jax

    if device_kind is None:
        from dhqr_tpu.obs.xray import _default_device_kind

        device_kind, _platform = _default_device_kind()
    from dhqr_tpu.utils.platform import device_dcn_gbps, device_ici_gbps

    ici = device_ici_gbps(device_kind) if device_kind else None
    # Round 20: the DCN tier's own bandwidth — None (with the skip
    # reason downstream) on CPU and unknown kinds, by design.
    dcn = device_dcn_gbps(device_kind) if device_kind else None

    tmpdir = keep_trace_dir or tempfile.mkdtemp(prefix="dhqr_pulse_")
    events: "list[dict]" = []
    reason: "str | None" = None
    # Warm the dispatch OUTSIDE the trace first: a cold first dispatch
    # spends seconds in XLA compile, and tracing that floods the
    # profiler with host-side compile events (measured: the device-op
    # events get truncated away entirely and a compile thread reads as
    # a fake 60-second shard lane). The traced run below is the WARM
    # program — the steady-state collective cost the report claims.
    # A thunk that raises here raises to the caller: a failing
    # dispatch is the engine's error path, not a measurement problem.
    out = jax.block_until_ready(thunk())
    # dhqr: ignore[DHQR008] the dispatch wall clock IS the measurement (profiler event time is cross-checked against it)
    t0 = _time.perf_counter()
    try:
        with jax.profiler.trace(tmpdir):
            out = jax.block_until_ready(thunk())
    # dhqr: ignore[DHQR006] profiler refusal (unsupported backend, nested trace) must cost the report, never the dispatch — the warm result above already stands
    except Exception as e:
        reason = (f"profiler capture failed: {type(e).__name__}: {e} "
                  "(backend profiler unsupported, or a trace was "
                  "already active)")
    # dhqr: ignore[DHQR008] closing read of the dispatch wall clock
    wall_s = _time.perf_counter() - t0
    if reason is None:
        events = parse_trace_dir(tmpdir)
        if not events:
            reason = ("profiler trace contained no events on this "
                      "backend")
    if keep_trace_dir is None:
        shutil.rmtree(tmpdir, ignore_errors=True)

    analytic, opaque, analytic_reason = _analytic_census(
        abstract, n_devices)

    measured = skew = None
    skew_reason = reason
    if reason is None:
        census = collective_census(events)
        lanes = census["lanes"]
        if census["families"]:
            measured = {}
            for family, row in sorted(census["families"].items()):
                # Normalize by the DEVICE count, not the lane count:
                # every participating device executes the collective
                # once per launch, but the CPU client runs device
                # programs on a shared thread POOL — in a long-lived
                # process one device's ops hop threads, so lanes can
                # outnumber devices and a lane-normalized count would
                # silently under-read (observed: 12 lanes for an
                # 8-device mesh in the dry run).
                n_dev = max(int(n_devices), 1)
                measured[family] = {
                    "launches": max(1, round(row["events"] / n_dev)),
                    "time_s": round(row["time_us"] / n_dev / 1e6, 9),
                }
        else:
            reason = ("no collective events in the profiler trace "
                      + ("(no annotated device ops on this backend)"
                         if not census["hlo_events"]
                         else "(the program launched no collectives, or "
                         "XLA elided them at this device count)"))
        # Shard lanes = lanes that joined a collective (every shard of
        # a collective program does); stray near-idle lanes would read
        # as fake skew. Collective-free programs keep every lane.
        shard_lanes = {k: v for k, v in lanes.items()
                       if v["collective_us"] > 0} or lanes
        if len(shard_lanes) >= 2:
            busy = sorted(r["busy_us"] / 1e6
                          for r in shard_lanes.values())
            coll = sorted(r["collective_us"] / 1e6
                          for r in shard_lanes.values())
            med = statistics.median(busy)
            skew = {
                "lanes": len(shard_lanes),
                "n_devices": int(n_devices),
                "per_shard_busy_s": [round(b, 6) for b in busy],
                "max_over_median": round(busy[-1] / med, 4)
                if med > 0 else None,
                "collective_max_over_median": round(
                    coll[-1] / statistics.median(coll), 4)
                if coll and statistics.median(coll) > 0 else None,
            }
            if len(shard_lanes) != int(n_devices):
                # Thread-pool execution (lanes hop threads in long-
                # lived processes): the spread is still evidence of
                # imbalance, but "lane" != "shard" 1:1 — say so.
                skew["lane_caveat"] = (
                    f"{len(shard_lanes)} execution lanes for "
                    f"{n_devices} devices — thread-pool scheduling; "
                    "read the spread as busy-time imbalance, not a "
                    "per-device identification")
            skew_reason = None
        else:
            skew_reason = (f"trace exposed {len(shard_lanes)} shard "
                           "execution lane(s): per-shard spread "
                           "needs >= 2")

    dhqr306 = _check_dhqr306(measured, analytic, opaque, n_devices,
                             ici, slack,
                             contract_families=contract_families,
                             wire_format=wire_format, dcn_gbps=dcn)

    comms: "dict | None" = None
    if measured is not None and skew is not None:
        comms_s = sum(f["time_s"] for f in measured.values())
        # Per-DEVICE busy seconds, same normalization as comms_s
        # (trace total ÷ device count): mixing per-lane busy with
        # per-device collective time flips the roofline verdict
        # whenever lanes outnumber devices (the thread-pool case).
        busy_dev = sum(skew["per_shard_busy_s"]) / max(
            int(n_devices), 1)
        # Wire bytes from the TRACED census — the lowering-independent
        # quantity. Summing over MEASURED families instead would zero
        # this out exactly on backends that decompose all-reduce into
        # reduce-scatter + all-gather phases (no analytic row under
        # the phase names).
        moved = sum(
            _net.wire_bytes(f, row.get("volume_bytes", 0), n_devices)
            for f, row in (analytic or {}).items())
        comms = _net.comms_roofline(
            comms_s, max(busy_dev - comms_s, 0.0),
            link_gbps=ici, wire_bytes_moved=moved or None)
    report = PulseReport(
        label=str(label), n_devices=int(n_devices),
        device_kind=device_kind, wall_s=wall_s,
        wire_format=wire_format,
        measured=measured, measured_unavailable=reason,
        analytic=analytic, analytic_unavailable=analytic_reason,
        opaque_families=opaque, skew=skew, skew_unavailable=skew_reason,
        ici_gbps=ici, dcn_gbps=dcn, dhqr306=dhqr306, comms=comms,
    )
    return out, report


# ----------------------------------------------------------------- store

class PulseStore:
    """Bounded label -> report store for one armed pulse session.

    ``begin(label)`` is the hot-path test the instrumented dispatches
    use: a label already measured (or currently being measured by a
    concurrent thread) runs the plain path — each label pays its
    profiler trace exactly once per armed session. Eviction bounds the
    resident REPORTS only; an evicted label stays claimed (the
    ``_seen`` set keeps the label string), so a busy store can never
    silently re-pay a profiler trace on the warm path — capture-once
    is a session property, not a residency property."""

    def __init__(self, max_reports: int = 256,
                 slack: float = DEFAULT_SLACK) -> None:
        if max_reports < 1:
            raise ValueError(
                f"max_reports must be >= 1, got {max_reports}")
        self.max_reports = int(max_reports)
        self.slack = float(slack)
        self._lock = _lockwitness.make_lock("PulseStore._lock")
        self._reports: "dict[str, PulseReport]" = {}  # guarded by: _lock
        self._seen: "set[str]" = set()                # guarded by: _lock
        self._captures = 0
        self._unsupported = 0
        self._failed_306 = 0
        self._evicted = 0

    def begin(self, label: str) -> bool:
        """Claim ``label`` for measurement (False = already measured,
        claimed, or measured-then-evicted — run the plain path)."""
        label = str(label)
        with self._lock:
            if label in self._seen:
                return False
            self._seen.add(label)
            return True

    def capture(self, label: str, report: PulseReport) -> None:
        with self._lock:
            self._captures += 1
            if report.measured is None:
                self._unsupported += 1
            if not report.dhqr306_pass:
                self._failed_306 += 1
            self._seen.add(str(label))  # direct captures (no begin)
            self._reports[str(label)] = report
            while len(self._reports) > self.max_reports:
                self._reports.pop(next(iter(self._reports)))
                self._evicted += 1

    def reports(self) -> "list[PulseReport]":
        with self._lock:
            return list(self._reports.values())

    def report(self, label: str) -> Optional[PulseReport]:
        with self._lock:
            return self._reports.get(str(label))

    def stats(self) -> dict:
        """The ``comms.*`` numbers the metrics registry exports."""
        with self._lock:
            reports = list(self._reports.values())
            skews = [r.skew["max_over_median"] for r in reports
                     if r.skew and r.skew.get("max_over_median")]
            coll = [r.measured_collective_s() for r in reports]
            return {
                "captures": self._captures,
                "reports": len(reports),
                "unsupported": self._unsupported,
                "dhqr306_failures": self._failed_306,
                "evicted": self._evicted,
                "capacity": self.max_reports,
                "measured_collective_s": round(
                    sum(c for c in coll if c), 6),
                "skew_max_over_median": round(max(skews), 4)
                if skews else 0.0,
            }

    def export_jsonl(self, path: str) -> int:
        """Append every resident report as one ``{"pulse": {...}}``
        JSON line (what ``python -m dhqr_tpu.obs pulse`` renders)."""
        reports = self.reports()
        with open(path, "a", encoding="utf-8") as fh:
            for rep in reports:
                fh.write(json.dumps({"pulse": rep.to_json()}) + "\n")
        return len(reports)


# The one armed store (or None — the fast path); same module-global
# discipline as faults.harness / obs.trace / obs.xray.
_ACTIVE: "PulseStore | None" = None
_ARM_LOCK = _lockwitness.make_lock("pulse._ARM_LOCK")


def arm(max_reports: int = 256, slack: float = DEFAULT_SLACK,
        store: "PulseStore | None" = None) -> PulseStore:
    """Arm process-wide pulse capture (normally reached via
    ``dhqr_tpu.obs.arm`` with ``ObsConfig.pulse`` / ``DHQR_OBS_PULSE``).
    ``store`` re-installs an existing store instead of creating a fresh
    one — the A/B-overhead benchmarks re-arm the store whose labels are
    already measured, so the armed arm exercises the warm (seen-label)
    path rather than paying a re-capture."""
    global _ACTIVE
    with _ARM_LOCK:
        _ACTIVE = store if store is not None \
            else PulseStore(max_reports=max_reports, slack=slack)
        return _ACTIVE


def disarm() -> None:
    global _ACTIVE
    with _ARM_LOCK:
        _ACTIVE = None


def active() -> Optional[PulseStore]:
    """The armed store, or None — THE hot-path read (the sharded
    dispatch seams consult it once per call)."""
    return _ACTIVE


class pulsed:
    """Scope a pulse session (arm on entry, restore the previous store
    on exit; scopes nest):

    >>> with pulse.pulsed() as store:
    ...     sharded_blocked_qr(A, mesh, block_size=nb)
    ...     store.reports()
    """

    def __init__(self, max_reports: int = 256,
                 slack: float = DEFAULT_SLACK) -> None:
        self._store = PulseStore(max_reports=max_reports, slack=slack)
        self._previous: "PulseStore | None" = None

    def __enter__(self) -> PulseStore:
        global _ACTIVE
        with _ARM_LOCK:
            self._previous = _ACTIVE
            _ACTIVE = self._store
        return self._store

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        with _ARM_LOCK:
            _ACTIVE = self._previous


def observed_dispatch(label: str, thunk: Callable[[], object], *,
                      abstract: "Callable[[], object] | None" = None,
                      n_devices: int = 1,
                      contract_families: "tuple | None" = None,
                      on_report=None,
                      wire_format: "str | None" = None):
    """The sharded tier's instrumentation seam: run ``thunk`` plainly
    when pulse is disarmed or ``label`` was already measured; measure
    it (once) when armed and new. The dispatch's result is returned
    either way, and measurement failure can never fail the dispatch
    (:func:`measure`'s degradation contract). A dispatch reached
    UNDER an active jax trace (the comms audit / jaxpr lint
    abstractly trace the same entry points) runs plain: profiling
    tracers is meaningless and ``block_until_ready`` on them is
    undefined. ``on_report(report)`` fires exactly once, right after
    a label's capture (the serve seam pairs the comms block into the
    xray store there) — never on the warm path, and never fatally."""
    store = _ACTIVE
    if store is None:
        return thunk()
    try:
        from jax.core import trace_state_clean

        if not trace_state_clean():
            return thunk()
    # dhqr: ignore[DHQR006] a jax without the probe (future rename) loses only the abstract-trace guard, never the dispatch
    except ImportError:
        pass
    if not store.begin(label):
        return thunk()
    out, report = measure(label, thunk, abstract=abstract,
                          n_devices=n_devices, slack=store.slack,
                          contract_families=contract_families,
                          wire_format=wire_format)
    store.capture(label, report)
    if on_report is not None:
        try:
            on_report(report)
        # dhqr: ignore[DHQR006] pairing is best-effort evidence: a callback bug must cost the pairing, never the dispatch
        except Exception:
            pass
    return out


# ------------------------------------------------------------------ table

def rows_from_json(records) -> "list[dict]":
    """Extract pulse blocks from parsed JSON records (artifact rows,
    ``export_jsonl`` lines, bench summaries): any dict carrying a
    ``"pulse"`` sub-dict or sub-list, or that IS a report (has
    ``dhqr306_pass``)."""
    rows = []
    for rec in records:
        if not isinstance(rec, dict):
            continue
        blk = rec.get("pulse")
        blocks = blk if isinstance(blk, list) else [blk]
        matched = False
        for one in blocks:
            if isinstance(one, dict):
                matched = True
                row = dict(one)
                row.setdefault("label", rec.get("stage")
                               or rec.get("metric") or "?")
                rows.append(row)
        if not matched and "dhqr306_pass" in rec:
            rows.append(dict(rec))
    return rows


def _fmt_ms(value) -> str:
    if not isinstance(value, (int, float)):
        return "-"
    return f"{value * 1e3:.3f}"


def format_table(rows: "list[dict]") -> str:
    """Aligned per-label table of pulse rows (the ``obs pulse`` CLI
    output): label, device count, measured per-family launches x time,
    total collective ms, comms fraction, per-shard skew, effective
    GB/s, DHQR306 status."""
    header = ("label", "P", "collectives", "comms_ms", "f(comms)",
              "skew", "effGB/s", "DHQR306")
    table = [header]
    for row in rows:
        measured = row.get("measured") or {}
        fams = " ".join(
            f"{fam}:{m.get('launches', '?')}x"
            for fam, m in sorted(measured.items())) or "-"
        comms_ms = sum(m.get("time_s", 0.0) for m in measured.values())
        comms = row.get("comms") or {}
        skew = (row.get("skew") or {}).get("max_over_median")
        verdict = (row.get("dhqr306") or {}).get("status") or (
            "ok" if row.get("dhqr306_pass") else "fail")
        table.append((
            str(row.get("label", "?"))[:48],
            str(row.get("n_devices", "?")),
            fams[:36],
            _fmt_ms(comms_ms) if measured else "-",
            (f"{comms['comms_fraction']:.2f}"
             if isinstance(comms.get("comms_fraction"), (int, float))
             else "-"),
            f"{skew:.2f}" if isinstance(skew, (int, float)) else "-",
            (f"{comms['effective_gbps']:.2f}"
             if isinstance(comms.get("effective_gbps"), (int, float))
             else "-"),
            verdict,
        ))
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(
            c.ljust(w) if j in (0, 2) else c.rjust(w)
            for j, (c, w) in enumerate(zip(r, widths))))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
