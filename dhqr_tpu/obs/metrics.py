"""MetricsRegistry — one process-wide registry of dotted-name metrics.

Before round 14 the stack's operational numbers lived in four private
``stats()`` dicts — the async scheduler's, the executable cache's, the
fault harness's, and tune's ``plan_gate_stats()`` — each with its own
spelling and no way to read "the process" in one snapshot. This module
unifies them under stable dotted names (``serve.cache.hits``,
``serve.sched.retries``, ``numeric.fallbacks``, ``faults.fired``,
``tune.plan_gate.failures``, ``obs.spans``, ...) while the old dict
shapes stay as thin compatibility views over the SAME counters (the
subsystems still own their
:class:`~dhqr_tpu.utils.profiling.Counters` /
:class:`~dhqr_tpu.utils.profiling.Ewma` /
:class:`~dhqr_tpu.utils.profiling.LatencyHistogram` instances — the
registry references, never copies, so there is exactly one set of
numbers).

Sources come in two kinds:

* **instances** (``register(prefix, obj)`` with an object exposing
  ``metrics_snapshot() -> dict[str, number]``) are held by WEAK
  reference: every :class:`~dhqr_tpu.serve.cache.ExecutableCache` and
  :class:`~dhqr_tpu.serve.AsyncScheduler` self-registers at
  construction, test instances evaporate with garbage collection, and
  two live schedulers SUM under one name (process telemetry, not
  per-object bookkeeping);
* **providers** (``register(prefix, callable)``) are held strongly and
  consulted at snapshot time — the default registry wires lazy
  providers for the fault harness (whatever
  :func:`dhqr_tpu.faults.harness.active` currently is), tune's plan
  gate, the numeric ladder's counters, and the armed trace recorder,
  so those modules never import obs (no cycle) and pay nothing until a
  snapshot is taken.

Exporters: :meth:`MetricsRegistry.export_jsonl` appends one
timestamped JSON object per call (the benchmark/bench-summary
stamping format) and :meth:`MetricsRegistry.export_prometheus` renders
the Prometheus text exposition format (``dhqr_serve_cache_hits 42``).
"""

from __future__ import annotations

import json
import re
import threading
import time
import weakref
from typing import Callable, Union

from dhqr_tpu.utils import lockwitness as _lockwitness

Number = Union[int, float]


def _flatten(prefix: str, values: dict) -> "dict[str, float]":
    """``{"hits": 3}`` under ``"serve.cache"`` -> ``{"serve.cache.hits":
    3.0}``; nested dicts flatten recursively; non-numeric values are
    dropped (a snapshot is numbers, not prose)."""
    out: "dict[str, float]" = {}
    for key, val in values.items():
        name = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(val, dict):
            out.update(_flatten(name, val))
        elif isinstance(val, bool):
            out[name] = float(val)
        elif isinstance(val, (int, float)):
            out[name] = float(val)
    return out


class MetricsRegistry:
    """Dotted-name metric aggregation over weakly-held instances and
    strongly-held provider callables (module docstring has the model).

    Thread-safe; snapshots are merge-SUMMED per name across sources so
    concurrent subsystems (two schedulers, N caches) read as one
    process. A source whose snapshot raises is skipped for that
    snapshot (telemetry must never take the serving path down with
    it).
    """

    def __init__(self) -> None:
        self._lock = _lockwitness.make_lock("MetricsRegistry._lock")
        # prefix -> list of (weakref-to-instance | callable)
        self._sources: "dict[str, list]" = {}  # guarded by: _lock

    def register(self, prefix: str,
                 source: "object | Callable[[], dict]") -> None:
        """Attach a source under ``prefix``. Instances (anything with a
        ``metrics_snapshot()`` method) are weakly referenced; bare
        callables returning a flat dict are held strongly."""
        if not prefix or not all(
                part for part in prefix.split(".")):
            raise ValueError(f"prefix must be dotted words, got {prefix!r}")
        entry = source if callable(source) and not hasattr(
            source, "metrics_snapshot") else weakref.ref(source)
        with self._lock:
            self._sources.setdefault(prefix, []).append(entry)

    def unregister(self, prefix: str) -> None:
        """Drop every source under ``prefix`` (tests)."""
        with self._lock:
            self._sources.pop(prefix, None)

    def _live_sources(self) -> "list[tuple[str, Callable[[], dict]]]":
        out = []
        with self._lock:
            for prefix, entries in list(self._sources.items()):
                kept = []
                for entry in entries:
                    if isinstance(entry, weakref.ref):
                        obj = entry()
                        if obj is None:
                            continue  # instance was garbage-collected
                        kept.append(entry)
                        out.append((prefix, obj.metrics_snapshot))
                    else:
                        kept.append(entry)
                        out.append((prefix, entry))
                if kept:
                    self._sources[prefix] = kept
                else:
                    del self._sources[prefix]
        return out

    #: Metric-name suffixes that are NOT additive across instances:
    #: config bounds and latency summaries. Two live schedulers' p99s
    #: do not add — summing would stamp a latency no request saw into
    #: the bench summary — so these merge by MAX (the conservative
    #: worst-instance reading, which is what an SLO check wants).
    #: Everything else (counters, occupancy, queue depth) sums.
    _MAX_MERGED_SUFFIXES = ("max_size", "capacity", "demote_after",
                            "p50_ms", "p99_ms", "mean_ms",
                            "skew_max_over_median")

    def snapshot(self) -> "dict[str, float]":
        """One consistent-per-source cut of every registered metric,
        dotted names, merged across same-prefix sources — counters sum,
        the non-additive gauges named in :data:`_MAX_MERGED_SUFFIXES`
        take the max. (Consistency is per SOURCE — each subsystem's
        snapshot is taken under its own lock — not global: a
        registry-wide stop-the-world would stall the serving path for
        telemetry.)"""
        merged: "dict[str, float]" = {}
        for prefix, fn in self._live_sources():
            try:
                values = fn()
            except Exception:
                continue  # dhqr: ignore[DHQR006] telemetry-only path: a
                # source mid-teardown (GC race, shut-down scheduler) must
                # not fail an unrelated snapshot; its numbers just skip
            for name, val in _flatten(prefix, values).items():
                if name.rsplit(".", 1)[-1] in self._MAX_MERGED_SUFFIXES:
                    merged[name] = max(merged.get(name, val), val)
                else:
                    merged[name] = merged.get(name, 0.0) + val
        return dict(sorted(merged.items()))

    # ------------------------------------------------------------ exporters

    def export_jsonl(self, path: str, clock=time.time,
                     **extra) -> dict:
        """Append one ``{"ts": ..., "metrics": {...}}`` JSON line to
        ``path`` and return the record. ``clock`` is injectable so
        tests (and fake-clock benchmarks) stamp deterministically;
        ``extra`` keys ride at the top level (phase names, run ids)."""
        record = dict(extra)
        record["ts"] = round(float(clock()), 3)
        record["metrics"] = self.snapshot()
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(record) + "\n")
        return record

    def export_prometheus(self, namespace: str = "dhqr") -> str:
        """The Prometheus text exposition format: one ``# TYPE``-tagged
        gauge per metric, every name sanitized through
        :func:`prometheus_name` (round-15 hygiene: dotted registry
        names — and the dashes/colons inside bucket labels and fault
        site names — must land as VALID prometheus identifiers, and two
        registry names that sanitize identically must not emit
        conflicting duplicate series, so collisions get a
        deterministic ``_dupN`` suffix in sorted-name order). (Gauge,
        not counter, uniformly: the registry also carries
        occupancy/percentile values, and a scraper treats a
        monotonically increasing gauge correctly.)"""
        lines = []
        seen: "dict[str, int]" = {}
        for name, value in self.snapshot().items():  # sorted by name
            metric = prometheus_name(name, namespace=namespace)
            bump = seen.get(metric, 0)
            seen[metric] = bump + 1
            if bump:
                metric = f"{metric}_dup{bump}"
            lines.append(f"# TYPE {metric} gauge")
            if value == int(value):
                lines.append(f"{metric} {int(value)}")
            else:
                lines.append(f"{metric} {value}")
        return "\n".join(lines) + ("\n" if lines else "")


#: What a Prometheus metric name must match (the exposition-format
#: grammar, colons excluded — they are reserved for recording rules).
_PROM_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def prometheus_name(name: str, namespace: str = "dhqr") -> str:
    """One dotted registry name as a VALID prometheus identifier:
    ``serve.cache.hits`` -> ``dhqr_serve_cache_hits``. Every character
    outside ``[a-zA-Z0-9_]`` folds to ``_`` (dots, the dashes/colons in
    bucket labels like ``64x16:float32``, braces from raw XLA property
    names), runs collapse to one ``_``, and a leading digit — possible
    only with an empty namespace — gets a ``_`` prefix. The round-trip
    test in tests/test_obs.py holds this over the full live registry
    snapshot."""
    raw = f"{namespace}_{name}" if namespace else str(name)
    metric = re.sub(r"_+", "_", re.sub(r"[^a-zA-Z0-9_]", "_", raw))
    metric = metric.rstrip("_") or "_"
    if not re.match(r"[a-zA-Z_]", metric):
        metric = "_" + metric
    assert _PROM_NAME_RE.match(metric), (name, metric)
    return metric


# --------------------------------------------------------------------------
# The process-default registry + the lazy default providers.

def _faults_provider() -> dict:
    """``faults.fired`` / ``faults.visits`` totals + per-site counts of
    WHATEVER harness is currently armed (nothing armed = no rows)."""
    from dhqr_tpu.faults import harness as _faults

    armed = _faults.active()
    if armed is None:
        return {}
    per_site = armed.stats()
    out = {
        "fired": sum(s["fired"] for s in per_site.values()),
        "visits": sum(s["visits"] for s in per_site.values()),
    }
    for site, counts in per_site.items():
        out[f"fired.{site}"] = counts["fired"]
        out[f"visits.{site}"] = counts["visits"]
    return out


def _tune_provider() -> dict:
    """tune's ``plan_gate_stats()`` as registry numbers: total recorded
    numeric-gate failures, distinct demoted keys, demoted lookups."""
    from dhqr_tpu.tune.search import PLAN_DEMOTE_AFTER, plan_gate_stats

    stats = plan_gate_stats()
    failures = stats.get("failures", {})
    return {
        "failures": sum(failures.values()),
        "failing_keys": len(failures),
        "demoted_keys": sum(1 for v in failures.values()
                            if v >= PLAN_DEMOTE_AFTER),
        "demoted_lookups": stats.get("demoted_lookups", 0),
        "wire_demoted_lookups": stats.get("wire_demoted_lookups", 0),
        "demote_after": stats.get("demote_after", PLAN_DEMOTE_AFTER),
    }


def _numeric_provider() -> dict:
    """The numeric ladder's module counters (``numeric.fallbacks`` et
    al. — see ``dhqr_tpu.numeric.ladder.COUNTERS``). The known names
    are emitted as zeros before the first bump so the series exist in
    every snapshot (scrapers want stable series, not ones that appear
    mid-run)."""
    from dhqr_tpu.numeric.ladder import COUNTERS

    out: dict = {name: 0 for name in (
        "guarded_calls", "screen_rejects", "fallbacks", "recovered",
        "exhausted")}
    out.update(COUNTERS.snapshot())
    return out


def _obs_provider() -> dict:
    """The armed trace recorder's own accounting (minted/spans/dropped),
    empty when tracing is disarmed."""
    from dhqr_tpu.obs import trace as _trace

    recorder = _trace.active()
    if recorder is None:
        return {}
    return recorder.stats()


def _xray_provider() -> dict:
    """The armed xray store's capture accounting (``xray.captures`` /
    ``xray.reports`` / ``xray.unsupported`` ...), empty when capture is
    disarmed — same armed-harness pattern as ``faults.*``/``obs.*``."""
    from dhqr_tpu.obs import xray as _xray

    store = _xray.active()
    if store is None:
        return {}
    return store.stats()


def _pulse_provider() -> dict:
    """The armed pulse store's runtime-comms accounting
    (``comms.captures`` / ``comms.dhqr306_failures`` /
    ``comms.skew_max_over_median`` / ``comms.measured_collective_s``
    ...), empty when pulse profiling is disarmed (round 16)."""
    from dhqr_tpu.obs import pulse as _pulse

    store = _pulse.active()
    if store is None:
        return {}
    return store.stats()


def _armor_provider() -> dict:
    """The armed armor state's ABFT accounting (``armor.verifications``
    / ``armor.detections`` / ``armor.recovered_redispatch`` /
    ``armor.recovered_degrade`` / ``armor.typed_failures`` /
    ``armor.degraded_labels`` / ``armor.wire_trips``), empty when the
    verification seam is disarmed — the armed-harness pattern of
    ``faults.*``/``obs.*`` (round 19)."""
    from dhqr_tpu import armor as _armor

    state = _armor.active()
    if state is None:
        return {}
    return state.metrics_snapshot()


def _solvers_provider() -> dict:
    """The round-17 solver families' module counters
    (``solvers.sketch_calls`` / ``solvers.update_refactors`` / ... —
    ``dhqr_tpu.solvers.{sketch,update}.COUNTERS``). Known names emitted
    as zeros before the first bump, like ``numeric.*`` — scrapers want
    stable series."""
    from dhqr_tpu.solvers.sketch import COUNTERS as _sk_counters
    from dhqr_tpu.solvers.update import COUNTERS as _up_counters

    out: dict = {name: 0 for name in (
        "sketch_calls", "sketch_operator_draws", "update_steps",
        "downdate_steps", "update_solves", "update_refactors",
        "update_breakdowns", "update_screen_rejects")}
    out.update(_sk_counters.snapshot())
    out.update(_up_counters.snapshot())
    return out


_REGISTRY: "MetricsRegistry | None" = None
_REGISTRY_LOCK = _lockwitness.make_lock("metrics._REGISTRY_LOCK")


def _new_default_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.register("faults", _faults_provider)
    reg.register("tune.plan_gate", _tune_provider)
    reg.register("numeric", _numeric_provider)
    reg.register("obs", _obs_provider)
    reg.register("xray", _xray_provider)
    reg.register("comms", _pulse_provider)
    reg.register("solvers", _solvers_provider)
    reg.register("armor", _armor_provider)
    # serve.cache.* / serve.sched.* have no lazy provider: every
    # ExecutableCache and AsyncScheduler instance self-registers at
    # construction (weakly — test instances evaporate with GC).
    return reg


def registry() -> MetricsRegistry:
    """The process-default registry (created on first use, with the
    default providers wired)."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = _new_default_registry()
    return _REGISTRY


def reset_registry() -> MetricsRegistry:
    """Replace the process-default registry with a fresh one (tests —
    instance sources registered by long-gone schedulers/caches are
    weakly held anyway, but a reset makes isolation exact). Returns
    the new registry."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = _new_default_registry()
    return _REGISTRY
