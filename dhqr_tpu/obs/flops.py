"""Analytic per-engine flop model — the denominator of every MFU claim.

The TPU linear-algebra paper (arXiv 2112.09017) reports QR/DMM results
as *fraction of peak per chip*; the reference repo prints runtime ratios
only. To report either, the useful-work numerator must be pinned down
once, in closed form, per engine — not re-derived in each benchmark
(bench.py's ``4/3 N^3`` was the square-matrix special case of
:func:`qr_flops`, written inline).

These are the standard LAPACK working-note operation counts for REAL
dtypes (complex multiplies ~4x the real count; callers on complex
inputs scale explicitly — nothing here inspects dtypes). They count
*useful* algorithmic work, deliberately ignoring padding, precision
emulation passes, and engine bookkeeping — so ``analytic / measured
cost_analysis flops`` reads as a padding/overhead ratio, and
``analytic / seconds / peak`` is the honest (conservative) MFU.

Deliberately stdlib-only (no jax, no package deps): the regress gate
and the xray table renderer import this in any python.

Golden-tested in tests/test_xray.py at three shapes per engine against
the literal closed forms.
"""

from __future__ import annotations

__all__ = [
    "apply_qt_flops",
    "back_substitute_flops",
    "batched_lstsq_flops",
    "batched_qr_flops",
    "cholqr_flops",
    "lstsq_flops",
    "qr_flops",
    "qr_update_flops",
    "sketched_lstsq_flops",
    "tsqr_flops",
    "updatable_solve_flops",
]


def qr_flops(m: int, n: int) -> float:
    """Householder QR factorization of (m, n), m >= n, factor only
    (packed reflectors + R; Q never formed): ``2mn^2 - (2/3)n^3``
    (LAPACK geqrf count; the blocked compact-WY engine performs the
    same leading-order work — the T-factor/aggregation overhead is
    engine bookkeeping, not counted). Square m = n gives the
    ``(4/3)n^3`` bench.py always used."""
    m, n = float(m), float(n)
    return 2.0 * m * n * n - (2.0 / 3.0) * n ** 3


def apply_qt_flops(m: int, n: int, k: int = 1) -> float:
    """Apply Q^T (m x n packed reflectors) to an (m, k) block:
    ``4mnk - 2n^2 k`` (LAPACK ormqr count; k = 1 for a vector RHS)."""
    m, n, k = float(m), float(n), float(k)
    return 4.0 * m * n * k - 2.0 * n * n * k


def back_substitute_flops(n: int, k: int = 1) -> float:
    """Triangular solve with the n x n R against k right-hand sides:
    ``n^2 k``."""
    n, k = float(n), float(k)
    return n * n * k


def lstsq_flops(m: int, n: int, refine: int = 0) -> float:
    """QR least squares on (m, n) with one RHS vector: factor + Q^T b +
    back substitution, plus ``refine`` iterative-refinement sweeps
    (each: residual matvec ``2mn`` + one more apply/solve pair)."""
    base = (qr_flops(m, n) + apply_qt_flops(m, n, 1)
            + back_substitute_flops(n, 1))
    sweep = (2.0 * float(m) * float(n) + apply_qt_flops(m, n, 1)
             + back_substitute_flops(n, 1))
    return base + max(0, int(refine)) * sweep


def tsqr_flops(m: int, n: int, p: int) -> float:
    """Communication-avoiding TSQR on (m, n) over ``p`` row blocks:
    ``p`` local QRs of (m/p, n) plus ``p - 1`` pairwise combine QRs of
    stacked (2n, n) blocks (the binary reduction tree performs exactly
    p - 1 combines regardless of its shape)."""
    p = max(1, int(p))
    return p * qr_flops(m / p, n) + (p - 1) * qr_flops(2 * n, n)


def cholqr_flops(m: int, n: int, passes: int = 2) -> float:
    """CholeskyQR on (m, n), ``passes`` Gram/Cholesky/solve passes
    (cholqr2 = 2, cholqr3 = 3). Per pass: Gram matrix ``m n^2`` (syrk,
    symmetric half), Cholesky ``n^3 / 3``, triangular solve of the m x n
    block ``m n^2``."""
    m, n = float(m), float(n)
    per_pass = 2.0 * m * n * n + (n ** 3) / 3.0
    return max(1, int(passes)) * per_pass


def sketched_lstsq_flops(m: int, n: int, s: int, refine: int = 0) -> float:
    """Sketch-and-precondition least squares on (m, n) with an s-row
    count-sketch core (round 17, ``dhqr_tpu.solvers.sketch``): sketch
    application ``2mn + 2m`` (sign multiply + bucket add per entry of A
    and b), the CholeskyQR core — Gram syrk ``s n^2`` (symmetric half,
    the :func:`cholqr_flops` counting convention) + Cholesky
    ``n^3/3`` — the semi-normal x0 (``2sn`` for ``(SA)^H Sb`` + two
    n x n triangular solves), then ``refine`` R-preconditioned CGLS
    iterations — each one A-matvec + one A^H-matvec (``4mn``) + two
    n x n triangular solves (``2n^2``) + ``~6m`` vector updates. The
    SRHT variant pays ``2 p n log2 p`` butterflies instead of the 2mn
    sketch application; the model deliberately counts the count-sketch
    (default) form — one closed form per engine family, like
    :func:`tsqr_flops` counting the binary tree."""
    m, n, s = float(m), float(n), float(s)
    base = (2.0 * m * n + 2.0 * m                 # sketch application
            + s * n * n + (n ** 3) / 3.0         # Gram syrk + Cholesky
            + 2.0 * s * n + 2.0 * n * n)         # semi-normal x0
    sweep = 4.0 * m * n + 2.0 * n * n + 6.0 * m
    return base + max(0, int(refine)) * sweep


def qr_update_flops(m: int, n: int) -> float:
    """One rank-1 update/downdate of a live (m, n) factorization
    (``dhqr_tpu.solvers.update.UpdatableQR``): the Gram-side matvec
    ``w = A^H u`` (``2mn``), the data update ``A += u v^H`` (``2mn``),
    the ``u . u`` dot (``2m``), three rank-1 symmetric Gram updates
    (``6n^2``), and — round 18 — the incremental R refresh as one
    Givens append plus one hyperbolic removal sweep (n rotations of
    two n-vectors each, ``6n^2`` per sweep = ``12n^2``), replacing the
    round-17 ``n^3/3`` full re-Cholesky that was the amortization
    floor (ROADMAP item 4). The whole step is now O(mn + n^2)."""
    m, n = float(m), float(n)
    return 4.0 * m * n + 2.0 * m + 18.0 * n * n


def updatable_solve_flops(m: int, n: int, refine: int = 1) -> float:
    """One CSNE solve against a live (m, n) factorization: ``A^H b``
    (``2mn``) + two n x n triangular solves (``2n^2``), plus ``refine``
    corrected sweeps (residual matvec + Gram-side matvec ``4mn`` + two
    more triangular solves)."""
    m, n = float(m), float(n)
    base = 2.0 * m * n + 2.0 * n * n
    sweep = 4.0 * m * n + 2.0 * n * n
    return base + max(0, int(refine)) * sweep


def batched_qr_flops(batch: int, m: int, n: int) -> float:
    """Stacked (batch, m, n) factor-only dispatch of the vmapped
    blocked engine: batch independent factorizations."""
    return max(0, int(batch)) * qr_flops(m, n)


def batched_lstsq_flops(batch: int, m: int, n: int,
                        refine: int = 0) -> float:
    """Stacked (batch, m, n) + (batch, m) least-squares dispatch:
    batch independent single-RHS solves (in-program refinement sweeps
    included, as on :func:`lstsq_flops`)."""
    return max(0, int(batch)) * lstsq_flops(m, n, refine=refine)
