"""Analytic network-cost model — the comms half of the roofline.

``obs.flops`` pins the useful-work numerator of every MFU claim; this
module pins the *wire* numerator of every comms claim: how long a
collective of a given family and payload SHOULD take on a known
interconnect, what effective bandwidth a measured collective achieved,
and where a program sits on the comms-vs-compute roofline. It is the
model behind the DHQR306 runtime contract (``obs.pulse``): measured
collective time must be explainable by traced volume ÷ interconnect
bandwidth × slack — the runtime counterpart of dhqr-audit's static
DHQR302 volume budget, and the before/after scale ROADMAP item 3's
compressed collectives (EQuARX, arXiv 2506.17615) will be judged on.

Algorithm factors follow the standard ring/bidirectional accounting
(the redistribution paper, arXiv 2112.01075, makes collective *choice*
the decisive cost): with the repo's volume convention — a collective's
payload is its OUTPUT aval bytes on one device (analysis/cost_model.py
docstring) — an all-reduce of an N-byte result moves ``2·(P-1)/P · N``
bytes over the slowest link, an all-gather of an N-byte gathered
result ``(P-1)/P · N``, a permute exactly ``N``.

Deliberately **stdlib-only** (no jax): the pulse CLI table and the
regress gate import this in any python, and the model must be
unit-testable without a backend.
"""

from __future__ import annotations

__all__ = [
    "ALGO_FACTORS",
    "FAMILY_TOKENS",
    "WIRE_ITEMSIZE",
    "classify_event",
    "collective_time_s",
    "comms_roofline",
    "effective_gbps",
    "explain_measured",
    "wire_bytes",
]

#: Wire bytes per f32 word under each dhqr-wire comms mode — kept in
#: sync with dhqr_tpu.precision.WIRE_ITEMSIZE (this module is
#: deliberately stdlib-only and must stay importable without the
#: package's jax-touching path; the parity is pinned by test).
WIRE_ITEMSIZE = {None: None, "bf16": 2, "int8": 1,
                 "dcn:bf16": 2, "dcn:int8": 1}

#: XLA HLO instruction-name tokens -> jax collective family, the
#: vocabulary shared by profiler trace events (``all-reduce.12``) and
#: the jaxpr census (``psum``). Longest-match-first where tokens nest
#: (``reduce-scatter`` contains neither of the others; ``all-to-all``
#: must win over nothing). ``collective-permute`` covers ppermute and
#: pshuffle lowerings.
FAMILY_TOKENS = (
    ("reduce-scatter", "reduce_scatter"),
    ("all-reduce", "psum"),
    ("all-gather", "all_gather"),
    ("all-to-all", "all_to_all"),
    ("collective-permute", "ppermute"),
    ("collective-broadcast", "pbroadcast"),
)

#: jaxpr primitive name -> the family key used above (the reduction
#: variants all lower to all-reduce; psum_scatter to reduce-scatter).
PRIMITIVE_FAMILY = {
    "psum": "psum", "pmin": "psum", "pmax": "psum",
    "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter", "psum_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute", "pshuffle": "ppermute",
    "pbroadcast": "pbroadcast",
}


def classify_event(name: str) -> "str | None":
    """Collective family of one profiler event name (an HLO
    instruction like ``all-reduce.8`` or a fusion embedding one), or
    None for non-collective events."""
    low = str(name).lower()
    for token, family in FAMILY_TOKENS:
        if token in low:
            return family
    return None


#: Per-family wire multipliers f(P): ``wire_bytes = f(P) * payload``
#: under the repo's output-aval payload convention. A family not listed
#: (a future collective) conservatively uses 1.0.
ALGO_FACTORS = {
    # all-reduce = reduce-scatter + all-gather over the same N bytes.
    "psum": lambda P: 2.0 * (P - 1) / P,
    # payload is the GATHERED (P*local) result; the wire moves the
    # other devices' (P-1) local shares.
    "all_gather": lambda P: (P - 1) / P,
    "reduce_scatter": lambda P: (P - 1) / P,
    # each device sends/receives (P-1)/P of its payload.
    "all_to_all": lambda P: (P - 1) / P,
    "ppermute": lambda P: 1.0,
    "pbroadcast": lambda P: (P - 1) / P,
}


def wire_bytes(family: str, payload_bytes: float, P: int) -> float:
    """Bytes a ``family`` collective of ``payload_bytes`` actually puts
    on the slowest link of a P-device ring (0 at P <= 1: nothing
    leaves the chip)."""
    if P <= 1:
        return 0.0
    factor = ALGO_FACTORS.get(family, lambda _p: 1.0)
    return factor(int(P)) * float(payload_bytes)


def collective_time_s(family: str, payload_bytes: float, P: int,
                      link_gbps: float) -> "float | None":
    """Lower-bound wall time of one collective on a ``link_gbps`` GB/s
    interconnect (bandwidth term only — latency is absorbed by the
    DHQR306 slack), or None without a known link speed."""
    if not link_gbps:
        return None
    return wire_bytes(family, payload_bytes, P) / (link_gbps * 1e9)


def effective_gbps(wire_bytes_moved: float,
                   seconds: float) -> "float | None":
    """Achieved wire bandwidth of a measured collective (GB/s), or
    None for a degenerate measurement."""
    if not seconds or seconds <= 0:
        return None
    return wire_bytes_moved / seconds / 1e9


def explain_measured(family: str, measured_s: float,
                     volume_bytes: float, P: int, link_gbps: float,
                     slack: float,
                     wire_format: "str | None" = None,
                     dcn_volume_bytes: float = 0.0,
                     dcn_gbps: "float | None" = None) -> dict:
    """The DHQR306 per-family check: is ``measured_s`` explainable by
    ``volume ÷ interconnect bandwidth × slack``?

    ``wire_format`` (dhqr-wire, round 18) tags a compressed dispatch:
    the traced census computes ``volume_bytes`` from the collective's
    OUTPUT avals, which under a compressed seam ARE the bf16/int8 wire
    payloads — so the bound here is automatically the compressed-wire
    bound, and a compressed engine must be ~2x faster-explainable or
    DHQR306 reads the regression. The tag also lets the roofline
    report the f32-equivalent volume (``x4 / wire itemsize``).

    Round 20 (dhqr-pod): ``dcn_volume_bytes`` is the share of
    ``volume_bytes`` whose collectives cross the DCN tier of a two-tier
    pod mesh (the traced census splits it by axis name —
    ``analysis.comms_pass.CommsStats.dcn_volume_bytes``); the remainder
    is ICI-local. Each tier is bounded against its OWN bandwidth and
    the bounds sum — DCN is 10-25x slower, so pricing the whole volume
    at ICI speed would fail every honest two-tier engine, and pricing
    it at DCN speed would let an ICI regression hide under the DCN
    floor. When the DCN share is non-zero but no DCN bandwidth is
    published for the device kind, the check SKIPS with that reason
    (never a crash, never a silently-wrong bound — satellite contract
    of utils/platform.device_dcn_gbps). Both arguments default to the
    pre-pod behavior: zero DCN share, single-tier bound.

    Returns ``{"status": "ok" | "fail" | "skip", "reason", "bound_s",
    "effective_gbps", "bandwidth_pct"}`` — ``skip`` (with the reason)
    when no link speed is published (CPU topologies) or the volume is
    zero; a measurement FASTER than the wire bound is fine (overlap,
    in-node shortcuts), only slower-than-explainable fails."""
    out: dict = {"family": family, "measured_s": round(measured_s, 6),
                 "volume_bytes": int(volume_bytes)}
    if wire_format is not None:
        out["wire_format"] = wire_format
        itemsize = WIRE_ITEMSIZE.get(wire_format)
        if itemsize:
            # What the same words would have cost uncompressed (f32):
            # the before/after the compressed-collectives claim is
            # judged on (ROADMAP item 3).
            out["f32_equivalent_bytes"] = int(volume_bytes * 4 / itemsize)
    dcn_share = max(0.0, min(float(dcn_volume_bytes or 0.0),
                             float(volume_bytes)))
    if dcn_share > 0:
        out["dcn_volume_bytes"] = int(dcn_share)
    ici_share = float(volume_bytes) - dcn_share
    moved = wire_bytes(family, volume_bytes, P)
    eff = effective_gbps(moved, measured_s)
    if eff is not None:
        out["effective_gbps"] = round(eff, 3)
    if not link_gbps:
        out["status"] = "skip"
        out["reason"] = ("no published interconnect bandwidth for this "
                         "device_kind (CPU topologies move words through "
                         "host memory)")
        return out
    if volume_bytes <= 0 or moved <= 0:
        out["status"] = "skip"
        out["reason"] = "no traced wire volume for this family"
        return out
    if dcn_share > 0 and not dcn_gbps:
        out["status"] = "skip"
        out["reason"] = (
            "collectives cross the DCN tier but no DCN bandwidth is "
            "published for this device_kind "
            "(utils/platform.device_dcn_gbps returned None) — a "
            "single-tier bound would be silently wrong in either "
            "direction")
        return out
    bound = wire_bytes(family, ici_share, P) / (link_gbps * 1e9)
    if dcn_share > 0:
        bound += wire_bytes(family, dcn_share, P) / (dcn_gbps * 1e9)
        out["dcn_gbps"] = round(float(dcn_gbps), 3)
    out["bound_s"] = round(bound, 6)
    out["bandwidth_pct"] = round(100.0 * (eff or 0.0) / link_gbps, 2)
    if measured_s <= bound * slack:
        out["status"] = "ok"
    else:
        out["status"] = "fail"
        out["reason"] = (
            f"measured {measured_s:.6f}s exceeds the wire explanation "
            f"{bound:.6f}s x slack {slack:g} — the collective is slower "
            "than volume / bandwidth accounts for (serialization, "
            "congestion, or a schedule regression)")
    return out


def comms_roofline(comms_s: "float | None", compute_s: "float | None",
                   link_gbps: "float | None" = None,
                   wire_bytes_moved: "float | None" = None) -> dict:
    """The comms side of the roofline for one executable: which side
    dominates, the comms fraction of the critical path, and the
    overlap headroom (how much of the collective time a perfect
    schedule could hide under compute). Degrades field-by-field to
    null-with-reason — the xray table renders whatever subset exists."""
    out: dict = {}
    if comms_s is None or compute_s is None:
        out["comms_bound"] = None
        out["comms_reason"] = ("no measured comms/compute split for this "
                               "program")
        return out
    total = comms_s + compute_s
    out["comms_s"] = round(comms_s, 6)
    out["compute_s"] = round(compute_s, 6)
    out["comms_fraction"] = round(comms_s / total, 4) if total else 0.0
    out["comms_bound"] = "comms" if comms_s > compute_s else "compute"
    # A schedule can hide min(comms, compute) of the collective time
    # behind MXU work; what remains is the exposed floor.
    hideable = min(comms_s, compute_s)
    out["overlap_headroom_s"] = round(hideable, 6)
    out["exposed_floor_s"] = round(max(comms_s - compute_s, 0.0), 6)
    if link_gbps and wire_bytes_moved:
        eff = effective_gbps(wire_bytes_moved, comms_s)
        if eff is not None:
            out["effective_gbps"] = round(eff, 3)
            out["bandwidth_pct"] = round(100.0 * eff / link_gbps, 2)
    return out
