"""Precision policy — one object naming every MXU-precision knob at once.

The framework's accuracy/throughput trade lives in four places that must be
chosen together to mean anything (docs/DESIGN.md "Precision policy"):

* the PANEL precision — the dependent chains (reflector norms/dots, the
  compact-WY T-factor recurrence) whose rounding every later column
  inherits;
* the TRAILING precision — the wide trailing-update GEMMs holding ~all the
  flops, whose rounding is NOT amplified (each output element is touched
  once);
* the APPLY precision — the Q/Q^H applies and triangular solves of the
  solve stage;
* the REFINEMENT count — iterative-refinement sweeps that reuse the stored
  factorization (``r = b - A x; x += solve(r)``, residual matvec at full
  precision) and buy back the backward error a cheaper factor gave up.

On TPU the MXU's native pass is bf16xbf16->f32: ``precision="highest"``
emulates full f32 with 6 passes, ``"high"`` with 3, ``"default"`` runs the
single native pass. Splitting the trailing precision away from the panel
precision therefore trades 2-6x of the bulk MXU work against a measured
backward-error cost (2.7e-5 at 4096^2 with trailing="high",
benchmarks/tpu_trailing_precision_probe.py) — which ``refine`` recovers at
a few percent of the factorization cost. A :class:`PrecisionPolicy` names
one point in that space; the named presets in :data:`PRECISION_POLICIES`
are the grid the bench ladder A/Bs (bench.py policy stages,
benchmarks/policy_ladder.py).

Every engine tier accepts ``policy=``: the factor-only entry points
(``blocked_householder_qr``, ``sharded_blocked_qr``, ``tsqr_r``,
``cholesky_qr2``) consume the precision fields and document that the
solve-stage fields (``apply``, ``refine``) do not apply to them; the solve
surfaces (``qr``/``lstsq``, ``tsqr_lstsq``, ``cholesky_qr_lstsq``) consume
all four.
"""

from __future__ import annotations

import dataclasses

# MXU precisions orderable by pass count on TPU (f32 inputs):
# highest = 6 passes, high = 3, default = 1 native bf16 pass. On CPU/GPU
# backends the names still parse but the passes collapse to native f32 —
# which is why the CPU ladder artifact shows flat errors and the TPU ladder
# is the decisive one.
TRAILING_PRECISIONS = ("highest", "high", "default")

# Effective MXU passes per f32 GEMM at each precision name — the
# effective-FLOP-ceiling model of docs/DESIGN.md (peak_bf16 / passes).
MXU_PASSES = {"highest": 6, "high": 3, "default": 1, "float32": 6}

# Collective wire formats (dhqr-wire, round 18). Defined HERE — the
# jax-free module — so the stdlib-only analysis tier (cost_model, the
# regress gate) and the seam itself (parallel/wire.py, which needs jax)
# share one vocabulary without an import cycle: precision <- wire <-
# engines. WIRE_ITEMSIZE is the bytes-per-f32-word factor the
# compressed DHQR302 budgets are priced with (int8's per-block scale
# sidecars are absorbed by the contract slack).
#
# Round 20 (dhqr-pod) adds the topology-tiered rungs "dcn:bf16" /
# "dcn:int8" (EQuARX, arXiv 2506.17615: compress only where the wire is
# slow): on a two-tier hierarchical schedule the ICI legs stay f32 and
# ONLY the isolated DCN crossing is compressed (+armor-tagged); on a
# flat schedule / 1-D mesh / 1-slice topology there is no isolated DCN
# leg, so the dcn:* rungs degrade to the exact f32 passthrough by
# construction. Their WIRE_ITEMSIZE prices the DCN leg (the tier the
# tiered DHQR302 budgets compress); the f32 ICI legs are priced at 4
# bytes by the tiered cost model, not by this factor.
COMMS_MODES = ("bf16", "int8", "dcn:bf16", "dcn:int8")
WIRE_ITEMSIZE = {None: None, "bf16": 2, "int8": 1,
                 "dcn:bf16": 2, "dcn:int8": 1}


def resolve_comms(comms) -> "str | None":
    """Validate/normalize a collective wire format: None (also the
    explicit "none"/"f32" spellings) keeps the uncompressed wire."""
    if comms is None or comms in ("none", "f32"):
        return None
    if comms not in COMMS_MODES:
        raise ValueError(
            f"comms must be one of {COMMS_MODES} or None (uncompressed), "
            f"got {comms!r}"
        )
    return comms


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One named point in the precision/refinement trade space.

    Attributes:
      panel: precision of the accuracy-critical dependent chains — panel
        factorization (reflector norms/dots) and the compact-WY T-factor.
        These errors are inherited by every later column, so the presets
        never lower this field.
      trailing: precision of the trailing-update GEMMs only (and, for the
        row engines, the bulk GEMM analogue: TSQR leaf trailing updates,
        the CholeskyQR Gram syrk). ``None`` means "same as panel" — no
        split.
      apply: precision of the solve stage's Q/Q^H applies and the
        refinement residual reuse. ``None`` means "same as panel".
      refine: iterative-refinement sweeps for the solve surfaces. Each
        sweep reuses the stored factorization (one full-precision residual
        matvec + one extra solve); the factor-only entry points ignore it
        by contract (a factorization has nothing to refine).
      comms: wire format of the sharded tier's collectives (dhqr-wire,
        round 18) — ``None`` keeps the uncompressed f32 wire (programs
        bit-identical to the pre-seam tier by construction), ``"bf16"``
        halves the traced collective volume with f32 accumulation
        everywhere outside the wire, ``"int8"`` quarters it with
        per-(32-row-block, column) scales on the one-hot
        broadcast/gather paths (see ``dhqr_tpu.parallel.wire``). Round
        20 (dhqr-pod) adds the topology-tiered rungs ``"dcn:bf16"`` /
        ``"dcn:int8"``: f32 inside the ICI domain, compressed only at
        the isolated DCN crossing of a two-tier hierarchical schedule
        (exact f32 everywhere on flat/1-tier topologies). Programs with
        no collectives (single-device engines, the batched serving
        dispatch) are unaffected by contract. The presets all keep
        ``comms=None``; compressed comms is selected explicitly, or
        per-platform by a tuned :class:`dhqr_tpu.tune.Plan` under the
        8x-LAPACK gate.
    """

    panel: str = "highest"
    trailing: "str | None" = None
    apply: "str | None" = None
    refine: int = 0
    comms: "str | None" = None

    def __post_init__(self):
        for field, value in (("panel", self.panel),
                             ("trailing", self.trailing),
                             ("apply", self.apply)):
            if value is not None and value not in MXU_PASSES:
                raise ValueError(
                    f"PrecisionPolicy.{field} must be one of "
                    f"{sorted(MXU_PASSES)} or None, got {value!r}"
                )
        if self.refine < 0:
            raise ValueError(f"refine must be >= 0, got {self.refine}")
        object.__setattr__(self, "comms", resolve_comms(self.comms))

    # -- resolution helpers -------------------------------------------------
    def resolved_trailing(self) -> str:
        return self.panel if self.trailing is None else self.trailing

    def resolved_apply(self) -> str:
        return self.panel if self.apply is None else self.apply

    def split_trailing(self) -> "str | None":
        """The ``trailing_precision`` engine argument: None when the policy
        does not actually split (engines treat None as "no split", keeping
        jit cache keys identical to the pre-policy spelling)."""
        t = self.resolved_trailing()
        return None if t == self.panel else t


# The named grid. "accurate" is the library default (6-pass f32 everywhere,
# no refinement — the committed <1e-5 backward-error tier). The split
# presets pair a cheaper trailing precision with ONE refinement sweep: the
# refine step is what makes them candidates rather than accuracy
# regressions (VERDICT r5 item 2 — the untested 2-3x lever).
PRECISION_POLICIES = {
    "accurate": PrecisionPolicy(),
    "balanced": PrecisionPolicy(trailing="high", refine=1),
    "fast": PrecisionPolicy(trailing="default", refine=1),
}

# The A/B ladder the bench + tests sweep: every trailing precision, with
# and without one refinement sweep (6 cells).
POLICY_LADDER = tuple(
    PrecisionPolicy(trailing=None if t == "highest" else t, refine=r)
    for t in TRAILING_PRECISIONS
    for r in (0, 1)
)


def resolve_policy(policy) -> PrecisionPolicy:
    """Accept a policy name, a :class:`PrecisionPolicy`, or a spec string.

    Spec strings name the fields positionally, slash-separated:
    ``"panel"``, ``"panel/trailing"``, ``"panel/trailing/rN"``, and —
    round 18 — a fourth comms-wire segment ``"panel/trailing/rN/bf16"``
    (a :data:`COMMS_MODES` member; e.g. ``"highest/default/r1/bf16"``
    is the bf16-trailing + one-refine + bf16-wire point, and
    ``"highest/bf16"`` compresses the wire only; the round-20 tiered
    rungs spell the same way — ``"highest/dcn:bf16"`` — the ``:`` is
    not a separator). This is the ``DHQR_POLICY`` environment spelling
    (utils/config.py).
    """
    if isinstance(policy, PrecisionPolicy):
        return policy
    if not isinstance(policy, str):
        raise TypeError(
            f"policy must be a PrecisionPolicy, a preset name "
            f"{sorted(PRECISION_POLICIES)}, or a spec string, got "
            f"{type(policy).__name__}"
        )
    if policy in PRECISION_POLICIES:
        return PRECISION_POLICIES[policy]
    parts = policy.split("/")
    # The comms segment is popped FIRST (it is the last segment when
    # present); the wire-format names never collide with the MXU
    # precision names or the rN spelling, so the grammar stays
    # position-free at the tail.
    comms = None
    if parts and parts[-1] in COMMS_MODES:
        comms = parts.pop()
    refine = 0
    if parts and parts[-1][:1] == "r" and parts[-1][1:].isdigit():
        refine = int(parts.pop()[1:])
    if not parts or len(parts) > 2 or not all(parts):
        raise ValueError(
            f"unknown policy {policy!r}: expected a preset name "
            f"{sorted(PRECISION_POLICIES)} or 'panel[/trailing][/rN][/comms]'"
        )
    panel = parts[0]
    trailing = parts[1] if len(parts) == 2 else None
    if trailing == panel:
        trailing = None
    return PrecisionPolicy(panel=panel, trailing=trailing, refine=refine,
                           comms=comms)


def escalation_policies(policy=None, *, base_refine: int = 0,
                        cheap: "bool | None" = None):
    """The accuracy-escalation tail of the numeric fallback ladder
    (``dhqr_tpu.numeric.ladder``): once the engine rungs run out, try
    ``accurate`` (when the caller was running anything cheaper than it
    without refinement), then ``accurate`` with one MORE refinement
    sweep than anything tried so far — the ``fast -> accurate ->
    refine+1`` laddering of docs/DESIGN.md "Numerical robustness".

    ``cheap`` overrides the is-this-policy-cheaper-than-accurate
    derivation for callers who spelled their precision via the classic
    knobs rather than a policy (the ladder passes it explicitly then).
    Returns a tuple of :class:`PrecisionPolicy`.
    """
    pol = resolve_policy(policy) if policy is not None else None
    refine = pol.refine if pol is not None else int(base_refine)
    if cheap is None:
        cheap = pol is not None and bool(
            pol.trailing or pol.apply or pol.comms
            or pol.panel != "highest")
    out = []
    if cheap and refine == 0:
        out.append(PRECISION_POLICIES["accurate"])
    out.append(PrecisionPolicy(refine=refine + 1))
    return tuple(out)


def apply_policy_to_factor_args(policy, precision, trailing_precision,
                                default_precision: str = "highest"):
    """Shared factor-tier merge: map ``policy`` onto the classic
    ``(precision, trailing_precision)`` argument pair.

    ``policy=None`` passes the classic arguments through untouched. With a
    policy, the classic knobs must keep their defaults (the caller's
    ``default_precision`` / None) — a call naming both spellings is
    ambiguous and refuses loudly rather than letting one silently win.
    """
    if policy is None:
        return precision, trailing_precision
    pol = resolve_policy(policy)
    if trailing_precision is not None:
        raise ValueError(
            "pass either policy= or trailing_precision=, not both "
            f"(policy resolves trailing to {pol.resolved_trailing()!r})"
        )
    if precision != default_precision:
        raise ValueError(
            "pass either policy= or precision=, not both "
            f"(policy sets the panel precision to {pol.panel!r})"
        )
    return pol.panel, pol.split_trailing()


def apply_policy_to_comms_arg(policy, comms):
    """Shared sharded-tier merge: map ``policy`` onto the classic
    ``comms`` wire-format argument (same refuse-loudly contract as
    :func:`apply_policy_to_factor_args` — a call naming both spellings
    is ambiguous). ``policy=None`` validates and passes ``comms``
    through."""
    if policy is None:
        return resolve_comms(comms)
    pol = resolve_policy(policy)
    if comms is not None:
        raise ValueError(
            "pass either policy= or comms=, not both "
            f"(policy sets the wire format to {pol.comms!r})"
        )
    return pol.comms
