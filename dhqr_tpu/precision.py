"""Precision policy — one object naming every MXU-precision knob at once.

The framework's accuracy/throughput trade lives in four places that must be
chosen together to mean anything (docs/DESIGN.md "Precision policy"):

* the PANEL precision — the dependent chains (reflector norms/dots, the
  compact-WY T-factor recurrence) whose rounding every later column
  inherits;
* the TRAILING precision — the wide trailing-update GEMMs holding ~all the
  flops, whose rounding is NOT amplified (each output element is touched
  once);
* the APPLY precision — the Q/Q^H applies and triangular solves of the
  solve stage;
* the REFINEMENT count — iterative-refinement sweeps that reuse the stored
  factorization (``r = b - A x; x += solve(r)``, residual matvec at full
  precision) and buy back the backward error a cheaper factor gave up.

On TPU the MXU's native pass is bf16xbf16->f32: ``precision="highest"``
emulates full f32 with 6 passes, ``"high"`` with 3, ``"default"`` runs the
single native pass. Splitting the trailing precision away from the panel
precision therefore trades 2-6x of the bulk MXU work against a measured
backward-error cost (2.7e-5 at 4096^2 with trailing="high",
benchmarks/tpu_trailing_precision_probe.py) — which ``refine`` recovers at
a few percent of the factorization cost. A :class:`PrecisionPolicy` names
one point in that space; the named presets in :data:`PRECISION_POLICIES`
are the grid the bench ladder A/Bs (bench.py policy stages,
benchmarks/policy_ladder.py).

Every engine tier accepts ``policy=``: the factor-only entry points
(``blocked_householder_qr``, ``sharded_blocked_qr``, ``tsqr_r``,
``cholesky_qr2``) consume the precision fields and document that the
solve-stage fields (``apply``, ``refine``) do not apply to them; the solve
surfaces (``qr``/``lstsq``, ``tsqr_lstsq``, ``cholesky_qr_lstsq``) consume
all four.
"""

from __future__ import annotations

import dataclasses

# MXU precisions orderable by pass count on TPU (f32 inputs):
# highest = 6 passes, high = 3, default = 1 native bf16 pass. On CPU/GPU
# backends the names still parse but the passes collapse to native f32 —
# which is why the CPU ladder artifact shows flat errors and the TPU ladder
# is the decisive one.
TRAILING_PRECISIONS = ("highest", "high", "default")

# Effective MXU passes per f32 GEMM at each precision name — the
# effective-FLOP-ceiling model of docs/DESIGN.md (peak_bf16 / passes).
MXU_PASSES = {"highest": 6, "high": 3, "default": 1, "float32": 6}


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One named point in the precision/refinement trade space.

    Attributes:
      panel: precision of the accuracy-critical dependent chains — panel
        factorization (reflector norms/dots) and the compact-WY T-factor.
        These errors are inherited by every later column, so the presets
        never lower this field.
      trailing: precision of the trailing-update GEMMs only (and, for the
        row engines, the bulk GEMM analogue: TSQR leaf trailing updates,
        the CholeskyQR Gram syrk). ``None`` means "same as panel" — no
        split.
      apply: precision of the solve stage's Q/Q^H applies and the
        refinement residual reuse. ``None`` means "same as panel".
      refine: iterative-refinement sweeps for the solve surfaces. Each
        sweep reuses the stored factorization (one full-precision residual
        matvec + one extra solve); the factor-only entry points ignore it
        by contract (a factorization has nothing to refine).
    """

    panel: str = "highest"
    trailing: "str | None" = None
    apply: "str | None" = None
    refine: int = 0

    def __post_init__(self):
        for field, value in (("panel", self.panel),
                             ("trailing", self.trailing),
                             ("apply", self.apply)):
            if value is not None and value not in MXU_PASSES:
                raise ValueError(
                    f"PrecisionPolicy.{field} must be one of "
                    f"{sorted(MXU_PASSES)} or None, got {value!r}"
                )
        if self.refine < 0:
            raise ValueError(f"refine must be >= 0, got {self.refine}")

    # -- resolution helpers -------------------------------------------------
    def resolved_trailing(self) -> str:
        return self.panel if self.trailing is None else self.trailing

    def resolved_apply(self) -> str:
        return self.panel if self.apply is None else self.apply

    def split_trailing(self) -> "str | None":
        """The ``trailing_precision`` engine argument: None when the policy
        does not actually split (engines treat None as "no split", keeping
        jit cache keys identical to the pre-policy spelling)."""
        t = self.resolved_trailing()
        return None if t == self.panel else t


# The named grid. "accurate" is the library default (6-pass f32 everywhere,
# no refinement — the committed <1e-5 backward-error tier). The split
# presets pair a cheaper trailing precision with ONE refinement sweep: the
# refine step is what makes them candidates rather than accuracy
# regressions (VERDICT r5 item 2 — the untested 2-3x lever).
PRECISION_POLICIES = {
    "accurate": PrecisionPolicy(),
    "balanced": PrecisionPolicy(trailing="high", refine=1),
    "fast": PrecisionPolicy(trailing="default", refine=1),
}

# The A/B ladder the bench + tests sweep: every trailing precision, with
# and without one refinement sweep (6 cells).
POLICY_LADDER = tuple(
    PrecisionPolicy(trailing=None if t == "highest" else t, refine=r)
    for t in TRAILING_PRECISIONS
    for r in (0, 1)
)


def resolve_policy(policy) -> PrecisionPolicy:
    """Accept a policy name, a :class:`PrecisionPolicy`, or a spec string.

    Spec strings name the fields positionally, slash-separated:
    ``"panel"``, ``"panel/trailing"``, ``"panel/trailing/rN"`` — e.g.
    ``"highest/default/r1"`` is the bf16-trailing + one-refine point. This
    is the ``DHQR_POLICY`` environment spelling (utils/config.py).
    """
    if isinstance(policy, PrecisionPolicy):
        return policy
    if not isinstance(policy, str):
        raise TypeError(
            f"policy must be a PrecisionPolicy, a preset name "
            f"{sorted(PRECISION_POLICIES)}, or a spec string, got "
            f"{type(policy).__name__}"
        )
    if policy in PRECISION_POLICIES:
        return PRECISION_POLICIES[policy]
    parts = policy.split("/")
    refine = 0
    if parts and parts[-1][:1] == "r" and parts[-1][1:].isdigit():
        refine = int(parts.pop()[1:])
    if not parts or len(parts) > 2 or not all(parts):
        raise ValueError(
            f"unknown policy {policy!r}: expected a preset name "
            f"{sorted(PRECISION_POLICIES)} or 'panel[/trailing][/rN]'"
        )
    panel = parts[0]
    trailing = parts[1] if len(parts) == 2 else None
    if trailing == panel:
        trailing = None
    return PrecisionPolicy(panel=panel, trailing=trailing, refine=refine)


def escalation_policies(policy=None, *, base_refine: int = 0,
                        cheap: "bool | None" = None):
    """The accuracy-escalation tail of the numeric fallback ladder
    (``dhqr_tpu.numeric.ladder``): once the engine rungs run out, try
    ``accurate`` (when the caller was running anything cheaper than it
    without refinement), then ``accurate`` with one MORE refinement
    sweep than anything tried so far — the ``fast -> accurate ->
    refine+1`` laddering of docs/DESIGN.md "Numerical robustness".

    ``cheap`` overrides the is-this-policy-cheaper-than-accurate
    derivation for callers who spelled their precision via the classic
    knobs rather than a policy (the ladder passes it explicitly then).
    Returns a tuple of :class:`PrecisionPolicy`.
    """
    pol = resolve_policy(policy) if policy is not None else None
    refine = pol.refine if pol is not None else int(base_refine)
    if cheap is None:
        cheap = pol is not None and bool(
            pol.trailing or pol.apply or pol.panel != "highest")
    out = []
    if cheap and refine == 0:
        out.append(PRECISION_POLICIES["accurate"])
    out.append(PrecisionPolicy(refine=refine + 1))
    return tuple(out)


def apply_policy_to_factor_args(policy, precision, trailing_precision,
                                default_precision: str = "highest"):
    """Shared factor-tier merge: map ``policy`` onto the classic
    ``(precision, trailing_precision)`` argument pair.

    ``policy=None`` passes the classic arguments through untouched. With a
    policy, the classic knobs must keep their defaults (the caller's
    ``default_precision`` / None) — a call naming both spellings is
    ambiguous and refuses loudly rather than letting one silently win.
    """
    if policy is None:
        return precision, trailing_precision
    pol = resolve_policy(policy)
    if trailing_precision is not None:
        raise ValueError(
            "pass either policy= or trailing_precision=, not both "
            f"(policy resolves trailing to {pol.resolved_trailing()!r})"
        )
    if precision != default_precision:
        raise ValueError(
            "pass either policy= or precision=, not both "
            f"(policy sets the panel precision to {pol.panel!r})"
        )
    return pol.panel, pol.split_trailing()
