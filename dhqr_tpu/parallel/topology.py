"""dhqr-pod — the two-tier ICI/DCN topology descriptor (round 20).

A TPU pod is not a flat ring: chips within a slice talk over ICI
(200-600 GB/s per chip, ``utils/platform._DEVICE_PEAKS``) while slices
talk over the data-center network at 25-50 GB/s — a 10-20x cliff that
"Large Scale Distributed Linear Algebra With TPUs" (arXiv 2112.09017)
shows decides whether dense factorizations scale at all. Until this
round every sharded engine ran flat collectives over a 1-D mesh, paying
the DCN price ~P times per collective; this module names the two tiers
so the wire seam (parallel/wire.py) can reduce inside ICI first, cross
DCN exactly once, and broadcast back.

The descriptor is :class:`TierAxes` — a frozen, hashable value the
engines accept anywhere they accept an ``axis_name`` string. It rides
the ``lru_cache`` build keys unchanged and carries the schedule choice
(``hierarchical=False`` spells the flat joint-axis baseline the pod
benchmark A/Bs against). Engines themselves stay tier-agnostic: the
four helpers at the bottom (:func:`axis_size`, :func:`spec_axes`,
:func:`axis_index`, :func:`axis_label`) are the complete surface an
engine needs, and each degrades to the 1-D spelling on a plain string
axis so the single-tier programs stay byte-identical.

Topology discovery:

* On TPU, multi-slice runtimes expose ``device.slice_index``; devices
  grouped by it give the real (DCN crossings x ICI domain) split.
  Single-slice device sets have one group — no DCN tier, flat mesh.
* On CPU (and for forcing a shape on TPU), ``DHQR_TOPO=PdcnxPici``
  (e.g. ``DHQR_TOPO=2x4``) simulates a factorization, so the same P=8
  host can run as 1x8 / 2x4 / 4x2 and the schedules, contracts, and
  benchmarks exercise the two-tier paths without a pod.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

DCN_AXIS = "dcn"
ICI_AXIS = "ici"

__all__ = [
    "DCN_AXIS",
    "ICI_AXIS",
    "TierAxes",
    "axis_index",
    "axis_label",
    "axis_size",
    "detect_topology",
    "parse_topo",
    "resolve_axis",
    "spec_axes",
]


@dataclasses.dataclass(frozen=True)
class TierAxes:
    """The two-tier mesh axis descriptor the engines thread in place of
    a 1-D ``axis_name`` string.

    ``dcn``/``ici`` name the mesh axes (outer = DCN crossings, inner =
    ICI domain); ``dcn_size``/``ici_size`` are their extents (device
    ``(d, i)`` of the 2-D mesh holds flat block ``d * ici_size + i``,
    the same device order as the 1-D mesh over the same device list).
    ``hierarchical=True`` selects the reduce-inside-ICI-first /
    cross-DCN-once wire schedule; ``False`` keeps the flat joint-axis
    collective over ``(dcn, ici)`` — the measured baseline. Frozen and
    hashable by construction: it is ``lru_cache`` key material in every
    engine ``_build_*``.
    """

    dcn: str = DCN_AXIS
    ici: str = ICI_AXIS
    dcn_size: int = 1
    ici_size: int = 1
    hierarchical: bool = True

    def __post_init__(self):
        if self.dcn_size < 1 or self.ici_size < 1:
            raise ValueError(
                f"tier sizes must be >= 1, got "
                f"{self.dcn_size}x{self.ici_size}"
            )
        if self.dcn == self.ici:
            raise ValueError(
                f"the two tier axes must be distinct, got {self.dcn!r} "
                "for both"
            )

    @property
    def size(self) -> int:
        """Total device count P = dcn_size * ici_size."""
        return self.dcn_size * self.ici_size

    def label(self) -> str:
        """Topology tag for engine labels: ``"2x4"`` (hierarchical) /
        ``"2x4f"`` (flat joint-axis schedule). The two schedules MUST
        label differently: pulse captures once per label and armor
        keys wire demotion on it."""
        return (f"{self.dcn_size}x{self.ici_size}"
                + ("" if self.hierarchical else "f"))


def parse_topo(spec: "str | None") -> "tuple[int, int] | None":
    """Parse a ``DHQR_TOPO``-style ``"PdcnxPici"`` spec (``"2x4"``) into
    ``(dcn_size, ici_size)``; None/empty passes through as None. A
    malformed spec refuses loudly — a typo silently running flat would
    invalidate every pod measurement made under it."""
    if spec is None or not str(spec).strip():
        return None
    parts = str(spec).strip().lower().split("x")
    if len(parts) != 2 or not all(p.isdigit() and int(p) >= 1
                                  for p in parts):
        raise ValueError(
            f"DHQR_TOPO must look like '2x4' (DCNxICI, both >= 1), "
            f"got {spec!r}"
        )
    return int(parts[0]), int(parts[1])


def detect_topology(devices: Sequence,
                    n_devices: "int | None" = None
                    ) -> "tuple[int, int] | None":
    """``(dcn_size, ici_size)`` for a device list, or None when there is
    no two-tier structure (single slice, or nothing detectable).

    Priority: the ``DHQR_TOPO`` env override (validated against the
    device count) wins — it is the CPU simulation knob and the TPU
    force-a-shape knob. Otherwise multi-slice TPU runtimes are detected
    through the per-device ``slice_index`` attribute (falling back to
    ``process_index`` grouping, the multi-host single-slice-per-host
    shape); uniform group sizes are required — a ragged pod is not a
    mesh and refuses loudly.
    """
    count = int(n_devices if n_devices is not None else len(devices))
    spec = parse_topo(os.environ.get("DHQR_TOPO"))
    if spec is not None:
        dcn, ici = spec
        if dcn * ici != count:
            raise ValueError(
                f"DHQR_TOPO={dcn}x{ici} does not factor the device "
                f"count {count} (needs dcn*ici == P)"
            )
        return (dcn, ici) if dcn > 1 else None
    groups: "dict[object, int]" = {}
    for d in devices[:count]:
        key = getattr(d, "slice_index", None)
        if key is None:
            key = getattr(d, "process_index", 0)
        groups[key] = groups.get(key, 0) + 1
    sizes = set(groups.values())
    if len(groups) <= 1 or len(sizes) != 1:
        return None  # one slice (flat), or ragged — no tier structure
    return len(groups), sizes.pop()


def resolve_axis(mesh, axis_name):
    """The engine entry-point resolution: map the caller's ``axis_name``
    onto what the mesh actually is.

    * a :class:`TierAxes` passes through (validated against the mesh);
    * a string naming a mesh axis passes through (the 1-D tier);
    * a string against a 2-D ``(dcn, ici)`` mesh resolves to the
      hierarchical :class:`TierAxes` — so ``sharded_lstsq(A, b,
      mesh=pod_mesh())`` just works with the default ``axis_name``.
    """
    names = tuple(mesh.axis_names)
    if isinstance(axis_name, TierAxes):
        for ax in (axis_name.dcn, axis_name.ici):
            if ax not in names:
                raise ValueError(
                    f"mesh axes {names} do not carry tier axis {ax!r}"
                )
        if (mesh.shape[axis_name.dcn] != axis_name.dcn_size
                or mesh.shape[axis_name.ici] != axis_name.ici_size):
            raise ValueError(
                f"TierAxes {axis_name.label()} does not match mesh "
                f"shape {dict(mesh.shape)}"
            )
        return axis_name
    if axis_name in names:
        return axis_name
    if DCN_AXIS in names and ICI_AXIS in names:
        return TierAxes(dcn_size=int(mesh.shape[DCN_AXIS]),
                        ici_size=int(mesh.shape[ICI_AXIS]))
    raise KeyError(
        f"axis {axis_name!r} not in mesh axes {names} and the mesh is "
        f"not a ({DCN_AXIS!r}, {ICI_AXIS!r}) pod mesh"
    )


def axis_size(mesh, axis) -> int:
    """Total device count of ``axis`` on ``mesh`` — the product of both
    tiers for a :class:`TierAxes`, ``mesh.shape[axis]`` for a string."""
    if isinstance(axis, TierAxes):
        return int(mesh.shape[axis.dcn]) * int(mesh.shape[axis.ici])
    return int(mesh.shape[axis])


def spec_axes(axis):
    """What a ``PartitionSpec`` dimension entry should carry for
    ``axis``: the ``(dcn, ici)`` tuple for a :class:`TierAxes` (sharding
    a dim over both axes, dcn-major — block ``d * ici_size + i`` on
    device ``(d, i)``, the 1-D device order), the string itself
    otherwise."""
    if isinstance(axis, TierAxes):
        return (axis.dcn, axis.ici)
    return axis


def axis_index(axis):
    """The shard body's own linear position along ``axis`` — the
    drop-in for ``lax.axis_index`` that flattens the two tiers
    dcn-major (matching :func:`spec_axes` block order)."""
    from jax import lax

    if isinstance(axis, TierAxes):
        return (lax.axis_index(axis.dcn) * axis.ici_size
                + lax.axis_index(axis.ici))
    return lax.axis_index(axis)


def axis_label(axis, nproc: int) -> str:
    """The ``P=`` token of an engine label: the topology tag
    (``"2x4"``/``"2x4f"``) for a :class:`TierAxes`, the plain device
    count for a 1-D axis — so every single-tier label stays
    byte-identical to previous rounds."""
    if isinstance(axis, TierAxes):
        return axis.label()
    return str(int(nproc))
