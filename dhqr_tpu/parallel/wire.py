"""dhqr-wire — the communication-compression seam under every sharded
collective (ROADMAP item 3; EQuARX, arXiv 2506.17615; the
redistribution paper, arXiv 2112.01075).

The sharded engines spend their scaling budget on two collective
patterns: **one-hot broadcasts** (the owner's panel/column rides a
``psum`` where every other device contributes exact zeros —
parallel/sharded_qr, parallel/sharded_solve) and **combine exchanges**
(TSQR's R-head ``all_gather``, CholeskyQR's dense Gram ``psum``). Both
move f32 words whose low mantissa bits the downstream math does not
need at the cheap end of the accuracy ladder. This module is the ONE
place a collective's *wire format* is chosen:

* ``comms=None`` — the seam is a **verbatim passthrough** to the raw
  ``lax`` collective: same primitive, same operand, same jaxpr. The
  ``accurate`` policy keeps ``comms=None``, so its programs are
  bit-identical to the uncompressed tier *by construction* (pinned by
  tests/test_wire.py's jaxpr-identity test).
* ``comms="bf16"`` — the payload crosses the wire as bfloat16 (2
  bytes/word, ~2x volume cut) and is decompressed to the compute dtype
  on arrival; every flop before and after the collective stays f32.
  On the one-hot broadcast paths the reduction adds exact zeros, so
  the *accumulation is exact* and the only error is the one f32->bf16
  rounding of the payload itself. On dense reductions (the CholeskyQR
  Gram psum) the ring adds in bf16 at depth <= P-1 — the same order
  as the quantization error at the P <= 8 meshes this tier targets,
  and the existing 8x-LAPACK gates decide admissibility exactly as
  for the trailing-precision split.
* ``comms="int8"`` — the second rung: payloads are quantized to int8
  with **per-(32-row-block, column) f32 scales** (absmax/127 per
  :data:`INT8_BLOCK_ROWS`-row block of each column — whole-column
  scales measured eta ~ 1e-2, see the constant's note; a scalar scale
  for 1-D payloads), riding sidecar collectives of bounded volume
  (4/:data:`INT8_BLOCK_ROWS` = 12.5% of the payload, absorbed by the
  int8 contracts' slack). One-hot
  reductions of int8 are exact (sums of zeros, no overflow —
  contributions are zero except the owner's); **dense reductions
  refuse the int8 rung and cap at bf16** (per-device scales cannot
  ride an additive reduction), as do complex dtypes on either rung
  (no bf16 complex storage format) — both degrade LOUDLY in the
  traced volume the DHQR302 budgets check, never silently in
  accuracy.

dhqr-audit enforces the claimed reduction (compressed-mode budgets in
``analysis/comms_contracts.json`` with tightened slack: DHQR302 fails
if a compressed engine stops moving ~2x fewer traced bytes), dhqr-lint
DHQR009 keeps every sharded collective in ``dhqr_tpu/parallel/``
routed through this seam, and dhqr-pulse's DHQR306 runtime contract
reads the compressed avals straight from the traced census (the wire
volume IS the compressed volume — obs/netmodel).

Round 19 (dhqr-armor) makes the seam the transport-integrity boundary
too: with the armor tier armed, every COMPRESSED payload ships one
packed f32 ``(sum, abs-sum, count)`` sidecar and a mismatch at
decompression poisons the payload
NaN-loud (:func:`_check_tag` — a corrupted compressed collective can
never be consumed as a plausible value), and the deterministic
``parallel.collective.{corrupt,nan,drop}`` fault sites mutate the
payload between tag and transmit at TRACE time
(:func:`_inject_collective`; the engine build caches are re-keyed per
fault epoch via :func:`seam_token`, so schedules re-draw per
re-trace). Everything in this module runs at trace time only — the
disarmed runtime cost is zero and the disarmed traced programs are
byte-identical to the pre-armor tier.

Round 20 (dhqr-pod) teaches the seam that not all hops cost the same:
on a two-tier ``(dcn, ici)`` mesh (parallel/topology.py) the
collectives run HIERARCHICAL schedules — reduce inside the fast ICI
domain first, cross the 10-20x-slower DCN exactly once per collective
(in 1/ici_size-row chunks), broadcast back over ICI — so the per-
collective cross-DCN volume shrinks ici_size-fold versus the flat ring
(arXiv 2112.09017's decisive cost). The ``dcn:bf16``/``dcn:int8``
rungs compose EQuARX on top: f32 inside ICI, compressed + tagged only
at that one DCN crossing. The flat/1-D paths and every existing rung
are untouched — the schedules share one set of leg bodies
(:func:`_psum_leg` / :func:`_gather_leg`), so tags, fault sites, and
quantization are written exactly once.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

# Round 19 (dhqr-armor): deterministic collective-level fault injection
# (the parallel.collective.* "wire"-kind sites) and per-payload
# integrity tags. Both are TRACE-time concerns — every function in this
# module only ever runs while a shard body is being traced — so the
# disarmed cost is one module-global read per traced collective and
# the compiled programs are byte-identical to the pre-seam tier.
from dhqr_tpu.faults import harness as _faults

# Round 20 (dhqr-pod): the two-tier topology descriptor. The seam
# branches on it ONCE per collective — a plain string axis takes the
# exact pre-pod code path.
from dhqr_tpu.parallel.topology import TierAxes

# The mode vocabulary lives in the jax-free precision module (shared
# with the stdlib-only analysis tier); re-exported here so the seam is
# self-contained for its callers.
from dhqr_tpu.precision import COMMS_MODES, WIRE_ITEMSIZE, resolve_comms

__all__ = [
    "COMMS_MODES",
    "CSNE_SWEEPS",
    "WIRE_ITEMSIZE",
    "resolve_comms",
    "seam_token",
    "wire_all_gather",
    "wire_psum",
]


def seam_token(comms: "str | None" = None):
    """Cache-key material for the engine ``_build_*`` lru caches (the
    armor module owns the definition — re-exported here so the engines
    import one seam). None in the common case, keeping existing cache
    keys byte-identical; non-None whenever trace-time state (armed
    wire fault sites, armor integrity tags on a compressed wire) can
    change the traced program."""
    from dhqr_tpu import armor as _armor

    return _armor.seam_token(comms)

#: Corrected-semi-normal-equation sweeps the row-sharded engines run
#: when (and only when) their combine exchange is compressed: the
#: quantized R factor alone cannot hold the repo's 8x normal-equations
#: bar (wire rounding is ~bf16 eps), so each compressed solve is
#: followed by this many ``x += (R^H R)^{-1} A^H (b - A x)`` sweeps —
#: the residual matvec exact in f32 on the local rows, the tiny (n,
#: nrhs) correction reduction riding the compressed wire as a
#: SECOND-order term. Two sweeps contract the error by (cond * eta)^2,
#: the same recovery budget Björck's CSNE gives ``solvers.update``.
#: The column-sharded engines do not need this knob: their refinement
#: lives in the model tier (``qr(policy.refine)`` loops the sharded
#: solve against the true A).
CSNE_SWEEPS = 2

#: Model-tier recovery floor per wire format (``qr_model.lstsq`` on a
#: mesh): the compressed column engines refine by at least this many
#: CSNE sweeps. int8's quantization step is coarser than bf16's
#: rounding even with block scales, so its stationary iteration needs
#: two more contractions to hold the 8x bar at the cond ~ 40 matrices
#: the acceptance grid sweeps (measured: bf16 converges in 2, int8 in
#: 4). The row engines keep the flat in-body :data:`CSNE_SWEEPS` —
#: their combine exchange quantizes once (no per-panel accumulation of
#: wire error), and both rungs measured within the bar at 2.
CSNE_MODEL_SWEEPS = {"bf16": 2, "int8": 4, "dcn:bf16": 2, "dcn:int8": 2}

#: The topology-tiered rungs (round 20, dhqr-pod; EQuARX-style
#: "compress where the wire is slow"): the payload crosses the ICI legs
#: of a hierarchical two-tier schedule in exact f32 and is compressed
#: (+armor-tagged) ONLY at the isolated DCN crossing. On a flat
#: schedule, a 1-D mesh, or a 1-slice topology there is no isolated DCN
#: leg, so these rungs degrade to the exact f32 passthrough — which is
#: why dcn:int8 needs only the bf16-level CSNE_MODEL_SWEEPS above: the
#: payload is quantized exactly once per collective (the block-scale
#: step ~1/254 is bf16-eps-level), never accumulated through a ring.
_DCN_TIERED = {"dcn:bf16": "bf16", "dcn:int8": "int8"}


def _leg_comms(comms):
    """Per-leg wire formats ``(ici_leg, dcn_leg)`` for one collective
    under ``comms``: the flat rungs compress both legs, the ``dcn:*``
    rungs only the DCN crossing."""
    if comms in _DCN_TIERED:
        return None, _DCN_TIERED[comms]
    return comms, comms


def _compressible(x) -> bool:
    """Only real floating payloads compress: complex has no bf16
    storage format, and integer payloads never ride these paths."""
    return jnp.issubdtype(x.dtype, jnp.floating)


#: Rows per int8 scale block (EQuARX-style block scaling). A factored
#: panel mixes O(sqrt(m))-magnitude R rows with O(1) reflector rows in
#: the same column; one whole-column scale quantizes the reflectors
#: against the R magnitude (measured: eta ~ 1e-2, CSNE recovery
#: diverging at cond ~ 40), while per-32-row blocks keep every scale
#: local (eta back at the ~1/254 step, bf16-level) for a 4/32 = 12.5%
#: scale-sidecar overhead the int8 contract slack absorbs.
INT8_BLOCK_ROWS = 32


def _quant_int8(x):
    """Symmetric int8 quantization with per-(row-block, column) scales
    for matrices (a scalar scale for 1-D payloads): absmax/127 per
    :data:`INT8_BLOCK_ROWS`-row block so the full int8 range is used
    locally. Returns ``(q int8, scale f32-like)``; ``scale`` has shape
    ``(ceil(rows/B), cols)`` for 2-D ``x``."""
    if x.ndim == 2:
        r, c = x.shape
        # Clamp the block to the row count: padding an r-row payload to
        # a full 32-row block would inflate the dequant intermediate up
        # to 4x for small heads — exactly the shard_map-body blow-up
        # DHQR303 bounds. With the clamp the padded height is < 2r.
        block = min(INT8_BLOCK_ROWS, max(r, 1))
        blocks = -(-r // block)
        pad = blocks * block - r
        xb = jnp.pad(x, ((0, pad), (0, 0))).reshape(blocks, block, c)
        absmax = jnp.max(jnp.abs(xb), axis=1)          # (blocks, c)
        scale = absmax / 127.0
        safe = _safe_scale(scale)
        q = jnp.clip(jnp.round(xb / safe[:, None, :]), -127, 127)
        q = q.reshape(blocks * block, c)[:r].astype(jnp.int8)
        return q, scale
    absmax = jnp.max(jnp.abs(x)) if x.ndim == 1 else jnp.max(
        jnp.abs(x), axis=tuple(range(x.ndim - 1)))
    scale = absmax / 127.0
    safe = _safe_scale(scale)
    q = jnp.clip(jnp.round(x / safe), -127, 127).astype(jnp.int8)
    return q, scale


def _safe_scale(scale):
    """Divide-safe quantization scale: zero blocks (an all-zero column
    block — scale 0) divide by 1 and round-trip exactly; a NaN scale
    (the block carried NaN — ``max`` propagates it) is KEPT, so the
    int8 payload dequantizes back to NaN instead of a finite garbage
    value. NaN-loudness is the armor tier's detection contract: a
    poisoned payload must never quantize itself respectable. (Inf
    blocks keep their inf scale the same way: q = x/inf = 0,
    dequant = 0 * inf = NaN — loud.)"""
    return jnp.where(scale > 0, scale,
                     jnp.where(jnp.isnan(scale), scale,
                               jnp.ones_like(scale)))


def _dequant_int8(q, scale, dtype):
    if q.ndim == 2 and scale.ndim == 2:
        r, c = q.shape
        block = min(INT8_BLOCK_ROWS, max(r, 1))   # same clamp as _quant
        blocks = scale.shape[0]
        pad = blocks * block - r
        qb = jnp.pad(q.astype(dtype), ((0, pad), (0, 0))).reshape(
            blocks, block, c)
        out = qb * scale.astype(dtype)[:, None, :]
        return out.reshape(blocks * block, c)[:r]
    return q.astype(dtype) * scale.astype(dtype)


def _inject_collective(x):
    """Trace-time collective-level fault injection (round 19, the
    ``parallel.collective.*`` "wire"-kind sites): consulted once per
    traced collective, in site order corrupt -> nan -> drop, each per
    its own seeded stream. A trigger bakes the mutation into the traced
    payload AFTER the sender's integrity tag was computed — the tag
    models the SENDER's truth, the mutation models the wire, so a
    tagged compressed payload detects its own corruption at
    decompression. The armor seam token re-keys the engine build caches
    per fault epoch / recovery re-dispatch so schedules re-draw per
    re-trace (one "visit" = one traced collective)."""
    harness = _faults.active()
    if harness is None:
        return x
    if harness.should_fire("parallel.collective.corrupt"):
        # A large additive hit on one element — a bit flip landing in
        # a high exponent bit: plausible dtype, wildly wrong value
        # (four decades over the payload's own scale, the way exponent
        # flips land — NOT a near-threshold nudge).
        hit = jnp.zeros(x.shape, x.dtype).at[(0,) * x.ndim].set(1)
        x = x + hit * (1e4 * (1.0 + jnp.max(jnp.abs(x)))).astype(x.dtype)
    if harness.should_fire("parallel.collective.nan"):
        x = x.at[(0,) * x.ndim].set(jnp.nan)
    if harness.should_fire("parallel.collective.drop"):
        # The collective completes, the words never arrive: the
        # dropped-shard signature (a one-hot psum of zeros is the
        # owner's panel simply missing from every replica).
        x = jnp.zeros_like(x)
    return x


#: Relative sum-tag slack for the bf16 wire: payload rounding is
#: <= 2^-8 per element (x4 margin). Dense bf16 reductions additionally
#: accumulate up to P-1 partial-sum roundings of ~2^-9 relative each,
#: so their bound carries a ``2^-9 * P`` term — P read from the tag
#: sidecar's own count lane, never assumed (at the pod scales ROADMAP
#: items 1-3 target, a P-free bound crosses the honest population).
_TAG_EPS_BF16 = 4.0 * 2.0 ** -8
_TAG_EPS_BF16_PER_RANK = 2.0 ** -9


def _tags_armed() -> bool:
    from dhqr_tpu import armor as _armor

    return _armor.wire_tags_armed()


def _pack_tags(x):
    """The integrity-tag sidecar: ONE f32 triple ``(sum, sum|.|,
    count)`` per payload, riding a single collective alongside it. The
    count lane reduces to the participating-device count P, which the
    dense bf16 bound needs; the abs lane anchors the relative slack."""
    return jnp.stack([jnp.sum(x), jnp.sum(jnp.abs(x)),
                      jnp.asarray(1.0, x.dtype)]).astype(jnp.float32)


def _int8_sum_bound(scale, elems_per_scale: int):
    """Exact worst-case |sum error| of an int8 block-scaled payload:
    per-element quantization error <= scale/2, ``elems_per_scale``
    elements covered by each scale entry — the row-block height for
    2-D payloads, the FULL element count for 1-D payloads (their one
    scalar scale covers everything; clamping at the block height there
    understates the bound and poisons honest long vectors)."""
    return (0.5 * max(int(elems_per_scale), 1)
            * jnp.sum(scale).astype(jnp.float32) + 1e-30)


def _check_tag(rx, tag_rx, bound):
    """Compare the received payload's checksum against the sender-side
    tag; on mismatch poison the WHOLE payload NaN — the armor post-hoc
    verification (and the PR-8 guards) are NaN-loud, so a corrupted
    compressed collective caught here can never be consumed as a
    plausible value downstream."""
    ok = jnp.abs(jnp.sum(rx).astype(jnp.float32) - tag_rx) <= bound
    return jnp.where(ok, rx, jnp.full_like(rx, jnp.nan))


def _int8_elems_per_scale(x) -> int:
    return (min(INT8_BLOCK_ROWS, max(x.shape[0], 1)) if x.ndim == 2
            else int(x.size))


def _psum_leg(x, axes, comms, onehot: bool):
    """One traced ``psum`` over ``axes`` (a mesh axis name or a tuple of
    them — one collective either way) at the ``comms`` wire format.
    This is the complete pre-pod ``wire_psum`` body: tags, fault sites,
    and the quantization rungs are written exactly once and reused by
    both the flat and the hierarchical schedules."""
    if comms is None or not _compressible(x):
        if _faults.active() is not None:
            x = _inject_collective(x)
        return lax.psum(x, axes)
    tagged = _tags_armed()
    if tagged:
        tags = _pack_tags(x)
    if _faults.active() is not None:
        x = _inject_collective(x)
    if comms == "int8" and onehot:
        q, scale = _quant_int8(x)
        q = lax.psum(q, axes)
        scale = lax.psum(scale, axes)
        rx = _dequant_int8(q, scale, x.dtype)
        if tagged:
            tags_rx = lax.psum(tags, axes)
            rx = _check_tag(rx, tags_rx[0],
                            _int8_sum_bound(scale,
                                            _int8_elems_per_scale(x)))
        return rx
    # bf16 — and int8's dense-reduction fallback.
    rx = lax.psum(x.astype(jnp.bfloat16), axes).astype(x.dtype)
    if tagged:
        tags_rx = lax.psum(tags, axes)
        # One-hot psums accumulate exactly (zeros); dense reductions
        # ring-add in bf16, so the bound grows with the participating
        # device count (the tag triple's own count lane).
        eps = _TAG_EPS_BF16 if onehot else (
            _TAG_EPS_BF16 + _TAG_EPS_BF16_PER_RANK * tags_rx[2])
        rx = _check_tag(rx, tags_rx[0], eps * tags_rx[1] + 1e-30)
    return rx


def _gather_leg(x, axes, comms):
    """One traced ``all_gather`` over ``axes`` at the ``comms`` wire
    format — the complete pre-pod ``wire_all_gather`` body, reused by
    both schedules (see :func:`_psum_leg`)."""
    if comms is None or not _compressible(x):
        if _faults.active() is not None:
            x = _inject_collective(x)
        return lax.all_gather(x, axes)
    tagged = _tags_armed()
    if tagged:
        tags = _pack_tags(x)
    if _faults.active() is not None:
        x = _inject_collective(x)
    if comms == "int8":
        import jax

        q, scale = _quant_int8(x)
        qg = lax.all_gather(q, axes)
        sg = lax.all_gather(scale, axes)
        # qg: (P, *x.shape); sg: (P, *scale.shape) — each device's
        # share decompresses against its own (block, column) scales.
        rx = jax.vmap(lambda qq, ss: _dequant_int8(qq, ss, x.dtype))(
            qg, sg)
        if tagged:
            tags_g = lax.all_gather(tags, axes)         # (P, 3)
            rx = _check_tag(
                rx, jnp.sum(tags_g[:, 0]),
                _int8_sum_bound(sg, _int8_elems_per_scale(x)))
        return rx
    rx = lax.all_gather(x.astype(jnp.bfloat16), axes).astype(x.dtype)
    if tagged:
        # A gather concatenates — no accumulation — so the bound is
        # the payload-rounding term alone, anchored on the gathered
        # abs lanes.
        tags_g = lax.all_gather(tags, axes)             # (P, 3)
        rx = _check_tag(rx, jnp.sum(tags_g[:, 0]),
                        _TAG_EPS_BF16 * jnp.sum(tags_g[:, 1]) + 1e-30)
    return rx


def _tier_psum(x, t: TierAxes, comms, onehot: bool):
    """The hierarchical two-tier reduction (dhqr-pod, round 20):
    reduce inside the ICI domain first, exchange across DCN exactly
    ONCE per collective (each ICI member carries a 1/ici_size row chunk
    of the partial, so the cross-DCN payload shrinks ici_size-fold vs
    the flat schedule), then broadcast the chunks back over ICI in f32.

    The DCN leg stays one-hot whenever the full-mesh collective was:
    the ICI reduction collapses the owner's domain to one non-zero
    contributor per DCN group, so int8's exactness argument survives
    tier by tier. Dense reductions (``onehot=False``) ring-add across
    ``dcn_size`` participants on the DCN leg — int8 is refused there by
    :func:`_psum_leg` exactly as on the flat wire.
    """
    ici_comms, dcn_comms = _leg_comms(comms)
    if not t.hierarchical:
        # Flat baseline on the 2-D mesh: ONE joint-axis collective —
        # the same schedule a 1-D mesh runs, spelled over both tiers.
        # The dcn:* rungs have no isolated DCN leg here: exact f32.
        return _psum_leg(x, (t.dcn, t.ici), ici_comms, onehot)
    r = _psum_leg(x, t.ici, ici_comms, onehot)
    if t.dcn_size == 1:
        return r
    if r.ndim == 0:
        return _psum_leg(r, t.dcn, dcn_comms, onehot)
    rows = r.shape[0]
    rp = -(-rows // t.ici_size) * t.ici_size
    if rp != rows:
        r = jnp.pad(r, [(0, rp - rows)] + [(0, 0)] * (r.ndim - 1))
    crows = rp // t.ici_size
    idx = lax.axis_index(t.ici)
    chunk = lax.dynamic_slice_in_dim(r, idx * crows, crows, axis=0)
    chunk = _psum_leg(chunk, t.dcn, dcn_comms, onehot)
    # Broadcast-back: tiled ICI gather reassembles the row chunks in
    # ici-index order — the original row order — on the fast tier, in
    # f32 (the DCN check/decompression already ran on the chunk).
    out = lax.all_gather(chunk, t.ici, axis=0, tiled=True)
    return out[:rows] if rp != rows else out


def _tier_all_gather(x, t: TierAxes, comms):
    """The hierarchical two-tier gather: exchange each device's local
    share across DCN first (the ONLY compressed/slow leg — dcn_size
    shares instead of the flat schedule's full P), then gather the
    stacks over ICI in f32 and restore the flat dcn-major device order
    (block ``d * ici_size + i`` — matching ``topology.spec_axes``)."""
    ici_comms, dcn_comms = _leg_comms(comms)
    if not t.hierarchical:
        return _gather_leg(x, (t.dcn, t.ici), ici_comms)
    if t.dcn_size == 1:
        return _gather_leg(x, t.ici, ici_comms)
    g = _gather_leg(x, t.dcn, dcn_comms)                # (dcn, *x)
    if t.ici_size == 1:
        return g
    gg = _gather_leg(g, t.ici, None)                    # (ici, dcn, *x)
    return jnp.moveaxis(gg, 0, 1).reshape((t.size,) + x.shape)


def wire_psum(x, axis_name, comms=None, *, onehot: bool = True):
    """``lax.psum`` with the payload compressed to the ``comms`` wire
    format (decompressed to ``x.dtype`` on return).

    ``onehot=True`` declares the engine invariant that at most ONE
    device contributes a non-zero ``x`` (the owner's panel broadcast):
    there the reduction adds exact zeros, so any wire format keeps the
    accumulation exact and int8's per-column scales can ride their own
    one-hot psum. ``onehot=False`` (dense reductions — the CholeskyQR
    Gram) reduces in the wire dtype; the int8 rung is refused there
    (per-device scales cannot be summed) and degrades to bf16.

    Round 19: with armor's wire tags armed, compressed payloads ship a
    f32 sum sidecar (one scalar per collective — one-hot psums keep it
    exact, dense psums sum the per-device truths, which is the right
    reference for the summed payload) and a mismatch at decompression
    poisons the payload NaN-loud. The ``parallel.collective.*`` fault
    sites mutate the payload between tag and transmit, on every rung
    including the f32 passthrough.

    Round 20 (dhqr-pod): ``axis_name`` may be a
    :class:`~dhqr_tpu.parallel.topology.TierAxes` — the collective then
    runs the hierarchical two-tier schedule (:func:`_tier_psum`;
    ``hierarchical=False`` spells the flat joint-axis baseline). The
    ``dcn:*`` rungs compress ONLY the isolated DCN crossing of that
    schedule; on a plain 1-D axis they degrade to the exact f32
    passthrough (there is no DCN leg to compress).
    """
    if isinstance(axis_name, TierAxes):
        return _tier_psum(x, axis_name, comms, onehot)
    if comms in _DCN_TIERED:
        comms = None  # no isolated DCN crossing on a 1-D axis
    return _psum_leg(x, axis_name, comms, onehot)


def wire_all_gather(x, axis_name, comms=None):
    """``lax.all_gather`` with the payload compressed to the ``comms``
    wire format. A gather is pure concatenation — no accumulation at
    any rung — so int8 per-column scales apply cleanly: each device
    quantizes its own share, the (tiny) scales gather alongside, and
    decompression is local. Armor wire tags and the collective fault
    sites apply exactly as on :func:`wire_psum` (the tag compares the
    gathered whole against the gathered per-device truths). A
    :class:`~dhqr_tpu.parallel.topology.TierAxes` axis runs the
    hierarchical DCN-first schedule (:func:`_tier_all_gather`); the
    ``dcn:*`` rungs compress only that DCN leg and degrade to the f32
    passthrough on a plain 1-D axis."""
    if isinstance(axis_name, TierAxes):
        return _tier_all_gather(x, axis_name, comms)
    if comms in _DCN_TIERED:
        comms = None  # no isolated DCN crossing on a 1-D axis
    return _gather_leg(x, axis_name, comms)
