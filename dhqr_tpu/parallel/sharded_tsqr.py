"""Mesh-sharded TSQR: row-partitioned tall-skinny QR / least squares.

The distributed form of :mod:`dhqr_tpu.ops.tsqr`: rows sharded over a 1-D
mesh axis, each device factors its local row block independently (zero
communication), then the (P*n x n) stack of R heads — tiny — is
all-gathered and the combine QR runs replicated on every device. Exactly
one collective for the whole factorization, versus one psum per panel in
the column-sharded engine: this is the communication-optimal regime for
m >> n.

This deliberately relaxes the reference's rows-never-partitioned invariant
(reference src/DistributedHouseholderQR.jl:33) — its column layout cannot
scale a 65536 x 256 problem (SURVEY.md §6 config 2), a row layout can.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dhqr_tpu.utils.compat import shard_map

# dhqr-pulse (round 16) runtime comms seam — acyclic, one None check
# disarmed (see parallel/sharded_qr.py).
from dhqr_tpu.obs import pulse as _pulse

from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.ops.solve import as_matrix_rhs
from dhqr_tpu.ops.tsqr import _combine_solve, _leaf_factor

ROW_AXIS = "rows"


def row_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = ROW_AXIS,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D device mesh over the row axis (the TSQR worker pool)."""
    from dhqr_tpu.parallel.mesh import column_mesh

    return column_mesh(n_devices, axis_name=axis_name, devices=devices)


def _tsqr_shard_body(Al, bl, *, n: int, nb: int, axis: str, precision: str,
                     pallas: bool = False, interpret: bool = False,
                     pallas_flat: "int | None" = None):
    """Per-device: local QR + Q^H b, then replicated combine of the R heads.

    Leaf and combine stages are shared with the single-device tree
    (ops/tsqr) so the two paths cannot numerically diverge.
    """
    Bl, restore = as_matrix_rhs(bl)
    R, c = _leaf_factor(Al, Bl, nb, precision, pallas, interpret,
                        pallas_flat)
    # ONE collective: gather every device's heads (P*n rows — tiny traffic).
    Rstack = lax.all_gather(R, axis).reshape(-1, n)
    cstack = lax.all_gather(c, axis).reshape(-1, c.shape[1])
    # Combine stage, replicated on every device (cheaper than a second
    # collective to scatter the result — same trade as the reference making
    # alpha a SharedArray, src:302).
    return restore(_combine_solve(Rstack, cstack, nb, precision, pallas,
                                  interpret, pallas_flat))


@lru_cache(maxsize=None)
def _build_tsqr(mesh: Mesh, axis_name: str, n: int, nb: int, precision: str,
                pallas: bool = False, interpret: bool = False,
                pallas_flat: "int | None" = None):
    body = partial(
        _tsqr_shard_body, n=n, nb=nb, axis=axis_name, precision=precision,
        pallas=pallas, interpret=interpret, pallas_flat=pallas_flat,
    )
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name, None), P(axis_name)),
            out_specs=P(),
            check_vma=False,  # x is replicated by construction (all_gather)
        )
    )


def sharded_tsqr_lstsq(
    A: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    block_size: int = 128,
    axis_name: str = ROW_AXIS,
    precision: str = DEFAULT_PRECISION,
    use_pallas: str = "auto",
) -> jax.Array:
    """Distributed tall-skinny least squares: rows sharded, one all-gather.

    Requires m divisible by the mesh size with each local block tall
    (m/P >= n). Returns x replicated. ``use_pallas`` routes the per-device
    leaf panel loops through the fused VMEM kernel (resolved against the
    LOCAL leaf shape m/P x nb — same semantics as ``tsqr_lstsq``).
    """
    from dhqr_tpu.ops.tsqr import _resolve_tsqr_pallas
    from dhqr_tpu.utils.platform import ensure_complex_supported

    ensure_complex_supported(A.dtype)
    m, n = A.shape
    nproc = mesh.shape[axis_name]
    if m % nproc != 0:
        raise ValueError(f"m={m} must be divisible by mesh size {nproc}")
    if m // nproc < n:
        raise ValueError(
            f"local row blocks must stay tall: m/P = {m // nproc} < n = {n}"
        )
    nb = min(int(block_size), n)
    pallas, interpret = _resolve_tsqr_pallas(use_pallas, m // nproc, n, nb,
                                             A.dtype)
    from dhqr_tpu.ops.blocked import PALLAS_FLAT_WIDTH

    A = jax.device_put(A, NamedSharding(mesh, P(axis_name, None)))
    b = jax.device_put(b, NamedSharding(mesh, P(axis_name)))
    from dhqr_tpu.ops.blocked import _pallas_cache_guard

    with _pallas_cache_guard(interpret):
        fn = _build_tsqr(mesh, axis_name, n, nb, precision, pallas,
                         interpret, PALLAS_FLAT_WIDTH)
        if _pulse.active() is None:
            return fn(A, b)
        return _pulse.observed_dispatch(
            f"tsqr_lstsq[P={nproc},{m}x{n},nb={nb}]",
            lambda: fn(A, b),
            abstract=lambda: jax.make_jaxpr(fn)(A, b), n_devices=nproc)


# Comms contract (dhqr-audit): exactly one all_gather pair per solve —
# P*n*(n + nrhs) words, independent of m (analysis/cost_model.py
# `tsqr_lstsq`); any psum/all_to_all here, or a second gather, is a
# DHQR301/302 finding.
