"""Mesh-sharded TSQR: row-partitioned tall-skinny QR / least squares.

The distributed form of :mod:`dhqr_tpu.ops.tsqr`: rows sharded over a 1-D
mesh axis, each device factors its local row block independently (zero
communication), then the (P*n x n) stack of R heads — tiny — is
all-gathered and the combine QR runs replicated on every device. Exactly
one collective for the whole factorization, versus one psum per panel in
the column-sharded engine: this is the communication-optimal regime for
m >> n.

This deliberately relaxes the reference's rows-never-partitioned invariant
(reference src/DistributedHouseholderQR.jl:33) — its column layout cannot
scale a 65536 x 256 problem (SURVEY.md §6 config 2), a row layout can.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dhqr_tpu.utils.compat import shard_map

# dhqr-pulse (round 16) runtime comms seam — acyclic, one None check
# disarmed (see parallel/sharded_qr.py).
from dhqr_tpu.obs import pulse as _pulse

# dhqr-wire (round 18) compression seam (DHQR009): the combine-tree
# gather may cross the wire as bf16/int8; comms=None is a passthrough.
from dhqr_tpu.parallel import wire as _wire

# dhqr-armor (round 19) ABFT verification seam (DHQR010) — one
# module-global None check disarmed, same discipline as pulse above.
from dhqr_tpu import armor as _armor

# dhqr-pod (round 20): two-tier topology descriptor + axis helpers.
from dhqr_tpu.parallel import topology as _topo

from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.ops.solve import as_matrix_rhs
from dhqr_tpu.ops.tsqr import _combine_solve, _leaf_factor

ROW_AXIS = "rows"


def row_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = ROW_AXIS,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D device mesh over the row axis (the TSQR worker pool)."""
    from dhqr_tpu.parallel.mesh import column_mesh

    return column_mesh(n_devices, axis_name=axis_name, devices=devices)


def _tsqr_shard_body(Al, bl, *, n: int, nb: int, axis: str, precision: str,
                     pallas: bool = False, interpret: bool = False,
                     pallas_flat: "int | None" = None,
                     comms: "str | None" = None):
    """Per-device: local QR + Q^H b, then replicated combine of the R heads.

    Leaf and combine stages are shared with the single-device tree
    (ops/tsqr) so the two paths cannot numerically diverge.
    """
    import jax.numpy as jnp

    Bl, restore = as_matrix_rhs(bl)
    R, c = _leaf_factor(Al, Bl, nb, precision, pallas, interpret,
                        pallas_flat)
    # ONE collective: gather every device's heads (P*n rows — tiny
    # traffic), over the comms wire format (a gather concatenates — no
    # accumulation at any rung; the combine QR below stays f32).
    Rstack = _wire.wire_all_gather(R, axis, comms).reshape(-1, n)
    cstack = _wire.wire_all_gather(c, axis, comms).reshape(-1, c.shape[1])
    # Combine stage, replicated on every device (cheaper than a second
    # collective to scatter the result — same trade as the reference making
    # alpha a SharedArray, src:302).
    if comms is None:
        return restore(_combine_solve(Rstack, cstack, nb, precision,
                                      pallas, interpret, pallas_flat))
    # Compressed wire: the gathered heads carry ~wire-eps rounding, so
    # the raw combine solve cannot hold the 8x normal-equations bar on
    # its own. Run the combine through the SHARED factored form
    # (ops/tsqr._combine_factor — same spelling as _combine_solve, so
    # the paths cannot numerically diverge) keeping its R, then
    # CSNE_SWEEPS corrected-semi-normal sweeps against the TRUE local
    # rows: x += (R^H R)^{-1} A^H (b - A x) — residual matvec exact in
    # f32, the (n, nrhs) correction reduction priced by
    # cost_model.tsqr_lstsq_wire.
    from dhqr_tpu.ops.solve import back_substitute, r_matrix
    from dhqr_tpu.ops.tsqr import _combine_factor

    H2, alpha2, c2 = _combine_factor(Rstack, cstack, nb, precision,
                                     pallas, interpret, pallas_flat)
    x = back_substitute(H2, alpha2, c2)
    Rt = r_matrix(H2, alpha2)

    def sns(g):
        y = lax.linalg.triangular_solve(Rt, g, left_side=True, lower=False,
                                        transpose_a=True, conjugate_a=True)
        return lax.linalg.triangular_solve(Rt, y, left_side=True,
                                           lower=False)

    for _ in range(_wire.CSNE_SWEEPS):
        r_loc = Bl - jnp.matmul(Al, x, precision="highest")
        # The (n, nrhs) correction reduction stays on the F32 wire
        # (comms=None is the seam's exact passthrough): quantizing the
        # correction would cap the sweep's contraction at the wire eps
        # it exists to remove, and its volume is O(1/(P*n)) of the
        # combine exchange (priced by the *_wire cost models).
        g = _wire.wire_psum(
            jnp.matmul(jnp.conj(Al.T), r_loc, precision="highest"),
            axis, None, onehot=False)
        x = x + sns(g)
    return restore(x)


@lru_cache(maxsize=None)
def _build_tsqr(mesh: Mesh, axis_name: str, n: int, nb: int, precision: str,
                pallas: bool = False, interpret: bool = False,
                pallas_flat: "int | None" = None,
                comms: "str | None" = None, seam=None):
    # ``seam`` (round 19) is cache-key material only — wire.seam_token:
    # None in the common case (key byte-identical to pre-armor), a
    # fresh tuple per fault epoch / armor re-arm / recovery re-dispatch
    # so trace-time injection and tag programs re-trace instead of
    # replaying a stale baked fault.
    body = partial(
        _tsqr_shard_body, n=n, nb=nb, axis=axis_name, precision=precision,
        pallas=pallas, interpret=interpret, pallas_flat=pallas_flat,
        comms=comms,
    )
    spec = _topo.spec_axes(axis_name)
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(spec, None), P(spec)),
            out_specs=P(),
            check_vma=False,  # x is replicated by construction (all_gather)
        )
    )


def sharded_tsqr_lstsq(
    A: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    block_size: int = 128,
    axis_name: str = ROW_AXIS,
    precision: str = DEFAULT_PRECISION,
    use_pallas: str = "auto",
    comms: "str | None" = None,
) -> jax.Array:
    """Distributed tall-skinny least squares: rows sharded, one all-gather.

    Requires m divisible by the mesh size with each local block tall
    (m/P >= n). Returns x replicated. ``use_pallas`` routes the per-device
    leaf panel loops through the fused VMEM kernel (resolved against the
    LOCAL leaf shape m/P x nb — same semantics as ``tsqr_lstsq``).
    """
    from dhqr_tpu.ops.tsqr import _resolve_tsqr_pallas
    from dhqr_tpu.utils.platform import ensure_complex_supported

    ensure_complex_supported(A.dtype)
    comms = _wire.resolve_comms(comms)
    m, n = A.shape
    axis_name = _topo.resolve_axis(mesh, axis_name)
    nproc = _topo.axis_size(mesh, axis_name)
    ptag = _topo.axis_label(axis_name, nproc)
    if m % nproc != 0:
        raise ValueError(f"m={m} must be divisible by mesh size {nproc}")
    if m // nproc < n:
        raise ValueError(
            f"local row blocks must stay tall: m/P = {m // nproc} < n = {n}"
        )
    nb = min(int(block_size), n)
    pallas, interpret = _resolve_tsqr_pallas(use_pallas, m // nproc, n, nb,
                                             A.dtype)
    from dhqr_tpu.ops.blocked import PALLAS_FLAT_WIDTH

    spec = _topo.spec_axes(axis_name)
    A = jax.device_put(A, NamedSharding(mesh, P(spec, None)))
    b = jax.device_put(b, NamedSharding(mesh, P(spec)))
    from dhqr_tpu.ops.blocked import _pallas_cache_guard

    base_label = f"tsqr_lstsq[P={ptag},{m}x{n},nb={nb}]"
    comms = _armor.effective_comms(base_label, comms)

    def _dispatch(wire_comms):
        with _pallas_cache_guard(interpret):
            fn = _build_tsqr(mesh, axis_name, n, nb, precision, pallas,
                             interpret, PALLAS_FLAT_WIDTH, wire_comms,
                             _wire.seam_token(wire_comms))
            if _pulse.active() is None:
                return fn(A, b)
            return _pulse.observed_dispatch(
                f"tsqr_lstsq[P={ptag},{m}x{n},nb={nb}"
                + (f",w{wire_comms}" if wire_comms else "") + "]",
                lambda: fn(A, b),
                abstract=lambda: jax.make_jaxpr(fn)(A, b), n_devices=nproc,
                wire_format=wire_comms)

    if _armor.active() is None:
        return _dispatch(comms)
    # ABFT verification (round 19): the normal-equations checksum over
    # the solve the dispatch already produced — O(mn), no
    # re-factorization; recovery re-dispatches, then degrades the
    # label's wire to the f32 passthrough, then refuses typed.
    return _armor.checked_dispatch(
        base_label, lambda: _dispatch(comms),
        lambda x: (_armor.checks.lstsq_gap(A, b, x), None),
        engine="tsqr", comms=comms,
        degrade=(lambda: _dispatch(None)) if comms else None,
        plan_shape=("lstsq", m, n, str(A.dtype), nproc))


# Comms contract (dhqr-audit): exactly one all_gather pair per solve —
# P*n*(n + nrhs) words, independent of m (analysis/cost_model.py
# `tsqr_lstsq`); any psum/all_to_all here, or a second gather, is a
# DHQR301/302 finding. The COMPRESSED variant (comms set) additionally
# allows the CSNE_SWEEPS (n, nrhs) correction psums (round 18 —
# `tsqr_lstsq_wire` model, wire bytes halved/quartered at the gather).
