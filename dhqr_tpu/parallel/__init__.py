"""Distribution layer (layer L1 of SURVEY.md §1) + sharded engines.

The reference makes ``Matrix`` / ``SharedArray`` / ``DArray`` look alike via
index shims and a ``LocalColumnBlock`` wrapper (reference
src/DistributedHouseholderQR.jl:11-40). Here the same seam is a
``jax.sharding.Mesh`` with a single column axis: the engines are written once
against local blocks inside ``shard_map`` and run unchanged from 1 device
(serial tier) to N devices (distributed tier).
"""

from dhqr_tpu.parallel.layout import (
    ColumnBlock,
    area_balanced_splits,
    column_block_ranges,
    local_column_block,
)
from dhqr_tpu.parallel.mesh import column_mesh, column_sharding, replicated_sharding
from dhqr_tpu.parallel.sharded_qr import sharded_blocked_qr, sharded_householder_qr
from dhqr_tpu.parallel.sharded_solve import sharded_lstsq, sharded_solve
from dhqr_tpu.parallel.sharded_tsqr import row_mesh, sharded_tsqr_lstsq
from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq
from dhqr_tpu.parallel.multihost import (
    global_column_mesh,
    global_row_mesh,
    initialize,
    process_info,
)

__all__ = [
    "ColumnBlock",
    "area_balanced_splits",
    "column_block_ranges",
    "local_column_block",
    "column_mesh",
    "column_sharding",
    "replicated_sharding",
    "sharded_householder_qr",
    "sharded_blocked_qr",
    "sharded_solve",
    "sharded_lstsq",
    "row_mesh",
    "sharded_tsqr_lstsq",
    "sharded_cholqr_lstsq",
    "initialize",
    "global_column_mesh",
    "global_row_mesh",
    "process_info",
]
