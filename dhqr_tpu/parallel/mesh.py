"""Mesh construction and sharding specs — the worker-pool equivalent.

The reference's execution resources are ``np`` Distributed.jl worker
processes created by ``addprocs(np)`` (reference test/runtests.jl:9) holding
one column block each (``DArray`` distributed ``(1, nworkers())``,
runtests.jl:71). Here the resources are a 1-D ``jax.sharding.Mesh`` over a
``"cols"`` axis; matrices are placed with ``P(None, "cols")`` so rows are
never partitioned — the invariant the reference asserts at src:33.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_AXIS = "cols"


def column_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = DEFAULT_AXIS,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D device mesh over the column axis.

    ``n_devices=None`` uses every visible device — the analogue of
    ``addprocs(np)`` sizing the worker pool (runtests.jl:4,9).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def column_sharding(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> NamedSharding:
    """Sharding for an (m, n) matrix: columns split over the mesh, rows whole.

    The reference's ``DArray(..., (1, nworkers()))`` layout (runtests.jl:71)
    with the rows-unpartitioned invariant (src:33) encoded in the spec.
    """
    return NamedSharding(mesh, P(None, axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement — the analogue of the reference's
    ``SharedArray`` side channel for alpha and b (src:302, 318)."""
    return NamedSharding(mesh, P())
